"""Turn TPU_PROFILE_RESULTS.json into recommended default flips.

Reads the profiler's record (bench/tpu_profile.py) and prints, as JSON
lines, which engine/precision defaults the numbers support changing and
which measurements are still missing. Decision rules mirror NOTES.md's
on-chip queue:

- trim_engine: pallas becomes the recon8_list default if it beats the
  approx trim by >10% QPS at equal (±0.01) recall.
- score_dtype: int8 likewise vs bf16.
- internal_distance_dtype: bfloat16 likewise vs float32.
- IVF-Flat engine: the fastest of query/list/pallas at >= query-engine
  recall - 0.01.
- trainer precision: bf16 trainer OK if its inertia is within 0.5% of
  HIGHEST.

Usage: python bench/apply_profile_hints.py [path-to-results.json]
"""

import json
import sys, os


def _qps(rec):
    return rec.get("qps") if isinstance(rec, dict) else None


def _recall(rec):
    return rec.get("recall") if isinstance(rec, dict) else None


def hint(out, name, winner, detail):
    out.append({"hint": name, "recommend": winner, "detail": detail})


_EXPECTED_KEYS = (
    "search_recon8_list_bf16_float32_approx_np32",
    "search_recon8_list_bf16_float32_pallas_np32",
    "search_recon8_list_int8_float32_approx_np32",
    "search_recon8_list_int8_float32_pallas_np32",
    "search_recon8_list_bf16_bfloat16_approx_np32",
    "search_lut_bf16_float32_approx_np32",
    "search_cb0_int8_bf16trim_np32",
    "search_cb8_int8_bf16trim_np32",
    "search_recon8_list_int8_bfloat16_exact_np32",
    "search_unrefined_np8_approx",
    "search_unrefined_np8_exact",
    "search_refined_np8_chunk128",
    "search_refined_np8_chunk64",
    "search_refined_np8_chunk32",
    "flat_search_query_np32",
    "flat_search_list_np32",
    "flat_search_pallas_np32",
    "bf_tiled_1M",
    "bf_pallas_1M",
    "inertia_highest",
    "inertia_bf16",
    "micro_bf16",
    "micro_int8",
)


def main(path: str):
    try:
        with open(path) as f:
            R = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        # the profiler may have been skipped entirely (dead transport):
        # report that as a decision input rather than crashing the queue
        print(json.dumps({"hint": "no_profile_results", "detail": str(e)[:200]}))
        return
    out = []
    missing = [k for k, v in R.items() if isinstance(v, dict) and "error" in v]
    missing += [k for k in _EXPECTED_KEYS if k not in R]
    compared = [0]  # comparisons that ran (even with no clear winner)

    def cmp(name, a_key, b_key, label_a, label_b):
        a, b = R.get(a_key), R.get(b_key)
        if not (_qps(a) and _qps(b)):
            return
        compared[0] += 1
        ra, rb = _recall(a) or 0.0, _recall(b) or 0.0
        if abs(ra - rb) <= 0.01:
            if _qps(b) > 1.1 * _qps(a):
                hint(out, name, label_b,
                     f"{label_b} {_qps(b):.0f} qps vs {label_a} {_qps(a):.0f} "
                     f"at recall {rb:.3f}/{ra:.3f}")
            elif _qps(a) > 1.1 * _qps(b):
                hint(out, name, label_a,
                     f"{label_a} {_qps(a):.0f} qps vs {label_b} {_qps(b):.0f}")
        else:
            hint(out, name, "inspect",
                 f"recall gap {ra:.3f} vs {rb:.3f} — not a pure speed trade")

    base = "search_recon8_list_bf16_float32_approx_np32"
    cmp("trim_engine_default", base,
        "search_recon8_list_bf16_float32_pallas_np32", "approx", "pallas")
    cmp("bf_engine_default", "bf_tiled_1M", "bf_pallas_1M", "tiled", "pallas")
    cmp("score_dtype_default", base,
        "search_recon8_list_int8_float32_approx_np32", "bf16", "int8")
    cmp("int8_trim_engine", "search_recon8_list_int8_float32_approx_np32",
        "search_recon8_list_int8_float32_pallas_np32", "approx", "pallas")
    cmp("internal_distance_dtype", base,
        "search_recon8_list_bf16_bfloat16_approx_np32", "float32", "bfloat16")
    cmp("pq_auto_engine", "search_lut_bf16_float32_approx_np32", base,
        "lut", "recon8_list")

    def pick_best(records, baseline=None, ref_recall=None, margin=1.1):
        """Shared pick-best among measured records: drop entries more
        than 0.01 recall under the reference (baseline's recall, or the
        max measured), take the QPS argmax of the survivors; the
        baseline (when it survived the recall floor) keeps the win
        unless a challenger beats it by `margin`. Returns (winner,
        detail) or (None, None) with <2 measured."""
        valid = {e: v for e, v in records.items() if _qps(v)}
        if len(valid) < 2:
            return None, None
        compared[0] += 1
        if ref_recall is None:
            ref_recall = _recall(valid.get(baseline)) or max(
                _recall(v) or 0.0 for v in valid.values()
            )
        ok = {e: v for e, v in valid.items()
              if (_recall(v) or 0.0) >= ref_recall - 0.01}
        winner = max(ok, key=lambda e: _qps(ok[e]))
        if baseline in ok and winner != baseline \
                and _qps(ok[winner]) <= margin * _qps(ok[baseline]):
            winner = baseline
        detail = {e: (_qps(v), _recall(v)) for e, v in valid.items()}
        absent = sorted(set(records) - set(valid), key=str)
        if absent:
            detail["unmeasured"] = absent
        return winner, detail

    # decide among the flat engines that DID measure (a Mosaic rejection
    # of the pallas config must not suppress the query-vs-list decision)
    flat = {e: R.get(f"flat_search_{e}_np32") for e in ("query", "list", "pallas")}
    w, detail = pick_best(flat, baseline="query", margin=1.0)
    if w is not None:
        hint(out, "ivf_flat_engine_default", w, detail)

    # listmajor chunk race (refined np8): best QPS at >= max-recall - 0.01;
    # the 128 default keeps the win unless a smaller chunk beats it by
    # >10%. The floor is the MAX measured recall (all three rows are the
    # same engine, differing only in trim noise) — so a recall-degraded
    # baseline cannot keep the win from outside the floor.
    chunks = {c: R.get(f"search_refined_np8_chunk{c}") for c in (128, 64, 32)}
    cmax = [(_recall(v) or 0.0) for v in chunks.values() if _qps(v)]
    w, detail = pick_best(chunks, baseline=128,
                          ref_recall=max(cmax) if cmax else None)
    if w is not None:
        hint(out, "listmajor_chunk", w, detail)

    # chunk_block structure race: 0 (one einsum per superblock, the
    # round-5 default) vs the inner-lax.map granularities; recall floor =
    # max measured (same engine, trim noise only), the 0 baseline keeps
    # the win unless a positive block beats it by >10%
    cbs = {c: R.get(f"search_cb{c}_int8_bf16trim_np32") for c in (0, 8, 32)}
    # (32 tolerated if an older record has it; the race now runs {0, 8})
    cbmax = [(_recall(v) or 0.0) for v in cbs.values() if _qps(v)]
    w, detail = pick_best(cbs, baseline=0,
                          ref_recall=max(cbmax) if cbmax else None)
    if w is not None:
        hint(out, "listmajor_chunk_block", w, detail)

    ih, ib = R.get("inertia_highest"), R.get("inertia_bf16")
    if ih and ib:
        rel = (ib - ih) / abs(ih)
        hint(out, "trainer_precision",
             "bf16 (DEFAULT)" if rel <= 0.005 else "keep HIGHEST",
             f"bf16 inertia {rel:+.4%} vs HIGHEST")

    # prefer the *_trueS re-run (measured at the built index's real slot
    # count) over the early-banked S=1024 numbers when both are valid
    mb, mi = R.get("micro_bf16_trueS"), R.get("micro_int8_trueS")
    if not (isinstance(mb, dict) and isinstance(mi, dict)
            and "tflops" in mb and "tflops" in mi):
        mb, mi = R.get("micro_bf16"), R.get("micro_int8")
    if isinstance(mb, dict) and isinstance(mi, dict) and "tflops" in mb and "tflops" in mi:
        hint(out, "chunk_matmul", "int8" if mi["tflops"] > 1.1 * mb["tflops"] else "bf16",
             f"int8 {mi['tflops']} vs bf16 {mb['tflops']} TFLOP/s")

    for h in out:
        print(json.dumps(h))
    if missing:
        print(json.dumps({"hint": "missing_measurements", "keys": missing}))
    if not out:
        detail = (
            "measured, but no pair cleared the 10% threshold — keep current defaults"
            if compared[0] else "profile record lacks the ladder keys"
        )
        print(json.dumps({"hint": "no_decisions", "detail": detail}))
    return out


# hints whose winners the library's "auto" paths consult directly
# (raft_tpu/core/tuned.py); everything else stays informational.
# value = (tuned key, caster applied to the recommend before writing)
_TUNABLE = {
    "pq_auto_engine": ("pq_auto_engine", str),
    "ivf_flat_engine_default": ("flat_auto_engine", str),
    "listmajor_chunk": ("listmajor_chunk", int),
    "listmajor_chunk_block": ("listmajor_chunk_block", int),
}


def apply_hints(out):
    """Merge the decided winners into raft_tpu/tuned_defaults.json — the
    committed artifact the library's auto dispatch reads. Only concrete
    engine winners are applied; 'inspect' verdicts and informational
    hints land under "hints" for the next session to read. MERGE, not
    overwrite: a partial/aborted profile must not erase winners an
    earlier good session measured (the queue runs --apply even when the
    profiler was skipped), and an empty decision set writes nothing."""
    from raft_tpu.core import tuned

    if not out:
        print(json.dumps({"applied": None,
                          "detail": "no decisions; tuned file left untouched"}))
        return
    updates = {"hints": {h["hint"]: h["recommend"] for h in out}}
    for hint_name, (key, caster) in _TUNABLE.items():
        for h in out:
            if h["hint"] != hint_name or h["recommend"] == "inspect":
                continue
            try:
                updates[key] = caster(h["recommend"])
            except (TypeError, ValueError):
                continue
    # runtime belt matching the lint-time tuned-key-registry check: a
    # _TUNABLE entry drifting from the registry — or a recommend value
    # outside a choice key's registered set — must not bank a winner
    # every reader will reject (the lint rule cannot see computed
    # values, so this is the only enforcement point for them)
    for k in sorted(k for k in updates if k != "hints"):
        entry = tuned.TUNED_KEYS.get(k)
        if entry is None:
            print(json.dumps({"skipped_unregistered_key": k}))
            del updates[k]
        elif entry["kind"] == "choice" and updates[k] not in entry["choices"]:
            print(json.dumps({"skipped_out_of_set_value": k,
                              "value": updates[k],
                              "choices": list(entry["choices"])}))
            del updates[k]
    tuned.merge(updates)
    print(json.dumps({"applied": tuned.path(),
                      "keys": [k for k in updates if k != "hints"]}))


if __name__ == "__main__":
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    args = [a for a in sys.argv[1:] if a != "--apply"]
    hints = main(args[0] if args else
                 os.path.join(repo, "TPU_PROFILE_RESULTS.json"))
    if "--apply" in sys.argv[1:]:
        apply_hints(hints or [])
