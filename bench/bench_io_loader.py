"""File-loader throughput: native prefetch ring vs memmap fallback.

The consumer "work" per batch is a deterministic sleep (a stand-in for
device compute whose cost is exactly known, immune to BLAS/thermal
variance): with per-batch work W and per-batch IO cost R, the prefetch
ring should approach max(W, R) per batch while the synchronous fallback
pays W + R. `--cold` evicts the file's pages (posix_fadvise DONTNEED)
before each mode so R reflects real IO, not a memcpy from page cache.
Runs anywhere (no chip needed) — IO is host-side by construction.

Usage: python bench/bench_io_loader.py [--rows N] [--cold] [--smoke]
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _evict(path: str) -> bool:
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
        finally:
            os.close(fd)
        return True
    except (OSError, AttributeError):
        return False


def run(rows: int, dim: int, batch_rows: int, work_ms: float, cold: bool):
    from raft_tpu.io import FileBatchLoader
    from raft_tpu import native

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "corpus.fbin")
        rng = np.random.default_rng(0)
        with open(path, "wb") as f:
            np.asarray([rows, dim], np.uint32).tofile(f)
            step = max(1, (1 << 24) // dim)
            for lo in range(0, rows, step):
                hi = min(lo + step, rows)
                rng.random((hi - lo, dim), dtype=np.float32).tofile(f)
        nbytes = rows * dim * 4

        results = {}
        for mode, use_native in (("native", True), ("fallback", False)):
            if use_native and not native.available():
                results[mode] = {"error": "native unavailable"}
                continue
            evicted = _evict(path) if cold else False
            t0 = time.perf_counter()
            total = 0
            touched = 0.0
            # copy=False: measure the zero-copy perf path both modes offer
            for block, valid in FileBatchLoader(path, batch_rows,
                                                native=use_native, copy=False):
                total += valid
                # touch every page (one element per <=4K page: rows are
                # 384 B here) so lazy page-in can't hide in either mode
                touched += float(block[:valid, 0].sum())
                time.sleep(work_ms / 1e3)  # deterministic per-batch "compute"
            dt = time.perf_counter() - t0
            assert total == rows, (total, rows)
            n_batches = -(-rows // batch_rows)
            results[mode] = {
                "s": round(dt, 3),
                "gb_per_s": round(nbytes / dt / 1e9, 3),
                "io_ms_per_batch": round(dt * 1e3 / n_batches - work_ms, 2),
                "evicted": evicted,
            }
        return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=2_000_000)
    ap.add_argument("--dim", type=int, default=96)
    ap.add_argument("--batch-rows", type=int, default=100_000)
    ap.add_argument("--work-ms", type=float, default=30.0)
    ap.add_argument("--cold", action="store_true")
    ap.add_argument("--smoke", action="store_true")
    a = ap.parse_args()
    if a.smoke:
        a.rows, a.batch_rows, a.work_ms = 100_000, 10_000, 5.0
    res = run(a.rows, a.dim, a.batch_rows, a.work_ms, a.cold)
    print(json.dumps({"suite": "io_loader", "rows": a.rows, "dim": a.dim,
                      "batch_rows": a.batch_rows, "work_ms": a.work_ms,
                      "cold": a.cold, **res}), flush=True)


if __name__ == "__main__":
    main()
