"""k-NN benches: brute-force, IVF-Flat, IVF-PQ (reference
cpp/bench/neighbors/knn.cuh + refine.cu). Reports search QPS; index build
is timed once per config (the reference builds in the fixture setup).

Survivable (ROADMAP 5a): `ensure_survivable_backend()` pins CPU
in-process when the relay transport is structurally dead, the geometry
shrinks to a CPU-feasible size (recorded in the case names), and every
row still banks — to BENCH_neighbors.json (honestly tagged
`"fallback": "in_process_cpu"`) and the append-only ledger — instead of
the old behavior of hanging until someone's timeout and leaving the
perf trajectory empty.

Usage: python bench/bench_neighbors.py [--smoke]
"""

import argparse
import sys, os, time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import Banker, ensure_survivable_backend, run_case


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    # BEFORE any device op (the transport check must not race a hang)
    fallback = ensure_survivable_backend()

    import jax
    import jax.numpy as jnp

    from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq, refine

    n, d, nq, k, n_lists = 1_000_000, 96, 4096, 10, 1024
    if fallback or args.smoke:
        # chip geometry is CPU-infeasible; a shrunk run that completes
        # and banks beats a full-size one that never finishes. Case
        # names carry the real geometry, so rows stay self-describing.
        n, nq, n_lists = (20_000, 256, 64) if args.smoke else (100_000, 512, 256)
    if args.smoke:
        fallback = None  # smoke rehearsals keep the .cpu diversion

    bank = Banker(
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "BENCH_neighbors.json"),
        meta={"dataset_rows": n, "dim": d, "queries": nq, "k": k,
              "n_lists": n_lists, "smoke": bool(args.smoke)},
        fallback=fallback,
    )

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((n, d), dtype=np.float32))
    q = jnp.asarray(rng.random((nq, d), dtype=np.float32))

    bank.add(run_case(
        "neighbors",
        f"brute_force_{n}x{d}_q{nq}_k{k}",
        lambda: brute_force.knn(x, q, k=k),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    ), echo=False)
    bank.check_transport()
    # fused scan+select-k engine (ops/fused_scan via matrix.scan_select_k
    # strategy="fused"): exact over bf16-rounded operands, score matrix
    # never touches HBM — the ISSUE 10 A/B against the tiled path
    bank.add(run_case(
        "neighbors",
        f"brute_force_fused_{n}x{d}_q{nq}_k{k}",
        lambda: brute_force.knn(x, q, k=k, engine="pallas"),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    ), echo=False)
    bank.check_transport()

    t0 = time.perf_counter()
    fidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=n_lists, kmeans_n_iters=10), x)
    jax.block_until_ready(fidx.slot_rows)
    bank.add({"suite": "neighbors", "case": f"ivf_flat_build_{n}",
              "value": round(time.perf_counter() - t0, 1), "unit": "s"},
             echo=True)
    bank.add(run_case(
        "neighbors",
        f"ivf_flat_search_{n}_q{nq}_k{k}_probes32",
        lambda: ivf_flat.search(ivf_flat.SearchParams(n_probes=32), fidx, q, k),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    ), echo=False)
    bank.add(run_case(
        "neighbors",
        f"ivf_flat_search_list_{n}_q{nq}_k{k}_probes32",
        lambda: ivf_flat.search(
            ivf_flat.SearchParams(n_probes=32, engine="list"), fidx, q, k
        ),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    ), echo=False)
    bank.check_transport()
    # fused list-scan engine: exact in-kernel scan+select per probed
    # block (no score tile in HBM, no bin-trim recall tax)
    bank.add(run_case(
        "neighbors",
        f"ivf_flat_search_fused_{n}_q{nq}_k{k}_probes32",
        lambda: ivf_flat.search(
            ivf_flat.SearchParams(n_probes=32, engine="pallas"), fidx, q, k
        ),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    ), echo=False)
    bank.check_transport()

    t0 = time.perf_counter()
    pidx = ivf_pq.build(ivf_pq.IndexParams(n_lists=n_lists, kmeans_n_iters=10, pq_dim=48), x)
    jax.block_until_ready(pidx.codes)
    bank.add({"suite": "neighbors", "case": f"ivf_pq_build_{n}",
              "value": round(time.perf_counter() - t0, 1), "unit": "s"},
             echo=True)
    bank.add(run_case(
        "neighbors",
        f"ivf_pq_search_{n}_q{nq}_k{k}_probes32",
        lambda: ivf_pq.search(ivf_pq.SearchParams(n_probes=32), pidx, q, k),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    ), echo=False)
    bank.add(run_case(
        "neighbors",
        f"ivf_pq_search_list_{n}_q{nq}_k{k}_probes32",
        lambda: ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32, score_mode="recon8_list"), pidx, q, k
        ),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    ), echo=False)
    bank.check_transport()
    # refinement (cpp/bench/neighbors/refine.cu): re-rank 4*k PQ candidates
    _, cand = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), pidx, q, 4 * k)
    bank.add(run_case(
        "neighbors",
        f"refine_{nq}x{4*k}_to_k{k}",
        lambda: refine(x, q, cand, k),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    ), echo=False)
    # fused exact-distance rerank over the same candidate sets
    bank.add(run_case(
        "neighbors",
        f"refine_fused_{nq}x{4*k}_to_k{k}",
        lambda: refine(x, q, cand, k, strategy="fused"),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    ), echo=False)
    print(f"banked -> {bank.path}")


if __name__ == "__main__":
    main()
