"""k-NN benches: brute-force, IVF-Flat, IVF-PQ (reference
cpp/bench/neighbors/knn.cuh + refine.cu). Reports search QPS; index build
is timed once per config (the reference builds in the fixture setup)."""

import sys, os, time, json

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from common import run_case
from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq, refine


def main():
    rng = np.random.default_rng(0)
    n, d, nq, k = 1_000_000, 96, 4096, 10
    x = jnp.asarray(rng.random((n, d), dtype=np.float32))
    q = jnp.asarray(rng.random((nq, d), dtype=np.float32))

    run_case(
        "neighbors",
        f"brute_force_{n}x{d}_q{nq}_k{k}",
        lambda: brute_force.knn(x, q, k=k),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    )
    # fused-scan engine (fused_l2_knn analogue): near-exact bin trim,
    # score tiles never round-trip HBM — A/B against the tiled path
    run_case(
        "neighbors",
        f"brute_force_pallas_{n}x{d}_q{nq}_k{k}",
        lambda: brute_force.knn(x, q, k=k, engine="pallas"),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    )

    t0 = time.perf_counter()
    fidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=1024, kmeans_n_iters=10), x)
    jax.block_until_ready(fidx.slot_rows)
    print(json.dumps({"suite": "neighbors", "case": "ivf_flat_build_1M", "value": round(time.perf_counter() - t0, 1), "unit": "s"}), flush=True)
    run_case(
        "neighbors",
        f"ivf_flat_search_{n}_q{nq}_k{k}_probes32",
        lambda: ivf_flat.search(ivf_flat.SearchParams(n_probes=32), fidx, q, k),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    )
    run_case(
        "neighbors",
        f"ivf_flat_search_list_{n}_q{nq}_k{k}_probes32",
        lambda: ivf_flat.search(
            ivf_flat.SearchParams(n_probes=32, engine="list"), fidx, q, k
        ),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    )

    t0 = time.perf_counter()
    pidx = ivf_pq.build(ivf_pq.IndexParams(n_lists=1024, kmeans_n_iters=10, pq_dim=48), x)
    jax.block_until_ready(pidx.codes)
    print(json.dumps({"suite": "neighbors", "case": "ivf_pq_build_1M", "value": round(time.perf_counter() - t0, 1), "unit": "s"}), flush=True)
    run_case(
        "neighbors",
        f"ivf_pq_search_{n}_q{nq}_k{k}_probes32",
        lambda: ivf_pq.search(ivf_pq.SearchParams(n_probes=32), pidx, q, k),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    )
    run_case(
        "neighbors",
        f"ivf_pq_search_list_{n}_q{nq}_k{k}_probes32",
        lambda: ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32, score_mode="recon8_list"), pidx, q, k
        ),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    )
    # refinement (cpp/bench/neighbors/refine.cu): re-rank 4*k PQ candidates
    _, cand = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), pidx, q, 4 * k)
    run_case(
        "neighbors",
        f"refine_{nq}x{4*k}_to_k{k}",
        lambda: refine(x, q, cand, k),
        iters=3,
        warmup=1,
        items=float(nq),
        unit="qps",
    )


if __name__ == "__main__":
    main()
