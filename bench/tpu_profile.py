"""One-process TPU profiling session for the headline ANN paths.

Ordered by decision value per minute of relay lifetime (the loopback
relay has died mid-session repeatedly): chunk-matmul + pairwise TFLOPS
microbenches first, then the full 1M x 96 IVF-PQ build and the QPS +
recall ladder over every PQ scoring engine (recon8_list bf16/int8 x
approx/pallas trim, recon8, lut) and the refined low-probe config, a
second 1M-row IVF-Flat index laddering its three engines (query / list /
fused residual scan), and LAST the stage-timed build breakdown + the
bf16-vs-HIGHEST trainer-precision comparison (duplicate kmeans fits).
One process = one chip claim (the tunnel is single-client). The results
record is printed and persisted INCREMENTALLY (after each banked
section, and on any dead-transport bail) to
/tmp/tpu_profile_results.json plus TPU_PROFILE_RESULTS.json at the repo
root (left untracked deliberately: a post-session chip recovery drops the
numbers where the next round finds and commits them).

Usage (from the repo root, chip exclusive):  python bench/tpu_profile.py
"""
import json, os, sys, time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax, jax.numpy as jnp
import numpy as np

R = {}


def _bail_if_transport_dead(where: str) -> None:
    """A dead relay turns every further device RPC into a ~50-min hang;
    checking between stages costs nothing (a /proc scan, no connection)
    and lets the session exit promptly WITH the results measured so far
    persisted (the 2026-07-31 outage killed the relay mid-kmeans and the
    whole ladder was lost)."""
    try:
        from raft_tpu.core.config import relay_transport_down
    except Exception:
        return
    if relay_transport_down():
        R["aborted"] = f"relay transport died before {where}"
        print(f"relay transport dead before {where}; writing partial results",
              file=sys.stderr, flush=True)
        _finish(R)
        sys.exit(3)


def t(name, fn):
    _bail_if_transport_dead(name)
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    R[name] = round(dt, 3)
    print(f"{name}: {dt:.3f}s", flush=True)
    return out

def measure_search(key_name, run, truth, nq, k, label=None):
    """Shared warm + 3-iter timing + recall record for a search callable
    returning (dists, ids); errors land in R without aborting."""
    label = label or key_name
    _bail_if_transport_dead(key_name)
    try:
        d, i = run()
        jax.block_until_ready((d, i))
        iters = 3
        # pipelined: batches issued back-to-back, ONE sync at the end
        # (device order serializes them) — throughput methodology parity
        # with bench.py and the reference's loop_on_state fixture; a
        # per-iteration sync would add the tunnel round-trip to every
        # batch and distort cross-engine ratios at small batch times
        t0 = time.perf_counter()
        for _ in range(iters):
            d, i = run()
        jax.block_until_ready((d, i))
        el = (time.perf_counter() - t0) / iters
        got = np.asarray(i)
        rec = float(np.mean([len(set(got[j]) & set(truth[j])) / k for j in range(nq)]))
        R[key_name] = {"qps": round(nq / el, 1), "recall": round(rec, 4)}
        print(f"{label}: {nq/el:.0f} qps recall {rec:.4f}", flush=True)
    except Exception as e:
        R[key_name] = {"error": str(e)[:200]}
        print(f"{label} FAILED: {e}", flush=True)
        from raft_tpu.core.config import is_device_fault

        if is_device_fault(e):
            # a TPU kernel fault poisons the PROCESS — every further
            # device op fails the same way (observed 2026-08-01: the lut
            # stage faulted and took the bf/refined/flat ladder with it).
            # Bank what's measured and exit; re-running in a fresh
            # process recovers the chip.
            R["aborted"] = f"device fault during {key_name}"
            _finish(R)
            sys.exit(4)


def main():
    # before any device op: backend init against a dead relay hangs ~25
    # min before failing, and none of the per-stage checks would run
    _bail_if_transport_dead("backend_init")
    # methodology provenance: per-engine "qps" keys are PIPELINED from
    # this marker on (batches issued back-to-back, one sync) — do not
    # compare against synced-era records without accounting for it
    R["qps_methodology"] = "pipelined_v2"
    # Queue staging (RAFT_TPU_PROFILE_STAGE): "critical" runs everything
    # except the stage-timing breakdown and the lut stage and exits, so
    # the headline bench starts ~6 min earlier in a short relay window;
    # "tail" runs only those two (rebuilding the index cache-warm);
    # unset = the full session in one process.
    stage = os.environ.get("RAFT_TPU_PROFILE_STAGE", "")
    early = stage != "tail"
    if stage == "tail":
        # preload the critical stage's banked record: _finish overwrites
        # the results file wholesale, and the tail process starts fresh —
        # without this the ladder keys would be lost to the hint applier.
        # The /tmp copy is the fallback: _finish writes it first, so a
        # kill mid-write of the repo copy leaves /tmp intact.
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for path in (os.path.join(repo, "TPU_PROFILE_RESULTS.json"),
                     "/tmp/tpu_profile_results.json"):
            try:
                with open(path) as f:
                    prior = json.load(f)
                if isinstance(prior, dict):
                    R.update(prior)
                    break
            except (OSError, ValueError) as e:
                print(f"tail preload: could not read {path}: {e}",
                      file=sys.stderr, flush=True)
        # a critical-stage abort marker must not label this (so far
        # successful) tail session; the tail's own bails re-set it
        R.pop("aborted", None)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from common import enable_persistent_cache

    enable_persistent_cache()
    # cheap, high-value numbers first — the relay has died mid-session
    # twice; everything banked before the long kmeans compile survives
    if early:
        _micro_benches(R)
        _pairwise_tflops(R)
        _finish(R)  # persist the partial record before the fragile stages
    from raft_tpu.neighbors import ivf_pq, brute_force
    from raft_tpu.cluster import kmeans_balanced

    n, dim, nq, k = 1_000_000, 96, 4096, 10
    # tail reruns rebuild cache-warm; distinct keys keep the critical
    # stage's cold datagen/build/truth timings in the merged record
    sfx = "_tail" if stage == "tail" else ""
    k1, k2, k3, k4, kc = jax.random.split(jax.random.PRNGKey(0), 5)
    centers0 = jax.random.uniform(kc, (1024, dim), jnp.float32, -5.0, 5.0)
    assign = jax.random.randint(k1, (n,), 0, 1024)
    dataset = t("datagen" + sfx, lambda: centers0[assign] + jax.random.normal(k2, (n, dim), jnp.float32))
    qassign = jax.random.randint(k3, (nq,), 0, 1024)
    queries = centers0[qassign] + jax.random.normal(k4, (nq, dim), jnp.float32)
    jax.block_until_ready(queries)

    # full build FIRST (the engine ladder needs only this index; the
    # stage-timed build breakdown is re-measured at the END — a short
    # relay lifetime must bank the default-flipping decisions, not
    # duplicate kmeans fits)
    params = ivf_pq.IndexParams(n_lists=1024, pq_dim=48, kmeans_n_iters=10)
    index = None
    def do_build():
        nonlocal index
        index = ivf_pq.build(params, dataset)
        return index.codes
    t("full_build" + sfx, do_build)
    R["max_list"] = int(index.codes.shape[1])

    # ---- ground truth ----
    truth = t("bf_truth" + sfx, lambda: brute_force.knn(dataset, queries, k=k)[1])
    truth = np.asarray(truth)

    # ---- engine ladder at n_probes=32, k=10 ----
    # the package re-exports the refine *function* under this name
    from raft_tpu.neighbors import refine as refine_fn
    for mode, dt, idd, trim in () if not early else (
        ("recon8_list", "bf16", "float32", "approx"),
        ("recon8_list", "bf16", "float32", "pallas"),  # fused list-scan kernel
        ("recon8_list", "int8", "float32", "pallas"),  # in-kernel int8 MXU rate
        ("recon8_list", "int8", "float32", "approx"),
        ("recon8_list", "bf16", "bfloat16", "approx"),  # bf16 trim scores
        ("recon8_list", "int8", "bfloat16", "approx"),
        # exact per-superblock top_k trim: quantifies the approx bin-trim
        # recall tax at np32 (VERDICT r4 #6; ann_ivf_pq.cuh:257-265 gates
        # >=0.85 unrefined because the reference's select is exact)
        ("recon8_list", "int8", "bfloat16", "exact"),
        ("recon8", "bf16", "float32", "approx"),
    ):
        p = ivf_pq.SearchParams(
            n_probes=32, score_mode=mode, score_dtype=dt,
            internal_distance_dtype=idd, trim_engine=trim,
        )
        measure_search(
            f"search_{mode}_{dt}_{idd}_{trim}_np32",
            lambda p=p: ivf_pq.search(p, index, queries, k),
            truth, nq, k, label=f"{mode}/{dt}/{idd}/{trim}",
        )
    _finish(R)  # the PQ engine ladder is the #1 default-flip input — bank it

    # chunk_block structure race (round-5 restructure): 0 scores a whole
    # superblock with ONE batched einsum (~nsuper scan iterations per
    # batch); 8 restores the round-4 inner lax.map (~256 serialized scan
    # iterations at this shape — the prime structural suspect for the
    # measured 60x roofline gap, docs/perf.md). Raced on the round-4
    # measured-best engine config; apply_profile_hints fits the
    # listmajor_chunk_block tuned key from these rows.
    from raft_tpu.core import tuned as _tuned0

    p_cb = ivf_pq.SearchParams(
        n_probes=32, score_mode="recon8_list", score_dtype="int8",
        internal_distance_dtype="bfloat16",
    )
    # {0, 8} only: the decision is structural (superblock einsum vs the
    # round-4 inner map); a third middle point costs a fresh ~30 s
    # compile in a historically 9-minute relay window for no extra
    # information
    for cb in (0, 8) if early else ():
        _tuned0._load()["listmajor_chunk_block"] = cb
        measure_search(
            f"search_cb{cb}_int8_bf16trim_np32",
            lambda: ivf_pq.search(p_cb, index, queries, k),
            truth, nq, k, label=f"chunk_block={cb}",
        )
    _tuned0.reload()  # drop the in-memory override, restoring disk state
    _finish(R)

    # brute-force A/B at the same shape: tiled XLA path vs the fused
    # list-scan engine (dataset + truth already resident)
    if early:
        measure_search(
            "bf_tiled_1M", lambda: brute_force.knn(dataset, queries, k=k),
            truth, nq, k, label="bf tiled",
        )
        # bf16-compute variant: one MXU pass vs f32's six-pass parity
        # mode; recall measured against the f32 truth says whether the
        # speed is real at this geometry (CPU rehearsal: +24% @ 0.9898)
        measure_search(
            "bf_tiled_bf16_1M",
            lambda: brute_force.knn(
                dataset, queries, k=k, compute_dtype=jnp.bfloat16
            ),
            truth, nq, k, label="bf tiled bf16",
        )
        measure_search(
            "bf_pallas_1M",
            lambda: brute_force.knn(dataset, queries, k=k, engine="pallas"),
            truth, nq, k, label="bf fused-scan",
        )

    # refined config (n_probes=8 + exact refine of 4k shortlist) raced
    # over the listmajor chunk width: at np8 the P//chunk + n_lists
    # fragmentation bound leaves 128-row chunks ~25% full (scan FLOPs/
    # score bytes pull toward small chunks, store streams toward large —
    # empirical). chunk=128 doubles as the plain refined-np8 record.
    from raft_tpu.core import tuned as _tuned

    p = ivf_pq.SearchParams(n_probes=8, score_mode="recon8_list")

    def run_refined():
        _, cand = ivf_pq.search(p, index, queries, 4 * k)
        return refine_fn(dataset, queries, cand, k)

    for ch in (128, 64, 32) if early else ():
        _tuned._load()["listmajor_chunk"] = ch
        measure_search(f"search_refined_np8_chunk{ch}", run_refined,
                       truth, nq, k, label=f"refined np8 chunk={ch}")
    _tuned.reload()  # drop the in-memory override, restoring disk state

    # approx-vs-exact trim at unrefined np8 (the headline's PQ-scan shape;
    # pairs with the np32 exact row above for the VERDICT r4 #6 tax table)
    for trim in ("approx", "exact") if early else ():
        p8 = ivf_pq.SearchParams(
            n_probes=8, score_mode="recon8_list", trim_engine=trim
        )
        measure_search(
            f"search_unrefined_np8_{trim}",
            lambda p8=p8: ivf_pq.search(p8, index, queries, k),
            truth, nq, k, label=f"unrefined np8 {trim} trim",
        )
    _finish(R)

    # ---- IVF-Flat engine ladder (query / list / fused residual scan) ----
    if early:
        try:
            from raft_tpu.neighbors import ivf_flat

            fparams = ivf_flat.IndexParams(n_lists=1024, kmeans_n_iters=10)
            findex = None

            def do_fbuild():
                nonlocal findex
                findex = ivf_flat.build(fparams, dataset)
                return findex.list_data

            t("ivf_flat_build", do_fbuild)
            for engine in ("query", "list", "pallas"):
                p = ivf_flat.SearchParams(n_probes=32, engine=engine)
                measure_search(
                    f"flat_search_{engine}_np32",
                    lambda p=p: ivf_flat.search(p, findex, queries, k),
                    truth, nq, k, label=f"flat/{engine}",
                )
        except Exception as e:
            R["ivf_flat_build"] = {"error": str(e)[:200]}
            print(f"ivf_flat ladder FAILED: {e}", flush=True)

        # re-run the scoring microbench at the true slot count under
        # *_trueS keys — a failure here must not clobber the banked S=1024
        # numbers (apply_profile_hints prefers trueS when present+valid)
        _micro_benches(R, S=R["max_list"], suffix="_trueS")
        # Everything except the trainer-precision inertia pair (and the
        # stage-timing breakdown) is banked at this point.
        _finish(R)
    if stage == "critical":
        # the stage-timing breakdown + lut run in the separate "tail"
        # queue entry, AFTER the headline bench has banked its rows
        return

    # ---- stage-timed build breakdown + trainer-precision decision ----
    # (duplicates work full_build already did, so it runs LAST)
    pq_dim, rot_dim = 48, 96
    key = jax.random.PRNGKey(0)
    key, rk = jax.random.split(key)
    rotation = t("rotation", lambda: ivf_pq._make_rotation(rk, rot_dim, dim, False))
    n_train = max(1024 * 4, int(n * 0.5))
    key, sk = jax.random.split(key)
    sel = jax.random.choice(sk, n, (n_train,), replace=False)
    xtr = t("trainset_gather", lambda: dataset[sel] @ rotation.T)
    centers = t("kmeans_fit", lambda: kmeans_balanced.fit(xtr, 1024, n_iters=10, metric="sqeuclidean", seed=0))
    # single-pass-bf16 trainer variant: time + quality delta vs HIGHEST
    from jax import lax as _lax
    cfast = t("kmeans_fit_bf16", lambda: kmeans_balanced.fit(
        xtr, 1024, n_iters=10, metric="sqeuclidean", seed=0,
        train_precision=_lax.Precision.DEFAULT))
    from raft_tpu.cluster.kmeans_common import cluster_cost_impl
    R["inertia_highest"] = float(cluster_cost_impl(xtr, centers))
    R["inertia_bf16"] = float(cluster_cost_impl(xtr, cfast))
    nb = 256
    max_cb = 65536
    key, rk2 = jax.random.split(key)
    cb_sel = jax.random.choice(rk2, n_train, (max_cb,), replace=False)
    x_cb = xtr[cb_sel]
    labels_cb = t("cb_predict", lambda: kmeans_balanced.predict(x_cb, centers, metric="sqeuclidean"))
    residuals = x_cb - centers[labels_cb]
    key, ck = jax.random.split(key)
    pqc = t("codebook_em", lambda: ivf_pq._train_codebooks_per_subspace(ck, residuals, pq_dim, nb, 25))
    t("label_and_encode_1M", lambda: ivf_pq.label_and_encode(dataset, rotation, centers, pqc, params.metric, False))
    _finish(R)

    # lut engine DEAD LAST in the whole session: its gather kernel-faulted
    # the device on 2026-08-01 (as the 5-D gather form did in round 1),
    # and a faulted process loses every stage scheduled after it. The
    # library now fences lut on TPU (VERDICT r4 #5); this is the one
    # sanctioned fault-repro context, so it sets the override.
    os.environ[ivf_pq._LUT_TPU_OVERRIDE] = "1"
    p = ivf_pq.SearchParams(n_probes=32, score_mode="lut")
    measure_search(
        "search_lut_bf16_float32_approx_np32",
        lambda: ivf_pq.search(p, index, queries, k),
        truth, nq, k, label="lut/bf16/float32/approx",
    )
    _finish(R)


def _time_tflops(R, name, fn, flops):
    """Warm once, time 10 iters, record {ms, tflops} under `name` (the
    shared loop for every early-banked throughput stage)."""
    try:
        jax.block_until_ready(fn())
        t0 = time.perf_counter()
        for _ in range(10):
            jax.block_until_ready(fn())
        dt = (time.perf_counter() - t0) / 10
        R[name] = {"ms": round(dt * 1e3, 2), "tflops": round(flops / dt / 1e12, 2)}
        print(f"{name}: {dt*1e3:.2f} ms {flops/dt/1e12:.2f} TFLOP/s", flush=True)
    except Exception as e:
        R[name] = {"error": str(e)[:200]}
        print(f"{name} FAILED: {e}", flush=True)


def _micro_benches(R, S=1024, suffix=""):
    """int8 vs bf16 scoring microbench at the chunk-matmul shape of the
    fused list scan. Runs FIRST in the session with a representative
    S=1024 slot count: its compiles are seconds, and the relay link has
    twice died during the multi-minute balanced-kmeans compile later on —
    the cheap headline numbers must be banked before the fragile stage.
    When the session survives the build, main() re-runs it at the
    measured S=max_list so the recorded keys end at the true shape."""
    _bail_if_transport_dead("micro_benches")
    CB, CHUNK, ROT, NBLK = 8, 128, 96, 32
    r8 = jax.random.randint(jax.random.PRNGKey(1), (NBLK, CB, S, ROT), -127, 128, jnp.int8)
    qs = jax.random.normal(jax.random.PRNGKey(2), (NBLK, CB, CHUNK, ROT), jnp.float32)
    scale = jnp.abs(jax.random.normal(jax.random.PRNGKey(3), (ROT,))) * 0.01 + 0.01
    jax.block_until_ready((r8, qs))

    @jax.jit
    def v1(r8, qs):
        def blk(inp):
            r, q = inp
            deq = r.astype(jnp.bfloat16) * scale.astype(jnp.bfloat16)[None, None, :]
            return jnp.einsum("lqd,lsd->lqs", q.astype(jnp.bfloat16), deq,
                              preferred_element_type=jnp.float32)
        return jax.lax.map(blk, (r8, qs))

    @jax.jit
    def v2(r8, qs):
        def blk(inp):
            r, q = inp
            qscaled = q * scale[None, None, :]
            qa = jnp.max(jnp.abs(qscaled), axis=2, keepdims=True) + 1e-12
            q8 = jnp.clip(jnp.round(qscaled / qa * 127.0), -127, 127).astype(jnp.int8)
            dots = jnp.einsum("lqd,lsd->lqs", q8, r, preferred_element_type=jnp.int32)
            return dots.astype(jnp.float32) * (qa / 127.0)
        return jax.lax.map(blk, (r8, qs))

    flops = 2 * NBLK * CB * CHUNK * S * ROT
    for name, fn in (("micro_bf16", v1), ("micro_int8", v2)):
        _time_tflops(R, name + suffix, lambda fn=fn: fn(r8, qs), flops)
    R["micro_S" + suffix] = S  # shape provenance for the recorded keys


def _pairwise_tflops(R):
    """Pairwise-distance TFLOPS/chip (BASELINE.md's second headline
    metric) at an MXU-saturating shape, banked early for the same
    fragile-relay reason as the matmul microbench."""
    _bail_if_transport_dead("pairwise_tflops")
    from raft_tpu.distance import pairwise_distance

    m = n = 8192
    d = 768
    x = jax.random.normal(jax.random.PRNGKey(7), (m, d), jnp.bfloat16)
    y = jax.random.normal(jax.random.PRNGKey(8), (n, d), jnp.bfloat16)
    jax.block_until_ready((x, y))
    for metric in ("sqeuclidean", "cosine"):
        _time_tflops(
            R, f"pairwise_{metric}_bf16",
            lambda metric=metric: pairwise_distance(x, y, metric=metric),
            2.0 * m * n * d,
        )


def _finish(R):
    """Print + persist the (possibly partial) results record."""
    print(json.dumps(R), flush=True)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for path in ("/tmp/tpu_profile_results.json",
                 os.path.join(repo, "TPU_PROFILE_RESULTS.json")):
        try:
            with open(path, "w") as f:
                json.dump(R, f, indent=1)
        except OSError as e:
            print(f"could not write {path}: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
