"""100M streamed-build REHEARSAL as a resumable job DAG: the on-disk →
FileBatchLoader → incremental-extend pipeline of the BASELINE north star
(100M x 768 on a pod), exercised end-to-end at a scaled-down geometry
and extrapolated — and now PREEMPTION-SAFE (ISSUE 8): the pipeline is a
`raft_tpu.jobs.Job` of four stages

    make_data -> train -> stream_extend -> search_eval

each committing a CRC-verified artifact into a JobDir, so a run killed
at ANY point (SIGKILL included) re-runs the same command line and
resumes: completed stages skip, and `stream_extend` resumes INSIDE
itself at the last batch-boundary checkpoint (`jobs.streaming`) to a
bit-identical index. `make_data` writes the dataset chunk-by-chunk
behind a durable progress marker (`jobs.resumable_write_npy` — the
`BENCH_10M_PARTIAL` failure-class fix), so even dataset synthesis
resumes instead of rewriting.

CPU-timed is meaningful here (VERDICT r4 #3): the pipeline shape — IO
overlap, incremental table growth, host->device staging — is what's
being rehearsed; chip day re-times it with the MXU doing the encode.

Run: `python bench/bench_100m_rehearsal.py [--rows N] [--dim D]
[--job-dir DIR]` (defaults 4M x 96 ≈ 1.5 GB on disk; pass
--rows 100000000 --dim 768 --job-dir /data/jobs/b100m on a pod).
Without --job-dir the JobDir is a temp dir (deleted afterwards — no
resume across invocations); with it, re-running after a kill resumes.
`--stop-after STAGE` suspends the job right after STAGE commits (exit
code 75, the preemption drill seam). SIGTERM mid-run is equivalent:
checkpoint-then-suspend, re-run to resume.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import common  # noqa: F401  (pins CPU when JAX_PLATFORMS=cpu asks)


def build_job(job_dir: str, rows: int, dim: int, batch: int, n_lists: int,
              bank, path: str = None, stop_after: str = None):
    """Declare the DAG. `path` (an existing dataset) drops the
    make_data stage; everything downstream fingerprints the dataset
    geometry so changing --rows/--dim re-runs from the right stage."""
    from raft_tpu import jobs
    from raft_tpu.neighbors import ivf_pq

    job = jobs.Job("bench_100m_rehearsal", job_dir)
    _maybe_suspend = common.stop_after_hook(job, stop_after)

    n_blobs = 2048
    make_chunk = common.blob_chunk_maker(n_blobs, dim)

    if path is None:
        def make_data(ctx):
            t0 = time.perf_counter()
            stats = jobs.resumable_write_npy(
                ctx.artifact_path("dataset.npy"), rows, dim,
                min(rows, 1_000_000), make_chunk, ctx=ctx)
            bank.add({"stage": "datagen_to_disk",
                      "s": round(time.perf_counter() - t0, 1),
                      "bytes": int(stats["nbytes"])})
            _maybe_suspend("make_data")
            return {"_artifacts": {"dataset": ctx.artifact_path("dataset.npy")},
                    "nbytes": int(stats["nbytes"])}

        job.add_stage("make_data", make_data,
                      inputs={"rows": rows, "dim": dim, "blobs": n_blobs})
        deps = ("make_data",)
        data_path = lambda ctx: ctx.dep_artifact("make_data", "dataset.npy")  # noqa: E731
    else:
        deps = ()
        data_path = lambda ctx: path  # noqa: E731

    def train(ctx):
        from raft_tpu import io as rio

        t0 = time.perf_counter()
        train_rows = min(rows, max(n_lists * 64, 512 * 1024))
        head = next(iter(rio.FileBatchLoader(data_path(ctx), train_rows)))[0]
        params = ivf_pq.IndexParams(
            n_lists=n_lists, pq_dim=max(8, dim // 2 // 8 * 8),
            kmeans_n_iters=4, add_data_on_build=False,
            kmeans_trainset_fraction=1.0,
        )
        index = ivf_pq.build(params, np.ascontiguousarray(head[:train_rows]))
        ivf_pq.save(ctx.artifact_path("trained"), index)
        bank.add({"stage": "train_quantizers", "train_rows": int(train_rows),
                  "s": round(time.perf_counter() - t0, 1)})
        _maybe_suspend("train")
        return {"_artifacts": {"trained": ctx.artifact_path("trained")},
                "train_rows": int(train_rows)}

    job.add_stage("train", train, deps=deps,
                  inputs={"rows": rows, "dim": dim, "n_lists": n_lists,
                          "path": path})

    def stream_extend(ctx):
        # streamed extend through the prefetch ring (the 100M build
        # loop), checkpointing at an amortized cadence (~n_batches/8)
        # so the kill-loss window stays bounded without the O(n^2)
        # every-batch full-index saves distorting the timed wall
        ckpt_every = common.stream_ckpt_every(rows, batch)
        index = ivf_pq.load(ctx.dep_artifact("train", "trained"))
        batch_times = []

        def on_batch(b, valid, secs):
            batch_times.append(secs)

        t0 = time.perf_counter()
        index, stats = jobs.resumable_extend_from_file(
            "ivf_pq", index, data_path(ctx), batch, ctx=ctx,
            checkpoint_every=ckpt_every, on_batch=on_batch)
        wall = time.perf_counter() - t0
        assert index.size == rows, (index.size, rows)
        ivf_pq.save(ctx.artifact_path("index"), index)
        # rows_this_run, not rows_ingested: a resumed run's wall clock
        # covers only the tail batches — charging the cumulative total
        # would bank inflated throughput (and a wild extrapolation)
        this_run = stats["rows_this_run"]
        row = {"stage": "streamed_extend", "s": round(wall, 1),
               "batches": stats["batches"],
               "resumed_from_batch": stats["resumed_from_batch"],
               "ckpt_every": ckpt_every,
               "rows_per_s": round(this_run / wall, 1) if wall else 0.0}
        if batch_times:
            row.update({
                "batch_s_best": round(min(batch_times), 2),
                "batch_s_worst": round(max(batch_times), 2),
                "io_hidden_frac": round(1.0 - sum(batch_times) / wall, 3),
            })
        bank.add(row)
        _maybe_suspend("stream_extend")
        return {"_artifacts": {"index": ctx.artifact_path("index")},
                "rows_per_s": row["rows_per_s"]}

    job.add_stage("stream_extend", stream_extend, deps=("train",),
                  inputs={"batch": batch, "rows": rows})

    def search_eval(ctx):
        # recall-sanity search off the committed index + the 100M
        # extrapolation (rows/s scales ~1/dim for the encode term)
        from raft_tpu import io as rio

        index = ivf_pq.load(ctx.dep_artifact("stream_extend", "index"))
        nq = 256
        if path is None:
            queries = make_chunk(0, nq)  # same blob mixture as the data
        else:
            queries = np.ascontiguousarray(
                next(iter(rio.FileBatchLoader(data_path(ctx), nq)))[0][:nq])
        sp = ivf_pq.SearchParams(n_probes=16)
        import jax

        t0 = time.perf_counter()
        d, i = ivf_pq.search(sp, index, queries, 10)
        jax.block_until_ready((d, i))
        dt = time.perf_counter() - t0
        bank.add({"stage": "search_eval", "nq": nq,
                  "qps_cold": round(nq / dt, 1)})
        # a resumed run whose stream_extend tail ingested zero rows has
        # no throughput measurement — skip the extrapolation rather
        # than fabricate one from a placeholder rows/s (the earlier,
        # real streamed_extend row is already banked)
        rows_per_s = ctx.dep_meta("stream_extend").get("rows_per_s") or 0.0
        if rows_per_s > 0:
            target_rows, target_dim = 100_000_000, 768
            est_s = target_rows / rows_per_s * (target_dim / dim)
            bank.add({"stage": "extrapolate_100Mx768",
                      "est_build_s_single_device": round(est_s, 0),
                      "est_build_s_v5e64_linear": round(est_s / 64, 0)})
        _maybe_suspend("search_eval")
        return {"nq": nq}

    job.add_stage("search_eval", search_eval, deps=("stream_extend",),
                  inputs={"nq": 256})
    return job


def main(rows: int, dim: int, batch: int, n_lists: int, path: str = None,
         job_dir: str = None, stop_after: str = None) -> int:
    from raft_tpu.core.config import chip_probe_would_hang

    if chip_probe_would_hang():
        print(json.dumps({"aborted": "relay transport dead"}), flush=True)
        return 3

    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_100M_REHEARSAL.json")
    bank = common.Banker(out, {"n_rows": rows, "dim": dim, "batch": batch,
                               "n_lists": n_lists}, resume=common.job_resuming(job_dir))
    common.enable_persistent_cache()

    with common.job_dir_or_temp(job_dir, "raft_tpu_100m_") as jd:
        job = build_job(jd, rows, dim, batch, n_lists, bank,
                        path=path, stop_after=stop_after)
        rc = common.run_job_to_exit(job)
        if rc == 0:
            bank.set("done", True)
        return rc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4_000_000)
    ap.add_argument("--dim", type=int, default=96)
    ap.add_argument("--batch", type=int, default=1_000_000)
    ap.add_argument("--n-lists", type=int, default=2048)
    ap.add_argument("--path", default=None,
                    help="existing npy/big-ann file instead of synthetic")
    ap.add_argument("--job-dir", default=None,
                    help="durable JobDir: re-run the same command after "
                         "a kill/preemption to resume (temp dir, no "
                         "resume, when omitted)")
    ap.add_argument("--stop-after", default=None,
                    help="suspend (exit 75) after this stage commits — "
                         "the preemption drill seam")
    a = ap.parse_args()
    sys.exit(main(a.rows, a.dim, a.batch, a.n_lists, a.path,
                  a.job_dir, a.stop_after))
