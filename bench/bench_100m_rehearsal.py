"""100M streamed-build REHEARSAL: the on-disk → FileBatchLoader →
incremental-extend pipeline of the BASELINE north star (100M x 768 on a
pod), exercised end-to-end at a scaled-down geometry and extrapolated.

The 10M bench (bench_10m_build.py) streams from host RAM; the 100M
regime cannot hold the dataset in RAM either, so its build path is
`io.extend_from_file` (C++ prefetch ring hiding file IO behind the
encode+scatter device work — batch_load_iterator parity,
ann_utils.cuh:388). This rehearsal:

  1. writes an npy dataset to disk in chunks (never holding it whole),
  2. trains the quantizers on a subsampled head slice,
  3. streams the file through extend_from_file, timing per-batch extend,
  4. reports measured rows/s and the extrapolated 100M wall-clock.

CPU-timed is meaningful here (VERDICT r4 #3): the pipeline shape — IO
overlap, incremental table growth, host->device staging — is what's
being rehearsed; chip day re-times it with the MXU doing the encode.

Run: `python bench/bench_100m_rehearsal.py [--rows N] [--dim D]`
(defaults 4M x 96 ≈ 1.5 GB on disk; pass --rows 100000000 --dim 768 on
a pod with the real dataset path).
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import common  # noqa: F401  (pins CPU when JAX_PLATFORMS=cpu asks)


def main(rows: int, dim: int, batch: int, n_lists: int, path: str = None):
    from raft_tpu.core.config import chip_probe_would_hang

    if chip_probe_would_hang():
        print(json.dumps({"aborted": "relay transport dead"}), flush=True)
        sys.exit(3)
    out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_100M_REHEARSAL.json")
    bank = common.Banker(out, {"n_rows": rows, "dim": dim, "batch": batch,
                               "n_lists": n_lists})
    common.enable_persistent_cache()
    import jax.numpy as jnp

    from raft_tpu import io as rio
    from raft_tpu.neighbors import ivf_pq

    tmpdir = None
    if path is None:
        tmpdir = tempfile.mkdtemp(prefix="raft_tpu_100m_")
        path = os.path.join(tmpdir, "dataset.npy")
        rng = np.random.default_rng(0)
        n_blobs = 2048
        centers = rng.uniform(-5.0, 5.0, (n_blobs, dim)).astype(np.float32)
        t0 = time.perf_counter()
        # chunked append-write: the file is built without ever holding
        # the dataset in RAM (the shape the 100M source data arrives in)
        header = np.lib.format.header_data_from_array_1_0(
            np.empty((0, dim), np.float32))
        header["shape"] = (rows, dim)
        with open(path, "wb") as f:
            np.lib.format.write_array_header_1_0(f, header)
            step = min(rows, 1_000_000)
            for lo in range(0, rows, step):
                hi = min(lo + step, rows)
                a = rng.integers(0, n_blobs, hi - lo)
                blk = centers[a] + rng.standard_normal(
                    (hi - lo, dim)).astype(np.float32)
                f.write(np.ascontiguousarray(blk).tobytes())
        bank.add({"stage": "datagen_to_disk",
                  "s": round(time.perf_counter() - t0, 1),
                  "bytes": os.path.getsize(path)})

    try:
        # quantizer training on a head slice via the loader (memmap path)
        t0 = time.perf_counter()
        train_rows = min(rows, max(n_lists * 64, 512 * 1024))
        head = next(iter(rio.FileBatchLoader(path, train_rows)))[0]
        params = ivf_pq.IndexParams(
            n_lists=n_lists, pq_dim=max(8, dim // 2 // 8 * 8),
            kmeans_n_iters=4, add_data_on_build=False,
            kmeans_trainset_fraction=1.0,
        )
        index = ivf_pq.build(params, np.ascontiguousarray(head[:train_rows]))
        bank.add({"stage": "train_quantizers", "train_rows": int(train_rows),
                  "s": round(time.perf_counter() - t0, 1)})

        # streamed extend through the prefetch ring (the 100M build loop)
        t0 = time.perf_counter()
        n_batches = [0]
        batch_times = []

        def timed_extend(idx, block, ids):
            bt = time.perf_counter()
            idx = ivf_pq.extend(idx, block, ids)
            idx.codes.block_until_ready()
            batch_times.append(time.perf_counter() - bt)
            n_batches[0] += 1
            return idx

        index = rio.extend_from_file(timed_extend, index, path, batch)
        wall = time.perf_counter() - t0
        rows_s = rows / wall
        bank.add({"stage": "streamed_extend", "s": round(wall, 1),
                  "batches": n_batches[0],
                  "rows_per_s": round(rows_s, 1),
                  "batch_s_best": round(min(batch_times), 2),
                  "batch_s_worst": round(max(batch_times), 2),
                  "io_hidden_frac": round(
                      1.0 - sum(batch_times) / wall, 3)})
        assert index.size == rows, (index.size, rows)

        # extrapolation to the north-star geometry: rows/s scales ~1/dim
        # for the encode (matmul-dominated) term, so scale by dim ratio
        target_rows, target_dim = 100_000_000, 768
        est_s = target_rows / rows_s * (target_dim / dim)
        bank.add({"stage": "extrapolate_100Mx768",
                  "est_build_s_single_device": round(est_s, 0),
                  "est_build_s_v5e64_linear": round(est_s / 64, 0)})
        bank.set("done", True)
    finally:
        if tmpdir is not None:
            import shutil

            shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4_000_000)
    ap.add_argument("--dim", type=int, default=96)
    ap.add_argument("--batch", type=int, default=1_000_000)
    ap.add_argument("--n-lists", type=int, default=2048)
    ap.add_argument("--path", default=None,
                    help="existing npy/big-ann file instead of synthetic")
    a = ap.parse_args()
    main(a.rows, a.dim, a.batch, a.n_lists, a.path)
