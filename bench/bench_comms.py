"""Collective micro-benches: ungrouped vs grouped (comm_split) reductions.

Grouped reductions ride a masked (G, ...) plane stack — one full-axis
collective computing every group's result at O(G)x the payload
(comms.py `_group_planes`; shard_map lacks axis_index_groups). This suite
measures that cost curve so the docs' "prefer few/large groups on hot
paths" guidance is numbers, not folklore (VERDICT r3 weak #7). Reference
analogue: the NCCL group sweep implicit in `comms_test.hpp`'s split
tests — NCCL communicators don't pay this multiplier, which is exactly
why the curve is worth recording on TPU hardware.

Runs on whatever mesh exists (single chip: world=1, grouping degenerates,
suite skips). Payload is a (rows, 256) f32 block per rank, the size class
the distributed searches psum during merges.
"""

import json
import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
from jax.sharding import PartitionSpec as P

from common import run_case


def main():
    # Comms() initializes the backend — bail in milliseconds on a dead
    # relay instead of hanging ~25 min (the shared guard; no-op when the
    # env pins CPU)
    from raft_tpu.core.config import chip_probe_would_hang

    if chip_probe_would_hang():
        print(json.dumps({"suite": "comms",
                          "aborted": "relay transport dead"}), flush=True)
        sys.exit(3)
    from raft_tpu.comms import Comms
    from raft_tpu.comms.comms import op_t

    comms = Comms()
    world = comms.get_size()
    if world < 2:
        print(json.dumps({"suite": "comms", "skipped": "world=1"}),
              flush=True)
        return
    ac = comms.comms
    rng = np.random.default_rng(0)
    rows, d = 64, 256
    x = rng.standard_normal((world, rows, d)).astype(np.float32)

    def bench_split(n_groups: int):
        colors = [r * n_groups // world for r in range(world)]

        def body(xs):
            sub = ac.comm_split(colors) if n_groups > 1 else ac
            return sub.allreduce(xs[0], op_t.SUM)

        f = jax.jit(lambda xs: jax.shard_map(
            body, mesh=comms.mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False)(xs))
        xsh = comms.shard(x)
        run_case("comms", f"allreduce_sum_g{n_groups}_w{world}",
                 lambda: f(xsh),
                 items=float(world * rows * d), unit="elems/s")

    # G=1 is the native psum baseline; the grouped points show the O(G)
    # plane multiplier (each halving of group size doubles plane count)
    g = 1
    while g <= world // 2:
        bench_split(g)
        g *= 2

    # grouped-reduce schedule race: intra-group ppermute ring vs masked
    # planes psum (comms._grouped_schedule): per-rank volume is
    # (s_max - 1) vs ~2G payloads, but latency terms are backend-
    # dependent (s_max - 1 sequential hops vs one fused collective), so
    # `--apply` writes the measured winner to `grouped_reduce_schedule`
    # on chip only (same rule as the merge-schedule key).
    # gwins: per raced shape, (ratio = (s_max-1)/G, winner, margin_ms) —
    # _apply fits the crossover constant from these, not a global winner
    gwins = []
    from jax import lax
    from raft_tpu.comms.comms import op_t as _op

    xsh_g = comms.shard(x)
    for n_groups in sorted({2, world // 2, world // 4}):
        # size-1 groups make the ring a zero-hop identity — a degenerate
        # "win" that must not calibrate the crossover
        if n_groups < 2 or world % n_groups or world // n_groups < 2:
            continue
        colors = [r * n_groups // world for r in range(world)]

        def body_ring(xs):
            sub = ac.comm_split(colors)
            return sub._grouped_reduce_ring(xs[0], _op.SUM)

        def body_planes(xs):
            sub = ac.comm_split(colors)
            planes = sub._group_planes(
                xs[0], sub._reduce_identity(xs.dtype, _op.SUM))
            return lax.psum(planes, sub.axis)[sub._group_id()]

        ms = {}
        for name, body in (("ring", body_ring), ("planes", body_planes)):
            f = jax.jit(lambda xs, body=body: jax.shard_map(
                body, mesh=comms.mesh, in_specs=P("data"),
                out_specs=P("data"), check_vma=False)(xs))
            rec = run_case(
                "comms", f"grouped_{name}_g{n_groups}_w{world}",
                lambda: f(xsh_g),
                items=float(world * rows * d), unit="elems/s")
            ms[name] = rec["ms"]
        gwins.append({
            "ratio": (world // n_groups - 1) / n_groups,
            "winner": min(ms, key=ms.get),
            "margin_ms": abs(ms["ring"] - ms["planes"]),
        })

    # replicated-merge schedule race: log-depth butterfly tournament vs
    # flat packed allgather (mnmg._merge_local_topk's two schedules; both
    # bit-exact) at serving shapes. The winner is backend-dependent —
    # volume/launches dominate on ICI, select compute on the CPU mesh —
    # so `--apply` writes the measured majority winner to tuned key
    # `mnmg_replicated_merge_schedule`, closing the dispatch loop.
    wins = {"allgather": 0.0, "tournament": 0.0}
    if world & (world - 1) == 0:
        from raft_tpu.comms.mnmg import (
            _merge_local_topk_allgather, _merge_local_topk_tournament)

        for nq, k in ((512, 10), (4096, 10), (4096, 100)):
            vv = rng.standard_normal((world * nq, k)).astype(np.float32)
            ii = rng.integers(0, 1 << 20, (world * nq, k)).astype(np.int32)
            vsh, ish = comms.shard(vv), comms.shard(ii)
            ms = {}
            for name, fn in (("allgather", _merge_local_topk_allgather),
                             ("tournament", _merge_local_topk_tournament)):
                f = jax.jit(lambda a, b, fn=fn: jax.shard_map(
                    lambda x, y: fn(ac, x, y, k, True),
                    mesh=comms.mesh, in_specs=(P("data"), P("data")),
                    out_specs=(P("data"), P("data")), check_vma=False)(a, b))
                rec = run_case("comms", f"merge_{name}_nq{nq}_k{k}_w{world}",
                               lambda: f(vsh, ish), items=float(nq),
                               unit="q/s")
                ms[name] = rec["ms"]
            winner = min(ms, key=ms.get)
            wins[winner] += abs(ms["allgather"] - ms["tournament"])
    return {"merge": wins, "grouped": gwins}


def _apply(races: dict) -> None:
    from raft_tpu.core import tuned

    if jax.default_backend() == "cpu":
        # the tuned keys are read by EVERY backend's dispatch, but the
        # schedule winners are backend-dependent and the per-backend
        # defaults already encode the CPU verdict — a CPU-measured key
        # would pin the chip's dispatch to the memcpy-mesh winner
        print(json.dumps({"applied": None,
                          "detail": "cpu race informs the default, not "
                                    "the tuned key; run on the chip"}))
        return
    applied = {}
    hints = {}
    wins = races.get("merge", {})
    if any(wins.values()):
        applied["mnmg_replicated_merge_schedule"] = max(wins, key=wins.get)
        hints["merge_schedule_measured_on"] = jax.default_backend()
    c = _fit_crossover(races.get("grouped", []))
    if c is not None:
        applied["grouped_reduce_crossover"] = c
        hints["grouped_reduce_measured_on"] = jax.default_backend()
    if not applied:
        print(json.dumps({"applied": None, "detail": "no race rows"}))
        return
    tuned.merge(dict(applied, hints=hints))
    print(json.dumps({"applied": applied}))


def _fit_crossover(gwins: list):
    """Calibrate the ring-vs-planes crossover constant c (dispatch: ring
    iff (s_max - 1) <= c * G, i.e. iff ratio <= c) from the raced
    shapes. Ring wins at ratio r imply c >= r; planes wins imply c < r.
    Returns the geometric midpoint of the separating gap, or None when
    the race gives no consistent signal (inconsistent winners keep the
    default rather than writing a misleading constant)."""
    ring_r = [w["ratio"] for w in gwins if w["winner"] == "ring"]
    planes_r = [w["ratio"] for w in gwins if w["winner"] == "planes"]
    if not gwins:
        return None
    if ring_r and planes_r:
        lo, hi = max(ring_r), min(planes_r)
        if lo >= hi:  # winners not separable by ratio — no fit
            return None
        return round(float((lo * hi) ** 0.5), 3)
    if ring_r:  # ring swept: crossover sits above every raced ratio
        return round(float(max(ring_r) * 2), 3)
    return round(float(min(planes_r) / 2), 3)  # planes swept


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--apply", action="store_true",
                    help="write the measured merge-schedule winner to "
                         "tuned_defaults (backend-tagged)")
    a = ap.parse_args()
    wins = main()
    if a.apply and wins is not None:
        _apply(wins)
