"""Collective micro-benches: ungrouped vs grouped (comm_split) reductions.

Grouped reductions ride a masked (G, ...) plane stack — one full-axis
collective computing every group's result at O(G)x the payload
(comms.py `_group_planes`; shard_map lacks axis_index_groups). This suite
measures that cost curve so the docs' "prefer few/large groups on hot
paths" guidance is numbers, not folklore (VERDICT r3 weak #7). Reference
analogue: the NCCL group sweep implicit in `comms_test.hpp`'s split
tests — NCCL communicators don't pay this multiplier, which is exactly
why the curve is worth recording on TPU hardware.

Runs on whatever mesh exists (single chip: world=1, grouping degenerates,
suite skips). Payload is a (rows, 256) f32 block per rank, the size class
the distributed searches psum during merges.
"""

import json
import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
from jax.sharding import PartitionSpec as P

from common import run_case


def main():
    # Comms() initializes the backend — bail in milliseconds on a dead
    # relay instead of hanging ~25 min (the shared guard; no-op when the
    # env pins CPU)
    from raft_tpu.core.config import chip_probe_would_hang

    if chip_probe_would_hang():
        print(json.dumps({"suite": "comms",
                          "aborted": "relay transport dead"}), flush=True)
        sys.exit(3)
    from raft_tpu.comms import Comms
    from raft_tpu.comms.comms import op_t

    comms = Comms()
    world = comms.get_size()
    if world < 2:
        print(json.dumps({"suite": "comms", "skipped": "world=1"}),
              flush=True)
        return
    ac = comms.comms
    rng = np.random.default_rng(0)
    rows, d = 64, 256
    x = rng.standard_normal((world, rows, d)).astype(np.float32)

    def bench_split(n_groups: int):
        colors = [r * n_groups // world for r in range(world)]

        def body(xs):
            sub = ac.comm_split(colors) if n_groups > 1 else ac
            return sub.allreduce(xs[0], op_t.SUM)

        f = jax.jit(lambda xs: jax.shard_map(
            body, mesh=comms.mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False)(xs))
        xsh = comms.shard(x)
        run_case("comms", f"allreduce_sum_g{n_groups}_w{world}",
                 lambda: f(xsh),
                 items=float(world * rows * d), unit="elems/s")

    # G=1 is the native psum baseline; the grouped points show the O(G)
    # plane multiplier (each halving of group size doubles plane count)
    g = 1
    while g <= world // 2:
        bench_split(g)
        g *= 2

    # replicated-merge schedule race: log-depth butterfly tournament vs
    # flat packed allgather (mnmg._merge_local_topk's two schedules; both
    # bit-exact) at serving shapes. The winner is backend-dependent —
    # volume/launches dominate on ICI, select compute on the CPU mesh —
    # so `--apply` writes the measured majority winner to tuned key
    # `mnmg_replicated_merge_schedule`, closing the dispatch loop.
    wins = {"allgather": 0.0, "tournament": 0.0}
    if world & (world - 1) == 0:
        from raft_tpu.comms.mnmg import (
            _merge_local_topk_allgather, _merge_local_topk_tournament)

        for nq, k in ((512, 10), (4096, 10), (4096, 100)):
            vv = rng.standard_normal((world * nq, k)).astype(np.float32)
            ii = rng.integers(0, 1 << 20, (world * nq, k)).astype(np.int32)
            vsh, ish = comms.shard(vv), comms.shard(ii)
            ms = {}
            for name, fn in (("allgather", _merge_local_topk_allgather),
                             ("tournament", _merge_local_topk_tournament)):
                f = jax.jit(lambda a, b, fn=fn: jax.shard_map(
                    lambda x, y: fn(ac, x, y, k, True),
                    mesh=comms.mesh, in_specs=(P("data"), P("data")),
                    out_specs=(P("data"), P("data")), check_vma=False)(a, b))
                rec = run_case("comms", f"merge_{name}_nq{nq}_k{k}_w{world}",
                               lambda: f(vsh, ish), items=float(nq),
                               unit="q/s")
                ms[name] = rec["ms"]
            winner = min(ms, key=ms.get)
            wins[winner] += abs(ms["allgather"] - ms["tournament"])
    return wins


def _apply(wins: dict) -> None:
    from raft_tpu.core import tuned

    if jax.default_backend() == "cpu":
        # the tuned key is read by EVERY backend's dispatch, but the
        # schedule winner is backend-dependent and the per-backend
        # defaults already encode the CPU verdict — a CPU-measured key
        # would pin the chip's dispatch to the memcpy-mesh winner
        print(json.dumps({"applied": None,
                          "detail": "cpu race informs the default, not "
                                    "the tuned key; run on the chip"}))
        return
    if not any(wins.values()):
        print(json.dumps({"applied": None, "detail": "no race rows"}))
        return
    winner = max(wins, key=wins.get)
    tuned.merge({"mnmg_replicated_merge_schedule": winner,
                 "hints": {"merge_schedule_measured_on":
                           jax.default_backend()}})
    print(json.dumps({"applied": {"mnmg_replicated_merge_schedule": winner}}))


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--apply", action="store_true",
                    help="write the measured merge-schedule winner to "
                         "tuned_defaults (backend-tagged)")
    a = ap.parse_args()
    wins = main()
    if a.apply and wins is not None:
        _apply(wins)
