#!/bin/bash
# Watcher v5 (repo-versioned; earlier versions lived only in /tmp and were
# lost to container resets). Polls the loopback relay transport and fires
# bench/run_onchip_queue.sh when the chip comes back.
#
# Rules (NOTES.md round-1 outage postmortem):
#  - never kill a chip process (a killed claim wedges the chip for hours);
#  - one chip client at a time: skip if a queue/bench process is running or
#    /tmp/chip_claim.lock exists (manual override for interactive sessions);
#  - transport check is a /proc/net/tcp LISTEN scan (no connection made),
#    so polling while dead costs nothing and cannot hang.
set -u
cd "$(dirname "$0")/.."
LOG=${WATCH_LOG:-/tmp/chip_watch.log}
exec >>"$LOG" 2>&1
echo "=== watcher v5 start $(date -u +%FT%TZ) pid=$$ ==="
transport_up() {
  python - <<'EOF'
import sys
sys.path.insert(0, '.')
try:
    from raft_tpu.core.config import relay_transport_down
    sys.exit(1 if relay_transport_down() else 0)
except Exception:
    sys.exit(1)
EOF
}
queue_busy() {
  [ -e /tmp/chip_claim.lock ] && return 0
  # matches run_onchip_queue.sh (resume now lives in the job runner:
  # RAFT_TPU_RUN_ALL_JOB_DIR + bench --job-dir flags, see docs/jobs.md)
  pgrep -f 'run_onchip_queue' >/dev/null 2>&1 && return 0
  # every chip-dialing bench entry point the queues can have in flight —
  # firing beside any of them means two clients on the single-client
  # chip (the contention class behind the 2026-08-01 clock artifact)
  pgrep -f 'tpu_profile\.py|bench_10m_build\.py|bench\.py|bench_diag\.py|bench_pallas_scan\.py|bench_select_k_strategies\.py|bench_comms\.py|bench_mnmg_merge\.py|bench_mnmg\.py|run_all\.py|apply_profile_hints\.py' >/dev/null 2>&1 && return 0
  return 1
}
# Start in the "was down" state: a watcher (re)started while the
# transport is already up must still fire — the motivating scenario is a
# container reset that loses the watcher while the chip recovers. The
# run-sentinel (touched by run_onchip_queue.sh at start) keeps that
# first-observation firing from re-running a queue that already ran
# this boot; a genuine DOWN->UP recovery clears it.
was_down=1
while true; do
  if transport_up; then
    if [ "$was_down" -eq 1 ]; then
      echo "transport UP $(date -u +%FT%TZ)"
      if queue_busy; then
        # stay armed (was_down stays 1): the fire condition must retry
        # on the next poll once the busy session releases, not wait for
        # another transport flap
        echo "queue/claim busy; staying armed"
      elif [ -e /tmp/onchip_queue_ran ]; then
        echo "queue already ran this boot; not firing"
        was_down=0
      else
        echo "firing on-chip queue"
        bash bench/run_onchip_queue.sh
        echo "queue finished rc=$? $(date -u +%FT%TZ)"
        was_down=0
      fi
    fi
    sleep 300
  else
    rm -f /tmp/onchip_queue_ran
    if [ "$was_down" -eq 0 ]; then
      echo "transport DOWN $(date -u +%FT%TZ)"
      was_down=1
    fi
    sleep 120
  fi
done
