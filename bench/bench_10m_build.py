"""10M-row single-chip IVF-PQ build via streamed extend (BASELINE config 4;
reference big-build loop: batch_load_iterator, ann_utils.cuh:388) — now a
resumable job DAG (ISSUE 8):

    make_data -> train -> stream_extend -> search_eval

`make_data` synthesizes the dataset + queries ON DISK chunk-by-chunk
behind a durable progress marker (`jobs.resumable_write_npy` — this
bench's `BENCH_10M_PARTIAL.json` death right after make_data is the
failure class that motivated it), `stream_extend` streams the file
through `jobs.resumable_extend_from_file` checkpointing at batch
boundaries, and `search_eval` runs the exact-BF race + the recall-gated
IVF-PQ ladder off the committed index. A run killed at any point —
SIGKILL included — re-runs the same command line and resumes; SIGTERM
checkpoints-then-suspends (exit 75).

Device residency after the build: codes (10M x 48 u8 = 480 MB) + slot
table (40 MB) + the lazily-built int8 reconstruction store — ~1.5 GB of
the v5e's 16 GB HBM.

Prints one JSON line per stage and a final recall-gated QPS record.
Run from the repo root on the chip: `python bench/bench_10m_build.py
[--job-dir DIR]` (~3.8 GB host RAM for the ground-truth upload).
"""

import argparse
import json
import sys, os, time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import common  # noqa: F401  (pins CPU when JAX_PLATFORMS=cpu asks for it)


def build_job(job_dir: str, bank, n: int, dim: int, nq: int, k: int,
              n_lists: int, batch: int, train_rows: int,
              stop_after: str = None):
    from raft_tpu import jobs
    from raft_tpu.neighbors import ivf_pq

    import jax
    import jax.numpy as jnp

    job = jobs.Job("bench_10m_build", job_dir)
    _maybe_suspend = common.stop_after_hook(job, stop_after)

    n_blobs = 4096
    make_chunk = common.blob_chunk_maker(n_blobs, dim)

    def make_data(ctx):
        t0 = time.perf_counter()
        jobs.resumable_write_npy(
            ctx.artifact_path("dataset.npy"), n, dim,
            min(n, 1_000_000), make_chunk, ctx=ctx)
        centers = common.blob_centers(n_blobs, dim)
        rng = np.random.default_rng(2)
        queries = (centers[rng.integers(0, n_blobs, nq)]
                   + rng.standard_normal((nq, dim)).astype(np.float32))
        np.save(ctx.artifact_path("queries.npy"), queries)
        bank.add({"stage": "make_data",
                  "s": round(time.perf_counter() - t0, 1)})
        bank.check_transport()
        _maybe_suspend("make_data")
        return {"_artifacts": {
            "dataset": ctx.artifact_path("dataset.npy"),
            "queries": ctx.artifact_path("queries.npy")}}

    job.add_stage("make_data", make_data,
                  inputs={"n": n, "dim": dim, "nq": nq, "blobs": n_blobs})

    def train(ctx):
        # train on a subsample the build picks per
        # kmeans_trainset_fraction of what it is handed; hand it
        # train_rows so the fraction covers real data
        data = np.load(ctx.dep_artifact("make_data", "dataset.npy"),
                       mmap_mode="r")
        params = ivf_pq.IndexParams(
            n_lists=n_lists, pq_dim=dim // 2, kmeans_n_iters=10,
            add_data_on_build=False)
        t0 = time.perf_counter()
        index = ivf_pq.build(params, np.ascontiguousarray(data[:train_rows]))
        jax.block_until_ready(index.centers)
        train_s = time.perf_counter() - t0
        ivf_pq.save(ctx.artifact_path("trained"), index)
        bank.add({"stage": "train_quantizers", "s": round(train_s, 1)})
        bank.check_transport()
        _maybe_suspend("train")
        return {"_artifacts": {"trained": ctx.artifact_path("trained")},
                "train_s": round(train_s, 1)}

    job.add_stage("train", train, deps=("make_data",),
                  inputs={"n_lists": n_lists, "train_rows": train_rows})

    def stream_extend(ctx):
        # amortized checkpoint cadence: every-batch full-index saves
        # are O(n^2) bytes and would distort the banked throughput
        ckpt_every = common.stream_ckpt_every(n, batch)
        index = ivf_pq.load(ctx.dep_artifact("train", "trained"))
        t0 = time.perf_counter()
        index, stats = jobs.resumable_extend_from_file(
            "ivf_pq", index,
            ctx.dep_artifact("make_data", "dataset.npy"), batch,
            ctx=ctx, checkpoint_every=ckpt_every)
        jax.block_until_ready(index.codes)
        extend_s = time.perf_counter() - t0
        ivf_pq.save(ctx.artifact_path("index"), index)
        # rows_per_s charges only the rows THIS run ingested: on a
        # resume the wall clock covered the tail batches, and n/extend_s
        # would bank an inflated number into the perfgate ledger
        this_run = stats["rows_this_run"]
        bank.add({
            "stage": "extend_streamed", "s": round(extend_s, 1),
            "rows_per_s": (round(this_run / extend_s, 1) if extend_s
                           else 0.0),
            "rows_ingested": stats["rows_ingested"],
            "resumed_from_batch": stats["resumed_from_batch"],
            "ckpt_every": ckpt_every,
            "max_list": int(index.codes.shape[1]),
        })
        bank.check_transport()
        _maybe_suspend("stream_extend")
        return {"_artifacts": {"index": ctx.artifact_path("index")},
                "extend_s": round(extend_s, 1)}

    job.add_stage("stream_extend", stream_extend, deps=("train",),
                  inputs={"batch": batch})

    def search_eval(ctx):
        from raft_tpu.neighbors import brute_force, ivf_pq
        from raft_tpu.neighbors.refine import refine_host

        dataset = np.ascontiguousarray(
            np.load(ctx.dep_artifact("make_data", "dataset.npy"),
                    mmap_mode="r"))
        queries = np.load(ctx.dep_artifact("make_data", "queries.npy"))
        index = ivf_pq.load(ctx.dep_artifact("stream_extend", "index"))
        build_s = (ctx.dep_meta("train").get("train_s", 0.0)
                   + ctx.dep_meta("stream_extend").get("extend_s", 0.0))

        t0 = time.perf_counter()
        _, truth = brute_force.knn(dataset, queries, k)  # fits v5e HBM
        truth = np.asarray(truth)
        bank.add({"stage": "ground_truth",
                  "s": round(time.perf_counter() - t0, 1)})
        bank.check_transport()

        # Exact-BF rows at this scale answer the algorithm-crossover
        # question the 1M headline raised (bf_tiled beat IVF-PQ there);
        # the bf16 variant is one MXU pass instead of six. The scan is
        # the point, so the operands go device-resident ONCE per mode,
        # sequentially, to stay inside the v5e HBM envelope beside the
        # index. Timing/suspect-gating reuse the headline bench's shared
        # protocol pieces.
        import bench as _hb  # repo-root bench.py (same sys.path)

        _min_ms = float(os.environ.get("RAFT_TPU_BENCH_MIN_BATCH_MS", "10"))
        dev = q_dev = nxt = None
        dev_q = jax.device_put(jnp.asarray(queries))
        dev32 = jax.device_put(jnp.asarray(dataset))
        jax.block_until_ready((dev_q, dev32))
        for tag in ("bf_tiled_f32", "bf_tiled_bf16"):
            try:
                if tag == "bf_tiled_bf16":
                    nxt = dev32.astype(jnp.bfloat16)
                    jax.block_until_ready(nxt)
                    del dev32
                    dev, q_dev = nxt, dev_q.astype(jnp.bfloat16)
                else:
                    dev, q_dev = dev32, dev_q
                run = lambda: brute_force.knn(dev, q_dev, k)
                jax.block_until_ready(run())
                iter_ms, dt_pipe = _hb._dual_time(run, iters=2)
                dt = sum(iter_ms) / len(iter_ms) / 1e3
                pipe_ok = 1e3 * dt_pipe >= _min_ms
                got = np.asarray(run()[1])
                rec = float(np.mean(
                    [len(set(got[j]) & set(truth[j])) / k for j in range(nq)]
                ))
                row = {
                    "metric": "bf_10M_qps", "mode": tag,
                    "qps_methodology": "pipelined_v2",
                    "qps": round(nq / (min(dt, dt_pipe) if pipe_ok else dt), 1),
                    "qps_synced": round(nq / dt, 1),
                    "batch_ms_best": round(min(iter_ms), 2),
                    "batch_ms_worst": round(max(iter_ms), 2),
                    "recall@10": round(rec, 4),
                }
                if 1e3 * dt < _min_ms:
                    row["suspect"] = True  # sub-floor clock: docs/perf.md
                bank.add(row)
            except Exception as e:
                bank.add({"stage": tag, "error": str(e)[:200]})
            bank.check_transport()
        # release the device copies before the refine ladder (rebinding
        # is the reliable way to drop function-local references)
        dev = q_dev = dev_q = dev32 = nxt = None  # noqa: F841

        gated = None
        for n_probes, use_refine in ((16, True), (32, True), (64, True),
                                     (64, False)):
            sp = ivf_pq.SearchParams(n_probes=n_probes)

            def run():
                if use_refine:
                    # host-dataset refine: only candidates visit HBM
                    _, cand = ivf_pq.search(sp, index, queries, 4 * k)
                    d, i = refine_host(dataset, queries, np.asarray(cand), k)
                else:
                    d, i = ivf_pq.search(sp, index, queries, k)
                jax.block_until_ready((d, i))
                return i

            try:
                ids = run()
            except Exception as e:
                bank.add({"stage": f"search_p{n_probes}",
                          "error": str(e)[:200]})
                bank.check_transport()
                continue
            iters = 3
            t0 = time.perf_counter()
            for _ in range(iters):
                run()
            dt = (time.perf_counter() - t0) / iters
            got = np.asarray(ids)
            rec = float(np.mean(
                [len(set(got[j]) & set(truth[j])) / k for j in range(nq)]))
            bank.add({
                "metric": "ivf_pq_10M_build_qps", "n_probes": n_probes,
                "refine": use_refine, "qps": round(nq / dt, 1),
                "recall@10": round(rec, 4),
                "build_s": round(build_s, 1),
                "gate_recall95": rec >= 0.95,
            })
            bank.check_transport()
            if rec >= 0.95:
                gated = n_probes
                break
        _maybe_suspend("search_eval")
        return {"gated_n_probes": gated}

    job.add_stage("search_eval", search_eval, deps=("stream_extend",),
                  inputs={"k": k, "nq": nq})
    return job


def main(n: int = 10_000_000, dim: int = 96, nq: int = 1024, k: int = 10,
         n_lists: int = 4096, batch: int = 1_000_000,
         train_rows: int = 2_000_000, job_dir: str = None,
         stop_after: str = None) -> int:
    # enable_persistent_cache triggers backend init, which hangs ~25 min
    # against a dead relay — bail in milliseconds instead (the shared
    # guard; no-op when the env pins CPU, so the smoke rehearsal runs
    # with the relay dead)
    from raft_tpu.core.config import chip_probe_would_hang

    if chip_probe_would_hang():
        print(json.dumps({"aborted": "relay transport dead"}), flush=True)
        return 3

    out = os.environ.get("RAFT_TPU_10M_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_10M_PARTIAL.json")
    bank = common.Banker(out, {"n": n, "dim": dim, "nq": nq, "k": k},
                         resume=common.job_resuming(job_dir))
    common.enable_persistent_cache()

    with common.job_dir_or_temp(job_dir, "raft_tpu_10m_") as jd:
        job = build_job(jd, bank, n, dim, nq, k, n_lists, batch,
                        train_rows, stop_after=stop_after)
        return common.run_job_to_exit(job)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    # --smoke: the SAME pipeline (chunked make_data -> subsample-train ->
    # streamed resumable extend -> ground truth -> recall-gated ladder
    # with refine_host) at CPU-tractable scale, so chip day measures
    # instead of debugging script wiring
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--job-dir", default=None,
                    help="durable JobDir: re-run the same command after "
                         "a kill/preemption to resume")
    ap.add_argument("--stop-after", default=None,
                    help="suspend (exit 75) after this stage commits")
    a = ap.parse_args()
    if a.smoke:
        # the rehearsal is CPU-by-definition: pin the platform so it
        # neither aborts on a dead relay nor dials the single-client
        # TPU tunnel when the relay is alive
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        # smoke results are rehearsal artifacts, not the chip record
        os.environ.setdefault("RAFT_TPU_10M_OUT",
                              "/tmp/bench_10m_smoke.json")
        sys.exit(main(n=120_000, dim=32, nq=256, k=10, n_lists=256,
                      batch=30_000, train_rows=60_000, job_dir=a.job_dir,
                      stop_after=a.stop_after))
    else:
        sys.exit(main(job_dir=a.job_dir, stop_after=a.stop_after))
