"""10M-row single-chip IVF-PQ build via streamed extend (BASELINE config 4;
reference big-build loop: batch_load_iterator, ann_utils.cuh:388).

The dataset lives in host RAM (10M x 96 f32 = 3.84 GB) and never fully
visits HBM: the quantizers train on the kmeans_trainset_fraction
subsample, then `extend_batched` streams 1M-row batches through the
incremental encode+scatter path. Device residency after the build:
codes (10M x 48 u8 = 480 MB) + slot table (40 MB) + the lazily-built
int8 reconstruction store (10M x 96 i8 = 960 MB + norms) — ~1.5 GB of
the v5e's 16 GB HBM, leaving room for the 100M-scale ladder on a pod.

Prints one JSON line per stage and a final recall-gated QPS record.
Run from the repo root on the chip: `python bench/bench_10m_build.py`
(~3.8 GB host RAM for the dataset + one 1M-row staging batch).
"""

import json
import sys, os, time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import common  # noqa: F401  (pins CPU when JAX_PLATFORMS=cpu asks for it)
import jax
import jax.numpy as jnp


def main(n: int = 10_000_000, dim: int = 96, nq: int = 1024, k: int = 10,
         n_lists: int = 4096, batch: int = 1_000_000, train_rows: int = 2_000_000):
    # enable_persistent_cache triggers backend init, which hangs ~25 min
    # against a dead relay — bail in milliseconds instead (the shared
    # guard; no-op when the env pins CPU, so the smoke rehearsal runs
    # with the relay dead)
    from raft_tpu.core.config import chip_probe_would_hang

    if chip_probe_would_hang():
        print(json.dumps({"aborted": "relay transport dead"}), flush=True)
        sys.exit(3)
    out = os.environ.get("RAFT_TPU_10M_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_10M_PARTIAL.json")
    bank = common.Banker(out, {"n": n, "dim": dim, "nq": nq, "k": k})
    common.enable_persistent_cache()
    from raft_tpu.neighbors import brute_force, ivf_pq
    from raft_tpu.neighbors.batch_loader import extend_batched

    rng = np.random.default_rng(0)
    n_blobs = 4096
    t0 = time.perf_counter()
    centers = rng.uniform(-5.0, 5.0, (n_blobs, dim)).astype(np.float32)
    dataset = np.empty((n, dim), np.float32)
    step = 1_000_000
    for lo in range(0, n, step):  # chunked host-side generation
        hi = min(lo + step, n)
        a = rng.integers(0, n_blobs, hi - lo)
        dataset[lo:hi] = centers[a] + rng.standard_normal((hi - lo, dim)).astype(np.float32)
    queries = centers[rng.integers(0, n_blobs, nq)] + rng.standard_normal(
        (nq, dim)
    ).astype(np.float32)
    bank.add({"stage": "make_data", "s": round(time.perf_counter() - t0, 1)})
    bank.check_transport()

    # train on a subsample the build picks per kmeans_trainset_fraction of
    # what it is handed; hand it 2M rows so the fraction covers real data
    params = ivf_pq.IndexParams(
        n_lists=n_lists, pq_dim=dim // 2, kmeans_n_iters=10,
        add_data_on_build=False
    )
    t0 = time.perf_counter()
    index = ivf_pq.build(params, dataset[:train_rows])
    jax.block_until_ready(index.centers)
    train_s = time.perf_counter() - t0
    bank.add({"stage": "train_quantizers", "s": round(train_s, 1)})
    bank.check_transport()

    t0 = time.perf_counter()
    index = extend_batched(ivf_pq.extend, index, dataset, batch_size=batch)
    jax.block_until_ready(index.codes)
    extend_s = time.perf_counter() - t0
    bank.add({
        "stage": "extend_streamed", "s": round(extend_s, 1),
        "rows_per_s": round(n / extend_s, 1),
        "max_list": int(index.codes.shape[1]),
    })
    bank.check_transport()

    t0 = time.perf_counter()
    _, truth = brute_force.knn(dataset, queries, k)  # full upload fits v5e HBM
    truth = np.asarray(truth)
    bank.add({"stage": "ground_truth", "s": round(time.perf_counter() - t0, 1)})
    bank.check_transport()

    # Exact-BF rows at this scale answer the algorithm-crossover
    # question the 1M headline raised (bf_tiled beat IVF-PQ there); the
    # bf16 variant is one MXU pass instead of six (see
    # brute_force.knn(compute_dtype=...)). The scan is the point, so the
    # operands go device-resident ONCE per mode (passing host arrays
    # would re-upload 3.8 GB through the relay every timed call), and
    # sequentially — f32 array released before the bf16 copy exists —
    # to stay inside the v5e HBM envelope beside the index. Timing and
    # suspect-gating reuse the headline bench's shared protocol pieces.
    import bench as _hb  # repo-root bench.py (same sys.path as common)

    _min_ms = float(os.environ.get("RAFT_TPU_BENCH_MIN_BATCH_MS", "10"))
    dev = q_dev = nxt = None
    dev_q = jax.device_put(jnp.asarray(queries))
    dev32 = jax.device_put(jnp.asarray(dataset))
    jax.block_until_ready((dev_q, dev32))
    for tag in ("bf_tiled_f32", "bf_tiled_bf16"):
        try:
            if tag == "bf_tiled_bf16":
                nxt = dev32.astype(jnp.bfloat16)
                jax.block_until_ready(nxt)
                del dev32
                dev, q_dev = nxt, dev_q.astype(jnp.bfloat16)
            else:
                dev, q_dev = dev32, dev_q
            run = lambda: brute_force.knn(dev, q_dev, k)
            jax.block_until_ready(run())
            iter_ms, dt_pipe = _hb._dual_time(run, iters=2)
            dt = sum(iter_ms) / len(iter_ms) / 1e3
            pipe_ok = 1e3 * dt_pipe >= _min_ms
            got = np.asarray(run()[1])
            rec = float(np.mean(
                [len(set(got[j]) & set(truth[j])) / k for j in range(nq)]
            ))
            row = {
                "metric": "bf_10M_qps", "mode": tag,
                "qps_methodology": "pipelined_v2",
                "qps": round(nq / (min(dt, dt_pipe) if pipe_ok else dt), 1),
                "qps_synced": round(nq / dt, 1),
                "batch_ms_best": round(min(iter_ms), 2),
                "batch_ms_worst": round(max(iter_ms), 2),
                "recall@10": round(rec, 4),
            }
            if 1e3 * dt < _min_ms:
                row["suspect"] = True  # sub-floor clock: see docs/perf.md
            bank.add(row)
        except Exception as e:
            bank.add({"stage": tag, "error": str(e)[:200]})
        bank.check_transport()
    # release the device copies before the refine ladder (rebinding is
    # the reliable way to drop function-local references)
    dev = q_dev = dev_q = dev32 = nxt = None  # noqa: F841

    from raft_tpu.neighbors.refine import refine_host

    for n_probes, use_refine in ((16, True), (32, True), (64, True), (64, False)):
        sp = ivf_pq.SearchParams(n_probes=n_probes)

        def run():
            if use_refine:
                # host-dataset refine: only candidate rows visit HBM
                _, cand = ivf_pq.search(sp, index, queries, 4 * k)
                d, i = refine_host(dataset, queries, np.asarray(cand), k)
            else:
                d, i = ivf_pq.search(sp, index, queries, k)
            jax.block_until_ready((d, i))
            return i

        try:
            ids = run()
        except Exception as e:
            bank.add({"stage": f"search_p{n_probes}", "error": str(e)[:200]})
            bank.check_transport()
            continue
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            run()
        dt = (time.perf_counter() - t0) / iters
        got = np.asarray(ids)
        rec = float(np.mean([len(set(got[j]) & set(truth[j])) / k for j in range(nq)]))
        bank.add({
            "metric": "ivf_pq_10M_build_qps", "n_probes": n_probes,
            "refine": use_refine, "qps": round(nq / dt, 1),
            "recall@10": round(rec, 4),
            "build_s": round(train_s + extend_s, 1),
            "gate_recall95": rec >= 0.95,
        })
        bank.check_transport()
        if rec >= 0.95:
            break


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    # --smoke: the SAME pipeline (subsample-train -> streamed
    # extend_batched -> ground truth -> recall-gated ladder with
    # refine_host) at CPU-tractable scale, so chip day measures instead
    # of debugging script wiring
    ap.add_argument("--smoke", action="store_true")
    a = ap.parse_args()
    if a.smoke:
        # the rehearsal is CPU-by-definition: pin the platform so it
        # neither aborts on a dead relay nor dials the single-client
        # TPU tunnel when the relay is alive
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        # smoke results are rehearsal artifacts, not the chip record
        os.environ.setdefault("RAFT_TPU_10M_OUT",
                              "/tmp/bench_10m_smoke.json")
        main(n=120_000, dim=32, nq=256, k=10, n_lists=256,
             batch=30_000, train_rows=60_000)
    else:
        main()
