"""random benches (reference cpp/bench/random/: make_blobs, permute,
rmat shapes)."""

import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import run_case
import jax.numpy as jnp

from raft_tpu import random as rrandom


def main():
    run_case("random", "make_blobs_1Mx64",
             lambda: rrandom.make_blobs(1_000_000, 64, n_clusters=64, seed=0)[0],
             items=64e6, unit="elems/s")
    rng_state = rrandom.RngState(0)
    run_case("random", "uniform_16M",
             lambda: rrandom.uniform(rng_state, (16 * 1024 * 1024,)),
             items=16e6 * 1.048576, unit="elems/s")
    run_case("random", "normal_16M",
             lambda: rrandom.normal(rng_state, (16 * 1024 * 1024,)),
             items=16e6 * 1.048576, unit="elems/s")
    run_case("random", "permute_1M",
             lambda: rrandom.permute(rng_state, 1_000_000), items=1e6, unit="elems/s")
    run_case("random", "rmat_2^20_edges",
             lambda: rrandom.rmat(16, 16, 1 << 20, state=rng_state),
             items=float(1 << 20), unit="edges/s")
    run_case("random", "sample_without_replacement_64k_of_1M",
             lambda: rrandom.sample_without_replacement(rng_state, 1024 * 1024, 65536),
             items=65536.0, unit="samples/s")


if __name__ == "__main__":
    main()
