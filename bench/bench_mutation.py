"""Mutation bench: recall-under-churn, mutation ingest throughput, and
the zero-dip serving drill — the live-index numbers ISSUE 16 puts on
the ledger.

The whole run is a resumable job DAG with mutation interleaved, the
ISSUE 8 discipline applied to the mutable-index lifecycle:

    make_data -> train -> stream_ingest -> serve_churn -> scrub_serve
              -> churn -> reentry

`stream_ingest` streams the dataset through
`jobs.resumable_extend_from_file` (ingest rows/s), `serve_churn` drives
a `SearchServer` while committed upsert/delete/rebalance batches drain
through its `MutationFeed` between device batches (QPS under churn,
coverage floor — the zero-dip number), `scrub_serve` re-runs the serve
loop with the `raft_tpu.integrity` watchdog ticking between batches
(sidecar re-hash lists/s + the served-QPS dip, which should be ~0),
`churn` replays a scripted
upsert/delete/rebalance sequence through `jobs.resumable_mutate`'s
crash-atomic mutation log (mutation rows/s + recall@k before/after
churn against a live-set ground truth), and `reentry` re-enters the
SAME ops list through the committed log and proves it converges without
re-applying anything — the kill/resume contract as a banked fact, not
just a test.

Every row lands through `common.Banker`: honest ledger lines
(BENCH_LEDGER.jsonl) stamped with git SHA + platform, CPU runs
diverted/tagged (`.cpu` rehearsal or the dead-relay fallback tag), and
`ci/test.sh mutation` gates fresh rows with `tools/perfgate --json`
run twice + cmp'd.

Usage: python bench/bench_mutation.py [--smoke] [--job-dir DIR]
"""

import argparse
import hashlib
import os
import sys
import time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import common


def _recall(got_ids, truth_ids, k):
    got, truth = np.asarray(got_ids), np.asarray(truth_ids)
    return float(np.mean([
        len(set(got[i]) & set(truth[i])) / k for i in range(len(truth))]))


def scripted_churn(data, n_ops, batch, seed=101):
    """Deterministic churn script over `data` (row index == source id,
    the streamed-ingest id assignment): alternate upsert batches (half
    replacing live ids, half fresh ids past the dataset) with delete
    batches, closing with a rebalance. Returns (ops, live_ids,
    live_vecs) where the live arrays are the post-churn ground-truth
    set — ALL randomness derives from `seed`, so the `reentry` stage
    regenerates the identical list."""
    rng = np.random.default_rng(seed)
    rows, dim = data.shape
    vecs = {int(i): data[i] for i in range(rows)}  # id -> live vector
    next_id, ops = rows, []
    for t in range(n_ops):
        live = np.fromiter(vecs.keys(), np.int64)
        if t % 2 == 0:
            repl = rng.choice(live, batch // 2, replace=False)
            fresh = np.arange(next_id, next_id + batch - batch // 2)
            next_id += len(fresh)
            ids = np.concatenate([repl, fresh]).astype(np.int32)
            vv = (data[rng.integers(0, rows, len(ids))]
                  + rng.standard_normal((len(ids), dim)).astype(np.float32)
                  * 0.05)
            ops.append(("upsert", vv, ids))
            for j, i in enumerate(ids):
                vecs[int(i)] = vv[j]
        else:
            victims = rng.choice(live, batch, replace=False).astype(np.int32)
            ops.append(("delete", victims))
            for i in victims:
                vecs.pop(int(i))
    ops.append(("rebalance",))
    live = np.fromiter(vecs.keys(), np.int64).astype(np.int32)
    return ops, live, np.stack([vecs[int(i)] for i in live])


def _sha(path):
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        h.update(fh.read())
    return h.hexdigest()


def build_job(job_dir, bank, *, rows, dim, nq, k, n_lists, batch,
              train_rows, churn_ops, churn_batch, stop_after=None):
    from raft_tpu import jobs, serve
    from raft_tpu.neighbors import brute_force, ivf_flat, mutation

    deadline_s = float(
        os.environ.get("RAFT_TPU_MUTATION_DEADLINE_S", "600"))
    probes = max(4, n_lists // 8)
    sp = ivf_flat.SearchParams(n_probes=probes, engine="query")
    job = jobs.Job("bench_mutation", job_dir)
    _maybe_suspend = common.stop_after_hook(job, stop_after)

    n_blobs = max(64, n_lists)
    make_chunk = common.blob_chunk_maker(n_blobs, dim)

    def make_data(ctx):
        t0 = time.perf_counter()
        jobs.resumable_write_npy(
            ctx.artifact_path("dataset.npy"), rows, dim,
            max(1, rows // 8), make_chunk, ctx=ctx)
        centers = common.blob_centers(n_blobs, dim)
        rng = np.random.default_rng(2)
        queries = (centers[rng.integers(0, n_blobs, nq)]
                   + rng.standard_normal((nq, dim)).astype(np.float32) * 0.3)
        np.save(ctx.artifact_path("queries.npy"), queries)
        bank.add({"suite": "mutation", "stage": "make_data",
                  "s": round(time.perf_counter() - t0, 2)})
        bank.check_transport()
        _maybe_suspend("make_data")
        return {}

    job.add_stage("make_data", make_data, deadline_s=deadline_s,
                  inputs={"rows": rows, "dim": dim, "nq": nq,
                          "blobs": n_blobs})

    def train(ctx):
        data = np.load(ctx.dep_artifact("make_data", "dataset.npy"),
                       mmap_mode="r")
        t0 = time.perf_counter()
        index = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=n_lists, kmeans_n_iters=4,
                                 add_data_on_build=False),
            np.ascontiguousarray(data[:train_rows]))
        ivf_flat.save(ctx.artifact_path("trained"), index)
        bank.add({"suite": "mutation", "stage": "train",
                  "s": round(time.perf_counter() - t0, 2)})
        bank.check_transport()
        _maybe_suspend("train")
        return {}

    job.add_stage("train", train, deps=("make_data",),
                  deadline_s=deadline_s,
                  inputs={"n_lists": n_lists, "train_rows": train_rows})

    def stream_ingest(ctx):
        import jax

        index = ivf_flat.load(ctx.dep_artifact("train", "trained"))
        ckpt_every = common.stream_ckpt_every(rows, batch)
        t0 = time.perf_counter()
        index, stats = jobs.resumable_extend_from_file(
            "ivf_flat", index,
            ctx.dep_artifact("make_data", "dataset.npy"), batch,
            ctx=ctx, checkpoint_every=ckpt_every)
        jax.block_until_ready(index.list_data)
        wall = time.perf_counter() - t0
        ivf_flat.save(ctx.artifact_path("index"), index)
        this_run = stats["rows_this_run"]  # resume-honest denominator
        bank.add({"suite": "mutation", "case": "stream_ingest",
                  "stage": "stream_ingest",
                  "value": round(this_run / wall, 1) if wall else 0.0,
                  "unit": "rows/s", "s": round(wall, 2),
                  "rows_ingested": stats["rows_ingested"],
                  "resumed_from_batch": stats["resumed_from_batch"]})
        bank.check_transport()
        _maybe_suspend("stream_ingest")
        return {}

    job.add_stage("stream_ingest", stream_ingest, deps=("train",),
                  deadline_s=deadline_s, inputs={"batch": batch})

    def serve_churn(ctx):
        # the zero-dip drill as a measurement: a SearchServer answers a
        # fixed query stream while committed delete/upsert/rebalance
        # batches drain through its MutationFeed BETWEEN device batches.
        # Banked: QPS under churn and the coverage floor (must be 1.0 —
        # a dip would be the exact regression this row exists to catch).
        index = ivf_flat.load(ctx.dep_artifact("stream_ingest", "index"))
        q = np.load(ctx.dep_artifact("make_data", "queries.npy"))[:64]
        rng = np.random.default_rng(7)
        server = serve.SearchServer(
            index, serve.ServerConfig(buckets=(64,)), search_params=sp)
        feed = mutation.MutationFeed()
        server.attach_mutations(feed)
        rounds, coverage_min, victims = 6, 1.0, None
        replies = []
        t0 = time.perf_counter()
        for r in range(rounds):
            fut = server.submit(q, k=k)
            server.step()
            rep = fut.result(timeout=120.0)
            replies.append(rep)
            coverage_min = min(coverage_min, float(rep.coverage))
            if r == 0:
                victims = np.unique(np.asarray(rep.ids)[:, 0])[:4]
                feed.publish(("delete", victims.astype(np.int32)))
            elif r == 2:
                up_ids = np.arange(rows, rows + 8, dtype=np.int32)
                up = (q[:8] + rng.standard_normal(
                    (8, dim)).astype(np.float32) * 0.01)
                feed.publish(("upsert", up, up_ids))
                feed.publish(("rebalance",))
        wall = time.perf_counter() - t0
        if np.isin(np.asarray(replies[-1].ids), victims).any():
            raise RuntimeError("tombstoned ids resurfaced in served results")
        if server.searcher.index is index:
            raise RuntimeError("mutation batches never swapped in")
        from raft_tpu.obs import slo as _slo

        row = {"suite": "mutation", "case": "serve_zero_dip",
               "stage": "serve_churn",
               "value": round(rounds * len(q) / wall, 1), "unit": "q/s",
               "coverage_min": coverage_min, "mutation_batches": 3,
               "rounds": rounds}
        # SLO verdict fields (obs.slo.judge_serve): zero-dip serving must
        # also hold its latency/error/coverage objectives under churn
        row.update(_slo.judge_serve(server.metrics.snapshot()))
        bank.add(row)
        bank.check_transport()
        _maybe_suspend("serve_churn")
        return {"coverage_min": coverage_min}

    job.add_stage("serve_churn", serve_churn, deps=("stream_ingest",),
                  deadline_s=deadline_s, inputs={"nq": 64, "k": k})

    def scrub_serve(ctx):
        # scrub-under-churn: the SAME serve loop, now with the
        # integrity watchdog ticking one sidecar slice between device
        # batches. Banked: CRC re-hash throughput and the served-QPS
        # dip vs the bare loop — "scrubbing is free at request time" as
        # a ledger number (dip ~ 0; coverage must hold 1.0, a phantom
        # quarantine on a clean index is its own regression).
        from raft_tpu import integrity

        index = ivf_flat.load(ctx.dep_artifact("stream_ingest", "index"))
        q = np.load(ctx.dep_artifact("make_data", "queries.npy"))[:64]
        rounds = 6
        budget = max(1, int(index.n_lists) // rounds + 1)

        def _drive(wd):
            server = serve.SearchServer(
                index, serve.ServerConfig(buckets=(64,)), search_params=sp)
            if wd is not None:
                server.attach_integrity(wd)
            t0 = time.perf_counter()
            for _ in range(rounds):
                fut = server.submit(q, k=k)
                server.step()
                if float(fut.result(timeout=120.0).coverage) < 1.0:
                    raise RuntimeError(
                        "phantom quarantine while scrubbing a clean index")
            return rounds * len(q) / (time.perf_counter() - t0)

        qps_bare = _drive(None)
        wd = integrity.IntegrityWatchdog("ivf_flat", budget_lists=budget)
        t0 = time.perf_counter()
        qps_scrub = _drive(wd)
        scrub_wall = time.perf_counter() - t0
        if wd.scrubber.mismatches:
            raise RuntimeError("clean-index scrub reported mismatches")
        dip = max(0.0, 1.0 - qps_scrub / qps_bare)
        bank.add({"suite": "mutation", "case": "scrub_under_churn",
                  "stage": "scrub_serve",
                  "value": round(wd.scrubber.lists_scanned / scrub_wall, 1),
                  "unit": "lists/s",
                  "qps_bare": round(qps_bare, 1),
                  "qps_scrub": round(qps_scrub, 1),
                  "qps_dip": round(dip, 4),
                  "lists_scanned": int(wd.scrubber.lists_scanned),
                  "laps": int(wd.scrubber.laps)})
        bank.check_transport()
        _maybe_suspend("scrub_serve")
        return {"qps_dip": round(dip, 4)}

    job.add_stage("scrub_serve", scrub_serve, deps=("serve_churn",),
                  deadline_s=deadline_s, inputs={"nq": 64, "k": k})

    def churn(ctx):
        data = np.ascontiguousarray(
            np.load(ctx.dep_artifact("make_data", "dataset.npy"),
                    mmap_mode="r"))
        q = np.load(ctx.dep_artifact("make_data", "queries.npy"))
        index = ivf_flat.load(ctx.dep_artifact("stream_ingest", "index"))
        ops, live_ids, live_vecs = scripted_churn(
            data, churn_ops, churn_batch)

        _, truth = brute_force.knn(data, q, k)
        _, got = ivf_flat.search(sp, index, q, k)
        recall_pre = _recall(got, truth, k)

        touched = sum(len(op[1]) for op in ops if op[0] != "rebalance")
        scratch = ctx.artifact_path("mutlog")
        t0 = time.perf_counter()
        index, stats = jobs.resumable_mutate(
            "ivf_flat", index, ops, scratch=scratch,
            ckpt_every=4, slack=churn_batch)
        wall = time.perf_counter() - t0
        bank.add({"suite": "mutation", "case": "mutation_ingest",
                  "stage": "churn",
                  "value": round(touched / wall, 1) if wall else 0.0,
                  "unit": "rows/s", "s": round(wall, 2),
                  "ops": stats["ops"], "rows_touched": int(touched),
                  "live_rows": stats["live_rows"],
                  "tombstones": stats["tombstones"],
                  "resumed_at": stats["resumed_at"]})
        bank.check_transport()

        # recall AFTER churn, against the live set's own ground truth —
        # the honest number: tombstoned rows are out of both sides, and
        # upserted rows must be findable at their new positions
        _, t_rows = brute_force.knn(live_vecs, q, k)
        truth_post = live_ids[np.asarray(t_rows)]
        _, got_post = ivf_flat.search(sp, index, q, k)
        recall_post = _recall(got_post, truth_post, k)
        bank.add({"suite": "mutation", "case": "recall_under_churn",
                  "stage": "churn", "value": round(recall_post, 4),
                  "unit": f"recall@{k}",
                  "recall_pre_churn": round(recall_pre, 4),
                  "n_probes": probes, "churn_ops": len(ops),
                  "churn_rows": int(touched)})
        bank.check_transport()
        _maybe_suspend("churn")
        return {"recall_post": round(recall_post, 4)}

    job.add_stage("churn", churn, deps=("serve_churn",),
                  deadline_s=deadline_s,
                  inputs={"churn_ops": churn_ops,
                          "churn_batch": churn_batch, "k": k})

    def reentry(ctx):
        # the kill/resume contract as a banked fact: re-enter the SAME
        # ops list through the committed mutation log — every op dedupes
        # by sequence number, nothing re-applies, and the re-committed
        # checkpoint is byte-identical to the one already on disk
        data = np.ascontiguousarray(
            np.load(ctx.dep_artifact("make_data", "dataset.npy"),
                    mmap_mode="r"))
        ops, _, _ = scripted_churn(data, churn_ops, churn_batch)
        scratch = ctx.dep_artifact("churn", "mutlog")
        ckpt = os.path.join(scratch, "index.ckpt")
        before = _sha(ckpt)
        seed = ivf_flat.load(ctx.dep_artifact("stream_ingest", "index"))
        _, stats = jobs.resumable_mutate(
            "ivf_flat", seed, ops, scratch=scratch,
            ckpt_every=4, slack=churn_batch)
        reapplied = stats["applied"] - stats["resumed_at"]
        stable = _sha(ckpt) == before
        bank.add({"suite": "mutation", "case": "log_reentry",
                  "stage": "reentry", "value": int(reapplied),
                  "unit": "reapplied_ops",
                  "resumed_at": stats["resumed_at"],
                  "applied": stats["applied"], "ckpt_stable": stable})
        if reapplied != 0 or not stable:
            raise RuntimeError(
                f"log re-entry diverged: reapplied={reapplied} "
                f"ckpt_stable={stable}")
        bank.check_transport()
        _maybe_suspend("reentry")
        return {"ckpt_stable": stable}

    job.add_stage("reentry", reentry, deps=("churn",),
                  deadline_s=deadline_s,
                  inputs={"churn_ops": churn_ops,
                          "churn_batch": churn_batch})
    return job


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=40_000)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--n-lists", type=int, default=128)
    ap.add_argument("--nq", type=int, default=256)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8_000)
    ap.add_argument("--train-rows", type=int, default=8_000)
    ap.add_argument("--churn-ops", type=int, default=12)
    ap.add_argument("--churn-batch", type=int, default=256)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--job-dir", default=None,
                    help="durable JobDir: re-run the same command after "
                         "a kill/preemption to resume")
    ap.add_argument("--stop-after", default=None,
                    help="suspend (exit 75) after this stage commits")
    args = ap.parse_args()
    if args.smoke:
        # the rehearsal is CPU-by-definition (bench_10m_build's smoke
        # pattern): pin the platform so it neither hangs on a dead relay
        # nor dials the single-client TPU tunnel
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        args.rows, args.n_lists, args.batch = 6_000, 32, 1_500
        args.dim, args.nq, args.train_rows = 16, 64, 2_000
        args.churn_ops, args.churn_batch = 7, 64

    fallback = common.ensure_survivable_backend()
    if args.smoke:
        fallback = None  # smoke rows stay in the .cpu rehearsal file

    from raft_tpu import obs

    obs.enable()  # mutation counters + events ride every banked row

    out_dir = os.environ.get("RAFT_TPU_BENCH_OUT", "").strip() or \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bank = common.Banker(
        os.path.join(out_dir, "BENCH_mutation.json"),
        meta={"dataset_rows": args.rows, "dim": args.dim,
              "n_lists": args.n_lists, "nq": args.nq, "k": args.k,
              "churn_ops": args.churn_ops, "churn_batch": args.churn_batch},
        fallback=fallback,
        resume=common.job_resuming(args.job_dir),
    )
    common.enable_persistent_cache()

    with common.job_dir_or_temp(args.job_dir, "raft_tpu_mutation_") as jd:
        job = build_job(jd, bank,
                        rows=args.rows, dim=args.dim, nq=args.nq, k=args.k,
                        n_lists=args.n_lists, batch=args.batch,
                        train_rows=args.train_rows,
                        churn_ops=args.churn_ops,
                        churn_batch=args.churn_batch,
                        stop_after=args.stop_after)
        rc = common.run_job_to_exit(job)
    print(f"banked -> {bank.path}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
