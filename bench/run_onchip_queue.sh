#!/bin/bash
# Serial on-chip work queue for the single-client tunneled chip.
#
# Run ONLY after a fresh probe confirmed the backend answers (see
# NOTES.md "Queued on-chip work"): one chip process at a time, each step
# runs to completion — no kills, ever (a killed claim wedges the chip
# for hours; NOTES.md round-1 outage). Order (2026-08-01, relay windows
# measured in minutes): critical profile stages -> apply hints ->
# HEADLINE BENCH (banks gate-clearing rows the partial-recovery path
# can report at round end) -> full-ladder validation (same tuned-key
# state as the headline rows) -> diagnostics and tuner races (none of
# which affect the single-chip headline config) -> profile tail (stage
# timings + the device-faulting lut stage) -> the 30-min 10M build.
#
# Mid-queue process-tree loss: DON'T hand-patch a resume script (the
# retired run_onchip_queue_resume.sh pattern). Resume lives in the job
# runner now — export RAFT_TPU_RUN_ALL_JOB_DIR (run_all skips completed
# suites) and pass --job-dir to bench_10m_build.py /
# bench_100m_rehearsal.py (stage + batch-boundary resume); re-running
# this script then fast-forwards through the finished work. docs/jobs.md.
set -u
cd "$(dirname "$0")/.."
LOG=${ONCHIP_LOG:-/tmp/onchip_queue.log}
exec >>"$LOG" 2>&1
echo "=== on-chip queue start $(date -u +%FT%TZ) ==="
# run-sentinel for the watcher: suppresses its fire-on-first-observation
# when the queue already ran this boot (cleared on transport loss)
touch /tmp/onchip_queue_ran
# exit 2 = transport confirmed dead; exit 0 = up OR could-not-check
# (fail-open like the python callers — a broken check must not silently
# zero out the whole session's chip work)
relay_check() {
  python -c "
import sys; sys.path.insert(0, '.')
try:
    from raft_tpu.core.config import relay_transport_down
    sys.exit(2 if relay_transport_down() else 0)
except SystemExit:
    raise
except Exception:
    sys.exit(0)
"
}
run_hostonly() {
  echo "--- $* ($(date -u +%T)) ---"
  "$@"
  local rc=$?
  echo "--- rc=$rc ($(date -u +%T)) ---"
  return $rc
}
run() {
  relay_check
  if [ $? -eq 2 ]; then
    echo "--- relay transport dead; skipping $* ($(date -u +%T)) ---"
    return
  fi
  run_hostonly "$@"
}
# Durable-job steps: the job dir exists for resume-after-kill, NOT for
# skipping the next session's measurement — stage fingerprints are
# geometry-only (git SHA deliberately excluded), so a dir surviving a
# COMPLETED run would make every later queue session silently skip the
# bench instead of banking fresh numbers for the new tree. A step that
# exits 0 (all stages committed + banked) clears its dir; any other
# exit (preempt 75, crash, kill, relay skip) keeps it so re-running
# this script fast-forwards through the finished stages.
run_job() {
  local jobdir="$1"; shift
  relay_check
  if [ $? -eq 2 ]; then
    echo "--- relay transport dead; skipping $* ($(date -u +%T)) ---"
    return
  fi
  if run_hostonly "$@"; then
    rm -rf "$jobdir"
  fi
}
# DIAG FIRST (VERDICT r4 #1: "nothing queue-jumps this"): attributes the
# 60x roofline gap — dispatch floor, stage decomposition at exact bench
# shape (incl. the chunk_block=0 superblock-einsum structure race), and
# refine isolation at the headline shape. FAST mode skips the resolved
# sqeuclidean A/B and the mini-build trace (~4 min saved; windows have
# been 9-20 min); the full diag re-runs in the tail below.
run env RAFT_TPU_DIAG_FAST=1 python bench/bench_diag.py
# critical profile stages only (engine ladder + chunk_block race); the
# stage-timing breakdown and the device-faulting lut stage run in the
# "tail" entry AFTER the headline bench, so a short relay window banks a
# QPS row
run env RAFT_TPU_PROFILE_STAGE=critical python bench/tpu_profile.py
# host-only: turns (possibly partial) profile results into default flips;
# must run even when the relay died mid-ladder
run_hostonly python bench/apply_profile_hints.py --apply
# HEADLINE FIRST after the decision ladder: every gate-clearing row it
# banks lands in BENCH_PARTIAL.jsonl, which bench.py's partial-recovery
# path reports even if the relay is dead at the driver's round-end run —
# one banked 0.95-gated row is worth more than any diagnostic
run python bench.py
# ordering-assumption validation directly after the headline so it runs
# under the SAME tuned-key state as the banked rows (the tuner races
# below mutate keys); cache-warm, so compute-only
run bash -c 'set -o pipefail; RAFT_TPU_BENCH_FULL_LADDER=1 python bench.py | tail -1 > LADDER_VALIDATION.json'
# diag tail (ONLY the parts fast mode skipped: pairwise A/B + mini-build
# profiler trace) once the headline has banked; merge-banks into the
# fast run's rows
run env RAFT_TPU_DIAG_TAIL=1 python bench/bench_diag.py
# isolated fused-scan kernel race (exact vs packed fold vs XLA inner
# loop vs store-stream roofline); --apply flips the pallas_fold key
run python bench/bench_pallas_scan.py --apply
run python bench/bench_select_k_strategies.py --apply
# merge-schedule race (tournament vs allgather replicated merge): the
# winner is backend-dependent; write the on-chip verdict
run python bench/bench_comms.py --apply
# profile tail: stage-timing breakdown + the device-faulting lut stage
# (dead last before the big build — a fault here costs nothing above)
run env RAFT_TPU_PROFILE_STAGE=tail python bench/tpu_profile.py
run_hostonly python bench/apply_profile_hints.py --apply
# the 30-min streamed big-build record runs after every headline number
# is banked (VERDICT r3 ranks it below the QPS/tuning evidence)
run_job /tmp/raft_tpu_jobs/bench_10m python bench/bench_10m_build.py --job-dir /tmp/raft_tpu_jobs/bench_10m
# merge-topology race on whatever mesh exists (single chip: world=1 is a
# no-op comparison, skipped fast; kept for pod slices)
run python bench/bench_mnmg_merge.py --apply
# full micro-suite sweep last: the critical ladder above already has its
# numbers if the chip drops partway through this
run_job /tmp/raft_tpu_jobs/run_all env RAFT_TPU_RUN_ALL_JOB_DIR=/tmp/raft_tpu_jobs/run_all python bench/run_all.py
# streamed-build rehearsal at chip speed (~1-2 min of device time at the
# default 4M-row geometry): banks a chip-timed rows/s for the 100Mx768
# extrapolation beside the CPU-timed BENCH_100M_REHEARSAL.json.cpu
run_job /tmp/raft_tpu_jobs/bench_100m python bench/bench_100m_rehearsal.py --job-dir /tmp/raft_tpu_jobs/bench_100m
# headline re-run under the fully tuned keys (the select_k/comms/merge
# --apply races above ran AFTER the first headline; the select thresholds
# in particular gate the brute-force scan's select phase): cache-warm,
# ~2 min, banks the best-keyed row in case the driver's round-end run
# hits a dead relay. KEEP_PARTIAL: this re-run belongs to the same queue
# session — truncating would erase every gate-clearing row banked above
# if the relay dies mid-re-run
run env RAFT_TPU_BENCH_KEEP_PARTIAL=1 python bench.py
echo "=== on-chip queue done $(date -u +%FT%TZ) ==="
