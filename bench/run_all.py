"""Run every bench suite (reference: the per-suite Google-Benchmark
executables under cpp/bench). Each suite prints JSON lines; failures in
one suite don't stop the rest. A dead relay transport no longer aborts
the sweep (ROADMAP 5a): the remaining schedule narrows to the
SURVIVABLE drivers — the ones that call
`common.ensure_survivable_backend()` themselves, pin CPU in-process,
and bank honestly-tagged fallback rows — so a dead transport still
produces fresh banked numbers instead of recycling stale ones. Suites
without the fallback are skipped with a note (launching a chip process
against a dead transport just hangs until someone's timeout)."""

import subprocess
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# host-side suites run FIRST and unconditionally: their measurements
# need no chip, so a dead relay must not cost them
HOST_SUITES = [
    ("bench_io_loader.py", ["--cold"]),
]
SUITES = [
    "bench_distance.py",
    "bench_matrix.py",
    "bench_linalg.py",
    "bench_random.py",
    "bench_sparse.py",
    "bench_cluster.py",
    "bench_neighbors.py",
    "bench_comms.py",
]

# drivers that call ensure_survivable_backend() before any device op:
# safe to launch against a dead transport — they pin CPU in-process and
# bank tagged fallback rows to their real results files + the ledger
SURVIVABLE = [
    "bench_perf_smoke.py",
    "bench_neighbors.py",
    "bench_serve.py",
    "bench_ivf_rabitq.py",
]


def _suites():
    """Test seam: RAFT_TPU_RUN_ALL_SUITES overrides the chip schedule
    (comma-separated file names) so the dead-relay continuation path is
    testable without a multi-minute sweep."""
    env = os.environ.get("RAFT_TPU_RUN_ALL_SUITES", "").strip()
    return [s for s in env.split(",") if s] if env else list(SUITES)


def _transport_dead() -> bool:
    try:
        from raft_tpu.core.config import chip_probe_would_hang

        return chip_probe_would_hang()
    except Exception:
        return False  # fail-open: a broken check must not zero the sweep


if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    rc = 0
    for s, extra in HOST_SUITES:
        print(f"== {s}", file=sys.stderr, flush=True)
        r = subprocess.run([sys.executable, "-u", os.path.join(here, s),
                            *extra])
        rc = rc or r.returncode
    survivable_only = False
    for s in _suites():
        if not survivable_only and _transport_dead():
            survivable_only = True
            print("== relay transport dead; continuing with survivable "
                  "suites only (in-process CPU fallback banks tagged "
                  "rows; prior suites' records already flushed)",
                  file=sys.stderr, flush=True)
        if survivable_only and s not in SURVIVABLE:
            print(f"== skipping {s} (no dead-relay fallback; a chip "
                  "process would hang)", file=sys.stderr, flush=True)
            continue
        print(f"== {s}", file=sys.stderr, flush=True)
        r = subprocess.run([sys.executable, "-u", os.path.join(here, s)])
        rc = rc or r.returncode
    sys.exit(rc)
