"""Run every bench suite (reference: the per-suite Google-Benchmark
executables under cpp/bench) — as SUPERVISED, RESUMABLE job stages
(ISSUE 8). Each suite runs as one stage of a `raft_tpu.jobs.Job` under
`jobs.run_supervised`: the child's output lines double as heartbeats,
so a suite that goes silent past RAFT_TPU_RUN_ALL_STALL_S (default
1800 s) is SIGKILLed as a typed StageTimeout and the sweep CONTINUES —
one hung bench no longer kills the session (the BENCH_r01–r05 failure
shape). Failures in one suite don't stop the rest (continue_on_error).

Resume: point RAFT_TPU_RUN_ALL_JOB_DIR at a durable directory and a
re-run after a mid-queue process-tree loss skips the suites that
already completed — the scenario `run_onchip_queue_resume.sh` used to
hand-patch, now retired into the runner. (Default: temp JobDir, no
cross-run resume.)

A dead relay transport no longer aborts the sweep (ROADMAP 5a): the
remaining schedule narrows to the SURVIVABLE drivers — the ones that
call `common.ensure_survivable_backend()` themselves, pin CPU
in-process, and bank honestly-tagged fallback rows — so a dead
transport still produces fresh banked numbers instead of recycling
stale ones. Suites without the fallback are skipped with a note
(launching a chip process against a dead transport just hangs until
someone's timeout)."""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import common  # noqa: E402  (shared jobification protocol)

# host-side suites run FIRST and unconditionally: their measurements
# need no chip, so a dead relay must not cost them
HOST_SUITES = [
    ("bench_io_loader.py", ["--cold"]),
]
SUITES = [
    "bench_distance.py",
    "bench_matrix.py",
    "bench_linalg.py",
    "bench_random.py",
    "bench_sparse.py",
    "bench_cluster.py",
    "bench_neighbors.py",
    "bench_comms.py",
]

# drivers that call ensure_survivable_backend() before any device op:
# safe to launch against a dead transport — they pin CPU in-process and
# bank tagged fallback rows to their real results files + the ledger
SURVIVABLE = [
    "bench_perf_smoke.py",
    "bench_neighbors.py",
    "bench_serve.py",
    "bench_ivf_rabitq.py",
]


def _suites():
    """Test seam: RAFT_TPU_RUN_ALL_SUITES overrides the chip schedule
    (comma-separated file names) so the dead-relay continuation path is
    testable without a multi-minute sweep."""
    env = os.environ.get("RAFT_TPU_RUN_ALL_SUITES", "").strip()
    return [s for s in env.split(",") if s] if env else list(SUITES)


def _transport_dead() -> bool:
    try:
        from raft_tpu.core.config import chip_probe_would_hang

        return chip_probe_would_hang()
    except Exception:
        return False  # fail-open: a broken check must not zero the sweep


class SuiteSkipped(RuntimeError):
    """A suite NOT run because of a transient environment condition (a
    dead relay). Raised — not returned — so the stage never commits:
    banking a transient skip as completion would make a durable job dir
    skip the suite forever, even after the relay recovers. Skips don't
    count as sweep failures (exit code stays 0)."""


def main() -> int:
    from raft_tpu import jobs

    here = os.path.dirname(os.path.abspath(__file__))
    stall_s = float(os.environ.get("RAFT_TPU_RUN_ALL_STALL_S", "1800"))
    env_dir = os.environ.get("RAFT_TPU_RUN_ALL_JOB_DIR", "").strip() or None

    state = {"survivable_only": False, "skipped": set()}

    def _suite_stage(suite, extra=(), gate=True):
        def stage(ctx):
            if gate and not state["survivable_only"] and _transport_dead():
                state["survivable_only"] = True
                print("== relay transport dead; continuing with survivable "
                      "suites only (in-process CPU fallback banks tagged "
                      "rows; prior suites' records already flushed)",
                      file=sys.stderr, flush=True)
            if (gate and state["survivable_only"]
                    and suite not in SURVIVABLE):
                print(f"== skipping {suite} (no dead-relay fallback; a "
                      "chip process would hang)", file=sys.stderr,
                      flush=True)
                state["skipped"].add(suite)
                raise SuiteSkipped(suite)  # no commit: re-runs retry it
            print(f"== {suite}", file=sys.stderr, flush=True)
            rc = jobs.run_supervised(
                [sys.executable, "-u", os.path.join(here, suite), *extra],
                describe=suite, stall_timeout_s=stall_s)
            if rc != 0:
                raise RuntimeError(f"{suite} exited {rc}")
            return {"rc": rc}

        return stage

    with common.job_dir_or_temp(env_dir, "raft_tpu_run_all_") as jd:
        job = jobs.Job("bench_sweep", jd)
        for s, extra in HOST_SUITES:
            job.add_stage(f"host:{s}", _suite_stage(s, extra, gate=False),
                          inputs={"suite": s, "args": list(extra)})
        for s in _suites():
            job.add_stage(s, _suite_stage(s), inputs={"suite": s})

        try:
            statuses = job.run(continue_on_error=True)
        except jobs.JobPreempted:
            print("== preempted; durable state committed — re-run with "
                  "RAFT_TPU_RUN_ALL_JOB_DIR set to resume",
                  file=sys.stderr, flush=True)
            return common.PREEMPT_EXIT
        failed = sorted(k for k, v in statuses.items()
                        if v == "failed" and k not in state["skipped"])
        if failed:
            print(f"== failed suites: {', '.join(failed)}",
                  file=sys.stderr, flush=True)
            return 1
        if state["skipped"]:
            # relay-skipped suites are deliberately uncommitted so a
            # re-run retries them — exiting 0 here would let callers
            # (run_onchip_queue.sh run_job) treat the sweep as complete
            # and delete the job dir, losing exactly that retry path
            print(f"== sweep incomplete: {len(state['skipped'])} "
                  "relay-skipped suite(s) await a re-run",
                  file=sys.stderr, flush=True)
            return common.PREEMPT_EXIT
        return 0


if __name__ == "__main__":
    sys.exit(main())
