"""Run every bench suite (reference: the per-suite Google-Benchmark
executables under cpp/bench). Each suite prints JSON lines; failures in one
suite don't stop the rest."""

import subprocess
import sys
import os

SUITES = [
    "bench_distance.py",
    "bench_matrix.py",
    "bench_linalg.py",
    "bench_random.py",
    "bench_sparse.py",
    "bench_cluster.py",
    "bench_neighbors.py",
    "bench_comms.py",
]

if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    rc = 0
    for s in SUITES:
        print(f"== {s}", file=sys.stderr, flush=True)
        r = subprocess.run([sys.executable, "-u", os.path.join(here, s)])
        rc = rc or r.returncode
    sys.exit(rc)
