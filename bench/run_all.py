"""Run every bench suite (reference: the per-suite Google-Benchmark
executables under cpp/bench). Each suite prints JSON lines; failures in one
suite don't stop the rest, but a dead relay transport does — each suite's
results are already banked when it exits, and launching another chip
process against a dead transport just hangs until someone's timeout."""

import subprocess
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# host-side suites run FIRST and unconditionally: their measurements
# need no chip, so a dead relay must not cost them
HOST_SUITES = [
    ("bench_io_loader.py", ["--cold"]),
]
SUITES = [
    "bench_distance.py",
    "bench_matrix.py",
    "bench_linalg.py",
    "bench_random.py",
    "bench_sparse.py",
    "bench_cluster.py",
    "bench_neighbors.py",
    "bench_comms.py",
]


def _transport_dead() -> bool:
    try:
        from raft_tpu.core.config import chip_probe_would_hang

        return chip_probe_would_hang()
    except Exception:
        return False  # fail-open: a broken check must not zero the sweep


if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    rc = 0
    for s, extra in HOST_SUITES:
        print(f"== {s}", file=sys.stderr, flush=True)
        r = subprocess.run([sys.executable, "-u", os.path.join(here, s),
                            *extra])
        rc = rc or r.returncode
    for s in SUITES:
        if _transport_dead():
            print(f"== relay transport dead; aborting sweep before {s} "
                  "(prior suites' records already flushed)",
                  file=sys.stderr, flush=True)
            sys.exit(rc or 3)  # a pre-abort suite failure still surfaces
        print(f"== {s}", file=sys.stderr, flush=True)
        r = subprocess.run([sys.executable, "-u", os.path.join(here, s)])
        rc = rc or r.returncode
    sys.exit(rc)
