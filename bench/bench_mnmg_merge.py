"""Distributed-search merge-topology race: replicated allgather merge vs
query-sharded all_to_all merge (`query_mode` in comms.mnmg search).

The replicated topology allgathers every rank's (nq, kk) candidate block
onto every rank — received volume per rank ≈ (R-1)·nq·kk·8 bytes — then
re-selects everywhere. The sharded topology routes each query block's
candidates to its owning rank only (one all_to_all, ≈ (R-1)/R·nq·kk·8
bytes per rank, an R× reduction) and each rank finalizes its own block:
the serving topology (reference merge analogue:
neighbors/detail/knn_merge_parts.cuh; survey §5.7).

Runs on whatever mesh exists (v5e slice, or the virtual CPU mesh with
--smoke; `--device-count N` forces an N-device virtual mesh so a world
sweep {4, 8, 16} can run off-chip). Each (nq, k) serving shape races both
modes end-to-end through `mnmg.ivf_pq_search`; results print as JSON
lines and persist incrementally to MERGE_RACE_RESULTS.json
(partial-banking discipline: every row lands before the next long
compile starts).

`--apply` fits the volume-aware auto rule to the recorded (nq, k)
surface: sharded iff nq >= `mnmg_query_sharded_min_nq` AND
nq >= k * `mnmg_query_sharded_min_nq_per_k`. Round-3 data showed the
winner flips with k at fixed nq (sharded won nq=2048/k=10, lost
nq=2048/k=100), so a single nq threshold cannot represent the surface;
the two-key rule is the smallest one that can.
"""

import argparse
import json
import sys, os, time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import common
import jax

# RAFT_TPU_MERGE_RACE_OUT: divert a world-sweep run's banked rows so it
# doesn't clobber the canonical (default-mesh) record
OUT = os.environ.get("RAFT_TPU_MERGE_RACE_OUT") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "MERGE_RACE_RESULTS.json")


def main(smoke: bool = False, apply: bool = False, device_count: int = 0):
    if device_count:
        # only meaningful for the virtual CPU mesh (world sweep off-chip);
        # must land before first backend init
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={device_count}"
        )
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        jax.config.update("jax_platforms", "cpu")
    from raft_tpu.comms import Comms, mnmg
    from raft_tpu.neighbors import ivf_pq

    common.enable_persistent_cache()
    c = Comms()
    r = c.get_size()
    if r < 2:
        print(json.dumps({"suite": "mnmg_merge", "skipped": "world=1: the "
                          "two merge topologies are identical"}), flush=True)
        return {"rows": [], "world": r}
    if smoke:
        # k varies at fixed nq (the axis round-3 data showed the winner
        # flips on); nq capped at 2048 to keep the CPU race bounded
        n, dim, n_lists, pq_dim = 40_000, 32, 64, 16
        grid = [(512, 10), (512, 100), (2048, 10), (2048, 32), (2048, 100)]
        n_probes = 16
    else:
        n, dim, n_lists, pq_dim = 1_000_000, 96, 1024, 48
        grid = [(4096, 10), (16384, 10), (65536, 10),
                (4096, 32), (16384, 32),
                (4096, 100), (16384, 100)]
        n_probes = 32

    rng = np.random.default_rng(0)
    nb = 512
    centers = rng.uniform(-5.0, 5.0, (nb, dim)).astype(np.float32)
    data = centers[rng.integers(0, nb, n)] + rng.standard_normal(
        (n, dim)).astype(np.float32)
    qmax = max(nq for nq, _ in grid)
    queries = centers[rng.integers(0, nb, qmax)] + rng.standard_normal(
        (qmax, dim)).astype(np.float32)

    bank = common.Banker(OUT, {
        "backend": jax.default_backend(), "world": r, "smoke": smoke,
        "index": {"n": n, "dim": dim, "n_lists": n_lists,
                  "pq_dim": pq_dim, "n_probes": n_probes},
    })
    record = bank.record

    params = ivf_pq.IndexParams(n_lists=n_lists, pq_dim=pq_dim,
                                kmeans_n_iters=6)
    index = mnmg.ivf_pq_build(c, params, data)

    for nq, k in grid:
        q = queries[:nq]
        row = {"nq": nq, "k": k,
               # received bytes per rank in the merge step (v f32 + id i32)
               "volume_replicated_B": (r - 1) * nq * k * 8,
               "volume_sharded_B": (r - 1) * nq * k * 8 // r}
        for mode in ("replicated", "sharded"):
            def run():
                return mnmg.ivf_pq_search(index, q, k, n_probes=n_probes,
                                          engine="recon8_list",
                                          query_mode=mode)
            jax.block_until_ready(run())  # compile + warm
            t0 = time.perf_counter()
            iters = 3
            for _ in range(iters):
                jax.block_until_ready(run())
            dt = (time.perf_counter() - t0) / iters
            row[f"{mode}_ms"] = round(dt * 1e3, 2)
            row[f"{mode}_qps"] = round(nq / dt, 1)
        row["winner"] = ("sharded" if row["sharded_ms"] < row["replicated_ms"]
                         else "replicated")
        bank.add({"suite": "mnmg_merge", **row})
        record["rows"][-1] = row  # keep the bare row shape for _apply
        bank.flush()
        bank.check_transport()

    if apply:
        _apply(record)
    return record


def fit_rule(rows):
    """Fit the two-key auto rule to a measured (nq, k) winner surface:
    predict sharded iff nq >= min_nq AND nq >= k * per_k. Exhaustive
    search over thresholds drawn from the data (plus +inf sentinels),
    minimizing (a) misclassified rows weighted by |winner margin| in ms —
    so a 10 ms noise flip can't outvote a 8000 ms regression — with
    ties broken toward LARGER thresholds (conservative: prefer
    replicated, whose layout every caller can consume). Returns
    (min_nq, per_k, weighted_error) or None when sharded never won."""
    data = [(int(r["nq"]), int(r["k"]), r["winner"] == "sharded",
             abs(r["replicated_ms"] - r["sharded_ms"])) for r in rows]
    if not any(s for _, _, s, _ in data):
        return None
    inf = float("inf")
    nq_cands = sorted({nq for nq, _, _, _ in data}) + [inf]
    ratio_cands = sorted({nq / k for nq, k, _, _ in data}) + [inf]
    best = None
    for min_nq in nq_cands:
        for per_k in ratio_cands:
            err = sum(w for nq, k, sharded, w in data
                      if (nq >= min_nq and nq >= k * per_k) != sharded)
            key = (err, -min_nq, -per_k)
            if best is None or key < best[0]:
                best = (key, min_nq, per_k)
    _, min_nq, per_k = best
    if min_nq == inf or per_k == inf:
        return None  # conservative fit degenerated to "never sharded"
    err = float(best[0][0])
    # a rule that misclassifies more than 10% of the total measured margin
    # does not represent the surface — leave the defaults untouched rather
    # than ship a fit known to mis-route measured shapes
    total_margin = sum(w for _, _, _, w in data)
    if total_margin > 0 and err > 0.10 * total_margin:
        return None
    # per_k stays float: int-truncating it would persist a MORE permissive
    # rule than the one validated against the surface
    return int(min_nq), float(per_k), err


def _apply(record: dict) -> None:
    """Fit + write the volume-aware crossover keys. The CPU mesh is an
    accepted measurement surface for these keys — the topology choice is
    about data movement between shards, which the virtual mesh exercises
    for real (unlike kernel timings, which only the chip can measure) —
    but a CPU fit never clobbers chip-backed keys (the measured_on hint
    records which surface wrote them)."""
    from raft_tpu.core import tuned

    prev = tuned.hints()
    prev_on = str(prev.get("mnmg_merge_measured_on", ""))
    if record["backend"] == "cpu" and prev_on and not prev_on.startswith("cpu"):
        print(json.dumps({"applied": None,
                          "detail": f"existing keys are chip-backed "
                                    f"({prev_on}); CPU fit not applied"}))
        return
    fit = fit_rule(record["rows"])
    if fit is None:
        print(json.dumps({"applied": None,
                          "detail": "replicated won everywhere, or the fit "
                                    "cannot represent the surface (residual "
                                    "error > 10% of measured margin); "
                                    "defaults untouched"}))
        return
    min_nq, per_k, err = fit
    applied = {"mnmg_query_sharded_min_nq": min_nq,
               "mnmg_query_sharded_min_nq_per_k": per_k}
    tuned.merge({**applied,
                 "hints": {"mnmg_merge_measured_on":
                           f"{record['backend']}_world{record['world']}",
                           "mnmg_merge_fit_weighted_err_ms": err}})
    print(json.dumps({"applied": applied, "weighted_err_ms": err}))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--apply", action="store_true")
    ap.add_argument("--device-count", type=int, default=0,
                    help="force an N-device virtual CPU mesh (world sweep)")
    a = ap.parse_args()
    rec = main(smoke=a.smoke, apply=a.apply, device_count=a.device_count)
    print(json.dumps({"suite": "mnmg_merge", "case": "done",
                      "rows": len(rec["rows"])}))
