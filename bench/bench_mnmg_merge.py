"""Distributed-search merge-topology race: replicated allgather merge vs
query-sharded all_to_all merge (`query_mode` in comms.mnmg search).

The replicated topology allgathers every rank's (nq, kk) candidate block
onto every rank — received volume per rank ≈ (R-1)·nq·kk·8 bytes — then
re-selects everywhere. The sharded topology routes each query block's
candidates to its owning rank only (one all_to_all, ≈ (R-1)/R·nq·kk·8
bytes per rank, an R× reduction) and each rank finalizes its own block:
the serving topology (reference merge analogue:
neighbors/detail/knn_merge_parts.cuh; survey §5.7).

Runs on whatever mesh exists (v5e slice, or the 8-device virtual CPU mesh
with --smoke). Each (nq, k) serving shape races both modes end-to-end
through `mnmg.ivf_pq_search`; results print as JSON lines and persist
incrementally to MERGE_RACE_RESULTS.json (partial-banking discipline:
every row lands before the next long compile starts). `--apply` writes
the crossover to tuned key `mnmg_query_sharded_min_nq` so
query_mode="auto" flips from data.
"""

import argparse
import json
import sys, os, time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import common
import jax

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "MERGE_RACE_RESULTS.json")


def main(smoke: bool = False, apply: bool = False):
    from raft_tpu.comms import Comms, mnmg
    from raft_tpu.neighbors import ivf_pq

    common.enable_persistent_cache()
    c = Comms()
    r = c.get_size()
    if r < 2:
        print(json.dumps({"suite": "mnmg_merge", "skipped": "world=1: the "
                          "two merge topologies are identical"}), flush=True)
        return {"rows": [], "world": r}
    if smoke:
        n, dim, n_lists, pq_dim = 40_000, 32, 64, 16
        grid = [(512, 10), (2048, 10), (2048, 100)]
        n_probes = 16
    else:
        n, dim, n_lists, pq_dim = 1_000_000, 96, 1024, 48
        grid = [(4096, 10), (16384, 10), (65536, 10),
                (4096, 100), (16384, 100)]
        n_probes = 32

    rng = np.random.default_rng(0)
    nb = 512
    centers = rng.uniform(-5.0, 5.0, (nb, dim)).astype(np.float32)
    data = centers[rng.integers(0, nb, n)] + rng.standard_normal(
        (n, dim)).astype(np.float32)
    qmax = max(nq for nq, _ in grid)
    queries = centers[rng.integers(0, nb, qmax)] + rng.standard_normal(
        (qmax, dim)).astype(np.float32)

    bank = common.Banker(OUT, {
        "backend": jax.default_backend(), "world": r, "smoke": smoke,
        "index": {"n": n, "dim": dim, "n_lists": n_lists,
                  "pq_dim": pq_dim, "n_probes": n_probes},
    })
    record = bank.record

    params = ivf_pq.IndexParams(n_lists=n_lists, pq_dim=pq_dim,
                                kmeans_n_iters=6)
    index = mnmg.ivf_pq_build(c, params, data)

    for nq, k in grid:
        q = queries[:nq]
        row = {"nq": nq, "k": k,
               # received bytes per rank in the merge step (v f32 + id i32)
               "volume_replicated_B": (r - 1) * nq * k * 8,
               "volume_sharded_B": (r - 1) * nq * k * 8 // r}
        for mode in ("replicated", "sharded"):
            def run():
                return mnmg.ivf_pq_search(index, q, k, n_probes=n_probes,
                                          engine="recon8_list",
                                          query_mode=mode)
            jax.block_until_ready(run())  # compile + warm
            t0 = time.perf_counter()
            iters = 3
            for _ in range(iters):
                jax.block_until_ready(run())
            dt = (time.perf_counter() - t0) / iters
            row[f"{mode}_ms"] = round(dt * 1e3, 2)
            row[f"{mode}_qps"] = round(nq / dt, 1)
        row["winner"] = ("sharded" if row["sharded_ms"] < row["replicated_ms"]
                         else "replicated")
        bank.add({"suite": "mnmg_merge", **row})
        record["rows"][-1] = row  # keep the bare row shape for _apply
        bank.flush()
        bank.check_transport()

    if apply:
        _apply(record)
    return record


def _apply(record: dict) -> None:
    """Encode the measured crossover: the smallest nq at which sharded won
    at EVERY k measured for that nq, provided replicated never won at a
    larger nq (non-monotone results leave the default untouched). The CPU
    mesh is an accepted measurement surface for this key — the topology
    choice is about data movement between shards, which the virtual mesh
    exercises for real (unlike kernel timings, which only the chip can
    measure)."""
    from raft_tpu.core import tuned

    by_nq = {}
    for row in record["rows"]:
        by_nq.setdefault(row["nq"], []).append(row["winner"] == "sharded")
    sharded_nqs = sorted(nq for nq, w in by_nq.items() if all(w))
    replicated_nqs = [nq for nq, w in by_nq.items() if not all(w)]
    if not sharded_nqs:
        print(json.dumps({"applied": None,
                          "detail": "replicated won everywhere"}))
        return
    if any(nq > sharded_nqs[0] for nq in replicated_nqs):
        print(json.dumps({"applied": None,
                          "detail": "non-monotone winners; no clean crossover"}))
        return
    thresh = sharded_nqs[0]
    tuned.merge({"mnmg_query_sharded_min_nq": int(thresh),
                 "hints": {"mnmg_merge_measured_on":
                           f"{record['backend']}_world{record['world']}"}})
    print(json.dumps({"applied": {"mnmg_query_sharded_min_nq": int(thresh)}}))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--apply", action="store_true")
    a = ap.parse_args()
    rec = main(smoke=a.smoke, apply=a.apply)
    print(json.dumps({"suite": "mnmg_merge", "case": "done",
                      "rows": len(rec["rows"])}))
