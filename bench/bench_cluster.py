"""k-means / balanced k-means benches (reference cpp/bench/cluster/
{kmeans,kmeans_balanced}.cu). Reports rows/s of fit throughput."""

import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax.numpy as jnp

from common import run_case
from raft_tpu.cluster import kmeans, kmeans_balanced, KMeansParams


def main():
    rng = np.random.default_rng(0)
    # last entry = BASELINE config 3 (balanced k=1024 on 10M x 96). fit()
    # itself has no trainset cap, so the 10M case passes max_train_points
    # = 2M — the trainset-subsample convention the IVF builds use for
    # this trainer (ivf_pq.py:335) — and the recorded number measures
    # that realistic build-path call, not an uncapped 10M flat EM.
    for n, d, k in [(100_000, 64, 256), (1_000_000, 96, 1024),
                    (10_000_000, 96, 1024)]:
        x = jnp.asarray(rng.random((n, d), dtype=np.float32))
        if n <= 1_000_000:
            # plain Lloyd runs the FULL dataset every iteration; at 10M
            # only the balanced trainer (BASELINE config 3) is the target
            run_case(
                "cluster",
                f"kmeans_fit_{n}x{d}_k{k}",
                lambda x=x, k=k: kmeans.fit(x, KMeansParams(n_clusters=k, max_iter=10))[0],
                iters=2,
                warmup=1,
                items=float(n * 10),
                unit="rows*iter/s",
            )
        trained = min(n, 2_000_000)  # rows the trainer touches
        cap = trained if trained < n else None
        run_case(
            "cluster",
            f"kmeans_balanced_fit_{n}x{d}_k{k}",
            lambda x=x, k=k, cap=cap: kmeans_balanced.fit(
                x, k, n_iters=10, max_train_points=cap
            ),
            iters=2,
            warmup=1,
            items=float(trained * 10),
            unit="rows*iter/s",
        )


if __name__ == "__main__":
    main()
