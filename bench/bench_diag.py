"""Short on-chip diagnostics: where does search wall-time actually go?

Three questions the 2026-08-01 nine-minute chip window left open, each
answerable in seconds of chip time:

1. **Dispatch floor.** Every small stage measured ~80 ms regardless of
   FLOPs, suggesting a fixed per-dispatch round-trip through the axon
   relay. Times a trivial jit'd op and a chained-10x variant; the gap
   between (10 x single) and (1 x chained) IS the per-dispatch overhead.
   If it is ~80 ms, engine QPS at nq=4096 is relay-bound, not
   compute-bound, and every cross-engine delta under ~2x is suspect.

2. **sqeuclidean anomaly.** pairwise L2Expanded measured 825 ms vs
   cosine's 80 ms at the SAME (8192, 768) gemm shape (same `_dot`, same
   bf16 single-pass precision) — a 10x gap with no structural
   explanation. A/Bs L2Expanded / CosineExpanded / InnerProduct /
   raw jnp.matmul, then L2 with the norm terms dropped, isolating
   whether the epilogue (xn + yn - 2d + maximum) is the cost.

3. **Device time vs wall time per engine.** One search per engine under
   jax.profiler.trace; the trace directory size/presence is recorded and
   wall time re-measured, so even without opening TensorBoard the
   numbers bound how much of the 0.62 s approx-trim iteration is device
   compute.

Results bank incrementally to DIAG_RESULTS.json (same Banker discipline
as every chip suite; the relay has died mid-session five times across
rounds)."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import common  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

R = {}
_OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "DIAG_RESULTS.json"
)
# a CPU rehearsal must never clobber chip-banked rows (same rule as
# common.Banker; config-string detection, no backend init)
import jax as _jax_cfg  # noqa: E402

if str(_jax_cfg.config.jax_platforms or "").startswith("cpu"):
    _OUT = _OUT + ".cpu"


# merge-preload: any prior banked rows (e.g. the queue's fast-mode run
# earlier in the same window) survive this run — a later run must only
# ADD rows, never clobber chip-banked attribution (Banker discipline)
try:
    with open(_OUT) as _f:
        _prior = json.load(_f)
    if isinstance(_prior, dict):
        _prior.pop("aborted", None)  # a prior bail must not label this run
        R.update(_prior)
except (OSError, ValueError):
    pass


def _bank():
    print(json.dumps(R), flush=True)
    try:
        with open(_OUT, "w") as f:
            json.dump(R, f, indent=1)
    except OSError:
        pass


def _bail_if_dead(where):
    # CPU-aware (chip_probe_would_hang): smoke rehearsals must run with
    # the relay dead, exactly like bench_10m_build's gate
    try:
        from raft_tpu.core.config import chip_probe_would_hang
    except Exception:
        return
    if chip_probe_would_hang():
        R["aborted"] = f"relay died before {where}"
        _bank()
        sys.exit(3)


def timeit(fn, iters=10):
    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(fn())
    return (time.perf_counter() - t0) / iters


def main():
    _bail_if_dead("backend_init")
    from common import enable_persistent_cache

    enable_persistent_cache()
    smoke = os.environ.get("RAFT_TPU_DIAG_SMOKE") == "1"
    # fast mode (the on-chip queue sets it): skip part 2 (the sqeuclidean
    # anomaly was RESOLVED in the 2026-08-01 window-2 ladder — both 1.48
    # TF/s) and part 3's mini-build + profiler trace (part 4's synthetic
    # stage decomposition answers the attribution question directly).
    # Relay windows have been 9-20 min; diag-first must not eat one.
    fast = os.environ.get("RAFT_TPU_DIAG_FAST") == "1"
    # tail mode: ONLY the parts fast mode skipped (pairwise A/B +
    # mini-build trace) — the queue runs it after the headline banks, so
    # chip minutes aren't re-spent on the already-banked stage rows
    if os.environ.get("RAFT_TPU_DIAG_TAIL") == "1":
        _run_pairwise_ab(smoke)
        _run_engine_profile(smoke)
        return

    # ---- 1. dispatch floor ----
    x = jnp.ones((128, 128), jnp.float32)
    f1 = jax.jit(lambda a: a + 1.0)

    @jax.jit
    def f10(a):
        for _ in range(10):
            a = a + 1.0
        return a

    t_single = timeit(lambda: f1(x))
    t_chain = timeit(lambda: f10(x))
    # 10 dispatches of f1 vs 1 dispatch doing 10x the work:
    per_dispatch = max(0.0, (10 * t_single - t_chain) / 9)
    R["dispatch_single_ms"] = round(t_single * 1e3, 3)
    R["dispatch_chain10_ms"] = round(t_chain * 1e3, 3)
    R["per_dispatch_overhead_ms"] = round(per_dispatch * 1e3, 3)
    _bank()

    if fast:
        R["fast_mode_skipped"] = "pairwise_ab + engine_profile"
        _bank()
    else:
        _run_pairwise_ab(smoke)
        _run_engine_profile(smoke)
    _run_stage_decomposition(smoke)
    _run_refine_isolation(smoke)


def _run_pairwise_ab(smoke):
    # ---- 2. sqeuclidean anomaly ----
    _bail_if_dead("pairwise_ab")
    from raft_tpu.distance import pairwise_distance
    from raft_tpu.distance.distance_types import DistanceType as D
    from raft_tpu.distance.pairwise import _dot, _row_norms_sq

    m = n = 512 if smoke else 8192
    d = 128 if smoke else 768
    kx, ky = jax.random.split(jax.random.PRNGKey(7))
    xb = jax.random.normal(kx, (m, d), jnp.bfloat16)
    yb = jax.random.normal(ky, (n, d), jnp.bfloat16)
    jax.block_until_ready((xb, yb))
    flops = 2.0 * m * n * d

    cases = {
        "matmul": jax.jit(lambda a, b: a @ b.T),
        "dot_f32acc": jax.jit(lambda a, b: _dot(a, b)),
        "inner_product": jax.jit(
            lambda a, b: pairwise_distance(a, b, metric=D.InnerProduct)
        ),
        "cosine": jax.jit(
            lambda a, b: pairwise_distance(a, b, metric=D.CosineExpanded)
        ),
        "l2_expanded": jax.jit(
            lambda a, b: pairwise_distance(a, b, metric=D.L2Expanded)
        ),
        # epilogue isolation: the L2 shape WITHOUT the norm broadcasts
        "l2_no_norms": jax.jit(
            lambda a, b: jnp.maximum(-2.0 * _dot(a, b), 0.0)
        ),
        # and the norm broadcasts WITHOUT the clamp
        "l2_no_clamp": jax.jit(
            lambda a, b: _row_norms_sq(a)[:, None]
            + _row_norms_sq(b)[None, :]
            - 2.0 * _dot(a, b)
        ),
    }
    for name, fn in cases.items():
        _bail_if_dead(name)
        try:
            dt = timeit(lambda fn=fn: fn(xb, yb), iters=5)
            R[f"pw_{name}"] = {
                "ms": round(dt * 1e3, 2),
                "tflops": round(flops / dt / 1e12, 2),
            }
            print(f"pw_{name}: {dt*1e3:.1f} ms {flops/dt/1e12:.2f} TF/s", flush=True)
        except Exception as e:
            R[f"pw_{name}"] = {"error": str(e)[:160]}
            from raft_tpu.core.config import is_device_fault

            if is_device_fault(e):
                R["aborted"] = f"device fault during pw_{name}"
                _bank()
                sys.exit(4)
        _bank()


def _run_engine_profile(smoke):
    # ---- 3. device-time share of one engine iteration ----
    # Build a small-but-representative index (256k rows: ~35 s, vs the
    # ladder's 1M) and profile one approx-trim search. The profile trace
    # gives exact device time; wall time alongside bounds relay overhead.
    _bail_if_dead("engine_profile")
    from raft_tpu.neighbors import ivf_pq

    if smoke:
        nrows, dim, nq, k, nl = 20_000, 32, 256, 10, 64
    else:
        nrows, dim, nq, k, nl = 256_000, 96, 4096, 10, 512
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    dataset = jax.random.normal(k1, (nrows, dim), jnp.float32)
    queries = jax.random.normal(k2, (nq, dim), jnp.float32)
    t0 = time.perf_counter()
    index = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=nl, pq_dim=dim // 2, kmeans_n_iters=4),
        dataset,
    )
    jax.block_until_ready(index.codes)
    R["mini_build_s"] = round(time.perf_counter() - t0, 1)
    p = ivf_pq.SearchParams(n_probes=32, score_mode="recon8_list")
    run = lambda: ivf_pq.search(p, index, queries, k)
    wall = timeit(run, iters=5)
    R["mini_search_wall_ms"] = round(wall * 1e3, 2)
    trace_dir = "/tmp/diag_trace"
    try:
        with jax.profiler.trace(trace_dir):
            jax.block_until_ready(run())
        sz = sum(
            os.path.getsize(os.path.join(dp, f))
            for dp, _, fs in os.walk(trace_dir)
            for f in fs
        )
        R["trace_bytes"] = sz
        R["trace_dir"] = trace_dir
    except Exception as e:
        R["trace_error"] = str(e)[:160]
    _bank()


def _run_stage_decomposition(smoke):
    # ---- 4. stage-decomposed list-major pipeline at EXACT bench shape ----
    # Synthetic arrays (no index build): which stage owns the ~60x gap
    # between the measured 620 ms/batch and the ~10 ms roofline —
    # the qs/store gathers, the scoring matmuls, the approx trim, or the
    # regroup/merge. Stage timings are each one jit'd program, pipelined
    # 3 iters like every other measurement here.
    _bail_if_dead("stage_decomposition")
    from raft_tpu.neighbors.probe_invert import invert_probes
    from raft_tpu.matrix.select_k import _select_k_impl

    if smoke:
        n_lists, L, rot, chunk, npb, nq4 = 16, 384, 32, 16, 4, 128
    else:
        n_lists, L, rot, chunk, npb, nq4 = 1024, 4992, 96, 128, 32, 4096
    kk = 10
    try:
        kA, kB, kC = jax.random.split(jax.random.PRNGKey(1), 3)
        recon8 = jax.random.randint(kA, (n_lists, L, rot), -127, 128, jnp.int8)
        rnorm = jnp.abs(jax.random.normal(kB, (n_lists, L), jnp.float32))
        q_rot = jax.random.normal(kC, (nq4, rot), jnp.float32)
        probes = jax.random.randint(
            jax.random.PRNGKey(2), (nq4, npb), 0, n_lists, jnp.int32
        )
        jax.block_until_ready((recon8, rnorm, q_rot, probes))

        q_pad = jnp.concatenate([q_rot, jnp.zeros((1, rot), jnp.float32)])

        # jit once; reused for both setup execution and the timed stages
        st_inv = jax.jit(lambda p: invert_probes(p, n_lists, chunk))
        st_qs = jax.jit(lambda qid_tbl: q_pad[qid_tbl])  # (ncb, chunk, rot)

        tables = st_inv(probes)
        jax.block_until_ready(tables)
        ncb = int(tables.lof.shape[0])
        qs = st_qs(tables.qid_tbl)
        jax.block_until_ready(qs)
    except Exception as e:
        R["st_setup"] = {"error": str(e)[:160]}
        from raft_tpu.core.config import is_device_fault

        if is_device_fault(e):
            R["aborted"] = "device fault during stage_decomposition setup"
            _bank()
            sys.exit(4)
        _bank()
        return

    def stage_store_gather(lof):
        # the approx engine's r8[lofb] stream, CB=8 blocks like block_fn
        def blk(lo):
            return jnp.sum(recon8[lo].astype(jnp.int32), axis=(1, 2))
        return jax.lax.map(blk, lof.reshape(-1, 8))

    def stage_score(lof, qs):
        def blk(inp):
            lo, q = inp
            rb = recon8[lo]  # (8, L, rot)
            dots = jnp.einsum(
                "cqd,csd->cqs", q.astype(jnp.bfloat16),
                rb.astype(jnp.bfloat16), preferred_element_type=jnp.float32,
            )
            return jnp.sum(dots, axis=2)  # collapse so scores never hit HBM
        return jax.lax.map(
            blk, (lof.reshape(-1, 8), qs.reshape(-1, 8, chunk, rot))
        )

    def stage_score_trim(lof, qs):
        def blk(inp):
            lo, q = inp
            rb = recon8[lo]
            dots = jnp.einsum(
                "cqd,csd->cqs", q.astype(jnp.bfloat16),
                rb.astype(jnp.bfloat16), preferred_element_type=jnp.float32,
            )
            scores = rnorm[lo][:, None, :] - 2.0 * dots
            return jax.lax.approx_min_k(scores, kk, recall_target=0.99)
        return jax.lax.map(
            blk, (lof.reshape(-1, 8), qs.reshape(-1, 8, chunk, rot))
        )

    def stage_score_trim_super(lof, qs):
        # round-5 structure: whole superblocks scored with ONE batched
        # einsum each (chunk_block=0) — same math as stage_score_trim but
        # ~nsuper outer iterations instead of ncb/8 serialized inner scan
        # steps; the delta between the two rows IS the scan overhead the
        # 60x gap hypothesis blames
        budget = 1 << 27
        sb = min(max(1, budget // max(1, chunk * L)), int(lof.shape[0]))
        n_s = -(-int(lof.shape[0]) // sb)
        pad_b = n_s * sb - int(lof.shape[0])
        lofp = jnp.pad(lof, (0, pad_b)) if pad_b else lof
        qsp = jnp.pad(qs, ((0, pad_b), (0, 0), (0, 0))) if pad_b else qs

        def blk(inp):
            lo, q = inp  # (sb,), (sb, chunk, rot)
            rb = recon8[lo]
            dots = jnp.einsum(
                "cqd,csd->cqs", q.astype(jnp.bfloat16),
                rb.astype(jnp.bfloat16), preferred_element_type=jnp.float32,
            )
            scores = rnorm[lo][:, None, :] - 2.0 * dots
            return jax.lax.approx_min_k(scores, kk, recall_target=0.99)
        return jax.lax.map(
            blk, (lofp.reshape(n_s, sb), qsp.reshape(n_s, sb, chunk, rot))
        )

    try:
        vals0 = jax.random.normal(jax.random.PRNGKey(3), (ncb, chunk, kk))
        rows0 = jax.random.randint(
            jax.random.PRNGKey(4), (ncb, chunk, kk), 0, 1 << 20, jnp.int32
        )
        jax.block_until_ready((vals0, rows0))
    except Exception as e:
        R["st_setup"] = {"error": str(e)[:160]}
        _bank()
        return

    def stage_regroup(vals, rows):
        from raft_tpu.neighbors.probe_invert import regroup_merge

        return regroup_merge(
            tables, vals, rows, _select_k_impl, nq4, npb, kk, True
        )

    stages = {
        "st_invert": (st_inv, (probes,)),
        "st_qs_gather": (st_qs, (tables.qid_tbl,)),
        "st_store_gather": (jax.jit(stage_store_gather), (tables.lof,)),
        "st_score_nohbm": (jax.jit(stage_score), (tables.lof, qs)),
        "st_score_trim": (jax.jit(stage_score_trim), (tables.lof, qs)),
        "st_score_trim_super": (
            jax.jit(stage_score_trim_super), (tables.lof, qs)
        ),
        "st_regroup_merge": (jax.jit(stage_regroup), (vals0, rows0)),
    }
    for name, (fn, args) in stages.items():
        _bail_if_dead(name)
        try:
            dt = timeit(lambda: fn(*args), iters=3)
            R[name] = {"ms": round(dt * 1e3, 2)}
            print(f"{name}: {dt*1e3:.1f} ms", flush=True)
        except Exception as e:
            R[name] = {"error": str(e)[:160]}
            from raft_tpu.core.config import is_device_fault

            if is_device_fault(e):
                R["aborted"] = f"device fault during {name}"
                _bank()
                sys.exit(4)
        _bank()
    R["st_shape"] = {"ncb": ncb, "chunk": chunk, "L": L, "rot": rot,
                     "nq": nq4, "n_probes": npb}
    _bank()


def _run_refine_isolation(smoke):
    # ---- 5. refine isolation at EXACT headline shape ----
    # The headline config is np8 REFINED: the stage decomposition above
    # covers only the PQ scan, but the 4k-shortlist exact rerank
    # (gather 4096x40 rows from the 1M dataset + distances + top-10) is
    # the other half of the 750 ms/batch. Synthetic arrays again — the
    # gather cost does not care about index contents.
    _bail_if_dead("refine_isolation")
    try:
        from raft_tpu.neighbors import refine as refine_fn

        key = jax.random.PRNGKey(3)
        k1, k2, k3 = jax.random.split(key, 3)
        n_full, dim_h, nq_h, k_h = 1_000_000, 96, 4096, 10
        if smoke:
            n_full, nq_h = 50_000, 256
        ds_h = jax.random.normal(k1, (n_full, dim_h), jnp.float32)
        qs_h = jax.random.normal(k2, (nq_h, dim_h), jnp.float32)
        cand_h = jax.random.randint(k3, (nq_h, 4 * k_h), 0, n_full)
        jax.block_until_ready((ds_h, qs_h, cand_h))
        # arrays as ARGUMENTS: closed-over they become compile-time
        # constants and XLA folds the whole rerank away (measured 0 ms)
        run = jax.jit(lambda a, b, c: refine_fn(a, b, c, k_h))
        jax.block_until_ready(run(ds_h, qs_h, cand_h))
        dt = timeit(lambda: run(ds_h, qs_h, cand_h), iters=3)
        R["st_refine_4k_shortlist"] = {"ms": round(dt * 1e3, 2),
                                       "n": n_full, "nq": nq_h,
                                       "cand": 4 * k_h}
        if smoke:
            # a rehearsal value must never read as the headline-shape
            # refine cost (same rule as bench.py's smoke tagging)
            R["st_refine_4k_shortlist"]["smoke"] = True
        print(f"st_refine_4k_shortlist: {dt*1e3:.1f} ms", flush=True)
    except Exception as e:
        R["st_refine_4k_shortlist"] = {"error": str(e)[:160]}
        from raft_tpu.core.config import is_device_fault

        if is_device_fault(e):
            R["aborted"] = "device fault during refine_isolation"
            _bank()
            sys.exit(4)
    _bank()


if __name__ == "__main__":
    main()
