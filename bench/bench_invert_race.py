"""Probe-inversion race: sort-based vs counting-based chunk tables.

VERDICT r4 #1 asked for attribution of the ~60x roofline gap; the first
on-chip diag (DIAG_RESULTS.json, 2026-08-02) named `st_invert` — the
probe-pair inversion — at 1810 ms ISOLATED at bench shape (nq=4096,
n_probes=32, n_lists=1024, chunk=128), dwarfing every scoring stage.
The sort-based construction leans on exactly the ops XLA lowers worst on
TPU: two chained 131k-element stable argsorts, two P-sized searchsorted
passes, and a 262k-element random gather. This bench

  1. attributes the cost sub-op by sub-op (sorts / searchsorted /
     gathers / the blocked-cumsum rank scan),
  2. races `invert_probes_sort` vs `invert_probes_count` end-to-end,
  3. verifies the two produce BIT-IDENTICAL tables (the counting
     construction is provably stable-order-equal; trust nothing),
  4. races the engine's (ncb, chunk) query-row gather `q_pad[qid_tbl]`
     against one-hot matmul formulations (the diag's st_qs_gather was
     106.7 ms isolated for a ~100 MB stream — ~1 GB/s),

and with --apply flips the `invert_impl` tuned key iff the counting
construction wins by >10% AND the equality gate passed on this backend.

Reference context: the reference has no inversion step at all — its
query-major CUDA kernel (ivf_pq_search.cuh:611) keeps the LUT SM-resident
so probe order is free; the list-major layout is the TPU-economics
replacement (probe_invert.py module docstring), which makes ITS setup
cost a first-class perf surface.
"""

from __future__ import annotations

import argparse
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

from common import Banker, run_case

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "INVERT_RACE_RESULTS.json")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--apply", action="store_true")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    from raft_tpu.neighbors.probe_invert import (
        invert_probes_sort,
        invert_probes_count,
        chunk_count,
    )

    smoke = args.smoke or str(jax.config.jax_platforms or "").startswith("cpu")
    if smoke:
        nq, n_probes, n_lists, chunk, rot = 512, 8, 128, 32, 32
    else:
        nq, n_probes, n_lists, chunk, rot = 4096, 32, 1024, 128, 96
    P = nq * n_probes
    bk = Banker(OUT, {"shape": {"nq": nq, "n_probes": n_probes,
                                "n_lists": n_lists, "chunk": chunk}})

    key = jax.random.PRNGKey(0)
    probes = jax.random.randint(key, (nq, n_probes), 0, n_lists, jnp.int32)
    flat = probes.reshape(-1)
    q_rot = jax.random.normal(jax.random.PRNGKey(1), (nq, rot), jnp.float32)
    q_pad = jnp.concatenate([q_rot, jnp.zeros((1, rot), jnp.float32)])
    jax.block_until_ready((probes, q_pad))

    def bench(case, fn, *a):
        bk.check_transport()
        jf = jax.jit(fn)
        r = run_case("invert_race", case, lambda: jf(*a), iters=10, warmup=2)
        bk.add(r)
        return r["ms"]

    # ---- 1. sub-op attribution ----
    qid = (jnp.arange(P, dtype=jnp.int32) // n_probes).astype(jnp.int32)
    bench("sub_argsort_stable", lambda f: jnp.argsort(f, stable=True), flat)
    bench("sub_argsort_unstable", lambda f: jnp.argsort(f, stable=False), flat)
    bench("sub_argsort_chain2",
          lambda f: jnp.argsort(jnp.argsort(f, stable=True)), flat)
    bench("sub_sort_variadic",
          lambda f, q: jax.lax.sort((f, q), num_keys=1)[1], flat, qid)
    order = jnp.argsort(flat, stable=True)
    sorted_lists = flat[order]
    sorted_q = (order // n_probes).astype(jnp.int32)
    lids = jnp.arange(n_lists, dtype=jnp.int32)
    bench("sub_searchsorted_P",
          lambda s: jnp.searchsorted(s, lids, side="left"), sorted_lists)
    starts = jnp.searchsorted(sorted_lists, lids, side="left").astype(jnp.int32)
    ends = jnp.searchsorted(sorted_lists, lids, side="right").astype(jnp.int32)
    counts = ends - starts
    base = jnp.cumsum((counts + chunk - 1) // chunk)
    base = (base - (counts + chunk - 1) // chunk).astype(jnp.int32)
    bench("sub_gather_P_from_small", lambda f: base[f], flat)
    ncb = chunk_count(nq, n_probes, n_lists, chunk)
    pair = jax.random.randint(jax.random.PRNGKey(2), (ncb, chunk), 0, P,
                              jnp.int32)
    bench("sub_gather_fancy_262k", lambda s: s[pair], sorted_q)
    off = jnp.sort(jax.random.randint(jax.random.PRNGKey(3), (ncb,), 0, P,
                                      jnp.int32))
    sq_pad = jnp.concatenate([sorted_q, jnp.full((chunk,), nq, jnp.int32)])
    bench("sub_dynslice_rows",
          lambda s: jax.vmap(
              lambda o: jax.lax.dynamic_slice(s, (o,), (chunk,)))(off),
          sq_pad)
    from raft_tpu.neighbors.probe_invert import _blocked_bucket_ranks
    bench("sub_rank_scan",
          lambda f: _blocked_bucket_ranks(f, n_lists)[0], flat)

    # ---- 2. end-to-end race ----
    t_sort = bench("invert_sort",
                   lambda p: invert_probes_sort(p, n_lists, chunk), probes)
    t_count = bench("invert_count",
                    lambda p: invert_probes_count(p, n_lists, chunk), probes)

    # ---- 3. equality gate (bit-identical tables) ----
    a = jax.jit(lambda p: invert_probes_sort(p, n_lists, chunk))(probes)
    b = jax.jit(lambda p: invert_probes_count(p, n_lists, chunk))(probes)
    # pair_valid is None on the unmasked path (jnp.array_equal(None,
    # None) is False, which would wedge the gate shut forever)
    eq = all(
        (x is None and y is None) if (x is None or y is None)
        else bool(jnp.array_equal(x, y))
        for x, y in zip(tuple(a), tuple(b)))
    bk.set("tables_equal", eq)
    print(f"tables_equal: {eq}", flush=True)

    # ---- 4. query-row gather formulations at (ncb, chunk) ----
    qid_tbl = a.qid_tbl
    bench("qs_gather", lambda qt: q_pad[qt], qid_tbl)

    def qs_onehot(qt, dtype, prec):
        oh = (qt[..., None] == jnp.arange(nq + 1, dtype=jnp.int32)).astype(dtype)
        return jnp.einsum("gcn,nd->gcd", oh, q_pad.astype(dtype),
                          precision=prec,
                          preferred_element_type=jnp.float32)

    # blocked to bound the one-hot plane; matches the engine's superblock
    def qs_onehot_blocked(qt, dtype, prec, nb=32):
        pads = (-qt.shape[0]) % nb
        qtp = jnp.pad(qt, ((0, pads), (0, 0))) if pads else qt
        out = jax.lax.map(
            lambda t: qs_onehot(t, dtype, prec),
            qtp.reshape(-1, nb, chunk))
        return out.reshape(-1, chunk, rot)[: qt.shape[0]]

    bench("qs_onehot_bf16",
          lambda qt: qs_onehot_blocked(qt, jnp.bfloat16, "default"), qid_tbl)
    bench("qs_onehot_f32h",
          lambda qt: qs_onehot_blocked(qt, jnp.float32, "highest"), qid_tbl)

    # one-hot selection correctness (bf16 one-hot of exact 0/1 x f32-exact
    # table rows must reproduce the gather when values fit bf16; here we
    # check the f32-highest variant reproduces the gather bitwise)
    g_ref = np.asarray(jax.jit(lambda qt: q_pad[qt])(qid_tbl))
    g_f32 = np.asarray(jax.jit(
        lambda qt: qs_onehot_blocked(qt, jnp.float32, "highest"))(qid_tbl))
    qs_exact = bool(np.array_equal(g_ref, g_f32))
    bk.set("qs_onehot_f32h_exact", qs_exact)
    print(f"qs_onehot_f32h_exact: {qs_exact}", flush=True)

    # ---- apply ----
    if args.apply:
        on_cpu = str(jax.config.jax_platforms or "").startswith("cpu") or (
            jax.default_backend() == "cpu"
        )
        if on_cpu:
            print("apply: CPU rehearsal — never flips chip keys", flush=True)
        elif eq and t_count < 0.9 * t_sort:
            from raft_tpu.core import tuned

            tuned.merge({"invert_impl": "count",
                         "hints": {"invert_race_ms":
                                   {"sort": t_sort, "count": t_count}}})
            print(f"applied: invert_impl=count ({t_count:.1f} vs "
                  f"{t_sort:.1f} ms)", flush=True)
        elif eq and t_sort < 0.9 * t_count:
            from raft_tpu.core import tuned

            tuned.merge({"invert_impl": "sort",
                         "hints": {"invert_race_ms":
                                   {"sort": t_sort, "count": t_count}}})
            print(f"applied: invert_impl=sort ({t_sort:.1f} vs "
                  f"{t_count:.1f} ms)", flush=True)
        else:
            print("apply: no clear winner or equality gate failed; "
                  "keys untouched", flush=True)


if __name__ == "__main__":
    main()
