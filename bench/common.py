"""Micro-benchmark harness (reference `cpp/bench/common/benchmark.hpp:113,145`).

The reference wraps Google Benchmark with a fixture that flushes L2, times
stream-ordered work, and reports items/s. The TPU analogue: block on device
results (`jax.block_until_ready`), time warm steady-state iterations after a
compile+warmup pass, and report one JSON line per case:

  {"suite": ..., "case": ..., "value": ..., "unit": ..., "ms": ...}

Run any suite directly (`python bench/bench_distance.py`) or all of them
(`python bench/run_all.py`). These are perf harnesses, not CI tests —
mirroring how the reference keeps cpp/bench out of CI (survey §4).
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import tempfile
import time
from typing import Callable, Optional

import jax
import numpy as np

# The image's sitecustomize force-registers the TPU PJRT plugin, which
# overrides an env-only CPU selection: a "CPU" smoke run would silently
# dial the (single-client) TPU tunnel. Pin the config when the env asks
# for CPU — exactly __graft_entry__'s pattern.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")


def enable_persistent_cache():
    """Enable jax's persistent compilation cache when the *initialized*
    backend is a real accelerator (triggers backend init — call only
    after the caller's dead-transport check). Over the tunneled relay a
    cold compile is a remote POST costing minutes, and the on-chip queue
    runs several processes back to back that retrace the same programs;
    env intent alone misses the common JAX_PLATFORMS-unset case (r1
    advisor finding). Never raises; returns the cache dir or None."""
    try:
        if jax.config.jax_compilation_cache_dir is not None:
            return jax.config.jax_compilation_cache_dir
        if jax.default_backend() == "cpu":
            return None
        from raft_tpu.core.config import enable_compilation_cache

        return enable_compilation_cache()
    except Exception:
        return None


def ensure_survivable_backend(_platforms=None, _dead=None):
    """ROADMAP item 5a (first slice): make a bench runnable when the
    on-chip child/relay path is down instead of hanging or aborting.

    Call at the top of a bench __main__, BEFORE any device op: when the
    relay transport is structurally dead (chip RPCs can only hang —
    core.config.relay_transport_down) and the env did not already pin
    CPU, pin the CPU platform in-process so the run completes and BANKS
    a real row rather than recycling a stale number. Returns the
    fallback tag ("in_process_cpu") when engaged, else None. Pass the
    tag to `Banker(..., fallback=tag)` so the row lands in the REAL
    results file, honestly labeled, not the .cpu rehearsal file.

    Smoke/rehearsal runs must NOT forward the tag to Banker (drop it
    and keep the .cpu diversion): smoke-scale rows replacing a chip
    session's real file is the exact clobber the diversion guards
    against — see bench_ivf_rabitq.py for the pattern.

    `_platforms`/`_dead` are test seams (tests/test_bench_harness.py);
    production callers pass nothing."""
    platforms = (str(jax.config.jax_platforms or "")
                 if _platforms is None else _platforms)
    if platforms.startswith("cpu"):
        return None  # an explicit CPU run is already survivable
    if _dead is None:
        try:
            from raft_tpu.core.config import relay_transport_down

            _dead = relay_transport_down()
        except Exception:
            return None  # fail-open: a broken check must not divert a run
    if not _dead:
        return None
    jax.config.update("jax_platforms", "cpu")
    return "in_process_cpu"


def run_case(
    suite: str,
    case: str,
    fn: Callable[[], object],
    *,
    iters: int = 5,
    warmup: int = 2,
    items: Optional[float] = None,
    unit: str = "ms",
) -> dict:
    """Time `fn` (which must return device arrays) and print one JSON line.

    With `items`, reports items/s throughput instead of latency.
    """
    enable_persistent_cache()
    for _ in range(max(1, warmup)):
        jax.block_until_ready(fn())
    # with observability on (RAFT_TPU_OBS=1), the timed loop's spans are
    # banked alongside the headline number, so every BENCH row carries
    # per-phase wall-clock attribution for free (docs/observability.md)
    import contextlib

    from raft_tpu import obs

    with (obs.capture_spans() if obs.enabled()
          else contextlib.nullcontext()) as cap:
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(fn())
        dt = (time.perf_counter() - t0) / iters
    rec = {"suite": suite, "case": case, "ms": round(dt * 1e3, 3)}
    phases = cap.totals() if cap is not None else None
    if phases:
        rec["phases"] = phases
        # headline MFU over the FENCED loop wall (phases carry per-span
        # host-window rates; this one divides charged cost by time the
        # device verifiably spent — the number the ledger gates)
        cost = cap.cost_totals()
        wall = dt * iters
        if cost["flops"] and wall > 0:
            from raft_tpu.obs import perf as _perf

            rec["gflops_per_s"] = round(cost["flops"] / wall / 1e9, 3)
            try:
                info = _perf.platform_info()
                m = _perf.mfu(cost["by_dtype"], wall, info)
            except Exception:
                m = None
            if m is not None:
                rec["mfu"] = round(m, 6)
                if info.get("nominal"):
                    rec["mfu_nominal"] = True
    if items is not None:
        rec["value"] = round(items / dt, 1)
        rec["unit"] = unit if unit != "ms" else "items/s"
    else:
        rec["value"] = rec["ms"]
        rec["unit"] = "ms"
    print(json.dumps(rec), flush=True)
    return rec


class Banker:
    """Incremental result persistence for on-chip bench runs (the
    relay-outage discipline, NOTES.md): every record lands in an atomic
    JSON file BEFORE the next long compile starts, so a transport death
    mid-run forfeits only the in-flight stage. `check_transport()`
    between stages converts a 25-minute hung probe into an instant
    rc=3 abort with the partial file already on disk.

    Every banked row is ADDITIONALLY appended to the append-only bench
    ledger (`BENCH_LEDGER.jsonl` next to the results file; override with
    RAFT_TPU_BENCH_LEDGER) stamped with git SHA + platform + honesty
    tags — the rolling history `tools/perfgate` gates regressions
    against. Snapshot files get overwritten every run; the ledger is the
    trajectory."""

    def __init__(self, path: str, meta: Optional[dict] = None,
                 fallback: Optional[str] = None, resume: bool = False):
        # a CPU rehearsal must never clobber a chip-banked results file
        # (2026-08-01: a --smoke run overwrote the window-2 select_k
        # chip rows); same config-string detection as check_transport —
        # no backend init. EXCEPTION: an engaged dead-relay fallback
        # (`ensure_survivable_backend`) banks to the REAL file — the
        # whole point of item 5a is that a dead relay stops recycling
        # stale rows — with the rows honestly tagged `fallback`.
        if meta and not {"rows", "aborted"}.isdisjoint(meta):
            # "rows" is the banked-row list and "aborted" the transport
            # flag; a geometry field silently landing on either corrupts
            # the record shape (first caught as an AttributeError three
            # stages into a run) — refuse up front instead
            raise ValueError("Banker meta keys 'rows'/'aborted' are "
                             "reserved (use e.g. 'dataset_rows')")
        self._bench = os.path.splitext(os.path.basename(path))[0]
        self._ledger_dir = os.path.dirname(os.path.abspath(path))
        self._fallback = str(fallback) if fallback is not None else None
        self._cpu = str(jax.config.jax_platforms or "").startswith("cpu")
        if fallback is not None:
            meta = dict(meta or {}, fallback=str(fallback))
        elif self._cpu:
            path = path + ".cpu"
            meta = dict(meta or {}, cpu_rehearsal=True)
        self.path = path
        self.record = dict(meta or {})
        self.record.setdefault("rows", [])
        self.record.setdefault("aborted", False)
        self._adopted: list = []
        if resume:
            # durable-job resume (--job-dir benches): stages the runner
            # skips never re-bank their rows, so the fresh record here
            # would wipe them from the snapshot — carry the prior run's
            # rows forward when its geometry meta matches this run's (a
            # geometry change invalidates the job fingerprints anyway,
            # so mismatched rows never carry). The ledger is unaffected:
            # adopted rows were already appended when first banked.
            self._adopt_prior_rows()
        self.flush()

    def _adopt_prior_rows(self) -> None:
        try:
            with open(self.path) as fh:
                prior = json.load(fh)
        except (OSError, ValueError):
            return
        keys = [k for k in self.record if k not in ("rows", "aborted")]
        if all(prior.get(k) == self.record[k] for k in keys):
            self.record["rows"] = list(prior.get("rows") or [])
            self._adopted = list(self.record["rows"])

    def add(self, row: dict, echo: bool = True) -> None:
        if echo:
            print(json.dumps(row), flush=True)
        # a fresh measurement supersedes any ADOPTED row for the same
        # stage: a stage killed after banking but before its manifest
        # commit re-runs on resume, and keeping both copies would
        # duplicate it in the snapshot (the ledger keeps both attempts —
        # it is the append-only trajectory of what actually ran)
        stage = row.get("stage")
        if stage is not None and self._adopted:
            drop = [id(r) for r in self._adopted if r.get("stage") == stage]
            if drop:
                self.record["rows"] = [
                    r for r in self.record["rows"] if id(r) not in drop]
                self._adopted = [r for r in self._adopted
                                 if id(r) not in drop]
        self.record["rows"].append(row)
        self.flush()
        self._ledger_append(row)

    def _ledger_append(self, row: dict) -> None:
        """One honest ledger line per banked row (ledger.bank_row never
        raises — a broken ledger must not kill the bench)."""
        try:
            from raft_tpu.obs import ledger
        except Exception:
            return
        ledger.bank_row(
            bench=self._bench, row=row,
            platform=("cpu" if self._cpu or self._fallback is not None
                      else "tpu"),
            repo_dir=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            ledger_dir=self._ledger_dir,
            fallback=self._fallback,
            cpu_rehearsal=True if (self._cpu and self._fallback is None)
            else None)

    def set(self, key: str, value) -> None:
        self.record[key] = value
        self.flush()

    def flush(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.record, f, indent=1)
        os.replace(tmp, self.path)

    def check_transport(self) -> None:
        """Abort (rc=3) if the relay transport died; partials stay banked.
        MUST NOT initialize a jax backend (jax.default_backend() /
        jax.devices() block ~25 min against a dead relay): CPU runs are
        detected from the config string alone."""
        platforms = str(jax.config.jax_platforms or "")
        if platforms.startswith("cpu"):
            return
        try:
            from raft_tpu.core.config import relay_transport_down

            dead = relay_transport_down()
        except Exception:
            return  # fail-open: a broken check must not kill a live run
        if dead:
            self.record["aborted"] = "relay transport dead"
            self.flush()
            print(json.dumps({"aborted": "relay transport dead"}), flush=True)
            raise SystemExit(3)


# -- shared jobification pieces (ISSUE 8) ------------------------------
#
# The job benches (bench_10m_build, bench_100m_rehearsal,
# bench_perf_smoke) share one preemption protocol: a durable --job-dir
# (temp dir, no resume, when omitted), a --stop-after drill seam, and
# "suspend == exit PREEMPT_EXIT". Keep the protocol here so a change to
# it lands once.

PREEMPT_EXIT = 75  # EX_TEMPFAIL: "re-run the same command to resume"


def job_resuming(job_dir: Optional[str]) -> bool:
    """True only when --job-dir points at a job with committed history —
    the one case `Banker(resume=)` may carry prior snapshot rows
    forward. A fresh job dir (or none) must NOT adopt an older
    session's rows: that would be exactly the stale-number recycling
    the survivable-bench work deleted."""
    if not job_dir:
        return False
    from raft_tpu.jobs.jobdir import MANIFEST_NAME  # one layout definition

    return os.path.exists(os.path.join(job_dir, MANIFEST_NAME))


def stream_ckpt_every(rows: int, batch: int) -> int:
    """Amortized checkpoint cadence for a bench's streaming-extend
    stage: every ~1/8th of the stream. checkpoint_every=1 would save
    the whole (growing) index at every batch boundary — O(n^2)
    checkpoint bytes charged to the banked throughput at 100M scale —
    while every n/8 bounds the kill-loss window to 1/8th of the build
    and keeps the checkpoint cost a rounding error in the timed wall."""
    n_batches = max(1, -(-int(rows) // max(1, int(batch))))
    return max(1, n_batches // 8)


def blob_centers(n_blobs: int, dim: int, seed: int = 0) -> np.ndarray:
    """The fixed blob centers the chunk maker re-derives per chunk
    (cheap vs. chunk cost; keeps every chunk self-contained)."""
    return np.random.default_rng(seed).uniform(
        -5.0, 5.0, (n_blobs, dim)).astype(np.float32)


def blob_chunk_maker(n_blobs: int, dim: int, *, centers_seed: int = 0,
                     chunk_seed: int = 1) -> Callable[[int, int], np.ndarray]:
    """Chunk synthesizer for `jobs.resumable_write_npy`: deterministic
    in (lo, hi) — ALL randomness derives from (chunk_seed, lo) — so a
    resumed file is byte-identical to a one-shot write."""
    def make_chunk(lo: int, hi: int) -> np.ndarray:
        centers = blob_centers(n_blobs, dim, seed=centers_seed)
        rng = np.random.default_rng((chunk_seed, lo))
        a = rng.integers(0, n_blobs, hi - lo)
        return (centers[a]
                + rng.standard_normal((hi - lo, dim)).astype(np.float32))
    return make_chunk


def stop_after_hook(job, stop_after: Optional[str]) -> Callable[[str], None]:
    """`--stop-after` drill seam: after the named stage commits, request
    a preempt so the runner suspends exactly as a SIGTERM would."""
    def _maybe_suspend(stage: str) -> None:
        if stop_after == stage:
            job.request_preempt()
    return _maybe_suspend


@contextlib.contextmanager
def job_dir_or_temp(job_dir: Optional[str], prefix: str):
    """Yield `job_dir` when the caller wants durable resume, else a
    fresh temp JobDir swept on exit (no resume across runs)."""
    if job_dir:
        yield job_dir
        return
    tmpdir = tempfile.mkdtemp(prefix=prefix)
    try:
        yield os.path.join(tmpdir, "job")
    finally:
        shutil.rmtree(tmpdir, ignore_errors=True)


def run_job_to_exit(job) -> int:
    """Run a bench job to a process exit code: 0 on success (statuses
    echoed as JSON), PREEMPT_EXIT on a suspend. Stage failures raise."""
    from raft_tpu import jobs

    try:
        statuses = job.run()
    except jobs.JobPreempted:
        print(json.dumps({"preempted": True, "statuses": job.statuses}),
              flush=True)
        return PREEMPT_EXIT
    print(json.dumps({"statuses": statuses}), flush=True)
    return 0
