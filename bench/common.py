"""Micro-benchmark harness (reference `cpp/bench/common/benchmark.hpp:113,145`).

The reference wraps Google Benchmark with a fixture that flushes L2, times
stream-ordered work, and reports items/s. The TPU analogue: block on device
results (`jax.block_until_ready`), time warm steady-state iterations after a
compile+warmup pass, and report one JSON line per case:

  {"suite": ..., "case": ..., "value": ..., "unit": ..., "ms": ...}

Run any suite directly (`python bench/bench_distance.py`) or all of them
(`python bench/run_all.py`). These are perf harnesses, not CI tests —
mirroring how the reference keeps cpp/bench out of CI (survey §4).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional

import jax

# The image's sitecustomize force-registers the TPU PJRT plugin, which
# overrides an env-only CPU selection: a "CPU" smoke run would silently
# dial the (single-client) TPU tunnel. Pin the config when the env asks
# for CPU — exactly __graft_entry__'s pattern.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")


def enable_persistent_cache():
    """Enable jax's persistent compilation cache when the *initialized*
    backend is a real accelerator (triggers backend init — call only
    after the caller's dead-transport check). Over the tunneled relay a
    cold compile is a remote POST costing minutes, and the on-chip queue
    runs several processes back to back that retrace the same programs;
    env intent alone misses the common JAX_PLATFORMS-unset case (r1
    advisor finding). Never raises; returns the cache dir or None."""
    try:
        if jax.config.jax_compilation_cache_dir is not None:
            return jax.config.jax_compilation_cache_dir
        if jax.default_backend() == "cpu":
            return None
        from raft_tpu.core.config import enable_compilation_cache

        return enable_compilation_cache()
    except Exception:
        return None


def ensure_survivable_backend(_platforms=None, _dead=None):
    """ROADMAP item 5a (first slice): make a bench runnable when the
    on-chip child/relay path is down instead of hanging or aborting.

    Call at the top of a bench __main__, BEFORE any device op: when the
    relay transport is structurally dead (chip RPCs can only hang —
    core.config.relay_transport_down) and the env did not already pin
    CPU, pin the CPU platform in-process so the run completes and BANKS
    a real row rather than recycling a stale number. Returns the
    fallback tag ("in_process_cpu") when engaged, else None. Pass the
    tag to `Banker(..., fallback=tag)` so the row lands in the REAL
    results file, honestly labeled, not the .cpu rehearsal file.

    Smoke/rehearsal runs must NOT forward the tag to Banker (drop it
    and keep the .cpu diversion): smoke-scale rows replacing a chip
    session's real file is the exact clobber the diversion guards
    against — see bench_ivf_rabitq.py for the pattern.

    `_platforms`/`_dead` are test seams (tests/test_bench_harness.py);
    production callers pass nothing."""
    platforms = (str(jax.config.jax_platforms or "")
                 if _platforms is None else _platforms)
    if platforms.startswith("cpu"):
        return None  # an explicit CPU run is already survivable
    if _dead is None:
        try:
            from raft_tpu.core.config import relay_transport_down

            _dead = relay_transport_down()
        except Exception:
            return None  # fail-open: a broken check must not divert a run
    if not _dead:
        return None
    jax.config.update("jax_platforms", "cpu")
    return "in_process_cpu"


def run_case(
    suite: str,
    case: str,
    fn: Callable[[], object],
    *,
    iters: int = 5,
    warmup: int = 2,
    items: Optional[float] = None,
    unit: str = "ms",
) -> dict:
    """Time `fn` (which must return device arrays) and print one JSON line.

    With `items`, reports items/s throughput instead of latency.
    """
    enable_persistent_cache()
    for _ in range(max(1, warmup)):
        jax.block_until_ready(fn())
    # with observability on (RAFT_TPU_OBS=1), the timed loop's spans are
    # banked alongside the headline number, so every BENCH row carries
    # per-phase wall-clock attribution for free (docs/observability.md)
    import contextlib

    from raft_tpu import obs

    with (obs.capture_spans() if obs.enabled()
          else contextlib.nullcontext()) as cap:
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(fn())
        dt = (time.perf_counter() - t0) / iters
    rec = {"suite": suite, "case": case, "ms": round(dt * 1e3, 3)}
    phases = cap.totals() if cap is not None else None
    if phases:
        rec["phases"] = phases
        # headline MFU over the FENCED loop wall (phases carry per-span
        # host-window rates; this one divides charged cost by time the
        # device verifiably spent — the number the ledger gates)
        cost = cap.cost_totals()
        wall = dt * iters
        if cost["flops"] and wall > 0:
            from raft_tpu.obs import perf as _perf

            rec["gflops_per_s"] = round(cost["flops"] / wall / 1e9, 3)
            try:
                info = _perf.platform_info()
                m = _perf.mfu(cost["by_dtype"], wall, info)
            except Exception:
                m = None
            if m is not None:
                rec["mfu"] = round(m, 6)
                if info.get("nominal"):
                    rec["mfu_nominal"] = True
    if items is not None:
        rec["value"] = round(items / dt, 1)
        rec["unit"] = unit if unit != "ms" else "items/s"
    else:
        rec["value"] = rec["ms"]
        rec["unit"] = "ms"
    print(json.dumps(rec), flush=True)
    return rec


class Banker:
    """Incremental result persistence for on-chip bench runs (the
    relay-outage discipline, NOTES.md): every record lands in an atomic
    JSON file BEFORE the next long compile starts, so a transport death
    mid-run forfeits only the in-flight stage. `check_transport()`
    between stages converts a 25-minute hung probe into an instant
    rc=3 abort with the partial file already on disk.

    Every banked row is ADDITIONALLY appended to the append-only bench
    ledger (`BENCH_LEDGER.jsonl` next to the results file; override with
    RAFT_TPU_BENCH_LEDGER) stamped with git SHA + platform + honesty
    tags — the rolling history `tools/perfgate` gates regressions
    against. Snapshot files get overwritten every run; the ledger is the
    trajectory."""

    def __init__(self, path: str, meta: Optional[dict] = None,
                 fallback: Optional[str] = None):
        # a CPU rehearsal must never clobber a chip-banked results file
        # (2026-08-01: a --smoke run overwrote the window-2 select_k
        # chip rows); same config-string detection as check_transport —
        # no backend init. EXCEPTION: an engaged dead-relay fallback
        # (`ensure_survivable_backend`) banks to the REAL file — the
        # whole point of item 5a is that a dead relay stops recycling
        # stale rows — with the rows honestly tagged `fallback`.
        if meta and not {"rows", "aborted"}.isdisjoint(meta):
            # "rows" is the banked-row list and "aborted" the transport
            # flag; a geometry field silently landing on either corrupts
            # the record shape (first caught as an AttributeError three
            # stages into a run) — refuse up front instead
            raise ValueError("Banker meta keys 'rows'/'aborted' are "
                             "reserved (use e.g. 'dataset_rows')")
        self._bench = os.path.splitext(os.path.basename(path))[0]
        self._ledger_dir = os.path.dirname(os.path.abspath(path))
        self._fallback = str(fallback) if fallback is not None else None
        self._cpu = str(jax.config.jax_platforms or "").startswith("cpu")
        if fallback is not None:
            meta = dict(meta or {}, fallback=str(fallback))
        elif self._cpu:
            path = path + ".cpu"
            meta = dict(meta or {}, cpu_rehearsal=True)
        self.path = path
        self.record = dict(meta or {})
        self.record.setdefault("rows", [])
        self.record.setdefault("aborted", False)
        self.flush()

    def add(self, row: dict, echo: bool = True) -> None:
        if echo:
            print(json.dumps(row), flush=True)
        self.record["rows"].append(row)
        self.flush()
        self._ledger_append(row)

    def _ledger_append(self, row: dict) -> None:
        """One honest ledger line per banked row (ledger.bank_row never
        raises — a broken ledger must not kill the bench)."""
        try:
            from raft_tpu.obs import ledger
        except Exception:
            return
        ledger.bank_row(
            bench=self._bench, row=row,
            platform=("cpu" if self._cpu or self._fallback is not None
                      else "tpu"),
            repo_dir=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            ledger_dir=self._ledger_dir,
            fallback=self._fallback,
            cpu_rehearsal=True if (self._cpu and self._fallback is None)
            else None)

    def set(self, key: str, value) -> None:
        self.record[key] = value
        self.flush()

    def flush(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.record, f, indent=1)
        os.replace(tmp, self.path)

    def check_transport(self) -> None:
        """Abort (rc=3) if the relay transport died; partials stay banked.
        MUST NOT initialize a jax backend (jax.default_backend() /
        jax.devices() block ~25 min against a dead relay): CPU runs are
        detected from the config string alone."""
        platforms = str(jax.config.jax_platforms or "")
        if platforms.startswith("cpu"):
            return
        try:
            from raft_tpu.core.config import relay_transport_down

            dead = relay_transport_down()
        except Exception:
            return  # fail-open: a broken check must not kill a live run
        if dead:
            self.record["aborted"] = "relay transport dead"
            self.flush()
            print(json.dumps({"aborted": "relay transport dead"}), flush=True)
            raise SystemExit(3)
