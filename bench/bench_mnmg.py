"""MNMG bench: distributed k-means + distributed IVF-PQ over a device mesh
(BASELINE config 5 — the raft-dask-equivalent path, survey §2.15/§5.8).

Runs on whatever mesh is available: a v5e pod slice (call site runs under
`bootstrap_multihost()` on every host), a single chip (mesh of 1), or the
8-device virtual CPU mesh (`JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8`, with --smoke).

Prints one JSON line per stage; shard counts and mesh size are recorded so
pod results are comparable across slice sizes.
"""

import glob
import json
import sys, os, time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import common  # noqa: F401  (pins CPU when JAX_PLATFORMS=cpu asks for it)
import jax


def main(smoke: bool = False):
    from raft_tpu.comms import Comms, mnmg
    from raft_tpu.neighbors import brute_force, ivf_pq

    c = Comms()
    r = c.get_size()
    if smoke:
        n, dim, k_means, n_lists, pq_dim, nq, k = 40_000, 32, 64, 32, 16, 256, 10
    else:
        n, dim, k_means, n_lists, pq_dim, nq, k = 10_000_000, 96, 1024, 1024, 48, 4096, 10

    rng = np.random.default_rng(0)
    n_blobs = 1024
    centers = rng.uniform(-5.0, 5.0, (n_blobs, dim)).astype(np.float32)
    a = rng.integers(0, n_blobs, n)
    data = centers[a] + rng.standard_normal((n, dim)).astype(np.float32)
    queries = centers[rng.integers(0, n_blobs, nq)] + rng.standard_normal(
        (nq, dim)
    ).astype(np.float32)

    # --- distributed k-means (the cuML MNMG pattern: per-iter allreduce)
    t0 = time.perf_counter()
    km_centers, inertia, n_iter = mnmg.kmeans_fit(c, data, k_means, max_iter=10)
    jax.block_until_ready(km_centers)
    print(json.dumps({
        "suite": "mnmg", "case": f"kmeans_{n}x{dim}_k{k_means}_r{r}",
        "s": round(time.perf_counter() - t0, 2), "n_iter": n_iter,
        "rows_per_s_per_rank": round(n * n_iter / (time.perf_counter() - t0) / r, 1),
    }), flush=True)

    # --- distributed IVF-PQ build + both search engines
    t0 = time.perf_counter()
    params = ivf_pq.IndexParams(n_lists=n_lists, pq_dim=pq_dim, kmeans_n_iters=10)
    dindex = mnmg.ivf_pq_build(c, params, data)
    jax.block_until_ready(dindex.codes)
    build_s = time.perf_counter() - t0
    print(json.dumps({
        "suite": "mnmg", "case": f"ivf_pq_build_{n}x{dim}_r{r}",
        "s": round(build_s, 2),
    }), flush=True)

    _, truth = brute_force.knn(data if smoke else data[: 2_000_000], queries, k)
    truth = np.asarray(truth)
    gate_note = "exact" if smoke else "truth over a 2M prefix (pipeline sanity)"

    # the refined case is the reference's recall-at-QPS recipe: fewer
    # probes + per-rank exact refine before the merge. The warmup call
    # populates the index's refine-layout cache, so the timed loop
    # measures search, not dataset re-upload.
    n_probes = min(32, n_lists)
    np_ref = min(8, n_lists)
    cases = [
        ("recon8_list", n_probes, {"engine": "recon8_list"}),
        ("lut", n_probes, {"engine": "lut"}),
        ("refined", np_ref, {"refine_dataset": data}),
    ]
    for name, probes, kwargs in cases:
        dv, di = mnmg.ivf_pq_search(dindex, queries, k, n_probes=probes, **kwargs)
        jax.block_until_ready((dv, di))
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            dv, di = mnmg.ivf_pq_search(dindex, queries, k, n_probes=probes,
                                        **kwargs)
            jax.block_until_ready((dv, di))
        dt = (time.perf_counter() - t0) / iters
        got = np.asarray(di)
        rec = float(np.mean([len(set(got[j]) & set(truth[j])) / k
                             for j in range(nq)])) if smoke else None
        print(json.dumps({
            "suite": "mnmg",
            "case": f"ivf_pq_search_{name}_{n}x{dim}_r{r}_p{probes}",
            "qps": round(nq / dt, 1),
            "recall@10": round(rec, 4) if rec is not None else gate_note,
        }), flush=True)

    # --- streaming ingestion + sharded checkpoint (the pod serving loop:
    # keep ingesting, checkpoint collectively, reload). The *_local APIs
    # are per-partition: every process generates the same global arrays
    # (shared rng seed) and passes only ITS contiguous slice, so counts
    # and throughput stay global-scale on multi-process runs.
    del dindex  # the replicated-build index; free shards before rebuilding
    n_extend = 10_000 if smoke else 1_000_000
    extra = centers[rng.integers(0, n_blobs, n_extend)] + rng.standard_normal(
        (n_extend, dim)).astype(np.float32)
    pi, nproc = jax.process_index(), jax.process_count()
    per_p = -(-n // nproc)
    per_e = -(-n_extend // nproc)
    lidx = mnmg.ivf_pq_build_local(c, params,
                                   data[pi * per_p:(pi + 1) * per_p])
    t0 = time.perf_counter()
    lidx = mnmg.ivf_pq_extend_local(lidx, extra[pi * per_e:(pi + 1) * per_e])
    jax.block_until_ready(lidx.codes)
    dt = time.perf_counter() - t0
    print(json.dumps({
        "suite": "mnmg", "case": f"ivf_pq_extend_local_{n_extend}_r{r}",
        "s": round(dt, 2), "rows_per_s": round(n_extend / dt, 1),
    }), flush=True)

    # checkpoint stage needs a filesystem every process can read (the
    # shared-fs contract of the sharded format); /tmp only qualifies
    # single-host — pods pass RAFT_TPU_BENCH_CKPT_DIR on shared storage
    ckpt_dir = os.environ.get("RAFT_TPU_BENCH_CKPT_DIR")
    if nproc > 1 and not ckpt_dir:
        print(json.dumps({"suite": "mnmg", "case": "sharded_ckpt",
                          "skipped": "multi-process without "
                          "RAFT_TPU_BENCH_CKPT_DIR (shared fs)"}), flush=True)
        return
    import tempfile

    ckpt = os.path.join(ckpt_dir or tempfile.gettempdir(),
                        "bench_mnmg_ckpt.rtpq")
    # stale cleanup: ONE process, and a barrier before anyone saves —
    # unsynchronized unlinks would race both each other and the fresh
    # part files (save_local writes parts before its first barrier)
    if pi == 0:
        for stale in glob.glob(ckpt + "*"):  # must not inflate bytes
            os.unlink(stale)
    if nproc > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("bench_mnmg_ckpt_clean")
    t0 = time.perf_counter()
    mnmg.ivf_pq_save_local(ckpt, lidx)
    save_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    reloaded = mnmg.ivf_pq_load(c, ckpt)
    jax.block_until_ready(reloaded.codes)
    load_s = time.perf_counter() - t0
    print(json.dumps({
        "suite": "mnmg", "case": f"sharded_ckpt_{lidx.n}rows_r{r}",
        "save_s": round(save_s, 2), "load_s": round(load_s, 2),
        "bytes": sum(os.path.getsize(p)
                     for p in glob.glob(ckpt + "*")),
    }), flush=True)
    if nproc > 1:  # all loads must finish before any file is deleted
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("bench_mnmg_ckpt_cleanup")
    if pi == 0:  # don't leave a half-GB checkpoint in /tmp
        for p in glob.glob(ckpt + "*"):
            os.unlink(p)


if __name__ == "__main__":
    main(smoke="--smoke" in sys.argv)
