"""select_k strategy race: lax.top_k vs two-phase vs approx_max_k vs
the Pallas counting-select engine — plus the OPERAND-level race of the
fused distance+select-k kernel vs the materializing two-phase scan
(`matrix.scan_select_k`), the measurement behind the tuned
`select_k_strategy` key, and (ISSUE 11) the INTEGER-scan races behind
`select_k_strategy_int8` (exact fused int8 PQ-recon trim vs the pallas
bin trim) and `select_k_strategy_bitplane` (fused RaBitQ AND+popcount
scan vs the XLA bit-plane reference).

Reference parity: matrix/detail/select_k.cuh:67-88 picks warpsort vs radix
from an empirically-derived (batch, len, k) heuristic measured with
cpp/bench/matrix/select_k.cu. This is the TPU-side measurement that sets
`_select_k_impl`'s dispatch thresholds (matrix/select_k.py): run on the
chip, read the per-shape winners, and encode them with a citation to the
recorded numbers.

Grid: the reference bench's (batch, len, k) ladder plus the IVF shapes
this library actually funnels through select_k (coarse probe selection,
per-chunk trims, final merges). approx entries are flagged: approx_max_k
at recall_target=0.99 is not exact, so it can only back the engines that
already budget for an approximate trim (the list-major chunk trim), never
the public matrix.select_k contract. The scan race is exact on both
sides: the fused kernel's only deviation is ranking the bf16-rounded
operands (the compute_dtype=bfloat16 class), so `--apply` may promote
it as the auto strategy on chip data alone.
"""

import json
import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from common import run_case
from raft_tpu.matrix.select_k import _two_phase_largest


def main(smoke: bool = False):
    # cache enablement rides run_case() in common.py
    from common import Banker

    bank = Banker(
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "SELECT_K_RACE_RESULTS.json"),
        {"smoke": smoke},
    )
    rng = np.random.default_rng(0)
    shapes = [
        # reference select_k.cu ladder
        (64, 1 << 14, 64),
        (64, 1 << 17, 128),
        (128, 1 << 20, 256),
        (1024, 1 << 14, 64),
        # IVF funnel shapes: coarse (nq x n_lists, small k), chunk trim
        # (chunk x max_list), final merge (nq x n_probes*k)
        (4096, 1024, 32),
        (128, 4096, 10),
        (4096, 320, 10),
        # brute-force per-tile select at headline geometry (the BF scan
        # calls _select_k_impl once per 32768-row tile; after the bf16
        # matmul flip this select is the scan's probable bottleneck)
        (4096, 1 << 15, 10),
    ]
    if smoke:  # CPU correctness pass: tiny grid, the chip run uses the full one
        shapes = [(16, 1 << 15, 32), (64, 512, 10)]
    from raft_tpu.matrix import select_k as select_k_public
    from raft_tpu.ops.select_counting import fits_counting

    interp = jax.default_backend() == "cpu"  # interpret too slow at scale
    strategies = {
        "topk": lambda v, k: lax.top_k(v, k),
        "twophase": lambda v, k: _two_phase_largest(v, k),
        "approx99": lambda v, k: lax.approx_max_k(v, k, recall_target=0.99),
        # the real public counting path (select_k owns negation/interp/dtype
        # handling — racing a private reimplementation would drift)
        "counting": lambda v, k: select_k_public(
            v, k, select_min=False, strategy="counting"
        ),
    }
    winners = {}
    for batch, length, k in shapes:
        vals = jnp.asarray(rng.random((batch, length), dtype=np.float32))
        best = None
        raced = []
        timings = {}
        for name, fn in strategies.items():
            if name == "twophase" and length < 2 * (1 << 14):
                continue  # needs >1 chunk to differ from topk
            # the wrapper pads rows to a lane multiple itself, so the fit
            # check must see the padded length or non-x128 shapes (the IVF
            # final-merge entry) silently lose their counting measurement
            padded_len = length + (-length) % 128
            if name == "counting" and not fits_counting(batch, padded_len, k):
                continue  # row exceeds the kernel's VMEM envelope
            if name == "counting" and interp and length > 1 << 15:
                continue  # interpret mode is too slow at large L
            if name == "counting":
                # select_k jits internally and validates in python; time it
                # as users call it rather than through an outer jit
                jfn = lambda v, fn=fn, k=k: fn(v, k)
            else:
                jfn = jax.jit(lambda v, fn=fn, k=k: fn(v, k))
            bank.check_transport()  # banked rows survive a mid-race death
            rec = run_case(
                "select_k_strategy",
                f"{name}_{batch}x{length}_k{k}",
                lambda v=vals, jfn=jfn: jfn(v),
                items=float(batch * length),
                unit="elems/s",
            )
            bank.record["rows"].append(rec)
            bank.flush()
            raced.append(name)
            timings[name] = rec["value"]
            if best is None or rec["value"] > best[1]:
                best = (name, rec["value"])
        bank.add({
            "suite": "select_k_strategy",
            "case": f"winner_{batch}x{length}_k{k}",
            "winner": best[0],
            "value": best[1],
            "unit": "elems/s",
        })
        winners[(batch, length, k)] = (best[0], tuple(raced), timings)

    # -- operand-level race: fused scan+select vs two-phase ------------
    # (nq, n, d, k): the brute-force headline geometry shrunk to the
    # backend, plus a rerank-shaped small-n entry. Exact on both sides;
    # the fused kernel scores bf16 operands (documented rounding class).
    scan_shapes = [(4096, 1 << 15, 96, 10), (1024, 4096, 96, 100)]
    if smoke:
        scan_shapes = [(128, 4096, 32, 10)]
    from raft_tpu.matrix import scan_select_k
    from raft_tpu.ops.fused_scan import fits_fused

    scan_winners = {}
    for nq, n, d, k in scan_shapes:
        if interp and n * nq > 1 << 20:
            continue  # interpret-mode kernel too slow at scale
        qv = jnp.asarray(rng.random((nq, d), dtype=np.float32))
        dv = jnp.asarray(rng.random((n, d), dtype=np.float32))
        best = None
        timings = {}
        for name in ("two_phase", "fused"):
            if name == "fused" and not fits_fused(nq, n, d, k):
                continue
            bank.check_transport()
            rec = run_case(
                "select_k_strategy",
                f"scan_{name}_{nq}x{n}x{d}_k{k}",
                lambda name=name: scan_select_k(qv, dv, k, strategy=name),
                items=float(nq),
                unit="qps",
            )
            bank.record["rows"].append(rec)
            bank.flush()
            timings[name] = rec["value"]
            if best is None or rec["value"] > best[1]:
                best = (name, rec["value"])
        if best is None:
            continue
        bank.add({
            "suite": "select_k_strategy",
            "case": f"scan_winner_{nq}x{n}x{d}_k{k}",
            "winner": best[0],
            "value": best[1],
            "unit": "qps",
        })
        scan_winners[(nq, n, d, k)] = (best[0], timings)

    # -- integer-scan races (ISSUE 11) ---------------------------------
    # (a) int8 PQ-recon trim: the exact fused int8 scan (dispatch
    #     strategy "fused_int8") vs the pallas bin trim — both score on
    #     the int8 MXU path with identical quantization, so the race is
    #     purely the trim geometry; a fused sweep flips the tuned
    #     `select_k_strategy_int8` key.
    # (b) RaBitQ bit-plane scan: the fused AND+popcount kernel vs the
    #     XLA bit-plane reference (identical estimator scores); a fused
    #     sweep flips `select_k_strategy_bitplane`.
    from raft_tpu.neighbors import ivf_pq, ivf_rabitq

    int_winners = {}
    if smoke or interp:
        nq_i, n_i, d_i, nl_i, probes_i, k_i = 64, 4000, 32, 16, 8, 10
    else:
        # bench geometry: the 1Mx96 headline shrunk to a race-friendly
        # 100K (build cost, not scan cost, is the bound here)
        nq_i, n_i, d_i, nl_i, probes_i, k_i = 4096, 100_000, 96, 1024, 32, 10
    data_i = jnp.asarray(rng.random((n_i, d_i), dtype=np.float32))
    q_i = jnp.asarray(rng.random((nq_i, d_i), dtype=np.float32))

    pq_idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=nl_i, kmeans_n_iters=4, pq_dim=d_i // 2),
        data_i,
    )
    pq_entries = {
        "int8_fused": ivf_pq.SearchParams(
            n_probes=probes_i, trim_engine="fused", score_dtype="int8"),
        "int8_pallas": ivf_pq.SearchParams(
            n_probes=probes_i, score_mode="recon8_list",
            trim_engine="pallas", score_dtype="int8"),
    }
    best = None
    timings = {}
    for name, sp in pq_entries.items():
        bank.check_transport()
        rec = run_case(
            "select_k_strategy",
            f"pqint8_{name}_{nq_i}x{n_i}x{d_i}_k{k_i}",
            lambda sp=sp: ivf_pq.search(sp, pq_idx, q_i, k_i),
            items=float(nq_i),
            unit="qps",
        )
        bank.record["rows"].append(rec)
        bank.flush()
        timings[name] = rec["value"]
        if best is None or rec["value"] > best[1]:
            best = (name, rec["value"])
    bank.add({
        "suite": "select_k_strategy",
        "case": f"pqint8_winner_{nq_i}x{n_i}x{d_i}_k{k_i}",
        "winner": best[0], "value": best[1], "unit": "qps",
    })
    int_winners["pq_int8"] = (best[0], timings)

    rb_idx = ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=nl_i, kmeans_n_iters=4), data_i)
    rb_entries = {
        "bitplane_fused": ivf_rabitq.SearchParams(
            n_probes=probes_i, scan_engine="fused"),
        "bitplane_xla": ivf_rabitq.SearchParams(
            n_probes=probes_i, scan_engine="xla"),
    }
    best = None
    timings = {}
    for name, sp in rb_entries.items():
        bank.check_transport()
        rec = run_case(
            "select_k_strategy",
            f"rabitq_{name}_{nq_i}x{n_i}x{d_i}_k{k_i}",
            lambda sp=sp: ivf_rabitq.search(sp, rb_idx, q_i, k_i),
            items=float(nq_i),
            unit="qps",
        )
        bank.record["rows"].append(rec)
        bank.flush()
        timings[name] = rec["value"]
        if best is None or rec["value"] > best[1]:
            best = (name, rec["value"])
    bank.add({
        "suite": "select_k_strategy",
        "case": f"rabitq_winner_{nq_i}x{n_i}x{d_i}_k{k_i}",
        "winner": best[0], "value": best[1], "unit": "qps",
    })
    int_winners["rabitq_bitplane"] = (best[0], timings)
    return winners, scan_winners, int_winners


def apply_winners(winners: dict, scan_winners: dict = None,
                  int_winners: dict = None, smoke: bool = False) -> None:
    """Turn the per-shape race results into tuned defaults (merge
    semantics). The chunked-dispatch threshold comes from the DIRECT
    topk-vs-twophase timings — the overall shape winner can be a third
    strategy, which would otherwise mask where the crossover sits: the
    smallest length where twophase beat topk head-to-head at every shape
    of that length, provided topk did not beat twophase at any longer
    length (a non-monotone grid means there is no clean crossover to
    encode). Counting winning EVERY shape it actually raced in promotes
    it as the auto strategy (it is exact, so the flip is purely
    performance). Refused for smoke/CPU runs: those measurements reflect
    interpret-mode/host behavior, not the chip the defaults serve."""
    from raft_tpu.core import tuned

    if smoke or jax.default_backend() == "cpu":
        print(json.dumps({"applied": None,
                          "detail": "smoke/CPU run; tuned file left untouched"}))
        return
    updates = {"hints": {
        f"select_k_{b}x{l}_k{k}": w for (b, l, k), (w, _, _) in winners.items()
    }}
    pair_verdicts = {}  # length -> [twophase beat topk, per shape]
    for (b, l, k), (_, _, timings) in winners.items():
        if "topk" in timings and "twophase" in timings:
            pair_verdicts.setdefault(l, []).append(
                timings["twophase"] > timings["topk"]
            )
    twophase_lens = sorted(l for l, f in pair_verdicts.items() if all(f))
    topk_lens = [l for l, f in pair_verdicts.items() if not all(f)]
    if twophase_lens and not any(l > twophase_lens[0] for l in topk_lens):
        updates["select_k_chunk_threshold"] = max(1024, twophase_lens[0] - 1)
    entered = {(b, l, k): w for (b, l, k), (w, raced, _) in winners.items()
               if "counting" in raced}
    if entered and all(w == "counting" for w in entered.values()):
        updates["select_k_auto_strategy"] = "counting"
    # the fused scan winning EVERY operand-level shape it entered
    # promotes it as the tuned select_k_strategy (matrix.scan_select_k
    # auto + knn/refine/ivf auto engines all consult this one key); it
    # ranks bf16-rounded operands, the same measured-acceptable class as
    # the bf16 matmul flip, so a clean sweep on chip data flips it
    if scan_winners:
        updates["hints"] = {**updates.get("hints", {}), **{
            f"scan_select_k_{nq}x{n}x{d}_k{k}": w
            for (nq, n, d, k), (w, _) in scan_winners.items()
        }}
        if all(w == "fused" for w, _ in scan_winners.values()):
            updates["select_k_strategy"] = "fused"
    # the integer-scan keys (ISSUE 11): each flips INDEPENDENTLY on its
    # own race — the int8 trim and the bit-plane scan serve different
    # engines, so one losing must not block the other's measured win.
    # Chip data only (the smoke/CPU refusal above covers both).
    if int_winners:
        updates["hints"] = {**updates.get("hints", {}), **{
            f"int_scan_{kind}": w for kind, (w, _) in int_winners.items()
        }}
        if int_winners.get("pq_int8", (None,))[0] == "int8_fused":
            updates["select_k_strategy_int8"] = "fused_int8"
        if int_winners.get("rabitq_bitplane", (None,))[0] == "bitplane_fused":
            updates["select_k_strategy_bitplane"] = "fused_bitplane"
    tuned.merge(updates)
    print(json.dumps({"applied": tuned.path(),
                      "keys": [k for k in updates if k != "hints"]}))


if __name__ == "__main__":
    smoke = "--smoke" in sys.argv
    w, sw, iw = main(smoke=smoke)
    if "--apply" in sys.argv:
        apply_winners(w or {}, sw or {}, iw or {}, smoke=smoke)
