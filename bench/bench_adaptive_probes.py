"""Adaptive-probing frontier bench (ISSUE 12, ROADMAP item 2).

Banks the recall-vs-scanned-lists frontier of per-query probe budgets
(neighbors/probe_budget) against the fixed-`n_probes` reference, per
engine (ivf_flat + ivf_pq), to BENCH_adaptive.json + the ledger:

  - a `fixed` baseline row (recall vs brute-force ground truth at the
    full probe count, scanned_frac 1.0),
  - one row per tau on the ladder (recall + ACTUAL scanned-list
    fraction from the plan, with early-termination bounds engaged),
  - a `frontier` row: the smallest tau whose recall is within 0.002 of
    the fixed baseline, with `meets_criteria` asserting the acceptance
    bar (<= 60% of the lists scanned at that recall).

--apply banks the measured calibration into the tuned store
(`adaptive_probe_policy`: recall -> tau targets + the frontier tau as
default), closing the measure->flip loop the serve layer's per-request
`recall_target` resolution rides. Smoke runs never --apply and never
clobber a chip-banked results file (the Banker .cpu diversion).

Usage: python bench/bench_adaptive_probes.py [--smoke] [--apply]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from common import Banker, ensure_survivable_backend, run_case  # noqa: E402

TAU_LADDER = (0.25, 0.35, 0.45, 0.6, 0.8)


def _recall(ids: np.ndarray, exact: np.ndarray) -> float:
    k = exact.shape[1]
    return float(np.mean([
        len(set(a.tolist()) & set(b.tolist())) / k
        for a, b in zip(ids, exact)]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--n-lists", type=int, default=256)
    ap.add_argument("--n-probes", type=int, default=32)
    ap.add_argument("--queries", type=int, default=1024)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--apply", action="store_true",
                    help="bank the measured recall->tau calibration "
                         "into tuned_defaults.json (adaptive_probe_policy)")
    args = ap.parse_args()
    if args.smoke:
        args.rows, args.n_lists, args.n_probes, args.queries = \
            20_000, 64, 16, 256

    # dead-relay discipline: pin CPU in-process and bank honestly-tagged
    # rows to the REAL file; smoke rehearsals keep the .cpu diversion
    fallback = ensure_survivable_backend()
    if args.smoke:
        fallback = None

    from raft_tpu.neighbors import (
        brute_force, ivf_flat, ivf_pq, probe_budget,
    )
    from raft_tpu.random import make_blobs

    out_dir = os.environ.get("RAFT_TPU_BENCH_OUT", "").strip() or \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bank = Banker(
        os.path.join(out_dir, "BENCH_adaptive.json"),
        meta={"dataset_rows": args.rows, "dim": args.dim,
              "n_lists": args.n_lists, "n_probes": args.n_probes,
              "queries": args.queries, "k": args.k,
              "smoke": bool(args.smoke)},
        fallback=fallback,
    )

    # clustered data with overlap: the regime adaptive budgets exist
    # for — easy queries sit deep inside a cluster, hard ones between
    data, _ = make_blobs(args.rows, args.dim,
                         n_clusters=max(args.n_lists // 2, 8),
                         cluster_std=3.0, seed=11)
    data = np.asarray(data, np.float32)
    rng = np.random.default_rng(3)
    q = data[rng.choice(args.rows, args.queries, replace=False)]
    _, exact = brute_force.knn(data, q, args.k)
    exact = np.asarray(exact)
    bank.check_transport()

    calib = {}
    for engine, build, search in (
        ("ivf_flat",
         lambda: ivf_flat.build(
             ivf_flat.IndexParams(n_lists=args.n_lists, kmeans_n_iters=10),
             data, seed=0),
         lambda idx, **kw: ivf_flat.search(
             ivf_flat.SearchParams(n_probes=args.n_probes, **kw),
             idx, q, args.k)),
        ("ivf_pq",
         lambda: ivf_pq.build(
             ivf_pq.IndexParams(n_lists=args.n_lists,
                                pq_dim=max(args.dim // 4, 8),
                                kmeans_n_iters=10), data, seed=0),
         lambda idx, **kw: ivf_pq.search(
             ivf_pq.SearchParams(n_probes=args.n_probes,
                                 score_mode="recon8_list", **kw),
             idx, q, args.k)),
    ):
        idx = build()
        bank.check_transport()
        n_probes = min(args.n_probes, idx.n_lists)

        fv, fi = search(idx)
        fixed_recall = _recall(np.asarray(fi), exact)
        row = run_case("adaptive_probes", f"{engine}_fixed",
                       lambda: search(idx)[0],
                       iters=3, warmup=1, items=args.queries, unit="qps")
        bank.add({"stage": f"{engine}_fixed", "engine": engine,
                  "recall": round(fixed_recall, 4), "scanned_frac": 1.0,
                  "qps": row["value"]})

        frontier = None
        for tau in TAU_LADDER:
            _, scanned = probe_budget.probe_plan(
                q, idx.centers, n_probes=n_probes, min_probes=1,
                k=args.k, metric=idx.metric, tau=tau,
                rotation=getattr(idx, "rotation", None),
                radii=idx.list_radii, sizes=idx.list_sizes)
            frac = float(np.asarray(scanned).sum()) / (args.queries
                                                       * n_probes)
            _, ai = search(idx, budget_tau=tau, early_term=True)
            rec = _recall(np.asarray(ai), exact)
            bank.add({"stage": f"{engine}_tau{tau}", "engine": engine,
                      "tau": tau, "recall": round(rec, 4),
                      "scanned_frac": round(frac, 4)})
            calib.setdefault(engine, []).append((rec, tau))
            if frontier is None and rec >= fixed_recall - 0.002:
                frontier = (tau, rec, frac)
            bank.check_transport()

        if frontier is None:
            frontier = (1.0, fixed_recall, 1.0)
        tau, rec, frac = frontier
        bank.add({"stage": f"{engine}_frontier", "engine": engine,
                  "tau": tau, "recall": round(rec, 4),
                  "fixed_recall": round(fixed_recall, 4),
                  "scanned_frac": round(frac, 4),
                  # the ISSUE 12 acceptance bar: fixed recall within
                  # 0.002 at <= 60% of the worst-case scanned lists
                  "meets_criteria": bool(rec >= fixed_recall - 0.002
                                         and frac <= 0.6)})

    if args.apply:
        # measured recall -> tau calibration: per tau keep the WORST
        # engine's recall (a target must hold across engines), then
        # make the table monotone so resolve_tau's first-cover pick is
        # well defined
        from raft_tpu.core import tuned

        by_tau = {}
        for pairs in calib.values():
            for rec, tau in pairs:
                by_tau[tau] = min(by_tau.get(tau, 1.0), rec)
        targets = sorted(
            ([round(r, 4), t] for t, r in by_tau.items()),
            key=lambda e: e[0])
        policy = {"default_tau": float(min(
            (t for r, t in targets if r >= 0.95), default=0.6)),
            "targets": targets}
        tuned.merge({probe_budget.POLICY_KEY: policy})
        bank.set("applied_policy", policy)
        print(f"applied adaptive_probe_policy -> {tuned.path()}")


if __name__ == "__main__":
    main()
