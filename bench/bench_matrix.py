"""select_k / argmin / gather benches (reference cpp/bench/matrix/
{select_k,argmin,gather}.cu). Shape grid follows the reference's
(batch, len, k) cases including the radix-vs-warpsort crossover region."""

import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax.numpy as jnp

from common import run_case
from raft_tpu import matrix


def main():
    rng = np.random.default_rng(0)
    for batch, length, k in [
        (64, 1 << 14, 64),
        (64, 1 << 17, 128),
        (128, 1 << 20, 256),
        (1024, 1 << 14, 64),
    ]:
        vals = jnp.asarray(rng.random((batch, length), dtype=np.float32))
        run_case(
            "matrix",
            f"select_k_{batch}x{length}_k{k}",
            lambda v=vals, k=k: matrix.select_k(v, k),
            items=float(batch * length),
            unit="elems/s",
        )
    a = jnp.asarray(rng.random((8192, 1024), dtype=np.float32))
    run_case("matrix", "argmin_8192x1024", lambda: matrix.argmin(a))
    idx = jnp.asarray(rng.integers(0, 8192, 4096, dtype=np.int32))
    run_case("matrix", "gather_4096_of_8192x1024", lambda: matrix.gather(a, idx))


if __name__ == "__main__":
    main()
