"""Quantized-collectives bench (comms/quantized; ROADMAP open item 3,
EQuARX arxiv 2506.17615).

Banks to BENCH_qcomms.json + the hermetic ledger:

  - wire rows: `comms.<op>.wire_bytes` (obs counters — ACTUAL bytes the
    transport charges, int8 payload + f32 scale sidecars) for exact vs
    int8 vs bf16 allreduce/allgather and the candidate exchange vs the
    exact packed-plane merge — the >=2x wire-reduction acceptance
    evidence,
  - recall rows: quantized candidate exchange + distributed knn vs the
    exact path (the 1e-3 recall-parity gate),
  - a mode x block latency race over allreduce + the search merge at a
    serving shape, recall-gated (a mode that trades recall past 1e-3
    can never be crowned).

`--apply` banks the race winner into tuned keys `comms_quant_mode` /
`comms_quant_block`, tagged with the `comms_quant_measured_on` backend
hint so only the measured backend's "auto" dispatch flips (the
merge-schedule rule). CPU runs never write the tuned keys — the cpu
race informs the default, not the key.

Usage: python bench/bench_qcomms.py [--smoke] [--apply]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# standalone CPU runs need the virtual mesh armed BEFORE jax imports
# (under pytest, conftest does this; a chip run leaves the env alone)
if (os.environ.get("JAX_PLATFORMS", "").strip().lower().startswith("cpu")
        and "XLA_FLAGS" not in os.environ):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from common import Banker, ensure_survivable_backend, run_case  # noqa: E402


def _recall(ids: np.ndarray, exact: np.ndarray) -> float:
    k = exact.shape[1]
    return float(np.mean([
        len(set(a.tolist()) & set(b.tolist())) / k
        for a, b in zip(np.asarray(ids), np.asarray(exact))]))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--elems", type=int, default=1 << 16,
                    help="per-rank allreduce/allgather payload values")
    ap.add_argument("--rows", type=int, default=20_000)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--queries", type=int, default=128)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--apply", action="store_true",
                    help="write the recall-gated race winner to tuned "
                         "keys comms_quant_mode/comms_quant_block "
                         "(backend-tagged)")
    args = ap.parse_args()
    if args.smoke:
        args.elems, args.rows, args.queries = 8192, 4000, 64

    # dead-relay discipline: bail in milliseconds instead of hanging
    from raft_tpu.core.config import chip_probe_would_hang

    if chip_probe_would_hang():
        print(json.dumps({"suite": "qcomms",
                          "aborted": "relay transport dead"}), flush=True)
        sys.exit(3)
    fallback = ensure_survivable_backend()
    if args.smoke:
        fallback = None

    import jax
    from jax.sharding import PartitionSpec as P

    from raft_tpu import obs
    from raft_tpu.comms import Comms, mnmg, quantized
    from raft_tpu.comms.comms import op_t
    from raft_tpu.comms.mnmg_merge import _merge_local_topk_allgather
    from raft_tpu.comms.quantized import QuantConfig
    from raft_tpu.neighbors import brute_force
    from raft_tpu.random import make_blobs

    comms = Comms()
    world = comms.get_size()
    if world < 2:
        print(json.dumps({"suite": "qcomms", "skipped": "world=1"}),
              flush=True)
        sys.exit(0)
    ac = comms.comms

    out_dir = os.environ.get("RAFT_TPU_BENCH_OUT", "").strip() or \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bank = Banker(
        os.path.join(out_dir, "BENCH_qcomms.json"),
        meta={"world": world, "elems": args.elems, "dataset_rows": args.rows,
              "dim": args.dim, "queries": args.queries, "k": args.k,
              "smoke": bool(args.smoke)},
        fallback=fallback,
    )
    bank.check_transport()

    rng = np.random.default_rng(0)
    modes = {"off": None,
             "int8": QuantConfig(mode="int8", block=quantized.DEFAULT_BLOCK),
             "bf16": QuantConfig(mode="bf16")}

    # -- wire rows: counter-audited bytes per mode ----------------------
    was_enabled = obs.enabled()
    obs.enable()
    x = rng.standard_normal((world, args.elems)).astype(np.float32)

    def traced(op_name, cfg):
        if op_name == "allreduce":
            body = lambda xs: ac.allreduce(  # noqa: E731
                xs[0], op_t.SUM, quantization=cfg)[None]
        else:
            body = lambda xs: ac.allgather(  # noqa: E731
                xs[0], quantization=cfg)[None]
        jax.shard_map(body, mesh=comms.mesh, in_specs=P("data"),
                      out_specs=P("data"), check_vma=False)(x)

    for op_name in ("allreduce", "allgather"):
        wire = {}
        for mode, cfg in modes.items():
            obs.reset()
            traced(op_name, cfg)
            wire[mode] = obs.registry().counter(
                f"comms.{op_name}.wire_bytes").value
        for mode in modes:
            bank.add({"stage": f"{op_name}_wire", "mode": mode,
                      "wire_bytes": int(wire[mode]),
                      "reduction_x": round(wire["off"]
                                           / max(1, wire[mode]), 2)})

    # -- candidate exchange: wire + recall ------------------------------
    nq, kk = args.queries, 32
    v = np.sort(rng.uniform(0.0, 100.0, (world, nq, kk)), axis=2)
    v = v.astype(np.float32)
    ids = rng.permutation(world * nq * kk).reshape(
        world, nq, kk).astype(np.int32)

    def run_merge(cfg):
        def body(vs, is_):
            if cfg is None:
                rv, rid = _merge_local_topk_allgather(
                    ac, vs[0], is_[0], args.k, True)
            else:
                rv, rid = quantized.exchange_candidates(
                    ac, vs[0], is_[0], args.k, True, cfg)
            return rv[None], rid[None]

        return jax.shard_map(
            body, mesh=comms.mesh, in_specs=(P("data"), P("data")),
            out_specs=(P("data"), P("data")), check_vma=False)(v, ids)

    xwire, xids = {}, {}
    for mode, cfg in modes.items():
        obs.reset()
        _, rid = run_merge(cfg)
        xwire[mode] = (obs.registry().counter("comms.allreduce.wire_bytes")
                       .value
                       + obs.registry().counter("comms.allgather.wire_bytes")
                       .value)
        xids[mode] = np.asarray(rid)[0]
    for mode in modes:
        bank.add({"stage": "exchange_wire", "mode": mode,
                  "wire_bytes": int(xwire[mode]),
                  "reduction_x": round(xwire["off"] / max(1, xwire[mode]),
                                       2),
                  "recall_vs_exact":
                      round(_recall(xids[mode], xids["off"]), 4)})
    if not was_enabled:
        obs.disable()
        obs.reset()
    bank.check_transport()

    # -- distributed knn recall parity ----------------------------------
    data, _ = make_blobs(args.rows, args.dim, n_clusters=16,
                         cluster_std=2.0, seed=11)
    data = np.asarray(data, np.float32)
    q = data[rng.choice(args.rows, min(args.queries, args.rows),
                        replace=False)]
    _, exact = brute_force.knn(data, q, args.k)
    _, oi = mnmg.knn(comms, data, q, args.k, quantization="off")
    base_recall = _recall(oi, exact)
    for mode in ("int8", "bf16"):
        _, qi = mnmg.knn(comms, data, q, args.k, quantization=mode)
        bank.add({"stage": "knn_recall", "mode": mode,
                  "recall_vs_exact_path": round(_recall(qi, oi), 4),
                  "recall_vs_truth": round(_recall(qi, exact), 4),
                  "exact_path_recall": round(base_recall, 4)})
    bank.check_transport()

    # -- mode x block latency race (recall-gated) -----------------------
    race = []
    vsh, ish = comms.shard(v.reshape(-1, kk)), comms.shard(
        ids.reshape(-1, kk))
    for mode in ("off", "int8", "bf16"):
        for block in (quantized.BLOCK_CHOICES if mode == "int8" else (0,)):
            cfg = (None if mode == "off"
                   else QuantConfig(mode=mode,
                                    block=block or quantized.DEFAULT_BLOCK))

            def ar_body(xs, cfg=cfg):
                return ac.allreduce(xs[0], op_t.SUM, quantization=cfg)[None]

            f_ar = jax.jit(lambda xs, b=ar_body: jax.shard_map(
                b, mesh=comms.mesh, in_specs=P("data"),
                out_specs=P("data"), check_vma=False)(xs))
            xsh = comms.shard(x)

            def mg_body(vs, is_, cfg=cfg):
                if cfg is None:
                    rv, rid = _merge_local_topk_allgather(
                        ac, vs, is_, args.k, True)
                else:
                    rv, rid = quantized.exchange_candidates(
                        ac, vs, is_, args.k, True, cfg)
                return rv, rid

            f_mg = jax.jit(lambda a, b, m=mg_body: jax.shard_map(
                m, mesh=comms.mesh, in_specs=(P("data"), P("data")),
                out_specs=(P("data"), P("data")), check_vma=False)(a, b))
            tag = f"{mode}_b{block}_w{world}" if block else \
                f"{mode}_w{world}"
            r1 = run_case("qcomms", f"allreduce_{tag}",
                          lambda: f_ar(xsh), iters=3, warmup=1,
                          items=float(world * args.elems), unit="elems/s")
            r2 = run_case("qcomms", f"merge_{tag}",
                          lambda: f_mg(vsh, ish), iters=3, warmup=1,
                          items=float(nq), unit="q/s")
            rec = _recall(np.asarray(f_mg(vsh, ish)[1])[:nq],
                          xids["off"]) if mode != "off" else 1.0
            race.append({"mode": mode, "block": block, "ms":
                         r1["ms"] + r2["ms"],
                         "recall_ok": bool(rec >= 1.0 - 1e-3)})
    eligible = [r for r in race if r["recall_ok"]]
    winner = min(eligible, key=lambda r: r["ms"]) if eligible else None
    bank.add({"stage": "race_winner",
              "mode": winner["mode"] if winner else None,
              "block": winner["block"] if winner else None,
              "eligible": len(eligible), "raced": len(race)})
    return winner


def _apply(winner) -> None:
    import jax

    from raft_tpu.core import tuned

    if jax.default_backend() == "cpu":
        # every backend's "auto" reads these keys, but the winner is
        # backend-dependent (ICI bandwidth vs memcpy mesh) — same rule
        # as the merge-schedule key
        print(json.dumps({"applied": None,
                          "detail": "cpu race informs the default, not "
                                    "the tuned key; run on the chip"}))
        return
    if winner is None or winner["mode"] == "off":
        # "off" winning means quantization loses on this backend — bank
        # the explicit off so "auto" stays exact even if a stale winner
        # was banked earlier
        applied = {"comms_quant_mode": "off"}
    else:
        applied = {"comms_quant_mode": winner["mode"]}
        if winner["block"]:
            applied["comms_quant_block"] = int(winner["block"])
    tuned.merge(dict(
        applied,
        hints={"comms_quant_measured_on": jax.default_backend()}))
    print(json.dumps({"applied": applied}))


if __name__ == "__main__":
    w = main()
    if "--apply" in sys.argv:
        _apply(w)
