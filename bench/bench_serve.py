"""Serving-engine bench: QPS and p50/p99 latency at a fixed recall
target, banked to BENCH_serve.json so later serving/perf PRs have a
trajectory to beat.

Protocol: build an IVF-Flat index, pick the smallest n_probes whose
offline recall@k (vs brute force, same data) clears `--recall`, then
drive a `SearchServer` with concurrent client threads issuing small
(1..8 row) requests — the online traffic shape micro-batching exists
for. Reported QPS/latency come from the server's own `ServerMetrics`
ring (the numbers an operator would scrape), plus a sequential
UNBATCHED baseline of the same request stream for the speedup column.

Runs anywhere (CPU rehearsal banks to BENCH_serve.json.cpu; a chip run
writes the real file — bench/common.Banker's discipline).

Usage: python bench/bench_serve.py [--smoke] [--rows N] [--clients T]
"""

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import Banker, ensure_survivable_backend


def pick_n_probes(dataset, queries, k, params_cls, search, build_idx,
                  target_recall, ladder=(1, 2, 4, 8, 16, 32)):
    """Smallest n_probes from `ladder` whose recall@k vs brute force
    clears `target_recall` (falls back to the ladder max)."""
    from raft_tpu.neighbors import brute_force

    _, exact = brute_force.knn(dataset, queries, k)
    exact = np.asarray(exact)
    for n_probes in ladder:
        _, got = search(params_cls(n_probes=n_probes, engine="query"),
                        build_idx, queries, k)
        got = np.asarray(got)
        recall = float(np.mean([
            len(set(got[i]) & set(exact[i])) / k for i in range(len(exact))
        ]))
        if recall >= target_recall:
            return n_probes, recall
    return ladder[-1], recall


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--n-lists", type=int, default=128)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--requests", type=int, default=250,
                    help="requests per client thread")
    ap.add_argument("--recall", type=float, default=0.95)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.smoke:
        args.rows, args.n_lists, args.clients, args.requests = 8_000, 32, 4, 50

    # BEFORE any device op (ROADMAP 5a): a dead relay pins CPU
    # in-process and the rows bank to the REAL file, honestly tagged —
    # never recycled, never hung. Smoke rehearsals keep the .cpu
    # diversion (same contract as bench_ivf_rabitq.py).
    fallback = ensure_survivable_backend()
    if args.smoke:
        fallback = None

    from raft_tpu import serve
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.random import make_blobs

    bank = Banker(
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "BENCH_serve.json"),
        meta={"dataset_rows": args.rows, "dim": args.dim, "n_lists": args.n_lists,
              "k": args.k, "clients": args.clients,
              "requests_per_client": args.requests,
              "recall_target": args.recall},
        fallback=fallback,
    )

    data, _ = make_blobs(args.rows, args.dim, n_clusters=max(8, args.n_lists),
                         cluster_std=0.6, seed=5)
    data = np.asarray(data, np.float32)
    rng = np.random.default_rng(11)
    probe_q = data[rng.integers(0, args.rows, 256)] + rng.standard_normal(
        (256, args.dim)).astype(np.float32) * 0.01

    idx = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=args.n_lists, kmeans_n_iters=5), data)
    bank.check_transport()
    n_probes, recall = pick_n_probes(
        data, probe_q, args.k, ivf_flat.SearchParams, ivf_flat.search, idx,
        args.recall)
    bank.set("n_probes", n_probes)
    bank.set("recall_at_k", round(recall, 4))

    sp = ivf_flat.SearchParams(n_probes=n_probes, engine="query")
    total = args.clients * args.requests
    sizes = rng.integers(1, 9, total)  # 1..8 rows per request
    reqs = [probe_q[rng.integers(0, 256, int(n))] for n in sizes]

    # -- unbatched baseline: the same stream served one call at a time
    bank.check_transport()
    import jax

    # warm every request shape (1..8 rows) so the baseline measures
    # steady-state latency, not XLA compiles — the server side likewise
    # pre-compiles its buckets via warmup_k
    for n in sorted({int(n) for n in sizes}):
        jax.block_until_ready(ivf_flat.search(sp, idx, probe_q[:n], args.k))
    lats = []
    base_n = min(total, 200)
    t0 = time.perf_counter()
    for q in reqs[:base_n]:
        t1 = time.perf_counter()
        jax.block_until_ready(ivf_flat.search(sp, idx, q, args.k))
        lats.append(time.perf_counter() - t1)
    base_wall = time.perf_counter() - t0
    bank.add({"suite": "serve", "case": "unbatched_baseline",
              "value": round(base_n / base_wall, 1), "unit": "req/s",
              "p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 3),
              "p99_ms": round(float(np.percentile(lats, 99)) * 1e3, 3)})

    # -- the server, driven by concurrent clients
    bank.check_transport()
    cfg = serve.ServerConfig(buckets=(16, 64, 256), max_wait_ms=1.0,
                             warmup_k=args.k)
    with serve.SearchServer(idx, cfg, search_params=sp) as server:
        t0 = time.perf_counter()

        def client(lo):
            for i in range(lo, lo + args.requests):
                server.submit(reqs[i], args.k).result(timeout=300.0)

        threads = [threading.Thread(target=client, args=(c * args.requests,))
                   for c in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        snap = server.metrics.snapshot()

    from raft_tpu.obs import slo as _slo

    row = {"suite": "serve", "case": "server",
           "value": round(snap["qps"], 1), "unit": "req/s",
           "wall_req_s": round(total / wall, 1),
           "p50_ms": round(snap["latency_ms_p50"], 3),
           "p99_ms": round(snap["latency_ms_p99"], 3),
           "batch_occupancy": round(snap["batch_occupancy"], 4),
           "requests_per_batch": round(snap["requests_per_batch"], 2),
           "batches": snap["batches"]}
    # the SLO verdict rides the row (obs.slo.judge_serve): perfgate's
    # trajectory gets a pass/fail signal beyond the medians
    row.update(_slo.judge_serve(snap))
    bank.add(row)
    bank.set("speedup_vs_unbatched",
             round((total / wall) / (base_n / base_wall), 2))
    print(f"banked -> {bank.path}")


if __name__ == "__main__":
    main()
