"""Pairwise-distance benches (reference cpp/bench/distance/distance_*.cu,
fused_l2_nn.cu, kernels.cu). Cases follow the reference's shape grid."""

import sys, os

sys.path.insert(0, os.path.dirname(__file__))

import numpy as np
import jax
import jax.numpy as jnp

from common import run_case
from raft_tpu.distance import pairwise_distance
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin
from raft_tpu.distance.kernels import gram_matrix, KernelParams, KernelType


def main():
    rng = np.random.default_rng(0)
    for m, n, d in [(1024, 1024, 64), (8192, 8192, 128), (16384, 16384, 256)]:
        x = jnp.asarray(rng.random((m, d), dtype=np.float32))
        y = jnp.asarray(rng.random((n, d), dtype=np.float32))
        flops = 2.0 * m * n * d
        for metric in (DistanceType.L2Expanded, DistanceType.CosineExpanded, DistanceType.L1):
            run_case(
                "distance",
                f"{metric.name}_{m}x{n}x{d}",
                lambda x=x, y=y, metric=metric: pairwise_distance(x, y, metric=metric),
                items=flops / 1e9,
                unit="GFLOP/s",
            )
    # fused L2 argmin (k-means inner loop shape: n rows vs k centers)
    for n, k, d in [(100_000, 1024, 96), (1_000_000, 1024, 96)]:
        x = jnp.asarray(rng.random((n, d), dtype=np.float32))
        c = jnp.asarray(rng.random((k, d), dtype=np.float32))
        run_case(
            "distance",
            f"fused_l2_nn_{n}x{k}x{d}",
            lambda x=x, c=c: fused_l2_nn_argmin(x, c),
            items=float(n),
            unit="rows/s",
        )
    # gram kernels (cpp/bench/distance/kernels.cu)
    x = jnp.asarray(rng.random((4096, 128), dtype=np.float32))
    for kind in (KernelType.LINEAR, KernelType.POLYNOMIAL, KernelType.RBF, KernelType.TANH):
        run_case(
            "distance",
            f"gram_{kind.name.lower()}_4096x128",
            lambda x=x, kind=kind: gram_matrix(x, x, KernelParams(kernel=kind)),
        )


if __name__ == "__main__":
    main()
