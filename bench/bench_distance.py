"""Pairwise-distance benches (reference cpp/bench/distance/distance_*.cu,
fused_l2_nn.cu, kernels.cu). Cases follow the reference's shape grid."""

import json
import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from common import run_case
from raft_tpu.distance import pairwise_distance
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin
from raft_tpu.distance.kernels import gram_matrix, KernelParams, KernelType
from raft_tpu.random import make_blobs


# v5e MXU peak (per chip): 197 TFLOP/s bf16. MFU here is against that
# peak; the library's f32 default (lax.Precision.HIGHEST, ~6 bf16 passes)
# caps useful-FLOP MFU near 1/6, so each shape also runs a bf16-input
# variant showing the achievable rate (BASELINE.md: pairwise TFLOPS/chip).
_V5E_BF16_PEAK_TFLOPS = 197.0


def main():
    rng = np.random.default_rng(0)
    for m, n, d in [(1024, 1024, 64), (8192, 8192, 128), (16384, 16384, 256),
                    (16384, 16384, 768)]:
        xf = rng.random((m, d), dtype=np.float32)
        yf = rng.random((n, d), dtype=np.float32)
        flops = 2.0 * m * n * d
        for dtype, tag in ((jnp.float32, "f32"), (jnp.bfloat16, "bf16")):
            x = jnp.asarray(xf).astype(dtype)
            y = jnp.asarray(yf).astype(dtype)
            for metric in (DistanceType.L2Expanded, DistanceType.CosineExpanded,
                           DistanceType.L1):
                if metric == DistanceType.L1 and tag == "bf16":
                    continue  # unexpanded path; bf16 variant adds nothing
                rec = run_case(
                    "distance",
                    f"{metric.name}_{tag}_{m}x{n}x{d}",
                    lambda x=x, y=y, metric=metric: pairwise_distance(x, y, metric=metric),
                    items=flops / 1e9,
                    unit="GFLOP/s",
                )
                tflops = rec["value"] / 1e3
                print(json.dumps({
                    "suite": "distance",
                    "case": f"{metric.name}_{tag}_{m}x{n}x{d}_mfu",
                    "value": round(tflops, 2),
                    "unit": "TFLOP/s",
                    "mfu_vs_v5e_bf16_peak": round(tflops / _V5E_BF16_PEAK_TFLOPS, 4),
                }), flush=True)
    # BASELINE config 1: pairwise L2SqrtExpanded on make_blobs 5000x50
    # (the pylibraft-parity reference case)
    blobs, _ = make_blobs(5000, 50, n_clusters=5, seed=0)
    run_case(
        "distance",
        "L2SqrtExpanded_blobs_5000x50",
        lambda b=blobs: pairwise_distance(b, b, metric=DistanceType.L2SqrtExpanded),
        items=float(5000 * 5000),
        unit="pairs/s",
    )

    # fused L2 argmin (k-means inner loop shape: n rows vs k centers)
    for n, k, d in [(100_000, 1024, 96), (1_000_000, 1024, 96)]:
        x = jnp.asarray(rng.random((n, d), dtype=np.float32))
        c = jnp.asarray(rng.random((k, d), dtype=np.float32))
        run_case(
            "distance",
            f"fused_l2_nn_{n}x{k}x{d}",
            lambda x=x, c=c: fused_l2_nn_argmin(x, c),
            items=float(n),
            unit="rows/s",
        )
    # gram kernels (cpp/bench/distance/kernels.cu)
    x = jnp.asarray(rng.random((4096, 128), dtype=np.float32))
    for kind in (KernelType.LINEAR, KernelType.POLYNOMIAL, KernelType.RBF, KernelType.TANH):
        run_case(
            "distance",
            f"gram_{kind.name.lower()}_4096x128",
            lambda x=x, kind=kind: gram_matrix(x, x, KernelParams(kernel=kind)),
        )


if __name__ == "__main__":
    main()
