#!/bin/bash
# Resume wrapper for run_onchip_queue.sh after the 2026-08-01 mid-queue
# process-tree loss: the critical profile ladder + apply-hints already
# banked (TPU_PROFILE_RESULTS.json, tuned_defaults.json), so resume from
# the headline bench (re-run under the BF-racer bench.py) and continue
# with the original ordering. Same rules: one chip client, no kills.
set -u
cd "$(dirname "$0")/.."
LOG=${ONCHIP_LOG:-/tmp/onchip_queue.log}
exec >>"$LOG" 2>&1
echo "=== on-chip queue RESUME start $(date -u +%FT%TZ) ==="
touch /tmp/onchip_queue_ran
relay_check() {
  python -c "
import sys; sys.path.insert(0, '.')
try:
    from raft_tpu.core.config import relay_transport_down
    sys.exit(2 if relay_transport_down() else 0)
except SystemExit:
    raise
except Exception:
    sys.exit(0)
"
}
run_hostonly() {
  echo "--- $* ($(date -u +%T)) ---"
  "$@"
  echo "--- rc=$? ($(date -u +%T)) ---"
}
run() {
  relay_check
  if [ $? -eq 2 ]; then
    echo "--- relay transport dead; skipping $* ($(date -u +%T)) ---"
    return
  fi
  run_hostonly "$@"
}
run python bench.py
run bash -c 'set -o pipefail; RAFT_TPU_BENCH_FULL_LADDER=1 python bench.py | tail -1 > LADDER_VALIDATION.json'
run python bench/bench_diag.py
run python bench/bench_pallas_scan.py --apply
run python bench/bench_select_k_strategies.py --apply
run python bench/bench_comms.py --apply
run env RAFT_TPU_PROFILE_STAGE=tail python bench/tpu_profile.py
run_hostonly python bench/apply_profile_hints.py --apply
run python bench/bench_10m_build.py
run python bench/bench_mnmg_merge.py --apply
run python bench/run_all.py
echo "=== on-chip queue RESUME done $(date -u +%FT%TZ) ==="
