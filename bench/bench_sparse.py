"""sparse benches (reference cpp/bench/sparse/: convert, spmv-style ops,
sparse pairwise distance shapes)."""

import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import run_case
import jax.numpy as jnp

import raft_tpu.sparse as rsp


def main():
    rng = np.random.default_rng(0)
    n, d, density = 100_000, 256, 0.05
    dense = rng.random((n, d), dtype=np.float32)
    dense[dense > density] = 0.0
    nnz = int((dense != 0).sum())
    csr = rsp.dense_to_csr(dense)

    run_case("sparse", f"dense_to_csr_{n}x{d}",
             lambda: rsp.dense_to_csr(dense).data, items=float(n * d), unit="elems/s")
    run_case("sparse", f"csr_to_dense_{n}x{d}",
             lambda: rsp.csr_to_dense(csr), items=float(nnz), unit="nnz/s")
    v = jnp.asarray(rng.random((d,), dtype=np.float32))
    run_case("sparse", f"spmv_{n}x{d}_nnz{nnz}",
             lambda: rsp.linalg.spmv(csr, v), items=float(nnz), unit="nnz/s")
    run_case("sparse", f"transpose_{n}x{d}",
             lambda: rsp.linalg.transpose(csr).data, items=float(nnz), unit="nnz/s")

    qd = rng.random((512, d), dtype=np.float32)
    qd[qd > density] = 0.0
    q = rsp.dense_to_csr(qd)
    run_case("sparse", f"pairwise_l2_{n}x512x{d}",
             lambda: rsp.distance.pairwise_distance(q, csr, "sqeuclidean"),
             items=2.0 * n * 512 * d / 1e9, unit="GFLOP/s")
    run_case("sparse", f"knn_k10_{n}x512x{d}",
             lambda: rsp.distance.knn(csr, q, 10)[1], items=512.0, unit="queries/s")

    # truly-sparse regime (text-workload shape): 1M columns, ~8 nnz/row —
    # one densified block pair would be 32 GB, so this exercises the
    # compact-active-column path (sparse/distance.py
    # _pairwise_compact_columns; the coo_spmv-strategies analogue)
    nr, nc, nnz_row = 8192, 1_000_000, 8
    idx = rng.integers(0, nc, (nr, nnz_row), dtype=np.int64)
    idx.sort(axis=1)
    data = (rng.random((nr, nnz_row)).astype(np.float32) + 0.1).reshape(-1)
    indptr = np.arange(0, nr * nnz_row + 1, nnz_row, dtype=np.int64)
    from raft_tpu.sparse.formats import CsrMatrix

    wide_x = CsrMatrix(indptr, idx.reshape(-1), data, (nr, nc))
    yr = 512
    wide_y = CsrMatrix(indptr[: yr + 1], idx[:yr].reshape(-1),
                       data[: yr * nnz_row], (yr, nc))
    run_case("sparse", f"pairwise_compact_{nr}x{yr}x1M",
             lambda: rsp.distance.pairwise_distance(wide_x, wide_y,
                                                    "sqeuclidean"),
             items=float(nr * yr), unit="pairs/s")


if __name__ == "__main__":
    main()
