"""sparse benches (reference cpp/bench/sparse/: convert, spmv-style ops,
sparse pairwise distance shapes)."""

import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import run_case
import jax.numpy as jnp

import raft_tpu.sparse as rsp


def main():
    rng = np.random.default_rng(0)
    n, d, density = 100_000, 256, 0.05
    dense = rng.random((n, d), dtype=np.float32)
    dense[dense > density] = 0.0
    nnz = int((dense != 0).sum())
    csr = rsp.dense_to_csr(dense)

    run_case("sparse", f"dense_to_csr_{n}x{d}",
             lambda: rsp.dense_to_csr(dense).data, items=float(n * d), unit="elems/s")
    run_case("sparse", f"csr_to_dense_{n}x{d}",
             lambda: rsp.csr_to_dense(csr), items=float(nnz), unit="nnz/s")
    v = jnp.asarray(rng.random((d,), dtype=np.float32))
    run_case("sparse", f"spmv_{n}x{d}_nnz{nnz}",
             lambda: rsp.linalg.spmv(csr, v), items=float(nnz), unit="nnz/s")
    run_case("sparse", f"transpose_{n}x{d}",
             lambda: rsp.linalg.transpose(csr).data, items=float(nnz), unit="nnz/s")

    qd = rng.random((512, d), dtype=np.float32)
    qd[qd > density] = 0.0
    q = rsp.dense_to_csr(qd)
    run_case("sparse", f"pairwise_l2_{n}x512x{d}",
             lambda: rsp.distance.pairwise_distance(q, csr, "sqeuclidean"),
             items=2.0 * n * 512 * d / 1e9, unit="GFLOP/s")
    run_case("sparse", f"knn_k10_{n}x512x{d}",
             lambda: rsp.distance.knn(csr, q, 10)[1], items=512.0, unit="queries/s")


if __name__ == "__main__":
    main()
