"""Tiny in-process perf bench: the ledger's heartbeat.

Runs in seconds on any backend (CPU included) and banks a brute-force
kNN row and an IVF-PQ search row through `common.Banker` — which means
every run appends honestly-tagged rows (git SHA, platform, span phases
with cost-model MFU) to BENCH_LEDGER.jsonl. `ci/test.sh perf` points
RAFT_TPU_BENCH_LEDGER at a temp file and runs this, then gates the
fresh rows with `python -m tools.perfgate --json` — so every future PR
banks fresh numbers and sees drift the moment it lands, even when the
chip queue is down (ROADMAP item 5a).

The two cases run as stages of a `raft_tpu.jobs.Job` (ISSUE 8): each
stage is watchdog-supervised (a stalled case is killed as a typed
StageTimeout instead of hanging the session) and every run leaves a
job timeline on the obs bus. The JobDir is a fresh temp dir by default
— a heartbeat bench should re-measure every run, never skip — or
RAFT_TPU_JOB_DIR for a durable, resumable sweep.

Observability is force-enabled in-process: the whole point of these
rows is the per-phase attribution and MFU they carry.

Usage: python bench/bench_perf_smoke.py [--rows N] [--queries N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import Banker, ensure_survivable_backend, run_case


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--queries", type=int, default=256)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--n-lists", type=int, default=32)
    args = ap.parse_args()

    fallback = ensure_survivable_backend()

    from raft_tpu import obs
    from raft_tpu.neighbors import brute_force, ivf_pq, ivf_rabitq

    obs.enable()

    # RAFT_TPU_BENCH_OUT redirects the results file (hermetic CI/tests);
    # the ledger path has its own env override (RAFT_TPU_BENCH_LEDGER)
    out_dir = os.environ.get("RAFT_TPU_BENCH_OUT", "").strip() or \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bank = Banker(
        os.path.join(out_dir, "BENCH_perf_smoke.json"),
        meta={"dataset_rows": args.rows, "dim": args.dim,
              "queries": args.queries, "k": args.k, "n_lists": args.n_lists},
        fallback=fallback,
    )

    rng = np.random.default_rng(7)
    data = rng.random((args.rows, args.dim), dtype=np.float32)
    q = rng.random((args.queries, args.dim), dtype=np.float32)

    from common import job_dir_or_temp

    from raft_tpu import jobs

    # a wall-clock deadline, not a stall timeout: these stages run one
    # opaque compile+measure call and never beat the heartbeat, so a
    # stall knob would just be a mislabeled deadline
    deadline_s = float(
        os.environ.get("RAFT_TPU_PERF_SMOKE_DEADLINE_S", "600"))

    def bf_knn(ctx):
        rec = run_case(
            "perf_smoke",
            f"bf_knn_{args.rows}x{args.dim}_q{args.queries}_k{args.k}",
            lambda: brute_force.knn(data, q, k=args.k),
            iters=3, warmup=1, items=float(args.queries), unit="qps")
        bank.add(rec, echo=False)
        bank.check_transport()
        return {"qps": rec.get("value")}

    def bf_knn_fused(ctx):
        # the ISSUE 10 fused scan+select engine, raced in the same group
        # so the ledger carries fused-vs-baseline at every SHA (its span
        # cost charges the fused geometry: no score-matrix bytes)
        rec = run_case(
            "perf_smoke",
            f"bf_knn_fused_{args.rows}x{args.dim}_q{args.queries}_k{args.k}",
            lambda: brute_force.knn(data, q, k=args.k, engine="pallas"),
            iters=3, warmup=1, items=float(args.queries), unit="qps")
        bank.add(rec, echo=False)
        bank.check_transport()
        return {"qps": rec.get("value")}

    def pq_search(ctx):
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=args.n_lists, kmeans_n_iters=4,
                               pq_dim=args.dim // 2), data)
        sp = ivf_pq.SearchParams(n_probes=8)
        rec = run_case(
            "perf_smoke",
            f"ivf_pq_search_{args.rows}_q{args.queries}_k{args.k}_probes8",
            lambda: ivf_pq.search(sp, idx, q, args.k),
            iters=3, warmup=1, items=float(args.queries), unit="qps")
        bank.add(rec, echo=False)
        return {"qps": rec.get("value")}

    def pq_int8_fused(ctx):
        # the ISSUE 11 int8 fused trim, measured every session so the
        # first chip queue carries fused-vs-baseline at every SHA (the
        # span cost charges int8 MXU flops against the int8 peak, no
        # score-matrix bytes; honesty-tagged interpret rows on CPU are
        # expected to lose to XLA locally)
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=args.n_lists, kmeans_n_iters=4,
                               pq_dim=args.dim // 2), data)
        sp = ivf_pq.SearchParams(n_probes=8, trim_engine="fused",
                                 score_dtype="int8")
        rec = run_case(
            "perf_smoke",
            f"ivf_pq_int8_fused_{args.rows}_q{args.queries}_k{args.k}_probes8",
            lambda: ivf_pq.search(sp, idx, q, args.k),
            iters=3, warmup=1, items=float(args.queries), unit="qps")
        bank.add(rec, echo=False)
        bank.check_transport()
        return {"qps": rec.get("value")}

    def rabitq_bitplane_fused(ctx):
        # the fused RaBitQ bit-plane scan next to its XLA reference —
        # popcount ops charged as integer ops against the "int" peak
        idx = ivf_rabitq.build(
            ivf_rabitq.IndexParams(n_lists=args.n_lists, kmeans_n_iters=4),
            data)
        sp = ivf_rabitq.SearchParams(n_probes=8, scan_engine="fused")
        rec = run_case(
            "perf_smoke",
            f"rabitq_bitplane_fused_{args.rows}_q{args.queries}_k{args.k}"
            "_probes8",
            lambda: ivf_rabitq.search(sp, idx, q, args.k),
            iters=3, warmup=1, items=float(args.queries), unit="qps")
        bank.add(rec, echo=False)
        bank.check_transport()
        return {"qps": rec.get("value")}

    geometry = {"rows": args.rows, "dim": args.dim,
                "queries": args.queries, "k": args.k}
    env_dir = os.environ.get("RAFT_TPU_JOB_DIR", "").strip() or None
    with job_dir_or_temp(env_dir, "raft_tpu_perf_smoke_") as jd:
        job = jobs.Job("perf_smoke", jd)
        job.add_stage("bf_knn", bf_knn, inputs=geometry,
                      deadline_s=deadline_s)
        job.add_stage("bf_knn_fused", bf_knn_fused, inputs=geometry,
                      deadline_s=deadline_s)
        job.add_stage("ivf_pq_search", pq_search,
                      inputs={**geometry, "n_lists": args.n_lists},
                      deadline_s=deadline_s)
        job.add_stage("ivf_pq_int8_fused", pq_int8_fused,
                      inputs={**geometry, "n_lists": args.n_lists,
                              "engine": "fused_int8"},
                      deadline_s=deadline_s)
        job.add_stage("rabitq_bitplane_fused", rabitq_bitplane_fused,
                      inputs={**geometry, "n_lists": args.n_lists,
                              "engine": "fused_bitplane"},
                      deadline_s=deadline_s)
        # independent cases: one timed-out case must not zero the whole
        # sweep — bank what completes, then fail loudly below
        try:
            statuses = job.run(continue_on_error=True)
        except jobs.JobPreempted:
            # SIGTERM = graceful suspend, not a crash: exit through the
            # shared preemption protocol so callers can tell them apart
            from common import PREEMPT_EXIT

            print("preempted; re-run with RAFT_TPU_JOB_DIR set to "
                  "resume", file=sys.stderr)
            sys.exit(PREEMPT_EXIT)

    print(f"banked -> {bank.path}")
    failed = sorted(s for s, st in statuses.items() if st == "failed")
    if failed:
        print(f"FAILED stages: {', '.join(failed)}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
