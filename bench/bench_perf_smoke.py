"""Tiny in-process perf bench: the ledger's heartbeat.

Runs in seconds on any backend (CPU included) and banks a brute-force
kNN row and an IVF-PQ search row through `common.Banker` — which means
every run appends honestly-tagged rows (git SHA, platform, span phases
with cost-model MFU) to BENCH_LEDGER.jsonl. `ci/test.sh perf` points
RAFT_TPU_BENCH_LEDGER at a temp file and runs this, then gates the
fresh rows with `python -m tools.perfgate --json` — so every future PR
banks fresh numbers and sees drift the moment it lands, even when the
chip queue is down (ROADMAP item 5a).

Observability is force-enabled in-process: the whole point of these
rows is the per-phase attribution and MFU they carry.

Usage: python bench/bench_perf_smoke.py [--rows N] [--queries N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import Banker, ensure_survivable_backend, run_case


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=4096)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--queries", type=int, default=256)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--n-lists", type=int, default=32)
    args = ap.parse_args()

    fallback = ensure_survivable_backend()

    from raft_tpu import obs
    from raft_tpu.neighbors import brute_force, ivf_pq

    obs.enable()

    # RAFT_TPU_BENCH_OUT redirects the results file (hermetic CI/tests);
    # the ledger path has its own env override (RAFT_TPU_BENCH_LEDGER)
    out_dir = os.environ.get("RAFT_TPU_BENCH_OUT", "").strip() or \
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bank = Banker(
        os.path.join(out_dir, "BENCH_perf_smoke.json"),
        meta={"dataset_rows": args.rows, "dim": args.dim,
              "queries": args.queries, "k": args.k, "n_lists": args.n_lists},
        fallback=fallback,
    )

    rng = np.random.default_rng(7)
    data = rng.random((args.rows, args.dim), dtype=np.float32)
    q = rng.random((args.queries, args.dim), dtype=np.float32)

    rec = run_case(
        "perf_smoke", f"bf_knn_{args.rows}x{args.dim}_q{args.queries}_k{args.k}",
        lambda: brute_force.knn(data, q, k=args.k),
        iters=3, warmup=1, items=float(args.queries), unit="qps")
    bank.add(rec, echo=False)
    bank.check_transport()

    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=args.n_lists, kmeans_n_iters=4,
                           pq_dim=args.dim // 2), data)
    sp = ivf_pq.SearchParams(n_probes=8)
    rec = run_case(
        "perf_smoke",
        f"ivf_pq_search_{args.rows}_q{args.queries}_k{args.k}_probes8",
        lambda: ivf_pq.search(sp, idx, q, args.k),
        iters=3, warmup=1, items=float(args.queries), unit="qps")
    bank.add(rec, echo=False)

    print(f"banked -> {bank.path}")


if __name__ == "__main__":
    main()
