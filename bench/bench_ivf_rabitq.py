"""IVF-RaBitQ vs IVF-PQ: the recall / QPS / build-time three-way race.

The RaBitQ claim (arXiv 2602.23999, ROADMAP item 2) is BOTH faster
search (binary codes + integer scan + cheap rerank) and much faster
index build (no codebook EM) at matched recall. This bench measures all
three axes at the same geometry and banks every row to
BENCH_rabitq.json via bench/common.Banker — incrementally, so a
transport death mid-run forfeits only the in-flight stage.

Survivability (ROADMAP item 5a, first slice): when the on-chip relay
transport is dead, `common.ensure_survivable_backend()` pins the CPU
platform in-process and the rows still bank to the REAL file, honestly
tagged `"fallback": "in_process_cpu"` — a dead relay stops recycling
stale numbers instead of aborting the measurement.

Protocol per engine:
  build      wall-clock of a full index build (the headline RaBitQ win)
  recall     offline recall@k vs brute force at a (n_probes, rerank)
             ladder; the banked config is the cheapest clearing
             --recall (default 0.95)
  qps        steady-state batched query throughput of that config

--apply writes the RaBitQ winner's knobs ("rabitq_rerank_mult",
"rabitq_query_bits") into raft_tpu/tuned_defaults.json through
core.tuned.merge — an atomic serialize.atomic_write, so a crash
mid-write can never truncate the tuned file. These keys steer RECALL
(platform-independent), not kernel choice, so a CPU-fallback run may
legitimately write them.

Usage: python bench/bench_ivf_rabitq.py [--smoke] [--apply]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import Banker, ensure_survivable_backend


def _recall(got: np.ndarray, exact: np.ndarray, k: int) -> float:
    return float(np.mean([
        len(set(got[i]) & set(exact[i])) / k for i in range(len(exact))
    ]))


def _time_build(fn, reps: int = 1):
    """Returns (best wall-clock seconds, the built index)."""
    import jax

    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        idx = fn()
        jax.block_until_ready(idx.codes)
        best = min(best, time.perf_counter() - t0)
    return best, idx


def _qps(search_fn, queries, iters: int = 3) -> float:
    import jax

    jax.block_until_ready(search_fn(queries))  # compile + warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(search_fn(queries))
    dt = (time.perf_counter() - t0) / iters
    return len(queries) / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--dim", type=int, default=96)
    ap.add_argument("--n-lists", type=int, default=256)
    ap.add_argument("--queries", type=int, default=512)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--recall", type=float, default=0.95)
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--apply", action="store_true",
                    help="write the RaBitQ winner's recall knobs to "
                         "tuned_defaults.json (atomic)")
    args = ap.parse_args()
    if args.smoke:
        args.rows, args.n_lists, args.queries = 10_000, 32, 128

    # BEFORE any device op: a dead relay pins CPU in-process and the
    # rows bank to the real file, tagged (ROADMAP 5a). A --smoke
    # rehearsal still gets the CPU pin (it must not hang either) but
    # NEVER the real-file diversion: smoke-scale rows replacing a chip
    # session's banked file is exactly the 2026-08-01 clobber the
    # Banker's .cpu guard exists for.
    fallback = ensure_survivable_backend()
    if args.smoke:
        fallback = None

    from raft_tpu.neighbors import brute_force, ivf_pq, ivf_rabitq
    from raft_tpu.random import make_blobs

    bank = Banker(
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "BENCH_rabitq.json"),
        meta={"dataset_rows": args.rows, "dim": args.dim,
              "n_lists": args.n_lists,
              "queries": args.queries, "k": args.k,
              "recall_target": args.recall, "smoke": bool(args.smoke)},
        fallback=fallback,
    )

    data, _ = make_blobs(args.rows, args.dim, n_clusters=max(args.n_lists // 4, 8),
                         cluster_std=1.0, seed=11)
    data = np.asarray(data, np.float32)
    rng = np.random.default_rng(3)
    q = data[rng.choice(args.rows, args.queries, replace=False)]
    _, exact = brute_force.knn(data, q, args.k)
    exact = np.asarray(exact)
    bank.check_transport()

    # ---- build-time race (the headline RaBitQ claim) -----------------
    rb_build_s, rb_idx = _time_build(lambda: ivf_rabitq.build(
        ivf_rabitq.IndexParams(n_lists=args.n_lists, kmeans_n_iters=10),
        data, seed=0))
    bank.add({"case": "build", "engine": "ivf_rabitq",
              "seconds": round(rb_build_s, 3)})
    bank.check_transport()
    pq_build_s, pq_idx = _time_build(lambda: ivf_pq.build(
        ivf_pq.IndexParams(n_lists=args.n_lists, kmeans_n_iters=10),
        data, seed=0))
    bank.add({"case": "build", "engine": "ivf_pq",
              "seconds": round(pq_build_s, 3),
              "rabitq_speedup": round(pq_build_s / max(rb_build_s, 1e-9), 2)})
    bank.check_transport()

    # ---- recall ladder -> cheapest gate-clearing config --------------
    rb_best = None
    for n_probes in (8, 16, 32, 64):
        if n_probes > args.n_lists:
            break
        for rerank_mult in (4, 8, 16, 25):
            sp = ivf_rabitq.SearchParams(n_probes=n_probes,
                                         rerank_mult=rerank_mult)
            _, ids = ivf_rabitq.search(sp, rb_idx, q, args.k)
            rec = _recall(np.asarray(ids), exact, args.k)
            bank.add({"case": "recall", "engine": "ivf_rabitq",
                      "n_probes": n_probes, "rerank_mult": rerank_mult,
                      "recall": round(rec, 4)})
            if rec >= args.recall:
                rb_best = (n_probes, rerank_mult, rec)
                break
        if rb_best:
            break
    bank.check_transport()

    # PQ races with ITS documented high-recall pipeline too (search a
    # 4k shortlist + exact refine, docs/vector_search.md) — comparing
    # reranked RaBitQ against unreranked PQ would flatter the new engine
    from raft_tpu.neighbors import refine as _refine

    def pq_search_refined(sp, x, k):
        _, cand = ivf_pq.search(sp, pq_idx, x, 4 * k)
        return _refine(data, x, np.asarray(cand), k)

    pq_best = None
    for refined in (False, True):
        for n_probes in (8, 16, 32, 64):
            if n_probes > args.n_lists:
                break
            sp = ivf_pq.SearchParams(n_probes=n_probes)
            if refined:
                _, ids = pq_search_refined(sp, q, args.k)
            else:
                _, ids = ivf_pq.search(sp, pq_idx, q, args.k)
            rec = _recall(np.asarray(ids), exact, args.k)
            bank.add({"case": "recall",
                      "engine": "ivf_pq+refine" if refined else "ivf_pq",
                      "n_probes": n_probes, "recall": round(rec, 4)})
            if rec >= args.recall:
                pq_best = (n_probes, rec, refined)
                break
        if pq_best:
            break
    bank.check_transport()

    # ---- QPS at the gate-clearing configs ----------------------------
    if rb_best:
        n_probes, rerank_mult, rec = rb_best
        sp = ivf_rabitq.SearchParams(n_probes=n_probes,
                                     rerank_mult=rerank_mult)
        qps = _qps(lambda x: ivf_rabitq.search(sp, rb_idx, x, args.k), q)
        bank.add({"case": "qps", "engine": "ivf_rabitq", "qps": round(qps, 1),
                  "n_probes": n_probes, "rerank_mult": rerank_mult,
                  "recall": round(rec, 4),
                  "build_seconds": round(rb_build_s, 3)})
    if pq_best:
        n_probes, rec, refined = pq_best
        sp = ivf_pq.SearchParams(n_probes=n_probes)
        if refined:
            qps = _qps(lambda x: pq_search_refined(sp, x, args.k), q)
        else:
            qps = _qps(lambda x: ivf_pq.search(sp, pq_idx, x, args.k), q)
        bank.add({"case": "qps",
                  "engine": "ivf_pq+refine" if refined else "ivf_pq",
                  "qps": round(qps, 1),
                  "n_probes": n_probes, "recall": round(rec, 4),
                  "build_seconds": round(pq_build_s, 3)})

    headline = {
        "case": "headline",
        "gate": args.recall,
        "rabitq_cleared": bool(rb_best),
        "pq_cleared": bool(pq_best),
        "build_speedup_vs_pq": round(pq_build_s / max(rb_build_s, 1e-9), 2),
    }
    bank.set("headline", headline)
    print("headline:", headline)

    if args.apply and rb_best:
        from raft_tpu.core import tuned

        # recall knobs only (platform-independent); engine/kernel keys
        # stay chip-measured. tuned.merge writes through
        # serialize.atomic_write — no torn tuned files.
        tuned.merge({"rabitq_rerank_mult": int(rb_best[1]),
                     "rabitq_query_bits": 8})
        print("applied tuned keys: rabitq_rerank_mult=%d rabitq_query_bits=8"
              % rb_best[1])


if __name__ == "__main__":
    main()
