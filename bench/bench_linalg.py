"""linalg benches (reference cpp/bench/linalg/: add/map/matrix_vector_op/
norm/reduce/reduce_rows_by_key/reduce_cols_by_key shapes)."""

import sys, os

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from common import run_case
import jax.numpy as jnp

from raft_tpu import linalg


def main():
    rng = np.random.default_rng(0)
    for m, n in [(8192, 1024), (16384, 4096)]:
        a = jnp.asarray(rng.random((m, n), dtype=np.float32))
        b = jnp.asarray(rng.random((m, n), dtype=np.float32))
        v = jnp.asarray(rng.random((n,), dtype=np.float32))
        keys = jnp.asarray(rng.integers(0, 64, m, dtype=np.int32))
        elems = float(m * n)
        run_case("linalg", f"eltwise_add_{m}x{n}",
                 lambda a=a, b=b: linalg.eltwise_add(a, b), items=elems, unit="elems/s")
        run_case("linalg", f"map_fma_{m}x{n}",
                 lambda a=a, b=b: linalg.map_op(lambda x, y: x * y + x, a, b),
                 items=elems, unit="elems/s")
        run_case("linalg", f"matrix_vector_op_{m}x{n}",
                 lambda a=a, v=v: linalg.matrix_vector_op(a, v, lambda x, y: x + y),
                 items=elems, unit="elems/s")
        run_case("linalg", f"row_norm_{m}x{n}",
                 lambda a=a: linalg.row_norm(a), items=elems, unit="elems/s")
        run_case("linalg", f"reduce_rows_by_key_{m}x{n}_k64",
                 lambda a=a, keys=keys: linalg.reduce_rows_by_key(a, keys, 64),
                 items=elems, unit="elems/s")
    # gemm at an MXU-shaped size (cublas wrapper parity)
    x = jnp.asarray(rng.random((4096, 4096), dtype=np.float32))
    run_case("linalg", "gemm_4096", lambda x=x: linalg.gemm(x, x),
             items=2.0 * 4096**3 / 1e9, unit="GFLOP/s")


if __name__ == "__main__":
    main()
