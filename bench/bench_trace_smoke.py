"""Hermetic serve-tracing smoke: the CI drill behind `ci/test.sh obs`.

Drives a step-mode `SearchServer` through ~1k traced requests (a mix of
served and deadline-expired traffic) with the full ISSUE-18 stack armed
— request-scope tracing, the flight recorder, and an attached SLO
watchtower — then proves the exporter contracts in-process:

  * `obs.to_chrome_trace()` rendered twice must be byte-identical and
    must parse as valid Chrome trace-event JSON;
  * the flight dump must land as one readable atomic JSON file (no
    `*.tmp.*` droppings) whose ring carries the run's events;
  * the obs snapshot saved to `--out` must carry trace records, all
    four per-stage histograms, terminal-outcome counters, and at least
    one SLO transition — `ci/test.sh obs` renders `obs.report` over it
    twice and `cmp`s the bytes.

Step mode keeps the run single-threaded and the clock monotonic-only,
so everything the snapshot pins (ids, counts, event order) replays
bit-for-bit. Exits non-zero on any violated contract.

Usage: python bench/bench_trace_smoke.py [--out DIR] [--requests N]
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="artifact dir (default: RAFT_TPU_BENCH_OUT or "
                         "a fresh temp dir)")
    ap.add_argument("--requests", type=int, default=1000)
    ap.add_argument("--rows", type=int, default=2000)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--k", type=int, default=10)
    args = ap.parse_args()
    out = (args.out or os.environ.get("RAFT_TPU_BENCH_OUT", "").strip()
           or tempfile.mkdtemp(prefix="trace_smoke_"))
    os.makedirs(out, exist_ok=True)

    from raft_tpu import obs, serve
    from raft_tpu.obs import export, flight, slo, trace

    obs.enable()
    obs.reset()
    trace.reset(seed=0)
    flight.install(maxlen=2048, dump_dir=out)

    rng = np.random.default_rng(0)
    data = rng.standard_normal((args.rows, args.dim)).astype(np.float32)
    queries = rng.standard_normal(
        (256, args.dim)).astype(np.float32)

    server = serve.SearchServer(
        data, serve.ServerConfig(buckets=(8, 32), max_wait_ms=0.0))
    # fast+slow windows both see the whole (sub-second) run, so the
    # expiry burst below breaches error_rate on both at once — the SLO
    # section in the report gets a real transition to render
    server.attach_watchtower(slo.Watchtower(slo.serve_objectives()))

    served = expired = 0
    i = 0
    while served + expired < args.requests:
        futs = []
        # one micro-batch per step: three live requests and, every
        # fourth group, one whose deadline already passed (admission
        # must kill it — the drop_wait/outcome story needs casualties)
        for j in range(3):
            n = 1 + (i + j) % 4
            q = queries[(i + j) % 256][None, :].repeat(n, axis=0)
            futs.append((server.submit(q, k=args.k), False))
        if i % 4 == 0:
            futs.append((server.submit(queries[i % 256][None, :],
                                       k=args.k, deadline_s=0.0), True))
        server.step()
        for fut, doomed in futs:
            try:
                fut.result(timeout=30.0)
                served += 1
            except serve.DeadlineExceeded:
                expired += 1
                if not doomed:
                    raise
        i += len(futs)

    # -- contract 1: chrome export is valid and byte-stable ------------
    one = obs.to_chrome_trace()
    two = obs.to_chrome_trace()
    if one != two:
        raise SystemExit("chrome trace render is not byte-stable")
    payload = json.loads(one)
    if not payload["traceEvents"]:
        raise SystemExit("chrome trace rendered no events")
    chrome_path = os.path.join(out, "chrome_trace.json")
    with open(chrome_path, "w") as f:
        f.write(one)

    # -- contract 2: the flight dump is one readable atomic file -------
    dump_path = flight.maybe_dump("bench_trace_smoke",
                                  served=served, expired=expired)
    if dump_path is None or not os.path.exists(dump_path):
        raise SystemExit("flight dump did not land")
    droppings = [p for p in os.listdir(out) if ".tmp." in p]
    if droppings:
        raise SystemExit(f"atomic_write left temp droppings: {droppings}")
    with open(dump_path) as f:
        dump = json.load(f)
    if not dump["events"]:
        raise SystemExit("flight ring dumped empty")

    # -- contract 3: the snapshot carries the full ISSUE-18 surface ----
    snap_path = os.path.join(out, "obs_snapshot.json")
    snap = export.save_snapshot(snap_path)
    counters = snap["metrics"]["counters"]
    hists = snap["metrics"]["histograms"]
    traces = [e for e in snap["events"] if e.get("kind") == "trace"]
    problems = []
    if counters.get("serve.outcome.ok", 0) != served:
        problems.append("serve.outcome.ok != served")
    if counters.get("serve.outcome.expired", 0) != expired:
        problems.append("serve.outcome.expired != expired")
    if counters.get("slo.breach", 0) < 1:
        problems.append("no slo.breach fired")
    for name in ("serve.stage.queue_wait_s", "serve.stage.linger_s",
                 "serve.stage.device_s", "serve.stage.scatter_s"):
        if hists.get(name, {}).get("count", 0) == 0:
            problems.append(f"{name} empty")
    if hists.get("serve.drop_wait_s", {}).get("count", 0) != expired:
        problems.append("serve.drop_wait_s count != expired")
    if not traces:
        problems.append("no trace events survived the bus window")
    if problems:
        raise SystemExit("snapshot contract violated: " + "; ".join(problems))

    print(json.dumps({
        "suite": "trace_smoke", "served": served, "expired": expired,
        "trace_events_on_bus": len(traces),
        "chrome_events": len(payload["traceEvents"]),
        "flight_ring_events": len(dump["events"]),
        "snapshot": snap_path, "flight_dump": dump_path,
        "chrome_trace": chrome_path,
    }, sort_keys=True))


if __name__ == "__main__":
    main()
