"""Race the fused list-scan kernel variants at the headline geometry.

The 2026-08-01 chip window measured the fused Pallas trim 2.5x SLOWER
than the XLA approx trim end-to-end (2384 vs 5948 qps), but end-to-end
mixes coarse select, probe inversion, and the final merge into the
number. This suite isolates the scan itself on a synthetic store at the
bench shape (n_lists=1024, L=lane_padded(~4928), rot=96, chunk=128,
ncb=1024) and races:

  exact   — the shipping kernel (f32 best+second fold, ~11 VPU ops/fold)
  packed  — int32-packed bf16-coarse fold (~3 ops/fold; same candidate
            contract at bf16-band precision — the trim class that WON
            the internal_distance_dtype A/B)
  xla     — gather store block + bf16 matmul + lax.approx_min_k, the
            approx engine's inner loop, on identical inputs

plus a store-bandwidth roofline row (just streaming the store through a
sum) so each variant's distance from memory-bound is visible.

--apply writes the pallas_fold tuned key when packed beats exact by
>10% (the engines read it; ivf_pq.py / ivf_flat.py / mnmg.py).

Results bank to PALLAS_SCAN_RACE.json after every row.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import common  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

R = {}
_OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "PALLAS_SCAN_RACE.json",
)


def _bank():
    print(json.dumps(R), flush=True)
    try:
        with open(_OUT, "w") as f:
            json.dump(R, f, indent=1)
    except OSError:
        pass


def _bail_if_dead(where):
    # CPU-aware (chip_probe_would_hang): the --smoke rehearsal must run
    # with the relay dead, exactly like bench_10m_build's gate
    try:
        from raft_tpu.core.config import chip_probe_would_hang
    except Exception:
        return
    if chip_probe_would_hang():
        R["aborted"] = f"relay died before {where}"
        _bank()
        sys.exit(3)


def main(apply: bool = False, smoke: bool = False):
    _bail_if_dead("backend_init")
    from common import enable_persistent_cache

    enable_persistent_cache()
    from raft_tpu.core.config import is_device_fault
    from raft_tpu.ops.pq_list_scan import lane_padded, pq_list_scan

    if smoke:
        n_lists, L, rot, ncb, chunk, kk = 16, lane_padded(300), 32, 8, 16, 10
    else:
        n_lists, L, rot, ncb, chunk, kk = 1024, lane_padded(4928), 96, 1024, 128, 10
    interp = jax.default_backend() == "cpu"
    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    r8 = jax.random.randint(k1, (n_lists, L, rot), -127, 128, jnp.int8)
    base = jnp.abs(jax.random.normal(k2, (n_lists, 1, L), jnp.float32)) * 10
    lof = jax.random.randint(k3, (ncb,), 0, n_lists, jnp.int32)
    qres = jax.random.normal(k4, (ncb, chunk, rot), jnp.float32)
    jax.block_until_ready((r8, base, lof, qres))
    R["shape"] = {"n_lists": n_lists, "L": L, "rot": rot, "ncb": ncb,
                  "chunk": chunk}
    store_gb = ncb * L * rot / 1e9  # bytes the scan streams (int8)

    def timeit(fn, iters=5):
        jax.block_until_ready(fn())
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(fn())
        return (time.perf_counter() - t0) / iters

    @jax.jit
    def xla_inner(lof, qres, r8, base):
        def blk(inp):
            lo, q = inp  # (cb,), (cb, chunk, rot)
            rb = r8[lo]  # gather (cb, L, rot)
            dots = jnp.einsum(
                "cqd,csd->cqs", q.astype(jnp.bfloat16),
                rb.astype(jnp.bfloat16), preferred_element_type=jnp.float32,
            )
            scores = base[lo].reshape(-1, 1, L) - 2.0 * dots
            return jax.lax.approx_min_k(scores, kk, recall_target=0.99)
        cb = 8
        return jax.lax.map(
            blk, (lof.reshape(-1, cb), qres.reshape(-1, cb, chunk, rot))
        )

    @jax.jit
    def roofline(r8):
        # stream the store once: the memory-bound floor for any scan
        return jnp.sum(r8.astype(jnp.int32), axis=(1, 2))

    cases = {
        "exact": lambda: pq_list_scan(lof, qres, r8, base, interpret=interp),
        "packed": lambda: pq_list_scan(
            lof, qres, r8, base, interpret=interp, fold="packed"
        ),
        "xla_approx": lambda: xla_inner(lof, qres, r8, base),
        "store_stream": lambda: roofline(r8),
    }
    for name, fn in cases.items():
        _bail_if_dead(name)
        try:
            dt = timeit(fn)
            row = {"ms": round(dt * 1e3, 2)}
            if name != "store_stream":
                row["store_gbps"] = round(store_gb / dt, 1)
            else:
                row["store_gbps"] = round(n_lists * L * rot / 1e9 / dt, 1)
            R[name] = row
            print(f"{name}: {row}", flush=True)
        except Exception as e:
            R[name] = {"error": str(e)[:160]}
            print(f"{name} FAILED: {e}", flush=True)
            if is_device_fault(e):
                R["aborted"] = f"device fault during {name}"
                _bank()
                sys.exit(4)
        _bank()

    ex, pk = R.get("exact"), R.get("packed")
    if apply and (smoke or jax.default_backend() == "cpu"):
        # interpret-mode timings at toy shapes must never flip the
        # production trim (same guard as bench_select_k_strategies)
        R["apply_skipped"] = "smoke/cpu run; tuned key untouched"
        _bank()
        apply = False
    if apply and isinstance(ex, dict) and isinstance(pk, dict) \
            and "ms" in ex and "ms" in pk:
        from raft_tpu.core import tuned

        winner = "packed" if pk["ms"] * 1.1 < ex["ms"] else "exact"
        tuned.merge({"pallas_fold": winner})
        R["applied"] = winner
        _bank()


if __name__ == "__main__":
    main(apply="--apply" in sys.argv, smoke="--smoke" in sys.argv)
