"""Sparse pairwise distances (sparse/distance/distance.cuh:36-54 —
19 metrics over CSR×CSR inputs; coo_spmv strategies in the reference).

TPU design: the CUDA implementation is a generalized SPMV with hash-table /
shared-memory row strategies — a poor fit for the MXU. On TPU the winning
strategy is *block densification*: stream row-blocks of the sparse inputs,
scatter them into dense (bm, k) tiles in registers/VMEM, and reuse the dense
pairwise engine (MXU matmuls for expanded metrics). Sparsity saves HBM
storage; compute runs dense where the hardware wants it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.sparse.formats import CsrMatrix, csr_to_dense
from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.distance.pairwise import _pairwise_impl

SUPPORTED_DISTANCES = [
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.L1,
    DistanceType.Canberra,
    DistanceType.Linf,
    DistanceType.LpUnexpanded,
    DistanceType.JaccardExpanded,
    DistanceType.CosineExpanded,
    DistanceType.HellingerExpanded,
    DistanceType.DiceExpanded,
    DistanceType.CorrelationExpanded,
    DistanceType.RusselRaoExpanded,
    DistanceType.HammingUnexpanded,
    DistanceType.JensenShannon,
    DistanceType.KLDivergence,
    DistanceType.BrayCurtis,
]


# Row-block size for the streaming densify: one x-block dense tile at a
# time, so device memory holds O(block*k + m_y*k) instead of O((m_x+m_y)*k).
_ROW_BLOCK = 4096


def pairwise_distance(x: CsrMatrix, y: CsrMatrix, metric="euclidean", p: float = 2.0):
    """CSR×CSR distance matrix via block densification + dense engine.

    y is densified once (it is the reused operand of every block matmul);
    x streams through in `_ROW_BLOCK`-row dense tiles — the TPU answer to
    the reference's coo_spmv row strategies (sparsity saves storage, the
    MXU wants dense tiles)."""
    m = resolve_metric(metric)
    if m not in SUPPORTED_DISTANCES:
        raise ValueError(f"metric {m} not supported for sparse inputs")
    if x.shape[1] != y.shape[1]:
        raise ValueError("column mismatch")
    yd = csr_to_dense(y).astype(jnp.float32)
    n_rows = x.shape[0]
    if n_rows <= _ROW_BLOCK:
        xd = csr_to_dense(x).astype(jnp.float32)
        return _pairwise_impl(xd, yd, m, metric_arg=float(p))
    out = []
    for xb in _iter_dense_blocks(x):
        out.append(_pairwise_impl(xb, yd, m, metric_arg=float(p)))
    return jnp.concatenate(out, axis=0)


def _iter_dense_blocks(x: CsrMatrix):
    """Yield dense float32 row blocks of a CSR matrix. The CSR buffers are
    pulled to host ONCE and sliced per block (not per-block full
    conversions)."""
    import numpy as np

    indptr = np.asarray(x.indptr)
    indices = np.asarray(x.indices)
    data = np.asarray(x.data)
    n_rows, n_cols = x.shape
    for lo in range(0, n_rows, _ROW_BLOCK):
        hi = min(lo + _ROW_BLOCK, n_rows)
        plo, phi = int(indptr[lo]), int(indptr[hi])
        block = CsrMatrix(
            jnp.asarray(indptr[lo : hi + 1] - plo),
            jnp.asarray(indices[plo:phi]),
            jnp.asarray(data[plo:phi]),
            (hi - lo, n_cols),
        )
        yield csr_to_dense(block).astype(jnp.float32)


def knn(x: CsrMatrix, y: CsrMatrix, k: int, metric="euclidean"):
    """Sparse brute-force kNN (sparse/neighbors/brute_force.cuh), following
    the dense brute_force convention: dataset=x, queries=y; returns
    (dists, idx) into x rows. The dataset streams through in dense row
    blocks whose partial top-k are merged (knn_merge_parts pattern)."""
    from raft_tpu.neighbors.brute_force import _bf_knn_impl
    from raft_tpu.matrix.select_k import _select_k_impl
    from raft_tpu.distance.distance_types import SIMILARITY_METRICS

    m = resolve_metric(metric)
    k = int(k)
    yd = csr_to_dense(y).astype(jnp.float32)
    n_rows = x.shape[0]
    if n_rows <= _ROW_BLOCK:
        xd = csr_to_dense(x).astype(jnp.float32)
        return _bf_knn_impl(xd, yd, k, m)
    # same selection rule as _bf_knn_impl's per-block top-k
    select_min = m not in SIMILARITY_METRICS
    parts_v, parts_i = [], []
    lo = 0
    for xb in _iter_dense_blocks(x):
        hi = lo + xb.shape[0]
        dv, di = _bf_knn_impl(xb, yd, min(k, hi - lo), m)
        parts_v.append(dv)
        parts_i.append(di + lo)
        lo = hi
    cat_v = jnp.concatenate(parts_v, axis=1)
    cat_i = jnp.concatenate(parts_i, axis=1)
    v, pos = _select_k_impl(cat_v, k, select_min)
    return v, jnp.take_along_axis(cat_i, pos, axis=1)
