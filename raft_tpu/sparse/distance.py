"""Sparse pairwise distances (sparse/distance/distance.cuh:36-54 —
19 metrics over CSR×CSR inputs; coo_spmv strategies in the reference).

TPU design: the CUDA implementation is a generalized SPMV with hash-table /
shared-memory row strategies — a poor fit for the MXU. On TPU the winning
strategy is *block densification*: stream row-blocks of the sparse inputs,
scatter them into dense (bm, k) tiles in registers/VMEM, and reuse the dense
pairwise engine (MXU matmuls for expanded metrics). Sparsity saves HBM
storage; compute runs dense where the hardware wants it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.sparse.formats import CsrMatrix, csr_to_dense
from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.distance.pairwise import _pairwise_impl

SUPPORTED_DISTANCES = [
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.L1,
    DistanceType.Canberra,
    DistanceType.Linf,
    DistanceType.LpUnexpanded,
    DistanceType.JaccardExpanded,
    DistanceType.CosineExpanded,
    DistanceType.HellingerExpanded,
    DistanceType.DiceExpanded,
    DistanceType.CorrelationExpanded,
    DistanceType.RusselRaoExpanded,
    DistanceType.HammingUnexpanded,
    DistanceType.JensenShannon,
    DistanceType.KLDivergence,
    DistanceType.BrayCurtis,
]


# Row-block size for the streaming densify: one x-block dense tile at a
# time, so device memory holds O(block*k + m_y*k) instead of O((m_x+m_y)*k).
_ROW_BLOCK = 4096

# Densified-operand budget: above this, the reused y operand is streamed
# in row blocks too instead of being materialized wholesale (the regime
# the reference's coo_spmv strategies exist for, coo_spmv.cuh).
_DENSIFY_BUDGET_BYTES = 2 << 30


def pairwise_distance(x: CsrMatrix, y: CsrMatrix, metric="euclidean", p: float = 2.0,
                      densify_budget_bytes: int = None, row_block: int = None):
    """CSR×CSR distance matrix via block densification + dense engine.

    y is normally densified once (it is the reused operand of every block
    matmul); x streams through in `row_block`-row dense tiles (default
    `_ROW_BLOCK`) — the TPU answer to the reference's coo_spmv row
    strategies (sparsity saves storage, the MXU wants dense tiles). When
    dense y would exceed `densify_budget_bytes` (default 2 GiB), y
    streams in row blocks as well and the output is assembled
    column-block-wise — every supported metric is row-wise, so blocking
    either operand is exact. When even one block pair is over budget,
    the column space compacts to the active-column union and, if needed,
    the row blocks shrink; only a budget no block shape can satisfy
    raises."""
    m = resolve_metric(metric)
    if m not in SUPPORTED_DISTANCES:
        raise ValueError(f"metric {m} not supported for sparse inputs")
    if x.shape[1] != y.shape[1]:
        raise ValueError("column mismatch")
    budget = _DENSIFY_BUDGET_BYTES if densify_budget_bytes is None else int(densify_budget_bytes)
    rb = int(row_block) if row_block else _ROW_BLOCK
    k = x.shape[1]
    min_block_bytes = 4 * k * (
        min(rb, x.shape[0]) + min(rb, y.shape[0])
    )
    if min_block_bytes > budget:
        # truly-sparse regime (text workloads: 1M-column CSRs): even one
        # densified block pair exceeds the budget. Compact the column
        # space to the union of ACTIVE columns (<= nnz_x + nnz_y) and
        # recurse — exact for every supported metric because inactive
        # columns contribute (0,0) to each pairwise term; the three
        # metrics that reference the full column count are corrected in
        # closed form. The TPU answer to the reference's hash-table /
        # row-strategy generalized spmv (sparse/distance/detail/
        # coo_spmv.cuh + coo_spmv_strategies/).
        return _pairwise_compact_columns(x, y, m, float(p), budget, rb)
    if 4 * y.shape[0] * k > budget:
        if 4 * x.shape[0] * k <= budget:
            # dense x fits: hold its blocks device-resident once and stream
            # y — each operand densified exactly once (operand order is
            # preserved: some metrics, e.g. KL divergence, are asymmetric)
            xblocks = list(_iter_dense_blocks(x, row_block=rb))
            cols = []
            for yb in _iter_dense_blocks(y, row_block=rb):
                cols.append(jnp.concatenate(
                    [_pairwise_impl(xb, yb, m, metric_arg=float(p)) for xb in xblocks],
                    axis=0,
                ))
            return jnp.concatenate(cols, axis=1)
        # both operands over budget: blocked-matmul panel re-read — x
        # re-streams per y block (the CSR host buffers are pulled once)
        xh = _host_csr(x)
        cols = [
            _pairwise_dense_y(x, yb, m, float(p), host=xh, row_block=rb)
            for yb in _iter_dense_blocks(y, row_block=rb)
        ]
        return jnp.concatenate(cols, axis=1)
    return _pairwise_dense_y(x, csr_to_dense(y).astype(jnp.float32), m, float(p),
                             row_block=rb)


def _compact_column_space(x: CsrMatrix, y: CsrMatrix):
    """Remap both CSRs onto the sorted union of their active columns.

    Returns (x', y', u) with u = |union| (>= 1; a dummy column keeps
    downstream shapes valid when both inputs are all-zero). Host-side
    O(nnz log nnz) — the same one-off cost class as `_host_csr`."""
    import numpy as np

    xi = np.asarray(x.indices)
    yi = np.asarray(y.indices)
    cols = np.union1d(xi, yi)
    if cols.size == 0:
        cols = np.zeros((1,), xi.dtype if xi.size else np.int32)
    u = int(cols.size)
    x2 = CsrMatrix(
        x.indptr, jnp.asarray(np.searchsorted(cols, xi).astype(np.int32)),
        x.data, (x.shape[0], u),
    )
    y2 = CsrMatrix(
        y.indptr, jnp.asarray(np.searchsorted(cols, yi).astype(np.int32)),
        y.data, (y.shape[0], u),
    )
    return x2, y2, u


def _pairwise_compact_columns(x: CsrMatrix, y: CsrMatrix, m: DistanceType,
                              p: float, budget: int, row_block: int = None):
    """Distance matrix in the compacted column space (see caller).

    Per-metric exactness over the full k = x.shape[1] columns:
      - sum-form metrics whose per-column term vanishes at (0,0) and whose
        normalization is k-free (16 of the 19) are computed as-is;
      - Hamming divides disagreement counts by k: rescale by u/k;
      - RusselRao is (k - <x,y>)/k: recover <x,y> from the compact value;
      - Correlation centers by full-k means: computed directly from
        compact inner products + row sums/sumsq with the true k.
    """
    D = DistanceType
    k = x.shape[1]
    x2, y2, u = _compact_column_space(x, y)
    # a caller-capped row_block stays the ceiling of the shrink search
    rb = row_block or _ROW_BLOCK
    while 4 * u * (min(rb, x.shape[0]) + min(rb, y.shape[0])) > budget and rb > 32:
        # the active-column union can itself be wide (dense-ish text
        # rows); shrink the dense row tiles until a block pair fits —
        # more, smaller matmuls instead of a refusal
        rb //= 2
    if 4 * u * (min(rb, x.shape[0]) + min(rb, y.shape[0])) > budget:
        raise ValueError(
            f"sparse inputs stay over densify_budget_bytes={budget} even "
            f"in the compacted column space ({u} active of {k} columns) "
            f"at the minimum {rb}-row block; raise the budget"
        )
    if m == D.HammingUnexpanded:
        d = pairwise_distance(x2, y2, m, p, densify_budget_bytes=budget,
                              row_block=rb)
        return d * (u / k)
    if m == D.RusselRaoExpanded:
        d = pairwise_distance(x2, y2, m, p, densify_budget_bytes=budget,
                              row_block=rb)
        # compact value is (u - dot)/u; the full-k metric is (k - dot)/k
        return 1.0 - (u / k) * (1.0 - d)
    if m == D.CorrelationExpanded:
        dot = pairwise_distance(
            x2, y2, D.InnerProduct, p, densify_budget_bytes=budget,
            row_block=rb,
        )
        sx = jax.ops.segment_sum(
            x2.data.astype(jnp.float32), x2.row_ids(), num_segments=x2.shape[0]
        )
        sy = jax.ops.segment_sum(
            y2.data.astype(jnp.float32), y2.row_ids(), num_segments=y2.shape[0]
        )
        qx = jax.ops.segment_sum(
            x2.data.astype(jnp.float32) ** 2, x2.row_ids(), num_segments=x2.shape[0]
        )
        qy = jax.ops.segment_sum(
            y2.data.astype(jnp.float32) ** 2, y2.row_ids(), num_segments=y2.shape[0]
        )
        cov = dot - sx[:, None] * sy[None, :] / k
        vx = jnp.maximum(qx - sx**2 / k, 0.0)
        vy = jnp.maximum(qy - sy**2 / k, 0.0)
        denom = jnp.sqrt(vx[:, None] * vy[None, :])
        return 1.0 - cov / jnp.maximum(denom, 1e-30)
    return pairwise_distance(x2, y2, m, p, densify_budget_bytes=budget,
                             row_block=rb)


def _pairwise_dense_y(x: CsrMatrix, yd, m: DistanceType, p: float, host=None,
                      row_block: int = None):
    """x streamed in dense row blocks against an already-dense y."""
    rb = row_block or _ROW_BLOCK
    if x.shape[0] <= rb:
        xd = csr_to_dense(x).astype(jnp.float32)
        return _pairwise_impl(xd, yd, m, metric_arg=p)
    out = []
    for xb in _iter_dense_blocks(x, host=host, row_block=rb):
        out.append(_pairwise_impl(xb, yd, m, metric_arg=p))
    return jnp.concatenate(out, axis=0)


def _host_csr(x: CsrMatrix):
    """Pull a CSR's buffers to host once (for repeated block slicing)."""
    import numpy as np

    return np.asarray(x.indptr), np.asarray(x.indices), np.asarray(x.data)


def _iter_dense_blocks(x: CsrMatrix, host=None, row_block: int = None):
    """Yield dense float32 row blocks of a CSR matrix. The CSR buffers are
    pulled to host ONCE (or passed in pre-pulled via `host` when the
    caller iterates repeatedly) and sliced per block."""
    rb = row_block or _ROW_BLOCK
    indptr, indices, data = _host_csr(x) if host is None else host
    n_rows, n_cols = x.shape
    for lo in range(0, n_rows, rb):
        hi = min(lo + rb, n_rows)
        plo, phi = int(indptr[lo]), int(indptr[hi])
        block = CsrMatrix(
            jnp.asarray(indptr[lo : hi + 1] - plo),
            jnp.asarray(indices[plo:phi]),
            jnp.asarray(data[plo:phi]),
            (hi - lo, n_cols),
        )
        yield csr_to_dense(block).astype(jnp.float32)


def knn(x: CsrMatrix, y: CsrMatrix, k: int, metric="euclidean"):
    """Sparse brute-force kNN (sparse/neighbors/brute_force.cuh), following
    the dense brute_force convention: dataset=x, queries=y; returns
    (dists, idx) into x rows. The dataset streams through in dense row
    blocks whose partial top-k are merged (knn_merge_parts pattern)."""
    from raft_tpu.neighbors.brute_force import _bf_knn_impl
    from raft_tpu.matrix.select_k import _select_k_impl
    from raft_tpu.distance.distance_types import SIMILARITY_METRICS

    m = resolve_metric(metric)
    k = int(k)
    yd = csr_to_dense(y).astype(jnp.float32)
    n_rows = x.shape[0]
    if n_rows <= _ROW_BLOCK:
        xd = csr_to_dense(x).astype(jnp.float32)
        return _bf_knn_impl(xd, yd, k, m)
    # same selection rule as _bf_knn_impl's per-block top-k
    select_min = m not in SIMILARITY_METRICS
    parts_v, parts_i = [], []
    lo = 0
    for xb in _iter_dense_blocks(x):
        hi = lo + xb.shape[0]
        dv, di = _bf_knn_impl(xb, yd, min(k, hi - lo), m)
        parts_v.append(dv)
        parts_i.append(di + lo)
        lo = hi
    cat_v = jnp.concatenate(parts_v, axis=1)
    cat_i = jnp.concatenate(parts_i, axis=1)
    v, pos = _select_k_impl(cat_v, k, select_min)
    return v, jnp.take_along_axis(cat_i, pos, axis=1)
