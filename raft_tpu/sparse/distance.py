"""Sparse pairwise distances (sparse/distance/distance.cuh:36-54 —
19 metrics over CSR×CSR inputs; coo_spmv strategies in the reference).

TPU design: the CUDA implementation is a generalized SPMV with hash-table /
shared-memory row strategies — a poor fit for the MXU. On TPU the winning
strategy is *block densification*: stream row-blocks of the sparse inputs,
scatter them into dense (bm, k) tiles in registers/VMEM, and reuse the dense
pairwise engine (MXU matmuls for expanded metrics). Sparsity saves HBM
storage; compute runs dense where the hardware wants it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.sparse.formats import CsrMatrix, csr_to_dense
from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.distance.pairwise import _pairwise_impl

SUPPORTED_DISTANCES = [
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.InnerProduct,
    DistanceType.L2Unexpanded,
    DistanceType.L2SqrtUnexpanded,
    DistanceType.L1,
    DistanceType.Canberra,
    DistanceType.Linf,
    DistanceType.LpUnexpanded,
    DistanceType.JaccardExpanded,
    DistanceType.CosineExpanded,
    DistanceType.HellingerExpanded,
    DistanceType.DiceExpanded,
    DistanceType.CorrelationExpanded,
    DistanceType.RusselRaoExpanded,
    DistanceType.HammingUnexpanded,
    DistanceType.JensenShannon,
    DistanceType.KLDivergence,
    DistanceType.BrayCurtis,
]


def pairwise_distance(x: CsrMatrix, y: CsrMatrix, metric="euclidean", p: float = 2.0):
    """CSR×CSR distance matrix via block densification + dense engine."""
    m = resolve_metric(metric)
    if m not in SUPPORTED_DISTANCES:
        raise ValueError(f"metric {m} not supported for sparse inputs")
    if x.shape[1] != y.shape[1]:
        raise ValueError("column mismatch")
    xd = csr_to_dense(x).astype(jnp.float32)
    yd = csr_to_dense(y).astype(jnp.float32)
    return _pairwise_impl(xd, yd, m, metric_arg=float(p))


def knn(x: CsrMatrix, y: CsrMatrix, k: int, metric="euclidean"):
    """Sparse brute-force kNN (sparse/neighbors/brute_force.cuh): for each
    row of y... reference convention: queries=y? We follow dense brute_force:
    dataset=x, queries=y; returns (dists, idx) into x rows."""
    from raft_tpu.neighbors.brute_force import _bf_knn_impl

    m = resolve_metric(metric)
    xd = csr_to_dense(x).astype(jnp.float32)
    yd = csr_to_dense(y).astype(jnp.float32)
    return _bf_knn_impl(xd, yd, int(k), m)
