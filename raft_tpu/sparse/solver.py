"""Sparse solvers: MST (Borůvka) and Lanczos eigensolver.

Reference parity: `sparse/solver/mst.cuh` / `mst_solver.cuh` (GPU Borůvka,
the single-linkage dependency) and `sparse/solver/lanczos.cuh:68,132`
(`computeSmallestEigenvectors`/`computeLargestEigenvectors`, restarted
Lanczos on CSR — the spectral-clustering dependency).

TPU design:
  - Borůvka maps beautifully to segment-min reductions: each round every
    component picks its lightest outgoing edge (segment_min), merges via
    pointer-jumping (log-depth, all vectorized), and the loop runs inside a
    single `lax.while_loop` — no atomics, deterministic.
  - Lanczos runs on a matvec closure with full reorthogonalization in f32
    (the reference's restart machinery exists to bound memory on huge
    graphs; here ncv is a parameter and the tridiagonal eigenproblem is
    solved densely with jnp.linalg.eigh).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.sparse.formats import CooMatrix, CsrMatrix


# ---------------------------------------------------------------------------
# Borůvka MST
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_vertices",))
def _boruvka(rows, cols, weights, n_vertices: int):
    """Returns (mst_src, mst_dst, mst_weight, in_mst_mask) with fixed-size
    (n_edges,) buffers; selected edges flagged in `in_mst_mask`."""
    n_edges = rows.shape[0]
    inf = jnp.inf

    def cond(state):
        comp, in_mst, changed, it = state
        return changed & (it < n_vertices)

    # canonical undirected endpoints: (a,b) and (b,a) share (lo,hi). Used as
    # a strict tie-break so the edge order is TOTAL — with a total order,
    # Borůvka hooking can only form 2-cycles (a longer cycle would need
    # equal keys on distinct undirected edges). Staged (w, lo, hi)
    # segment-mins avoid an int64 composite (x64 is off).
    lo = jnp.minimum(rows, cols).astype(jnp.int32)
    hi = jnp.maximum(rows, cols).astype(jnp.int32)

    def body(state):
        comp, in_mst, _, it = state
        cr, cc = comp[rows], comp[cols]
        cross = cr != cc
        key = jnp.where(cross, weights, inf)
        # lightest outgoing edge per component (by source component)
        best_w = jax.ops.segment_min(key, cr, num_segments=n_vertices)
        at_min = (key == best_w[cr]) & cross
        big = jnp.int32(2**31 - 1)
        lo_cand = jnp.where(at_min, lo, big)
        best_lo = jax.ops.segment_min(lo_cand, cr, num_segments=n_vertices)
        at_lo = at_min & (lo == best_lo[cr])
        hi_cand = jnp.where(at_lo, hi, big)
        best_hi = jax.ops.segment_min(hi_cand, cr, num_segments=n_vertices)
        is_best = at_lo & (hi == best_hi[cr])
        # deterministic pick: smallest directed edge id among candidates
        eid = jnp.arange(n_edges)
        cand = jnp.where(is_best, eid, n_edges)
        pick = jax.ops.segment_min(cand, cr, num_segments=n_vertices)
        valid_pick = pick < n_edges
        pick_safe = jnp.where(valid_pick, pick, 0)
        # mark picked edges; invalid picks scatter out-of-bounds (dropped)
        newly = jnp.zeros((n_edges,), bool).at[
            jnp.where(valid_pick, pick, n_edges)
        ].set(True, mode="drop")
        in_mst = in_mst | newly
        # merge: component of src points to component of dst for picked edges;
        # invalid picks write out-of-bounds (dropped) so they can't clobber
        parent = jnp.arange(n_vertices)
        src_comp = comp[rows[pick_safe]]
        dst_comp = comp[cols[pick_safe]]
        parent = parent.at[
            jnp.where(valid_pick, src_comp, n_vertices)
        ].set(dst_comp, mode="drop")
        # break 2-cycles (a->b and b->a): root the pair at one endpoint
        p2 = parent[parent]
        vid = jnp.arange(n_vertices)
        parent = jnp.where((p2 == vid) & (parent < vid), vid, parent)
        # pointer jumping to full compression (log depth)
        def jump(_, p):
            return p[p]
        parent = lax.fori_loop(0, 32, jump, parent)
        new_comp = parent[comp]
        changed = jnp.any(new_comp != comp)
        return new_comp, in_mst, changed, it + 1

    comp0 = jnp.arange(n_vertices)
    in_mst0 = jnp.zeros((n_edges,), bool)
    comp, in_mst, _, _ = lax.while_loop(
        cond, body, (comp0, in_mst0, jnp.array(True), jnp.array(0))
    )
    return comp, in_mst


def mst(coo: CooMatrix, n_vertices: Optional[int] = None) -> CooMatrix:
    """Minimum spanning forest edges (sparse/solver/mst.cuh). Input should be
    a symmetric COO graph; output has one direction per chosen edge."""
    n = coo.shape[0] if n_vertices is None else n_vertices
    rows = jnp.asarray(coo.rows).astype(jnp.int32)
    cols = jnp.asarray(coo.cols).astype(jnp.int32)
    w = jnp.asarray(coo.vals).astype(jnp.float32)
    comp, in_mst = _boruvka(rows, cols, w, n)
    mask = np.asarray(in_mst)
    r, c, v = np.asarray(rows)[mask], np.asarray(cols)[mask], np.asarray(w)[mask]
    # dedupe undirected duplicates (a,b)/(b,a)
    lo, hi = np.minimum(r, c), np.maximum(r, c)
    key = lo.astype(np.int64) * coo.shape[1] + hi
    _, first = np.unique(key, return_index=True)
    return CooMatrix(
        jnp.asarray(r[first]), jnp.asarray(c[first]), jnp.asarray(v[first]), coo.shape
    )


# ---------------------------------------------------------------------------
# Lanczos
# ---------------------------------------------------------------------------


def lanczos(
    matvec: Callable,
    n: int,
    n_components: int,
    which: str = "smallest",
    ncv: Optional[int] = None,
    seed: int = 0,
    v0=None,
) -> Tuple[jax.Array, jax.Array]:
    """Lanczos eigensolver on a symmetric operator given as a matvec
    closure; returns (eigenvalues (k,), eigenvectors (n, k)).

    Full reorthogonalization (ncv kept modest) replaces the reference's
    implicit restarts — the spectral-clustering use cases need only a few
    extreme eigenpairs of moderately-sized Laplacians.
    """
    k = n_components
    m = min(n, ncv if ncv is not None else max(2 * k + 8, 32))
    if v0 is None:
        v0 = jax.random.normal(jax.random.PRNGKey(seed), (n,), dtype=jnp.float32)
    v0 = v0 / jnp.linalg.norm(v0)

    V = jnp.zeros((m, n), jnp.float32).at[0].set(v0)
    alphas = jnp.zeros((m,), jnp.float32)
    betas = jnp.zeros((m,), jnp.float32)

    def step(i, state):
        V, alphas, betas = state
        v = V[i]
        w = matvec(v)
        a = jnp.dot(w, v)
        w = w - a * v - jnp.where(i > 0, betas[i - 1], 0.0) * V[jnp.maximum(i - 1, 0)]
        # full reorthogonalization against all previous vectors
        proj = V @ w  # (m,)
        mask = (jnp.arange(m) <= i).astype(jnp.float32)
        w = w - (proj * mask) @ V
        b = jnp.linalg.norm(w)
        V = V.at[i + 1].set(jnp.where(b > 1e-8, w / jnp.maximum(b, 1e-30), 0.0))
        return V.at[i].set(v), alphas.at[i].set(a), betas.at[i].set(b)

    V, alphas, betas = lax.fori_loop(0, m - 1, step, (V, alphas, betas))
    # final alpha
    w_last = matvec(V[m - 1])
    alphas = alphas.at[m - 1].set(jnp.dot(w_last, V[m - 1]))

    T = jnp.diag(alphas) + jnp.diag(betas[: m - 1], 1) + jnp.diag(betas[: m - 1], -1)
    theta, S = jnp.linalg.eigh(T)
    if which == "smallest":
        sel = jnp.arange(k)
    else:
        sel = jnp.arange(m - k, m)[::-1]
    vals = theta[sel]
    vecs = (S[:, sel].T @ V).T  # (n, k)
    vecs = vecs / jnp.maximum(jnp.linalg.norm(vecs, axis=0, keepdims=True), 1e-30)
    return vals, vecs


def compute_smallest_eigenvectors(csr: CsrMatrix, k: int, seed: int = 0):
    """sparse/solver/lanczos.cuh:68 parity — smallest eigenpairs of a CSR."""
    from raft_tpu.sparse.linalg import spmv

    return lanczos(lambda v: spmv(csr, v), csr.shape[0], k, "smallest", seed=seed)


def compute_largest_eigenvectors(csr: CsrMatrix, k: int, seed: int = 0):
    from raft_tpu.sparse.linalg import spmv

    return lanczos(lambda v: spmv(csr, v), csr.shape[0], k, "largest", seed=seed)
