"""Sparse linear algebra (sparse/linalg/{add,transpose,symmetrize,norm,
spectral}.cuh + cuSparse SPMV/SPMM wrappers).

TPU design: SPMV/SPMM run as COO segment-sums (deterministic scatter-free
reductions); the Laplacian is materialized lazily as a matvec closure for
the Lanczos solver.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from raft_tpu.sparse.formats import CooMatrix, CsrMatrix, csr_to_coo, coo_to_csr


def spmv(csr: CsrMatrix, x) -> jax.Array:
    """y = A @ x via per-nnz gather + segment_sum."""
    xv = jnp.asarray(x)
    rows = csr.row_ids()
    contrib = jnp.asarray(csr.data) * xv[jnp.asarray(csr.indices)]
    return jax.ops.segment_sum(contrib, rows, num_segments=csr.shape[0])


def spmm(csr: CsrMatrix, B) -> jax.Array:
    """Y = A @ B (nnz-gather rows of B, segment-sum)."""
    b = jnp.asarray(B)
    rows = csr.row_ids()
    contrib = jnp.asarray(csr.data)[:, None] * b[jnp.asarray(csr.indices)]
    return jax.ops.segment_sum(contrib, rows, num_segments=csr.shape[0])


def transpose(csr: CsrMatrix) -> CsrMatrix:
    coo = csr_to_coo(csr)
    t = CooMatrix(coo.cols, coo.rows, coo.vals, (csr.shape[1], csr.shape[0]))
    return coo_to_csr(t)


def add(a: CsrMatrix, b: CsrMatrix) -> CsrMatrix:
    """A + B (host dedup; build-time op)."""
    from raft_tpu.sparse.ops import max_duplicates

    ca, cb = csr_to_coo(a), csr_to_coo(b)
    merged = CooMatrix(
        jnp.concatenate([jnp.asarray(ca.rows), jnp.asarray(cb.rows)]),
        jnp.concatenate([jnp.asarray(ca.cols), jnp.asarray(cb.cols)]),
        jnp.concatenate([jnp.asarray(ca.vals), jnp.asarray(cb.vals)]),
        a.shape,
    )
    return coo_to_csr(max_duplicates(merged))


def symmetrize(coo: CooMatrix, op: str = "max") -> CooMatrix:
    """Make A symmetric: combine with its transpose (sparse/linalg/
    symmetrize.cuh). op in {max, sum, mean} — 'max' is the knn-graph default."""
    import numpy as np

    r = np.concatenate([np.asarray(coo.rows), np.asarray(coo.cols)])
    c = np.concatenate([np.asarray(coo.cols), np.asarray(coo.rows)])
    v = np.concatenate([np.asarray(coo.vals), np.asarray(coo.vals)])
    key = r.astype(np.int64) * coo.shape[1] + c
    uniq, inv = np.unique(key, return_inverse=True)
    out = np.zeros(len(uniq), v.dtype)
    if op == "sum":
        np.add.at(out, inv, v)
    elif op == "max":
        np.maximum.at(out, inv, v)
    elif op == "mean":
        np.add.at(out, inv, v)
        cnt = np.zeros(len(uniq), np.int32)
        np.add.at(cnt, inv, 1)
        out = out / np.maximum(cnt, 1)
    else:
        raise ValueError(op)
    return CooMatrix(
        jnp.asarray((uniq // coo.shape[1]).astype(np.int32)),
        jnp.asarray((uniq % coo.shape[1]).astype(np.int32)),
        jnp.asarray(out),
        coo.shape,
    )


def row_norm_csr(csr: CsrMatrix, norm_type: str = "l2") -> jax.Array:
    rows = csr.row_ids()
    d = jnp.asarray(csr.data)
    if norm_type == "l2":
        return jax.ops.segment_sum(d * d, rows, num_segments=csr.shape[0])
    if norm_type == "l1":
        return jax.ops.segment_sum(jnp.abs(d), rows, num_segments=csr.shape[0])
    if norm_type == "linf":
        return jax.ops.segment_max(jnp.abs(d), rows, num_segments=csr.shape[0])
    raise ValueError(norm_type)


def laplacian_matvec(adj: CsrMatrix, normalized: bool = True) -> Callable:
    """Return v -> L@v for the (normalized) graph Laplacian
    (spectral/matrix_wrappers.hpp laplacian_matrix_t semantics)."""
    deg = spmv(adj, jnp.ones((adj.shape[1],), jnp.float32))
    if not normalized:
        def mv(v):
            return deg * v - spmv(adj, v)
        return mv
    dinv = 1.0 / jnp.sqrt(jnp.maximum(deg, 1e-12))

    def mv(v):
        return v - dinv * spmv(adj, dinv * v)

    return mv
