"""Sparse formats: COO & CSR containers + conversions.

Reference parity: owning/view CSR & COO types (core/csr_matrix.hpp,
core/coo_matrix.hpp, core/sparse_types.hpp) and format conversions
(sparse/convert/{coo,csr,dense}.cuh).

TPU design: arrays are jax.Arrays with STATIC nnz (XLA static shapes);
"growing" returns a new container. Conversions are vectorized
(searchsorted/cumsum), not per-element kernels. Genuinely sparse compute on
TPU pays gather costs, so ops that feed the MXU densify blocks on the fly
(see sparse/distance) — the formats here are the bookkeeping layer.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp


@dataclasses.dataclass
class CooMatrix:
    """COO (row, col, val) triplets; rows need not be sorted."""

    rows: jax.Array
    cols: jax.Array
    vals: jax.Array
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    def sort_by_row(self) -> "CooMatrix":
        order = jnp.lexsort((jnp.asarray(self.cols), jnp.asarray(self.rows)))
        return CooMatrix(
            jnp.asarray(self.rows)[order],
            jnp.asarray(self.cols)[order],
            jnp.asarray(self.vals)[order],
            self.shape,
        )


@dataclasses.dataclass
class CsrMatrix:
    """CSR (indptr, indices, data)."""

    indptr: jax.Array
    indices: jax.Array
    data: jax.Array
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    def row_ids(self) -> jax.Array:
        """Expand indptr to per-nnz row ids (convert/csr.cuh csr_to_coo rows)."""
        ptr = jnp.asarray(self.indptr)
        return (jnp.searchsorted(ptr, jnp.arange(self.nnz), side="right") - 1).astype(
            jnp.int32
        )


# -- conversions -------------------------------------------------------------


def coo_to_csr(coo: CooMatrix) -> CsrMatrix:
    s = coo.sort_by_row()
    n_rows = coo.shape[0]
    counts = jax.ops.segment_sum(
        jnp.ones((s.nnz,), jnp.int32), jnp.asarray(s.rows), num_segments=n_rows
    )
    indptr = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)]).astype(
        jnp.int32
    )
    return CsrMatrix(indptr, jnp.asarray(s.cols).astype(jnp.int32), s.vals, coo.shape)


def csr_to_coo(csr: CsrMatrix) -> CooMatrix:
    return CooMatrix(csr.row_ids(), jnp.asarray(csr.indices), jnp.asarray(csr.data), csr.shape)


def dense_to_csr(dense, tol: float = 0.0) -> CsrMatrix:
    """Host-side conversion (dynamic nnz is inherently host work); the
    indptr counting pass uses the native C++ runtime when available."""
    from raft_tpu import native

    d = np.asarray(dense)
    mask = np.abs(d) > tol
    rows, cols = np.nonzero(mask)
    indptr = native.coo_rows_to_indptr(rows, d.shape[0])
    if indptr is None:
        counts = np.bincount(rows, minlength=d.shape[0])
        indptr = np.zeros(d.shape[0] + 1, np.int32)
        np.cumsum(counts, out=indptr[1:])
    indptr = indptr.astype(np.int32)
    return CsrMatrix(
        jnp.asarray(indptr),
        jnp.asarray(cols.astype(np.int32)),
        jnp.asarray(d[mask]),
        d.shape,
    )


def dense_to_coo(dense, tol: float = 0.0) -> CooMatrix:
    d = np.asarray(dense)
    mask = np.abs(d) > tol
    rows, cols = np.nonzero(mask)
    return CooMatrix(
        jnp.asarray(rows.astype(np.int32)),
        jnp.asarray(cols.astype(np.int32)),
        jnp.asarray(d[mask]),
        d.shape,
    )


def csr_to_dense(csr: CsrMatrix) -> jax.Array:
    out = jnp.zeros(csr.shape, jnp.asarray(csr.data).dtype)
    return out.at[csr.row_ids(), jnp.asarray(csr.indices)].add(jnp.asarray(csr.data))


def coo_to_dense(coo: CooMatrix) -> jax.Array:
    out = jnp.zeros(coo.shape, jnp.asarray(coo.vals).dtype)
    return out.at[jnp.asarray(coo.rows), jnp.asarray(coo.cols)].add(jnp.asarray(coo.vals))
