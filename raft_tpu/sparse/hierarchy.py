"""Deprecated alias of raft_tpu.cluster.single_linkage (reference
sparse/hierarchy/single_linkage.cuh forwarding shim kept for cuML)."""

import warnings

warnings.warn(
    "raft_tpu.sparse.hierarchy is deprecated; use raft_tpu.cluster.single_linkage",
    DeprecationWarning,
    stacklevel=2,
)

from raft_tpu.cluster.single_linkage import SingleLinkageOutput, single_linkage

__all__ = ["SingleLinkageOutput", "single_linkage"]
