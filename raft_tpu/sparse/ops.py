"""Sparse structural ops (sparse/op/{sort,filter,reduce,slice,row_op}.cuh,
sparse/linalg/degree.cuh)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.sparse.formats import CooMatrix, CsrMatrix, coo_to_csr


def coo_sort(coo: CooMatrix) -> CooMatrix:
    return coo.sort_by_row()


def coo_remove_zeros(coo: CooMatrix, tol: float = 0.0) -> CooMatrix:
    """Filter explicit zeros (op/filter.cuh). Host op (dynamic nnz)."""
    v = np.asarray(coo.vals)
    keep = np.abs(v) > tol
    return CooMatrix(
        jnp.asarray(np.asarray(coo.rows)[keep]),
        jnp.asarray(np.asarray(coo.cols)[keep]),
        jnp.asarray(v[keep]),
        coo.shape,
    )


def max_duplicates(coo: CooMatrix) -> CooMatrix:
    """Deduplicate (row, col) pairs keeping the SUM of duplicates
    (op/reduce.cuh semantics). Host op (dynamic nnz)."""
    r = np.asarray(coo.rows).astype(np.int64)
    c = np.asarray(coo.cols).astype(np.int64)
    v = np.asarray(coo.vals)
    key = r * coo.shape[1] + c
    uniq, inv = np.unique(key, return_inverse=True)
    sums = np.zeros(len(uniq), v.dtype)
    np.add.at(sums, inv, v)
    return CooMatrix(
        jnp.asarray((uniq // coo.shape[1]).astype(np.int32)),
        jnp.asarray((uniq % coo.shape[1]).astype(np.int32)),
        jnp.asarray(sums),
        coo.shape,
    )


def csr_row_slice(csr: CsrMatrix, start: int, stop: int) -> CsrMatrix:
    """Row-range submatrix (op/slice.cuh). Host op."""
    ptr = np.asarray(csr.indptr)
    lo, hi = int(ptr[start]), int(ptr[stop])
    return CsrMatrix(
        jnp.asarray(ptr[start : stop + 1] - lo),
        jnp.asarray(np.asarray(csr.indices)[lo:hi]),
        jnp.asarray(np.asarray(csr.data)[lo:hi]),
        (stop - start, csr.shape[1]),
    )


def degree(coo: CooMatrix) -> jax.Array:
    """Per-row nnz counts (sparse/linalg/degree.cuh)."""
    return jax.ops.segment_sum(
        jnp.ones((coo.nnz,), jnp.int32), jnp.asarray(coo.rows), num_segments=coo.shape[0]
    )


def csr_row_op(csr: CsrMatrix, fn) -> CsrMatrix:
    """Apply fn(row_id, values)->values per nnz (op/row_op.cuh)."""
    rows = csr.row_ids()
    new_data = fn(rows, jnp.asarray(csr.data))
    return CsrMatrix(csr.indptr, csr.indices, new_data, csr.shape)
