"""Deprecated aliases (reference sparse/selection/{knn,knn_graph,
connect_components}.cuh:17-27 `#pragma message` deprecation shims kept for
cuML): `knn` now lives in raft_tpu.sparse.distance, the graph helpers in
raft_tpu.sparse.neighbors."""

import warnings

warnings.warn(
    "raft_tpu.sparse.selection is deprecated; use raft_tpu.sparse.distance.knn"
    " and raft_tpu.sparse.neighbors for the graph helpers",
    DeprecationWarning,
    stacklevel=2,
)

from raft_tpu.sparse.distance import knn
from raft_tpu.sparse.neighbors import connect_components, cross_component_nn, knn_graph

__all__ = ["knn", "knn_graph", "connect_components", "cross_component_nn"]
