"""Sparse neighbors: knn graph construction + cross-component connection.

Reference parity: `sparse/neighbors/{knn_graph,connect_components}.cuh`
(the single-linkage dependencies) and deprecated aliases under
sparse/selection/.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.sparse.formats import CooMatrix
from raft_tpu.distance.distance_types import DistanceType, resolve_metric


def knn_graph(X, k: int, metric="sqeuclidean") -> CooMatrix:
    """Symmetrized k-NN graph as COO (sparse/neighbors/knn_graph.cuh)."""
    from raft_tpu.neighbors.brute_force import knn as bf_knn
    from raft_tpu.sparse.linalg import symmetrize

    x = jnp.asarray(X, jnp.float32)
    n = x.shape[0]
    d, i = bf_knn(x, x, min(k + 1, n), metric=metric)
    # drop self column
    d = np.asarray(d)[:, 1:]
    i = np.asarray(i)[:, 1:]
    rows = np.repeat(np.arange(n, dtype=np.int32), d.shape[1])
    coo = CooMatrix(
        jnp.asarray(rows), jnp.asarray(i.reshape(-1).astype(np.int32)),
        jnp.asarray(d.reshape(-1).astype(np.float32)), (n, n),
    )
    return symmetrize(coo, op="max")


def cross_component_nn(X, labels, metric="sqeuclidean") -> Tuple[jax.Array, jax.Array]:
    """For every point, its nearest neighbor in a DIFFERENT component
    (masked 1-NN — the fused masked-L2-NN of the reference, masked_nn.cuh,
    applied to components). Returns (dists (n,), idx (n,))."""
    x = jnp.asarray(X, jnp.float32)
    l = jnp.asarray(labels).astype(jnp.int32)
    n = x.shape[0]

    bm = max(1, min(n, (1 << 21) // max(1, n)))

    nblocks = -(-n // bm)
    pad = nblocks * bm - n
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    lp = jnp.pad(l, (0, pad)) if pad else l
    yn = jnp.sum(x * x, axis=1)

    def block(inp):
        xb, lb = inp
        d = jnp.maximum(
            jnp.sum(xb * xb, 1)[:, None] + yn[None, :] - 2.0 * xb @ x.T, 0.0
        )
        same = lb[:, None] == l[None, :]
        d = jnp.where(same, jnp.inf, d)
        return jnp.min(d, axis=1), jnp.argmin(d, axis=1).astype(jnp.int32)

    dmin, idx = lax.map(block, (xp.reshape(nblocks, bm, -1), lp.reshape(nblocks, bm)))
    return dmin.reshape(-1)[:n], idx.reshape(-1)[:n]


def connect_components(X, labels, metric="sqeuclidean") -> CooMatrix:
    """Edges connecting graph components (sparse/neighbors/
    connect_components.cuh): for each component, the minimal cross-component
    edge from any of its points. Returned COO is symmetrized."""
    x = np.asarray(X, np.float32)
    l = np.asarray(labels).astype(np.int64)
    n = len(l)
    n_comp = int(l.max()) + 1 if n else 0
    if n_comp <= 1:
        return CooMatrix(
            jnp.zeros((0,), jnp.int32), jnp.zeros((0,), jnp.int32),
            jnp.zeros((0,), jnp.float32), (n, n),
        )
    dmin, idx = cross_component_nn(x, l, metric)
    dmin, idx = np.asarray(dmin), np.asarray(idx)
    rows, cols, vals = [], [], []
    for c in range(n_comp):
        members = np.nonzero(l == c)[0]
        if len(members) == 0:
            continue
        best = members[np.argmin(dmin[members])]
        rows.append(best)
        cols.append(idx[best])
        vals.append(dmin[best])
    r = np.asarray(rows, np.int32)
    c = np.asarray(cols, np.int32)
    v = np.asarray(vals, np.float32)
    return CooMatrix(
        jnp.asarray(np.concatenate([r, c])),
        jnp.asarray(np.concatenate([c, r])),
        jnp.asarray(np.concatenate([v, v])),
        (n, n),
    )
