"""Sparse formats, ops, linear algebra, distances, neighbors, solvers.

TPU-native equivalent of `cpp/include/raft/sparse/` (survey §2.11).
"""

from raft_tpu.sparse.formats import (
    CooMatrix,
    CsrMatrix,
    coo_to_csr,
    csr_to_coo,
    dense_to_csr,
    dense_to_coo,
    csr_to_dense,
    coo_to_dense,
)
from raft_tpu.sparse.ops import (
    coo_sort,
    coo_remove_zeros,
    max_duplicates,
    csr_row_slice,
    degree,
    csr_row_op,
)
from raft_tpu.sparse import linalg
from raft_tpu.sparse import distance
from raft_tpu.sparse import neighbors
from raft_tpu.sparse import solver

__all__ = [
    "CooMatrix",
    "CsrMatrix",
    "coo_to_csr",
    "csr_to_coo",
    "dense_to_csr",
    "dense_to_coo",
    "csr_to_dense",
    "coo_to_dense",
    "coo_sort",
    "coo_remove_zeros",
    "max_duplicates",
    "csr_row_slice",
    "degree",
    "csr_row_op",
    "linalg",
    "distance",
    "neighbors",
    "solver",
]
