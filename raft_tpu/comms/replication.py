"""r-way shard replication for the distributed indexes: ring placement,
deterministic failover election, device-side mirroring, and the cached
failover views the searches consult.

PR 1's degraded mode answers a rank failure by dropping its shard —
coverage falls below 1.0 and recall with it. This module upgrades the
story to LOSSLESS failover: at build time each rank's list tables are
mirrored onto its replica holders (ring placement — rank i also hosts
replicas of ranks i-1..i-(r-1)'s shards, so r total copies of every
shard exist and any r-1 simultaneous failures leave a survivor); at
search time, `failover_view` consults the `RankHealth` mask and, for
every unhealthy rank with a surviving holder, activates EXACTLY ONE
holder (deterministic primary-order election: the first healthy rank in
u+1, u+2, ... order) whose copy re-materializes the lost shard into the
search's input tables via a static ppermute — the merge then sees the
identical per-rank candidate blocks a fully-healthy mesh produces, so
results are BIT-IDENTICAL with coverage 1.0.

Mirrors and patches are XLA collectives over the mesh (ppermute rides
ICI/DCN; EQuARX, arXiv 2506.17615, is the cost argument for keeping
redundant copies coherent this way), so they work on single-controller
and process-spanning meshes alike. The patched view is cached per
failure pattern: the first degraded search after a failure pays one
ppermute repair-gather, every subsequent search costs exactly what a
healthy search costs (no extra replica scans in the hot path).

Memory cost is the classic r-way trade: each rank holds its own shard
plus r-1 mirror copies — r x index memory total (r=2 doubles it). See
docs/using_comms.md "Replication & recovery" for the placement diagram
and the r-vs-overhead table.

`core.faults` site "replica.stale": a `kill_rank` fault at this site
declares a rank's HOSTED REPLICA COPIES unusable (stale mirror — e.g. it
missed an extend) without killing the rank itself; elections skip stale
holders, and a shard whose every holder is dead-or-stale falls back to
the PR 1 degraded path (or checkpoint rehydration in
`recovery.repair`).
"""

from __future__ import annotations

import copy
import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.comms.comms import Comms
from raft_tpu.comms.mnmg_common import _cached_wrapper, wrapper_key

STALE_SITE = "replica.stale"


@dataclasses.dataclass(frozen=True)
class ReplicaPlacement:
    """Deterministic ring placement of r copies of every shard over a
    `world`-rank mesh: rank i's PRIMARY shard is mirrored onto holders
    i+1, ..., i+(r-1) (mod world); equivalently rank i HOSTS replica
    slot m of rank (i-1-m)'s shard. r=1 means no replication."""

    world: int
    r: int

    def __post_init__(self):
        if not (1 <= self.r <= self.world):
            raise ValueError(
                f"replication factor r={self.r} must be in [1, world="
                f"{self.world}]"
            )

    def holders(self, rank: int) -> Tuple[int, ...]:
        """Ranks holding a replica of `rank`'s shard, in election
        (primary) order: rank+1 first."""
        return tuple((rank + 1 + m) % self.world for m in range(self.r - 1))

    def hosted(self, rank: int) -> Tuple[int, ...]:
        """Shard owners whose replicas `rank` hosts; index in the tuple
        is the replica SLOT: slot m holds rank (rank-1-m)'s shard."""
        return tuple((rank - 1 - m) % self.world for m in range(self.r - 1))

    def slot(self, holder: int, shard: int) -> int:
        """Replica slot of `shard`'s copy on `holder` (raises if holder
        does not host it)."""
        m = (holder - 1 - shard) % self.world
        if not (0 <= m < self.r - 1):
            raise ValueError(
                f"rank {holder} holds no replica of shard {shard} "
                f"(r={self.r})"
            )
        return m

    def elect(self, shard: int, health,
              stale: Tuple[int, ...] = ()) -> Optional[int]:
        """Deterministic primary-order election: the first HEALTHY,
        non-stale holder of `shard` in ring order, or None when no
        survivor remains (the shard is lost to failover — degraded mode
        or checkpoint recovery take over)."""
        for h in self.holders(shard):
            if bool(health.mask[h]) and h not in stale:
                return h
        return None

    def assignment(self, health,
                   stale: Tuple[int, ...] = ()) -> Dict[int, int]:
        """{dead_rank: elected_holder} for every unhealthy rank with a
        surviving replica holder (identical on every caller — the
        election is a pure function of (placement, mask, stale))."""
        out: Dict[int, int] = {}
        for u in range(self.world):
            if bool(health.mask[u]):
                continue
            h = self.elect(u, health, stale=stale)
            if h is not None:
                out[int(u)] = int(h)
        return out


def stale_holders(plan: Optional[faults.FaultPlan] = None) -> Tuple[int, ...]:
    """Ranks whose hosted replica copies the (installed or passed) fault
    plan declares stale — `kill_rank` faults at site "replica.stale"."""
    plan = plan if plan is not None else faults.active_plan()
    if plan is None:
        return ()
    return plan.killed_ranks(STALE_SITE)


@dataclasses.dataclass
class ShardReplicas:
    """The mirror state attached to a Distributed* index: `tables` maps
    each replicated primary attribute name to its (R, r-1, ...) sharded
    mirror array (slot m of rank j = rank (j-1-m)'s primary block), and
    `_views` caches failover views per failure pattern."""

    placement: ReplicaPlacement
    tables: Dict[str, Any]
    _views: dict = dataclasses.field(default_factory=dict)

    @property
    def r(self) -> int:
        return self.placement.r


def _mirror_fn(comms: Comms, r: int, ndim: int, dtype, qcfg=None):
    """One compiled mirror program per (mesh, r, rank): stacks the r-1
    ring-shifted copies of a (R, ...) rank-major table into the
    (R, r-1, ...) replica layout (out[j, m] = in[(j-1-m) % R]).

    With a resolved `qcfg` (comms/quantized.QuantConfig) on a FLOAT
    table, the fan-out ships the block-quantized encoding instead of the
    raw rows — encode once, ppermute the int8 payload + f32 scale
    sidecar to each holder, decode there — cutting the r-1-copy mirror
    wire ~4x. The stored replica then carries codec error, so a failover
    that re-materializes from it is no longer bit-identical (see
    `mirror_table`). Integer tables always travel exact."""
    R = comms.get_size()
    axis = comms.axis

    def build():
        @jax.jit
        def run(a):
            def body(a):  # a: (1, ...) — this rank's primary block
                outs = []
                if qcfg is not None and qcfg.mode == "int8":
                    from raft_tpu.comms import quantized

                    rank = lax.axis_index(axis)
                    qa, sc = quantized.quantize_blocks(a, qcfg.block)
                    sc = faults.corrupt_in_trace(
                        quantized.ENCODE_SITE, sc, rank)
                    for m in range(r - 1):
                        perm = [(i, (i + 1 + m) % R) for i in range(R)]
                        qy = lax.ppermute(qa, axis, perm)
                        scy = lax.ppermute(sc, axis, perm)
                        scy = faults.corrupt_in_trace(
                            quantized.DECODE_SITE, scy, rank)
                        outs.append(quantized.dequantize_blocks(
                            qy, scy, a.shape, a.dtype))
                elif qcfg is not None and qcfg.mode == "bf16":
                    ab = a.astype(jnp.bfloat16)
                    for m in range(r - 1):
                        perm = [(i, (i + 1 + m) % R) for i in range(R)]
                        outs.append(lax.ppermute(ab, axis, perm)
                                    .astype(a.dtype))
                else:
                    for m in range(r - 1):
                        perm = [(i, (i + 1 + m) % R) for i in range(R)]
                        outs.append(lax.ppermute(a, axis, perm))
                return jnp.stack(outs, axis=1)  # (1, r-1, ...)

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=P(axis, *([None] * (ndim - 1))),
                out_specs=P(axis, *([None] * ndim)), check_vma=False,
            )(a)

        return run

    return _cached_wrapper(
        wrapper_key("replication_mirror", comms, r, ndim,
                    jnp.dtype(dtype).name, qcfg),
        build,
    )


def mirror_table(comms: Comms, arr, r: int, quantization=None):
    """Mirror a (R, ...) rank-major sharded table onto its ring replica
    holders; returns the (R, r-1, ...) sharded replica array.

    `quantization` (None | "off" | "int8" | "bf16" | "auto" | resolved
    QuantConfig — comms/quantized.resolve semantics) opts the fan-out
    into block-scaled wire transport. The DEFAULT (None) keeps the
    mirror byte-exact, which is what the lossless-failover contract
    ("results BIT-IDENTICAL with coverage 1.0") rests on: a quantized
    mirror re-materializes a failed shard to within the codec tolerance
    instead — a recall-neutral wire saving at build/extend time for
    callers who accept approximate failover. Integer tables (codes,
    slot_gids) are never quantized regardless."""
    qcfg = None
    if quantization is not None and quantization != "off":
        from raft_tpu.comms import quantized

        qcfg = quantized.resolve(quantization)
    if qcfg is not None and not jnp.issubdtype(
            jnp.dtype(arr.dtype), jnp.floating):
        qcfg = None  # int tables always exact (the failover id contract)
    if qcfg is not None and obs.enabled():
        from raft_tpu.comms import quantized

        n = 1
        for dim in arr.shape:
            n *= int(dim)
        n //= comms.get_size()  # per-rank primary block
        if qcfg.mode == "int8":
            wire = (r - 1) * quantized.packet_bytes(n, qcfg.block)
            wdt = "int8+f32-scales"
        else:
            wire = (r - 1) * n * 2
            wdt = "bfloat16"
        obs.collective("mirror", arr, axis=comms.axis, world=comms.get_size(),
                       wire_bytes=wire, wire_dtype=wdt)
    return _mirror_fn(comms, r, arr.ndim, arr.dtype, qcfg)(arr)


def _patch_fn(comms: Comms, moves: Tuple[Tuple[int, int, int], ...],
              ndim: int, dtype):
    """One compiled failover-patch program per (mesh, assignment): for
    each static (dead, holder, slot) move, ppermute the holder's replica
    copy to the dead rank, which takes it as its primary block. Healthy
    ranks pass their primary through untouched."""
    axis = comms.axis
    by_slot: Dict[int, list] = {}
    for dead, holder, m in moves:
        by_slot.setdefault(m, []).append((holder, dead))

    def build():
        @jax.jit
        def run(primary, rep):
            def body(p, rp):  # p: (1, ...); rp: (1, r-1, ...)
                rank = lax.axis_index(axis)
                out = p
                for m, pairs in sorted(by_slot.items()):
                    moved = lax.ppermute(rp[:, m], axis, pairs)
                    is_dest = functools.reduce(
                        jnp.logical_or,
                        [rank == u for _, u in pairs])
                    out = jnp.where(is_dest, moved, out)
                return out

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(axis, *([None] * (ndim - 1))),
                          P(axis, *([None] * ndim))),
                out_specs=P(axis, *([None] * (ndim - 1))), check_vma=False,
            )(primary, rep)

        return run

    return _cached_wrapper(
        wrapper_key("replication_patch", comms, moves, ndim,
                    jnp.dtype(dtype).name),
        build,
    )


def patch_tables(comms: Comms, primary, rep,
                 moves: Tuple[Tuple[int, int, int], ...]):
    """Re-materialize dead ranks' primary blocks from their elected
    holders' replica copies (`moves` = static (dead, holder, slot)
    triples). Returns the patched (R, ...) sharded table — bit-identical
    blocks to the pre-failure primaries."""
    return _patch_fn(comms, moves, primary.ndim, primary.dtype)(primary, rep)


# -- index integration -------------------------------------------------

def _replicated_attrs(index) -> Tuple[str, ...]:
    """The primary table attributes a Distributed* index mirrors (the
    rank-major sharded arrays a shard failure loses)."""
    if hasattr(index, "aux"):  # DistributedIvfRabitq
        return ("codes", "aux", "slot_gids")
    if hasattr(index, "codes"):  # DistributedIvfPq
        return ("codes", "slot_gids")
    return ("list_data", "slot_gids")  # DistributedIvfFlat


def replicate_index(index, r: int, quantization=None):
    """Attach r-way ring replicas to a built/loaded Distributed* index
    (idempotent per r; r=1 detaches). The mirrors are device-side
    ppermute copies of the primary tables — every rank ships its block
    to its r-1 holders once, here, and failover later costs one patch
    ppermute per failure pattern.

    `quantization` opts the FLOAT mirror tables into block-scaled wire
    transport (see `mirror_table`); the default keeps every mirror
    byte-exact and the failover contract bit-identical."""
    comms = index.comms
    if r == 1:
        index.replicas = None
        return index
    placement = ReplicaPlacement(comms.get_size(), int(r))
    existing = getattr(index, "replicas", None)
    if existing is not None and existing.placement == placement:
        return index
    tables = {
        name: mirror_table(comms, getattr(index, name), placement.r,
                           quantization=quantization)
        for name in _replicated_attrs(index)
    }
    index.replicas = ShardReplicas(placement, tables)
    if obs.enabled():
        obs.event("replication", action="mirror", r=placement.r,
                  world=placement.world)
    return index


def _health_key(health, stale: Tuple[int, ...]) -> tuple:
    return (health.mask.tobytes(), stale)


def failover_view(index, health):
    """The search-time entry point: given a (possibly degraded)
    `RankHealth`, return `(search_index, effective_health,
    repaired_ranks)`.

    - healthy mask / no replicas: the index and mask pass through
      unchanged (zero overhead on the hot path).
    - degraded with surviving holders: returns a cached VIEW of the
      index whose primary tables have each dead rank's shard
      re-materialized from its elected holder's replica copy, plus an
      effective mask in which those ranks count healthy — the merge
      masks only the genuinely-lost shards, coverage climbs back to
      1.0, and results are bit-identical to the all-healthy run.
      Failures beyond r-1 (no surviving holder) stay masked: the PR 1
      degraded path still engages for them.
    """
    replicas = getattr(index, "replicas", None)
    # the patch ppermute below is guarded by health state, which is
    # controller-uniform by protocol (every controller feeds its mask
    # from the same probe/plan) — all controllers branch together
    if health is None or not health.degraded or replicas is None:  # raftlint: disable=collective-divergence
        return index, health, ()
    if health.world != replicas.placement.world:  # raftlint: disable=collective-divergence
        # mis-sized mask: pass through for _resolve_health's loud reject
        return index, health, ()
    from raft_tpu.comms.resilience import RankHealth

    stale = stale_holders()
    key = _health_key(health, stale)
    cached = replicas._views.get(key)
    if cached is not None:
        view, eff_mask, repaired = cached
        return view, RankHealth(eff_mask.copy()), repaired
    assignment = replicas.placement.assignment(health, stale=stale)
    if not assignment:
        return index, health, ()
    comms = index.comms
    moves = tuple(sorted(
        (u, h, replicas.placement.slot(h, u))
        for u, h in assignment.items()
    ))
    view = copy.copy(index)
    for name in _replicated_attrs(index):
        setattr(view, name, patch_tables(
            comms, getattr(index, name), replicas.tables[name], moves))
    _reset_derived_stores(view)
    view.replicas = None  # views never re-enter failover
    eff_mask = np.array(health.mask, copy=True)
    for u in assignment:
        eff_mask[u] = True
    repaired = tuple(sorted(assignment))
    for u, h in sorted(assignment.items()):
        obs.event("failover", rank=u, holder=h,
                  slot=replicas.placement.slot(h, u))
    # each cached view pins FULL-SIZE patched copies of the primary
    # tables on device — bound by entries-worth-of-bytes, not count: keep
    # only the current pattern plus one predecessor (masks transition
    # old -> new during a failure/heal; anything older is dead weight
    # that would stack whole index copies during an instability event)
    while len(replicas._views) >= 2:
        replicas._views.pop(next(iter(replicas._views)))
    replicas._views[key] = (view, eff_mask, repaired)
    return view, RankHealth(eff_mask.copy()), repaired


def failover_sharded_rows(comms: Comms, xs, replication: int, health):
    """Failover for the brute-force kNN's row-sharded dataset. Unlike
    the IVF indexes (device-resident tables that must be
    re-materialized from device mirror copies), `knn` re-ships its
    shards from the caller's host dataset on EVERY call — each rank's
    block is already a fresh copy of trusted bytes, so the dataset
    itself is the replica source and the ring placement only has to
    decide WHICH dead ranks are coverable: for each unhealthy rank with
    a healthy, non-stale ring holder, the election succeeds and the
    rank serves at full fidelity (its mask bit flips in the effective
    health); past r-1 failures the election fails and the degraded path
    masks the shard exactly as before. No device mirror/patch round
    trip runs — it would ppermute r-1 dataset copies per degraded call
    only to reproduce `xs` byte-for-byte. Returns
    `(xs, effective_health, repaired_ranks)` — pass-through when
    healthy or unreplicated."""
    if replication <= 1:
        return xs, health, ()
    placement = ReplicaPlacement(comms.get_size(), int(replication))
    if (health is None or not health.degraded
            or health.world != placement.world):
        return xs, health, ()
    from raft_tpu.comms.resilience import RankHealth

    stale = stale_holders()
    assignment = placement.assignment(health, stale=stale)
    if not assignment:
        return xs, health, ()
    eff_mask = np.array(health.mask, copy=True)
    for u in assignment:
        eff_mask[u] = True
    for u, h in sorted(assignment.items()):
        obs.event("failover", rank=u, holder=h,
                  slot=placement.slot(h, u))
    return xs, RankHealth(eff_mask), tuple(sorted(assignment))


def _reset_derived_stores(index) -> None:
    """Clear the lazily-built derived stores a table patch invalidates
    (they rebuild deterministically from the patched tables, so the
    rebuilt values match a never-failed index bit for bit)."""
    for name in ("recon8", "recon_scale", "recon_norm", "resid_bf16",
                 "resid_norm", "slot_gids_pad", "_refine_cache"):
        if hasattr(index, name):
            setattr(index, name, None)
