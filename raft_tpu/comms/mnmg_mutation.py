"""Rank-local online mutation for the distributed indexes.

The Distributed* layouts carry GLOBAL row ids in `slot_gids` (-1 =
pad), and every per-rank engine masks candidates to the worst score
where the gid table reads -1 — the same mechanism the single-chip
tombstones ride (neighbors/mutation). So MNMG mutation is a pure
elementwise transform of the gid tables:

- **delete**: gids in the victim set flip to -1 — on the primary
  `slot_gids`, on the r-way replica mirror (`replicas.tables`), and on
  the host mirrors (`host_gids`, `local_gids`). An elementwise map
  commutes with the ring-placement ppermute that built the mirrors, so
  every copy stays coherent with NO collective: each rank masks the
  blocks it already holds.
- **upsert**: delete the old ids, append through the existing
  distributed extend (which re-mirrors via `_carry_replication`), then
  remap the fresh tail gid block [old_n, old_n+n) onto the caller's
  ids — again elementwise on primaries + mirrors + host mirrors.

Payload tables (`list_data`/`codes`/`aux`) are untouched by deletes:
dead slots keep their rows but can never win a merge (their gid is the
pad sentinel), exactly the single-chip mask-don't-move contract.
Cached failover views (`replicas._views`) and the gid-derived fused
stores (`slot_gids_pad`) are dropped — they rebuild from the mutated
tables on the next degraded/fused search.

Coherence gate: the serve layer defers mutation while the health mask
is degraded (`MnmgSearcher.maybe_apply_mutations`), so a masked rank
never misses a mutation — by the time batches drain, every rank's
primary AND hosted mirrors are present to transform.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp

from raft_tpu import obs

#: gid-derived lazy stores that must rebuild after a gid transform
_GID_DERIVED = ("slot_gids_pad", "_refine_cache", "_id_bound")


def _clone(index):
    import copy

    out = copy.copy(index)
    rep = getattr(index, "replicas", None)
    if rep is not None:
        import dataclasses

        out.replicas = dataclasses.replace(
            rep, tables=dict(rep.tables), _views={})
    return out


def _map_gids(index, fn, host_fn):
    """Apply an elementwise gid transform to every copy of the gid
    tables: device primary, device replica mirror, host mirrors.
    `fn` maps a jnp int32 array, `host_fn` a numpy int32 array."""
    out = _clone(index)
    out.slot_gids = fn(index.slot_gids)
    rep = getattr(out, "replicas", None)
    if rep is not None and "slot_gids" in rep.tables:
        rep.tables["slot_gids"] = fn(rep.tables["slot_gids"])
    for name in ("host_gids", "local_gids"):
        tbl = getattr(index, name, None)
        if tbl is not None:
            setattr(out, name, host_fn(np.asarray(tbl)))
    for name in _GID_DERIVED:
        if hasattr(out, name):
            setattr(out, name, None)
    return out


def delete(index, ids):
    """Mask every slot holding one of `ids` to the pad sentinel across
    all copies; returns the new index (the input object is untouched —
    in-flight searches keep their gid tables, zero-dip)."""
    ids = np.unique(np.asarray(ids, np.int64).ravel())
    dev_ids = jnp.asarray(ids, jnp.int32)

    def fn(g):
        return jnp.where(jnp.isin(g, dev_ids), jnp.int32(-1), g)

    def host_fn(g):
        return np.where(np.isin(g, ids), -1, g).astype(g.dtype)

    out = _map_gids(index, fn, host_fn)
    if obs.enabled():
        obs.counter("mutation.tombstones").inc(int(ids.size))
        obs.event("mutation", op="delete", index_kind="mnmg", n=int(ids.size))
    return out


def _remap_tail(index, old_n: int, new_ids: np.ndarray):
    """Rewrite the freshly-appended gid block [old_n, old_n + n) onto
    the caller's ids, every copy. Extend assigns the block in batch
    order (gid old_n + i is batch row i), so the lookup is a gather."""
    lut = np.asarray(new_ids, np.int64)
    n = lut.shape[0]
    dev_lut = jnp.asarray(lut, jnp.int32)

    def fn(g):
        fresh = (g >= old_n) & (g < old_n + n)
        src = jnp.clip(g - old_n, 0, max(n - 1, 0))
        return jnp.where(fresh, dev_lut[src], g)

    def host_fn(g):
        fresh = (g >= old_n) & (g < old_n + n)
        src = np.clip(g - old_n, 0, max(n - 1, 0))
        return np.where(fresh, lut[src], g).astype(g.dtype)

    return _map_gids(index, fn, host_fn)


def upsert(index, kind: str, vectors, ids: Optional[np.ndarray] = None):
    """Distributed upsert: retire the old ids, append through the
    distributed extend (replicas re-mirror inside it), then remap the
    fresh tail gids onto the caller's ids. `ids=None` is a pure insert
    (extend's own fresh gids stand). Returns the new index."""
    from raft_tpu.comms.mnmg_ivf_build import ivf_flat_extend, ivf_pq_extend

    if kind == "ivf_flat":
        extend = ivf_flat_extend
    elif kind == "ivf_pq":
        extend = ivf_pq_extend
    else:
        # DistributedIvfRabitq has no distributed extend yet (ROADMAP
        # 5c) — refuse loudly instead of silently dropping the rows
        raise NotImplementedError(
            f"distributed upsert is not available for {kind!r}: no "
            "distributed extend exists (deletes work; rebuild or use "
            "the single-chip mutation path for upserts)")
    vectors = np.asarray(vectors, np.float32)
    if ids is not None:
        ids = np.asarray(ids, np.int64).ravel()
        if ids.shape[0] != vectors.shape[0]:
            raise ValueError(
                f"{vectors.shape[0]} vectors but {ids.shape[0]} ids")
        index = delete(index, ids)
    old_n = int(index.n)
    out = extend(index, vectors)
    if ids is not None:
        out = _remap_tail(out, old_n, ids)
    if obs.enabled():
        obs.counter("mutation.upserts").inc(int(vectors.shape[0]))
        obs.event("mutation", op="upsert", index_kind="mnmg",
                  n=int(vectors.shape[0]))
    return out


def apply_batch(index, kind: str, batch: tuple):
    """Apply one `neighbors.mutation.MutationFeed` batch to a
    distributed index, returning the new index. Rebalance is a no-op at
    MNMG scale for now: deletes leave masked holes that the per-rank
    stores carry until a rebuild (the compaction job is single-chip)."""
    op = batch[0]
    if op == "upsert":
        return upsert(index, kind, batch[1], batch[2])
    if op == "delete":
        return delete(index, batch[1])
    if op == "rebalance":
        return index
    raise ValueError(f"unknown mutation op {op!r}")
