"""Distributed IVF-Flat / IVF-PQ index types, builds, extends, and the
single-chip bridge (distribute_index)."""


import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu import obs
from raft_tpu.comms.comms import Comms
from raft_tpu.comms.mnmg_common import (
    _cached_wrapper,
    wrapper_key,
    _codebook_cap,
    _distributed_id_bound,
    _gather_replicated,
    _local_layout,
    _local_shard_rows_host,
    _metric_name,
    _pack_local,
    _pq_geometry,
    _rank_valid_counts,
    _ranks_by_proc,
    _rotate_fn,
    _shard_rows,
    _train_codebooks,
    _valid_global_positions,
    _valid_weights,
)
from raft_tpu.comms.mnmg_kmeans import _kmeans_fit_sharded, _spmd_predict


def distribute_index(comms: Comms, index):
    """Bridge a SINGLE-CHIP index onto the mesh for distributed serving
    (build once on one chip — or load from a single-chip checkpoint —
    then search across every rank). Each list's slots are block-split
    across ranks, so every rank scans its share of every probed list and
    the usual top-k merge applies. Accepts `ivf_flat.Index` and
    `ivf_pq.Index`; returns the matching Distributed* index. Searches
    return the same ids as the single-chip index. The slot-block layout
    is not a contiguous per-rank row range and gids may be arbitrary
    caller ids, so refine_dataset and extend are rejected on the result
    (extend the single-chip index and re-distribute)."""
    R = comms.get_size()
    slots = np.asarray(index.slot_rows)
    n_lists, max_list = slots.shape
    mlr = max(1, -(-max_list // R))
    pad = R * mlr - max_list
    slots_p = np.pad(slots, ((0, 0), (0, pad)), constant_values=-1)
    gids_r = np.ascontiguousarray(
        slots_p.reshape(n_lists, R, mlr).transpose(1, 0, 2)
    )
    if getattr(index, "source_ids", None) is not None:
        src = np.asarray(index.source_ids)
        gids_r = np.where(
            gids_r >= 0, src[np.clip(gids_r, 0, len(src) - 1)], -1
        ).astype(np.int32)
    sizes = (gids_r >= 0).sum(axis=2).astype(np.int32)  # (R, n_lists)

    def split_payload(tbl):
        t = np.asarray(tbl)
        tp = np.pad(t, ((0, 0), (0, pad)) + ((0, 0),) * (t.ndim - 2))
        perm = (1, 0, 2) + (() if t.ndim == 2 else (3,))
        return np.ascontiguousarray(
            tp.reshape((n_lists, R, mlr) + t.shape[2:]).transpose(perm)
        )

    if hasattr(index, "codes"):  # ivf_pq.Index
        return DistributedIvfPq(
            comms,
            index.params,
            comms.replicate(np.asarray(index.rotation)),
            comms.replicate(np.asarray(index.centers)),
            comms.replicate(np.asarray(index.pq_centers)),
            _place_rank_major(comms, split_payload(index.codes)),
            _place_rank_major(comms, gids_r),
            int(index.size),
            host_gids=None if comms.spans_processes() else gids_r,
            list_sizes=None if comms.spans_processes() else sizes,
            bridged=True,
        )
    return DistributedIvfFlat(
        comms,
        index.params,
        comms.replicate(np.asarray(index.centers)),
        _place_rank_major(comms, split_payload(index.list_data)),
        _place_rank_major(comms, gids_r),
        int(index.size),
        host_gids=None if comms.spans_processes() else gids_r,
        list_sizes=None if comms.spans_processes() else sizes,
        bridged=True,
    )


def _place_rank_major(comms: Comms, host_arr: np.ndarray):
    """Shard a (R, ...) rank-major host table onto the mesh rank axis —
    on a process-spanning mesh each controller contributes the blocks of
    its own mesh ranks (checkpoint loads assume a shared filesystem, the
    standard multi-host checkpoint contract)."""
    if not comms.spans_processes():
        # keep host numpy as-is: shard() transfers per-shard, so multi-GB
        # tables never land whole on the default device
        return comms.shard(host_arr, axis=0)
    my = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
    return jax.make_array_from_process_local_data(
        comms._sharding(host_arr.ndim, 0), np.ascontiguousarray(host_arr[my])
    )

class DistributedIvfFlat:
    """Data-parallel IVF-Flat: global coarse centers (distributed k-means),
    per-rank list-major stores over the local shard, searched SPMD + merged.

    list_data (R, n_lists, max_list, d) and slot_gids (R, n_lists, max_list)
    are sharded on axis 0; slot_gids holds GLOBAL dataset row ids (-1 pad),
    so shard-local search results merge without id translation. Host
    mirrors (`host_gids`, `list_sizes`) enable O(n_new) `ivf_flat_extend`."""

    def __init__(self, comms, params, centers, list_data, slot_gids, n,
                 host_gids=None, list_sizes=None, bridged: bool = False,
                 local_gids=None, local_sizes=None):
        self.comms = comms
        self.params = params
        self.centers = centers
        self.list_data = list_data
        self.slot_gids = slot_gids
        self.n = n
        self.host_gids = host_gids
        self.list_sizes = list_sizes
        # per-PROCESS mirrors of this controller's rank shards — what a
        # *_build_local index keeps instead of the global host mirrors,
        # enabling the collective `ivf_flat_extend_local`
        self.local_gids = local_gids
        self.local_sizes = local_sizes
        # fused-scan derived store (engine="pallas"), built lazily:
        # lane-padded bf16 residuals + norms + padded gid view, plus the
        # compiled candidate-buffer width (grown monotonically with k —
        # see mnmg_ivf_search._build_distributed_resid)
        self.resid_bf16 = None
        self.resid_norm = None
        self.slot_gids_pad = None
        self.fused_kb = None
        # bridged = built by distribute_index from a single-chip index:
        # slot gids may be arbitrary caller ids (not 0..n-1), so extend's
        # id assignment could collide — extend the single-chip index and
        # re-distribute instead
        self.bridged = bridged
        # r-way ring replica mirrors (comms/replication.py): attached by
        # replicate_index / build(replication=); searches fail over to
        # them losslessly when the health mask degrades
        self.replicas = None
        self._id_bound = None

    @property
    def id_bound(self) -> int:
        """One past the largest global id a search can return — the id
        space a `prefilter` must cover (== n except for bridged indexes,
        whose gids may be arbitrary caller ids). Cached per instance
        (extends return new indexes)."""
        if self._id_bound is None:
            self._id_bound = _distributed_id_bound(self)
        return self._id_bound


def _maybe_replicate(index, replication: int):
    """Attach build-time ring mirrors when `replication` > 1 (one
    ppermute fan-out of the just-built tables; comms/replication.py)."""
    if int(replication) > 1:
        from raft_tpu.comms.replication import replicate_index

        replicate_index(index, int(replication))
    return index


def _carry_replication(old_index, new_index):
    """Extends return fresh index objects; re-mirror them at the source
    index's replication factor so a replicated index never silently
    loses (or serves stale) failover copies across an extend."""
    rep = getattr(old_index, "replicas", None)
    if rep is not None:
        from raft_tpu.comms.replication import replicate_index

        replicate_index(new_index, rep.r)
    return new_index


@obs.spanned("mnmg.ivf_flat_build")
def ivf_flat_build(comms: Comms, params, dataset, seed: int = 0,
                   replication: int = 1) -> DistributedIvfFlat:
    """Distributed IVF-Flat build: global coarse centers via distributed
    Lloyd EM, per-rank list stores filled SPMD from the row shards (the
    host only handles labels and slot tables — no host-side list-major
    copy of the dataset). `replication` > 1 mirrors each rank's list
    tables onto its r-1 ring replica holders at build time (r x memory)
    so searches fail over losslessly through up to r-1 rank failures."""
    x = np.asarray(dataset, np.float32)
    n, d = x.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > dataset rows {n}")
    r = comms.get_size()

    # one H2D shard of the dataset feeds training, assignment AND packing
    xs, _, per = _shard_rows(comms, x)
    w = comms.shard(_valid_weights(n, per, r), axis=0)
    rng = np.random.default_rng(seed)
    sub = x[rng.choice(n, min(n, max(params.n_lists * 8, 1024)), replace=False)]
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    centers0 = _kmeans_plusplus(jax.random.PRNGKey(seed), jnp.asarray(sub),
                                params.n_lists)
    centers, _, _ = _kmeans_fit_sharded(
        comms, xs, w, comms.replicate(centers0),
        max_iter=params.kmeans_n_iters, metric_name=_metric_name(params.metric),
        balance=True, seed=seed, n_valid=n,
    )
    labels = np.asarray(_spmd_predict(comms, xs, centers))[: n]

    local_tbl, gids, sizes, _ = _pack_rank_tables(labels, n, per, r, params.n_lists)
    tbl_sh = comms.shard(jnp.asarray(local_tbl), axis=0)
    ldata = _spmd_pack_rows(comms, xs, tbl_sh, per, jnp.float32)
    return _maybe_replicate(DistributedIvfFlat(
        comms,
        params,
        comms.replicate(jnp.asarray(centers)),
        ldata,
        comms.shard(jnp.asarray(gids), axis=0),
        n,
        host_gids=gids,
        list_sizes=sizes,
    ), replication)

def _pack_local_tables(comms: Comms, labels_local: np.ndarray,
                       valid_counts: np.ndarray, counts: np.ndarray,
                       per: int, n_lists: int):
    """Per-process slot-table packing for the *_local builds: each process
    packs its own ranks' lists from its local labels (no host ever sees
    global labels), agrees on the global list width, and stamps slot gids
    with CALLER row ids (position in the process-order concatenation of
    the partitions — the shard_from_local convention). Returns
    (tbl_sh, gids_sh, gids_local, sizes_local): the first two sharded on
    the rank axis, the last two this process's host mirrors
    ((lranks, n_lists, max_list) gid table and (lranks, n_lists) fill
    counts) that make `*_extend_local` O(n_new)."""
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    pi = jax.process_index()
    my_ranks = _ranks_by_proc(comms.mesh).get(pi, [])
    lranks = len(my_ranks)
    packed = []
    my_max = 1
    for l, j in enumerate(my_ranks):
        nv = int(valid_counts[j])
        t, _ = _pack_lists(labels_local[l * per : l * per + nv], n_lists)
        packed.append(t.astype(np.int32))
        my_max = max(my_max, t.shape[1])
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        all_max = np.asarray(
            multihost_utils.process_allgather(jnp.asarray([my_max]), tiled=True)
        )
        max_list = int(all_max.max())
    else:
        max_list = my_max
    proc_offset = int(np.asarray(counts[:pi], np.int64).sum())
    local_tbl = np.full((lranks, n_lists, max_list), -1, np.int32)
    gids_local = np.full((lranks, n_lists, max_list), -1, np.int32)
    sizes_local = np.zeros((lranks, n_lists), np.int32)
    for l, t in enumerate(packed):
        local_tbl[l, :, : t.shape[1]] = t
        valid = t >= 0
        gids_local[l, :, : t.shape[1]][valid] = proc_offset + l * per + t[valid]
        sizes_local[l] = valid.sum(axis=1).astype(np.int32)
    return (
        comms.shard_from_local(local_tbl, axis=0),
        comms.shard_from_local(gids_local, axis=0),
        gids_local,
        sizes_local,
    )


def ivf_flat_build_local(
    comms: Comms, params, local_dataset, seed: int = 0,
    replication: int = 1,
) -> DistributedIvfFlat:
    """Distributed IVF-Flat build where each controller contributes its
    OWN data partition (collective; the per-worker-partition raft-dask
    model). Coarse centers train with the distributed balanced EM over
    every process's rows; each process packs its ranks' list tables from
    its local labels, so no host ever materializes global labels. The
    returned index searches exactly like ivf_flat_build's (the index
    arrays are global); grow it with the collective
    `ivf_flat_extend_local` (`ivf_flat_extend`/save need the single-
    controller host mirrors and reject these indexes)."""
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    local = np.asarray(local_dataset, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    n = int(counts.sum())
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > total rows {n}")
    xp, wl = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    w = comms.shard_from_local(wl, axis=0)
    valid_counts = _rank_valid_counts(comms, counts, per)

    gpos = _valid_global_positions(comms, counts, per)
    rng = np.random.default_rng(seed)
    sel = gpos[rng.choice(n, min(n, max(params.n_lists * 8, 1024)), replace=False)]
    sub = _gather_replicated(comms, xs, sel)
    centers0 = _kmeans_plusplus(
        jax.random.PRNGKey(seed), jnp.asarray(sub), params.n_lists
    )
    centers, _, _ = _kmeans_fit_sharded(
        comms, xs, w, comms.replicate(np.asarray(centers0)),
        max_iter=params.kmeans_n_iters, metric_name=_metric_name(params.metric),
        balance=True, seed=seed, n_valid=n, valid_counts=valid_counts,
    )

    labels_sh = _spmd_predict(comms, xs, centers)
    labels_local = _local_shard_rows_host(labels_sh)
    tbl_sh, gids_sh, gids_local, sizes_local = _pack_local_tables(
        comms, labels_local, valid_counts, counts, per, params.n_lists
    )
    ldata = _spmd_pack_rows(comms, xs, tbl_sh, per, jnp.float32)
    return _maybe_replicate(DistributedIvfFlat(
        comms,
        params,
        comms.replicate(centers) if not Comms._is_global(centers) else centers,
        ldata,
        gids_sh,
        n,
        host_gids=None,
        list_sizes=None,
        local_gids=gids_local,
        local_sizes=sizes_local,
    ), replication)


class DistributedIvfPq:
    """Data-parallel IVF-PQ: rotation/coarse centers/codebooks trained
    distributed (replicated afterwards), per-rank bit-code tables over the
    local shard (device-resident end to end), searched SPMD + merged.

    codes (R, n_lists, max_list, pq_dim) uint8 and slot_gids
    (R, n_lists, max_list) int32 are sharded on axis 0; slot_gids holds
    GLOBAL dataset row ids (-1 pad), so shard-local search results merge
    without id translation — the TPU equivalent of the reference's
    application-level MNMG ANN sharding (survey §5.7).

    Host mirrors kept for O(n_new) `extend`: `host_gids` (the slot table)
    and `list_sizes` (R, n_lists) fill counts. The int8 reconstruction
    stores for the list-major search engine (`recon8`/`recon_scale`/
    `recon_norm`) are built lazily per rank on first search."""

    def __init__(self, comms, params, rotation, centers, pq_centers, codes,
                 slot_gids, n, host_gids=None, list_sizes=None,
                 extended: bool = False, bridged: bool = False,
                 local_gids=None, local_sizes=None):
        self.comms = comms
        self.params = params
        self.rotation = rotation
        self.centers = centers
        self.pq_centers = pq_centers
        self.codes = codes
        self.slot_gids = slot_gids
        self.n = n
        self.host_gids = host_gids
        self.list_sizes = list_sizes
        # per-PROCESS mirrors (see DistributedIvfFlat): enable the
        # collective ivf_pq_extend_local on *_build_local indexes
        self.local_gids = local_gids
        self.local_sizes = local_sizes
        # extend appends each batch under a fresh per-rank gid block, so
        # per-rank gid ownership stops being one contiguous range: the
        # refined pipeline then runs post-merge over the full-dataset
        # layout (driver builds) or refuses (*_local-extended / bridged)
        # — see _refine_layout / _refine_merged
        self.extended = extended
        self.bridged = bridged  # see DistributedIvfFlat.bridged
        self.replicas = None  # see DistributedIvfFlat.replicas
        self.recon8 = None
        self.recon_scale = None
        self.recon_norm = None
        self.slot_gids_pad = None  # lane-padded gid view (pallas trim)
        self._refine_cache = None
        self._id_bound = None

    @property
    def id_bound(self) -> int:
        """One past the largest global id a search can return — the id
        space a `prefilter` must cover (== n except for bridged indexes,
        whose gids may be arbitrary caller ids). Cached per instance
        (extends return new indexes)."""
        if self._id_bound is None:
            self._id_bound = _distributed_id_bound(self)
        return self._id_bound

    def clear_refine_cache(self) -> None:
        """Release the device-sharded dataset copy a refined search
        pinned (one entry, keyed by dataset identity)."""
        self._refine_cache = None


def _spmd_label_encode(comms: Comms, xs, rotation, centers, pq_centers,
                       metric, per_cluster: bool):
    """Label + PQ-encode the sharded rows inside shard_map (shard-resident:
    the O(n·d) encode never leaves the devices). Returns sharded
    (labels (n,), codes (n, pq_dim))."""
    from raft_tpu.neighbors.ivf_pq import label_and_encode

    def build():
        @jax.jit
        def run(xs, rotation, centers, pq_centers):
            def body(xs, rotation, centers, pq_centers):
                return label_and_encode(
                    xs, rotation, centers, pq_centers, metric, per_cluster
                )

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None), P(None, None), P(None, None),
                          P(None, None, None)),
                out_specs=(P(comms.axis), P(comms.axis, None)),
                check_vma=False,
            )(xs, rotation, centers, pq_centers)

        return run

    # called once per streamed-extend batch (see _cached_wrapper)
    run = _cached_wrapper(
        wrapper_key("spmd_label_encode", comms, metric, per_cluster),
        build,
    )
    return run(xs, rotation, centers, pq_centers)


def _pack_rank_tables(labels_np, n, per, r, n_lists):
    """Host-side slot-table construction from assignment labels (cheap int
    ops on n int32s — the bulky row payload stays on device and is packed
    by `_spmd_pack_rows`). Returns (local_tbl, gids, sizes, max_list):
    local_tbl (R, n_lists, max_list) holds SHARD-LOCAL row indices (-1
    pad), gids the same slots as global ids."""
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    tables, sizes = [], []
    max_list = 1
    for rr in range(r):
        lo, hi = rr * per, min((rr + 1) * per, n)
        if lo >= hi:
            tables.append(np.full((n_lists, 1), -1, np.int32))
            sizes.append(np.zeros(n_lists, np.int32))
            continue
        t, sz = _pack_lists(labels_np[lo:hi], n_lists)
        tables.append(t.astype(np.int32))
        sizes.append(np.asarray(sz, np.int32))
        max_list = max(max_list, t.shape[1])
    local_tbl = np.full((r, n_lists, max_list), -1, np.int32)
    gids = np.full((r, n_lists, max_list), -1, np.int32)
    for rr, t in enumerate(tables):
        local_tbl[rr, :, : t.shape[1]] = t
        valid = t >= 0
        gids[rr, :, : t.shape[1]][valid] = t[valid] + rr * per
    return local_tbl, gids, np.stack(sizes), max_list


def _spmd_pack_rows(comms: Comms, rows_sh, local_tbl_sh, per: int, out_dtype):
    """Gather sharded flat rows (n, d) into the per-rank list-major tables
    (R, n_lists, max_list, d) inside shard_map — the distributed
    process_and_fill_codes (ivf_pq_build.cuh:724) for PQ codes, and the
    list-store fill for IVF-Flat — as a gather (no TPU scatters)."""

    def build():
        @jax.jit
        def run(rows_sh, tbl):
            def body(rows_sh, tbl):
                t = tbl[0]  # (n_lists, max_list) local row ids
                packed = rows_sh[jnp.clip(t, 0, per - 1)]  # (n_lists, S, d)
                packed = jnp.where(
                    (t >= 0)[..., None], packed, 0).astype(out_dtype)
                return packed[None]

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None), P(comms.axis, None, None)),
                out_specs=P(comms.axis, None, None, None), check_vma=False,
            )(rows_sh, tbl)

        return run

    # called once per streamed-extend batch (see _cached_wrapper)
    run = _cached_wrapper(
        wrapper_key("spmd_pack_rows", comms, int(per),
                    jnp.dtype(out_dtype).name),
        build,
    )

    return run(rows_sh, local_tbl_sh)


def _coarse_fit_rotated(comms: Comms, params, x, rotation, rot_rep, rng,
                        seed: int):
    """Distributed coarse-center fit over the rotated trainset fraction —
    the ONE scaffolding shared by the PQ and RaBitQ driver builds
    (trainset sizing, seeding and the EM invocation cannot diverge per
    quantizer; same consolidation rationale as `_train_codebooks`).
    Draws from the caller's `rng` IN ORDER, so a caller's later draws
    (PQ's codebook sample) see the same stream as before the extraction.
    Returns (centers, xt trainset rows, n_train)."""
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    n = x.shape[0]
    n_lists = params.n_lists
    r = comms.get_size()
    frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
    n_train = min(n, max(n_lists * 4, int(n * frac)))
    train_sel = rng.choice(n, n_train, replace=False)
    xt = x[train_sel]
    xts, _, per_t = _shard_rows(comms, xt)

    xt_rot = _rotate_fn(comms.mesh, comms.axis)(xts, rot_rep)
    w = comms.shard(_valid_weights(n_train, per_t, r), axis=0)
    seed_rows = xt[rng.choice(n_train, min(n_train, max(n_lists * 8, 1024)),
                              replace=False)]
    centers0 = _kmeans_plusplus(
        jax.random.PRNGKey(seed), jnp.asarray(seed_rows) @ rotation.T, n_lists
    )
    centers, _, _ = _kmeans_fit_sharded(
        comms, xt_rot, w, comms.replicate(centers0),
        max_iter=max(params.kmeans_n_iters, 2),
        metric_name=_metric_name(params.metric),
        balance=True, seed=seed, n_valid=n_train,
    )
    return centers, xt, n_train


@obs.spanned("mnmg.ivf_pq_build")
def ivf_pq_build(comms: Comms, params, dataset, seed: int = 0,
                 replication: int = 1) -> DistributedIvfPq:
    """Distributed IVF-PQ build (detail/ivf_pq_build.cuh:1074 at MNMG
    scale): coarse centers train with DISTRIBUTED Lloyd EM over the rotated
    trainset fraction (kmeans_trainset_fraction parity with the single-chip
    build — not a token subsample), codebooks train on the same capped
    residual sample as the single-chip path, and the full dataset is
    labeled/encoded SPMD with the codes staying device-resident; the host
    only ever handles labels (n int32) and slot tables."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    x = np.asarray(dataset, np.float32)
    n, d = x.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > dataset rows {n}")
    r = comms.get_size()
    per = -(-n // r)
    n_lists = params.n_lists
    per_cluster = params.codebook_kind == ivf_pq_mod.PER_CLUSTER

    pq_dim, pq_len, rot_dim = _pq_geometry(params, d)
    key = jax.random.PRNGKey(seed)
    key, rk = jax.random.split(key)
    rotation = ivf_pq_mod._make_rotation(
        rk, rot_dim, d, params.force_random_rotation or rot_dim != d
    )
    rot_rep = comms.replicate(rotation)

    # --- coarse centers: distributed EM over the rotated trainset
    # fraction (shared scaffolding; rng draws continue below)
    rng = np.random.default_rng(seed)
    centers, xt, n_train = _coarse_fit_rotated(
        comms, params, x, rotation, rot_rep, rng, seed
    )

    # --- codebooks: capped residual sample (cap parity with the
    # single-chip build: EM only needs enough rows per codebook entry)
    max_cb = _codebook_cap(params, n_lists)
    cb_sel = rng.choice(n_train, min(n_train, max_cb), replace=False)
    x_cb_rot = jnp.asarray(xt[cb_sel]) @ rotation.T
    from raft_tpu.cluster import kmeans_balanced

    cb_labels = kmeans_balanced.predict(x_cb_rot, centers, metric=_metric_name(params.metric))
    residuals = x_cb_rot - centers[cb_labels]
    key, ck = jax.random.split(key)
    pq_centers = _train_codebooks(
        params, ck, residuals, cb_labels, n_lists, pq_dim, pq_len
    )

    # --- SPMD label + encode the full dataset (codes stay on device)
    xs, _, _ = _shard_rows(comms, x)
    cen_rep = comms.replicate(centers)
    pqc_rep = comms.replicate(pq_centers)
    labels_sh, codes_sh = _spmd_label_encode(
        comms, xs, rot_rep, cen_rep, pqc_rep, params.metric, per_cluster
    )
    labels_np = np.asarray(labels_sh)  # (r*per,) — pad rows ignored below

    local_tbl, gids, sizes, max_list = _pack_rank_tables(
        labels_np, n, per, r, n_lists
    )
    tbl_sh = comms.shard(jnp.asarray(local_tbl), axis=0)
    packed = _spmd_pack_rows(comms, codes_sh, tbl_sh, per, jnp.uint8)

    return _maybe_replicate(DistributedIvfPq(
        comms,
        params,
        rot_rep,
        cen_rep,
        pqc_rep,
        packed,
        comms.shard(jnp.asarray(gids), axis=0),
        n,
        host_gids=gids,
        list_sizes=sizes,
    ), replication)


def ivf_pq_build_local(
    comms: Comms, params, local_dataset, seed: int = 0,
    replication: int = 1,
) -> DistributedIvfPq:
    """Distributed IVF-PQ build where each controller contributes its OWN
    data partition (collective; per-worker-partition raft-dask model).
    The trainset fraction is drawn per-process from local rows, coarse
    centers train with the distributed balanced EM, codebooks train on a
    replicated capped residual sample (deterministic — every controller
    derives identical quantizers), and the full data is labeled+encoded
    SPMD with per-process table packing. Searches like ivf_pq_build's
    index (slot gids are caller row ids in process-concatenation order);
    extend/save need single-controller host mirrors and reject these."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
    from raft_tpu.cluster.kmeans import _kmeans_plusplus
    from raft_tpu.cluster import kmeans_balanced

    local = np.asarray(local_dataset, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    n = int(counts.sum())
    d = local.shape[1]
    n_lists = params.n_lists
    if n_lists > n:
        raise ValueError(f"n_lists={n_lists} > total rows {n}")
    per_cluster = params.codebook_kind == ivf_pq_mod.PER_CLUSTER

    pq_dim, pq_len, rot_dim = _pq_geometry(params, d)
    key = jax.random.PRNGKey(seed)
    key, rk = jax.random.split(key)
    rotation = ivf_pq_mod._make_rotation(
        rk, rot_dim, d, params.force_random_rotation or rot_dim != d
    )
    rot_rep = comms.replicate(np.asarray(rotation))

    # --- trainset: every process contributes its proportional fraction
    frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
    n_train_target = min(n, max(n_lists * 4, int(n * frac)))
    pi = jax.process_index()
    my_n = int(counts[pi])
    my_train = min(my_n, max(1, int(round(n_train_target * my_n / max(n, 1)))))
    rng_p = np.random.default_rng(seed * 1_000_003 + pi)
    xt_local = local[rng_p.choice(my_n, my_train, replace=False)]
    counts_t, per_t, _ = _local_layout(comms, my_train)
    xt_p, _wt = _pack_local(xt_local, per_t, lranks)
    xts = comms.shard_from_local(xt_p, axis=0)
    wt = comms.shard_from_local(_wt, axis=0)
    n_train = int(counts_t.sum())
    valid_counts_t = _rank_valid_counts(comms, counts_t, per_t)

    xt_rot = _rotate_fn(comms.mesh, comms.axis)(xts, rot_rep)

    gpos_t = _valid_global_positions(comms, counts_t, per_t)
    rng = np.random.default_rng(seed)
    sel = gpos_t[
        rng.choice(n_train, min(n_train, max(n_lists * 8, 1024)), replace=False)
    ]
    sub = _gather_replicated(comms, xt_rot, sel)
    centers0 = _kmeans_plusplus(jax.random.PRNGKey(seed), jnp.asarray(sub), n_lists)
    centers, _, _ = _kmeans_fit_sharded(
        comms, xt_rot, wt, comms.replicate(np.asarray(centers0)),
        max_iter=max(params.kmeans_n_iters, 2),
        metric_name=_metric_name(params.metric),
        balance=True, seed=seed, n_valid=n_train, valid_counts=valid_counts_t,
    )

    # --- codebooks: replicated capped residual sample (cap parity with
    # the driver build); identical on every controller
    max_cb = _codebook_cap(params, n_lists)
    cb_sel = gpos_t[rng.choice(n_train, min(n_train, max_cb), replace=False)]
    x_cb_rot = jnp.asarray(_gather_replicated(comms, xt_rot, cb_sel))
    centers_host = jnp.asarray(np.asarray(centers.addressable_shards[0].data))
    cb_labels = kmeans_balanced.predict(
        x_cb_rot, centers_host, metric=_metric_name(params.metric)
    )
    residuals = x_cb_rot - centers_host[cb_labels]
    key, ck = jax.random.split(key)
    pq_centers = _train_codebooks(
        params, ck, residuals, cb_labels, n_lists, pq_dim, pq_len
    )

    # --- SPMD label + encode every process's rows
    xp, _ = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    cen_rep = comms.replicate(centers) if not Comms._is_global(centers) else centers
    pqc_rep = comms.replicate(np.asarray(pq_centers))
    labels_sh, codes_sh = _spmd_label_encode(
        comms, xs, rot_rep, cen_rep, pqc_rep, params.metric, per_cluster
    )
    labels_local = _local_shard_rows_host(labels_sh)
    valid_counts = _rank_valid_counts(comms, counts, per)
    tbl_sh, gids_sh, gids_local, sizes_local = _pack_local_tables(
        comms, labels_local, valid_counts, counts, per, n_lists
    )
    packed = _spmd_pack_rows(comms, codes_sh, tbl_sh, per, jnp.uint8)
    return _maybe_replicate(DistributedIvfPq(
        comms,
        params,
        rot_rep,
        cen_rep,
        pqc_rep,
        packed,
        gids_sh,
        n,
        host_gids=None,
        list_sizes=None,
        local_gids=gids_local,
        local_sizes=sizes_local,
    ), replication)


def ivf_pq_extend(index: DistributedIvfPq, new_vectors) -> DistributedIvfPq:
    """Distributed extend (ivf_pq_build.cuh:1061 at MNMG scale): the new
    batch is sharded round-robin, labeled/encoded SPMD on each rank, and
    appended into grown per-rank tables with a device-side gather —
    O(n_new + table copy), same complexity as the single-chip extend."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    comms = index.comms
    r = comms.get_size()
    nv = np.asarray(new_vectors, np.float32)
    n_new = nv.shape[0]
    if n_new == 0:
        return index
    if comms.spans_processes():
        # constructible via ivf_pq_load on a spanning mesh: extend is a
        # single-controller (driver) operation — the new batch is one full
        # host array, which no single controller can shard here
        raise ValueError(
            "distributed extend is single-controller; on a multi-process "
            "mesh use ivf_pq_extend_local (each controller passes its own "
            "new rows)"
        )
    if getattr(index, "bridged", False):
        raise ValueError(
            "extend on a bridged (distribute_index) layout can collide "
            "caller ids; extend the single-chip index and re-distribute"
        )
    if index.host_gids is None or index.list_sizes is None:
        raise ValueError(
            "index lacks global host mirrors (built with ivf_pq_build_local?);"
            " use ivf_pq_extend_local"
        )
    n_lists = index.params.n_lists
    per_cluster = index.params.codebook_kind == ivf_pq_mod.PER_CLUSTER
    pq_dim = index.codes.shape[-1]
    old_max = index.codes.shape[2]

    nvs, _, per_new = _shard_rows(comms, nv)
    labels_sh, codes_sh = _spmd_label_encode(
        comms, nvs, index.rotation, index.centers, index.pq_centers,
        index.params.metric, per_cluster,
    )
    new_tbl, host_gids, new_sizes, new_max = _append_rank_tables(
        np.asarray(labels_sh), index.list_sizes, index.host_gids, old_max,
        per_new, n_new, n_lists, index.n, r,
    )
    packed = _spmd_grow_tables(
        comms, index.codes, codes_sh, comms.shard(jnp.asarray(new_tbl), axis=0),
        per_new, new_max, jnp.uint8,
    )
    return _carry_replication(index, DistributedIvfPq(
        comms,
        index.params,
        index.rotation,
        index.centers,
        index.pq_centers,
        packed,
        comms.shard(jnp.asarray(host_gids), axis=0),
        index.n + n_new,
        host_gids=host_gids,
        list_sizes=new_sizes,
        extended=True,
    ))


def _place_append_batches(labels_np, per_new: int, n_valid: int,
                          old_sizes, n_lists: int, old_max: int):
    """Per-rank destination slots for a rank-blocked new batch appended
    after each list's fill: rank rr's valid rows are the prefix
    clip(n_valid - rr*per_new, 0, per_new) of its block (vectorized via
    ivf_flat._append_slots — bincount/argsort, O(n_new) numpy; a Python
    per-row loop here would serialize a 1M-row extend). The ONE
    placement walk shared by the single-controller and collective
    extends. Returns (placements, new_sizes, max_size)."""
    from raft_tpu.neighbors.ivf_flat import _append_slots

    new_sizes = old_sizes.copy()
    mx = old_max
    placements = []  # per rank: (labels, slot_abs) or None for empty shards
    for rr in range(old_sizes.shape[0]):
        nv = int(np.clip(n_valid - rr * per_new, 0, per_new))
        if nv == 0:  # trailing rank past the batch
            placements.append(None)
            continue
        lab = labels_np[rr * per_new : rr * per_new + nv].astype(np.int64)
        slot_abs, sizes_rr, _ = _append_slots(
            lab, old_sizes[rr].astype(np.int64), n_lists
        )
        new_sizes[rr] = sizes_rr.astype(np.int32)
        mx = max(mx, int(sizes_rr.max()))
        placements.append((lab, slot_abs))
    return placements, new_sizes, mx


def _align_group(mx: int, old_max: int, group: int = 32) -> int:
    """Round the grown list width up to the slot-group multiple, never
    shrinking below the old width."""
    return max(-(-mx // group) * group, old_max)


def _stamp_append_tables(placements, old_gids, old_max: int, new_max: int,
                         n_lists: int, id_base):
    """Grow gid tables and build the new-row placement table: row j of
    rank rr's valid prefix lands at its placement slot with id
    id_base[rr] + j — the ONE id-assignment stamp shared by both extend
    paths. Returns (new_tbl local-new-row ids, grown gids)."""
    r = len(placements)
    new_tbl = np.full((r, n_lists, new_max), -1, np.int32)
    gids = np.full((r, n_lists, new_max), -1, np.int32)
    gids[:, :, :old_max] = old_gids
    for rr, pl in enumerate(placements):
        if pl is None:
            continue
        lab, slot_abs = pl
        j = np.arange(len(lab), dtype=np.int32)
        new_tbl[rr, lab, slot_abs] = j
        gids[rr, lab, slot_abs] = int(id_base[rr]) + j
    return new_tbl, gids


def _append_rank_tables(labels_np, old_sizes, old_host_gids, old_max: int,
                        per_new: int, n_new: int, n_lists: int, n_old: int,
                        r: int):
    """Host bookkeeping for the single-controller distributed extend.
    Returns (new_tbl local-new-row ids, host_gids, new_sizes, new_max)."""
    placements, new_sizes, mx = _place_append_batches(
        labels_np, per_new, n_new, old_sizes, n_lists, old_max
    )
    new_max = _align_group(mx, old_max)
    new_tbl, host_gids = _stamp_append_tables(
        placements, old_host_gids, old_max, new_max, n_lists,
        n_old + per_new * np.arange(r, dtype=np.int64),
    )
    return new_tbl, host_gids, new_sizes, new_max


def _spmd_grow_tables(comms: Comms, old_tbl, rows_sh, new_tbl_sh,
                      per_new: int, new_max: int, out_dtype):
    """Grow per-rank list tables to new_max slots and place the sharded new
    rows at their destination slots inside shard_map (device gather, no
    scatters) — the distributed _grow_and_scatter."""
    n_lists = old_tbl.shape[1]
    old_max = old_tbl.shape[2]
    d = old_tbl.shape[3]

    @jax.jit
    def grow(old_tbl, rows_sh, tbl):
        def body(old_tbl, rows_sh, tbl):
            t = tbl[0]  # (n_lists, new_max)
            out = jnp.zeros((n_lists, new_max, d), out_dtype)
            out = out.at[:, :old_max].set(old_tbl[0])
            new_vals = rows_sh[jnp.clip(t, 0, max(per_new - 1, 0))]
            out = jnp.where((t >= 0)[..., None], new_vals.astype(out_dtype), out)
            return out[None]

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None, None, None), P(comms.axis, None),
                      P(comms.axis, None, None)),
            out_specs=P(comms.axis, None, None, None), check_vma=False,
        )(old_tbl, rows_sh, tbl)

    return grow(old_tbl, rows_sh, new_tbl_sh)


def ivf_flat_extend(index: DistributedIvfFlat, new_vectors) -> DistributedIvfFlat:
    """Distributed IVF-Flat extend: the new batch is sharded round-robin,
    labeled SPMD, and appended into grown per-rank list stores with a
    device-side gather — O(n_new + table copy)."""
    comms = index.comms
    r = comms.get_size()
    nv = np.asarray(new_vectors, np.float32)
    n_new = nv.shape[0]
    if n_new == 0:
        return index
    if comms.spans_processes():
        # constructible via ivf_flat_load on a spanning mesh: extend is a
        # single-controller (driver) operation — the new batch is one full
        # host array, which no single controller can shard here
        raise ValueError(
            "distributed extend is single-controller; on a multi-process "
            "mesh use ivf_flat_extend_local (each controller passes its "
            "own new rows)"
        )
    if getattr(index, "bridged", False):
        raise ValueError(
            "extend on a bridged (distribute_index) layout can collide "
            "caller ids; extend the single-chip index and re-distribute"
        )
    if index.host_gids is None or index.list_sizes is None:
        raise ValueError(
            "index lacks global host mirrors (built with ivf_flat_build_local?"
            "); use ivf_flat_extend_local"
        )
    n_lists = index.params.n_lists
    old_max = index.list_data.shape[2]

    nvs, _, per_new = _shard_rows(comms, nv)
    labels_sh = _spmd_predict(comms, nvs, index.centers)
    new_tbl, host_gids, new_sizes, new_max = _append_rank_tables(
        np.asarray(labels_sh), index.list_sizes, index.host_gids, old_max,
        per_new, n_new, n_lists, index.n, r,
    )
    ldata = _spmd_grow_tables(
        comms, index.list_data, nvs, comms.shard(jnp.asarray(new_tbl), axis=0),
        per_new, new_max, jnp.float32,
    )
    return _carry_replication(index, DistributedIvfFlat(
        comms,
        index.params,
        index.centers,
        ldata,
        comms.shard(jnp.asarray(host_gids), axis=0),
        index.n + n_new,
        host_gids=host_gids,
        list_sizes=new_sizes,
    ))


def _extend_local_impl(index, local_new, label_payload_fn, store, out_dtype,
                       dim: int):
    """Collective extend where each controller appends its OWN new rows
    (the multi-controller analogue of `*_extend`; raft-dask model). New
    ids continue the build's id space: position in the process-order
    concatenation of the NEW partitions, offset by the old total.

    Every process: pack+shard its rows, SPMD label/encode, place its
    ranks' new rows with _append_slots against its per-process mirrors,
    agree on the new global list width (one host allgather), and grow
    the sharded tables device-side. Returns (grown_store, gids_sh,
    gids_local, sizes_local, n_total), or None for an empty batch.
    `dim` validates the caller's row width up front (a mismatch would
    otherwise surface as an XLA shape error mid-collective)."""
    comms = index.comms
    local = np.asarray(local_new, np.float32)
    if local.ndim != 2 or local.shape[1] != dim:
        raise ValueError(
            f"new rows must be (n, {dim}), got {local.shape}"
        )
    if getattr(index, "bridged", False):
        raise ValueError(
            "extend on a bridged (distribute_index) layout can collide "
            "caller ids; extend the single-chip index and re-distribute"
        )
    if index.local_gids is None or index.local_sizes is None:
        raise ValueError(
            "index lacks the per-process mirrors extend_local appends "
            "against (kept by *_build_local builds and checkpoint loads)"
        )
    counts_new, per_new, lranks = _local_layout(comms, local.shape[0])
    total_new = int(counts_new.sum())
    if total_new == 0:
        return None
    n_lists = index.params.n_lists
    old_max = store.shape[2]

    xp, _ = _pack_local(local, per_new, lranks)
    nvs = comms.shard_from_local(xp, axis=0)
    labels_sh, payload_sh = label_payload_fn(nvs)
    labels_local = _local_shard_rows_host(labels_sh)

    pi = jax.process_index()
    placements, sizes_new, my_max = _place_append_batches(
        labels_local, per_new, int(counts_new[pi]), index.local_sizes,
        n_lists, old_max,
    )
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        all_max = np.asarray(multihost_utils.process_allgather(
            jnp.asarray([my_max]), tiled=True))
        my_max = int(all_max.max())
    new_max = _align_group(my_max, old_max)

    new_base = index.n + int(counts_new[:pi].sum())
    new_tbl, gids_grown = _stamp_append_tables(
        placements, index.local_gids, old_max, new_max, n_lists,
        new_base + per_new * np.arange(lranks, dtype=np.int64),
    )
    tbl_sh = comms.shard_from_local(new_tbl, axis=0)
    grown = _spmd_grow_tables(comms, store, payload_sh, tbl_sh, per_new,
                              new_max, out_dtype)
    gids_sh = comms.shard_from_local(gids_grown, axis=0)
    return grown, gids_sh, gids_grown, sizes_new, index.n + total_new


def ivf_flat_extend_local(index: DistributedIvfFlat,
                          local_new_vectors) -> DistributedIvfFlat:
    """Collective multi-controller IVF-Flat extend: every process calls
    with its OWN new rows (zero-row partitions fine). Returned ids for
    the new rows continue the id space — old total + position in the
    process-order concatenation of the new partitions."""
    res = _extend_local_impl(
        index, local_new_vectors,
        lambda nvs: (_spmd_predict(index.comms, nvs, index.centers), nvs),
        index.list_data, jnp.float32, dim=int(index.list_data.shape[-1]),
    )
    if res is None:
        return index
    ldata, gids_sh, gids_local, sizes_local, n_total = res
    return _carry_replication(index, DistributedIvfFlat(
        index.comms, index.params, index.centers, ldata, gids_sh, n_total,
        local_gids=gids_local, local_sizes=sizes_local,
    ))


def ivf_pq_extend_local(index: DistributedIvfPq,
                        local_new_vectors) -> DistributedIvfPq:
    """Collective multi-controller IVF-PQ extend (see
    ivf_flat_extend_local). The returned index re-derives its int8
    reconstruction store lazily on first search. It is marked extended;
    unlike driver-built extends (which refine post-merge over the full
    dataset), a *_local-extended layout cannot refine — its partitions'
    ids straddle the original and appended id blocks."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    per_cluster = index.params.codebook_kind == ivf_pq_mod.PER_CLUSTER
    res = _extend_local_impl(
        index, local_new_vectors,
        lambda nvs: _spmd_label_encode(
            index.comms, nvs, index.rotation, index.centers,
            index.pq_centers, index.params.metric, per_cluster,
        ),
        index.codes, jnp.uint8, dim=int(index.rotation.shape[1]),
    )
    if res is None:
        return index
    codes, gids_sh, gids_local, sizes_local, n_total = res
    return _carry_replication(index, DistributedIvfPq(
        index.comms, index.params, index.rotation, index.centers,
        index.pq_centers, codes, gids_sh, n_total, extended=True,
        local_gids=gids_local, local_sizes=sizes_local,
    ))
