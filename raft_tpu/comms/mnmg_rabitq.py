"""Distributed IVF-RaBitQ: driver build, SPMD binary-code search with
degraded mode + lossless replica failover, and the refine pipeline.

The index shards exactly like DistributedIvfPq — rank-major per-list
tables over the row shards — but the payload is the RaBitQ pair
(packed uint32 sign codes + the 2-scalar correction table) and there is
NO codebook stage: the build is the distributed coarse k-means plus one
SPMD encode pass, which is the whole fast-build story at pod scale.

Production surfaces from day one (ISSUE 6):
  - `health=` masks dead ranks pre-merge and returns
    `DegradedSearchResult(coverage)`; on a `replication=` build,
    surviving ring holders fail over BIT-IDENTICALLY at coverage 1.0
    through r-1 failures (comms/replication.py — the codes/aux/slot
    tables are all mirrored).
  - `refine_dataset` runs the exact per-rank rerank
    (mnmg_ivf_search._refine_local): every candidate a rank reports came
    from its own rows, so the rerank needs no cross-rank gathers.
  - chaos site "mnmg.ivf_rabitq.scores" poisons a shard's reported
    scores pre-merge (drilled in tests/test_resilience.py).
  - CRC-checked checkpoints with mirror healing live in mnmg_ckpt
    (`ivf_rabitq_save` / `ivf_rabitq_load`).
"""


import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.comms.comms import Comms
from raft_tpu.comms.mnmg_common import (
    _cached_wrapper, _distributed_id_bound, _mask_dead_rank,
    _pack_result, _pad_queries, _replicated_filter_bits, _resolve_health,
    _shard_filtered, _shard_rows, rank_captured, wrapper_key,
)
from raft_tpu.comms.mnmg_merge import (
    _merge_local_topk, _merge_local_topk_scatter, _resolve_query_mode,
)
from raft_tpu.comms.mnmg_ivf_build import (
    _maybe_replicate, _pack_rank_tables, _spmd_pack_rows,
)

SCORES_SITE = "mnmg.ivf_rabitq.scores"


class DistributedIvfRabitq:
    """Data-parallel IVF-RaBitQ: replicated rotation/centers, per-rank
    packed-code + correction tables over the local shard.

    codes (R, n_lists, max_list, W) uint32 and aux (R, n_lists,
    max_list, 2) f32 are sharded on axis 0; slot_gids holds GLOBAL row
    ids (-1 pad) so shard-local results merge without translation. Host
    mirrors (`host_gids`, `list_sizes`) serve the checkpoint writer."""

    def __init__(self, comms, params, rotation, centers, codes, aux,
                 slot_gids, n, host_gids=None, list_sizes=None,
                 bridged: bool = False):
        self.comms = comms
        self.params = params
        self.rotation = rotation
        self.centers = centers
        self.codes = codes
        self.aux = aux
        self.slot_gids = slot_gids
        self.n = n
        self.host_gids = host_gids
        self.list_sizes = list_sizes
        self.bridged = bridged
        self.extended = False  # no distributed extend yet (ROADMAP 5c)
        self.replicas = None  # see DistributedIvfFlat.replicas
        # fused bit-plane scan's lazy per-rank derived store (see
        # _build_distributed_bitplane): word-transposed lane-padded
        # codes, per-slot estimator meta rows, padded gid table, and
        # the monotonically-grown candidate-buffer width
        self.codes_t = None
        self.bp_meta = None
        self.slot_gids_pad = None
        self.fused_kb = None
        self._refine_cache = None
        self._id_bound = None

    @property
    def id_bound(self) -> int:
        """One past the largest global id a search can return — the id
        space a `prefilter` must cover."""
        if self._id_bound is None:
            self._id_bound = _distributed_id_bound(self)
        return self._id_bound

    def clear_refine_cache(self) -> None:
        """Release the device-sharded dataset copy a refined search
        pinned (one entry, keyed by dataset identity)."""
        self._refine_cache = None


def _spmd_label_encode_rabitq(comms: Comms, xs, rotation, centers, metric):
    """Label + RaBitQ-encode the sharded rows inside shard_map (the
    O(n*d) encode never leaves the devices). Returns sharded
    (labels (n,), codes (n, W) uint32, aux (n, 2) f32)."""
    from raft_tpu.neighbors.ivf_rabitq import label_and_encode

    def build():
        @jax.jit
        def run(xs, rotation, centers):
            def body(xs, rotation, centers):
                return label_and_encode(xs, rotation, centers, metric)

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None), P(None, None), P(None, None)),
                out_specs=(P(comms.axis), P(comms.axis, None),
                           P(comms.axis, None)),
                check_vma=False,
            )(xs, rotation, centers)

        return run

    run = _cached_wrapper(
        wrapper_key("spmd_label_encode_rabitq", comms, metric),
        build,
    )
    return run(xs, rotation, centers)


@obs.spanned("mnmg.ivf_rabitq_build")
def ivf_rabitq_build(comms: Comms, params, dataset, seed: int = 0,
                     replication: int = 1) -> DistributedIvfRabitq:
    """Distributed IVF-RaBitQ build: coarse centers via distributed
    Lloyd EM over the rotated trainset fraction, then one SPMD
    label+encode pass — no codebook stage at all, so the build is
    coarse-kmeans-bound (the pod-scale fast-build claim, measured in
    bench/bench_ivf_rabitq.py). `replication` > 1 mirrors each rank's
    code/correction/slot tables onto its ring holders at build time so
    searches fail over losslessly through r-1 failures."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
    from raft_tpu.neighbors.ivf_rabitq import rabitq_rot_dim

    x = np.asarray(dataset, np.float32)
    n, d = x.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > dataset rows {n}")
    r = comms.get_size()
    per = -(-n // r)
    n_lists = params.n_lists

    rot_dim = rabitq_rot_dim(d)
    key = jax.random.PRNGKey(seed)
    key, rk = jax.random.split(key)
    rotation = ivf_pq_mod._make_rotation(rk, rot_dim, d, True)
    rot_rep = comms.replicate(rotation)

    # coarse centers: the ONE distributed coarse-fit scaffolding shared
    # with ivf_pq_build (minus its codebook stage — nothing follows)
    from raft_tpu.comms.mnmg_ivf_build import _coarse_fit_rotated

    rng = np.random.default_rng(seed)
    centers, _, _ = _coarse_fit_rotated(
        comms, params, x, rotation, rot_rep, rng, seed
    )

    # SPMD label + encode the full dataset (codes stay on device). The
    # encode chaos site fires HERE on the host — inside the traced body
    # it would only fire at trace time and a warm wrapper cache would
    # silently disarm the drill
    from raft_tpu.neighbors.ivf_rabitq import ENCODE_SITE

    faults.fault_point(ENCODE_SITE, rank=jax.process_index())
    xs, _, _ = _shard_rows(comms, x)
    cen_rep = comms.replicate(centers)
    labels_sh, codes_sh, aux_sh = _spmd_label_encode_rabitq(
        comms, xs, rot_rep, cen_rep, params.metric
    )
    labels_np = np.asarray(labels_sh)  # (r*per,) — pad rows ignored below

    local_tbl, gids, sizes, _max_list = _pack_rank_tables(
        labels_np, n, per, r, n_lists
    )
    tbl_sh = comms.shard(jnp.asarray(local_tbl), axis=0)
    packed_codes = _spmd_pack_rows(comms, codes_sh, tbl_sh, per, jnp.uint32)
    packed_aux = _spmd_pack_rows(comms, aux_sh, tbl_sh, per, jnp.float32)

    return _maybe_replicate(DistributedIvfRabitq(
        comms,
        params,
        rot_rep,
        cen_rep,
        packed_codes,
        packed_aux,
        comms.shard(jnp.asarray(gids), axis=0),
        n,
        host_gids=gids,
        list_sizes=sizes,
    ), replication)


def _build_distributed_bitplane(index: DistributedIvfRabitq, k: int) -> None:
    """Lazy per-rank derived store for the distributed fused bit-plane
    scan (the RaBitQ analogue of `_build_distributed_recon`): the packed
    sign codes word-transposed to (R, n_lists, W, L) with the slot axis
    lane-padded, the (R, n_lists, 3, L) per-slot estimator meta rows
    [popcount, |r|, <o, x_bar>], and a width-matched padded gid table —
    all computed on the sharded arrays (XLA keeps everything
    rank-local). `index.fused_kb` records the compiled candidate-buffer
    width and grows monotonically (the shared invalidation contract)."""
    from raft_tpu.neighbors.ivf_rabitq import derive_bitplane_tables
    from raft_tpu.ops.fused_scan import fused_kbuf
    from raft_tpu.ops.pq_list_scan import lane_padded

    lpad = lane_padded(int(index.codes.shape[2]))
    if index.codes_t is None or int(index.codes_t.shape[3]) != lpad:
        # one shared derivation with the single-chip store (leading
        # rank axis rides the ellipsis) — the kernel operand contract
        # has exactly one author
        index.codes_t, index.bp_meta, index.slot_gids_pad = (
            derive_bitplane_tables(index.codes, index.aux,
                                   index.slot_gids, lpad)
        )
    kb = fused_kbuf(int(k))
    if index.fused_kb is None or kb > index.fused_kb:
        index.fused_kb = kb


@rank_captured("mnmg.ivf_rabitq_search")
@obs.spanned("mnmg.ivf_rabitq_search")
def ivf_rabitq_search(index: DistributedIvfRabitq, queries, k: int,
                      n_probes: int = 20, refine_dataset=None,
                      refine_mult: int = 4, prefilter=None,
                      query_mode: str = "auto", query_bits: int = 0,
                      scan_engine: str = "auto", health=None,
                      adaptive: bool = False, recall_target=None,
                      budget_tau=None, min_probes: int = 1,
                      quantization: str = "auto"):
    """SPMD binary-code search: every rank scans its local packed codes
    for the same global probes and the estimator-ranked local top-k
    merge on all ranks ("replicated") or route to per-rank query blocks
    ("sharded"). `refine_dataset` (full dataset, insertion order)
    enables the exact per-rank rerank of a `refine_mult * k` shortlist —
    each rank re-ranks its OWN candidates against its dataset shard, so
    the merged distances are exact. `prefilter`, `health`, replica
    failover and `DegradedSearchResult` behave exactly as in
    `ivf_pq_search` (shared plumbing).

    `scan_engine` mirrors the single-chip `SearchParams.scan_engine`:
    "xla" (the materializing bit-plane reference), "fused" (the fused
    AND+popcount scan per rank through the matrix/select_k dispatch —
    explicit requests past the kernel's envelope raise), or "auto"
    (fused only on the chip-measured tuned winner,
    matrix/select_k.BITPLANE_SCAN_KEY)."""
    from raft_tpu.neighbors.ivf_rabitq import (
        _search_impl_rabitq, _search_impl_rabitq_fused, rerank_depth,
        resolve_query_bits,
    )
    from raft_tpu.neighbors.ivf_pq import _coarse_select  # noqa: F401 (doc)
    from raft_tpu.comms.mnmg_ivf_search import _refine_layout, _refine_local
    from raft_tpu.comms.replication import failover_view
    from raft_tpu.distance.distance_types import DistanceType

    # lossless failover first (see ivf_pq_search): with surviving
    # holders the patched view + effective mask make the rest of this
    # function see repaired ranks as healthy
    index, health, repaired = failover_view(index, health)

    comms = index.comms
    ac = comms.comms
    from raft_tpu.comms import quantized

    qcfg = quantized.resolve(quantization)
    q = jnp.asarray(queries, jnp.float32)
    metric = index.params.metric
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    n_probes = int(min(n_probes, index.params.n_lists))
    qbits = resolve_query_bits(query_bits)

    # scan-engine resolution through the dispatch layer (identical to
    # the single-chip search: explicit "fused" raises past the
    # envelope, "auto" promotes only on the tuned chip winner). The
    # geometry is global across ranks, so every controller resolves the
    # same engine — no rank diverges.
    if scan_engine not in ("auto", "xla", "fused"):
        raise ValueError(f"unknown scan_engine {scan_engine!r}")
    from raft_tpu.matrix.select_k import (
        check_bitplane_request, resolve_bitplane_strategy,
    )
    from raft_tpu.ops.fused_scan import FUSED_MAX_K, fused_kbuf
    from raft_tpu.ops.pq_list_scan import lane_padded

    kk_depth = (rerank_depth(int(k), max(refine_mult, 1))
                if refine_dataset is not None else int(k))
    lpad = lane_padded(int(index.codes.shape[2]))
    words = int(index.codes.shape[3])
    if scan_engine == "fused":
        fused_kb = check_bitplane_request(
            "scan_engine='fused'", lpad, words, int(qbits), kk_depth,
            index.fused_kb, "scan_engine='xla'",
        )
        strat = "fused_bitplane"
    elif scan_engine == "auto" and 0 < kk_depth <= FUSED_MAX_K:
        fused_kb = max(fused_kbuf(kk_depth), index.fused_kb or 0)
        strat = resolve_bitplane_strategy(lpad, words, int(qbits),
                                          kk_depth, kbuf=fused_kb)
    else:
        fused_kb, strat = None, "xla"
    use_fused = strat == "fused_bitplane"

    # adaptive per-rank probe budgets (see ivf_flat_search: replicated
    # rotation/centers make one host-side plan the every-rank plan;
    # bounds off distributed)
    from raft_tpu.neighbors import probe_budget

    ap = probe_budget.resolve(
        n_probes, adaptive=adaptive, recall_target=recall_target,
        budget_tau=budget_tau, min_probes=min_probes, early_term=False)
    keep = None
    scanned_mean = None
    if ap is not None:
        keep, scanned = probe_budget.probe_plan(
            q, index.centers, n_probes=n_probes,
            min_probes=ap.min_probes, k=int(kk_depth), metric=metric,
            tau=ap.tau, rotation=index.rotation)
        scanned_mean = probe_budget.account(
            "mnmg.ivf_rabitq", scanned, int(q.shape[0]), n_probes)
    if obs.enabled():
        # n_rows = total padded slots of the (R, n_lists, max_list)
        # code tables — every rank scans its probed lists' pad slots too
        obs.span_cost(**obs.perf.cost_for(
            "mnmg.ivf_rabitq_search", nq=int(q.shape[0]),
            n_probes=(scanned_mean if scanned_mean is not None
                      else n_probes),
            n_lists=int(index.params.n_lists),
            n_rows=int(index.codes.shape[0] * index.codes.shape[1]
                       * index.codes.shape[2]),
            dim=int(index.centers.shape[-1]), k=int(k),
            query_bits=int(qbits),
            rerank_mult=int(refine_mult) if refine_dataset is not None else 0,
            fused=use_fused))
    mode = _resolve_query_mode(query_mode, comms, q.shape[0], k)
    live_rep, mode, coverage = _resolve_health(comms, health, query_mode, mode)
    nq = q.shape[0]
    if mode == "sharded":
        q, nq = _pad_queries(q, comms.get_size())
    merge = _merge_local_topk if mode == "replicated" else _merge_local_topk_scatter
    out_spec = P(None, None) if mode == "replicated" else P(comms.axis, None)

    qr = comms.replicate(q)
    adaptive_on = ap is not None
    if keep is not None and keep.shape[0] != q.shape[0]:
        # sharded-mode query padding: pad rows scan nothing
        keep = jnp.pad(keep, ((0, q.shape[0] - keep.shape[0]), (0, 0)),
                       constant_values=False)
    pv_rep = comms.replicate(
        keep if keep is not None else jnp.zeros((1, 1), bool))
    pf_bits, pf_n = _replicated_filter_bits(comms, prefilter, index.id_bound)
    refine = refine_dataset is not None
    if refine:
        xs_r, base_r, valid_r = _refine_layout(index, refine_dataset)
        base_rep = comms.replicate(np.asarray(base_r, np.int32))
        valid_rep = comms.replicate(np.asarray(valid_r, np.int32))
        kk = rerank_depth(int(k), max(refine_mult, 1))
    else:
        from raft_tpu.comms.mnmg_common import _ranks_by_proc

        xs_r = comms.shard(
            jnp.zeros((comms.get_size(), 1), jnp.float32), axis=0
        ) if not comms.spans_processes() else comms.shard_from_local(
            np.zeros((len(_ranks_by_proc(comms.mesh).get(
                jax.process_index(), [])), 1), np.float32), axis=0
        )
        base_rep = comms.replicate(np.zeros(comms.get_size(), np.int32))
        valid_rep = comms.replicate(np.zeros(comms.get_size(), np.int32))
        kk = int(k)

    def finish_body(v, gid, q, xs, base, valid, live):
        rank = ac.get_rank()
        if refine:
            v, gid = _refine_local(q, gid, xs, base, valid, rank,
                                   metric, worst)
        else:
            v = jnp.where(gid >= 0, v, worst)
        # corrupt AFTER the local refine (site models the shard's
        # REPORTED scores — same placement rationale as
        # mnmg.ivf_pq.scores)
        v = faults.corrupt_in_trace(SCORES_SITE, v, rank)
        v, gid = _mask_dead_rank(v, gid, live, rank, worst)
        return merge(ac, v, gid, k, select_min, quant=qcfg)

    if use_fused:
        _build_distributed_bitplane(index, kk_depth)
        fused_kb = index.fused_kb  # monotone: may exceed this call's kk
        interp = jax.default_backend() == "cpu"
        from raft_tpu.neighbors.probe_invert import resolve_setup_impls

        setup_impls = resolve_setup_impls(
            int(index.params.n_lists), engine="flat")

        def build_run_fused():
            @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
            def run(rotation, centers, codes_t, bp_meta, gid_tbl, q, xs,
                    base, valid, bits, live, pv, k: int, use_pf: bool):
                def body(rotation, centers, codes_t, bp_meta, gid_tbl, q,
                         xs, base, valid, bits, live, pv):
                    srows = _shard_filtered(gid_tbl[0], bits, pf_n, use_pf)
                    v, gid = _search_impl_rabitq_fused(
                        q, rotation, centers, codes_t[0], bp_meta[0],
                        srows, kk, n_probes, metric, query_bits=qbits,
                        kb=fused_kb, interpret=interp,
                        setup_impls=setup_impls,
                        pvalid=pv if adaptive_on else None,
                    )
                    return finish_body(v, gid, q, xs, base, valid, live)

                return jax.shard_map(
                    body, mesh=comms.mesh,
                    in_specs=(P(None, None), P(None, None),
                              P(comms.axis, None, None, None),
                              P(comms.axis, None, None, None),
                              P(comms.axis, None, None),
                              P(None, None), P(comms.axis, None), P(None),
                              P(None), P(None), P(None), P(None, None)),
                    out_specs=(out_spec, out_spec), check_vma=False,
                )(rotation, centers, codes_t, bp_meta, gid_tbl, q, xs,
                  base, valid, bits, live, pv)

            return run

        run = _cached_wrapper(
            wrapper_key(
                "rabitq_fused", comms, mode, metric, int(k),
                kk, n_probes, refine, pf_n, qbits, fused_kb, interp,
                setup_impls, adaptive_on, qcfg),
            build_run_fused,
        )
        v, gid = run(
            index.rotation, index.centers, index.codes_t, index.bp_meta,
            index.slot_gids_pad, qr, xs_r, base_rep, valid_rep, pf_bits,
            live_rep, pv_rep, int(k), prefilter is not None,
        )
        return _pack_result(v, gid, nq, coverage, repaired)

    def build_run():
        @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
        def run(rotation, centers, codes, aux, gid_tbl, q, xs, base, valid,
                bits, live, pv, k: int, use_pf: bool):
            def body(rotation, centers, codes, aux, gid_tbl, q, xs, base,
                     valid, bits, live, pv):
                srows = _shard_filtered(gid_tbl[0], bits, pf_n, use_pf)
                # slot table holds global ids, so the impl's ids are
                # global
                v, gid = _search_impl_rabitq(
                    q, rotation, centers, codes[0], aux[0], srows,
                    kk, n_probes, metric, query_bits=qbits,
                    pvalid=pv if adaptive_on else None,
                )
                return finish_body(v, gid, q, xs, base, valid, live)

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(None, None), P(None, None),
                          P(comms.axis, None, None, None),
                          P(comms.axis, None, None, None),
                          P(comms.axis, None, None),
                          P(None, None), P(comms.axis, None), P(None),
                          P(None), P(None), P(None), P(None, None)),
                out_specs=(out_spec, out_spec), check_vma=False,
            )(rotation, centers, codes, aux, gid_tbl, q, xs, base, valid,
              bits, live, pv)

        return run

    run = _cached_wrapper(
        wrapper_key(
            "rabitq", comms, mode, metric, int(k), kk,
            n_probes, refine, pf_n, qbits, adaptive_on, qcfg),
        build_run,
    )
    v, gid = run(
        index.rotation, index.centers, index.codes, index.aux,
        index.slot_gids, qr, xs_r, base_rep, valid_rep, pf_bits, live_rep,
        pv_rep, int(k), prefilter is not None,
    )
    return _pack_result(v, gid, nq, coverage, repaired)
