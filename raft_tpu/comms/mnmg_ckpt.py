"""Sharded + single-file checkpoints for the distributed IVF indexes
(per-process part files, manifest-as-commit-marker, fold-merge loads
onto smaller meshes).

Integrity: every write goes through the atomic write-to-temp-then-
rename container codec with per-array CRC-32C checksums
(core/serialize.py); loads VERIFY them, and on a replicated index's
checkpoint (build `replication=` / `mnmg.replicate_index`) a corrupt
shard table detected by checksum is HEALED from a peer's mirror slice
— the replica copies saved alongside the primaries — instead of
crashing or (worse) silently serving flipped bits. Chaos site
"ckpt.corrupt_file" flips seeded data-region bytes right after a save
so the detect-and-heal path is drillable (ci/test.sh chaos)."""


import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.core.serialize import ChecksumError, serialize_arrays
from raft_tpu.comms.comms import Comms
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.comms.mnmg_common import _ranks_by_proc
from raft_tpu.comms.mnmg_ivf_build import (
    DistributedIvfFlat, DistributedIvfPq, _place_rank_major,
)

CORRUPT_SITE = "ckpt.corrupt_file"


def _write_ckpt(filename: str, arrays: dict, meta: dict) -> None:
    """The ONE checkpoint write path: atomic checksummed container write
    + the "ckpt.corrupt_file" injection site (post-rename, so the drill
    models bit-rot of a COMMITTED checkpoint, not a torn write)."""
    from raft_tpu.core.serialize import container_data_start

    serialize_arrays(filename, arrays, meta)
    faults.corrupt_file(CORRUPT_SITE, filename,
                        start=container_data_start(filename),
                        rank=jax.process_index())


def _replica_arrays(index, store_name: str) -> dict:
    """The mirror payload a replicated index's checkpoint carries: the
    ring replica copies of the shard tables ((R, r-1, ...) rank-major)
    plus the matching fill-count mirror. A load that finds a corrupt
    primary array re-materializes it from these — each rank's slice
    here was WRITTEN by its peer holder, so one flipped shard never
    loses data (see _heal_from_mirrors)."""
    rep = getattr(index, "replicas", None)
    if rep is None:
        return {}
    sizes = np.asarray(index.list_sizes)
    r = rep.r
    R = sizes.shape[0]
    rep_sizes = np.stack(
        [sizes[(np.arange(R) - 1 - m) % R] for m in range(r - 1)], axis=1)
    out = {
        "replica_store": np.asarray(rep.tables[store_name]),
        "replica_gids": np.asarray(rep.tables["slot_gids"]),
        "replica_sizes": rep_sizes,
    }
    if "aux" in rep.tables:  # IVF-RaBitQ: the correction table mirrors too
        out["replica_aux"] = np.asarray(rep.tables["aux"])
    return out


def _heal_from_mirrors(filename: str, arrays: dict, meta: dict,
                       bad: list, store_key: str,
                       extra_healable: dict = None) -> dict:
    """Heal a single-file checkpoint whose shard tables failed checksum
    verification, using the replica mirror arrays (written by the peer
    holders): primary[u] is rebuilt from holder (u+1)'s slot-0 copy.
    Corrupt MIRROR arrays are merely dropped (live replicas re-derive
    from the healed primaries at load); a primary whose mirror is also
    gone — or an unmirrored field (quantizers) — is unrecoverable and
    raises the ChecksumError. `extra_healable` adds index-specific
    primary->mirror pairs (IVF-RaBitQ's correction table)."""
    r = int(meta.get("replication", 1))
    mirror_fields = {"replica_store", "replica_gids", "replica_sizes"}
    healable = {store_key: "replica_store", "host_gids": "replica_gids",
                "list_sizes": "replica_sizes"}
    if extra_healable:
        healable.update(extra_healable)
        mirror_fields |= set(extra_healable.values())
    prim_bad = [b for b in bad if b not in mirror_fields]
    healed = dict(arrays)
    for b in set(bad) & mirror_fields:
        healed.pop(b, None)
    if not prim_bad:
        obs.event("ckpt.heal", file=filename, fields=sorted(bad),
                  source="dropped_mirrors")
        return healed
    if r <= 1:
        raise ChecksumError(filename, bad)
    R = int(meta["n_ranks"])
    src = (np.arange(R) + 1) % R  # slot 0 of rank u+1 holds u's shard
    recovered = set()
    # gid tables heal before sizes: the sizes fallback derives from gid
    # pads, which is valid for ORIGINAL-clean *or* just-healed gids
    order = [store_key, "host_gids", "list_sizes"]
    for b in sorted(prim_bad, key=lambda x: (order.index(x)
                                             if x in order else len(order))):
        mirror = healable.get(b)
        if mirror is not None and mirror not in bad:
            healed[b] = np.ascontiguousarray(
                np.asarray(arrays[mirror])[src, 0])
        elif (b == "list_sizes"
              and ("host_gids" not in bad or "host_gids" in recovered)):
            # fill counts re-derive from the (clean or healed) gid pads
            healed[b] = (np.asarray(healed["host_gids"]) >= 0).sum(
                axis=-1).astype(np.int32)
        else:
            raise ChecksumError(filename, bad)
        recovered.add(b)
    obs.event("ckpt.heal", file=filename, fields=sorted(prim_bad),
              source="mirror")
    return healed


def _fold_merge_tables(store, gids, sizes, r: int):
    """Merge a checkpoint's `fold` stored ranks per mesh rank: per-list
    slots concatenate along the slot axis (all hold global ids), then
    valid slots are compacted to a prefix (extend appends at
    list_sizes[l], which assumes no interior pad gaps)."""
    r_stored = store.shape[0]
    fold = r_stored // r
    n_lists, max_list = store.shape[1], store.shape[2]
    trail = store.shape[3:]
    store = store.reshape(r, fold, n_lists, max_list, *trail)
    store = np.moveaxis(store, 1, 2).reshape(r, n_lists, fold * max_list, *trail)
    gids = gids.reshape(r, fold, n_lists, max_list)
    gids = np.moveaxis(gids, 1, 2).reshape(r, n_lists, fold * max_list)
    sizes = sizes.reshape(r, fold, n_lists).sum(axis=1)
    pad_last = np.argsort(gids < 0, axis=-1, kind="stable")
    gids = np.take_along_axis(gids, pad_last, axis=-1)
    idx = pad_last.reshape(pad_last.shape + (1,) * len(trail))
    store = np.take_along_axis(store, idx, axis=2)
    return store, gids, sizes


def _load_rank_tables(store_np, gids_np, sizes_np, r_stored: int, r: int):
    """Shared loader scaffolding: re-shard a checkpoint's rank-major
    tables onto an r-rank mesh (fold-merge when smaller), else copy the
    deserializer's read-only views into writable mirrors."""
    if r_stored != r:
        if r_stored % r != 0:
            raise ValueError(
                f"stored rank count {r_stored} not divisible by mesh size {r}"
            )
        return _fold_merge_tables(store_np, gids_np, sizes_np, r)
    # copy: the deserializer hands out read-only frombuffer views and
    # every other constructor path provides writable host mirrors
    return store_np, gids_np.copy(), sizes_np


def ivf_flat_save(filename: str, index: DistributedIvfFlat) -> None:
    """Serialize a distributed IVF-Flat index (centers + rank-major list
    stores + fill counts); `ivf_flat_load` re-shards onto the loading
    session's mesh (see ivf_pq_save for the layout contract). A
    replicated index also writes its mirror tables, making the
    checkpoint itself shard-redundant: a corrupt primary array heals
    from the mirrors at load."""
    if index.host_gids is None or index.list_sizes is None:
        raise ValueError("index lacks host mirrors; rebuild with ivf_flat_build")
    if index.comms.spans_processes():
        # sharded tables span non-addressable devices; serializing needs a
        # single-controller session (re-load the checkpoint there)
        raise ValueError("distributed save is single-controller")
    rep = getattr(index, "replicas", None)
    _write_ckpt(
        filename,
        {
            "centers": index.centers,
            "list_data": index.list_data,
            "host_gids": index.host_gids,
            "list_sizes": index.list_sizes,
            **_replica_arrays(index, "list_data"),
        },
        {
            "kind": "mnmg_ivf_flat",
            "version": 1,
            "n": index.n,
            "n_ranks": int(index.list_data.shape[0]),
            "metric": int(index.params.metric),
            "n_lists": index.params.n_lists,
            "bridged": bool(getattr(index, "bridged", False)),
            "replication": int(rep.r) if rep is not None else 1,
        },
    )


def _save_local_impl(filename: str, index, store_arr, kind: str,
                     quant_arrays: dict, extra_meta: dict) -> None:
    """Collective sharded checkpoint: every process writes ITS ranks'
    tables to `{filename}.part{pi}` (device shards leave via
    addressable_shards — no cross-process gather, no single host ever
    holding the full index), process 0 writes the manifest (replicated
    quantizers + the rank->part map), and a global barrier makes the
    checkpoint complete when the call returns. The orbax-style
    per-process layout; `ivf_*_load` re-assembles on any mesh whose
    size divides the stored rank count."""
    comms = index.comms
    if getattr(index, "bridged", False):
        raise ValueError(
            "bridged (distribute_index) layouts checkpoint via the "
            "single-chip index they were distributed from"
        )
    local_gids, local_sizes = index.local_gids, index.local_sizes
    if local_gids is None or local_sizes is None:
        if index.host_gids is not None and index.list_sizes is not None:
            # classic single-controller build: derive this process's
            # slices from the global host mirrors
            local_gids, local_sizes = _local_mirror_slices(
                comms, np.asarray(index.host_gids),
                np.asarray(index.list_sizes))
        else:
            raise ValueError(
                "index lacks the per-process mirrors a sharded save "
                "writes (kept by *_build_local builds, *_build builds, "
                "and checkpoint loads)"
            )
    ranks_by_proc = _ranks_by_proc(comms.mesh)
    pi = jax.process_index()
    my_ranks = ranks_by_proc.get(pi, [])

    def local_rows(arr):
        shards = {int(s.index[0].start or 0): np.asarray(s.data)
                  for s in arr.addressable_shards}
        return np.concatenate([shards[j] for j in my_ranks], axis=0)

    part_arrays = {"store": local_rows(store_arr), "gids": local_gids,
                   "sizes": local_sizes}
    rep = getattr(index, "replicas", None)
    if rep is not None:
        # each part also carries THIS process's hosted replica slots
        # ((lranks, r-1, ...) mirror copies of its ring predecessors'
        # shards) — the peer slices a corrupt part heals from at load
        store_name = "codes" if hasattr(index, "codes") else "list_data"
        part_arrays["mirror_store"] = local_rows(rep.tables[store_name])
        part_arrays["mirror_gids"] = local_rows(rep.tables["slot_gids"])
    _write_ckpt(
        f"{filename}.part{pi}",
        part_arrays,
        {"kind": kind + "_part", "ranks": [int(j) for j in my_ranks]},
    )

    def barrier(tag):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"raft_tpu_save_local:{kind}:{tag}")

    # manifest-as-commit-marker (the orbax ordering): every part must be
    # complete on disk BEFORE the manifest exists, so a mid-save crash
    # leaves no valid-looking manifest pointing at torn part files
    barrier("parts")
    if pi == 0:
        nproc = jax.process_count()
        _write_ckpt(
            filename,
            quant_arrays,
            {
                "kind": kind,
                "version": 1,
                "n": index.n,
                "n_ranks": comms.get_size(),
                "n_parts": nproc,
                "parts": [[int(j) for j in ranks_by_proc.get(p, [])]
                          for p in range(nproc)],
                "replication": int(rep.r) if rep is not None else 1,
                **extra_meta,
            },
        )
    barrier("manifest")  # loads issued right after return see it


def _load_local_tables(comms: Comms, filename: str, meta: dict):
    """Per-process assembly of a sharded checkpoint: read only the part
    files covering THIS process's mesh ranks (fold-merging when the
    mesh is smaller than the stored rank count). Returns host
    (store, gids, sizes) for this process's ranks, mesh-rank order.

    Checksum-verified: a part whose primary tables fail CRC is healed
    rank by rank from the mirror slices its ring peers' parts carry
    (checkpoints of replicated indexes; `meta["replication"]` > 1) —
    only when no intact copy of a needed shard exists anywhere does the
    load raise `ChecksumError`."""
    from raft_tpu.core.serialize import deserialize_arrays_checked

    r = comms.get_size()
    r_stored = int(meta["n_ranks"])
    rep_r = int(meta.get("replication", 1))
    if r_stored % r:
        raise ValueError(
            f"stored rank count {r_stored} not divisible by mesh size {r}"
        )
    fold = r_stored // r
    my_ranks = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
    needed = [j * fold + k for j in my_ranks for k in range(fold)]
    where = {}
    for p, ranks in enumerate(meta["parts"]):
        for row, g in enumerate(ranks):
            where[int(g)] = (p, row)
    missing = [g for g in needed if g not in where]
    if missing:
        raise ValueError(f"manifest maps no part for stored ranks {missing}")

    part_cache: dict = {}

    def read_part(p):
        if p not in part_cache:
            arrays, _, bad = deserialize_arrays_checked(
                f"{filename}.part{p}", to_device=False)
            part_cache[p] = (arrays, set(bad))
        return part_cache[p]

    def heal_rank(g):
        """Rebuild stored rank g's tables from a peer part's mirror
        slice (holder h = g+1+m hosts g's copy at slot m)."""
        for m in range(rep_r - 1):
            h = (g + 1 + m) % r_stored
            loc = where.get(h)
            if loc is None:
                continue
            p2, row2 = loc
            arrays2, bad2 = read_part(p2)
            if ("mirror_store" not in arrays2
                    or {"mirror_store", "mirror_gids"} & bad2):
                continue
            mg = np.asarray(arrays2["mirror_gids"])[row2, m]
            ms = np.asarray(arrays2["mirror_store"])[row2, m]
            obs.event("ckpt.heal", file=f"{filename}.part{where[g][0]}",
                      rank=int(g), holder=int(h), source="mirror")
            return ms, mg, (mg >= 0).sum(axis=-1).astype(np.int32)
        raise ChecksumError(f"{filename}.part{where[g][0]}",
                            ["store", "gids"])

    by_part = {}
    for g in needed:
        p, row = where[g]
        by_part.setdefault(p, []).append((g, row))
    rows = {}
    for p, entries in by_part.items():
        arrays, bad = read_part(p)
        store_p = np.asarray(arrays["store"])
        gids_p = np.asarray(arrays["gids"])
        sizes_p = np.asarray(arrays["sizes"])
        if {"store", "gids"} & bad:
            for g, _row in entries:
                rows[g] = heal_rank(g)
            continue
        if "sizes" in bad:
            # gids verified clean: fill counts re-derive from the pads
            sizes_p = (gids_p >= 0).sum(axis=-1).astype(np.int32)
            obs.event("ckpt.heal", file=f"{filename}.part{p}",
                      fields=["sizes"], source="gids")
        for g, row in entries:
            rows[g] = (store_p[row], gids_p[row], sizes_p[row])
    store = np.stack([rows[g][0] for g in needed])
    gids = np.stack([rows[g][1] for g in needed])
    sizes = np.stack([rows[g][2] for g in needed])
    if fold > 1:
        store, gids, sizes = _fold_merge_tables(store, gids, sizes,
                                                len(my_ranks))
    return store, gids, sizes.astype(np.int32)


def _local_mirror_slices(comms: Comms, gids: np.ndarray, sizes: np.ndarray):
    """This process's rank slices of a checkpoint's rank-major host
    tables — the per-process mirrors that make `*_extend_local` work on
    loaded indexes (each controller keeps only its own ranks' mirrors,
    in `_ranks_by_proc` order to match `_pack_local_tables`)."""
    my_ranks = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
    return (gids[my_ranks].copy(),
            sizes[my_ranks].astype(np.int32).copy())


def ivf_flat_save_local(filename: str, index: DistributedIvfFlat) -> None:
    """Collective sharded checkpoint of a distributed IVF-Flat index:
    every controller writes its own ranks' tables (`{filename}.part{p}`),
    process 0 the manifest — no single host ever materializes the full
    index (the pod-scale checkpoint path; `ivf_flat_save` needs a
    single-controller session). Load with `ivf_flat_load` on any mesh
    whose size divides the stored rank count (shared-fs contract)."""
    _save_local_impl(
        filename, index, index.list_data, "mnmg_ivf_flat_sharded",
        {"centers": np.asarray(index.centers.addressable_shards[0].data)},
        {"metric": int(index.params.metric),
         "n_lists": index.params.n_lists},
    )


def _load_verified(filename: str, store_key: str, extra_healable: dict = None):
    """Checked read of a single-file/manifest container: checksum
    failures on the primary shard tables heal from the in-file mirrors
    (`_heal_from_mirrors`); anything else raises `ChecksumError`."""
    from raft_tpu.core.serialize import (
        check_ckpt_version, deserialize_arrays_checked,
    )

    arrays, meta, bad = deserialize_arrays_checked(filename, to_device=False)
    # version gate BEFORE the heal: a newer-than-library checkpoint may
    # carry fields whose heal semantics this build cannot know
    check_ckpt_version(meta, filename)
    if bad:
        arrays = _heal_from_mirrors(filename, arrays, meta, bad, store_key,
                                    extra_healable=extra_healable)
    return arrays, meta


def _reattach_replicas(index, meta):
    """Re-mirror a loaded index at its checkpoint's replication factor
    (device-side ppermutes of the freshly loaded primaries — always
    coherent, even when the checkpoint's own mirror arrays healed the
    load)."""
    # fold-merge loads can land on a mesh smaller than r: clamp — r
    # copies of every shard cannot outnumber the ranks holding them
    r = min(int(meta.get("replication", 1)), index.comms.get_size())
    if r > 1:
        from raft_tpu.comms.replication import replicate_index

        replicate_index(index, r)
    return index


def ivf_flat_load(comms: Comms, filename: str) -> DistributedIvfFlat:
    """Load a distributed IVF-Flat index — a single-file checkpoint
    (`ivf_flat_save`) or a sharded one (`ivf_flat_save_local`) —
    re-sharding onto this session's mesh (stored rank count must be a
    multiple of the mesh size). Checksum-verified; corrupt shard tables
    heal from the checkpoint's mirror slices, and a `replication` > 1
    checkpoint comes back with live replicas attached."""
    from raft_tpu.neighbors import ivf_flat as ivf_flat_mod

    # chaos site: flaky/slow reads — `resilience.rehydrate` retries this
    faults.fault_point("mnmg_ckpt.load", rank=jax.process_index())
    arrays, meta = _load_verified(filename, "list_data")
    if meta.get("kind") == "mnmg_ivf_flat_sharded":
        ldata, gids_l, sizes_l = _load_local_tables(comms, filename, meta)
        params = ivf_flat_mod.IndexParams(
            n_lists=int(meta["n_lists"]), metric=DistanceType(meta["metric"])
        )
        return _reattach_replicas(DistributedIvfFlat(
            comms,
            params,
            comms.replicate(jnp.asarray(arrays["centers"])),
            comms.shard_from_local(ldata, axis=0),
            comms.shard_from_local(gids_l, axis=0),
            int(meta["n"]),
            # single-controller mesh: this process's assembly IS the full
            # rank-major table, so classic extend/save work too; spanning
            # meshes keep only the per-process mirrors
            host_gids=None if comms.spans_processes() else gids_l,
            list_sizes=None if comms.spans_processes() else sizes_l,
            local_gids=gids_l,
            local_sizes=sizes_l,
        ), meta)
    if meta.get("kind") != "mnmg_ivf_flat":
        raise ValueError(f"not a distributed ivf_flat file: {meta.get('kind')}")
    r = comms.get_size()
    ldata, gids, sizes = _load_rank_tables(
        np.asarray(arrays["list_data"]), np.asarray(arrays["host_gids"]),
        np.asarray(arrays["list_sizes"]), int(meta["n_ranks"]), r,
    )
    params = ivf_flat_mod.IndexParams(
        n_lists=int(meta["n_lists"]), metric=DistanceType(meta["metric"])
    )
    local_gids, local_sizes = _local_mirror_slices(comms, gids, sizes)
    return _reattach_replicas(DistributedIvfFlat(
        comms,
        params,
        comms.replicate(jnp.asarray(arrays["centers"])),
        _place_rank_major(comms, ldata),
        _place_rank_major(comms, gids),
        int(meta["n"]),
        # global host mirrors only where extend/save can consume them: on
        # a spanning mesh both raise, and the mirrors are index-sized host
        # RAM pinned on EVERY controller for nothing; the per-process
        # slices below keep the collective extend_local available there
        host_gids=None if comms.spans_processes() else gids,
        list_sizes=None if comms.spans_processes() else sizes.astype(np.int32),
        bridged=bool(meta.get("bridged", False)),
        local_gids=local_gids,
        local_sizes=local_sizes,
    ), meta)


def ivf_pq_save(filename: str, index: DistributedIvfPq) -> None:
    """Serialize a distributed IVF-PQ index (quantizers + the rank-major
    code/slot tables + fill counts) with the shared container codec —
    the pod-scale checkpoint/resume analogue of the single-chip
    ivf_pq.save (detail/ivf_pq_serialize.cuh). The rank-major layout is
    stored as-is; `ivf_pq_load` re-shards onto the loading session's mesh
    (any rank count whose padded geometry matches). A replicated index
    also writes its mirror tables (see ivf_flat_save)."""
    from raft_tpu.neighbors.ivf_pq import PER_CLUSTER

    if index.host_gids is None or index.list_sizes is None:
        raise ValueError("index lacks host mirrors; rebuild with ivf_pq_build")
    if index.comms.spans_processes():
        # sharded tables span non-addressable devices; serializing needs a
        # single-controller session (re-load the checkpoint there)
        raise ValueError("distributed save is single-controller")
    rep = getattr(index, "replicas", None)
    _write_ckpt(
        filename,
        {
            "rotation": index.rotation,
            "centers": index.centers,
            "pq_centers": index.pq_centers,
            "codes": index.codes,
            "host_gids": index.host_gids,
            "list_sizes": index.list_sizes,
            **_replica_arrays(index, "codes"),
        },
        {
            "kind": "mnmg_ivf_pq",
            "version": 1,
            "n": index.n,
            "n_ranks": int(index.codes.shape[0]),
            "metric": int(index.params.metric),
            "n_lists": index.params.n_lists,
            "pq_dim": int(index.codes.shape[-1]),
            "pq_bits": index.params.pq_bits,
            "per_cluster": index.params.codebook_kind == PER_CLUSTER,
            "extended": bool(getattr(index, "extended", False)),
            "bridged": bool(getattr(index, "bridged", False)),
            "replication": int(rep.r) if rep is not None else 1,
        },
    )


def ivf_pq_save_local(filename: str, index: DistributedIvfPq) -> None:
    """Collective sharded checkpoint of a distributed IVF-PQ index (see
    ivf_flat_save_local): per-process part files + a process-0 manifest
    with the replicated quantizers. Load with `ivf_pq_load`."""
    from raft_tpu.neighbors.ivf_pq import PER_CLUSTER

    _save_local_impl(
        filename, index, index.codes, "mnmg_ivf_pq_sharded",
        {"rotation": np.asarray(index.rotation.addressable_shards[0].data),
         "centers": np.asarray(index.centers.addressable_shards[0].data),
         "pq_centers": np.asarray(
             index.pq_centers.addressable_shards[0].data)},
        {"metric": int(index.params.metric),
         "n_lists": index.params.n_lists,
         "pq_dim": int(index.codes.shape[-1]),
         "pq_bits": index.params.pq_bits,
         "per_cluster": index.params.codebook_kind == PER_CLUSTER,
         "extended": bool(getattr(index, "extended", False))},
    )


def ivf_rabitq_save(filename: str, index) -> None:
    """Serialize a distributed IVF-RaBitQ index (rotation/centers + the
    rank-major packed-code, correction and slot tables + fill counts)
    through the shared CRC container. A replicated index also writes its
    mirror tables — including the correction-table mirror
    (`replica_aux`) — so a corrupt primary array heals at load exactly
    like the flat/PQ checkpoints."""
    if index.host_gids is None or index.list_sizes is None:
        raise ValueError(
            "index lacks host mirrors; rebuild with ivf_rabitq_build")
    if index.comms.spans_processes():
        # sharded tables span non-addressable devices; serializing needs
        # a single-controller session (re-load the checkpoint there)
        raise ValueError("distributed save is single-controller")
    rep = getattr(index, "replicas", None)
    _write_ckpt(
        filename,
        {
            "rotation": index.rotation,
            "centers": index.centers,
            "codes": index.codes,
            "aux": index.aux,
            "host_gids": index.host_gids,
            "list_sizes": index.list_sizes,
            **_replica_arrays(index, "codes"),
        },
        {
            "kind": "mnmg_ivf_rabitq",
            "version": 1,
            "n": index.n,
            "n_ranks": int(index.codes.shape[0]),
            "metric": int(index.params.metric),
            "n_lists": index.params.n_lists,
            "bridged": bool(getattr(index, "bridged", False)),
            "replication": int(rep.r) if rep is not None else 1,
        },
    )


def ivf_rabitq_load(comms: Comms, filename: str):
    """Load a distributed IVF-RaBitQ checkpoint, re-sharding onto this
    session's mesh (stored rank count must be a multiple of the mesh
    size; fold-merge shares the flat/PQ path). Checksum-verified:
    corrupt code/correction/slot tables heal from the checkpoint's
    mirror slices, and a `replication` > 1 checkpoint comes back with
    live replicas attached."""
    from raft_tpu.neighbors import ivf_rabitq as ivf_rabitq_mod
    from raft_tpu.comms.mnmg_rabitq import DistributedIvfRabitq

    # chaos site: flaky/slow reads — `resilience.rehydrate` retries this
    faults.fault_point("mnmg_ckpt.load", rank=jax.process_index())
    arrays, meta = _load_verified(filename, "codes",
                                  extra_healable={"aux": "replica_aux"})
    if meta.get("kind") != "mnmg_ivf_rabitq":
        raise ValueError(
            f"not a distributed ivf_rabitq file: {meta.get('kind')}")
    r = comms.get_size()
    codes, gids, sizes = _load_rank_tables(
        np.asarray(arrays["codes"]), np.asarray(arrays["host_gids"]),
        np.asarray(arrays["list_sizes"]), int(meta["n_ranks"]), r,
    )
    # the correction table re-shards under the SAME gid permutation
    # (fold-merge keys its slot compaction off the gids, which are
    # identical in both calls)
    aux, _, _ = _load_rank_tables(
        np.asarray(arrays["aux"]), np.asarray(arrays["host_gids"]),
        np.asarray(arrays["list_sizes"]), int(meta["n_ranks"]), r,
    )
    params = ivf_rabitq_mod.IndexParams(
        n_lists=int(meta["n_lists"]), metric=DistanceType(meta["metric"]),
        store_dataset=False,
    )
    return _reattach_replicas(DistributedIvfRabitq(
        comms,
        params,
        comms.replicate(jnp.asarray(arrays["rotation"])),
        comms.replicate(jnp.asarray(arrays["centers"])),
        _place_rank_major(comms, codes),
        _place_rank_major(comms, np.ascontiguousarray(aux)),
        _place_rank_major(comms, gids),
        int(meta["n"]),
        host_gids=None if comms.spans_processes() else gids,
        list_sizes=None if comms.spans_processes() else sizes.astype(np.int32),
        bridged=bool(meta.get("bridged", False)),
    ), meta)


def _pq_params_from_meta(meta):
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    return ivf_pq_mod.IndexParams(
        n_lists=int(meta["n_lists"]),
        pq_dim=int(meta["pq_dim"]),
        pq_bits=int(meta.get("pq_bits", 8)),
        metric=DistanceType(meta["metric"]),
        codebook_kind=(
            ivf_pq_mod.PER_CLUSTER if meta.get("per_cluster")
            else ivf_pq_mod.PER_SUBSPACE
        ),
    )


def ivf_pq_load(comms: Comms, filename: str) -> DistributedIvfPq:
    """Load a distributed IVF-PQ index — single-file (`ivf_pq_save`) or
    sharded (`ivf_pq_save_local`) — and re-shard it onto this session's
    mesh. The stored rank count must be divisible by (or equal to) the
    mesh size — shards are merged along the rank axis by concatenating
    slot tables (per-rank tables of the same list stack side by side).
    Checksum-verified with mirror healing (see ivf_flat_load)."""
    # chaos site: flaky/slow reads — `resilience.rehydrate` retries this
    faults.fault_point("mnmg_ckpt.load", rank=jax.process_index())
    # to_device=False: the unsharded tables are multi-GB at pod scale and
    # must never land whole on one device — they go host -> shards directly
    arrays, meta = _load_verified(filename, "codes")
    if meta.get("kind") == "mnmg_ivf_pq_sharded":
        codes_l, gids_l, sizes_l = _load_local_tables(comms, filename, meta)
        return _reattach_replicas(DistributedIvfPq(
            comms,
            _pq_params_from_meta(meta),
            comms.replicate(jnp.asarray(arrays["rotation"])),
            comms.replicate(jnp.asarray(arrays["centers"])),
            comms.replicate(jnp.asarray(arrays["pq_centers"])),
            comms.shard_from_local(codes_l, axis=0),
            comms.shard_from_local(gids_l, axis=0),
            int(meta["n"]),
            # see ivf_flat_load: full tables double as host mirrors on a
            # single-controller mesh
            host_gids=None if comms.spans_processes() else gids_l,
            list_sizes=None if comms.spans_processes() else sizes_l,
            extended=bool(meta.get("extended", False)),
            local_gids=gids_l,
            local_sizes=sizes_l,
        ), meta)
    if meta.get("kind") != "mnmg_ivf_pq":
        raise ValueError(f"not a distributed ivf_pq file: {meta.get('kind')}")
    r = comms.get_size()
    codes, gids, sizes = _load_rank_tables(
        np.asarray(arrays["codes"]), np.asarray(arrays["host_gids"]),
        np.asarray(arrays["list_sizes"]), int(meta["n_ranks"]), r,
    )
    params = _pq_params_from_meta(meta)
    local_gids, local_sizes = _local_mirror_slices(comms, gids, sizes)
    return _reattach_replicas(DistributedIvfPq(
        comms,
        params,
        comms.replicate(jnp.asarray(arrays["rotation"])),
        comms.replicate(jnp.asarray(arrays["centers"])),
        comms.replicate(jnp.asarray(arrays["pq_centers"])),
        _place_rank_major(comms, codes),
        _place_rank_major(comms, gids),
        int(meta["n"]),
        # global host mirrors only where extend/save can consume them: on
        # a spanning mesh both raise, and the mirrors are index-sized host
        # RAM pinned on EVERY controller for nothing; the per-process
        # slices keep the collective extend_local available there
        host_gids=None if comms.spans_processes() else gids,
        list_sizes=None if comms.spans_processes() else sizes.astype(np.int32),
        extended=bool(meta.get("extended", False)),
        bridged=bool(meta.get("bridged", False)),
        local_gids=local_gids,
        local_sizes=local_sizes,
    ), meta)
