"""Sharded + single-file checkpoints for the distributed IVF indexes
(per-process part files, manifest-as-commit-marker, fold-merge loads
onto smaller meshes)."""


import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.core import faults
from raft_tpu.comms.comms import Comms
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.comms.mnmg_common import _ranks_by_proc
from raft_tpu.comms.mnmg_ivf_build import (
    DistributedIvfFlat, DistributedIvfPq, _place_rank_major,
)


def _fold_merge_tables(store, gids, sizes, r: int):
    """Merge a checkpoint's `fold` stored ranks per mesh rank: per-list
    slots concatenate along the slot axis (all hold global ids), then
    valid slots are compacted to a prefix (extend appends at
    list_sizes[l], which assumes no interior pad gaps)."""
    r_stored = store.shape[0]
    fold = r_stored // r
    n_lists, max_list = store.shape[1], store.shape[2]
    trail = store.shape[3:]
    store = store.reshape(r, fold, n_lists, max_list, *trail)
    store = np.moveaxis(store, 1, 2).reshape(r, n_lists, fold * max_list, *trail)
    gids = gids.reshape(r, fold, n_lists, max_list)
    gids = np.moveaxis(gids, 1, 2).reshape(r, n_lists, fold * max_list)
    sizes = sizes.reshape(r, fold, n_lists).sum(axis=1)
    pad_last = np.argsort(gids < 0, axis=-1, kind="stable")
    gids = np.take_along_axis(gids, pad_last, axis=-1)
    idx = pad_last.reshape(pad_last.shape + (1,) * len(trail))
    store = np.take_along_axis(store, idx, axis=2)
    return store, gids, sizes


def _load_rank_tables(store_np, gids_np, sizes_np, r_stored: int, r: int):
    """Shared loader scaffolding: re-shard a checkpoint's rank-major
    tables onto an r-rank mesh (fold-merge when smaller), else copy the
    deserializer's read-only views into writable mirrors."""
    if r_stored != r:
        if r_stored % r != 0:
            raise ValueError(
                f"stored rank count {r_stored} not divisible by mesh size {r}"
            )
        return _fold_merge_tables(store_np, gids_np, sizes_np, r)
    # copy: the deserializer hands out read-only frombuffer views and
    # every other constructor path provides writable host mirrors
    return store_np, gids_np.copy(), sizes_np


def ivf_flat_save(filename: str, index: DistributedIvfFlat) -> None:
    """Serialize a distributed IVF-Flat index (centers + rank-major list
    stores + fill counts); `ivf_flat_load` re-shards onto the loading
    session's mesh (see ivf_pq_save for the layout contract)."""
    from raft_tpu.core.serialize import serialize_arrays

    if index.host_gids is None or index.list_sizes is None:
        raise ValueError("index lacks host mirrors; rebuild with ivf_flat_build")
    if index.comms.spans_processes():
        # sharded tables span non-addressable devices; serializing needs a
        # single-controller session (re-load the checkpoint there)
        raise ValueError("distributed save is single-controller")
    serialize_arrays(
        filename,
        {
            "centers": index.centers,
            "list_data": index.list_data,
            "host_gids": index.host_gids,
            "list_sizes": index.list_sizes,
        },
        {
            "kind": "mnmg_ivf_flat",
            "version": 1,
            "n": index.n,
            "n_ranks": int(index.list_data.shape[0]),
            "metric": int(index.params.metric),
            "n_lists": index.params.n_lists,
            "bridged": bool(getattr(index, "bridged", False)),
        },
    )


def _save_local_impl(filename: str, index, store_arr, kind: str,
                     quant_arrays: dict, extra_meta: dict) -> None:
    """Collective sharded checkpoint: every process writes ITS ranks'
    tables to `{filename}.part{pi}` (device shards leave via
    addressable_shards — no cross-process gather, no single host ever
    holding the full index), process 0 writes the manifest (replicated
    quantizers + the rank->part map), and a global barrier makes the
    checkpoint complete when the call returns. The orbax-style
    per-process layout; `ivf_*_load` re-assembles on any mesh whose
    size divides the stored rank count."""
    from raft_tpu.core.serialize import serialize_arrays

    comms = index.comms
    if getattr(index, "bridged", False):
        raise ValueError(
            "bridged (distribute_index) layouts checkpoint via the "
            "single-chip index they were distributed from"
        )
    local_gids, local_sizes = index.local_gids, index.local_sizes
    if local_gids is None or local_sizes is None:
        if index.host_gids is not None and index.list_sizes is not None:
            # classic single-controller build: derive this process's
            # slices from the global host mirrors
            local_gids, local_sizes = _local_mirror_slices(
                comms, np.asarray(index.host_gids),
                np.asarray(index.list_sizes))
        else:
            raise ValueError(
                "index lacks the per-process mirrors a sharded save "
                "writes (kept by *_build_local builds, *_build builds, "
                "and checkpoint loads)"
            )
    ranks_by_proc = _ranks_by_proc(comms.mesh)
    pi = jax.process_index()
    my_ranks = ranks_by_proc.get(pi, [])
    shards = {int(s.index[0].start or 0): np.asarray(s.data)
              for s in store_arr.addressable_shards}
    store_local = np.concatenate([shards[j] for j in my_ranks], axis=0)
    serialize_arrays(
        f"{filename}.part{pi}",
        {"store": store_local, "gids": local_gids, "sizes": local_sizes},
        {"kind": kind + "_part", "ranks": [int(j) for j in my_ranks]},
    )

    def barrier(tag):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"raft_tpu_save_local:{kind}:{tag}")

    # manifest-as-commit-marker (the orbax ordering): every part must be
    # complete on disk BEFORE the manifest exists, so a mid-save crash
    # leaves no valid-looking manifest pointing at torn part files
    barrier("parts")
    if pi == 0:
        nproc = jax.process_count()
        serialize_arrays(
            filename,
            quant_arrays,
            {
                "kind": kind,
                "version": 1,
                "n": index.n,
                "n_ranks": comms.get_size(),
                "n_parts": nproc,
                "parts": [[int(j) for j in ranks_by_proc.get(p, [])]
                          for p in range(nproc)],
                **extra_meta,
            },
        )
    barrier("manifest")  # loads issued right after return see it


def _load_local_tables(comms: Comms, filename: str, meta: dict):
    """Per-process assembly of a sharded checkpoint: read only the part
    files covering THIS process's mesh ranks (fold-merging when the
    mesh is smaller than the stored rank count). Returns host
    (store, gids, sizes) for this process's ranks, mesh-rank order."""
    from raft_tpu.core.serialize import deserialize_arrays

    r = comms.get_size()
    r_stored = int(meta["n_ranks"])
    if r_stored % r:
        raise ValueError(
            f"stored rank count {r_stored} not divisible by mesh size {r}"
        )
    fold = r_stored // r
    my_ranks = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
    needed = [j * fold + k for j in my_ranks for k in range(fold)]
    where = {}
    for p, ranks in enumerate(meta["parts"]):
        for row, g in enumerate(ranks):
            where[int(g)] = (p, row)
    missing = [g for g in needed if g not in where]
    if missing:
        raise ValueError(f"manifest maps no part for stored ranks {missing}")
    by_part = {}
    for g in needed:
        p, row = where[g]
        by_part.setdefault(p, []).append((g, row))
    rows = {}
    for p, entries in by_part.items():
        arrays, _ = deserialize_arrays(f"{filename}.part{p}", to_device=False)
        store_p = np.asarray(arrays["store"])
        gids_p = np.asarray(arrays["gids"])
        sizes_p = np.asarray(arrays["sizes"])
        for g, row in entries:
            rows[g] = (store_p[row], gids_p[row], sizes_p[row])
    store = np.stack([rows[g][0] for g in needed])
    gids = np.stack([rows[g][1] for g in needed])
    sizes = np.stack([rows[g][2] for g in needed])
    if fold > 1:
        store, gids, sizes = _fold_merge_tables(store, gids, sizes,
                                                len(my_ranks))
    return store, gids, sizes.astype(np.int32)


def _local_mirror_slices(comms: Comms, gids: np.ndarray, sizes: np.ndarray):
    """This process's rank slices of a checkpoint's rank-major host
    tables — the per-process mirrors that make `*_extend_local` work on
    loaded indexes (each controller keeps only its own ranks' mirrors,
    in `_ranks_by_proc` order to match `_pack_local_tables`)."""
    my_ranks = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
    return (gids[my_ranks].copy(),
            sizes[my_ranks].astype(np.int32).copy())


def ivf_flat_save_local(filename: str, index: DistributedIvfFlat) -> None:
    """Collective sharded checkpoint of a distributed IVF-Flat index:
    every controller writes its own ranks' tables (`{filename}.part{p}`),
    process 0 the manifest — no single host ever materializes the full
    index (the pod-scale checkpoint path; `ivf_flat_save` needs a
    single-controller session). Load with `ivf_flat_load` on any mesh
    whose size divides the stored rank count (shared-fs contract)."""
    _save_local_impl(
        filename, index, index.list_data, "mnmg_ivf_flat_sharded",
        {"centers": np.asarray(index.centers.addressable_shards[0].data)},
        {"metric": int(index.params.metric),
         "n_lists": index.params.n_lists},
    )


def ivf_flat_load(comms: Comms, filename: str) -> DistributedIvfFlat:
    """Load a distributed IVF-Flat index — a single-file checkpoint
    (`ivf_flat_save`) or a sharded one (`ivf_flat_save_local`) —
    re-sharding onto this session's mesh (stored rank count must be a
    multiple of the mesh size)."""
    from raft_tpu.core.serialize import deserialize_arrays
    from raft_tpu.neighbors import ivf_flat as ivf_flat_mod

    # chaos site: flaky/slow reads — `resilience.rehydrate` retries this
    faults.fault_point("mnmg_ckpt.load", rank=jax.process_index())
    arrays, meta = deserialize_arrays(filename, to_device=False)
    if meta.get("kind") == "mnmg_ivf_flat_sharded":
        ldata, gids_l, sizes_l = _load_local_tables(comms, filename, meta)
        params = ivf_flat_mod.IndexParams(
            n_lists=int(meta["n_lists"]), metric=DistanceType(meta["metric"])
        )
        return DistributedIvfFlat(
            comms,
            params,
            comms.replicate(jnp.asarray(arrays["centers"])),
            comms.shard_from_local(ldata, axis=0),
            comms.shard_from_local(gids_l, axis=0),
            int(meta["n"]),
            # single-controller mesh: this process's assembly IS the full
            # rank-major table, so classic extend/save work too; spanning
            # meshes keep only the per-process mirrors
            host_gids=None if comms.spans_processes() else gids_l,
            list_sizes=None if comms.spans_processes() else sizes_l,
            local_gids=gids_l,
            local_sizes=sizes_l,
        )
    if meta.get("kind") != "mnmg_ivf_flat":
        raise ValueError(f"not a distributed ivf_flat file: {meta.get('kind')}")
    r = comms.get_size()
    ldata, gids, sizes = _load_rank_tables(
        np.asarray(arrays["list_data"]), np.asarray(arrays["host_gids"]),
        np.asarray(arrays["list_sizes"]), int(meta["n_ranks"]), r,
    )
    params = ivf_flat_mod.IndexParams(
        n_lists=int(meta["n_lists"]), metric=DistanceType(meta["metric"])
    )
    local_gids, local_sizes = _local_mirror_slices(comms, gids, sizes)
    return DistributedIvfFlat(
        comms,
        params,
        comms.replicate(jnp.asarray(arrays["centers"])),
        _place_rank_major(comms, ldata),
        _place_rank_major(comms, gids),
        int(meta["n"]),
        # global host mirrors only where extend/save can consume them: on
        # a spanning mesh both raise, and the mirrors are index-sized host
        # RAM pinned on EVERY controller for nothing; the per-process
        # slices below keep the collective extend_local available there
        host_gids=None if comms.spans_processes() else gids,
        list_sizes=None if comms.spans_processes() else sizes.astype(np.int32),
        bridged=bool(meta.get("bridged", False)),
        local_gids=local_gids,
        local_sizes=local_sizes,
    )


def ivf_pq_save(filename: str, index: DistributedIvfPq) -> None:
    """Serialize a distributed IVF-PQ index (quantizers + the rank-major
    code/slot tables + fill counts) with the shared container codec —
    the pod-scale checkpoint/resume analogue of the single-chip
    ivf_pq.save (detail/ivf_pq_serialize.cuh). The rank-major layout is
    stored as-is; `ivf_pq_load` re-shards onto the loading session's mesh
    (any rank count whose padded geometry matches)."""
    from raft_tpu.core.serialize import serialize_arrays
    from raft_tpu.neighbors.ivf_pq import PER_CLUSTER

    if index.host_gids is None or index.list_sizes is None:
        raise ValueError("index lacks host mirrors; rebuild with ivf_pq_build")
    if index.comms.spans_processes():
        # sharded tables span non-addressable devices; serializing needs a
        # single-controller session (re-load the checkpoint there)
        raise ValueError("distributed save is single-controller")
    serialize_arrays(
        filename,
        {
            "rotation": index.rotation,
            "centers": index.centers,
            "pq_centers": index.pq_centers,
            "codes": index.codes,
            "host_gids": index.host_gids,
            "list_sizes": index.list_sizes,
        },
        {
            "kind": "mnmg_ivf_pq",
            "version": 1,
            "n": index.n,
            "n_ranks": int(index.codes.shape[0]),
            "metric": int(index.params.metric),
            "n_lists": index.params.n_lists,
            "pq_dim": int(index.codes.shape[-1]),
            "pq_bits": index.params.pq_bits,
            "per_cluster": index.params.codebook_kind == PER_CLUSTER,
            "extended": bool(getattr(index, "extended", False)),
            "bridged": bool(getattr(index, "bridged", False)),
        },
    )


def ivf_pq_save_local(filename: str, index: DistributedIvfPq) -> None:
    """Collective sharded checkpoint of a distributed IVF-PQ index (see
    ivf_flat_save_local): per-process part files + a process-0 manifest
    with the replicated quantizers. Load with `ivf_pq_load`."""
    from raft_tpu.neighbors.ivf_pq import PER_CLUSTER

    _save_local_impl(
        filename, index, index.codes, "mnmg_ivf_pq_sharded",
        {"rotation": np.asarray(index.rotation.addressable_shards[0].data),
         "centers": np.asarray(index.centers.addressable_shards[0].data),
         "pq_centers": np.asarray(
             index.pq_centers.addressable_shards[0].data)},
        {"metric": int(index.params.metric),
         "n_lists": index.params.n_lists,
         "pq_dim": int(index.codes.shape[-1]),
         "pq_bits": index.params.pq_bits,
         "per_cluster": index.params.codebook_kind == PER_CLUSTER,
         "extended": bool(getattr(index, "extended", False))},
    )


def _pq_params_from_meta(meta):
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    return ivf_pq_mod.IndexParams(
        n_lists=int(meta["n_lists"]),
        pq_dim=int(meta["pq_dim"]),
        pq_bits=int(meta.get("pq_bits", 8)),
        metric=DistanceType(meta["metric"]),
        codebook_kind=(
            ivf_pq_mod.PER_CLUSTER if meta.get("per_cluster")
            else ivf_pq_mod.PER_SUBSPACE
        ),
    )


def ivf_pq_load(comms: Comms, filename: str) -> DistributedIvfPq:
    """Load a distributed IVF-PQ index — single-file (`ivf_pq_save`) or
    sharded (`ivf_pq_save_local`) — and re-shard it onto this session's
    mesh. The stored rank count must be divisible by (or equal to) the
    mesh size — shards are merged along the rank axis by concatenating
    slot tables (per-rank tables of the same list stack side by side)."""
    from raft_tpu.core.serialize import deserialize_arrays

    # chaos site: flaky/slow reads — `resilience.rehydrate` retries this
    faults.fault_point("mnmg_ckpt.load", rank=jax.process_index())
    # to_device=False: the unsharded tables are multi-GB at pod scale and
    # must never land whole on one device — they go host -> shards directly
    arrays, meta = deserialize_arrays(filename, to_device=False)
    if meta.get("kind") == "mnmg_ivf_pq_sharded":
        codes_l, gids_l, sizes_l = _load_local_tables(comms, filename, meta)
        return DistributedIvfPq(
            comms,
            _pq_params_from_meta(meta),
            comms.replicate(jnp.asarray(arrays["rotation"])),
            comms.replicate(jnp.asarray(arrays["centers"])),
            comms.replicate(jnp.asarray(arrays["pq_centers"])),
            comms.shard_from_local(codes_l, axis=0),
            comms.shard_from_local(gids_l, axis=0),
            int(meta["n"]),
            # see ivf_flat_load: full tables double as host mirrors on a
            # single-controller mesh
            host_gids=None if comms.spans_processes() else gids_l,
            list_sizes=None if comms.spans_processes() else sizes_l,
            extended=bool(meta.get("extended", False)),
            local_gids=gids_l,
            local_sizes=sizes_l,
        )
    if meta.get("kind") != "mnmg_ivf_pq":
        raise ValueError(f"not a distributed ivf_pq file: {meta.get('kind')}")
    r = comms.get_size()
    codes, gids, sizes = _load_rank_tables(
        np.asarray(arrays["codes"]), np.asarray(arrays["host_gids"]),
        np.asarray(arrays["list_sizes"]), int(meta["n_ranks"]), r,
    )
    params = _pq_params_from_meta(meta)
    local_gids, local_sizes = _local_mirror_slices(comms, gids, sizes)
    return DistributedIvfPq(
        comms,
        params,
        comms.replicate(jnp.asarray(arrays["rotation"])),
        comms.replicate(jnp.asarray(arrays["centers"])),
        comms.replicate(jnp.asarray(arrays["pq_centers"])),
        _place_rank_major(comms, codes),
        _place_rank_major(comms, gids),
        int(meta["n"]),
        # global host mirrors only where extend/save can consume them: on
        # a spanning mesh both raise, and the mirrors are index-sized host
        # RAM pinned on EVERY controller for nothing; the per-process
        # slices keep the collective extend_local available there
        host_gids=None if comms.spans_processes() else gids,
        list_sizes=None if comms.spans_processes() else sizes.astype(np.int32),
        extended=bool(meta.get("extended", False)),
        bridged=bool(meta.get("bridged", False)),
        local_gids=local_gids,
        local_sizes=local_sizes,
    )
