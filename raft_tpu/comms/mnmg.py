"""Multi-node-multi-device (MNMG) algorithms over the comms layer.

Reference parity: RAFT's MNMG story (survey §2.15/§3.4/§5.7-5.8): algorithms
are written against `handle.get_comms()`; raft-dask shards the dataset over
workers; k-means wraps each iteration in allreduce of partial sums; ANN
search does shard-local top-k then merges (knn_merge_parts). The reference
keeps the MNMG drivers in cuML/cuGraph — here they are in-tree, expressed as
shard_map SPMD programs over the Comms mesh.

All functions take a `Comms` session; arrays are host/global arrays that get
sharded row-wise (equal shards, padded) across the comms axis.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.comms.comms import Comms, AxisComms, op_t
from raft_tpu.cluster.kmeans_common import assign_and_reduce
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu.distance.distance_types import DistanceType, resolve_metric


def _shard_rows(comms: Comms, x: np.ndarray):
    """Pad rows to a multiple of n_ranks and shard; returns (sharded, n, wpr)."""
    n = x.shape[0]
    r = comms.get_size()
    per = -(-n // r)
    pad = per * r - n
    xp = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)]) if pad else x
    return comms.shard(xp, axis=0), n, per


def _valid_weights(n: int, per: int, r: int) -> np.ndarray:
    w = np.zeros(per * r, np.float32)
    w[:n] = 1.0
    return w


def _merge_local_topk(ac: AxisComms, v, ids, k: int, select_min: bool):
    """Merge per-rank local top-k candidates into a global top-k on every
    rank (the knn_merge_parts pattern, neighbors/detail/knn_merge_parts.cuh):
    allgather the (nq, kk) shard results, interleave rank-major -> row-major,
    and re-select. `ids` must already be global (invalid entries masked to
    the worst value in `v` by the caller). Call inside shard_map."""
    kk = v.shape[-1]
    gv = ac.allgather(v[None], axis=0)  # (R, ..., nq, kk)
    gi = ac.allgather(ids[None], axis=0)
    r_ = gv.shape[0]
    cat_v = jnp.moveaxis(gv.reshape(r_, -1, kk), 0, 1).reshape(-1, r_ * kk)
    cat_i = jnp.moveaxis(gi.reshape(r_, -1, kk), 0, 1).reshape(-1, r_ * kk)
    mv, mp = _select_k_impl(cat_v, min(k, r_ * kk), select_min)
    return mv, jnp.take_along_axis(cat_i, mp, axis=1)


# ---------------------------------------------------------------------------
# distributed k-means
# ---------------------------------------------------------------------------


def kmeans_fit(
    comms: Comms,
    X,
    n_clusters: int,
    max_iter: int = 100,
    tol: float = 1e-4,
    seed: int = 0,
) -> Tuple[jax.Array, float, int]:
    """Distributed Lloyd: shard rows, allreduce partial sums per iteration
    (survey §3.4 MNMG variant). Returns (centers, inertia, n_iter)."""
    x = np.asarray(X, np.float32)
    xs, n, per = _shard_rows(comms, x)
    w = comms.shard(_valid_weights(n, per, comms.get_size()), axis=0)

    # init: global k-means++ on a gathered subsample (cheap, build-time)
    rng = np.random.default_rng(seed)
    sub = x[rng.choice(n, min(n, max(n_clusters * 8, 1024)), replace=False)]
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    centers = _kmeans_plusplus(jax.random.PRNGKey(seed), jnp.asarray(sub), n_clusters)
    centers = comms.replicate(centers)

    ac = comms.comms

    @jax.jit
    def step(xs, w, centers):
        def body(xs, w, centers):
            _, sums, counts, inertia = assign_and_reduce(xs, centers, w)
            sums = ac.allreduce(sums)
            counts = ac.allreduce(counts)
            inertia = ac.allreduce(inertia)
            safe = jnp.maximum(counts, 1.0)[:, None]
            new_centers = jnp.where(counts[:, None] > 0, sums / safe, centers)
            shift = jnp.sum((new_centers - centers) ** 2)
            return new_centers, inertia, shift

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None), P(comms.axis), P(None, None)),
            out_specs=(P(None, None), P(), P()), check_vma=False,
        )(xs, w, centers)

    inertia = np.inf
    it = 0
    for it in range(1, max_iter + 1):
        centers, inertia, shift = step(xs, w, centers)
        if float(shift) < tol * tol:
            break
    return centers, float(inertia), it


def kmeans_predict(comms: Comms, X, centers) -> jax.Array:
    """Distributed assignment; returns global labels (n,) on host order."""
    x = np.asarray(X, np.float32)
    xs, n, per = _shard_rows(comms, x)
    c = comms.replicate(jnp.asarray(centers, jnp.float32))
    ac = comms.comms

    @jax.jit
    def run(xs, c):
        def body(xs, c):
            labels, _, _, _ = assign_and_reduce(xs, c, needs_sums=False)
            return labels

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None), P(None, None)),
            out_specs=P(comms.axis), check_vma=False,
        )(xs, c)

    return run(xs, c)[:n]


# ---------------------------------------------------------------------------
# distributed brute-force k-NN
# ---------------------------------------------------------------------------


def knn(
    comms: Comms,
    dataset,
    queries,
    k: int,
    metric="sqeuclidean",
) -> Tuple[jax.Array, jax.Array]:
    """Shard-local exact kNN + allgather + merge (knn_merge_parts pattern,
    survey §5.7). Queries are replicated; dataset is sharded by rows."""
    from raft_tpu.neighbors.brute_force import _bf_knn_impl

    m = resolve_metric(metric)
    x = np.asarray(dataset, np.float32)
    q = jnp.asarray(queries, jnp.float32)
    xs, n, per = _shard_rows(comms, x)
    qr = comms.replicate(q)
    ac = comms.comms
    select_min = m != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    kk = int(min(k, per))

    @jax.jit
    def run(xs, qr):
        def body(xs, qr):
            rank = ac.get_rank()
            v, i = _bf_knn_impl(xs, qr, kk, m)
            # mask out padded rows (global row id >= n)
            gid = i.astype(jnp.int32) + rank.astype(jnp.int32) * per
            v = jnp.where(gid < n, v, worst)
            return _merge_local_topk(ac, v, gid, k, select_min)

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None), P(None, None)),
            out_specs=(P(None, None), P(None, None)), check_vma=False,
        )(xs, qr)

    return run(xs, qr)


# ---------------------------------------------------------------------------
# distributed ANN (IVF-Flat / IVF-PQ): shard rows, shared centers,
# per-shard slot tables, merge local top-k
# ---------------------------------------------------------------------------


class DistributedIvfFlat:
    """Data-parallel IVF-Flat: global coarse centers (distributed k-means),
    per-rank list-major stores over the local shard, searched SPMD + merged.

    list_data (R, n_lists, max_list, d) and slot_gids (R, n_lists, max_list)
    are sharded on axis 0; slot_gids holds GLOBAL dataset row ids (-1 pad),
    so shard-local search results merge without id translation."""

    def __init__(self, comms, params, centers, list_data, slot_gids, n):
        self.comms = comms
        self.params = params
        self.centers = centers
        self.list_data = list_data
        self.slot_gids = slot_gids
        self.n = n


def ivf_flat_build(comms: Comms, params, dataset, seed: int = 0) -> DistributedIvfFlat:
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    x = np.asarray(dataset, np.float32)
    n, d = x.shape
    r = comms.get_size()
    per = -(-n // r)

    # global centers: distributed kmeans on the full data (balanced-ish)
    centers, _, _ = kmeans_fit(comms, x, params.n_lists, max_iter=params.kmeans_n_iters, seed=seed)
    labels = np.asarray(kmeans_predict(comms, x, centers))

    # per-rank list-major packing to one shared max_list size
    tables = []
    max_list = 1
    for rr in range(r):
        lo, hi = rr * per, min((rr + 1) * per, n)
        t, _ = _pack_lists(labels[lo:hi], params.n_lists)
        tables.append((t, lo))
        max_list = max(max_list, t.shape[1])
    gids = np.full((r, params.n_lists, max_list), -1, np.int32)
    ldata = np.zeros((r, params.n_lists, max_list, d), np.float32)
    for rr, (t, lo) in enumerate(tables):
        valid = t >= 0
        gids[rr, :, : t.shape[1]][valid] = t[valid] + lo
        ldata[rr, :, : t.shape[1]][valid] = x[t[valid] + lo]
    return DistributedIvfFlat(
        comms,
        params,
        comms.replicate(jnp.asarray(centers)),
        comms.shard(jnp.asarray(ldata), axis=0),
        comms.shard(jnp.asarray(gids), axis=0),
        n,
    )


class DistributedIvfPq:
    """Data-parallel IVF-PQ: rotation/coarse centers/codebooks trained once
    on a subsample (replicated), per-rank bit-code tables over the local
    shard, searched SPMD + merged.

    codes (R, n_lists, max_list, pq_dim) uint8 and slot_gids
    (R, n_lists, max_list) int32 are sharded on axis 0; slot_gids holds
    GLOBAL dataset row ids (-1 pad), so shard-local search results merge
    without id translation — the TPU equivalent of the reference's
    application-level MNMG ANN sharding (survey §5.7)."""

    def __init__(self, comms, params, rotation, centers, pq_centers, codes,
                 slot_gids, n):
        self.comms = comms
        self.params = params
        self.rotation = rotation
        self.centers = centers
        self.pq_centers = pq_centers
        self.codes = codes
        self.slot_gids = slot_gids
        self.n = n


def ivf_pq_build(comms: Comms, params, dataset, seed: int = 0) -> DistributedIvfPq:
    """Train once (subsample), encode per shard, pack per-rank tables."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    x = np.asarray(dataset, np.float32)
    n, d = x.shape
    r = comms.get_size()
    per = -(-n // r)

    # shared quantizers: single-device training on a subsample
    import dataclasses as _dc

    rng = np.random.default_rng(seed)
    n_sub = min(n, max(params.n_lists * 32, 8192))
    sub = x[rng.choice(n, n_sub, replace=False)]
    base = ivf_pq_mod.build(
        _dc.replace(params, add_data_on_build=False), sub, seed=seed
    )
    rotation = np.asarray(base.rotation)
    centers = np.asarray(base.centers)
    per_cluster = params.codebook_kind == ivf_pq_mod.PER_CLUSTER
    pq_dim = int(base.pq_centers.shape[0] if not per_cluster
                 else base.rot_dim // base.pq_centers.shape[-1])

    # label + encode every shard with the shared quantizers, pack per rank
    tables = []
    max_list = 1
    shard_codes = []
    for rr in range(r):
        lo, hi = rr * per, min((rr + 1) * per, n)
        if lo >= hi:  # empty trailing shard (n not divisible by ranks)
            tables.append((np.full((params.n_lists, 1), -1, np.int64), lo))
            shard_codes.append(np.zeros((0, pq_dim), np.uint8))
            continue
        labels, codes_local = ivf_pq_mod.label_and_encode(
            x[lo:hi], jnp.asarray(rotation), jnp.asarray(centers),
            base.pq_centers, params.metric, per_cluster,
        )
        t, _ = _pack_lists(np.asarray(labels), params.n_lists)
        tables.append((t, lo))
        shard_codes.append(np.asarray(codes_local))
        max_list = max(max_list, t.shape[1])

    gids = np.full((r, params.n_lists, max_list), -1, np.int32)
    ctbl = np.zeros((r, params.n_lists, max_list, pq_dim), np.uint8)
    for rr, (t, lo) in enumerate(tables):
        valid = t >= 0
        gids[rr, :, : t.shape[1]][valid] = t[valid] + lo
        ctbl[rr, :, : t.shape[1]][valid] = shard_codes[rr][t[valid]]
    return DistributedIvfPq(
        comms,
        params,
        comms.replicate(jnp.asarray(rotation)),
        comms.replicate(jnp.asarray(centers)),
        comms.replicate(base.pq_centers),
        comms.shard(jnp.asarray(ctbl), axis=0),
        comms.shard(jnp.asarray(gids), axis=0),
        n,
    )


def ivf_pq_search(index: DistributedIvfPq, queries, k: int, n_probes: int = 20):
    """SPMD search: every rank scores its local lists for the same global
    probes (LUT engine); local top-k are merged on all ranks."""
    from raft_tpu.neighbors.ivf_pq import _search_impl, PER_CLUSTER

    comms = index.comms
    ac = comms.comms
    q = comms.replicate(jnp.asarray(queries, jnp.float32))
    metric = index.params.metric
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    n_probes = int(min(n_probes, index.params.n_lists))
    per_cluster = index.params.codebook_kind == PER_CLUSTER

    @functools.partial(jax.jit, static_argnames=("k",))
    def run(rotation, centers, pq_centers, codes, gid_tbl, q, k: int):
        def body(rotation, centers, pq_centers, codes, gid_tbl, q):
            # slot table holds global ids, so _search_impl's ids are global
            v, gid = _search_impl(
                q, rotation, centers, pq_centers, codes[0], gid_tbl[0],
                k, n_probes, metric, per_cluster,
            )
            v = jnp.where(gid >= 0, v, worst)
            return _merge_local_topk(ac, v, gid, k, select_min)

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(None, None), P(None, None), P(None, None, None),
                      P(comms.axis, None, None, None), P(comms.axis, None, None),
                      P(None, None)),
            out_specs=(P(None, None), P(None, None)), check_vma=False,
        )(rotation, centers, pq_centers, codes, gid_tbl, q)

    return run(
        index.rotation, index.centers, index.pq_centers, index.codes,
        index.slot_gids, q, int(k),
    )


def ivf_flat_search(index: DistributedIvfFlat, queries, k: int, n_probes: int = 20):
    """SPMD search: every rank scans its local lists for the same global
    probes; local top-k are merged (all ranks produce the final result)."""
    from raft_tpu.neighbors.ivf_flat import _search_impl

    comms = index.comms
    ac = comms.comms
    q = comms.replicate(jnp.asarray(queries, jnp.float32))
    metric = index.params.metric
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    n_probes = int(min(n_probes, index.params.n_lists))

    @functools.partial(jax.jit, static_argnames=("k",))
    def run(ld, gid_tbl, centers, q, k: int):
        def body(ld, gid_tbl, centers, q):
            # slot table holds global ids, so _search_impl's ids are global
            v, gid = _search_impl(q, centers, ld[0], gid_tbl[0], k, n_probes, metric)
            v = jnp.where(gid >= 0, v, worst)
            return _merge_local_topk(ac, v, gid, k, select_min)

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None, None, None), P(comms.axis, None, None),
                      P(None, None), P(None, None)),
            out_specs=(P(None, None), P(None, None)), check_vma=False,
        )(ld, gid_tbl, centers, q)

    return run(index.list_data, index.slot_gids, index.centers, q, int(k))
