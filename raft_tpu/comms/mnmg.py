"""Multi-node-multi-device (MNMG) algorithms over the comms layer.

Reference parity: RAFT's MNMG story (survey §2.15/§3.4/§5.7-5.8): algorithms
are written against `handle.get_comms()`; raft-dask shards the dataset over
workers; k-means wraps each iteration in allreduce of partial sums; ANN
search does shard-local top-k then merges (knn_merge_parts). The reference
keeps the MNMG drivers in cuML/cuGraph — here they are in-tree, expressed as
shard_map SPMD programs over the Comms mesh.

All functions take a `Comms` session; arrays are host/global arrays that get
sharded row-wise (equal shards, padded) across the comms axis.


The implementation is split by concern (VERDICT r4 #9) — this module is
the stable public surface re-exporting every entry point:

  mnmg_common      shared sharding layouts, host mirrors, prefilter bits,
                   the serving-path jit wrapper cache
  mnmg_merge       top-k merge schedules + query-mode resolution
  mnmg_kmeans      distributed k-means (driver-sharded + *_local)
  mnmg_knn         distributed brute-force kNN
  mnmg_ivf_build   Distributed IVF index types, builds, extends, bridge
  mnmg_ckpt        sharded + single-file checkpoints
  mnmg_ivf_search  distributed searches (engines, refine, prefilters)
"""

from raft_tpu.comms.mnmg_common import (  # noqa: F401
    _cached_wrapper,
    _distributed_id_bound,
    _knn_prefilter_words,
    _local_layout,
    _metric_name,
    _pack_local,
    _pad_queries,
    _ranks_by_proc,
    _replicated_filter_bits,
    _shard_filtered,
    _shard_rows,
)
from raft_tpu.comms.mnmg_merge import (  # noqa: F401
    _merge_local_topk,
    _merge_local_topk_allgather,
    _merge_local_topk_scatter,
    _merge_local_topk_tournament,
    _pack_vi,
    _replicated_merge_schedule,
    _resolve_query_mode,
)
from raft_tpu.comms.mnmg_kmeans import (  # noqa: F401
    _kmeans_fit_sharded,
    _spmd_predict,
    kmeans_fit,
    kmeans_fit_local,
    kmeans_predict,
    kmeans_predict_local,
)
from raft_tpu.comms.mnmg_knn import (  # noqa: F401
    _knn_sharded,
    knn,
    knn_local,
)
from raft_tpu.comms.mnmg_ivf_build import (  # noqa: F401
    DistributedIvfFlat,
    DistributedIvfPq,
    _place_rank_major,
    _spmd_label_encode,
    distribute_index,
    ivf_flat_build,
    ivf_flat_build_local,
    ivf_flat_extend,
    ivf_flat_extend_local,
    ivf_pq_build,
    ivf_pq_build_local,
    ivf_pq_extend,
    ivf_pq_extend_local,
)
from raft_tpu.comms.mnmg_ckpt import (  # noqa: F401
    ivf_flat_load,
    ivf_flat_save,
    ivf_flat_save_local,
    ivf_pq_load,
    ivf_pq_save,
    ivf_pq_save_local,
    ivf_rabitq_load,
    ivf_rabitq_save,
)
from raft_tpu.comms.mnmg_rabitq import (  # noqa: F401
    DistributedIvfRabitq,
    ivf_rabitq_build,
    ivf_rabitq_search,
)
from raft_tpu.comms.mnmg_ivf_search import (  # noqa: F401
    _build_distributed_recon,
    _refine_layout,
    ivf_flat_search,
    ivf_pq_search,
)
from raft_tpu.comms.replication import (  # noqa: F401
    ReplicaPlacement,
    ShardReplicas,
    failover_view,
    replicate_index,
)
from raft_tpu.comms.recovery import (  # noqa: F401
    RecoveryError,
    heal,
    rank_rejoin,
    repair,
)
