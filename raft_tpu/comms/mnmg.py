"""Multi-node-multi-device (MNMG) algorithms over the comms layer.

Reference parity: RAFT's MNMG story (survey §2.15/§3.4/§5.7-5.8): algorithms
are written against `handle.get_comms()`; raft-dask shards the dataset over
workers; k-means wraps each iteration in allreduce of partial sums; ANN
search does shard-local top-k then merges (knn_merge_parts). The reference
keeps the MNMG drivers in cuML/cuGraph — here they are in-tree, expressed as
shard_map SPMD programs over the Comms mesh.

All functions take a `Comms` session; arrays are host/global arrays that get
sharded row-wise (equal shards, padded) across the comms axis.
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.comms.comms import Comms, AxisComms, op_t
from raft_tpu.cluster.kmeans_common import assign_and_reduce
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu.distance.distance_types import DistanceType, resolve_metric


def _metric_name(metric) -> str:
    """Coarse-trainer metric for an ANN index metric (shared by every
    distributed build so driver and *_local paths can't diverge)."""
    return "inner_product" if metric == DistanceType.InnerProduct else "sqeuclidean"


def _pq_geometry(params, d: int):
    """(pq_dim, pq_len, rot_dim) for a dataset dim — one derivation for
    the driver and *_local PQ builds."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    pq_dim = params.pq_dim or ivf_pq_mod._auto_pq_dim(d)
    pq_len = -(-d // pq_dim)
    return pq_dim, pq_len, pq_dim * pq_len


@functools.lru_cache(maxsize=8)
def _rotate_fn(mesh, axis):
    """One compiled sharded-rotation program per mesh (a @ R.T)."""

    @jax.jit
    def run(a, R):
        def body(a, R):
            return a @ R.T

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=P(axis, None), check_vma=False,
        )(a, R)

    return run


def _codebook_cap(params, n_lists: int) -> int:
    """Residual-sample cap for codebook EM (parity with the single-chip
    build: EM only needs enough rows per codebook entry)."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    nb = 1 << params.pq_bits
    cap = max(65536, 64 * nb)
    if params.codebook_kind == ivf_pq_mod.PER_CLUSTER:
        cap = max(cap, 256 * n_lists)
    return cap


def _train_codebooks(params, key, residuals, cb_labels, n_lists: int,
                     pq_dim: int, pq_len: int):
    """Codebook EM on a residual sample — the one implementation both
    distributed builds call, so cap/iteration/kind changes can't diverge."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    nb = 1 << params.pq_bits
    if params.codebook_kind == ivf_pq_mod.PER_CLUSTER:
        return ivf_pq_mod._train_codebooks_per_cluster(
            key, residuals, cb_labels, n_lists, pq_len, nb, 25
        )
    return ivf_pq_mod._train_codebooks_per_subspace(key, residuals, pq_dim, nb, 25)


def _ranks_by_proc(mesh) -> dict:
    """process_index -> sorted mesh-rank positions. The *_local layout's
    correctness rests on every helper using THIS one ordering."""
    out: dict = {}
    for j, d in enumerate(mesh.devices.flat):
        out.setdefault(d.process_index, []).append(j)
    return {p: sorted(v) for p, v in out.items()}


def _shard_rows(comms: Comms, x: np.ndarray):
    """Pad rows to a multiple of n_ranks and shard; returns (sharded, n, wpr)."""
    n = x.shape[0]
    r = comms.get_size()
    per = -(-n // r)
    pad = per * r - n
    xp = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)]) if pad else x
    return comms.shard(xp, axis=0), n, per


def _valid_weights(n: int, per: int, r: int) -> np.ndarray:
    w = np.zeros(per * r, np.float32)
    w[:n] = 1.0
    return w


def _pack_vi(v, ids):
    """One (nq, 2*kk) f32 plane carrying scores + bit-cast int32 ids, so a
    merge transports BOTH tensors in a SINGLE collective — same bytes,
    half the collective launches (launch latency dominates merge cost at
    serving batch sizes). Transport-safe: collectives move bytes; no FP
    arithmetic ever touches the id lanes (bit patterns may read as
    NaN/denormal f32 but are only ever bit-cast back)."""
    return jnp.concatenate(
        [v.astype(jnp.float32),
         lax.bitcast_convert_type(ids.astype(jnp.int32), jnp.float32)],
        axis=-1)


def _merge_local_topk(ac: AxisComms, v, ids, k: int, select_min: bool):
    """Merge per-rank local top-k candidates into a global top-k on every
    rank (the knn_merge_parts pattern, neighbors/detail/knn_merge_parts.cuh).
    `ids` must already be global (invalid entries masked to the worst
    value in `v` by the caller). Call inside shard_map.

    Power-of-two full-axis comms ride the log-depth butterfly tournament
    (`_merge_local_topk_tournament`): exchanged volume O(nq·k·log R) and
    select width 2k per round, vs the allgather's O(nq·kk·R) receive and
    one R·kk-wide select — the ICI-friendly schedule at pod widths.
    Non-power-of-two and split comms take the allgather path: one packed
    (nq, 2*kk) collective, interleave rank-major -> row-major, re-select."""
    if (ac.groups is None and ac.size > 1
            and (ac.size & (ac.size - 1)) == 0
            and _replicated_merge_schedule() == "tournament"):
        return _merge_local_topk_tournament(ac, v, ids, k, select_min)
    return _merge_local_topk_allgather(ac, v, ids, k, select_min)


def _replicated_merge_schedule() -> str:
    """Which replicated-merge schedule to run (both are bit-exact, so
    this is a pure engine choice). The cost model is BACKEND-dependent:
    on TPU ICI, exchanged volume and collective launches dominate and
    the log-depth tournament's O(nq·k·log R) wins at pod widths; on the
    CPU mesh, collectives are memcpys and the tournament's extra select
    rounds measured ~2x SLOWER than one flat allgather select
    (bench_comms merge race, world=8). Default: tournament on TPU,
    allgather elsewhere. Tuned key `mnmg_replicated_merge_schedule`
    (written by the on-chip bench_comms race) overrides — but only on
    the backend it was measured on (`merge_schedule_measured_on` hint):
    a chip-written winner must not flip the CPU mesh, and vice versa."""
    from raft_tpu.core import tuned

    t = tuned.get("mnmg_replicated_merge_schedule")
    measured_on = (tuned.get("hints") or {}).get("merge_schedule_measured_on")
    if t in ("tournament", "allgather") and measured_on == jax.default_backend():
        return t
    from raft_tpu.core.config import is_tpu_backend

    return "tournament" if is_tpu_backend() else "allgather"


def _merge_local_topk_allgather(ac: AxisComms, v, ids, k: int,
                                select_min: bool):
    """Flat merge: one packed allgather, rank-major interleave, one wide
    select. The fallback schedule (and the tournament's bit-exactness
    oracle in tests)."""
    kk = v.shape[-1]
    g = ac.allgather(_pack_vi(v, ids)[None], axis=0)  # (R, nq, 2*kk)
    r_ = g.shape[0]
    cat = jnp.moveaxis(g.reshape(r_, -1, 2 * kk), 0, 1)  # (nq, R, 2*kk)
    cat_v = cat[..., :kk].reshape(-1, r_ * kk)
    cat_i = lax.bitcast_convert_type(cat[..., kk:], jnp.int32).reshape(-1, r_ * kk)
    mv, mp = _select_k_impl(cat_v, min(k, r_ * kk), select_min)
    return mv, jnp.take_along_axis(cat_i, mp, axis=1)


def _merge_local_topk_tournament(ac: AxisComms, v, ids, k: int,
                                 select_min: bool):
    """Butterfly (recursive-halving) merge: log2(R) ppermute rounds, each
    exchanging this rank's current candidate set with its XOR-partner and
    re-selecting top-min(k, 2w). Every rank converges to the identical
    global top-k (the replicated contract) with O(nq·k·log R) traffic.

    Bit-compatible with the allgather merge: candidates carry their
    rank-major global position, interior rounds restore position order
    after each select, and the stable top_k then breaks value ties by
    position exactly like one flat rank-major select would. A candidate
    trimmed early had >= k better-or-tied-with-lower-pos candidates in
    its own subset, so the flat merge drops it too. Each round moves one
    packed (.., 3w) plane (scores + bit-cast ids + bit-cast positions) —
    one collective per round."""
    r_ = ac.size
    kk = v.shape[-1]
    me = lax.axis_index(ac.axis)
    pos0 = me * kk + jnp.arange(kk, dtype=jnp.int32)
    cur_v = v.astype(jnp.float32)
    cur_i = ids.astype(jnp.int32)
    cur_p = jnp.broadcast_to(pos0, v.shape).astype(jnp.int32)
    d = 1
    while d < r_:
        w = cur_v.shape[-1]
        packed = jnp.concatenate(
            [cur_v,
             lax.bitcast_convert_type(cur_i, jnp.float32),
             lax.bitcast_convert_type(cur_p, jnp.float32)], axis=-1)
        other = lax.ppermute(packed, ac.axis,
                             [(i, i ^ d) for i in range(r_)])
        ov = other[..., :w]
        oi = lax.bitcast_convert_type(other[..., w:2 * w], jnp.int32)
        op = lax.bitcast_convert_type(other[..., 2 * w:], jnp.int32)
        lo_first = (me & d) == 0  # keep global position order in the cat
        cat_v = jnp.where(lo_first, jnp.concatenate([cur_v, ov], -1),
                          jnp.concatenate([ov, cur_v], -1))
        cat_i = jnp.where(lo_first, jnp.concatenate([cur_i, oi], -1),
                          jnp.concatenate([oi, cur_i], -1))
        cat_p = jnp.where(lo_first, jnp.concatenate([cur_p, op], -1),
                          jnp.concatenate([op, cur_p], -1))
        w2 = min(k, 2 * w)
        mv, mp = _select_k_impl(cat_v, w2, select_min)
        mi = jnp.take_along_axis(cat_i, mp, axis=-1)
        mpos = jnp.take_along_axis(cat_p, mp, axis=-1)
        d *= 2
        if d < r_:
            # interior round: back to position order so the next round's
            # stable select tie-breaks like the flat merge; the final
            # round returns best-first (the output contract)
            order = jnp.argsort(mpos, axis=-1)
            mv = jnp.take_along_axis(mv, order, axis=-1)
            mi = jnp.take_along_axis(mi, order, axis=-1)
            mpos = jnp.take_along_axis(mpos, order, axis=-1)
        cur_v, cur_i, cur_p = mv, mi, mpos
    return cur_v, cur_i


def _merge_local_topk_scatter(ac: AxisComms, v, ids, k: int, select_min: bool):
    """Query-sharded merge (the high-QPS serving topology): instead of
    allgathering every rank's (nq, kk) candidates onto every rank
    (volume R·nq·kk received per rank), ONE all_to_all of the packed
    scores+ids plane routes each query block's candidates to its owning
    rank only (volume ~nq·kk per rank, an R× reduction), which re-selects
    locally. Returns this rank's (nq/R, k') block; stitch globally with
    out_specs P(axis). nq must be divisible by the comm size (callers
    pad). Call inside shard_map on the full (unsplit) comm."""
    kk = v.shape[-1]
    r_ = ac.get_size()
    t = lax.all_to_all(_pack_vi(v, ids), ac.axis, split_axis=0,
                       concat_axis=0, tiled=True)
    nq_blk = v.shape[0] // r_
    cat = jnp.moveaxis(t.reshape(r_, nq_blk, 2 * kk), 0, 1)  # (nq_blk, R, 2*kk)
    cat_v = cat[..., :kk].reshape(nq_blk, r_ * kk)
    cat_i = lax.bitcast_convert_type(cat[..., kk:], jnp.int32).reshape(nq_blk, r_ * kk)
    mv, mp = _select_k_impl(cat_v, min(k, r_ * kk), select_min)
    return mv, jnp.take_along_axis(cat_i, mp, axis=1)


def _resolve_query_mode(query_mode: str, comms: Comms, nq: int, k: int) -> str:
    """Pick the merge topology. "replicated" allgather-merges on every
    rank (full results everywhere — what the driver pattern and
    multi-controller `np.asarray` readers expect); "sharded" all_to_alls
    candidates so each rank finalizes only its own query block (R× less
    merge traffic — the serving topology).

    "auto" is volume-aware: merge volume is nq×k×world, and the recorded
    race surface (MERGE_RACE_RESULTS.json) shows the winner flips with k,
    not nq alone — at nq=2048 sharded wins at k=10 and loses at k=100.
    So the flip requires BOTH an absolute batch size (tuned key
    `mnmg_query_sharded_min_nq`) and enough queries per returned neighbor
    (`mnmg_query_sharded_min_nq_per_k`: nq >= k * ratio) so the sharded
    path's per-query routing overhead amortizes. Both keys are measured
    by the race grid in bench/bench_mnmg_merge.py (--apply derives them
    from the surface); the defaults bracket the recorded CPU flip points
    until a TPU race lands. Stays replicated on process-spanning meshes
    where every controller must read the full result."""
    if query_mode in ("replicated", "sharded"):
        return query_mode
    if query_mode != "auto":
        raise ValueError(f"unknown query_mode {query_mode!r}")
    if comms.spans_processes():
        return "replicated"
    from raft_tpu.core import tuned

    min_nq = int(tuned.get("mnmg_query_sharded_min_nq", 4096))
    per_k = float(tuned.get("mnmg_query_sharded_min_nq_per_k", 64))
    return "sharded" if (nq >= min_nq and nq >= k * per_k) else "replicated"


def _pad_queries(q, world: int):
    """Pad nq up to a multiple of the comm size (sharded merge splits the
    query axis evenly); callers slice the result back to nq rows."""
    nq = q.shape[0]
    pad = (-nq) % world
    if pad:
        q = jnp.concatenate([q, jnp.zeros((pad, q.shape[1]), q.dtype)])
    return q, nq


# ---------------------------------------------------------------------------
# distributed k-means
# ---------------------------------------------------------------------------


def _kmeans_fit_sharded(
    comms: Comms,
    xs,
    w,
    centers=None,
    max_iter: int = 100,
    tol: float = 1e-4,
    metric_name: str = "sqeuclidean",
    balance: bool = False,
    seed: int = 0,
    balancing_ratio: float = 4.0,
    n_valid: Optional[int] = None,
    inits=None,
    valid_counts: Optional[np.ndarray] = None,
) -> Tuple[jax.Array, float, int]:
    """Lloyd EM over an already-sharded dataset (`xs` sharded on rows along
    the comms axis, `w` row-validity weights, `centers` replicated).
    `inits` (a sequence of initial center sets) runs restart trials that
    share one compiled EM step and returns the best-inertia run:
    per-iteration partial sums are allreduced across ranks (survey §3.4
    MNMG variant). Returns (centers, inertia, n_iter).

    With `balance`, undersized clusters (global count below
    n/k/balancing_ratio) are re-seeded toward a random valid row each
    iteration — kmeans_balanced's adjust_centers semantics, distributed:
    each cluster's proposal row comes from one rank's shard (cluster_id
    mod ranks) and is shared by psum, so replicated centers stay
    identical everywhere. Two trailing clean EM steps follow, like the
    single-chip balanced trainer. Balanced coarse centers keep IVF list
    sizes even, which directly bounds max_list padding in the list-major
    stores.

    For inner_product/cosine, centers are re-normalized each iteration
    (kmeans_balanced's _maybe_normalize semantics): with unit-norm centers,
    the L2 argmin of assign_and_reduce equals the argmax-dot assignment
    (||x||^2 - 2 x.c + 1 is monotone in -x.c), so the fused L2 engine
    serves both metrics."""
    ac = comms.comms
    ip = metric_name in ("inner_product", "cosine")
    r = comms.get_size()
    k = int(jnp.asarray(centers if centers is not None else inits[0]).shape[0])
    if balance:
        if n_valid is None:
            raise ValueError("balance=True requires n_valid (host-known rows)")
        per = xs.shape[0] // r
        # per-rank valid row counts are host knowledge (valid rows are a
        # prefix of each shard): exact at any scale — a float32 sum of w
        # would saturate at 2^24 rows. Default derivation assumes the
        # valid rows form one contiguous global prefix; multi-controller
        # layouts interleave processes and pass their own valid_counts.
        if valid_counts is None:
            valid_counts = np.clip(
                n_valid - per * np.arange(r, dtype=np.int64), 0, per
            )
        valid_counts = np.asarray(valid_counts, np.int64)
        # proposal ownership maps clusters onto the DATA-HOLDING ranks
        # (an empty rank's only row is the zero pad — a useless proposal)
        holders = np.flatnonzero(valid_counts > 0)
        if holders.size == 0:
            holders = np.asarray([0], np.int64)
        owners = jnp.asarray(holders[np.arange(k) % holders.size], jnp.int32)
        threshold = float(n_valid) / k / balancing_ratio

    def _norm(c):
        return c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True), 1e-12)

    if ip and centers is not None:
        centers = _norm(jnp.asarray(centers))

    @functools.partial(jax.jit, static_argnames=("adjust",))
    def step(xs, w, centers, key, adjust: bool):
        def body(xs, w, centers, key):
            _, sums, counts, inertia = assign_and_reduce(xs, centers, w)
            sums = ac.allreduce(sums)
            counts = ac.allreduce(counts)
            inertia = ac.allreduce(inertia)
            safe = jnp.maximum(counts, 1.0)[:, None]
            new_centers = jnp.where(counts[:, None] > 0, sums / safe, centers)
            if adjust:
                # same key on every rank -> same proposal indices; each
                # cluster's proposal comes from one data-holding rank
                rank = lax.axis_index(ac.axis)
                valid = jnp.maximum(jnp.asarray(valid_counts, jnp.int32)[rank], 1)
                props = jax.random.randint(key, (k,), 0, 1 << 30) % valid
                mine = owners == rank
                local = jnp.where(mine[:, None], xs[props].astype(jnp.float32), 0.0)
                proposals = ac.allreduce(local)
                small = counts < threshold
                wc = jnp.minimum(counts, 7.0)[:, None]
                adjusted = (wc * new_centers + proposals) / (wc + 1.0)
                new_centers = jnp.where(small[:, None], adjusted, new_centers)
            if ip:
                new_centers = _norm(new_centers)
            shift = jnp.sum((new_centers - centers) ** 2)
            return new_centers, inertia, shift

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None), P(comms.axis), P(None, None), P(None)),
            out_specs=(P(None, None), P(), P()), check_vma=False,
        )(xs, w, centers, key)

    def run_one(centers):
        inertia = np.inf
        it = 0
        key = jax.random.PRNGKey(seed)
        for it in range(1, max_iter + 1):
            key, k1 = jax.random.split(key)
            centers, inertia, shift = step(xs, w, centers, k1, balance)
            if not balance and float(shift) < tol * tol:
                break
        if balance:  # trailing clean EM (un-balanced Lloyd updates)
            for _ in range(2):
                centers, inertia, _ = step(xs, w, centers, key, False)
        return centers, float(inertia), it

    if inits is None:
        return run_one(centers)
    # restart trials share `step`'s single compilation (the closure is
    # created once per fit, so jit caches across trials)
    best = None
    for c0 in inits:
        out = run_one(_norm(jnp.asarray(c0)) if ip else c0)
        if best is None or out[1] < best[1]:
            best = out
    return best


def kmeans_fit(
    comms: Comms,
    X,
    n_clusters: int,
    max_iter: int = 100,
    tol: float = 1e-4,
    seed: int = 0,
    n_init: int = 1,
) -> Tuple[jax.Array, float, int]:
    """Distributed Lloyd: shard rows, allreduce partial sums per iteration
    (survey §3.4 MNMG variant). Returns (centers, inertia, n_iter).
    `n_init` restarts with different k-means++ seeds keep the best-inertia
    run (KMeansParams.n_init parity) — Lloyd's local optima depend
    heavily on init luck."""
    x = np.asarray(X, np.float32)
    xs, n, per = _shard_rows(comms, x)
    w = comms.shard(_valid_weights(n, per, comms.get_size()), axis=0)
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    inits = []
    for t in range(max(1, n_init)):
        rng = np.random.default_rng(seed + t)
        sub = x[rng.choice(n, min(n, max(n_clusters * 8, 1024)), replace=False)]
        c0 = _kmeans_plusplus(jax.random.PRNGKey(seed + t), jnp.asarray(sub), n_clusters)
        inits.append(comms.replicate(c0))
    return _kmeans_fit_sharded(comms, xs, w, max_iter=max_iter, tol=tol, inits=inits)


# ---------------------------------------------------------------------------
# multi-controller entry points: every process contributes its OWN rows
# (the raft-dask usage model — each Dask worker holds a partition,
# docs/source/using_comms.rst:1-40). The single-controller kmeans_fit/
# kmeans_predict above take the full array on the driver; these take the
# process-local partition and assemble the global sharded layout.
# ---------------------------------------------------------------------------


def _local_layout(comms: Comms, n_local: int):
    """Collective: allgather per-process local row counts and derive the
    uniform per-rank shard size. Returns (counts (nproc,), per, lranks)
    where every process pads its rows to lranks * per.

    The count gather is job-global (process_allgather), so the mesh must
    span every process of the job — a sub-mesh would deadlock or count
    rows that are not in the mesh's arrays."""
    nproc = jax.process_count()
    pi = jax.process_index()
    mesh_procs = {d.process_index for d in comms.mesh.devices.flat}
    if nproc > 1 and mesh_procs != set(range(nproc)):
        raise ValueError(
            "the *_local collectives need a mesh spanning every process of "
            f"the job (mesh covers {sorted(mesh_procs)} of {nproc})"
        )
    lranks = sum(1 for d in comms.mesh.devices.flat if d.process_index == pi)
    if nproc == 1:
        counts = np.asarray([n_local], np.int64)
    else:
        from jax.experimental import multihost_utils

        counts = np.asarray(
            multihost_utils.process_allgather(jnp.asarray([n_local]), tiled=True),
            np.int64,
        )
    per = max(1, -(-int(counts.max()) // lranks))
    return counts, per, lranks


def _valid_global_positions(comms: Comms, counts: np.ndarray, per: int) -> np.ndarray:
    """Global row positions of every VALID row in the padded sharded
    layout. Mesh device order decides where each process's rows land
    (make_array_from_process_local_data fills a process's shards in
    global-index order), so this walks the mesh rather than assuming
    process-major contiguous blocks — ICI-optimized meshes interleave."""
    ranks_by_proc = _ranks_by_proc(comms.mesh)
    parts = []
    for p, cnt in enumerate(np.asarray(counts, np.int64)):
        rp = np.asarray(ranks_by_proc.get(p, []), np.int64)
        li = np.arange(int(cnt), dtype=np.int64)
        parts.append(rp[li // per] * per + (li % per))
    return np.concatenate(parts) if parts else np.zeros((0,), np.int64)


def _pack_local(local: np.ndarray, per: int, lranks: int):
    """Pad this process's rows to its lranks * per block; returns
    (padded rows, validity weights)."""
    block = lranks * per
    pad = block - local.shape[0]
    xp = (
        np.concatenate([local, np.zeros((pad,) + local.shape[1:], local.dtype)])
        if pad
        else local
    )
    wl = np.zeros(block, np.float32)
    wl[: local.shape[0]] = 1.0
    return xp, wl


@functools.lru_cache(maxsize=8)
def _gather_fn(mesh):
    # one compilation per mesh: index is an argument, not a baked constant,
    # so every restart/subsample reuses the executable
    return jax.jit(
        lambda a, idx: a[idx], out_shardings=NamedSharding(mesh, P())
    )


def _gather_replicated(comms: Comms, xs, positions: np.ndarray) -> np.ndarray:
    """Gather `positions` rows of a (possibly process-spanning) sharded
    array, replicated, and return them as host numpy — the collective
    subsample gather used for initialization."""
    out = _gather_fn(comms.mesh)(xs, jnp.asarray(positions, jnp.int32))
    return np.asarray(out.addressable_shards[0].data)


def kmeans_fit_local(
    comms: Comms,
    local_X,
    n_clusters: int,
    max_iter: int = 100,
    tol: float = 1e-4,
    seed: int = 0,
    n_init: int = 1,
) -> Tuple[jax.Array, float, int]:
    """Distributed Lloyd where each controller passes its OWN partition
    (collective: every process must call with the same arguments apart
    from local_X). Returns (replicated centers, global inertia, n_iter).
    Single-process it matches kmeans_fit on the concatenated rows;
    `n_init` restarts keep the best-inertia run."""
    local = np.asarray(local_X, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    xp, wl = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    w = comms.shard_from_local(wl, axis=0)
    n = int(counts.sum())
    if n_clusters > n:
        raise ValueError(f"n_clusters={n_clusters} > total rows {n}")

    # init: k-means++ on a deterministic global subsample — identical on
    # every controller (same seed, same gathered rows)
    gpos = _valid_global_positions(comms, counts, per)
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    subsample = min(n, max(n_clusters * 8, 1024))
    inits = []
    for t in range(max(1, n_init)):
        rng = np.random.default_rng(seed + t)
        sel = gpos[rng.choice(n, subsample, replace=False)]
        sub = _gather_replicated(comms, xs, sel)
        c0 = _kmeans_plusplus(jax.random.PRNGKey(seed + t), jnp.asarray(sub), n_clusters)
        inits.append(comms.replicate(np.asarray(c0)))
    return _kmeans_fit_sharded(comms, xs, w, max_iter=max_iter, tol=tol, inits=inits)


def kmeans_predict_local(comms: Comms, local_X, centers) -> jax.Array:
    """Nearest-center labels for this process's OWN rows (collective).
    Returns the (n_local,) labels of the local partition."""
    local = np.asarray(local_X, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    xp, _ = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    labels = _spmd_predict(comms, xs, centers)
    return _local_shard_rows_host(labels)[: local.shape[0]]


def _spmd_predict(comms: Comms, xs, centers) -> jax.Array:
    """Nearest-center labels over an already-sharded dataset (includes any
    pad rows; callers slice to [:n])."""

    def build():
        @jax.jit
        def run(xs, c):
            def body(xs, c):
                labels, _, _, _ = assign_and_reduce(xs, c, needs_sums=False)
                return labels

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None), P(None, None)),
                out_specs=P(comms.axis), check_vma=False,
            )(xs, c)

        return run

    # predict is a serving path called per request (see _cached_wrapper)
    run = _cached_wrapper(("spmd_predict", comms.mesh, comms.axis), build)
    # centers may already be a replicated global array (kmeans_fit_local
    # output) — replicate() reshards those and asarray would fail on them
    c = centers if Comms._is_global(centers) else jnp.asarray(centers, jnp.float32)
    return run(xs, comms.replicate(c))


def kmeans_predict(comms: Comms, X, centers) -> jax.Array:
    """Distributed assignment; returns global labels (n,) on host order."""
    x = np.asarray(X, np.float32)
    xs, n, per = _shard_rows(comms, x)
    return _spmd_predict(comms, xs, centers)[:n]


# ---------------------------------------------------------------------------
# distributed brute-force k-NN
# ---------------------------------------------------------------------------


def _distributed_id_bound(index) -> int:
    """One past the largest gid of a Distributed* index. n for normal
    builds (gids are 0..n-1); for bridged indexes the gids are caller
    ids, so read the actual max (host mirror when present, one device
    reduce otherwise)."""
    if not getattr(index, "bridged", False):
        return int(index.n)
    if index.host_gids is not None:
        hg = np.asarray(index.host_gids)
        return int(hg.max()) + 1 if hg.size else 0
    return int(jnp.max(index.slot_gids)) + 1


def _pack_mask_words(mask_padded: np.ndarray) -> np.ndarray:
    """(R, per) bool -> (R, W) uint32 per-rank bitset rows. Each row is
    padded to whole 32-bit words, so packing the flattened mask through
    Bitset.from_mask yields exactly the per-row word layout the
    shard-local `Bitset(bits[0], per)` rebuild expects — ONE source of
    truth for the bit layout."""
    from raft_tpu.core.bitset import Bitset

    R, per = mask_padded.shape
    W = (per + 31) // 32
    pad = W * 32 - per
    mp = np.pad(mask_padded, ((0, 0), (0, pad))) if pad else mask_padded
    return np.asarray(Bitset.from_mask(mp.reshape(-1)).bits).reshape(R, W)


def _pad_global_mask(mask: np.ndarray, rank_base, valid_counts,
                     per: int) -> np.ndarray:
    """Scatter a global keep-mask into the padded (R, per) shard layout
    (pad rows stay False; they are masked by n_valid anyway)."""
    R = len(rank_base)
    out = np.zeros((R, per), bool)
    for j in range(R):
        v, b = int(valid_counts[j]), int(rank_base[j])
        if v:
            out[j, :v] = mask[b : b + v]
    return out


def _knn_prefilter_words(prefilter, n: int, rank_base, valid_counts,
                         per: int):
    """Coerce a knn prefilter (global ids 0..n-1) into per-rank packed
    bitset rows, or None. Mask inputs stay on host (no pack/unpack round
    trip); Bitset inputs unpack once."""
    if prefilter is None:
        return None
    from raft_tpu.core.bitset import Bitset

    if isinstance(prefilter, Bitset):
        if prefilter.n != n:
            raise ValueError(
                f"prefilter covers {prefilter.n} ids but the index has {n}"
            )
        mask = np.asarray(prefilter.to_mask())
    else:
        mask = np.asarray(prefilter)
        if mask.dtype != np.bool_ or mask.ndim != 1:
            raise ValueError(
                "prefilter must be a Bitset or a 1-D boolean mask, got "
                f"{mask.dtype} ndim={mask.ndim}"
            )
        if mask.shape[0] != n:
            raise ValueError(
                f"prefilter mask has {mask.shape[0]} entries but the index has {n}"
            )
    return _pack_mask_words(_pad_global_mask(mask, rank_base, valid_counts, per))


# Per-process cache of the jitted SPMD serving wrappers. The search
# entry points build their shard_map programs inside the function body
# (the closures need per-call statics), so without this cache EVERY
# serving call re-created the jitted wrapper and re-traced the whole
# program — measured ~8.5 s/call on the 8-device CPU mesh for a
# distributed IVF-PQ search whose compute is milliseconds. The key MUST
# cover every non-array closure input that shapes the traced program;
# array shapes/dtypes are keyed by jit's own cache on the persistent
# wrapper. Bounded defensively (distinct mode/engine/geometry
# combinations are few in practice).
_JIT_WRAPPER_CACHE: dict = {}


def _cached_wrapper(key, build):
    f = _JIT_WRAPPER_CACHE.pop(key, None)
    if f is None:
        while len(_JIT_WRAPPER_CACHE) >= 64:
            # evict one LRU entry (dict preserves insertion order and the
            # pop/re-insert above refreshes recency) — clearing wholesale
            # would drop every HOT wrapper whenever a long-lived serving
            # process accumulates 64 parameter combinations
            _JIT_WRAPPER_CACHE.pop(next(iter(_JIT_WRAPPER_CACHE)))
        f = build()
    _JIT_WRAPPER_CACHE[key] = f
    return f


def _knn_sharded(comms: Comms, xs, queries, k: int, n_total: int, per: int,
                 rank_base: np.ndarray, valid_counts: np.ndarray, m,
                 pf_words=None, query_mode: str = "auto",
                 compute_dtype=None):
    """Shard-local exact kNN + merge over an already-sharded dataset.
    `rank_base[j]` maps rank j's shard-local row i to caller id base+i;
    `valid_counts[j]` rows of rank j's shard are real (a prefix — pads
    are masked BEFORE selection so they can't displace true neighbors).
    The one implementation behind knn() and knn_local()."""
    from raft_tpu.neighbors.brute_force import _bf_knn_impl

    from raft_tpu.core.bitset import Bitset

    ac = comms.comms
    select_min = m != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    kk = int(min(k, per))
    qh = jnp.asarray(queries, jnp.float32)
    mode = _resolve_query_mode(query_mode, comms, qh.shape[0], kk)
    nq = qh.shape[0]
    if mode == "sharded":
        qh, nq = _pad_queries(qh, comms.get_size())
    merge = _merge_local_topk if mode == "replicated" else _merge_local_topk_scatter
    out_spec = P(None, None) if mode == "replicated" else P(comms.axis, None)
    qr = comms.replicate(qh)
    base_rep = comms.replicate(np.asarray(rank_base, np.int32))
    valid_rep = comms.replicate(np.asarray(valid_counts, np.int32))
    filtered = pf_words is not None
    if not filtered:  # 1-word placeholder keeps one jitted signature
        pf_words = np.zeros((comms.get_size(), 1), np.uint32)
    if comms.spans_processes():
        lr = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
        bits_sh = comms.shard_from_local(np.asarray(pf_words)[lr], axis=0)
    else:
        bits_sh = comms.shard(jnp.asarray(pf_words), axis=0)

    def build():
        @functools.partial(jax.jit, static_argnames=("use_pf",))
        def run(xs, qr, base, valid, bits, use_pf: bool):
            def body(xs, qr, base, valid, bits):
                rank = ac.get_rank()
                nv = valid[rank]
                pf = Bitset(bits[0], per) if use_pf else None
                if compute_dtype is not None:
                    # cast fuses into the scan's matmul loads; distances
                    # stay f32 (accumulation dtype), so masking/merge
                    # below are unchanged — see
                    # brute_force.knn(compute_dtype=...)
                    xs = xs.astype(compute_dtype)
                    qr = qr.astype(compute_dtype)
                v, i = _bf_knn_impl(xs, qr, kk, m, n_valid=nv, prefilter=pf)
                i = i.astype(jnp.int32)
                # i >= 0 drops tiled-path init slots (-1), which would
                # otherwise map to base[rank]-1 — the previous shard's
                # last row
                keep = (i >= 0) & (i < nv)
                if use_pf:
                    # fewer than kk survivors: worst-scored slots may
                    # carry a filtered row's local index out of the tie —
                    # re-test the ids against the bitset (a score test
                    # would also drop a survivor whose distance
                    # overflowed to inf, and would keep NaN-scored
                    # filtered rows)
                    keep = keep & pf.test(i)
                gid = jnp.where(keep, base[rank] + i, -1)
                v = jnp.where(keep, v, worst)
                return merge(ac, v, gid, min(k, n_total), select_min)

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None), P(None, None), P(None),
                          P(None), P(comms.axis, None)),
                out_specs=(out_spec, out_spec), check_vma=False,
            )(xs, qr, base, valid, bits)

        return run

    # every non-array closure input of the traced program, or the cache
    # would silently reuse a wrong program (see _JIT_WRAPPER_CACHE)
    run = _cached_wrapper(
        ("knn_sharded", comms.mesh, comms.axis, mode, m, int(kk),
         int(min(k, n_total)), int(per),
         None if compute_dtype is None else jnp.dtype(compute_dtype).name),
        build,
    )
    v, gid = run(xs, qr, base_rep, valid_rep, bits_sh, filtered)
    return (v[:nq], gid[:nq]) if v.shape[0] != nq else (v, gid)


def knn(
    comms: Comms,
    dataset,
    queries,
    k: int,
    metric="sqeuclidean",
    prefilter=None,
    query_mode: str = "auto",
    compute_dtype=None,
) -> Tuple[jax.Array, jax.Array]:
    """Shard-local exact kNN + allgather + merge (knn_merge_parts pattern,
    survey §5.7). Queries are replicated; dataset is sharded by rows.
    `prefilter` (core.Bitset or boolean mask over dataset row ids)
    excludes rows before selection on every rank. `query_mode` picks the
    merge topology (see `_resolve_query_mode`). `compute_dtype` is the
    per-shard scan's operand dtype (same near-exact speed/recall trade
    as `brute_force.knn`'s knob; merge semantics unchanged)."""
    m = resolve_metric(metric)
    x = np.asarray(dataset, np.float32)
    xs, n, per = _shard_rows(comms, x)
    r = comms.get_size()
    rank_base = per * np.arange(r, dtype=np.int64)
    valid_counts = np.clip(n - rank_base, 0, per)
    pf_words = _knn_prefilter_words(prefilter, n, rank_base, valid_counts, per)
    return _knn_sharded(comms, xs, queries, k, n, per, rank_base, valid_counts,
                        m, pf_words=pf_words, query_mode=query_mode,
                        compute_dtype=compute_dtype)


def knn_local(
    comms: Comms,
    local_dataset,
    queries,
    k: int,
    metric="sqeuclidean",
    prefilter=None,
    query_mode: str = "auto",
    compute_dtype=None,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed exact kNN where each controller contributes its OWN
    rows (collective). Queries must be the same on every controller;
    returned ids are caller row ids — positions in the process-order
    concatenation of the partitions. `prefilter` covers that same global
    id space and, like queries, must be identical on every controller."""
    m = resolve_metric(metric)
    local = np.asarray(local_dataset, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    n = int(counts.sum())
    xp, _ = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    rank_base, valid_counts = _rank_layout(comms, counts, per)
    pf_words = _knn_prefilter_words(prefilter, n, rank_base, valid_counts, per)
    return _knn_sharded(comms, xs, queries, k, n, per, rank_base, valid_counts,
                        m, pf_words=pf_words, query_mode=query_mode,
                        compute_dtype=compute_dtype)


def distribute_index(comms: Comms, index):
    """Bridge a SINGLE-CHIP index onto the mesh for distributed serving
    (build once on one chip — or load from a single-chip checkpoint —
    then search across every rank). Each list's slots are block-split
    across ranks, so every rank scans its share of every probed list and
    the usual top-k merge applies. Accepts `ivf_flat.Index` and
    `ivf_pq.Index`; returns the matching Distributed* index. Searches
    return the same ids as the single-chip index. The slot-block layout
    is not a contiguous per-rank row range and gids may be arbitrary
    caller ids, so refine_dataset and extend are rejected on the result
    (extend the single-chip index and re-distribute)."""
    R = comms.get_size()
    slots = np.asarray(index.slot_rows)
    n_lists, max_list = slots.shape
    mlr = max(1, -(-max_list // R))
    pad = R * mlr - max_list
    slots_p = np.pad(slots, ((0, 0), (0, pad)), constant_values=-1)
    gids_r = np.ascontiguousarray(
        slots_p.reshape(n_lists, R, mlr).transpose(1, 0, 2)
    )
    if getattr(index, "source_ids", None) is not None:
        src = np.asarray(index.source_ids)
        gids_r = np.where(
            gids_r >= 0, src[np.clip(gids_r, 0, len(src) - 1)], -1
        ).astype(np.int32)
    sizes = (gids_r >= 0).sum(axis=2).astype(np.int32)  # (R, n_lists)

    def split_payload(tbl):
        t = np.asarray(tbl)
        tp = np.pad(t, ((0, 0), (0, pad)) + ((0, 0),) * (t.ndim - 2))
        perm = (1, 0, 2) + (() if t.ndim == 2 else (3,))
        return np.ascontiguousarray(
            tp.reshape((n_lists, R, mlr) + t.shape[2:]).transpose(perm)
        )

    if hasattr(index, "codes"):  # ivf_pq.Index
        return DistributedIvfPq(
            comms,
            index.params,
            comms.replicate(np.asarray(index.rotation)),
            comms.replicate(np.asarray(index.centers)),
            comms.replicate(np.asarray(index.pq_centers)),
            _place_rank_major(comms, split_payload(index.codes)),
            _place_rank_major(comms, gids_r),
            int(index.size),
            host_gids=None if comms.spans_processes() else gids_r,
            list_sizes=None if comms.spans_processes() else sizes,
            bridged=True,
        )
    return DistributedIvfFlat(
        comms,
        index.params,
        comms.replicate(np.asarray(index.centers)),
        _place_rank_major(comms, split_payload(index.list_data)),
        _place_rank_major(comms, gids_r),
        int(index.size),
        host_gids=None if comms.spans_processes() else gids_r,
        list_sizes=None if comms.spans_processes() else sizes,
        bridged=True,
    )


def _place_rank_major(comms: Comms, host_arr: np.ndarray):
    """Shard a (R, ...) rank-major host table onto the mesh rank axis —
    on a process-spanning mesh each controller contributes the blocks of
    its own mesh ranks (checkpoint loads assume a shared filesystem, the
    standard multi-host checkpoint contract)."""
    if not comms.spans_processes():
        # keep host numpy as-is: shard() transfers per-shard, so multi-GB
        # tables never land whole on the default device
        return comms.shard(host_arr, axis=0)
    my = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
    return jax.make_array_from_process_local_data(
        comms._sharding(host_arr.ndim, 0), np.ascontiguousarray(host_arr[my])
    )


# ---------------------------------------------------------------------------
# distributed ANN (IVF-Flat / IVF-PQ): shard rows, shared centers,
# per-shard slot tables, merge local top-k
# ---------------------------------------------------------------------------


class DistributedIvfFlat:
    """Data-parallel IVF-Flat: global coarse centers (distributed k-means),
    per-rank list-major stores over the local shard, searched SPMD + merged.

    list_data (R, n_lists, max_list, d) and slot_gids (R, n_lists, max_list)
    are sharded on axis 0; slot_gids holds GLOBAL dataset row ids (-1 pad),
    so shard-local search results merge without id translation. Host
    mirrors (`host_gids`, `list_sizes`) enable O(n_new) `ivf_flat_extend`."""

    def __init__(self, comms, params, centers, list_data, slot_gids, n,
                 host_gids=None, list_sizes=None, bridged: bool = False,
                 local_gids=None, local_sizes=None):
        self.comms = comms
        self.params = params
        self.centers = centers
        self.list_data = list_data
        self.slot_gids = slot_gids
        self.n = n
        self.host_gids = host_gids
        self.list_sizes = list_sizes
        # per-PROCESS mirrors of this controller's rank shards — what a
        # *_build_local index keeps instead of the global host mirrors,
        # enabling the collective `ivf_flat_extend_local`
        self.local_gids = local_gids
        self.local_sizes = local_sizes
        # fused-scan derived store (engine="pallas"), built lazily:
        # lane-padded bf16 residuals + norms + padded gid view
        self.resid_bf16 = None
        self.resid_norm = None
        self.slot_gids_pad = None
        # bridged = built by distribute_index from a single-chip index:
        # slot gids may be arbitrary caller ids (not 0..n-1), so extend's
        # id assignment could collide — extend the single-chip index and
        # re-distribute instead
        self.bridged = bridged
        self._id_bound = None

    @property
    def id_bound(self) -> int:
        """One past the largest global id a search can return — the id
        space a `prefilter` must cover (== n except for bridged indexes,
        whose gids may be arbitrary caller ids). Cached per instance
        (extends return new indexes)."""
        if self._id_bound is None:
            self._id_bound = _distributed_id_bound(self)
        return self._id_bound


def ivf_flat_build(comms: Comms, params, dataset, seed: int = 0) -> DistributedIvfFlat:
    """Distributed IVF-Flat build: global coarse centers via distributed
    Lloyd EM, per-rank list stores filled SPMD from the row shards (the
    host only handles labels and slot tables — no host-side list-major
    copy of the dataset)."""
    x = np.asarray(dataset, np.float32)
    n, d = x.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > dataset rows {n}")
    r = comms.get_size()

    # one H2D shard of the dataset feeds training, assignment AND packing
    xs, _, per = _shard_rows(comms, x)
    w = comms.shard(_valid_weights(n, per, r), axis=0)
    rng = np.random.default_rng(seed)
    sub = x[rng.choice(n, min(n, max(params.n_lists * 8, 1024)), replace=False)]
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    centers0 = _kmeans_plusplus(jax.random.PRNGKey(seed), jnp.asarray(sub),
                                params.n_lists)
    centers, _, _ = _kmeans_fit_sharded(
        comms, xs, w, comms.replicate(centers0),
        max_iter=params.kmeans_n_iters, metric_name=_metric_name(params.metric),
        balance=True, seed=seed, n_valid=n,
    )
    labels = np.asarray(_spmd_predict(comms, xs, centers))[: n]

    local_tbl, gids, sizes, _ = _pack_rank_tables(labels, n, per, r, params.n_lists)
    tbl_sh = comms.shard(jnp.asarray(local_tbl), axis=0)
    ldata = _spmd_pack_rows(comms, xs, tbl_sh, per, jnp.float32)
    return DistributedIvfFlat(
        comms,
        params,
        comms.replicate(jnp.asarray(centers)),
        ldata,
        comms.shard(jnp.asarray(gids), axis=0),
        n,
        host_gids=gids,
        list_sizes=sizes,
    )


def _rank_valid_counts(comms: Comms, counts: np.ndarray, per: int) -> np.ndarray:
    """Per-RANK valid row counts (mesh-rank order) for the *_local padded
    layout: each process's valid rows are a prefix of its mesh-ordered
    shard blocks."""
    return _rank_layout(comms, counts, per)[1]


def _rank_layout(comms: Comms, counts: np.ndarray, per: int):
    """Per-RANK (caller-id base, valid row count) for the *_local padded
    layout — the ONE walk of the (process, local-rank, mesh-rank)
    mapping, so knn_local's ids and the IVF builds' gids cannot
    diverge. Returns (rank_base (r,), valid_counts (r,))."""
    r = comms.get_size()
    base = np.zeros(r, np.int64)
    valid = np.zeros(r, np.int64)
    ranks_by_proc = _ranks_by_proc(comms.mesh)
    counts = np.asarray(counts, np.int64)
    for p, cnt in enumerate(counts):
        off = int(counts[:p].sum())
        for l, j in enumerate(ranks_by_proc.get(p, [])):
            base[j] = off + l * per
            valid[j] = int(np.clip(cnt - l * per, 0, per))
    return base, valid


def _local_shard_rows_host(arr) -> np.ndarray:
    """This process's addressable shards of a row-sharded array,
    concatenated in global-index order — its padded local block."""
    shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])


def _pack_local_tables(comms: Comms, labels_local: np.ndarray,
                       valid_counts: np.ndarray, counts: np.ndarray,
                       per: int, n_lists: int):
    """Per-process slot-table packing for the *_local builds: each process
    packs its own ranks' lists from its local labels (no host ever sees
    global labels), agrees on the global list width, and stamps slot gids
    with CALLER row ids (position in the process-order concatenation of
    the partitions — the shard_from_local convention). Returns
    (tbl_sh, gids_sh, gids_local, sizes_local): the first two sharded on
    the rank axis, the last two this process's host mirrors
    ((lranks, n_lists, max_list) gid table and (lranks, n_lists) fill
    counts) that make `*_extend_local` O(n_new)."""
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    pi = jax.process_index()
    my_ranks = _ranks_by_proc(comms.mesh).get(pi, [])
    lranks = len(my_ranks)
    packed = []
    my_max = 1
    for l, j in enumerate(my_ranks):
        nv = int(valid_counts[j])
        t, _ = _pack_lists(labels_local[l * per : l * per + nv], n_lists)
        packed.append(t.astype(np.int32))
        my_max = max(my_max, t.shape[1])
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        all_max = np.asarray(
            multihost_utils.process_allgather(jnp.asarray([my_max]), tiled=True)
        )
        max_list = int(all_max.max())
    else:
        max_list = my_max
    proc_offset = int(np.asarray(counts[:pi], np.int64).sum())
    local_tbl = np.full((lranks, n_lists, max_list), -1, np.int32)
    gids_local = np.full((lranks, n_lists, max_list), -1, np.int32)
    sizes_local = np.zeros((lranks, n_lists), np.int32)
    for l, t in enumerate(packed):
        local_tbl[l, :, : t.shape[1]] = t
        valid = t >= 0
        gids_local[l, :, : t.shape[1]][valid] = proc_offset + l * per + t[valid]
        sizes_local[l] = valid.sum(axis=1).astype(np.int32)
    return (
        comms.shard_from_local(local_tbl, axis=0),
        comms.shard_from_local(gids_local, axis=0),
        gids_local,
        sizes_local,
    )


def ivf_flat_build_local(
    comms: Comms, params, local_dataset, seed: int = 0
) -> DistributedIvfFlat:
    """Distributed IVF-Flat build where each controller contributes its
    OWN data partition (collective; the per-worker-partition raft-dask
    model). Coarse centers train with the distributed balanced EM over
    every process's rows; each process packs its ranks' list tables from
    its local labels, so no host ever materializes global labels. The
    returned index searches exactly like ivf_flat_build's (the index
    arrays are global); grow it with the collective
    `ivf_flat_extend_local` (`ivf_flat_extend`/save need the single-
    controller host mirrors and reject these indexes)."""
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    local = np.asarray(local_dataset, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    n = int(counts.sum())
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > total rows {n}")
    xp, wl = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    w = comms.shard_from_local(wl, axis=0)
    valid_counts = _rank_valid_counts(comms, counts, per)

    gpos = _valid_global_positions(comms, counts, per)
    rng = np.random.default_rng(seed)
    sel = gpos[rng.choice(n, min(n, max(params.n_lists * 8, 1024)), replace=False)]
    sub = _gather_replicated(comms, xs, sel)
    centers0 = _kmeans_plusplus(
        jax.random.PRNGKey(seed), jnp.asarray(sub), params.n_lists
    )
    centers, _, _ = _kmeans_fit_sharded(
        comms, xs, w, comms.replicate(np.asarray(centers0)),
        max_iter=params.kmeans_n_iters, metric_name=_metric_name(params.metric),
        balance=True, seed=seed, n_valid=n, valid_counts=valid_counts,
    )

    labels_sh = _spmd_predict(comms, xs, centers)
    labels_local = _local_shard_rows_host(labels_sh)
    tbl_sh, gids_sh, gids_local, sizes_local = _pack_local_tables(
        comms, labels_local, valid_counts, counts, per, params.n_lists
    )
    ldata = _spmd_pack_rows(comms, xs, tbl_sh, per, jnp.float32)
    return DistributedIvfFlat(
        comms,
        params,
        comms.replicate(centers) if not Comms._is_global(centers) else centers,
        ldata,
        gids_sh,
        n,
        host_gids=None,
        list_sizes=None,
        local_gids=gids_local,
        local_sizes=sizes_local,
    )


class DistributedIvfPq:
    """Data-parallel IVF-PQ: rotation/coarse centers/codebooks trained
    distributed (replicated afterwards), per-rank bit-code tables over the
    local shard (device-resident end to end), searched SPMD + merged.

    codes (R, n_lists, max_list, pq_dim) uint8 and slot_gids
    (R, n_lists, max_list) int32 are sharded on axis 0; slot_gids holds
    GLOBAL dataset row ids (-1 pad), so shard-local search results merge
    without id translation — the TPU equivalent of the reference's
    application-level MNMG ANN sharding (survey §5.7).

    Host mirrors kept for O(n_new) `extend`: `host_gids` (the slot table)
    and `list_sizes` (R, n_lists) fill counts. The int8 reconstruction
    stores for the list-major search engine (`recon8`/`recon_scale`/
    `recon_norm`) are built lazily per rank on first search."""

    def __init__(self, comms, params, rotation, centers, pq_centers, codes,
                 slot_gids, n, host_gids=None, list_sizes=None,
                 extended: bool = False, bridged: bool = False,
                 local_gids=None, local_sizes=None):
        self.comms = comms
        self.params = params
        self.rotation = rotation
        self.centers = centers
        self.pq_centers = pq_centers
        self.codes = codes
        self.slot_gids = slot_gids
        self.n = n
        self.host_gids = host_gids
        self.list_sizes = list_sizes
        # per-PROCESS mirrors (see DistributedIvfFlat): enable the
        # collective ivf_pq_extend_local on *_build_local indexes
        self.local_gids = local_gids
        self.local_sizes = local_sizes
        # extend appends each batch under a fresh per-rank gid block, so
        # per-rank gid ownership stops being one contiguous range: the
        # refined pipeline then runs post-merge over the full-dataset
        # layout (driver builds) or refuses (*_local-extended / bridged)
        # — see _refine_layout / _refine_merged
        self.extended = extended
        self.bridged = bridged  # see DistributedIvfFlat.bridged
        self.recon8 = None
        self.recon_scale = None
        self.recon_norm = None
        self.slot_gids_pad = None  # lane-padded gid view (pallas trim)
        self._refine_cache = None
        self._id_bound = None

    @property
    def id_bound(self) -> int:
        """One past the largest global id a search can return — the id
        space a `prefilter` must cover (== n except for bridged indexes,
        whose gids may be arbitrary caller ids). Cached per instance
        (extends return new indexes)."""
        if self._id_bound is None:
            self._id_bound = _distributed_id_bound(self)
        return self._id_bound

    def clear_refine_cache(self) -> None:
        """Release the device-sharded dataset copy a refined search
        pinned (one entry, keyed by dataset identity)."""
        self._refine_cache = None


def _spmd_label_encode(comms: Comms, xs, rotation, centers, pq_centers,
                       metric, per_cluster: bool):
    """Label + PQ-encode the sharded rows inside shard_map (shard-resident:
    the O(n·d) encode never leaves the devices). Returns sharded
    (labels (n,), codes (n, pq_dim))."""
    from raft_tpu.neighbors.ivf_pq import label_and_encode

    def build():
        @jax.jit
        def run(xs, rotation, centers, pq_centers):
            def body(xs, rotation, centers, pq_centers):
                return label_and_encode(
                    xs, rotation, centers, pq_centers, metric, per_cluster
                )

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None), P(None, None), P(None, None),
                          P(None, None, None)),
                out_specs=(P(comms.axis), P(comms.axis, None)),
                check_vma=False,
            )(xs, rotation, centers, pq_centers)

        return run

    # called once per streamed-extend batch (see _cached_wrapper)
    run = _cached_wrapper(
        ("spmd_label_encode", comms.mesh, comms.axis, metric, per_cluster),
        build,
    )
    return run(xs, rotation, centers, pq_centers)


def _pack_rank_tables(labels_np, n, per, r, n_lists):
    """Host-side slot-table construction from assignment labels (cheap int
    ops on n int32s — the bulky row payload stays on device and is packed
    by `_spmd_pack_rows`). Returns (local_tbl, gids, sizes, max_list):
    local_tbl (R, n_lists, max_list) holds SHARD-LOCAL row indices (-1
    pad), gids the same slots as global ids."""
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    tables, sizes = [], []
    max_list = 1
    for rr in range(r):
        lo, hi = rr * per, min((rr + 1) * per, n)
        if lo >= hi:
            tables.append(np.full((n_lists, 1), -1, np.int32))
            sizes.append(np.zeros(n_lists, np.int32))
            continue
        t, sz = _pack_lists(labels_np[lo:hi], n_lists)
        tables.append(t.astype(np.int32))
        sizes.append(np.asarray(sz, np.int32))
        max_list = max(max_list, t.shape[1])
    local_tbl = np.full((r, n_lists, max_list), -1, np.int32)
    gids = np.full((r, n_lists, max_list), -1, np.int32)
    for rr, t in enumerate(tables):
        local_tbl[rr, :, : t.shape[1]] = t
        valid = t >= 0
        gids[rr, :, : t.shape[1]][valid] = t[valid] + rr * per
    return local_tbl, gids, np.stack(sizes), max_list


def _spmd_pack_rows(comms: Comms, rows_sh, local_tbl_sh, per: int, out_dtype):
    """Gather sharded flat rows (n, d) into the per-rank list-major tables
    (R, n_lists, max_list, d) inside shard_map — the distributed
    process_and_fill_codes (ivf_pq_build.cuh:724) for PQ codes, and the
    list-store fill for IVF-Flat — as a gather (no TPU scatters)."""

    def build():
        @jax.jit
        def run(rows_sh, tbl):
            def body(rows_sh, tbl):
                t = tbl[0]  # (n_lists, max_list) local row ids
                packed = rows_sh[jnp.clip(t, 0, per - 1)]  # (n_lists, S, d)
                packed = jnp.where(
                    (t >= 0)[..., None], packed, 0).astype(out_dtype)
                return packed[None]

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None), P(comms.axis, None, None)),
                out_specs=P(comms.axis, None, None, None), check_vma=False,
            )(rows_sh, tbl)

        return run

    # called once per streamed-extend batch (see _cached_wrapper)
    run = _cached_wrapper(
        ("spmd_pack_rows", comms.mesh, comms.axis, int(per),
         jnp.dtype(out_dtype).name),
        build,
    )

    return run(rows_sh, local_tbl_sh)


def ivf_pq_build(comms: Comms, params, dataset, seed: int = 0) -> DistributedIvfPq:
    """Distributed IVF-PQ build (detail/ivf_pq_build.cuh:1074 at MNMG
    scale): coarse centers train with DISTRIBUTED Lloyd EM over the rotated
    trainset fraction (kmeans_trainset_fraction parity with the single-chip
    build — not a token subsample), codebooks train on the same capped
    residual sample as the single-chip path, and the full dataset is
    labeled/encoded SPMD with the codes staying device-resident; the host
    only ever handles labels (n int32) and slot tables."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    x = np.asarray(dataset, np.float32)
    n, d = x.shape
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > dataset rows {n}")
    r = comms.get_size()
    per = -(-n // r)
    n_lists = params.n_lists
    per_cluster = params.codebook_kind == ivf_pq_mod.PER_CLUSTER

    pq_dim, pq_len, rot_dim = _pq_geometry(params, d)
    key = jax.random.PRNGKey(seed)
    key, rk = jax.random.split(key)
    rotation = ivf_pq_mod._make_rotation(
        rk, rot_dim, d, params.force_random_rotation or rot_dim != d
    )
    rot_rep = comms.replicate(rotation)

    # --- coarse centers: distributed EM over the rotated trainset fraction
    frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
    n_train = min(n, max(n_lists * 4, int(n * frac)))
    rng = np.random.default_rng(seed)
    train_sel = rng.choice(n, n_train, replace=False)
    xt = x[train_sel]
    xts, _, per_t = _shard_rows(comms, xt)

    xt_rot = _rotate_fn(comms.mesh, comms.axis)(xts, rot_rep)
    w = comms.shard(_valid_weights(n_train, per_t, r), axis=0)
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    seed_rows = xt[rng.choice(n_train, min(n_train, max(n_lists * 8, 1024)),
                              replace=False)]
    centers0 = _kmeans_plusplus(
        jax.random.PRNGKey(seed), jnp.asarray(seed_rows) @ rotation.T, n_lists
    )
    centers, _, _ = _kmeans_fit_sharded(
        comms, xt_rot, w, comms.replicate(centers0),
        max_iter=max(params.kmeans_n_iters, 2), metric_name=_metric_name(params.metric),
        balance=True, seed=seed, n_valid=n_train,
    )

    # --- codebooks: capped residual sample (cap parity with the
    # single-chip build: EM only needs enough rows per codebook entry)
    max_cb = _codebook_cap(params, n_lists)
    cb_sel = rng.choice(n_train, min(n_train, max_cb), replace=False)
    x_cb_rot = jnp.asarray(xt[cb_sel]) @ rotation.T
    from raft_tpu.cluster import kmeans_balanced

    cb_labels = kmeans_balanced.predict(x_cb_rot, centers, metric=_metric_name(params.metric))
    residuals = x_cb_rot - centers[cb_labels]
    key, ck = jax.random.split(key)
    pq_centers = _train_codebooks(
        params, ck, residuals, cb_labels, n_lists, pq_dim, pq_len
    )

    # --- SPMD label + encode the full dataset (codes stay on device)
    xs, _, _ = _shard_rows(comms, x)
    cen_rep = comms.replicate(centers)
    pqc_rep = comms.replicate(pq_centers)
    labels_sh, codes_sh = _spmd_label_encode(
        comms, xs, rot_rep, cen_rep, pqc_rep, params.metric, per_cluster
    )
    labels_np = np.asarray(labels_sh)  # (r*per,) — pad rows ignored below

    local_tbl, gids, sizes, max_list = _pack_rank_tables(
        labels_np, n, per, r, n_lists
    )
    tbl_sh = comms.shard(jnp.asarray(local_tbl), axis=0)
    packed = _spmd_pack_rows(comms, codes_sh, tbl_sh, per, jnp.uint8)

    return DistributedIvfPq(
        comms,
        params,
        rot_rep,
        cen_rep,
        pqc_rep,
        packed,
        comms.shard(jnp.asarray(gids), axis=0),
        n,
        host_gids=gids,
        list_sizes=sizes,
    )


def ivf_pq_build_local(
    comms: Comms, params, local_dataset, seed: int = 0
) -> DistributedIvfPq:
    """Distributed IVF-PQ build where each controller contributes its OWN
    data partition (collective; per-worker-partition raft-dask model).
    The trainset fraction is drawn per-process from local rows, coarse
    centers train with the distributed balanced EM, codebooks train on a
    replicated capped residual sample (deterministic — every controller
    derives identical quantizers), and the full data is labeled+encoded
    SPMD with per-process table packing. Searches like ivf_pq_build's
    index (slot gids are caller row ids in process-concatenation order);
    extend/save need single-controller host mirrors and reject these."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
    from raft_tpu.cluster.kmeans import _kmeans_plusplus
    from raft_tpu.cluster import kmeans_balanced

    local = np.asarray(local_dataset, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    n = int(counts.sum())
    d = local.shape[1]
    n_lists = params.n_lists
    if n_lists > n:
        raise ValueError(f"n_lists={n_lists} > total rows {n}")
    per_cluster = params.codebook_kind == ivf_pq_mod.PER_CLUSTER

    pq_dim, pq_len, rot_dim = _pq_geometry(params, d)
    key = jax.random.PRNGKey(seed)
    key, rk = jax.random.split(key)
    rotation = ivf_pq_mod._make_rotation(
        rk, rot_dim, d, params.force_random_rotation or rot_dim != d
    )
    rot_rep = comms.replicate(np.asarray(rotation))

    # --- trainset: every process contributes its proportional fraction
    frac = min(max(params.kmeans_trainset_fraction, 0.0), 1.0)
    n_train_target = min(n, max(n_lists * 4, int(n * frac)))
    pi = jax.process_index()
    my_n = int(counts[pi])
    my_train = min(my_n, max(1, int(round(n_train_target * my_n / max(n, 1)))))
    rng_p = np.random.default_rng(seed * 1_000_003 + pi)
    xt_local = local[rng_p.choice(my_n, my_train, replace=False)]
    counts_t, per_t, _ = _local_layout(comms, my_train)
    xt_p, _wt = _pack_local(xt_local, per_t, lranks)
    xts = comms.shard_from_local(xt_p, axis=0)
    wt = comms.shard_from_local(_wt, axis=0)
    n_train = int(counts_t.sum())
    valid_counts_t = _rank_valid_counts(comms, counts_t, per_t)

    xt_rot = _rotate_fn(comms.mesh, comms.axis)(xts, rot_rep)

    gpos_t = _valid_global_positions(comms, counts_t, per_t)
    rng = np.random.default_rng(seed)
    sel = gpos_t[
        rng.choice(n_train, min(n_train, max(n_lists * 8, 1024)), replace=False)
    ]
    sub = _gather_replicated(comms, xt_rot, sel)
    centers0 = _kmeans_plusplus(jax.random.PRNGKey(seed), jnp.asarray(sub), n_lists)
    centers, _, _ = _kmeans_fit_sharded(
        comms, xt_rot, wt, comms.replicate(np.asarray(centers0)),
        max_iter=max(params.kmeans_n_iters, 2),
        metric_name=_metric_name(params.metric),
        balance=True, seed=seed, n_valid=n_train, valid_counts=valid_counts_t,
    )

    # --- codebooks: replicated capped residual sample (cap parity with
    # the driver build); identical on every controller
    max_cb = _codebook_cap(params, n_lists)
    cb_sel = gpos_t[rng.choice(n_train, min(n_train, max_cb), replace=False)]
    x_cb_rot = jnp.asarray(_gather_replicated(comms, xt_rot, cb_sel))
    centers_host = jnp.asarray(np.asarray(centers.addressable_shards[0].data))
    cb_labels = kmeans_balanced.predict(
        x_cb_rot, centers_host, metric=_metric_name(params.metric)
    )
    residuals = x_cb_rot - centers_host[cb_labels]
    key, ck = jax.random.split(key)
    pq_centers = _train_codebooks(
        params, ck, residuals, cb_labels, n_lists, pq_dim, pq_len
    )

    # --- SPMD label + encode every process's rows
    xp, _ = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    cen_rep = comms.replicate(centers) if not Comms._is_global(centers) else centers
    pqc_rep = comms.replicate(np.asarray(pq_centers))
    labels_sh, codes_sh = _spmd_label_encode(
        comms, xs, rot_rep, cen_rep, pqc_rep, params.metric, per_cluster
    )
    labels_local = _local_shard_rows_host(labels_sh)
    valid_counts = _rank_valid_counts(comms, counts, per)
    tbl_sh, gids_sh, gids_local, sizes_local = _pack_local_tables(
        comms, labels_local, valid_counts, counts, per, n_lists
    )
    packed = _spmd_pack_rows(comms, codes_sh, tbl_sh, per, jnp.uint8)
    return DistributedIvfPq(
        comms,
        params,
        rot_rep,
        cen_rep,
        pqc_rep,
        packed,
        gids_sh,
        n,
        host_gids=None,
        list_sizes=None,
        local_gids=gids_local,
        local_sizes=sizes_local,
    )


def ivf_pq_extend(index: DistributedIvfPq, new_vectors) -> DistributedIvfPq:
    """Distributed extend (ivf_pq_build.cuh:1061 at MNMG scale): the new
    batch is sharded round-robin, labeled/encoded SPMD on each rank, and
    appended into grown per-rank tables with a device-side gather —
    O(n_new + table copy), same complexity as the single-chip extend."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    comms = index.comms
    r = comms.get_size()
    nv = np.asarray(new_vectors, np.float32)
    n_new = nv.shape[0]
    if n_new == 0:
        return index
    if comms.spans_processes():
        # constructible via ivf_pq_load on a spanning mesh: extend is a
        # single-controller (driver) operation — the new batch is one full
        # host array, which no single controller can shard here
        raise ValueError(
            "distributed extend is single-controller; on a multi-process "
            "mesh use ivf_pq_extend_local (each controller passes its own "
            "new rows)"
        )
    if getattr(index, "bridged", False):
        raise ValueError(
            "extend on a bridged (distribute_index) layout can collide "
            "caller ids; extend the single-chip index and re-distribute"
        )
    if index.host_gids is None or index.list_sizes is None:
        raise ValueError(
            "index lacks global host mirrors (built with ivf_pq_build_local?);"
            " use ivf_pq_extend_local"
        )
    n_lists = index.params.n_lists
    per_cluster = index.params.codebook_kind == ivf_pq_mod.PER_CLUSTER
    pq_dim = index.codes.shape[-1]
    old_max = index.codes.shape[2]

    nvs, _, per_new = _shard_rows(comms, nv)
    labels_sh, codes_sh = _spmd_label_encode(
        comms, nvs, index.rotation, index.centers, index.pq_centers,
        index.params.metric, per_cluster,
    )
    new_tbl, host_gids, new_sizes, new_max = _append_rank_tables(
        np.asarray(labels_sh), index.list_sizes, index.host_gids, old_max,
        per_new, n_new, n_lists, index.n, r,
    )
    packed = _spmd_grow_tables(
        comms, index.codes, codes_sh, comms.shard(jnp.asarray(new_tbl), axis=0),
        per_new, new_max, jnp.uint8,
    )
    return DistributedIvfPq(
        comms,
        index.params,
        index.rotation,
        index.centers,
        index.pq_centers,
        packed,
        comms.shard(jnp.asarray(host_gids), axis=0),
        index.n + n_new,
        host_gids=host_gids,
        list_sizes=new_sizes,
        extended=True,
    )


def _place_append_batches(labels_np, per_new: int, n_valid: int,
                          old_sizes, n_lists: int, old_max: int):
    """Per-rank destination slots for a rank-blocked new batch appended
    after each list's fill: rank rr's valid rows are the prefix
    clip(n_valid - rr*per_new, 0, per_new) of its block (vectorized via
    ivf_flat._append_slots — bincount/argsort, O(n_new) numpy; a Python
    per-row loop here would serialize a 1M-row extend). The ONE
    placement walk shared by the single-controller and collective
    extends. Returns (placements, new_sizes, max_size)."""
    from raft_tpu.neighbors.ivf_flat import _append_slots

    new_sizes = old_sizes.copy()
    mx = old_max
    placements = []  # per rank: (labels, slot_abs) or None for empty shards
    for rr in range(old_sizes.shape[0]):
        nv = int(np.clip(n_valid - rr * per_new, 0, per_new))
        if nv == 0:  # trailing rank past the batch
            placements.append(None)
            continue
        lab = labels_np[rr * per_new : rr * per_new + nv].astype(np.int64)
        slot_abs, sizes_rr, _ = _append_slots(
            lab, old_sizes[rr].astype(np.int64), n_lists
        )
        new_sizes[rr] = sizes_rr.astype(np.int32)
        mx = max(mx, int(sizes_rr.max()))
        placements.append((lab, slot_abs))
    return placements, new_sizes, mx


def _align_group(mx: int, old_max: int, group: int = 32) -> int:
    """Round the grown list width up to the slot-group multiple, never
    shrinking below the old width."""
    return max(-(-mx // group) * group, old_max)


def _stamp_append_tables(placements, old_gids, old_max: int, new_max: int,
                         n_lists: int, id_base):
    """Grow gid tables and build the new-row placement table: row j of
    rank rr's valid prefix lands at its placement slot with id
    id_base[rr] + j — the ONE id-assignment stamp shared by both extend
    paths. Returns (new_tbl local-new-row ids, grown gids)."""
    r = len(placements)
    new_tbl = np.full((r, n_lists, new_max), -1, np.int32)
    gids = np.full((r, n_lists, new_max), -1, np.int32)
    gids[:, :, :old_max] = old_gids
    for rr, pl in enumerate(placements):
        if pl is None:
            continue
        lab, slot_abs = pl
        j = np.arange(len(lab), dtype=np.int32)
        new_tbl[rr, lab, slot_abs] = j
        gids[rr, lab, slot_abs] = int(id_base[rr]) + j
    return new_tbl, gids


def _append_rank_tables(labels_np, old_sizes, old_host_gids, old_max: int,
                        per_new: int, n_new: int, n_lists: int, n_old: int,
                        r: int):
    """Host bookkeeping for the single-controller distributed extend.
    Returns (new_tbl local-new-row ids, host_gids, new_sizes, new_max)."""
    placements, new_sizes, mx = _place_append_batches(
        labels_np, per_new, n_new, old_sizes, n_lists, old_max
    )
    new_max = _align_group(mx, old_max)
    new_tbl, host_gids = _stamp_append_tables(
        placements, old_host_gids, old_max, new_max, n_lists,
        n_old + per_new * np.arange(r, dtype=np.int64),
    )
    return new_tbl, host_gids, new_sizes, new_max


def _spmd_grow_tables(comms: Comms, old_tbl, rows_sh, new_tbl_sh,
                      per_new: int, new_max: int, out_dtype):
    """Grow per-rank list tables to new_max slots and place the sharded new
    rows at their destination slots inside shard_map (device gather, no
    scatters) — the distributed _grow_and_scatter."""
    n_lists = old_tbl.shape[1]
    old_max = old_tbl.shape[2]
    d = old_tbl.shape[3]

    @jax.jit
    def grow(old_tbl, rows_sh, tbl):
        def body(old_tbl, rows_sh, tbl):
            t = tbl[0]  # (n_lists, new_max)
            out = jnp.zeros((n_lists, new_max, d), out_dtype)
            out = out.at[:, :old_max].set(old_tbl[0])
            new_vals = rows_sh[jnp.clip(t, 0, max(per_new - 1, 0))]
            out = jnp.where((t >= 0)[..., None], new_vals.astype(out_dtype), out)
            return out[None]

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None, None, None), P(comms.axis, None),
                      P(comms.axis, None, None)),
            out_specs=P(comms.axis, None, None, None), check_vma=False,
        )(old_tbl, rows_sh, tbl)

    return grow(old_tbl, rows_sh, new_tbl_sh)


def ivf_flat_extend(index: DistributedIvfFlat, new_vectors) -> DistributedIvfFlat:
    """Distributed IVF-Flat extend: the new batch is sharded round-robin,
    labeled SPMD, and appended into grown per-rank list stores with a
    device-side gather — O(n_new + table copy)."""
    comms = index.comms
    r = comms.get_size()
    nv = np.asarray(new_vectors, np.float32)
    n_new = nv.shape[0]
    if n_new == 0:
        return index
    if comms.spans_processes():
        # constructible via ivf_flat_load on a spanning mesh: extend is a
        # single-controller (driver) operation — the new batch is one full
        # host array, which no single controller can shard here
        raise ValueError(
            "distributed extend is single-controller; on a multi-process "
            "mesh use ivf_flat_extend_local (each controller passes its "
            "own new rows)"
        )
    if getattr(index, "bridged", False):
        raise ValueError(
            "extend on a bridged (distribute_index) layout can collide "
            "caller ids; extend the single-chip index and re-distribute"
        )
    if index.host_gids is None or index.list_sizes is None:
        raise ValueError(
            "index lacks global host mirrors (built with ivf_flat_build_local?"
            "); use ivf_flat_extend_local"
        )
    n_lists = index.params.n_lists
    old_max = index.list_data.shape[2]

    nvs, _, per_new = _shard_rows(comms, nv)
    labels_sh = _spmd_predict(comms, nvs, index.centers)
    new_tbl, host_gids, new_sizes, new_max = _append_rank_tables(
        np.asarray(labels_sh), index.list_sizes, index.host_gids, old_max,
        per_new, n_new, n_lists, index.n, r,
    )
    ldata = _spmd_grow_tables(
        comms, index.list_data, nvs, comms.shard(jnp.asarray(new_tbl), axis=0),
        per_new, new_max, jnp.float32,
    )
    return DistributedIvfFlat(
        comms,
        index.params,
        index.centers,
        ldata,
        comms.shard(jnp.asarray(host_gids), axis=0),
        index.n + n_new,
        host_gids=host_gids,
        list_sizes=new_sizes,
    )


def _extend_local_impl(index, local_new, label_payload_fn, store, out_dtype,
                       dim: int):
    """Collective extend where each controller appends its OWN new rows
    (the multi-controller analogue of `*_extend`; raft-dask model). New
    ids continue the build's id space: position in the process-order
    concatenation of the NEW partitions, offset by the old total.

    Every process: pack+shard its rows, SPMD label/encode, place its
    ranks' new rows with _append_slots against its per-process mirrors,
    agree on the new global list width (one host allgather), and grow
    the sharded tables device-side. Returns (grown_store, gids_sh,
    gids_local, sizes_local, n_total), or None for an empty batch.
    `dim` validates the caller's row width up front (a mismatch would
    otherwise surface as an XLA shape error mid-collective)."""
    comms = index.comms
    local = np.asarray(local_new, np.float32)
    if local.ndim != 2 or local.shape[1] != dim:
        raise ValueError(
            f"new rows must be (n, {dim}), got {local.shape}"
        )
    if getattr(index, "bridged", False):
        raise ValueError(
            "extend on a bridged (distribute_index) layout can collide "
            "caller ids; extend the single-chip index and re-distribute"
        )
    if index.local_gids is None or index.local_sizes is None:
        raise ValueError(
            "index lacks the per-process mirrors extend_local appends "
            "against (kept by *_build_local builds and checkpoint loads)"
        )
    counts_new, per_new, lranks = _local_layout(comms, local.shape[0])
    total_new = int(counts_new.sum())
    if total_new == 0:
        return None
    n_lists = index.params.n_lists
    old_max = store.shape[2]

    xp, _ = _pack_local(local, per_new, lranks)
    nvs = comms.shard_from_local(xp, axis=0)
    labels_sh, payload_sh = label_payload_fn(nvs)
    labels_local = _local_shard_rows_host(labels_sh)

    pi = jax.process_index()
    placements, sizes_new, my_max = _place_append_batches(
        labels_local, per_new, int(counts_new[pi]), index.local_sizes,
        n_lists, old_max,
    )
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        all_max = np.asarray(multihost_utils.process_allgather(
            jnp.asarray([my_max]), tiled=True))
        my_max = int(all_max.max())
    new_max = _align_group(my_max, old_max)

    new_base = index.n + int(counts_new[:pi].sum())
    new_tbl, gids_grown = _stamp_append_tables(
        placements, index.local_gids, old_max, new_max, n_lists,
        new_base + per_new * np.arange(lranks, dtype=np.int64),
    )
    tbl_sh = comms.shard_from_local(new_tbl, axis=0)
    grown = _spmd_grow_tables(comms, store, payload_sh, tbl_sh, per_new,
                              new_max, out_dtype)
    gids_sh = comms.shard_from_local(gids_grown, axis=0)
    return grown, gids_sh, gids_grown, sizes_new, index.n + total_new


def ivf_flat_extend_local(index: DistributedIvfFlat,
                          local_new_vectors) -> DistributedIvfFlat:
    """Collective multi-controller IVF-Flat extend: every process calls
    with its OWN new rows (zero-row partitions fine). Returned ids for
    the new rows continue the id space — old total + position in the
    process-order concatenation of the new partitions."""
    res = _extend_local_impl(
        index, local_new_vectors,
        lambda nvs: (_spmd_predict(index.comms, nvs, index.centers), nvs),
        index.list_data, jnp.float32, dim=int(index.list_data.shape[-1]),
    )
    if res is None:
        return index
    ldata, gids_sh, gids_local, sizes_local, n_total = res
    return DistributedIvfFlat(
        index.comms, index.params, index.centers, ldata, gids_sh, n_total,
        local_gids=gids_local, local_sizes=sizes_local,
    )


def ivf_pq_extend_local(index: DistributedIvfPq,
                        local_new_vectors) -> DistributedIvfPq:
    """Collective multi-controller IVF-PQ extend (see
    ivf_flat_extend_local). The returned index re-derives its int8
    reconstruction store lazily on first search. It is marked extended;
    unlike driver-built extends (which refine post-merge over the full
    dataset), a *_local-extended layout cannot refine — its partitions'
    ids straddle the original and appended id blocks."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    per_cluster = index.params.codebook_kind == ivf_pq_mod.PER_CLUSTER
    res = _extend_local_impl(
        index, local_new_vectors,
        lambda nvs: _spmd_label_encode(
            index.comms, nvs, index.rotation, index.centers,
            index.pq_centers, index.params.metric, per_cluster,
        ),
        index.codes, jnp.uint8, dim=int(index.rotation.shape[1]),
    )
    if res is None:
        return index
    codes, gids_sh, gids_local, sizes_local, n_total = res
    return DistributedIvfPq(
        index.comms, index.params, index.rotation, index.centers,
        index.pq_centers, codes, gids_sh, n_total, extended=True,
        local_gids=gids_local, local_sizes=sizes_local,
    )


def _fold_merge_tables(store, gids, sizes, r: int):
    """Merge a checkpoint's `fold` stored ranks per mesh rank: per-list
    slots concatenate along the slot axis (all hold global ids), then
    valid slots are compacted to a prefix (extend appends at
    list_sizes[l], which assumes no interior pad gaps)."""
    r_stored = store.shape[0]
    fold = r_stored // r
    n_lists, max_list = store.shape[1], store.shape[2]
    trail = store.shape[3:]
    store = store.reshape(r, fold, n_lists, max_list, *trail)
    store = np.moveaxis(store, 1, 2).reshape(r, n_lists, fold * max_list, *trail)
    gids = gids.reshape(r, fold, n_lists, max_list)
    gids = np.moveaxis(gids, 1, 2).reshape(r, n_lists, fold * max_list)
    sizes = sizes.reshape(r, fold, n_lists).sum(axis=1)
    pad_last = np.argsort(gids < 0, axis=-1, kind="stable")
    gids = np.take_along_axis(gids, pad_last, axis=-1)
    idx = pad_last.reshape(pad_last.shape + (1,) * len(trail))
    store = np.take_along_axis(store, idx, axis=2)
    return store, gids, sizes


def _load_rank_tables(store_np, gids_np, sizes_np, r_stored: int, r: int):
    """Shared loader scaffolding: re-shard a checkpoint's rank-major
    tables onto an r-rank mesh (fold-merge when smaller), else copy the
    deserializer's read-only views into writable mirrors."""
    if r_stored != r:
        if r_stored % r != 0:
            raise ValueError(
                f"stored rank count {r_stored} not divisible by mesh size {r}"
            )
        return _fold_merge_tables(store_np, gids_np, sizes_np, r)
    # copy: the deserializer hands out read-only frombuffer views and
    # every other constructor path provides writable host mirrors
    return store_np, gids_np.copy(), sizes_np


def ivf_flat_save(filename: str, index: DistributedIvfFlat) -> None:
    """Serialize a distributed IVF-Flat index (centers + rank-major list
    stores + fill counts); `ivf_flat_load` re-shards onto the loading
    session's mesh (see ivf_pq_save for the layout contract)."""
    from raft_tpu.core.serialize import serialize_arrays

    if index.host_gids is None or index.list_sizes is None:
        raise ValueError("index lacks host mirrors; rebuild with ivf_flat_build")
    if index.comms.spans_processes():
        # sharded tables span non-addressable devices; serializing needs a
        # single-controller session (re-load the checkpoint there)
        raise ValueError("distributed save is single-controller")
    serialize_arrays(
        filename,
        {
            "centers": index.centers,
            "list_data": index.list_data,
            "host_gids": index.host_gids,
            "list_sizes": index.list_sizes,
        },
        {
            "kind": "mnmg_ivf_flat",
            "version": 1,
            "n": index.n,
            "n_ranks": int(index.list_data.shape[0]),
            "metric": int(index.params.metric),
            "n_lists": index.params.n_lists,
            "bridged": bool(getattr(index, "bridged", False)),
        },
    )


def _save_local_impl(filename: str, index, store_arr, kind: str,
                     quant_arrays: dict, extra_meta: dict) -> None:
    """Collective sharded checkpoint: every process writes ITS ranks'
    tables to `{filename}.part{pi}` (device shards leave via
    addressable_shards — no cross-process gather, no single host ever
    holding the full index), process 0 writes the manifest (replicated
    quantizers + the rank->part map), and a global barrier makes the
    checkpoint complete when the call returns. The orbax-style
    per-process layout; `ivf_*_load` re-assembles on any mesh whose
    size divides the stored rank count."""
    from raft_tpu.core.serialize import serialize_arrays

    comms = index.comms
    if getattr(index, "bridged", False):
        raise ValueError(
            "bridged (distribute_index) layouts checkpoint via the "
            "single-chip index they were distributed from"
        )
    local_gids, local_sizes = index.local_gids, index.local_sizes
    if local_gids is None or local_sizes is None:
        if index.host_gids is not None and index.list_sizes is not None:
            # classic single-controller build: derive this process's
            # slices from the global host mirrors
            local_gids, local_sizes = _local_mirror_slices(
                comms, np.asarray(index.host_gids),
                np.asarray(index.list_sizes))
        else:
            raise ValueError(
                "index lacks the per-process mirrors a sharded save "
                "writes (kept by *_build_local builds, *_build builds, "
                "and checkpoint loads)"
            )
    ranks_by_proc = _ranks_by_proc(comms.mesh)
    pi = jax.process_index()
    my_ranks = ranks_by_proc.get(pi, [])
    shards = {int(s.index[0].start or 0): np.asarray(s.data)
              for s in store_arr.addressable_shards}
    store_local = np.concatenate([shards[j] for j in my_ranks], axis=0)
    serialize_arrays(
        f"{filename}.part{pi}",
        {"store": store_local, "gids": local_gids, "sizes": local_sizes},
        {"kind": kind + "_part", "ranks": [int(j) for j in my_ranks]},
    )

    def barrier(tag):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"raft_tpu_save_local:{kind}:{tag}")

    # manifest-as-commit-marker (the orbax ordering): every part must be
    # complete on disk BEFORE the manifest exists, so a mid-save crash
    # leaves no valid-looking manifest pointing at torn part files
    barrier("parts")
    if pi == 0:
        nproc = jax.process_count()
        serialize_arrays(
            filename,
            quant_arrays,
            {
                "kind": kind,
                "version": 1,
                "n": index.n,
                "n_ranks": comms.get_size(),
                "n_parts": nproc,
                "parts": [[int(j) for j in ranks_by_proc.get(p, [])]
                          for p in range(nproc)],
                **extra_meta,
            },
        )
    barrier("manifest")  # loads issued right after return see it


def _load_local_tables(comms: Comms, filename: str, meta: dict):
    """Per-process assembly of a sharded checkpoint: read only the part
    files covering THIS process's mesh ranks (fold-merging when the
    mesh is smaller than the stored rank count). Returns host
    (store, gids, sizes) for this process's ranks, mesh-rank order."""
    from raft_tpu.core.serialize import deserialize_arrays

    r = comms.get_size()
    r_stored = int(meta["n_ranks"])
    if r_stored % r:
        raise ValueError(
            f"stored rank count {r_stored} not divisible by mesh size {r}"
        )
    fold = r_stored // r
    my_ranks = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
    needed = [j * fold + k for j in my_ranks for k in range(fold)]
    where = {}
    for p, ranks in enumerate(meta["parts"]):
        for row, g in enumerate(ranks):
            where[int(g)] = (p, row)
    missing = [g for g in needed if g not in where]
    if missing:
        raise ValueError(f"manifest maps no part for stored ranks {missing}")
    by_part = {}
    for g in needed:
        p, row = where[g]
        by_part.setdefault(p, []).append((g, row))
    rows = {}
    for p, entries in by_part.items():
        arrays, _ = deserialize_arrays(f"{filename}.part{p}", to_device=False)
        store_p = np.asarray(arrays["store"])
        gids_p = np.asarray(arrays["gids"])
        sizes_p = np.asarray(arrays["sizes"])
        for g, row in entries:
            rows[g] = (store_p[row], gids_p[row], sizes_p[row])
    store = np.stack([rows[g][0] for g in needed])
    gids = np.stack([rows[g][1] for g in needed])
    sizes = np.stack([rows[g][2] for g in needed])
    if fold > 1:
        store, gids, sizes = _fold_merge_tables(store, gids, sizes,
                                                len(my_ranks))
    return store, gids, sizes.astype(np.int32)


def _local_mirror_slices(comms: Comms, gids: np.ndarray, sizes: np.ndarray):
    """This process's rank slices of a checkpoint's rank-major host
    tables — the per-process mirrors that make `*_extend_local` work on
    loaded indexes (each controller keeps only its own ranks' mirrors,
    in `_ranks_by_proc` order to match `_pack_local_tables`)."""
    my_ranks = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
    return (gids[my_ranks].copy(),
            sizes[my_ranks].astype(np.int32).copy())


def ivf_flat_save_local(filename: str, index: DistributedIvfFlat) -> None:
    """Collective sharded checkpoint of a distributed IVF-Flat index:
    every controller writes its own ranks' tables (`{filename}.part{p}`),
    process 0 the manifest — no single host ever materializes the full
    index (the pod-scale checkpoint path; `ivf_flat_save` needs a
    single-controller session). Load with `ivf_flat_load` on any mesh
    whose size divides the stored rank count (shared-fs contract)."""
    _save_local_impl(
        filename, index, index.list_data, "mnmg_ivf_flat_sharded",
        {"centers": np.asarray(index.centers.addressable_shards[0].data)},
        {"metric": int(index.params.metric),
         "n_lists": index.params.n_lists},
    )


def ivf_flat_load(comms: Comms, filename: str) -> DistributedIvfFlat:
    """Load a distributed IVF-Flat index — a single-file checkpoint
    (`ivf_flat_save`) or a sharded one (`ivf_flat_save_local`) —
    re-sharding onto this session's mesh (stored rank count must be a
    multiple of the mesh size)."""
    from raft_tpu.core.serialize import deserialize_arrays
    from raft_tpu.neighbors import ivf_flat as ivf_flat_mod

    arrays, meta = deserialize_arrays(filename, to_device=False)
    if meta.get("kind") == "mnmg_ivf_flat_sharded":
        ldata, gids_l, sizes_l = _load_local_tables(comms, filename, meta)
        params = ivf_flat_mod.IndexParams(
            n_lists=int(meta["n_lists"]), metric=DistanceType(meta["metric"])
        )
        return DistributedIvfFlat(
            comms,
            params,
            comms.replicate(jnp.asarray(arrays["centers"])),
            comms.shard_from_local(ldata, axis=0),
            comms.shard_from_local(gids_l, axis=0),
            int(meta["n"]),
            # single-controller mesh: this process's assembly IS the full
            # rank-major table, so classic extend/save work too; spanning
            # meshes keep only the per-process mirrors
            host_gids=None if comms.spans_processes() else gids_l,
            list_sizes=None if comms.spans_processes() else sizes_l,
            local_gids=gids_l,
            local_sizes=sizes_l,
        )
    if meta.get("kind") != "mnmg_ivf_flat":
        raise ValueError(f"not a distributed ivf_flat file: {meta.get('kind')}")
    r = comms.get_size()
    ldata, gids, sizes = _load_rank_tables(
        np.asarray(arrays["list_data"]), np.asarray(arrays["host_gids"]),
        np.asarray(arrays["list_sizes"]), int(meta["n_ranks"]), r,
    )
    params = ivf_flat_mod.IndexParams(
        n_lists=int(meta["n_lists"]), metric=DistanceType(meta["metric"])
    )
    local_gids, local_sizes = _local_mirror_slices(comms, gids, sizes)
    return DistributedIvfFlat(
        comms,
        params,
        comms.replicate(jnp.asarray(arrays["centers"])),
        _place_rank_major(comms, ldata),
        _place_rank_major(comms, gids),
        int(meta["n"]),
        # global host mirrors only where extend/save can consume them: on
        # a spanning mesh both raise, and the mirrors are index-sized host
        # RAM pinned on EVERY controller for nothing; the per-process
        # slices below keep the collective extend_local available there
        host_gids=None if comms.spans_processes() else gids,
        list_sizes=None if comms.spans_processes() else sizes.astype(np.int32),
        bridged=bool(meta.get("bridged", False)),
        local_gids=local_gids,
        local_sizes=local_sizes,
    )


def ivf_pq_save(filename: str, index: DistributedIvfPq) -> None:
    """Serialize a distributed IVF-PQ index (quantizers + the rank-major
    code/slot tables + fill counts) with the shared container codec —
    the pod-scale checkpoint/resume analogue of the single-chip
    ivf_pq.save (detail/ivf_pq_serialize.cuh). The rank-major layout is
    stored as-is; `ivf_pq_load` re-shards onto the loading session's mesh
    (any rank count whose padded geometry matches)."""
    from raft_tpu.core.serialize import serialize_arrays
    from raft_tpu.neighbors.ivf_pq import PER_CLUSTER

    if index.host_gids is None or index.list_sizes is None:
        raise ValueError("index lacks host mirrors; rebuild with ivf_pq_build")
    if index.comms.spans_processes():
        # sharded tables span non-addressable devices; serializing needs a
        # single-controller session (re-load the checkpoint there)
        raise ValueError("distributed save is single-controller")
    serialize_arrays(
        filename,
        {
            "rotation": index.rotation,
            "centers": index.centers,
            "pq_centers": index.pq_centers,
            "codes": index.codes,
            "host_gids": index.host_gids,
            "list_sizes": index.list_sizes,
        },
        {
            "kind": "mnmg_ivf_pq",
            "version": 1,
            "n": index.n,
            "n_ranks": int(index.codes.shape[0]),
            "metric": int(index.params.metric),
            "n_lists": index.params.n_lists,
            "pq_dim": int(index.codes.shape[-1]),
            "pq_bits": index.params.pq_bits,
            "per_cluster": index.params.codebook_kind == PER_CLUSTER,
            "extended": bool(getattr(index, "extended", False)),
            "bridged": bool(getattr(index, "bridged", False)),
        },
    )


def ivf_pq_save_local(filename: str, index: DistributedIvfPq) -> None:
    """Collective sharded checkpoint of a distributed IVF-PQ index (see
    ivf_flat_save_local): per-process part files + a process-0 manifest
    with the replicated quantizers. Load with `ivf_pq_load`."""
    from raft_tpu.neighbors.ivf_pq import PER_CLUSTER

    _save_local_impl(
        filename, index, index.codes, "mnmg_ivf_pq_sharded",
        {"rotation": np.asarray(index.rotation.addressable_shards[0].data),
         "centers": np.asarray(index.centers.addressable_shards[0].data),
         "pq_centers": np.asarray(
             index.pq_centers.addressable_shards[0].data)},
        {"metric": int(index.params.metric),
         "n_lists": index.params.n_lists,
         "pq_dim": int(index.codes.shape[-1]),
         "pq_bits": index.params.pq_bits,
         "per_cluster": index.params.codebook_kind == PER_CLUSTER,
         "extended": bool(getattr(index, "extended", False))},
    )


def _pq_params_from_meta(meta):
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    return ivf_pq_mod.IndexParams(
        n_lists=int(meta["n_lists"]),
        pq_dim=int(meta["pq_dim"]),
        pq_bits=int(meta.get("pq_bits", 8)),
        metric=DistanceType(meta["metric"]),
        codebook_kind=(
            ivf_pq_mod.PER_CLUSTER if meta.get("per_cluster")
            else ivf_pq_mod.PER_SUBSPACE
        ),
    )


def ivf_pq_load(comms: Comms, filename: str) -> DistributedIvfPq:
    """Load a distributed IVF-PQ index — single-file (`ivf_pq_save`) or
    sharded (`ivf_pq_save_local`) — and re-shard it onto this session's
    mesh. The stored rank count must be divisible by (or equal to) the
    mesh size — shards are merged along the rank axis by concatenating
    slot tables (per-rank tables of the same list stack side by side)."""
    from raft_tpu.core.serialize import deserialize_arrays

    # to_device=False: the unsharded tables are multi-GB at pod scale and
    # must never land whole on one device — they go host -> shards directly
    arrays, meta = deserialize_arrays(filename, to_device=False)
    if meta.get("kind") == "mnmg_ivf_pq_sharded":
        codes_l, gids_l, sizes_l = _load_local_tables(comms, filename, meta)
        return DistributedIvfPq(
            comms,
            _pq_params_from_meta(meta),
            comms.replicate(jnp.asarray(arrays["rotation"])),
            comms.replicate(jnp.asarray(arrays["centers"])),
            comms.replicate(jnp.asarray(arrays["pq_centers"])),
            comms.shard_from_local(codes_l, axis=0),
            comms.shard_from_local(gids_l, axis=0),
            int(meta["n"]),
            # see ivf_flat_load: full tables double as host mirrors on a
            # single-controller mesh
            host_gids=None if comms.spans_processes() else gids_l,
            list_sizes=None if comms.spans_processes() else sizes_l,
            extended=bool(meta.get("extended", False)),
            local_gids=gids_l,
            local_sizes=sizes_l,
        )
    if meta.get("kind") != "mnmg_ivf_pq":
        raise ValueError(f"not a distributed ivf_pq file: {meta.get('kind')}")
    r = comms.get_size()
    codes, gids, sizes = _load_rank_tables(
        np.asarray(arrays["codes"]), np.asarray(arrays["host_gids"]),
        np.asarray(arrays["list_sizes"]), int(meta["n_ranks"]), r,
    )
    params = _pq_params_from_meta(meta)
    local_gids, local_sizes = _local_mirror_slices(comms, gids, sizes)
    return DistributedIvfPq(
        comms,
        params,
        comms.replicate(jnp.asarray(arrays["rotation"])),
        comms.replicate(jnp.asarray(arrays["centers"])),
        comms.replicate(jnp.asarray(arrays["pq_centers"])),
        _place_rank_major(comms, codes),
        _place_rank_major(comms, gids),
        int(meta["n"]),
        # global host mirrors only where extend/save can consume them: on
        # a spanning mesh both raise, and the mirrors are index-sized host
        # RAM pinned on EVERY controller for nothing; the per-process
        # slices keep the collective extend_local available there
        host_gids=None if comms.spans_processes() else gids,
        list_sizes=None if comms.spans_processes() else sizes.astype(np.int32),
        extended=bool(meta.get("extended", False)),
        bridged=bool(meta.get("bridged", False)),
        local_gids=local_gids,
        local_sizes=local_sizes,
    )


def _build_distributed_recon(index: DistributedIvfPq,
                             pad_to_lanes: bool = False) -> None:
    """Per-rank int8 reconstruction stores for the list-major engine,
    decoded from the packed codes inside shard_map (lazily, idempotent —
    the distributed build_reconstruction). With `pad_to_lanes` the slot
    axis pads to the fused Pallas list-scan's 128-lane contract
    (recon_norm +inf, slot gids -1 on pad slots — masked exactly like
    in-list padding); once padded, the store stays padded (monotone,
    same contract as the single-chip build_reconstruction)."""
    base = int(index.codes.shape[2])
    have = int(index.recon8.shape[2]) if index.recon8 is not None else -1
    if have >= base:
        if pad_to_lanes:
            _pad_distributed_recon(index, base)
        return
    from raft_tpu.neighbors.ivf_pq import _decode_quantize

    comms = index.comms
    per_cluster = index.params.codebook_kind == _per_cluster_kind()

    @jax.jit
    def run(codes, pq_centers):
        def body(codes, pq_centers):
            r8, scale, rnorm = _decode_quantize(codes[0], pq_centers, per_cluster)
            return r8[None], scale, rnorm[None]

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None, None, None), P(None, None, None)),
            out_specs=(P(comms.axis, None, None, None), P(None),
                       P(comms.axis, None, None)), check_vma=False,
        )(codes, pq_centers)

    index.recon8, index.recon_scale, index.recon_norm = run(
        index.codes, index.pq_centers
    )
    index.slot_gids_pad = index.slot_gids
    if pad_to_lanes:
        _pad_distributed_recon(index, base)


def _pad_distributed_recon(index: DistributedIvfPq, base: int) -> None:
    """Pad the (sharded) recon store's slot axis to the Pallas lane
    contract; no-op when already wide enough."""
    from raft_tpu.ops.pq_list_scan import lane_padded

    lpad = lane_padded(base)
    extra = lpad - int(index.recon8.shape[2])
    if extra <= 0:
        return
    if index.slot_gids_pad is None:
        index.slot_gids_pad = index.slot_gids
    index.recon8 = jnp.pad(index.recon8, ((0, 0), (0, 0), (0, extra), (0, 0)))
    index.recon_norm = jnp.pad(index.recon_norm,
                               ((0, 0), (0, 0), (0, extra)),
                               constant_values=jnp.inf)
    index.slot_gids_pad = jnp.pad(index.slot_gids_pad,
                                  ((0, 0), (0, 0), (0, extra)),
                                  constant_values=-1)


def _per_cluster_kind():
    from raft_tpu.neighbors.ivf_pq import PER_CLUSTER

    return PER_CLUSTER


def _refine_layout(index, refine_dataset, allow_extended: bool = False):
    """Sharded original rows + per-rank (base, valid) for the distributed
    refine: rank j owns caller ids [base_j, base_j + valid_j), and its
    dataset shard row l holds caller id base_j + l — true for both the
    driver layout (contiguous global rows) and the *_local layout.

    The layout (including the device-sharded copy of the dataset) is
    cached on the index keyed by the dataset object's identity, so a
    serving loop passing the same array re-ships nothing. SINGLE-
    controller only: on a spanning mesh a per-process identity hit would
    let one process skip the layout collectives another still enters —
    a silent deadlock — so multi-controller calls always recompute
    (symmetric collectives every call). Release the pinned copy with
    index.clear_refine_cache()."""
    comms = index.comms
    cacheable = not comms.spans_processes()
    cache = getattr(index, "_refine_cache", None)
    if cacheable and cache is not None and cache[0] is refine_dataset:
        return cache[1], cache[2], cache[3]
    if getattr(index, "bridged", False):
        raise ValueError(
            "refine_dataset needs gids that index the dataset rows: "
            "bridged (distribute_index) layouts may carry arbitrary "
            "caller ids — refine on the single-chip index instead"
        )
    if getattr(index, "extended", False):
        # allow_extended = the post-merge refine topology, whose
        # ownership follows this layout's contiguous sharding rather
        # than the index's (now non-contiguous) list placement. It needs
        # the full-dataset layout: a *_local-extended partition's ids
        # are split between the original and extended id blocks, which
        # the per-partition layout cannot express.
        if not allow_extended or index.host_gids is None:
            raise ValueError(
                "refine on an extended index runs post-merge over the "
                "FULL dataset layout (driver-built indexes do this "
                "automatically); *_local-extended layouts are "
                "unsupported — rebuild to refine"
            )
    if index.host_gids is not None:  # driver build: the FULL host array
        x = np.asarray(refine_dataset, np.float32)
        if x.shape[0] != index.n:
            raise ValueError(
                f"refine_dataset has {x.shape[0]} rows, index holds {index.n}"
            )
        xs, n, per = _shard_rows(comms, x)
        r = comms.get_size()
        base = per * np.arange(r, dtype=np.int64)
        valid = np.clip(n - base, 0, per)
        if cacheable:
            index._refine_cache = (refine_dataset, xs, base, valid)
        return xs, base, valid
    # *_local build: THIS process's partition (collective)
    local = np.asarray(refine_dataset, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    if int(counts.sum()) != index.n:
        raise ValueError(
            f"refine_dataset partitions sum to {int(counts.sum())} rows, "
            f"index holds {index.n}"
        )
    xp, _ = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    base, valid = _rank_layout(comms, counts, per)
    if cacheable:
        index._refine_cache = (refine_dataset, xs, base, valid)
    return xs, base, valid


def _exact_scores(q, rows, metric):
    """Exact (nq, kk) scores of gathered candidate rows."""
    if metric == DistanceType.InnerProduct:
        return jnp.einsum("qd,qkd->qk", q, rows)
    diff = q[:, None, :] - rows
    exact = jnp.sum(diff * diff, axis=2)
    if metric == DistanceType.L2SqrtExpanded:
        exact = jnp.sqrt(jnp.maximum(exact, 0.0))
    return exact


def _refine_local(q, gid, xs, base, valid, rank, metric, worst):
    """Exact per-rank re-rank: every candidate a rank reports came from
    its own lists, so its original row is in the rank's dataset shard —
    the distributed form of neighbors/refine.cuh with no cross-rank
    gathers. PQ scores are discarded; gids alone drive the gather."""
    local = gid - base[rank]
    own = (gid >= 0) & (local >= 0) & (local < valid[rank])
    rows = xs[jnp.clip(local, 0, xs.shape[0] - 1)]  # (nq, kk, d)
    exact = _exact_scores(q, rows, metric)
    return jnp.where(own, exact, worst), jnp.where(own, gid, -1)


def _refine_merged(ac, q, mgid, xs, base, valid, rank, metric, worst, k,
                   select_min):
    """Post-merge exact re-rank (inside shard_map): candidate ownership
    follows the refine dataset's CONTIGUOUS sharding, not the index's
    list placement — so it refines layouts whose per-rank gid ownership
    is non-contiguous (extended indexes), which the pre-merge
    `_refine_local` cannot. Each gid has exactly one owner in the
    contiguous layout; owners contribute exact scores, everyone else the
    worst value, and one MIN/MAX allreduce of the (nq, kk) shortlist
    assembles the exact scores on every rank. -1 merge pads have no
    owner, stay at worst, and sort last with id -1."""
    local = mgid - base[rank]
    own = (mgid >= 0) & (local >= 0) & (local < valid[rank])
    rows = xs[jnp.clip(local, 0, xs.shape[0] - 1)]  # (nq, kk, d)
    exact = _exact_scores(q, rows, metric)
    contrib = jnp.where(own, exact, worst)
    combined = ac.allreduce(contrib, op_t.MIN if select_min else op_t.MAX)
    fv, fp = _select_k_impl(combined, min(k, combined.shape[1]), select_min)
    return fv, jnp.take_along_axis(mgid, fp, axis=1)


def _replicated_filter_bits(comms: Comms, prefilter, id_bound: int):
    """Coerce a distributed-search prefilter into (replicated packed
    bits, bit count). Without a filter, a 1-word placeholder keeps one
    jitted signature (the use_pf static flag skips it)."""
    if prefilter is None:
        return comms.replicate(np.zeros(1, np.uint32)), 1
    from raft_tpu.core.bitset import as_bitset

    bs = as_bitset(prefilter, id_bound)
    return comms.replicate(np.asarray(bs.bits)), bs.n


def _shard_filtered(gid_tbl, bits, n: int, use_pf: bool):
    """Filtered view of a shard-local gid table (global ids; -1 pad) —
    inside shard_map, so plain ops on the local block."""
    if not use_pf:
        return gid_tbl
    from raft_tpu.core.bitset import Bitset, filter_slot_table

    return filter_slot_table(gid_tbl, None, Bitset(bits, n))


def ivf_pq_search(index: DistributedIvfPq, queries, k: int, n_probes: int = 20,
                  engine: str = "auto", refine_dataset=None,
                  refine_mult: int = 4, prefilter=None,
                  query_mode: str = "auto", trim_engine: str = "approx",
                  score_dtype: str = "bf16"):
    """SPMD search: every rank scores its local lists for the same global
    probes; local top-k are merged on all ranks ("replicated") or routed
    to per-rank query blocks ("sharded" — R× less merge traffic for
    serving; see `_resolve_query_mode` for "auto"). Both modes return the
    full (nq, k) result as a global jax.Array; sharded output is laid out
    query-sharded across the mesh instead of replicated.

    `engine`: "recon8_list" (the list-major int8-reconstruction engine the
    single-chip flagship uses — each rank streams each probed list once),
    "lut" (query-major, for tiny batches), or "auto" (same duplication
    heuristic as the single-chip `search`). With engine="recon8_list",
    `trim_engine="pallas"` runs the fused list-scan trim per rank and
    `score_dtype="int8"` scores with symmetric int8 queries (the int8
    MXU path) — both mirror the single-chip SearchParams options.

    `refine_dataset` enables the high-recall pipeline (neighbors/
    refine.cuh distributed): each rank takes a `refine_mult * k`
    shortlist from its PQ scores, re-ranks its OWN candidates exactly
    against the original vectors (a rank's candidates all come from its
    own rows — no cross-rank gathers), and the exact scores merge.
    Pass the full dataset for driver-built indexes, or this process's
    partition for *_local-built ones. EXTENDED driver-built indexes
    refine post-merge instead (`_refine_merged`: the global shortlist
    merges first, then owners in the dataset's contiguous sharding
    contribute exact scores through one MIN/MAX allreduce) — pass the
    full dataset including the extended rows; *_local-extended layouts
    cannot refine. This topology reduces across ranks per query, so an
    extended+refined search always returns the REPLICATED output layout
    — an explicit query_mode="sharded" request degrades to replicated
    with a warning.

    `prefilter` (core.Bitset or boolean mask over the GLOBAL id space,
    `index.id_bound` ids; identical on every controller) excludes
    samples before trim/selection on every rank — the slot tables hold
    global ids, so one replicated bitset serves all shards."""
    from raft_tpu.neighbors.ivf_pq import (
        _search_impl, _search_impl_recon8_listmajor, PER_CLUSTER,
    )

    comms = index.comms
    ac = comms.comms
    q = jnp.asarray(queries, jnp.float32)
    metric = index.params.metric
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    n_probes = int(min(n_probes, index.params.n_lists))
    per_cluster = index.params.codebook_kind == PER_CLUSTER
    # extended indexes refine POST-merge (ownership by the refine
    # dataset's contiguous sharding, see _refine_merged); that topology
    # reduces across ranks per query, so it needs replicated queries
    refine_merged = (refine_dataset is not None
                     and bool(getattr(index, "extended", False)))
    mode = _resolve_query_mode(query_mode, comms, q.shape[0], k)
    if refine_merged:
        if query_mode == "sharded":
            # an EXPLICIT sharded request changes the returned layout the
            # caller asked for — surface the degrade (silent fallback is
            # reserved for "auto"; ADVICE r3)
            warnings.warn(
                "query_mode='sharded' is incompatible with refined search "
                "on an extended index (post-merge refine reduces across "
                "ranks per query); returning the REPLICATED layout",
                stacklevel=2,
            )
        mode = "replicated"
    nq = q.shape[0]
    if mode == "sharded":
        q, nq = _pad_queries(q, comms.get_size())
    merge = _merge_local_topk if mode == "replicated" else _merge_local_topk_scatter
    out_spec = P(None, None) if mode == "replicated" else P(comms.axis, None)

    if engine == "auto":
        if score_dtype == "int8" or trim_engine == "pallas":
            # an explicit int8 / pallas-trim request pins the engine that
            # honors it (same rule as the single-chip search: numerics
            # must not depend on batch size or tuned state)
            engine = "recon8_list"
        else:
            from raft_tpu.core import tuned

            # same policy as ivf_pq._resolve_score_mode, restricted to
            # the two distributed engines: on TPU the resolution NEVER
            # lands on lut (its gather kernel-faults the device —
            # docs/perf.md device-fault section), even from a
            # CPU-rehearsal-fitted tuned key
            on_tpu = jax.default_backend() == "tpu"
            t = tuned.get("pq_auto_engine")
            if t in ("recon8_list", "lut") and not (t == "lut" and on_tpu):
                engine = t
            else:
                dup = q.shape[0] * n_probes / max(1, index.params.n_lists)
                engine = "recon8_list" if (dup >= 4.0 or on_tpu) else "lut"
    if engine not in ("recon8_list", "lut"):
        raise ValueError(f"unknown engine {engine!r}")
    if engine == "lut":
        from raft_tpu.neighbors.ivf_pq import _check_lut_allowed

        _check_lut_allowed()  # explicit lut on TPU: same fence as single-chip

    qr = comms.replicate(q)
    pf_bits, pf_n = _replicated_filter_bits(comms, prefilter, index.id_bound)
    refine = refine_dataset is not None
    if refine:
        xs_r, base_r, valid_r = _refine_layout(
            index, refine_dataset, allow_extended=refine_merged)
        base_rep = comms.replicate(np.asarray(base_r, np.int32))
        valid_rep = comms.replicate(np.asarray(valid_r, np.int32))
        # shortlist never narrower than k (a cap below k would shrink the
        # merged output width); inflation capped at 256 gathered rows
        kk = int(max(k, min(max(refine_mult, 1) * k, 256)))
    else:
        # zero-size placeholders keep one jitted signature per engine
        xs_r = comms.shard(
            jnp.zeros((comms.get_size(), 1), jnp.float32), axis=0
        ) if not comms.spans_processes() else comms.shard_from_local(
            np.zeros((len(_ranks_by_proc(comms.mesh).get(jax.process_index(), [])), 1),
                     np.float32), axis=0
        )
        base_rep = comms.replicate(np.zeros(comms.get_size(), np.int32))
        valid_rep = comms.replicate(np.zeros(comms.get_size(), np.int32))
        kk = int(k)

    def finish(v, gid, q, xs, base, valid):
        if refine_merged:
            v = jnp.where(gid >= 0, v, worst)
            # global shortlist kept as wide as the pre-merge path's total
            # exact re-rank depth (r ranks x kk each, under the same
            # 256-row gather cap) — merging down to kk first would drop
            # true neighbors PQ ranks 21st+ before exact scoring. Never
            # narrower than kk itself: kk >= k, and a sub-k shortlist
            # would shrink the (nq, k) output width.
            kk_merged = min(comms.get_size() * kk, max(256, kk))
            _, mgid = merge(ac, v, gid, kk_merged, select_min)
            return _refine_merged(ac, q, mgid, xs, base, valid,
                                  ac.get_rank(), metric, worst, k, select_min)
        if refine:
            rank = ac.get_rank()
            v, gid = _refine_local(q, gid, xs, base, valid, rank, metric, worst)
        else:
            v = jnp.where(gid >= 0, v, worst)
        return merge(ac, v, gid, k, select_min)

    def trim(out):
        v, gid = out
        return (v[:nq], gid[:nq]) if v.shape[0] != nq else out

    if trim_engine not in ("approx", "pallas"):
        raise ValueError(f"unknown trim_engine {trim_engine!r}")
    if trim_engine == "pallas" and engine != "recon8_list":
        raise ValueError("trim_engine='pallas' requires engine='recon8_list'")
    if score_dtype not in ("bf16", "int8"):
        raise ValueError(f"unknown score_dtype {score_dtype!r}")
    if score_dtype == "int8" and engine != "recon8_list":
        raise ValueError("score_dtype='int8' requires engine='recon8_list'")
    int8_q = score_dtype == "int8"
    if engine == "recon8_list":
        use_pallas_trim = trim_engine == "pallas"
        if use_pallas_trim:
            # the fused list-scan's shape contract, checked per rank
            # (max_list is global across ranks, so this is static)
            from raft_tpu.ops.pq_list_scan import (
                _BINS, fits_pallas, lane_padded,
            )

            if kk > _BINS:
                raise ValueError(
                    f"trim_engine='pallas' caps per-list candidates at "
                    f"{_BINS}; k={kk}"
                )
            # rotation is (rot_dim, dim); the scanned store axis is rot_dim
            lpad = lane_padded(int(index.codes.shape[2]))
            if not fits_pallas(128, lpad, int(index.rotation.shape[0])):
                raise ValueError(
                    f"trim_engine='pallas': list length {lpad} exceeds the "
                    "kernel's VMEM envelope; use trim_engine='approx'"
                )
            from raft_tpu.neighbors.ivf_pq import (
                _search_impl_recon8_listmajor_pallas,
            )
        _build_distributed_recon(index, pad_to_lanes=use_pallas_trim)
        # ALWAYS the padded view: _build_distributed_recon keeps
        # slot_gids_pad width-matched to recon8 (== slot_gids until a
        # pallas search pads the store in place — after which the approx
        # engine must see the same padded width or its score/slot
        # broadcast shapes diverge)
        gid_source = index.slot_gids_pad
        interp = jax.default_backend() == "cpu"
        from raft_tpu.ops.pq_list_scan import fold_variant

        pfold = fold_variant()
        # distributed list-major engines honor the same measured scoring
        # granularity as the single-chip search (a chip race that rejects
        # the superblock structure must flip the serving path too)
        from raft_tpu.core import tuned as _tuned
        from raft_tpu.neighbors.probe_invert import CHUNK_BLOCKS

        cb = int(_tuned.get_choice("listmajor_chunk_block", CHUNK_BLOCKS, 0))

        def build_list():
            @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
            def run_list(rotation, centers, recon8, scale, rnorm, gid_tbl,
                         q, xs, base, valid, bits, k: int, use_pf: bool):
                def body(rotation, centers, recon8, scale, rnorm, gid_tbl,
                         q, xs, base, valid, bits):
                    srows = _shard_filtered(gid_tbl[0], bits, pf_n, use_pf)
                    if use_pallas_trim:
                        v, gid = _search_impl_recon8_listmajor_pallas(
                            q, rotation, centers, recon8[0], scale,
                            rnorm[0], srows, kk, n_probes, metric,
                            interpret=interp, int8_queries=int8_q,
                            fold=pfold,
                        )
                    else:
                        v, gid = _search_impl_recon8_listmajor(
                            q, rotation, centers, recon8[0], scale,
                            rnorm[0], srows, kk, n_probes, metric,
                            chunk_block=cb, int8_queries=int8_q,
                        )
                    return finish(v, gid, q, xs, base, valid)

                return jax.shard_map(
                    body, mesh=comms.mesh,
                    in_specs=(P(None, None), P(None, None),
                              P(comms.axis, None, None, None), P(None),
                              P(comms.axis, None, None),
                              P(comms.axis, None, None),
                              P(None, None), P(comms.axis, None), P(None),
                              P(None), P(None)),
                    out_specs=(out_spec, out_spec), check_vma=False,
                )(rotation, centers, recon8, scale, rnorm, gid_tbl, q, xs,
                  base, valid, bits)

            return run_list

        run_list = _cached_wrapper(
            ("pq_recon8_list", comms.mesh, comms.axis, mode, metric,
             int(k), kk, n_probes, refine, refine_merged, pf_n, int8_q,
             use_pallas_trim, interp, pfold, cb),
            build_list,
        )
        return trim(run_list(
            index.rotation, index.centers, index.recon8, index.recon_scale,
            index.recon_norm, gid_source, qr, xs_r, base_rep, valid_rep,
            pf_bits, int(k), prefilter is not None,
        ))

    def build_lut():
        @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
        def run(rotation, centers, pq_centers, codes, gid_tbl, q,
                xs, base, valid, bits, k: int, use_pf: bool):
            def body(rotation, centers, pq_centers, codes, gid_tbl, q,
                     xs, base, valid, bits):
                # slot table holds global ids, so _search_impl's ids are
                # global
                v, gid = _search_impl(
                    q, rotation, centers, pq_centers, codes[0],
                    _shard_filtered(gid_tbl[0], bits, pf_n, use_pf),
                    kk, n_probes, metric, per_cluster,
                )
                return finish(v, gid, q, xs, base, valid)

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(None, None), P(None, None),
                          P(None, None, None),
                          P(comms.axis, None, None, None),
                          P(comms.axis, None, None),
                          P(None, None), P(comms.axis, None), P(None),
                          P(None), P(None)),
                out_specs=(out_spec, out_spec), check_vma=False,
            )(rotation, centers, pq_centers, codes, gid_tbl, q, xs, base,
              valid, bits)

        return run

    run = _cached_wrapper(
        ("pq_lut", comms.mesh, comms.axis, mode, metric, int(k), kk,
         n_probes, refine, refine_merged, pf_n, per_cluster),
        build_lut,
    )
    return trim(run(
        index.rotation, index.centers, index.pq_centers, index.codes,
        index.slot_gids, qr, xs_r, base_rep, valid_rep, pf_bits, int(k),
        prefilter is not None,
    ))


def _build_distributed_resid(index: DistributedIvfFlat) -> None:
    """Lazy per-rank derived store for the distributed fused Pallas scan
    (the IVF-Flat analogue of _build_distributed_recon): lane-padded
    bf16 per-slot RESIDUALS v - center_l plus f32 norms, with pad slots
    exact-zero / gid -1 — same derivation as the single-chip
    _pad_store_to_lanes, computed on the sharded arrays (centers are
    replicated, so XLA keeps everything rank-local)."""
    from raft_tpu.ops.pq_list_scan import lane_padded

    base = int(index.list_data.shape[2])
    lpad = lane_padded(base)
    if index.resid_bf16 is not None and int(index.resid_bf16.shape[2]) == lpad:
        return
    ld = jnp.pad(index.list_data, ((0, 0), (0, 0), (0, lpad - base), (0, 0)))
    sg = jnp.pad(index.slot_gids, ((0, 0), (0, 0), (0, lpad - base)),
                 constant_values=-1)
    resid = ld.astype(jnp.float32) - jnp.asarray(index.centers)[None, :, None, :]
    resid = jnp.where((sg >= 0)[..., None], resid, 0.0)
    index.resid_bf16 = resid.astype(jnp.bfloat16)
    index.resid_norm = jnp.sum(resid ** 2, axis=3)
    index.slot_gids_pad = sg


def ivf_flat_search(index: DistributedIvfFlat, queries, k: int, n_probes: int = 20,
                    prefilter=None, query_mode: str = "auto",
                    engine: str = "auto"):
    """SPMD search: every rank scans its local lists for the same global
    probes; local top-k are merged on all ranks ("replicated") or routed
    to per-rank query blocks ("sharded"; see `_resolve_query_mode`).
    `engine`: "query" (query-major, tiny batches), "list" (list-major
    — each rank streams each probed list once; the serving engine), or
    "pallas" (the fused list-scan per rank over lane-padded bf16
    residual stores — near-exact, same bin-trim loss class as the
    single-chip engine); "auto" uses the tuned/duplication heuristic the
    single-chip search uses (a tuned "pallas" winner maps to "list" —
    explicit opt-in for the distributed fused engine until it is
    chip-measured distributed). `prefilter` (core.Bitset or boolean mask
    over the GLOBAL id space, `index.id_bound` ids; identical on every
    controller) excludes samples before selection on every rank."""
    from raft_tpu.neighbors.ivf_flat import (
        _search_impl, _search_impl_listmajor, _search_impl_listmajor_pallas,
    )

    comms = index.comms
    ac = comms.comms
    qh = jnp.asarray(queries, jnp.float32)
    metric = index.params.metric
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    n_probes = int(min(n_probes, index.params.n_lists))
    pf_bits, pf_n = _replicated_filter_bits(comms, prefilter, index.id_bound)
    if engine == "auto":
        from raft_tpu.neighbors.ivf_flat import resolve_auto_engine

        engine = resolve_auto_engine(qh.shape[0], n_probes,
                                     index.params.n_lists, pallas_ok=None)
    if engine not in ("query", "list", "pallas"):
        raise ValueError(f"unknown engine {engine!r} (distributed ivf_flat "
                         "supports 'query', 'list', 'pallas', 'auto')")
    mode = _resolve_query_mode(query_mode, comms, qh.shape[0], int(k))
    nq = qh.shape[0]
    if mode == "sharded":
        qh, nq = _pad_queries(qh, comms.get_size())
    merge = _merge_local_topk if mode == "replicated" else _merge_local_topk_scatter
    out_spec = P(None, None) if mode == "replicated" else P(comms.axis, None)
    q = comms.replicate(qh)

    if engine == "pallas":
        from raft_tpu.ops.pq_list_scan import _BINS, fits_pallas, lane_padded

        if int(k) > _BINS:
            raise ValueError(
                f"engine='pallas' caps per-list candidates at {_BINS}; k={k}"
            )
        d = int(index.list_data.shape[-1])
        lpad = lane_padded(int(index.list_data.shape[2]))
        # store_itemsize=2: the scanned store is the bf16 residual copy
        # (same gate as the single-chip _pallas_fits)
        if not fits_pallas(128, lpad, d, store_itemsize=2):
            raise ValueError(
                f"engine='pallas': padded list length {lpad} x dim {d} "
                "exceeds the kernel's VMEM envelope; use engine='list'"
            )
        _build_distributed_resid(index)
        interp = jax.default_backend() == "cpu"
        from raft_tpu.ops.pq_list_scan import fold_variant

        pfold = fold_variant()

        def build_pallas():
            @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
            def run_pallas(resid, rnorm, gid_tbl, centers, q, bits, k: int,
                           use_pf: bool):
                def body(resid, rnorm, gid_tbl, centers, q, bits):
                    v, gid = _search_impl_listmajor_pallas(
                        q, centers, resid[0], rnorm[0],
                        _shard_filtered(gid_tbl[0], bits, pf_n, use_pf),
                        k, n_probes, metric, interpret=interp, fold=pfold,
                    )
                    v = jnp.where(gid >= 0, v, worst)
                    return merge(ac, v, gid, k, select_min)

                return jax.shard_map(
                    body, mesh=comms.mesh,
                    in_specs=(P(comms.axis, None, None, None),
                              P(comms.axis, None, None),
                              P(comms.axis, None, None),
                              P(None, None), P(None, None), P(None)),
                    out_specs=(out_spec, out_spec), check_vma=False,
                )(resid, rnorm, gid_tbl, centers, q, bits)

            return run_pallas

        run_pallas = _cached_wrapper(
            ("flat_pallas", comms.mesh, comms.axis, mode, metric,
             n_probes, pf_n, interp, pfold),
            build_pallas,
        )
        v, gid = run_pallas(index.resid_bf16, index.resid_norm,
                            index.slot_gids_pad, index.centers, q, pf_bits,
                            int(k), prefilter is not None)
        return (v[:nq], gid[:nq]) if v.shape[0] != nq else (v, gid)

    if engine == "query":
        impl, cb = _search_impl, None
    else:
        from raft_tpu.core import tuned as _tuned
        from raft_tpu.neighbors.probe_invert import CHUNK_BLOCKS

        cb = int(_tuned.get_choice("listmajor_chunk_block", CHUNK_BLOCKS, 0))
        impl = functools.partial(_search_impl_listmajor, chunk_block=cb)

    def build_flat():
        @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
        def run(ld, gid_tbl, centers, q, bits, k: int, use_pf: bool):
            def body(ld, gid_tbl, centers, q, bits):
                # slot table holds global ids, so the impl's ids are
                # global
                v, gid = impl(
                    q, centers, ld[0],
                    _shard_filtered(gid_tbl[0], bits, pf_n, use_pf),
                    k, n_probes, metric,
                )
                v = jnp.where(gid >= 0, v, worst)
                return merge(ac, v, gid, k, select_min)

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None, None, None),
                          P(comms.axis, None, None),
                          P(None, None), P(None, None), P(None)),
                out_specs=(out_spec, out_spec), check_vma=False,
            )(ld, gid_tbl, centers, q, bits)

        return run

    run = _cached_wrapper(
        ("flat", comms.mesh, comms.axis, mode, metric, n_probes, pf_n,
         engine, cb),
        build_flat,
    )
    v, gid = run(index.list_data, index.slot_gids, index.centers, q, pf_bits,
                 int(k), prefilter is not None)
    return (v[:nq], gid[:nq]) if v.shape[0] != nq else (v, gid)
