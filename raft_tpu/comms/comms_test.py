"""Comms self-tests, callable from user code.

Reference parity: `raft::comms::test_collective_*` (comms/comms_test.hpp:1-171,
detail/test.hpp) exposed to Python via raft-dask's comms_utils.pyx:78-171
(`perform_test_comms_allreduce` etc.) and exercised in test_comms.py:45-317.
Each returns True iff the collective produced the mathematically expected
value on every rank.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import Comms, op_t


def _all_ranks_ok(comms: Comms, per_rank_fn) -> bool:
    """Run per_rank_fn(ax_comms) -> bool scalar per rank; AND-reduce."""
    ac = comms.comms

    def fn():
        ok = per_rank_fn(ac)
        return ac.allreduce(jnp.asarray(ok).astype(jnp.float32), op_t.SUM)

    n = comms.get_size()
    out = jax.shard_map(
        fn, mesh=comms.mesh, in_specs=(), out_specs=P(), check_vma=False
    )()
    return bool(np.asarray(out) == n)


def perform_test_comms_allreduce(comms: Comms) -> bool:
    def body(ac):
        v = jnp.ones((), jnp.float32)
        return ac.allreduce(v) == ac.get_size()

    return _all_ranks_ok(comms, body)


def perform_test_comms_bcast(comms: Comms, root: int = 0) -> bool:
    def body(ac):
        rank = ac.get_rank()
        v = jnp.where(rank == root, 42.0, 0.0)
        return ac.bcast(v, root=root) == 42.0

    return _all_ranks_ok(comms, body)


def perform_test_comms_reduce(comms: Comms, root: int = 0) -> bool:
    def body(ac):
        r = ac.reduce(jnp.ones((), jnp.float32), root=root)
        rank = ac.get_rank()
        expected = jnp.where(rank == root, float(comms.get_size()), 0.0)
        return r == expected

    return _all_ranks_ok(comms, body)


def perform_test_comms_allgather(comms: Comms) -> bool:
    def body(ac):
        rank = ac.get_rank()
        v = rank.astype(jnp.float32)[None]
        g = ac.allgather(v)  # (n, 1)
        want = jnp.arange(ac.get_size(), dtype=jnp.float32)[:, None]
        return jnp.all(g == want)

    return _all_ranks_ok(comms, body)


def perform_test_comms_gather(comms: Comms, root: int = 0) -> bool:
    def body(ac):
        rank = ac.get_rank()
        g = ac.gather(rank.astype(jnp.float32)[None], root=root)
        want = jnp.arange(ac.get_size(), dtype=jnp.float32)[:, None]
        ok_root = jnp.all(g == want)
        return jnp.where(rank == root, ok_root, True)

    return _all_ranks_ok(comms, body)


def perform_test_comms_reducescatter(comms: Comms) -> bool:
    def body(ac):
        n = ac.get_size()
        v = jnp.ones((n,), jnp.float32)
        r = ac.reducescatter(v)  # each rank gets its slice summed: n
        return jnp.all(r == n)

    return _all_ranks_ok(comms, body)


def perform_test_comms_send_recv(comms: Comms) -> bool:
    """Ring send/recv (test_comms.py send_recv analogue)."""
    def body(ac):
        rank = ac.get_rank()
        got = ac.shift(rank.astype(jnp.float32), offset=1)
        n = ac.get_size()
        want = (rank.astype(jnp.float32) - 1) % n
        return got == want

    return _all_ranks_ok(comms, body)


def perform_test_comms_device_multicast_sendrecv(comms: Comms) -> bool:
    n = comms.get_size()
    dests = [[(i + 1) % n, (i + 2) % n] for i in range(n)]

    def body(ac):
        rank = ac.get_rank().astype(jnp.float32)
        got = ac.device_multicast_sendrecv(rank, dests)
        want = ((rank - 1) % n) + ((rank - 2) % n)
        return got == want

    return _all_ranks_ok(comms, body)


def perform_test_comm_split(comms: Comms) -> bool:
    """comm_split into even/odd ranks (test_comms.py comm_split test)."""
    n = comms.get_size()
    if n % 2:
        return True
    colors = [r % 2 for r in range(n)]

    def body(ac):
        sub = ac.comm_split(colors)
        v = jnp.ones((), jnp.float32)
        return sub.allreduce(v) == sub.get_size()

    return _all_ranks_ok(comms, body)


def perform_test_comms_barrier(comms: Comms) -> bool:
    def body(ac):
        return ac.barrier() == ac.get_size()

    return _all_ranks_ok(comms, body)


ALL_TESTS = [
    perform_test_comms_allreduce,
    perform_test_comms_bcast,
    perform_test_comms_reduce,
    perform_test_comms_allgather,
    perform_test_comms_gather,
    perform_test_comms_reducescatter,
    perform_test_comms_send_recv,
    perform_test_comms_device_multicast_sendrecv,
    perform_test_comm_split,
    perform_test_comms_barrier,
]
