"""Distributed IVF-Flat / IVF-PQ searches: per-rank engines under
shard_map, refine, prefilters, and the replicated/sharded merges."""


import functools
import warnings

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.comms.comms import op_t
from raft_tpu.matrix.select_k import _select_k_impl
from raft_tpu.distance.distance_types import DistanceType
from raft_tpu.comms.mnmg_common import (
    _cached_wrapper, _local_layout, _mask_dead_rank, _pack_local,
    _pack_result, _pad_queries, _rank_layout, _ranks_by_proc,
    _replicated_filter_bits, _resolve_health, _shard_filtered, _shard_rows,
    rank_captured, wrapper_key,
)
from raft_tpu.comms.mnmg_merge import (
    _merge_local_topk, _merge_local_topk_scatter, _resolve_query_mode,
)
from raft_tpu.comms.mnmg_ivf_build import (
    DistributedIvfFlat, DistributedIvfPq,
)


def _build_distributed_recon(index: DistributedIvfPq,
                             pad_to_lanes: bool = False) -> None:
    """Per-rank int8 reconstruction stores for the list-major engine,
    decoded from the packed codes inside shard_map (lazily, idempotent —
    the distributed build_reconstruction). With `pad_to_lanes` the slot
    axis pads to the fused Pallas list-scan's 128-lane contract
    (recon_norm +inf, slot gids -1 on pad slots — masked exactly like
    in-list padding); once padded, the store stays padded (monotone,
    same contract as the single-chip build_reconstruction)."""
    base = int(index.codes.shape[2])
    have = int(index.recon8.shape[2]) if index.recon8 is not None else -1
    if have >= base:
        if pad_to_lanes:
            _pad_distributed_recon(index, base)
        return
    from raft_tpu.neighbors.ivf_pq import _decode_quantize

    comms = index.comms
    per_cluster = index.params.codebook_kind == _per_cluster_kind()

    @jax.jit
    def run(codes, pq_centers):
        def body(codes, pq_centers):
            r8, scale, rnorm = _decode_quantize(codes[0], pq_centers, per_cluster)
            return r8[None], scale, rnorm[None]

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None, None, None), P(None, None, None)),
            out_specs=(P(comms.axis, None, None, None), P(None),
                       P(comms.axis, None, None)), check_vma=False,
        )(codes, pq_centers)

    index.recon8, index.recon_scale, index.recon_norm = run(
        index.codes, index.pq_centers
    )
    index.slot_gids_pad = index.slot_gids
    if pad_to_lanes:
        _pad_distributed_recon(index, base)


def _pad_distributed_recon(index: DistributedIvfPq, base: int) -> None:
    """Pad the (sharded) recon store's slot axis to the Pallas lane
    contract; no-op when already wide enough."""
    from raft_tpu.ops.pq_list_scan import lane_padded

    lpad = lane_padded(base)
    extra = lpad - int(index.recon8.shape[2])
    if extra <= 0:
        return
    if index.slot_gids_pad is None:
        index.slot_gids_pad = index.slot_gids
    index.recon8 = jnp.pad(index.recon8, ((0, 0), (0, 0), (0, extra), (0, 0)))
    index.recon_norm = jnp.pad(index.recon_norm,
                               ((0, 0), (0, 0), (0, extra)),
                               constant_values=jnp.inf)
    index.slot_gids_pad = jnp.pad(index.slot_gids_pad,
                                  ((0, 0), (0, 0), (0, extra)),
                                  constant_values=-1)


def _per_cluster_kind():
    from raft_tpu.neighbors.ivf_pq import PER_CLUSTER

    return PER_CLUSTER


def _refine_layout(index, refine_dataset, allow_extended: bool = False):
    """Sharded original rows + per-rank (base, valid) for the distributed
    refine: rank j owns caller ids [base_j, base_j + valid_j), and its
    dataset shard row l holds caller id base_j + l — true for both the
    driver layout (contiguous global rows) and the *_local layout.

    The layout (including the device-sharded copy of the dataset) is
    cached on the index keyed by the dataset object's identity, so a
    serving loop passing the same array re-ships nothing. SINGLE-
    controller only: on a spanning mesh a per-process identity hit would
    let one process skip the layout collectives another still enters —
    a silent deadlock — so multi-controller calls always recompute
    (symmetric collectives every call). Release the pinned copy with
    index.clear_refine_cache()."""
    comms = index.comms
    cacheable = not comms.spans_processes()
    cache = getattr(index, "_refine_cache", None)
    if cacheable and cache is not None and cache[0] is refine_dataset:
        return cache[1], cache[2], cache[3]
    if getattr(index, "bridged", False):
        raise ValueError(
            "refine_dataset needs gids that index the dataset rows: "
            "bridged (distribute_index) layouts may carry arbitrary "
            "caller ids — refine on the single-chip index instead"
        )
    if getattr(index, "extended", False):
        # allow_extended = the post-merge refine topology, whose
        # ownership follows this layout's contiguous sharding rather
        # than the index's (now non-contiguous) list placement. It needs
        # the full-dataset layout: a *_local-extended partition's ids
        # are split between the original and extended id blocks, which
        # the per-partition layout cannot express.
        if not allow_extended or index.host_gids is None:
            raise ValueError(
                "refine on an extended index runs post-merge over the "
                "FULL dataset layout (driver-built indexes do this "
                "automatically); *_local-extended layouts are "
                "unsupported — rebuild to refine"
            )
    if index.host_gids is not None:  # driver build: the FULL host array
        x = np.asarray(refine_dataset, np.float32)
        if x.shape[0] != index.n:
            raise ValueError(
                f"refine_dataset has {x.shape[0]} rows, index holds {index.n}"
            )
        xs, n, per = _shard_rows(comms, x)
        r = comms.get_size()
        base = per * np.arange(r, dtype=np.int64)
        valid = np.clip(n - base, 0, per)
        if cacheable:
            index._refine_cache = (refine_dataset, xs, base, valid)
        return xs, base, valid
    # *_local build: THIS process's partition (collective)
    local = np.asarray(refine_dataset, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    if int(counts.sum()) != index.n:
        raise ValueError(
            f"refine_dataset partitions sum to {int(counts.sum())} rows, "
            f"index holds {index.n}"
        )
    xp, _ = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    base, valid = _rank_layout(comms, counts, per)
    if cacheable:
        index._refine_cache = (refine_dataset, xs, base, valid)
    return xs, base, valid


def _exact_scores(q, rows, metric):
    """Exact (nq, kk) scores of gathered candidate rows."""
    if metric == DistanceType.InnerProduct:
        return jnp.einsum("qd,qkd->qk", q, rows)
    diff = q[:, None, :] - rows
    exact = jnp.sum(diff * diff, axis=2)
    if metric == DistanceType.L2SqrtExpanded:
        exact = jnp.sqrt(jnp.maximum(exact, 0.0))
    return exact


def _refine_local(q, gid, xs, base, valid, rank, metric, worst):
    """Exact per-rank re-rank: every candidate a rank reports came from
    its own lists, so its original row is in the rank's dataset shard —
    the distributed form of neighbors/refine.cuh with no cross-rank
    gathers. PQ scores are discarded; gids alone drive the gather."""
    local = gid - base[rank]
    own = (gid >= 0) & (local >= 0) & (local < valid[rank])
    rows = xs[jnp.clip(local, 0, xs.shape[0] - 1)]  # (nq, kk, d)
    exact = _exact_scores(q, rows, metric)
    return jnp.where(own, exact, worst), jnp.where(own, gid, -1)


def _refine_merged(ac, q, mgid, xs, base, valid, rank, metric, worst, k,
                   select_min):
    """Post-merge exact re-rank (inside shard_map): candidate ownership
    follows the refine dataset's CONTIGUOUS sharding, not the index's
    list placement — so it refines layouts whose per-rank gid ownership
    is non-contiguous (extended indexes), which the pre-merge
    `_refine_local` cannot. Each gid has exactly one owner in the
    contiguous layout; owners contribute exact scores, everyone else the
    worst value, and one MIN/MAX allreduce of the (nq, kk) shortlist
    assembles the exact scores on every rank. -1 merge pads have no
    owner, stay at worst, and sort last with id -1."""
    local = mgid - base[rank]
    own = (mgid >= 0) & (local >= 0) & (local < valid[rank])
    rows = xs[jnp.clip(local, 0, xs.shape[0] - 1)]  # (nq, kk, d)
    exact = _exact_scores(q, rows, metric)
    contrib = jnp.where(own, exact, worst)
    combined = ac.allreduce(contrib, op_t.MIN if select_min else op_t.MAX)
    fv, fp = _select_k_impl(combined, min(k, combined.shape[1]), select_min)
    return fv, jnp.take_along_axis(mgid, fp, axis=1)

@rank_captured("mnmg.ivf_pq_search")
@obs.spanned("mnmg.ivf_pq_search")
def ivf_pq_search(index: DistributedIvfPq, queries, k: int, n_probes: int = 20,
                  engine: str = "auto", refine_dataset=None,
                  refine_mult: int = 4, prefilter=None,
                  query_mode: str = "auto", trim_engine: str = "approx",
                  score_dtype: str = "bf16", health=None,
                  adaptive: bool = False, recall_target=None,
                  budget_tau=None, min_probes: int = 1,
                  quantization: str = "auto"):
    """SPMD search: every rank scores its local lists for the same global
    probes; local top-k are merged on all ranks ("replicated") or routed
    to per-rank query blocks ("sharded" — R× less merge traffic for
    serving; see `_resolve_query_mode` for "auto"). Both modes return the
    full (nq, k) result as a global jax.Array; sharded output is laid out
    query-sharded across the mesh instead of replicated.

    `engine`: "recon8_list" (the list-major int8-reconstruction engine the
    single-chip flagship uses — each rank streams each probed list once),
    "lut" (query-major, for tiny batches), or "auto" (same duplication
    heuristic as the single-chip `search`). With engine="recon8_list",
    `trim_engine="pallas"` runs the bin-trimming fused list-scan per
    rank, `trim_engine="fused"` the EXACT fused scan+select trim
    (matrix/select_k list-scan dispatch; with score_dtype="int8" it is
    the dispatch layer's "fused_int8" int8-MXU strategy — ISSUE 11), and
    `score_dtype="int8"` scores with symmetric int8 queries — all
    mirroring the single-chip SearchParams options.

    `refine_dataset` enables the high-recall pipeline (neighbors/
    refine.cuh distributed): each rank takes a `refine_mult * k`
    shortlist from its PQ scores, re-ranks its OWN candidates exactly
    against the original vectors (a rank's candidates all come from its
    own rows — no cross-rank gathers), and the exact scores merge.
    Pass the full dataset for driver-built indexes, or this process's
    partition for *_local-built ones. EXTENDED driver-built indexes
    refine post-merge instead (`_refine_merged`: the global shortlist
    merges first, then owners in the dataset's contiguous sharding
    contribute exact scores through one MIN/MAX allreduce) — pass the
    full dataset including the extended rows; *_local-extended layouts
    cannot refine. This topology reduces across ranks per query, so an
    extended+refined search always returns the REPLICATED output layout
    — an explicit query_mode="sharded" request degrades to replicated
    with a warning.

    `prefilter` (core.Bitset or boolean mask over the GLOBAL id space,
    `index.id_bound` ids; identical on every controller) excludes
    samples before trim/selection on every rank — the slot tables hold
    global ids, so one replicated bitset serves all shards.

    `health` (resilience.RankHealth) enables degraded mode: unhealthy
    ranks' candidates are masked out of the merge (survivors' results
    are bit-identical to prefiltering the dead shard's rows away) and
    the return becomes a `DegradedSearchResult(values, ids, coverage)`
    with coverage = served shards / total. On an index with r-way
    replicas (`mnmg.replicate_index` / build `replication=`), unhealthy
    ranks with a surviving replica holder FAIL OVER instead: the
    holder's copy re-materializes the shard, results stay bit-identical
    to the all-healthy run at coverage 1.0, and the ranks appear in
    `DegradedSearchResult.repaired_ranks` — only failures past r-1
    degrade. Degraded masks are incompatible with the post-merge refine
    of extended indexes (exact scores there come from the refine
    dataset's contiguous owners, who may be dead).

    `quantization` selects the replicated merge's wire transport
    (comms/quantized): "off" is bit-identical to the exact merge,
    "int8"/"bf16" ship block-quantized candidate scores and re-rank
    survivors on exact psum-resolved values; the default "auto" stays
    exact until a chip bench banks a `comms_quant_mode` winner for this
    backend."""
    from raft_tpu.neighbors.ivf_pq import (
        _search_impl, _search_impl_recon8_listmajor, PER_CLUSTER,
    )
    from raft_tpu.comms.replication import failover_view

    # lossless failover first: with surviving replica holders the
    # patched view + effective mask make the rest of this function (and
    # its refine/extended checks) see repaired ranks as healthy
    index, health, repaired = failover_view(index, health)

    comms = index.comms
    ac = comms.comms
    from raft_tpu.comms import quantized

    # resolved before the wrapper caches below: the hashable config is
    # part of every cache key, so a tuned comms_quant_mode flip rebuilds
    # the traced program (cache-key completeness)
    qcfg = quantized.resolve(quantization)
    q = jnp.asarray(queries, jnp.float32)
    metric = index.params.metric
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    n_probes = int(min(n_probes, index.params.n_lists))
    per_cluster = index.params.codebook_kind == PER_CLUSTER
    # adaptive per-rank probe budgets: rotation/centers are replicated,
    # so ONE host-side plan is the every-rank plan (see ivf_flat_search;
    # bounds off distributed — radii are per-rank local state). Computed
    # on the UNPADDED queries so the accounting counts real rows only.
    from raft_tpu.neighbors import probe_budget

    ap = probe_budget.resolve(
        n_probes, adaptive=adaptive, recall_target=recall_target,
        budget_tau=budget_tau, min_probes=min_probes, early_term=False)
    keep = None
    scanned_mean = None
    if ap is not None:
        keep, scanned = probe_budget.probe_plan(
            q, index.centers, n_probes=n_probes,
            min_probes=ap.min_probes, k=int(k), metric=metric, tau=ap.tau,
            rotation=index.rotation)
        scanned_mean = probe_budget.account(
            "mnmg.ivf_pq", scanned, int(q.shape[0]), n_probes)
    # extended indexes refine POST-merge (ownership by the refine
    # dataset's contiguous sharding, see _refine_merged); that topology
    # reduces across ranks per query, so it needs replicated queries
    refine_merged = (refine_dataset is not None
                     and bool(getattr(index, "extended", False)))
    mode = _resolve_query_mode(query_mode, comms, q.shape[0], k)
    if refine_merged:
        if query_mode == "sharded":
            # an EXPLICIT sharded request changes the returned layout the
            # caller asked for — surface the degrade (silent fallback is
            # reserved for "auto"; ADVICE r3)
            warnings.warn(
                "query_mode='sharded' is incompatible with refined search "
                "on an extended index (post-merge refine reduces across "
                "ranks per query); returning the REPLICATED layout",
                stacklevel=2,
            )
        mode = "replicated"
    # health is controller-uniform by protocol: every controller raises
    # together (or none does) — no rank diverges past this point
    if refine_merged and health is not None and health.degraded:  # raftlint: disable=collective-divergence
        raise ValueError(
            "degraded-mode refine on an extended index is unsupported: "
            "post-merge exact scores come from the refine dataset's "
            "contiguous owners, and a dead owner cannot score its rows — "
            "search without refine_dataset, or rehydrate first"
        )
    live_rep, mode, coverage = _resolve_health(comms, health, query_mode, mode)
    nq = q.shape[0]
    if mode == "sharded":
        q, nq = _pad_queries(q, comms.get_size())
    merge = _merge_local_topk if mode == "replicated" else _merge_local_topk_scatter
    out_spec = P(None, None) if mode == "replicated" else P(comms.axis, None)

    if engine == "auto":
        if score_dtype == "int8" or trim_engine in ("pallas", "fused"):
            # an explicit int8 / pallas-trim / fused-trim request pins
            # the engine that honors it (same rule as the single-chip
            # search: numerics must not depend on batch size or tuned
            # state)
            engine = "recon8_list"
        else:
            from raft_tpu.core import tuned

            # same policy as ivf_pq._resolve_score_mode, restricted to
            # the two distributed engines: on TPU the resolution NEVER
            # lands on lut (its gather kernel-faults the device —
            # docs/perf.md device-fault section), even from a
            # CPU-rehearsal-fitted tuned key
            on_tpu = jax.default_backend() == "tpu"
            t = tuned.get("pq_auto_engine")
            if t in ("recon8_list", "lut") and not (t == "lut" and on_tpu):
                engine = t
            else:
                dup = q.shape[0] * n_probes / max(1, index.params.n_lists)
                engine = "recon8_list" if (dup >= 4.0 or on_tpu) else "lut"
    if engine not in ("recon8_list", "lut"):
        raise ValueError(f"unknown engine {engine!r}")
    if obs.enabled():
        # charged AFTER engine resolution: the list-major engine streams
        # every padded slot on every rank; lut touches the probed lists.
        # n_rows = total padded slots across the (R, n_lists, max_list)
        # code tables — pad slots are scored too.
        obs.span_cost(**obs.perf.cost_for(
            "mnmg.ivf_pq_search", nq=int(q.shape[0]), n_probes=n_probes,
            n_lists=int(index.params.n_lists),
            n_rows=int(index.codes.shape[0] * index.codes.shape[1]
                       * index.codes.shape[2]),
            dim=int(index.centers.shape[-1]),
            pq_dim=int(index.codes.shape[-1]), k=int(k), dtype=score_dtype,
            scanned_lists=(int(index.params.n_lists)
                           if engine == "recon8_list" and trim_engine != "fused"
                           else (scanned_mean if scanned_mean is not None
                                 else n_probes))))
    if engine == "lut":
        from raft_tpu.neighbors.ivf_pq import _check_lut_allowed

        _check_lut_allowed()  # explicit lut on TPU: same fence as single-chip

    qr = comms.replicate(q)
    adaptive_on = ap is not None
    if keep is not None and keep.shape[0] != q.shape[0]:
        # sharded-mode query padding: pad rows scan nothing
        keep = jnp.pad(keep, ((0, q.shape[0] - keep.shape[0]), (0, 0)),
                       constant_values=False)
    pv_rep = comms.replicate(
        keep if keep is not None else jnp.zeros((1, 1), bool))
    pf_bits, pf_n = _replicated_filter_bits(comms, prefilter, index.id_bound)
    refine = refine_dataset is not None
    if refine:
        xs_r, base_r, valid_r = _refine_layout(
            index, refine_dataset, allow_extended=refine_merged)
        base_rep = comms.replicate(np.asarray(base_r, np.int32))
        valid_rep = comms.replicate(np.asarray(valid_r, np.int32))
        # shortlist never narrower than k (a cap below k would shrink the
        # merged output width); inflation capped at 256 gathered rows
        kk = int(max(k, min(max(refine_mult, 1) * k, 256)))
    else:
        # zero-size placeholders keep one jitted signature per engine
        xs_r = comms.shard(
            jnp.zeros((comms.get_size(), 1), jnp.float32), axis=0
        ) if not comms.spans_processes() else comms.shard_from_local(
            np.zeros((len(_ranks_by_proc(comms.mesh).get(jax.process_index(), [])), 1),
                     np.float32), axis=0
        )
        base_rep = comms.replicate(np.zeros(comms.get_size(), np.int32))
        valid_rep = comms.replicate(np.zeros(comms.get_size(), np.int32))
        kk = int(k)

    def finish(v, gid, q, xs, base, valid, live):
        rank = ac.get_rank()
        if refine_merged:
            v = faults.corrupt_in_trace("mnmg.ivf_pq.scores", v, rank)
            v = jnp.where(gid >= 0, v, worst)
            # global shortlist kept as wide as the pre-merge path's total
            # exact re-rank depth (r ranks x kk each, under the same
            # 256-row gather cap) — merging down to kk first would drop
            # true neighbors PQ ranks 21st+ before exact scoring. Never
            # narrower than kk itself: kk >= k, and a sub-k shortlist
            # would shrink the (nq, k) output width.
            kk_merged = min(comms.get_size() * kk, max(256, kk))
            _, mgid = merge(ac, v, gid, kk_merged, select_min, quant=qcfg)
            return _refine_merged(ac, q, mgid, xs, base, valid,
                                  rank, metric, worst, k, select_min)
        if refine:
            v, gid = _refine_local(q, gid, xs, base, valid, rank, metric, worst)
        else:
            v = jnp.where(gid >= 0, v, worst)
        # corrupt AFTER the local refine: the site models the shard's
        # REPORTED scores, and the refine path discards the PQ scores
        # (gids alone drive its exact re-rank) — injecting earlier would
        # make the drill silently inert on refined searches
        v = faults.corrupt_in_trace("mnmg.ivf_pq.scores", v, rank)
        # degraded mode: an unhealthy rank's shard stops contributing
        v, gid = _mask_dead_rank(v, gid, live, rank, worst)
        return merge(ac, v, gid, k, select_min, quant=qcfg)

    def trim(out):
        return _pack_result(out[0], out[1], nq, coverage, repaired)

    if trim_engine not in ("approx", "pallas", "fused"):
        raise ValueError(f"unknown trim_engine {trim_engine!r}")
    for eng_req in ("pallas", "fused"):
        if trim_engine == eng_req and engine != "recon8_list":
            raise ValueError(
                f"trim_engine='{eng_req}' requires engine='recon8_list'"
            )
    if score_dtype not in ("bf16", "int8"):
        raise ValueError(f"unknown score_dtype {score_dtype!r}")
    if score_dtype == "int8" and engine != "recon8_list":
        raise ValueError("score_dtype='int8' requires engine='recon8_list'")
    int8_q = score_dtype == "int8"
    if engine == "recon8_list":
        use_pallas_trim = trim_engine == "pallas"
        use_fused_trim = trim_engine == "fused"
        fused_kb = None
        if use_pallas_trim:
            # the fused list-scan's shape contract, checked per rank
            # (max_list is global across ranks, so this is static)
            from raft_tpu.ops.pq_list_scan import (
                _BINS, fits_pallas, lane_padded,
            )

            if kk > _BINS:
                raise ValueError(
                    f"trim_engine='pallas' caps per-list candidates at "
                    f"{_BINS}; k={kk}"
                )
            # rotation is (rot_dim, dim); the scanned store axis is rot_dim
            lpad = lane_padded(int(index.codes.shape[2]))
            if not fits_pallas(128, lpad, int(index.rotation.shape[0])):
                raise ValueError(
                    f"trim_engine='pallas': list length {lpad} exceeds the "
                    "kernel's VMEM envelope; use trim_engine='approx'"
                )
            from raft_tpu.neighbors.ivf_pq import (
                _search_impl_recon8_listmajor_pallas,
            )
        if use_fused_trim:
            # the EXACT fused trim per rank (ISSUE 11): bf16 or — with
            # score_dtype="int8" — the int8 MXU path, both through the
            # matrix/select_k list-scan dispatch. Same envelope/kbuf
            # contract as the single-chip engine (the ONE shared
            # validation), checked per rank
            from raft_tpu.matrix.select_k import check_fused_list_request
            from raft_tpu.ops.pq_list_scan import lane_padded

            fused_kb = check_fused_list_request(
                "trim_engine='fused'",
                lane_padded(int(index.codes.shape[2])),
                int(index.rotation.shape[0]), int(kk), 1,
                getattr(index, "fused_kb", None), "trim_engine='approx'",
            )
            from raft_tpu.neighbors.ivf_pq import (
                _search_impl_recon8_listmajor_fused,
            )

            # monotonic candidate-buffer bookkeeping, like the flat
            # engine's _build_distributed_resid
            index.fused_kb = fused_kb
        _build_distributed_recon(
            index, pad_to_lanes=use_pallas_trim or use_fused_trim)
        # ALWAYS the padded view: _build_distributed_recon keeps
        # slot_gids_pad width-matched to recon8 (== slot_gids until a
        # pallas search pads the store in place — after which the approx
        # engine must see the same padded width or its score/slot
        # broadcast shapes diverge)
        gid_source = index.slot_gids_pad
        interp = jax.default_backend() == "cpu"
        from raft_tpu.ops.pq_list_scan import fold_variant

        pfold = fold_variant()
        # distributed list-major engines honor the same measured scoring
        # granularity as the single-chip search (a chip race that rejects
        # the superblock structure must flip the serving path too)
        from raft_tpu.core import tuned as _tuned
        from raft_tpu.neighbors.probe_invert import CHUNK_BLOCKS

        cb = int(_tuned.get_choice("listmajor_chunk_block", CHUNK_BLOCKS, 0))
        from raft_tpu.neighbors.probe_invert import resolve_setup_impls

        # resolved OUTSIDE the jitted closure (and in the wrapper cache
        # key below): a tuned flip mid-process must rebuild the wrapper
        setup_impls = resolve_setup_impls(int(index.params.n_lists))

        def build_list():
            @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
            def run_list(rotation, centers, recon8, scale, rnorm, gid_tbl,
                         q, xs, base, valid, bits, live, pv,
                         k: int, use_pf: bool):
                def body(rotation, centers, recon8, scale, rnorm, gid_tbl,
                         q, xs, base, valid, bits, live, pv):
                    srows = _shard_filtered(gid_tbl[0], bits, pf_n, use_pf)
                    pvk = pv if adaptive_on else None
                    if use_fused_trim:
                        v, gid = _search_impl_recon8_listmajor_fused(
                            q, rotation, centers, recon8[0], scale,
                            rnorm[0], srows, kk, n_probes, metric,
                            interpret=interp, int8_queries=int8_q,
                            kb=fused_kb, setup_impls=setup_impls,
                            pvalid=pvk,
                        )
                    elif use_pallas_trim:
                        v, gid = _search_impl_recon8_listmajor_pallas(
                            q, rotation, centers, recon8[0], scale,
                            rnorm[0], srows, kk, n_probes, metric,
                            interpret=interp, int8_queries=int8_q,
                            fold=pfold, setup_impls=setup_impls,
                            pvalid=pvk,
                        )
                    else:
                        v, gid = _search_impl_recon8_listmajor(
                            q, rotation, centers, recon8[0], scale,
                            rnorm[0], srows, kk, n_probes, metric,
                            chunk_block=cb, int8_queries=int8_q,
                            setup_impls=setup_impls,
                            pvalid=pvk,
                        )
                    return finish(v, gid, q, xs, base, valid, live)

                return jax.shard_map(
                    body, mesh=comms.mesh,
                    in_specs=(P(None, None), P(None, None),
                              P(comms.axis, None, None, None), P(None),
                              P(comms.axis, None, None),
                              P(comms.axis, None, None),
                              P(None, None), P(comms.axis, None), P(None),
                              P(None), P(None), P(None), P(None, None)),
                    out_specs=(out_spec, out_spec), check_vma=False,
                )(rotation, centers, recon8, scale, rnorm, gid_tbl, q, xs,
                  base, valid, bits, live, pv)

            return run_list

        run_list = _cached_wrapper(
            wrapper_key(
                "pq_recon8_list", comms, mode, metric,
                int(k), kk, n_probes, refine, refine_merged, pf_n, int8_q,
                use_pallas_trim, use_fused_trim, fused_kb, interp, pfold,
                cb, setup_impls, adaptive_on, qcfg),
            build_list,
        )
        return trim(run_list(
            index.rotation, index.centers, index.recon8, index.recon_scale,
            index.recon_norm, gid_source, qr, xs_r, base_rep, valid_rep,
            pf_bits, live_rep, pv_rep, int(k), prefilter is not None,
        ))

    def build_lut():
        @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
        def run(rotation, centers, pq_centers, codes, gid_tbl, q,
                xs, base, valid, bits, live, pv, k: int, use_pf: bool):
            def body(rotation, centers, pq_centers, codes, gid_tbl, q,
                     xs, base, valid, bits, live, pv):
                # slot table holds global ids, so _search_impl's ids are
                # global
                v, gid = _search_impl(
                    q, rotation, centers, pq_centers, codes[0],
                    _shard_filtered(gid_tbl[0], bits, pf_n, use_pf),
                    kk, n_probes, metric, per_cluster,
                    pvalid=pv if adaptive_on else None,
                )
                return finish(v, gid, q, xs, base, valid, live)

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(None, None), P(None, None),
                          P(None, None, None),
                          P(comms.axis, None, None, None),
                          P(comms.axis, None, None),
                          P(None, None), P(comms.axis, None), P(None),
                          P(None), P(None), P(None), P(None, None)),
                out_specs=(out_spec, out_spec), check_vma=False,
            )(rotation, centers, pq_centers, codes, gid_tbl, q, xs, base,
              valid, bits, live, pv)

        return run

    run = _cached_wrapper(
        wrapper_key(
            "pq_lut", comms, mode, metric, int(k), kk,
            n_probes, refine, refine_merged, pf_n, per_cluster, adaptive_on,
            qcfg),
        build_lut,
    )
    return trim(run(
        index.rotation, index.centers, index.pq_centers, index.codes,
        index.slot_gids, qr, xs_r, base_rep, valid_rep, pf_bits, live_rep,
        pv_rep, int(k), prefilter is not None,
    ))


def _build_distributed_resid(index: DistributedIvfFlat, k: int) -> None:
    """Lazy per-rank derived store for the distributed fused Pallas scan
    (the IVF-Flat analogue of _build_distributed_recon): lane-padded
    bf16 per-slot RESIDUALS v - center_l plus f32 norms, with pad slots
    exact-zero / gid -1 — same derivation as the single-chip
    _pad_store_to_lanes, computed on the sharded arrays (centers are
    replicated, so XLA keeps everything rank-local). Mirrors the
    single-chip candidate-buffer bookkeeping: `index.fused_kb` records
    the compiled width and grows monotonically when `k` outruns it
    (never a silent per-list truncation)."""
    from raft_tpu.ops.fused_scan import fused_kbuf
    from raft_tpu.ops.pq_list_scan import lane_padded

    base = int(index.list_data.shape[2])
    lpad = lane_padded(base)
    if index.resid_bf16 is None or int(index.resid_bf16.shape[2]) != lpad:
        ld = jnp.pad(index.list_data, ((0, 0), (0, 0), (0, lpad - base), (0, 0)))
        sg = jnp.pad(index.slot_gids, ((0, 0), (0, 0), (0, lpad - base)),
                     constant_values=-1)
        resid = ld.astype(jnp.float32) - jnp.asarray(index.centers)[None, :, None, :]
        resid = jnp.where((sg >= 0)[..., None], resid, 0.0)
        index.resid_bf16 = resid.astype(jnp.bfloat16)
        index.resid_norm = jnp.sum(resid ** 2, axis=3)
        index.slot_gids_pad = sg
    kb = fused_kbuf(int(k))
    if getattr(index, "fused_kb", None) is None or kb > index.fused_kb:
        index.fused_kb = kb


@rank_captured("mnmg.ivf_flat_search")
@obs.spanned("mnmg.ivf_flat_search")
def ivf_flat_search(index: DistributedIvfFlat, queries, k: int, n_probes: int = 20,
                    prefilter=None, query_mode: str = "auto",
                    engine: str = "auto", health=None,
                    adaptive: bool = False, recall_target=None,
                    budget_tau=None, min_probes: int = 1,
                    quantization: str = "auto"):
    """SPMD search: every rank scans its local lists for the same global
    probes; local top-k are merged on all ranks ("replicated") or routed
    to per-rank query blocks ("sharded"; see `_resolve_query_mode`).
    `engine`: "query" (query-major, tiny batches), "list" (list-major
    — each rank streams each probed list once; the serving engine), or
    "pallas" (the fused distance+select-k scan per rank over
    lane-padded bf16 residual stores — exact-within-probed-lists modulo
    bf16 rounding, like the single-chip fused engine); "auto" uses the
    tuned/duplication heuristic the
    single-chip search uses (a tuned "pallas" winner maps to "list" —
    explicit opt-in for the distributed fused engine until it is
    chip-measured distributed). `prefilter` (core.Bitset or boolean mask
    over the GLOBAL id space, `index.id_bound` ids; identical on every
    controller) excludes samples before selection on every rank.

    `health` (resilience.RankHealth) enables degraded mode: unhealthy
    ranks' candidates are masked out of the merge and the return becomes
    a `DegradedSearchResult(values, ids, coverage)`; on a replicated
    index surviving holders fail over losslessly (coverage stays 1.0,
    `repaired_ranks` reports them) — see `ivf_pq_search`, including the
    `quantization` merge-transport knob."""
    from raft_tpu.neighbors.ivf_flat import (
        _search_impl, _search_impl_listmajor, _search_impl_listmajor_pallas,
    )
    from raft_tpu.comms.replication import failover_view
    from raft_tpu.comms import quantized

    # lossless failover before anything reads the mask (see ivf_pq_search)
    index, health, repaired = failover_view(index, health)

    comms = index.comms
    ac = comms.comms
    qcfg = quantized.resolve(quantization)
    qh = jnp.asarray(queries, jnp.float32)
    metric = index.params.metric
    select_min = metric != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    n_probes = int(min(n_probes, index.params.n_lists))
    pf_bits, pf_n = _replicated_filter_bits(comms, prefilter, index.id_bound)
    if engine == "auto":
        from raft_tpu.neighbors.ivf_flat import resolve_auto_engine

        engine = resolve_auto_engine(qh.shape[0], n_probes,
                                     index.params.n_lists, pallas_ok=None)
    if engine not in ("query", "list", "pallas"):
        raise ValueError(f"unknown engine {engine!r} (distributed ivf_flat "
                         "supports 'query', 'list', 'pallas', 'auto')")
    # adaptive per-rank probe budgets (ROADMAP item 2): centers and
    # queries are REPLICATED, so the coarse geometry — and therefore the
    # keep mask — is identical on every rank; one host-side plan serves
    # the whole mesh as a replicated operand and the merge is unchanged.
    # Bounds stay off distributed (radii are per-rank local state).
    from raft_tpu.neighbors import probe_budget

    ap = probe_budget.resolve(
        n_probes, adaptive=adaptive, recall_target=recall_target,
        budget_tau=budget_tau, min_probes=min_probes, early_term=False)
    keep = None
    scanned_mean = None
    if ap is not None:
        keep, scanned = probe_budget.probe_plan(
            qh, index.centers, n_probes=n_probes,
            min_probes=ap.min_probes, k=int(k), metric=metric, tau=ap.tau)
        scanned_mean = probe_budget.account(
            "mnmg.ivf_flat", scanned, int(qh.shape[0]), n_probes)
    if obs.enabled():
        # charged AFTER engine resolution (list-major streams every
        # padded slot on every rank); n_rows = total padded slots of the
        # (R, n_lists, max_list) store. Adaptive budgets charge the
        # ACTUAL scanned mean on the probed-list engines.
        obs.span_cost(**obs.perf.cost_for(
            "mnmg.ivf_flat_search", nq=int(qh.shape[0]), n_probes=n_probes,
            n_lists=int(index.params.n_lists),
            n_rows=int(index.list_data.shape[0] * index.list_data.shape[1]
                       * index.list_data.shape[2]),
            dim=int(index.list_data.shape[-1]), k=int(k),
            scanned_lists=(int(index.params.n_lists) if engine == "list"
                           else (scanned_mean if scanned_mean is not None
                                 else n_probes))))
    mode = _resolve_query_mode(query_mode, comms, qh.shape[0], int(k))
    live_rep, mode, coverage = _resolve_health(comms, health, query_mode, mode)
    nq = qh.shape[0]
    if mode == "sharded":
        qh, nq = _pad_queries(qh, comms.get_size())
    merge = _merge_local_topk if mode == "replicated" else _merge_local_topk_scatter
    out_spec = P(None, None) if mode == "replicated" else P(comms.axis, None)
    q = comms.replicate(qh)
    adaptive_on = ap is not None
    if keep is not None and keep.shape[0] != qh.shape[0]:
        # sharded-mode query padding: pad rows scan nothing
        keep = jnp.pad(keep, ((0, qh.shape[0] - keep.shape[0]), (0, 0)),
                       constant_values=False)
    # the keep-mask operand is ALWAYS passed (a (1, 1) dummy on the
    # fixed path, unused and DCE'd) so each engine keeps one body/spec
    pv_rep = comms.replicate(
        keep if keep is not None else jnp.zeros((1, 1), bool))
    from raft_tpu.neighbors.probe_invert import resolve_setup_impls

    # resolved OUTSIDE the jitted closures and keyed in the wrapper cache
    # (a tuned flip mid-process must rebuild the wrapper); n_lists engages
    # the _COUNT_MAX_LISTS guard, engine="flat" keys the qs impl to the
    # flat engines' f32-HIGHEST precision contract (ADVICE r5)
    setup_impls = resolve_setup_impls(int(index.params.n_lists), engine="flat")

    def pack(v, gid):
        return _pack_result(v, gid, nq, coverage, repaired)

    if engine == "pallas":
        from raft_tpu.ops.fused_scan import (
            FUSED_MAX_K, fits_fused_list, fused_kbuf,
        )
        from raft_tpu.ops.pq_list_scan import lane_padded

        if int(k) > FUSED_MAX_K:
            raise ValueError(
                f"engine='pallas' caps per-list candidates at "
                f"{FUSED_MAX_K}; k={k}"
            )
        d = int(index.list_data.shape[-1])
        lpad = lane_padded(int(index.list_data.shape[2]))
        # store_itemsize=2: the scanned store is the bf16 residual copy;
        # gated at the width the kernel will RUN with — the recorded
        # fused_kb when a previous larger-k search already grew it (same
        # rule as the single-chip _pallas_fits)
        kb_run = max(fused_kbuf(int(k)),
                     getattr(index, "fused_kb", None) or 0)
        if not fits_fused_list(128, lpad, d, int(k), store_itemsize=2,
                               kbuf=kb_run):
            raise ValueError(
                f"engine='pallas': padded list length {lpad} x dim {d} "
                "exceeds the kernel's VMEM envelope; use engine='list'"
            )
        _build_distributed_resid(index, int(k))
        interp = jax.default_backend() == "cpu"
        kb = int(index.fused_kb)

        def build_pallas():
            @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
            def run_pallas(resid, rnorm, gid_tbl, centers, q, bits, live,
                           pv, k: int, use_pf: bool):
                def body(resid, rnorm, gid_tbl, centers, q, bits, live, pv):
                    v, gid = _search_impl_listmajor_pallas(
                        q, centers, resid[0], rnorm[0],
                        _shard_filtered(gid_tbl[0], bits, pf_n, use_pf),
                        k, n_probes, metric, kb=kb, interpret=interp,
                        setup_impls=setup_impls,
                        fault_key=faults.trace_key(),
                        pvalid=pv if adaptive_on else None,
                    )
                    rank = ac.get_rank()
                    v = faults.corrupt_in_trace("mnmg.ivf_flat.scores", v, rank)
                    v = jnp.where(gid >= 0, v, worst)
                    v, gid = _mask_dead_rank(v, gid, live, rank, worst)
                    return merge(ac, v, gid, k, select_min, quant=qcfg)

                return jax.shard_map(
                    body, mesh=comms.mesh,
                    in_specs=(P(comms.axis, None, None, None),
                              P(comms.axis, None, None),
                              P(comms.axis, None, None),
                              P(None, None), P(None, None), P(None),
                              P(None), P(None, None)),
                    out_specs=(out_spec, out_spec), check_vma=False,
                )(resid, rnorm, gid_tbl, centers, q, bits, live, pv)

            return run_pallas

        run_pallas = _cached_wrapper(
            wrapper_key(
                "flat_pallas", comms, mode, metric,
                n_probes, pf_n, interp, kb, setup_impls, adaptive_on, qcfg),
            build_pallas,
        )
        v, gid = run_pallas(index.resid_bf16, index.resid_norm,
                            index.slot_gids_pad, index.centers, q, pf_bits,
                            live_rep, pv_rep, int(k), prefilter is not None)
        return pack(v, gid)

    if engine == "query":
        impl, cb = _search_impl, None
    else:
        from raft_tpu.core import tuned as _tuned
        from raft_tpu.neighbors.probe_invert import CHUNK_BLOCKS

        cb = int(_tuned.get_choice("listmajor_chunk_block", CHUNK_BLOCKS, 0))
        # setup_impls forwarded (ADVICE r5): without it the tuned
        # invert/qs flips were in the cache key but never reached the
        # traced program — the wrapper rebuilt, then traced the default
        impl = functools.partial(_search_impl_listmajor, chunk_block=cb,
                                 setup_impls=setup_impls)

    def build_flat():
        @functools.partial(jax.jit, static_argnames=("k", "use_pf"))
        def run(ld, gid_tbl, centers, q, bits, live, pv, k: int, use_pf: bool):
            def body(ld, gid_tbl, centers, q, bits, live, pv):
                # slot table holds global ids, so the impl's ids are
                # global
                v, gid = impl(
                    q, centers, ld[0],
                    _shard_filtered(gid_tbl[0], bits, pf_n, use_pf),
                    k, n_probes, metric,
                    pvalid=pv if adaptive_on else None,
                )
                rank = ac.get_rank()
                v = faults.corrupt_in_trace("mnmg.ivf_flat.scores", v, rank)
                v = jnp.where(gid >= 0, v, worst)
                v, gid = _mask_dead_rank(v, gid, live, rank, worst)
                return merge(ac, v, gid, k, select_min, quant=qcfg)

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None, None, None),
                          P(comms.axis, None, None),
                          P(None, None), P(None, None), P(None), P(None),
                          P(None, None)),
                out_specs=(out_spec, out_spec), check_vma=False,
            )(ld, gid_tbl, centers, q, bits, live, pv)

        return run

    run = _cached_wrapper(
        wrapper_key(
            "flat", comms, mode, metric, n_probes, pf_n,
            engine, cb, setup_impls, adaptive_on, qcfg),
        build_flat,
    )
    v, gid = run(index.list_data, index.slot_gids, index.centers, q, pf_bits,
                 live_rep, pv_rep, int(k), prefilter is not None)
    return pack(v, gid)
