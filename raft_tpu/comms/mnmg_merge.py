"""Top-k merge schedules + query-mode (merge-topology) resolution for
distributed searches: packed single-collective planes, allgather vs
log-depth butterfly tournament, sharded all_to_all merge."""


import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.comms.comms import Comms, AxisComms
from raft_tpu.matrix.select_k import _select_k_impl


def _pack_vi(v, ids):
    """One (nq, 2*kk) f32 plane carrying scores + bit-cast int32 ids, so a
    merge transports BOTH tensors in a SINGLE collective — same bytes,
    half the collective launches (launch latency dominates merge cost at
    serving batch sizes). Transport-safe: collectives move bytes; no FP
    arithmetic ever touches the id lanes (bit patterns may read as
    NaN/denormal f32 but are only ever bit-cast back)."""
    return jnp.concatenate(
        [v.astype(jnp.float32),
         lax.bitcast_convert_type(ids.astype(jnp.int32), jnp.float32)],
        axis=-1)


def _merge_local_topk(ac: AxisComms, v, ids, k: int, select_min: bool,
                      quant=None):
    """Merge per-rank local top-k candidates into a global top-k on every
    rank (the knn_merge_parts pattern, neighbors/detail/knn_merge_parts.cuh).
    `ids` must already be global (invalid entries masked to the worst
    value in `v` by the caller). Call inside shard_map.

    Power-of-two full-axis comms ride the log-depth butterfly tournament
    (`_merge_local_topk_tournament`): exchanged volume O(nq·k·log R) and
    select width 2k per round, vs the allgather's O(nq·kk·R) receive and
    one R·kk-wide select — the ICI-friendly schedule at pod widths.
    Non-power-of-two and split comms take the allgather path: one packed
    (nq, 2*kk) collective, interleave rank-major -> row-major, re-select.

    `quant` (a resolved `quantized.QuantConfig`, or None for the exact
    schedules) routes full-axis merges through the quantized candidate
    exchange: block-quantized scores travel, survivors re-rank on exact
    psum-resolved values (comms/quantized.exchange_candidates). Split
    comms stay exact — the exchange's implicit rank-major positions
    assume the full axis. Callers must fold `quant` into their cached
    wrapper keys (it is hashable for exactly that purpose)."""
    if quant is not None and ac.groups is None and ac.size > 1:
        from raft_tpu.comms import quantized

        return quantized.exchange_candidates(ac, v, ids, k, select_min, quant)
    if (ac.groups is None and ac.size > 1
            and (ac.size & (ac.size - 1)) == 0
            and _replicated_merge_schedule() == "tournament"):
        return _merge_local_topk_tournament(ac, v, ids, k, select_min)
    return _merge_local_topk_allgather(ac, v, ids, k, select_min)


def _replicated_merge_schedule() -> str:
    """Which replicated-merge schedule to run (both are bit-exact, so
    this is a pure engine choice). The cost model is BACKEND-dependent:
    on TPU ICI, exchanged volume and collective launches dominate and
    the log-depth tournament's O(nq·k·log R) wins at pod widths; on the
    CPU mesh, collectives are memcpys and the tournament's extra select
    rounds measured ~2x SLOWER than one flat allgather select
    (bench_comms merge race, world=8). Default: tournament on TPU,
    allgather elsewhere. Tuned key `mnmg_replicated_merge_schedule`
    (written by the on-chip bench_comms race) overrides — but only on
    the backend it was measured on (`merge_schedule_measured_on` hint):
    a chip-written winner must not flip the CPU mesh, and vice versa."""
    from raft_tpu.core import tuned

    t = tuned.get("mnmg_replicated_merge_schedule")
    measured_on = tuned.hints().get("merge_schedule_measured_on")
    if t in ("tournament", "allgather") and measured_on == jax.default_backend():
        return t
    from raft_tpu.core.config import is_tpu_backend

    return "tournament" if is_tpu_backend() else "allgather"


def _merge_local_topk_allgather(ac: AxisComms, v, ids, k: int,
                                select_min: bool):
    """Flat merge: one packed allgather, rank-major interleave, one wide
    select. The fallback schedule (and the tournament's bit-exactness
    oracle in tests)."""
    kk = v.shape[-1]
    g = ac.allgather(_pack_vi(v, ids)[None], axis=0)  # (R, nq, 2*kk)
    r_ = g.shape[0]
    cat = jnp.moveaxis(g.reshape(r_, -1, 2 * kk), 0, 1)  # (nq, R, 2*kk)
    cat_v = cat[..., :kk].reshape(-1, r_ * kk)
    cat_i = lax.bitcast_convert_type(cat[..., kk:], jnp.int32).reshape(-1, r_ * kk)
    mv, mp = _select_k_impl(cat_v, min(k, r_ * kk), select_min)
    return mv, jnp.take_along_axis(cat_i, mp, axis=1)


def _merge_local_topk_tournament(ac: AxisComms, v, ids, k: int,
                                 select_min: bool):
    """Butterfly (recursive-halving) merge: log2(R) ppermute rounds, each
    exchanging this rank's current candidate set with its XOR-partner and
    re-selecting top-min(k, 2w). Every rank converges to the identical
    global top-k (the replicated contract) with O(nq·k·log R) traffic.

    Bit-compatible with the allgather merge: candidates carry their
    rank-major global position, interior rounds restore position order
    after each select, and the stable top_k then breaks value ties by
    position exactly like one flat rank-major select would. A candidate
    trimmed early had >= k better-or-tied-with-lower-pos candidates in
    its own subset, so the flat merge drops it too. Each round moves one
    packed (.., 3w) plane (scores + bit-cast ids + bit-cast positions) —
    one collective per round."""
    r_ = ac.size
    kk = v.shape[-1]
    me = lax.axis_index(ac.axis)
    pos0 = me * kk + jnp.arange(kk, dtype=jnp.int32)
    cur_v = v.astype(jnp.float32)
    cur_i = ids.astype(jnp.int32)
    cur_p = jnp.broadcast_to(pos0, v.shape).astype(jnp.int32)
    d = 1
    while d < r_:
        w = cur_v.shape[-1]
        packed = jnp.concatenate(
            [cur_v,
             lax.bitcast_convert_type(cur_i, jnp.float32),
             lax.bitcast_convert_type(cur_p, jnp.float32)], axis=-1)
        other = lax.ppermute(packed, ac.axis,
                             [(i, i ^ d) for i in range(r_)])
        ov = other[..., :w]
        oi = lax.bitcast_convert_type(other[..., w:2 * w], jnp.int32)
        op = lax.bitcast_convert_type(other[..., 2 * w:], jnp.int32)
        lo_first = (me & d) == 0  # keep global position order in the cat
        cat_v = jnp.where(lo_first, jnp.concatenate([cur_v, ov], -1),
                          jnp.concatenate([ov, cur_v], -1))
        cat_i = jnp.where(lo_first, jnp.concatenate([cur_i, oi], -1),
                          jnp.concatenate([oi, cur_i], -1))
        cat_p = jnp.where(lo_first, jnp.concatenate([cur_p, op], -1),
                          jnp.concatenate([op, cur_p], -1))
        w2 = min(k, 2 * w)
        mv, mp = _select_k_impl(cat_v, w2, select_min)
        mi = jnp.take_along_axis(cat_i, mp, axis=-1)
        mpos = jnp.take_along_axis(cat_p, mp, axis=-1)
        d *= 2
        if d < r_:
            # interior round: back to position order so the next round's
            # stable select tie-breaks like the flat merge; the final
            # round returns best-first (the output contract)
            order = jnp.argsort(mpos, axis=-1)
            mv = jnp.take_along_axis(mv, order, axis=-1)
            mi = jnp.take_along_axis(mi, order, axis=-1)
            mpos = jnp.take_along_axis(mpos, order, axis=-1)
        cur_v, cur_i, cur_p = mv, mi, mpos
    return cur_v, cur_i


def _merge_local_topk_scatter(ac: AxisComms, v, ids, k: int, select_min: bool,
                              quant=None):
    """Query-sharded merge (the high-QPS serving topology): instead of
    allgathering every rank's (nq, kk) candidates onto every rank
    (volume R·nq·kk received per rank), ONE all_to_all of the packed
    scores+ids plane routes each query block's candidates to its owning
    rank only (volume ~nq·kk per rank, an R× reduction), which re-selects
    locally. Returns this rank's (nq/R, k') block; stitch globally with
    out_specs P(axis). nq must be divisible by the comm size (callers
    pad). Call inside shard_map on the full (unsplit) comm.

    `quant` is accepted for signature parity with `_merge_local_topk`
    but IGNORED: the all_to_all already cuts received volume R× below
    the replicated merge, and quantizing the routed plane is future
    work (drivers pass one merge closure for both topologies)."""
    kk = v.shape[-1]
    r_ = ac.get_size()
    t = lax.all_to_all(_pack_vi(v, ids), ac.axis, split_axis=0,
                       concat_axis=0, tiled=True)
    nq_blk = v.shape[0] // r_
    cat = jnp.moveaxis(t.reshape(r_, nq_blk, 2 * kk), 0, 1)  # (nq_blk, R, 2*kk)
    cat_v = cat[..., :kk].reshape(nq_blk, r_ * kk)
    cat_i = lax.bitcast_convert_type(cat[..., kk:], jnp.int32).reshape(nq_blk, r_ * kk)
    mv, mp = _select_k_impl(cat_v, min(k, r_ * kk), select_min)
    return mv, jnp.take_along_axis(cat_i, mp, axis=1)


def _resolve_query_mode(query_mode: str, comms: Comms, nq: int, k: int) -> str:
    """Pick the merge topology. "replicated" allgather-merges on every
    rank (full results everywhere — what the driver pattern and
    multi-controller `np.asarray` readers expect); "sharded" all_to_alls
    candidates so each rank finalizes only its own query block (R× less
    merge traffic — the serving topology).

    "auto" is volume-aware: merge volume is nq×k×world, and the recorded
    race surface (MERGE_RACE_RESULTS.json) shows the winner flips with k,
    not nq alone — at nq=2048 sharded wins at k=10 and loses at k=100.
    So the flip requires BOTH an absolute batch size (tuned key
    `mnmg_query_sharded_min_nq`) and enough queries per returned neighbor
    (`mnmg_query_sharded_min_nq_per_k`: nq >= k * ratio) so the sharded
    path's per-query routing overhead amortizes. Both keys are measured
    by the race grid in bench/bench_mnmg_merge.py (--apply derives them
    from the surface); the defaults bracket the recorded CPU flip points
    until a TPU race lands. Stays replicated on process-spanning meshes
    where every controller must read the full result."""
    if query_mode in ("replicated", "sharded"):
        return query_mode
    if query_mode != "auto":
        raise ValueError(f"unknown query_mode {query_mode!r}")
    if comms.spans_processes():
        return "replicated"
    from raft_tpu.core import tuned

    min_nq = int(tuned.get("mnmg_query_sharded_min_nq", 4096))
    per_k = float(tuned.get("mnmg_query_sharded_min_nq_per_k", 64))
    return "sharded" if (nq >= min_nq and nq >= k * per_k) else "replicated"
