"""Shared MNMG plumbing: sharding layouts, host mirrors, prefilter
bit-packing, the serving-path jit wrapper cache (split out of the
round-1..4 single-file mnmg.py; VERDICT r4 #9), and the per-rank obs
capture hook the distributed trace merge reads."""


import functools
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu import obs
from raft_tpu.comms.comms import Comms
from raft_tpu.distance.distance_types import DistanceType

#: env var naming a directory: when set (and obs is enabled), every MNMG
#: driver entry point serializes this controller's span/event capture to
#: `<dir>/obs_rank<NNN>.json` on the way out — the per-rank files
#: `python -m raft_tpu.obs.report --merge` aligns into one distributed
#: timeline. Multi-controller SPMD gives one file per process; the
#: single-controller 8-virtual-device mesh gives rank 0's view.
RANK_SNAPSHOT_ENV = "RAFT_TPU_OBS_RANK_DIR"


def rank_captured(label: str):
    """Decorator form of `maybe_save_rank_snapshot` for the MNMG driver
    entry points: after the wrapped driver returns (and its `@obs.spanned`
    span has closed, so the span event is in the capture), serialize this
    controller's obs state to the per-rank file. Stack it OUTSIDE
    `@obs.spanned`. The first positional argument must be a Comms session
    or carry one as `.comms` (every driver does)."""
    def deco(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            out = f(*args, **kwargs)
            if obs.enabled():
                # resolve the session from the first argument however it
                # was passed (positionally, or by keyword as `comms` /
                # `index`); a Comms session itself also HAS a .comms
                # (its AxisComms view) — the isinstance check must win
                first = (args[0] if args
                         else kwargs.get("comms", kwargs.get("index")))
                comms = (first if isinstance(first, Comms)
                         else getattr(first, "comms", None))
                if isinstance(comms, Comms):
                    maybe_save_rank_snapshot(comms, label)
            return out

        return wrapper

    return deco


def maybe_save_rank_snapshot(comms: Comms, label: str):
    """Env-gated per-rank obs capture (see RANK_SNAPSHOT_ENV). Returns
    the path written, or None when the gate is off. Never raises — a
    full disk must not fail the search that just completed."""
    out_dir = os.environ.get(RANK_SNAPSHOT_ENV, "").strip()
    if not out_dir or not obs.enabled():
        return None
    try:
        rank = int(jax.process_index())
        n_proc = int(jax.process_count())
        # single-controller meshes still record the device-axis world so
        # the merged report's "world" header matches the SPMD program
        world = n_proc if n_proc > 1 else comms.get_size()
        path = os.path.join(out_dir, f"obs_rank{rank:03d}.json")
        obs.save_snapshot(path, rank=rank, world=world, label=label)
        return path
    except Exception:
        return None


def _metric_name(metric) -> str:
    """Coarse-trainer metric for an ANN index metric (shared by every
    distributed build so driver and *_local paths can't diverge)."""
    return "inner_product" if metric == DistanceType.InnerProduct else "sqeuclidean"


def _pq_geometry(params, d: int):
    """(pq_dim, pq_len, rot_dim) for a dataset dim — one derivation for
    the driver and *_local PQ builds."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    pq_dim = params.pq_dim or ivf_pq_mod._auto_pq_dim(d)
    pq_len = -(-d // pq_dim)
    return pq_dim, pq_len, pq_dim * pq_len


@functools.lru_cache(maxsize=8)
def _rotate_fn(mesh, axis):
    """One compiled sharded-rotation program per mesh (a @ R.T)."""

    @jax.jit
    def run(a, R):
        def body(a, R):
            return a @ R.T

        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=P(axis, None), check_vma=False,
        )(a, R)

    return run


def _codebook_cap(params, n_lists: int) -> int:
    """Residual-sample cap for codebook EM (parity with the single-chip
    build: EM only needs enough rows per codebook entry)."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod

    nb = 1 << params.pq_bits
    cap = max(65536, 64 * nb)
    if params.codebook_kind == ivf_pq_mod.PER_CLUSTER:
        cap = max(cap, 256 * n_lists)
    return cap


def _train_codebooks(params, key, residuals, cb_labels, n_lists: int,
                     pq_dim: int, pq_len: int):
    """Codebook EM on a residual sample — the one implementation both
    distributed builds call, so cap/iteration/kind changes can't
    diverge. Routed through the shared quantizer layer (same jitted
    trainers the single-chip build uses — bit-identical)."""
    from raft_tpu.neighbors.quantizer import PqQuantizer

    quant = PqQuantizer(
        codebook_kind=params.codebook_kind, pq_bits=params.pq_bits,
        pq_dim=pq_dim, pq_len=pq_len, n_lists=n_lists,
    )
    return quant.train(key, residuals, cb_labels).pq_centers


def _ranks_by_proc(mesh) -> dict:
    """process_index -> sorted mesh-rank positions. The *_local layout's
    correctness rests on every helper using THIS one ordering."""
    out: dict = {}
    for j, d in enumerate(mesh.devices.flat):
        out.setdefault(d.process_index, []).append(j)
    return {p: sorted(v) for p, v in out.items()}


def _shard_rows(comms: Comms, x: np.ndarray):
    """Pad rows to a multiple of n_ranks and shard; returns (sharded, n, wpr)."""
    n = x.shape[0]
    r = comms.get_size()
    per = -(-n // r)
    pad = per * r - n
    xp = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)]) if pad else x
    return comms.shard(xp, axis=0), n, per


def _valid_weights(n: int, per: int, r: int) -> np.ndarray:
    w = np.zeros(per * r, np.float32)
    w[:n] = 1.0
    return w

def _pad_queries(q, world: int):
    """Pad nq up to a multiple of the comm size (sharded merge splits the
    query axis evenly); callers slice the result back to nq rows."""
    nq = q.shape[0]
    pad = (-nq) % world
    if pad:
        q = jnp.concatenate([q, jnp.zeros((pad, q.shape[1]), q.dtype)])
    return q, nq

def _local_layout(comms: Comms, n_local: int):
    """Collective: allgather per-process local row counts and derive the
    uniform per-rank shard size. Returns (counts (nproc,), per, lranks)
    where every process pads its rows to lranks * per.

    The count gather is job-global (process_allgather), so the mesh must
    span every process of the job — a sub-mesh would deadlock or count
    rows that are not in the mesh's arrays."""
    nproc = jax.process_count()
    pi = jax.process_index()
    mesh_procs = {d.process_index for d in comms.mesh.devices.flat}
    if nproc > 1 and mesh_procs != set(range(nproc)):
        raise ValueError(
            "the *_local collectives need a mesh spanning every process of "
            f"the job (mesh covers {sorted(mesh_procs)} of {nproc})"
        )
    lranks = sum(1 for d in comms.mesh.devices.flat if d.process_index == pi)
    if nproc == 1:
        counts = np.asarray([n_local], np.int64)
    else:
        from jax.experimental import multihost_utils

        counts = np.asarray(
            multihost_utils.process_allgather(jnp.asarray([n_local]), tiled=True),
            np.int64,
        )
    per = max(1, -(-int(counts.max()) // lranks))
    return counts, per, lranks


def _valid_global_positions(comms: Comms, counts: np.ndarray, per: int) -> np.ndarray:
    """Global row positions of every VALID row in the padded sharded
    layout. Mesh device order decides where each process's rows land
    (make_array_from_process_local_data fills a process's shards in
    global-index order), so this walks the mesh rather than assuming
    process-major contiguous blocks — ICI-optimized meshes interleave."""
    ranks_by_proc = _ranks_by_proc(comms.mesh)
    parts = []
    for p, cnt in enumerate(np.asarray(counts, np.int64)):
        rp = np.asarray(ranks_by_proc.get(p, []), np.int64)
        li = np.arange(int(cnt), dtype=np.int64)
        parts.append(rp[li // per] * per + (li % per))
    return np.concatenate(parts) if parts else np.zeros((0,), np.int64)


def _pack_local(local: np.ndarray, per: int, lranks: int):
    """Pad this process's rows to its lranks * per block; returns
    (padded rows, validity weights)."""
    block = lranks * per
    pad = block - local.shape[0]
    xp = (
        np.concatenate([local, np.zeros((pad,) + local.shape[1:], local.dtype)])
        if pad
        else local
    )
    wl = np.zeros(block, np.float32)
    wl[: local.shape[0]] = 1.0
    return xp, wl


@functools.lru_cache(maxsize=8)
def _gather_fn(mesh):
    # one compilation per mesh: index is an argument, not a baked constant,
    # so every restart/subsample reuses the executable
    return jax.jit(
        lambda a, idx: a[idx], out_shardings=NamedSharding(mesh, P())
    )


def _gather_replicated(comms: Comms, xs, positions: np.ndarray) -> np.ndarray:
    """Gather `positions` rows of a (possibly process-spanning) sharded
    array, replicated, and return them as host numpy — the collective
    subsample gather used for initialization."""
    out = _gather_fn(comms.mesh)(xs, jnp.asarray(positions, jnp.int32))
    return np.asarray(out.addressable_shards[0].data)

def _distributed_id_bound(index) -> int:
    """One past the largest gid of a Distributed* index. n for normal
    builds (gids are 0..n-1); for bridged indexes the gids are caller
    ids, so read the actual max (host mirror when present, one device
    reduce otherwise)."""
    if not getattr(index, "bridged", False):
        return int(index.n)
    if index.host_gids is not None:
        hg = np.asarray(index.host_gids)
        return int(hg.max()) + 1 if hg.size else 0
    return int(jnp.max(index.slot_gids)) + 1


def _pack_mask_words(mask_padded: np.ndarray) -> np.ndarray:
    """(R, per) bool -> (R, W) uint32 per-rank bitset rows. Each row is
    padded to whole 32-bit words, so packing the flattened mask through
    Bitset.from_mask yields exactly the per-row word layout the
    shard-local `Bitset(bits[0], per)` rebuild expects — ONE source of
    truth for the bit layout."""
    from raft_tpu.core.bitset import Bitset

    R, per = mask_padded.shape
    W = (per + 31) // 32
    pad = W * 32 - per
    mp = np.pad(mask_padded, ((0, 0), (0, pad))) if pad else mask_padded
    return np.asarray(Bitset.from_mask(mp.reshape(-1)).bits).reshape(R, W)


def _pad_global_mask(mask: np.ndarray, rank_base, valid_counts,
                     per: int) -> np.ndarray:
    """Scatter a global keep-mask into the padded (R, per) shard layout
    (pad rows stay False; they are masked by n_valid anyway)."""
    R = len(rank_base)
    out = np.zeros((R, per), bool)
    for j in range(R):
        v, b = int(valid_counts[j]), int(rank_base[j])
        if v:
            out[j, :v] = mask[b : b + v]
    return out


def _knn_prefilter_words(prefilter, n: int, rank_base, valid_counts,
                         per: int):
    """Coerce a knn prefilter (global ids 0..n-1) into per-rank packed
    bitset rows, or None. Mask inputs stay on host (no pack/unpack round
    trip); Bitset inputs unpack once."""
    if prefilter is None:
        return None
    from raft_tpu.core.bitset import Bitset

    if isinstance(prefilter, Bitset):
        if prefilter.n != n:
            raise ValueError(
                f"prefilter covers {prefilter.n} ids but the index has {n}"
            )
        mask = np.asarray(prefilter.to_mask())
    else:
        mask = np.asarray(prefilter)
        if mask.dtype != np.bool_ or mask.ndim != 1:
            raise ValueError(
                "prefilter must be a Bitset or a 1-D boolean mask, got "
                f"{mask.dtype} ndim={mask.ndim}"
            )
        if mask.shape[0] != n:
            raise ValueError(
                f"prefilter mask has {mask.shape[0]} entries but the index has {n}"
            )
    return _pack_mask_words(_pad_global_mask(mask, rank_base, valid_counts, per))


# Per-process cache of the jitted SPMD serving wrappers. The search
# entry points build their shard_map programs inside the function body
# (the closures need per-call statics), so without this cache EVERY
# serving call re-created the jitted wrapper and re-traced the whole
# program — measured ~8.5 s/call on the 8-device CPU mesh for a
# distributed IVF-PQ search whose compute is milliseconds. The key MUST
# cover every non-array closure input that shapes the traced program;
# array shapes/dtypes are keyed by jit's own cache on the persistent
# wrapper. Bounded defensively (distinct mode/engine/geometry
# combinations are few in practice).
_JIT_WRAPPER_CACHE: dict = {}


def wrapper_key(tag, comms, *parts):
    """The ONE construction of a serving-wrapper cache key: the site tag,
    the mesh geometry (mesh + named axis — two sessions on different
    meshes must never share a compiled program), then every non-array
    closure input that shapes the traced program. Every `_cached_wrapper`
    caller routes through here so the geometry prefix cannot drift per
    site; `tools/raftlint`'s ``cache-key-completeness`` rule resolves
    this helper and proves each site's trace-shaping closure inputs
    actually reach the key (the PR-1/PR-4/PR-12 stale-program class,
    caught at lint time)."""
    return (tag, comms.mesh, comms.axis) + parts


def _cached_wrapper(key, build):
    from raft_tpu.core import faults

    # an installed FaultPlan changes the traced program (injection sites
    # in comms/MNMG bodies), so the plan fingerprint is part of EVERY
    # wrapper key: installing/clearing chaos can never serve a stale
    # trace (None when no plan is active — the common case)
    key = (key, faults.trace_key())
    f = _JIT_WRAPPER_CACHE.pop(key, None)
    if f is None:
        while len(_JIT_WRAPPER_CACHE) >= 64:
            # evict one LRU entry (dict preserves insertion order and the
            # pop/re-insert above refreshes recency) — clearing wholesale
            # would drop every HOT wrapper whenever a long-lived serving
            # process accumulates 64 parameter combinations
            _JIT_WRAPPER_CACHE.pop(next(iter(_JIT_WRAPPER_CACHE)))
        f = build()
    _JIT_WRAPPER_CACHE[key] = f
    return f

def _rank_valid_counts(comms: Comms, counts: np.ndarray, per: int) -> np.ndarray:
    """Per-RANK valid row counts (mesh-rank order) for the *_local padded
    layout: each process's valid rows are a prefix of its mesh-ordered
    shard blocks."""
    return _rank_layout(comms, counts, per)[1]


def _rank_layout(comms: Comms, counts: np.ndarray, per: int):
    """Per-RANK (caller-id base, valid row count) for the *_local padded
    layout — the ONE walk of the (process, local-rank, mesh-rank)
    mapping, so knn_local's ids and the IVF builds' gids cannot
    diverge. Returns (rank_base (r,), valid_counts (r,))."""
    r = comms.get_size()
    base = np.zeros(r, np.int64)
    valid = np.zeros(r, np.int64)
    ranks_by_proc = _ranks_by_proc(comms.mesh)
    counts = np.asarray(counts, np.int64)
    for p, cnt in enumerate(counts):
        off = int(counts[:p].sum())
        for l, j in enumerate(ranks_by_proc.get(p, [])):
            base[j] = off + l * per
            valid[j] = int(np.clip(cnt - l * per, 0, per))
    return base, valid


def _local_shard_rows_host(arr) -> np.ndarray:
    """This process's addressable shards of a row-sharded array,
    concatenated in global-index order — its padded local block."""
    shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards])

# replicated all-ones live masks, one per mesh geometry: the healthy
# path (health=None) is every serving call, and re-running device_put on
# a fresh ones array per query batch is a pointless host->device
# round-trip (degraded masks change per probe, so only the healthy
# constant caches)
_ONES_MASK_CACHE: dict = {}


def _healthy_mask_rep(comms: Comms):
    key = (comms.mesh, comms.axis)
    m = _ONES_MASK_CACHE.get(key)
    if m is None:
        while len(_ONES_MASK_CACHE) >= 8:
            _ONES_MASK_CACHE.pop(next(iter(_ONES_MASK_CACHE)))
        m = comms.replicate(np.ones(comms.get_size(), np.float32))
        _ONES_MASK_CACHE[key] = m
    return m


def _resolve_health(comms: Comms, health, query_mode: str, mode: str):
    """Degraded-mode plumbing shared by every distributed search: coerce
    an optional `resilience.RankHealth` into (replicated (R,) f32 live
    mask, final query mode, coverage-or-None). With unhealthy ranks the
    merge topology is forced to "replicated" — the sharded all_to_all
    routes each query block to ONE owning rank, and a dead owner would
    drop its block entirely rather than degrade it (an explicit
    "sharded" request surfaces the degrade with a warning, mirroring the
    refine-on-extended precedent)."""
    import warnings

    r = comms.get_size()
    if health is None:
        return _healthy_mask_rep(comms), mode, None
    if health.world != r:
        raise ValueError(
            f"health mask covers {health.world} ranks, mesh has {r}"
        )
    if health.degraded and mode == "sharded":
        if query_mode == "sharded":
            warnings.warn(
                "query_mode='sharded' routes each query block to one "
                "owning rank, which degraded mode cannot mask; returning "
                "the REPLICATED layout",
                stacklevel=3,
            )
        mode = "replicated"
    return comms.replicate(health.live_f32()), mode, health.coverage()


def _pack_result(v, gid, nq: int, coverage, repaired_ranks=()):
    """The ONE degraded-result return shape: trim query padding back to
    nq rows, then plain `(v, gid)` without a health mask or a
    `DegradedSearchResult(v, gid, coverage, repaired_ranks)` with one —
    shared by every distributed search so the contract cannot drift per
    entry point. `repaired_ranks` lists unhealthy ranks served
    losslessly by replica failover (comms/replication.py); their shards
    count as covered."""
    from raft_tpu.comms.resilience import DegradedSearchResult

    if v.shape[0] != nq:
        v, gid = v[:nq], gid[:nq]
    if coverage is None:
        return v, gid
    return DegradedSearchResult(v, gid, coverage, tuple(repaired_ranks))


def _mask_dead_rank(v, gid, live, rank, worst):
    """Inside shard_map: blank an unhealthy rank's local candidates
    (worst score, id -1) so the merge sees exactly what a prefilter
    excluding its rows would produce — survivors' results are
    bit-identical to a mesh that never had the rank."""
    alive = live[rank] > 0
    return jnp.where(alive, v, worst), jnp.where(alive, gid, -1)


def _replicated_filter_bits(comms: Comms, prefilter, id_bound: int):
    """Coerce a distributed-search prefilter into (replicated packed
    bits, bit count). Without a filter, a 1-word placeholder keeps one
    jitted signature (the use_pf static flag skips it)."""
    if prefilter is None:
        return comms.replicate(np.zeros(1, np.uint32)), 1
    from raft_tpu.core.bitset import as_bitset

    bs = as_bitset(prefilter, id_bound)
    return comms.replicate(np.asarray(bs.bits)), bs.n


def _shard_filtered(gid_tbl, bits, n: int, use_pf: bool):
    """Filtered view of a shard-local gid table (global ids; -1 pad) —
    inside shard_map, so plain ops on the local block."""
    if not use_pf:
        return gid_tbl
    from raft_tpu.core.bitset import Bitset, filter_slot_table

    return filter_slot_table(gid_tbl, None, Bitset(bits, n))
