"""Comms: the TPU-native communicator.

Reference parity: `raft::comms::comms_t` (core/comms.hpp:123-242) — virtual
interface with allreduce/bcast/reduce/allgather(v)/gather(v)/reducescatter/
device_send/recv/sendrecv/barrier/comm_split, implemented by NCCL+UCX
(comms/detail/std_comms.hpp) and MPI (comms/detail/mpi_comms.hpp) backends,
injected into the handle (core/resource/comms.hpp).

TPU design (per survey §2.8): ranks are positions along a named axis of a
`jax.sharding.Mesh`; collectives are `jax.lax.{psum,pmax,pmin,all_gather,
psum_scatter,ppermute}` issued INSIDE `shard_map`-mapped functions and ride
ICI (intra-pod) / DCN (cross-pod) — XLA inserts and schedules the transfers,
replacing NCCL stream-ordered calls. `comm_split` maps to static
`axis_index_groups`, not a new communicator handle. Host-side UCX p2p has no
analogue; `device_sendrecv` maps to `ppermute`.

Two layers:
  - `AxisComms`: rank-view used inside shard_map'ped code (the comms_t
    methods). Stateless; safe to close over.
  - `Comms`: the session object (raft-dask `Comms`, common/comms.py:37) —
    owns/validates the mesh, builds AxisComms, runs self-tests, and offers
    `run()` to launch an SPMD function over the mesh (the `client.run`
    moment of raft-dask).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core import faults
from raft_tpu import obs


# Instrumented AxisComms entry points account (calls, payload bytes)
# per collective into the obs registry at TRACE time — XLA owns
# execution, so trace-time op counts are the deterministic number (see
# raft_tpu/obs docstring). Delegating methods (reduce -> allreduce,
# gather(v)/allgatherv -> allgather, barrier -> allreduce) count at each
# layer they pass through, so "barrier.calls" and the allreduce it rides
# both appear.


def _resolve_quant(quantization):
    """Normalize a collective's `quantization=` argument without touching
    the default path: None/"off" return None WITHOUT importing the codec
    module, so the exact path's import graph — and its traced jaxpr —
    is byte-identical to the pre-quantization library. Everything else
    defers to `comms.quantized.resolve` (tuned "auto" resolution,
    explicit modes, QuantConfig passthrough)."""
    if quantization is None or quantization == "off":
        return None
    from raft_tpu.comms import quantized

    return quantized.resolve(quantization)


class op_t(enum.Enum):
    """Reduction ops (core/comms.hpp op_t)."""

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"


class datatype_t(enum.Enum):
    """Kept for API parity (core/comms.hpp datatype_t); jax dtypes rule."""

    FLOAT32 = "float32"
    FLOAT64 = "float64"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"


@dataclasses.dataclass(frozen=True)
class AxisComms:
    """comms_t rank view over one mesh axis. Use inside shard_map'ped fns.

    `groups` (optional) restricts collectives to static rank groups — the
    comm_split analogue (axis_index_groups).
    """

    axis: str
    size: int
    groups: Optional[tuple] = None

    # -- topology ------------------------------------------------------
    def get_size(self):
        """Rank count. Plain int, except after an unequal comm_split where
        the size differs per rank: then a traced per-rank int32 scalar
        (usable inside the SPMD program, not as a Python int)."""
        if self.groups is not None:
            sizes = [len(g) for g in self.groups]
            if len(set(sizes)) == 1:
                return sizes[0]
            return jnp.asarray(np.asarray(sizes, np.int32))[self._group_id()]
        return self.size

    def _max_group_size(self) -> int:
        return max(len(g) for g in self.groups)

    def _wire_world(self) -> int:
        """World size the obs wire model should assume: a comm_split
        communicator moves data only within its groups, so charging the
        full axis size would overstate modeled wire traffic (worst-case
        group size covers uneven splits)."""
        return self._max_group_size() if self.groups is not None else self.size

    def get_rank(self):
        idx = lax.axis_index(self.axis)
        if self.groups is None:
            return idx
        # rank within the group = position of idx in its group (groups may
        # be ragged after an unequal comm_split)
        flat_rank = np.zeros((self.size,), np.int32)
        for g in self.groups:
            for pos, r in enumerate(g):
                flat_rank[r] = pos
        return jnp.asarray(flat_rank)[idx]

    # -- collectives ---------------------------------------------------
    def _group_id(self):
        """Static rank->group-id lookup, indexed by the traced axis index."""
        gid = np.zeros((self.size,), np.int32)
        for g_i, g in enumerate(self.groups):
            for r in g:
                gid[r] = g_i
        return jnp.asarray(gid)[lax.axis_index(self.axis)]

    def _grouped_combine(self, x, combine):
        """Exact-PROD grouped fallback: all_gather the full axis, statically
        combine each group's slice, dynamically select this rank's group
        result. O(world) memory — only the integer/small PROD path (which
        needs an exact product, and jax has no product collective) still
        uses it; every other grouped collective rides `_group_planes`."""
        g = lax.all_gather(x, self.axis, axis=0)  # (size, ...)
        per_group = jnp.stack([combine(g[jnp.asarray(grp)]) for grp in self.groups])
        return per_group[self._group_id()]

    def _group_planes(self, x, identity):
        """(G, ...) stack: plane g holds x on members of group g and the
        reduction identity elsewhere. One full-axis psum/pmin/pmax of this
        stack computes EVERY group's reduction at once — O(G) memory and
        collective volume instead of the O(world) all_gather (shard_map
        lacks axis_index_groups, so grouped reductions are emulated)."""
        onehot = jnp.arange(len(self.groups)) == self._group_id()
        shape = (len(self.groups),) + (1,) * x.ndim
        return jnp.where(onehot.reshape(shape), x[None], identity)

    @staticmethod
    def _reduce_identity(dtype, op: op_t):
        """Neutral element of `op` in `dtype` (non-members contribute it)."""
        if op in (op_t.SUM,):
            return jnp.zeros((), dtype)
        if op == op_t.PROD:
            return jnp.ones((), dtype)
        if dtype == jnp.bool_:
            return jnp.asarray(op == op_t.MIN, jnp.bool_)
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(jnp.inf if op == op_t.MIN else -jnp.inf, dtype)
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if op == op_t.MIN else info.min, dtype)

    @staticmethod
    def _prod_split(x):
        """(3, ...) planes whose per-plane SUM recombines into a product:
        zero count (exact), negative count (exact), log-magnitude (fp
        rounding only). Stays in x's dtype so f64 keeps f64 precision."""
        return jnp.stack([
            (x == 0).astype(x.dtype),
            (x < 0).astype(x.dtype),
            jnp.log(jnp.where(x == 0, 1.0, jnp.abs(x))),
        ])

    @staticmethod
    def _prod_recombine(planes, dtype):
        zeros, neg, logmag = planes
        mag = jnp.exp(logmag)
        signed = jnp.where(neg % 2 == 1, -mag, mag)
        return jnp.where(zeros > 0, jnp.zeros_like(signed), signed).astype(dtype)

    def _allreduce_prod(self, x):
        exact = x.size <= 4096 or not jnp.issubdtype(x.dtype, jnp.floating)
        if self.groups is None:
            if exact:
                # exact path (needed for ints: float32 log-space rounds
                # off-by-one near 2^20): gather the axis, then product
                return jnp.prod(lax.all_gather(x, self.axis, axis=0), axis=0)
            # O(1)-memory float path: zero/negative counts handled exactly
            # (float32 counts, exact up to 2^24 ranks), magnitude in log
            # space; one fused psum of all three planes instead of three
            # collective rounds
            return self._prod_recombine(lax.psum(self._prod_split(x), self.axis),
                                        x.dtype)
        if exact:
            return self._grouped_combine(x, lambda v: jnp.prod(v, axis=0))
        # grouped float PROD: the three sum-planes group-mask with identity
        # 0 (zero zeros, zero negatives, log(1)), so one psum of a
        # (G, 3, ...) stack reduces every group at once
        planes = lax.psum(self._group_planes(self._prod_split(x), 0), self.axis)
        return self._prod_recombine(planes[self._group_id()], x.dtype)

    _REDUCE_PRIM = {op_t.SUM: lax.psum, op_t.MAX: lax.pmax, op_t.MIN: lax.pmin}

    def _ring_perm(self):
        """Static (src, dst) pairs rotating each value one step forward
        within its OWN group (groups are disjoint, so one permutation
        encodes every group's ring at once)."""
        perm = []
        for grp in self.groups:
            for i, r in enumerate(grp):
                perm.append((r, grp[(i + 1) % len(grp)]))
        return perm

    def _grouped_reduce_ring(self, x, op: op_t):
        """Grouped allreduce as an intra-group rotation ring: step k
        ppermutes the ORIGINAL values one slot forward within each group
        and ranks accept the first (own_size - 1) arrivals, so after
        max_group_size - 1 steps every rank holds its group's reduction.
        Per-rank volume is (s_max - 1) x payload vs the masked-planes
        psum's ~2G x payload — the win grows with the number of groups
        (the world=64 -> 32 pairs worst case: 1 step vs ~64 payloads).
        Ragged groups work because rotation never crosses a group
        boundary: arrival k+1 at a rank in a group of size s is a
        distinct member's value iff k + 1 < s, exactly the accept gate."""
        combine = {op_t.SUM: jnp.add, op_t.MIN: jnp.minimum,
                   op_t.MAX: jnp.maximum}[op]
        sizes = np.zeros((self.size,), np.int32)
        for g in self.groups:
            for r in g:
                sizes[r] = len(g)
        s_own = jnp.asarray(sizes)[lax.axis_index(self.axis)]
        perm = self._ring_perm()
        acc = x
        y = x
        for k in range(self._max_group_size() - 1):
            y = lax.ppermute(y, self.axis, perm)
            acc = jnp.where(k + 1 < s_own, combine(acc, y), acc)
        return acc

    def _grouped_schedule(self) -> str:
        """ring | planes for grouped SUM/MIN/MAX, by the volume model:
        ring sends (s_max - 1) x payload per rank, the planes psum ~2G x
        payload — ring unless (s_max - 1) > c * G. Chip latency terms
        move the crossover constant c (default 2.0), so the measured
        race calibrates it via tuned key `grouped_reduce_crossover`
        rather than pinning one global winner (no single winner can
        represent a shape-dependent dispatch: 32 pairs on world=64 wants
        ring's one hop, 2 half-world groups want one fused psum).
        `grouped_reduce_schedule` = "ring" | "planes" remains as a blunt
        manual override."""
        from raft_tpu.core import tuned

        key = tuned.get("grouped_reduce_schedule")
        if key in ("ring", "planes"):
            return key
        try:
            c = float(tuned.get("grouped_reduce_crossover", 2.0))
        except (TypeError, ValueError):
            c = 2.0
        g = len(self.groups)
        return "ring" if self._max_group_size() - 1 <= c * g else "planes"

    def _inject(self, site: str, x, identity):
        """Chaos hook (core.faults): with an installed FaultPlan, drop
        this rank's contribution to the identity and/or NaN-corrupt its
        payload at the named site. Without a plan the trace is untouched
        (the `active_for` gate), so healthy programs stay byte-identical.
        Cached SPMD wrappers key on `faults.trace_key()` (via
        `mnmg_common._cached_wrapper`), so plans can't serve stale
        traces."""
        if not faults.active_for(site):
            return x
        r = lax.axis_index(self.axis)
        x = faults.drop_contribution(site, x, r, identity)
        return faults.corrupt_in_trace(site, x, r)

    def allreduce(self, x, op: op_t = op_t.SUM, quantization=None):
        qcfg = _resolve_quant(quantization)
        if qcfg is not None:
            from raft_tpu.comms import quantized

            return quantized.qallreduce(self, x, op, qcfg)
        x = jnp.asarray(x)
        obs.collective("allreduce", x, axis=self.axis, world=self._wire_world())
        x = self._inject("comms.allreduce", x, self._reduce_identity(x.dtype, op))
        return self._allreduce_raw(x, op)

    def _allreduce_raw(self, x, op: op_t):
        """Allreduce dispatch alone — no obs accounting, no fault
        injection (the callers own both). The quantized transports reuse
        this so their cast/int8 payloads ride the exact schedules."""
        if op == op_t.PROD:
            return self._allreduce_prod(x)
        if op not in self._REDUCE_PRIM:
            raise ValueError(op)
        prim = self._REDUCE_PRIM[op]
        if self.groups is None:
            return prim(x, self.axis)
        if self._grouped_schedule() == "ring":
            return self._grouped_reduce_ring(x, op)
        planes = self._group_planes(x, self._reduce_identity(x.dtype, op))
        return prim(planes, self.axis)[self._group_id()]

    def _grouped_bcast_ring(self, contrib, root: int):
        """Grouped bcast on the intra-group ring: rotate the root-masked
        contribution; the rank at ring-distance k from its group root
        accepts arrival k (static gate). Same (s_max - 1) x payload
        volume as the grouped-reduce ring vs the planes psum's ~2G x."""
        dist = np.zeros((self.size,), np.int32)
        for g in self.groups:
            s = len(g)
            for pos, r in enumerate(g):
                dist[r] = (pos - root) % s
        d_own = jnp.asarray(dist)[lax.axis_index(self.axis)]
        perm = self._ring_perm()
        acc = contrib  # distance 0 == the root's own value
        y = contrib
        for k in range(self._max_group_size() - 1):
            y = lax.ppermute(y, self.axis, perm)
            acc = jnp.where(d_own == k + 1, y, acc)
        return acc

    def bcast(self, x, root: int = 0, quantization=None):
        """Broadcast root's value to all ranks (root is the group-local rank
        when split) — a single psum of the root-masked value; on a split
        comm, G root-masked planes or the intra-group ring (same schedule
        dispatch as the grouped reductions)."""
        qcfg = _resolve_quant(quantization)
        if qcfg is not None:
            from raft_tpu.comms import quantized

            return quantized.qbcast(self, x, qcfg, root=root)
        xa = jnp.asarray(x)
        obs.collective("bcast", xa, axis=self.axis, world=self._wire_world())
        return self._bcast_raw(xa, root)

    def _bcast_raw(self, xa, root: int):
        """Bcast dispatch alone (root masking + schedules) — no obs
        accounting; the quantized transport reuses it for int8/bf16
        payloads (a sum with one non-zero contribution is exact in any
        dtype, so the masked psum never overflows)."""
        contrib = jnp.where(self.get_rank() == root, xa, jnp.zeros_like(xa))
        if self.groups is None:
            return lax.psum(contrib, self.axis)
        if self._grouped_schedule() == "ring":
            return self._grouped_bcast_ring(contrib, root)
        planes = lax.psum(self._group_planes(contrib, 0), self.axis)
        return planes[self._group_id()]

    def reduce(self, x, root: int = 0, op: op_t = op_t.SUM):
        """All ranks participate; non-roots receive zeros (functional SPMD —
        every rank gets a value; callers use root's)."""
        red = self.allreduce(x, op)
        keep = (self.get_rank() == root)
        return jnp.where(keep, red, jnp.zeros_like(red))

    def _grouped_allgather_ring(self, x):
        """(m, ...) group-slot stack via the intra-group ring: arrival k
        is the value of the member k ring-steps behind, placed at that
        member's group-local position; slots past this group's size stay
        zero (the pad contract). (s_max - 1) x payload per rank vs the
        full-axis all_gather's (world - 1) x — a G x volume cut."""
        m = self._max_group_size()
        sizes = np.zeros((self.size,), np.int32)
        for g in self.groups:
            for r in g:
                sizes[r] = len(g)
        s_own = jnp.asarray(sizes)[lax.axis_index(self.axis)]
        pos = self.get_rank()
        perm = self._ring_perm()
        out = jnp.zeros((m,) + x.shape, x.dtype)
        out = lax.dynamic_update_index_in_dim(out, x, pos, 0)
        y = x
        for k in range(1, m):
            y = lax.ppermute(y, self.axis, perm)
            src = (pos - k) % s_own
            upd = lax.dynamic_update_index_in_dim(out, y, src, 0)
            # wrapped arrivals (k >= own size) would clobber real slots
            out = jnp.where(k < s_own, upd, out)
        return out

    def allgather(self, x, axis: int = 0, tiled: bool = False,
                  quantization=None):
        qcfg = _resolve_quant(quantization)
        if qcfg is not None:
            from raft_tpu.comms import quantized

            return quantized.qallgather(self, x, qcfg, axis=axis, tiled=tiled)
        x = jnp.asarray(x)
        obs.collective("allgather", x, axis=self.axis, world=self._wire_world())
        x = self._inject("comms.allgather", x, jnp.zeros((), x.dtype))
        return self._allgather_raw(x, axis, tiled)

    def _allgather_raw(self, x, axis: int, tiled: bool):
        """Allgather dispatch alone — no obs accounting, no fault
        injection (callers own both); reused by the quantized transport
        for the int8 payload + scale-sidecar planes."""
        if self.groups is not None:
            if self._grouped_schedule() == "ring":
                out = self._grouped_allgather_ring(x)
            else:
                g = lax.all_gather(x, self.axis, axis=0)
                m = self._max_group_size()
                slots = []
                for grp in self.groups:
                    s = g[jnp.asarray(grp)]  # (len(grp), ...)
                    if len(grp) < m:  # unequal split: zero-pad group slots
                        pad = [(0, m - len(grp))] + [(0, 0)] * (s.ndim - 1)
                        s = jnp.pad(s, pad)
                    slots.append(s)
                out = jnp.stack(slots)[self._group_id()]  # (m, ...)
            if tiled:
                out = jnp.concatenate([out[i] for i in range(out.shape[0])], axis=axis)
            elif axis != 0:
                out = jnp.moveaxis(out, 0, axis)
            return out
        return lax.all_gather(x, self.axis, axis=axis, tiled=tiled)

    def allgatherv(self, x, counts: Sequence[int], axis: int = 0):
        """Variable-size allgather (core/comms.hpp:171 allgatherv).

        SPMD/XLA requires identical static shapes on every rank, so the
        convention is: every rank passes x with the same static extent
        `x.shape[axis] >= max(counts)`, of which only the leading
        `counts[rank]` slices are valid. The invalid tail is zeroed here so
        padding slots are deterministic, then ranks are stacked on a new
        leading dim: result[(r, ..., i, ...)] is valid for i < counts[r].
        On an unequal split comm, `counts` has length max-group-size and is
        indexed by group-local rank; result slots r >= this group's size
        (traced `get_size()`) are zero padding, not data.
        """
        counts = [int(c) for c in counts]
        need = self._max_group_size() if self.groups is not None else self.size
        if len(counts) != need:
            raise ValueError(
                f"len(counts)={len(counts)} != comm size {need}; counts is "
                "indexed by (group-local) rank"
            )
        if x.shape[axis] < max(counts):
            raise ValueError(
                f"x.shape[{axis}]={x.shape[axis]} < max(counts)={max(counts)}; "
                "allgatherv needs every rank padded to a shared static extent"
            )
        cnt = jnp.asarray(np.asarray(counts, np.int32))[self.get_rank()]
        idx_shape = [1] * x.ndim
        idx_shape[axis] = x.shape[axis]
        valid = jnp.arange(x.shape[axis]).reshape(idx_shape) < cnt
        return self.allgather(jnp.where(valid, x, jnp.zeros_like(x)), axis=0)

    def gather(self, x, root: int = 0, axis: int = 0):
        g = self.allgather(x, axis=axis)
        keep = (self.get_rank() == root)
        return jnp.where(keep, g, jnp.zeros_like(g))

    def gatherv(self, x, counts: Sequence[int], root: int = 0, axis: int = 0):
        """Variable-size gather to root (core/comms.hpp:182 gatherv): the
        allgatherv result on root, zeros elsewhere."""
        g = self.allgatherv(x, counts, axis=axis)
        keep = (self.get_rank() == root)
        return jnp.where(keep, g, jnp.zeros_like(g))

    def reducescatter(self, x, op: op_t = op_t.SUM, axis: int = 0,
                      quantization=None):
        """Reduce over the comm, scatter chunks of the result along `axis`
        (core/comms.hpp:192 reducescatter, arbitrary op_t).

        `x.shape[axis]` must divide evenly into the chunk count: the comm
        size, or on a split comm the LARGEST group's size m (static shapes
        under XLA). Unequal-split pad semantics mirror allgatherv: group-
        local rank p receives chunk p of its group's reduction; the
        trailing m - len(group) chunks of a smaller group's reduction land
        on no rank (callers needing them use allreduce).
        """
        qcfg = _resolve_quant(quantization)
        if qcfg is not None:
            from raft_tpu.comms import quantized

            return quantized.qreducescatter(self, x, op, qcfg, axis=axis)
        x = jnp.asarray(x)
        obs.collective("reducescatter", x, axis=self.axis, world=self._wire_world())
        if self.groups is not None:
            m = self._max_group_size()
            if x.shape[axis] % m:
                raise ValueError(
                    f"x.shape[{axis}]={x.shape[axis]} not divisible by the "
                    f"largest group size {m}"
                )
            per = x.shape[axis] // m
            # rides the grouped-allreduce schedule dispatch (ring or
            # planes), then slices this rank's chunk — not the
            # (s-1)/s-payload reduce-scatter optimum, but the ring path
            # already beats the old O(G) planes cost wherever it wins
            red = self.allreduce(x, op)
            return lax.dynamic_slice_in_dim(
                red, self.get_rank() * per, per, axis=axis)
        if x.shape[axis] % self.size:
            raise ValueError(
                f"x.shape[{axis}]={x.shape[axis]} not divisible by comm "
                f"size {self.size}"
            )
        if op == op_t.SUM:
            return lax.psum_scatter(x, self.axis, scatter_dimension=axis,
                                    tiled=True)
        per = x.shape[axis] // self.size
        if op in (op_t.MIN, op_t.MAX):
            # volume-optimal (each rank ships world-1 chunks, the
            # reduce_scatter lower bound): all_to_all transposes chunk
            # ownership, then the reduction is rank-local
            t = lax.all_to_all(x, self.axis, split_axis=axis,
                               concat_axis=axis, tiled=True)
            seg = t.reshape(t.shape[:axis] + (self.size, per) + t.shape[axis + 1:])
            return (jnp.min if op == op_t.MIN else jnp.max)(seg, axis=axis)
        # PROD: exact/log-space allreduce, then this rank's chunk
        red = self.allreduce(x, op)
        return lax.dynamic_slice_in_dim(
            red, lax.axis_index(self.axis) * per, per, axis=axis)

    def _reducescatter_raw(self, x, op: op_t, axis: int):
        """Reduce-scatter dispatch alone — no obs accounting (callers own
        it); the quantized bf16 transport reuses it so the cast payload
        rides the exact schedules (SUM psum_scatter / MIN-MAX all_to_all
        / grouped allreduce-then-slice)."""
        if self.groups is not None:
            m = self._max_group_size()
            per = x.shape[axis] // m
            red = self._allreduce_raw(x, op)
            return lax.dynamic_slice_in_dim(
                red, self.get_rank() * per, per, axis=axis)
        if op == op_t.SUM:
            return lax.psum_scatter(x, self.axis, scatter_dimension=axis,
                                    tiled=True)
        per = x.shape[axis] // self.size
        if op in (op_t.MIN, op_t.MAX):
            t = lax.all_to_all(x, self.axis, split_axis=axis,
                               concat_axis=axis, tiled=True)
            seg = t.reshape(t.shape[:axis] + (self.size, per) + t.shape[axis + 1:])
            return (jnp.min if op == op_t.MIN else jnp.max)(seg, axis=axis)
        red = self._allreduce_raw(x, op)
        return lax.dynamic_slice_in_dim(
            red, lax.axis_index(self.axis) * per, per, axis=axis)

    # -- p2p (device_send/recv/sendrecv -> ppermute) -------------------
    def device_sendrecv(self, x, perm: Sequence[tuple]):
        """Explicit (src, dst) permutation — comms_t.device_sendrecv."""
        x = jnp.asarray(x)
        obs.collective("device_sendrecv", x, axis=self.axis, world=self._wire_world())
        return lax.ppermute(x, self.axis, perm=list(perm))

    def shift(self, x, offset: int = 1):
        """Ring shift by offset (the common send/recv pattern). On a split
        comm the ring is per group (global-rank perm built from each group's
        static member list)."""
        x = jnp.asarray(x)
        obs.collective("shift", x, axis=self.axis, world=self._wire_world())
        if self.groups is not None:
            perm = []
            for g in self.groups:
                perm += [(g[i], g[(i + offset) % len(g)]) for i in range(len(g))]
            return lax.ppermute(x, self.axis, perm=perm)
        n = self.size
        perm = [(i, (i + offset) % n) for i in range(n)]
        return lax.ppermute(x, self.axis, perm=perm)

    def device_multicast_sendrecv(self, x, dests: Sequence[Sequence[int]]):
        """Each rank i sends to dests[i] (list). Implemented as a sum of
        ppermutes (multicast = union of permutations)."""
        x = jnp.asarray(x)
        obs.collective("device_multicast_sendrecv", x, axis=self.axis, world=self._wire_world())
        n = self.size
        out = jnp.zeros_like(x)
        max_fan = max(len(d) for d in dests)
        for j in range(max_fan):
            perm = [(i, dests[i][j]) for i in range(n) if j < len(dests[i])]
            out = out + lax.ppermute(x, self.axis, perm=perm)
        return out

    def barrier(self, token=None):
        """Synchronization point: an allreduce of a scalar (comms_t.barrier
        semantics — collectives are ordered, so this fences)."""
        obs.collective("barrier", token if token is not None else jnp.zeros((), jnp.float32), axis=self.axis, world=self._wire_world())
        t = jnp.zeros((), jnp.float32) if token is None else jnp.sum(token) * 0
        return self.allreduce(t + 1.0, op_t.SUM)

    # -- host-side async p2p: DELIBERATELY ABSENT ----------------------
    # The reference's UCX-backed host p2p (comms_t.isend/irecv/waitall,
    # core/comms.hpp:154-176, and the NCCL group_start/group_end window,
    # :212-230) has no XLA analogue BY DESIGN: TPU transfers are issued
    # by the compiler inside a traced program (ppermute/collectives over
    # ICI/DCN), not as host-initiated async requests against a stream.
    # The mapping for each reference use-case:
    #   isend/irecv pairs  -> device_sendrecv / shift (ppermute) inside
    #                         the shard_map'd step
    #   waitall            -> nothing to wait on: XLA orders transfers;
    #                         jax.block_until_ready on the output fences
    #   group_start/end    -> trace-level fusion: everything in one jit
    #                         is already one "group"
    # These loud stubs document that rescope at the call site instead of
    # an AttributeError (SURVEY §2.8; VERDICT r4 missing #5).

    def isend(self, *a, **k):
        raise NotImplementedError(
            "comms_t.isend has no TPU analogue: XLA issues transfers "
            "inside traced programs. Use device_sendrecv/shift (ppermute) "
            "in a shard_map'd function; see the p2p notes in comms.py."
        )

    def irecv(self, *a, **k):
        raise NotImplementedError(
            "comms_t.irecv has no TPU analogue: XLA issues transfers "
            "inside traced programs. Use device_sendrecv/shift (ppermute) "
            "in a shard_map'd function; see the p2p notes in comms.py."
        )

    def waitall(self, *a, **k):
        raise NotImplementedError(
            "comms_t.waitall has no TPU analogue: XLA orders transfers in "
            "the compiled program; jax.block_until_ready on a result is "
            "the host-side fence. See the p2p notes in comms.py."
        )

    def group_start(self):
        raise NotImplementedError(
            "NCCL group_start/group_end windows have no TPU analogue: all "
            "collectives traced into one jit already fuse/schedule as one "
            "group. See the p2p notes in comms.py."
        )

    def group_end(self):
        raise NotImplementedError(
            "NCCL group_start/group_end windows have no TPU analogue: all "
            "collectives traced into one jit already fuse/schedule as one "
            "group. See the p2p notes in comms.py."
        )

    # -- split ---------------------------------------------------------
    def comm_split(self, colors: Sequence[int]) -> "AxisComms":
        """Static comm_split: ranks with the same color form a sub-comm
        (core/comms.hpp comm_split; NCCL subcomm re-init in std_comms).
        Colors must be Python ints (static). Groups may be unequal-sized
        (std_comms supports arbitrary color partitions): collectives then
        combine over each group's actual members; `get_size()` becomes a
        traced per-rank scalar and grouped `allgather` pads slots to the
        largest group."""
        colors = list(colors)
        if len(colors) != self.size:
            raise ValueError("colors must list one color per rank")
        groups = {}
        for r, c in enumerate(colors):
            groups.setdefault(c, []).append(r)
        return AxisComms(self.axis, self.size, tuple(tuple(g) for g in groups.values()))

    def sync_stream(self):
        """No-op on TPU: XLA orders collectives; host sync is Resources.sync."""
        return None


class Comms:
    """Session object bootstrapping SPMD execution over a mesh
    (raft-dask `Comms`, python/raft-dask/raft_dask/common/comms.py:37).

    Single-host: wraps local devices in a Mesh. Multi-host: call
    `jax.distributed.initialize()` first (the MPI/Dask-bootstrap analogue);
    the same Mesh API then spans hosts and collectives ride ICI/DCN.
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = "data",
                 n_devices: Optional[int] = None):
        if mesh is None:
            devs = jax.devices()
            if n_devices is not None:
                devs = devs[:n_devices]
            mesh = Mesh(np.array(devs), axis_names=(axis,))
        if axis not in mesh.axis_names:
            raise ValueError(f"axis {axis!r} not in mesh axes {mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        self.nccl_initialized = True  # API parity flag (raft-dask .init())
        self.ucx_initialized = False
        self._spans: Optional[bool] = None

    @property
    def comms(self) -> AxisComms:
        return AxisComms(self.axis, self.mesh.shape[self.axis])

    def get_size(self) -> int:
        return self.mesh.shape[self.axis]

    # -- launching SPMD functions (the client.run moment) --------------
    def run(self, fn: Callable, *args, in_specs=None, out_specs=None, **shard_kwargs):
        """Run fn(comms, *shards) SPMD over the mesh via shard_map."""
        comms = self.comms
        in_specs = in_specs if in_specs is not None else P(self.axis)
        out_specs = out_specs if out_specs is not None else P(self.axis)
        wrapped = lambda *a: fn(comms, *a)
        return jax.shard_map(
            wrapped, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False,
            **shard_kwargs,
        )(*args)

    def spans_processes(self) -> bool:
        """True when the mesh includes devices of other controller
        processes (multi-host / multi-controller SPMD). Computed once —
        the mesh is fixed at construction."""
        if self._spans is None:
            pi = jax.process_index()
            self._spans = any(d.process_index != pi for d in self.mesh.devices.flat)
        return self._spans

    def _sharding(self, ndim: int, axis: Optional[int]) -> NamedSharding:
        spec = [None] * ndim
        if axis is not None:
            spec[axis] = self.axis
        return NamedSharding(self.mesh, P(*spec))

    @staticmethod
    def _is_global(x) -> bool:
        """An array already laid out across processes (reshard is legal)."""
        return isinstance(x, jax.Array) and not x.is_fully_addressable

    def shard(self, x, axis: int = 0):
        """Place a FULL array sharded along the comms axis. Host numpy
        arrays transfer per-shard (device_put with a NamedSharding) — they
        are NOT first committed whole to the default device, so multi-GB
        host tables can be sharded onto meshes no single device could
        hold. On a process-spanning mesh only an already-global jax.Array
        is accepted (resharded); no one process holds a full host array —
        use `shard_from_local`."""
        if self._is_global(x):
            return jax.device_put(x, self._sharding(x.ndim, axis))
        if self.spans_processes():
            raise ValueError(
                "shard(full_array) is single-controller; on a multi-process "
                "mesh each process holds only its partition — use "
                "shard_from_local(local_rows)"
            )
        arr = x if isinstance(x, (np.ndarray, jax.Array)) else jnp.asarray(x)
        return jax.device_put(arr, self._sharding(arr.ndim, axis))

    def shard_from_local(self, local_x, axis: int = 0):
        """Assemble a globally-sharded array from this process's OWN rows
        (the raft-dask model: each worker contributes its partition,
        comms.py:37). Every process must call this collectively with its
        HOST-resident local slice; the concatenation along `axis` in
        process order forms the global array. Works single-process too
        (== shard)."""
        if self._is_global(local_x):
            raise ValueError(
                "shard_from_local takes this process's host rows, not an "
                "already process-spanning jax.Array (reshard via shard())"
            )
        if not self.spans_processes():
            return self.shard(local_x, axis=axis)
        arr = np.asarray(local_x)
        return jax.make_array_from_process_local_data(
            self._sharding(arr.ndim, axis), arr
        )

    def replicate(self, x):
        """Replicate an array over the mesh. On a process-spanning mesh
        every controller must pass the same host value (the standard
        multi-controller SPMD contract); already-global arrays reshard."""
        if self._is_global(x):
            return jax.device_put(x, self._sharding(x.ndim, None))
        if self.spans_processes():
            # normalize host data (lists, scalars, process-local arrays)
            # for the multi-controller assembly path
            arr = np.asarray(x)
            return jax.make_array_from_process_local_data(
                self._sharding(arr.ndim, None), arr
            )
        arr = x if isinstance(x, (np.ndarray, jax.Array)) else jnp.asarray(x)
        return jax.device_put(arr, self._sharding(arr.ndim, None))

    def destroy(self):
        """API parity with raft-dask Comms.destroy (comms.py:218); XLA owns
        the channels, nothing to tear down."""
        self.nccl_initialized = False


def init_comms(resources, mesh: Optional[Mesh] = None, axis: str = "data",
               n_devices: Optional[int] = None) -> Comms:
    """Build a Comms session and inject it into the Resources handle
    (inject_comms_on_handle, raft-dask comms_utils.pyx:27)."""
    c = Comms(mesh=mesh, axis=axis, n_devices=n_devices)
    resources.set_comms(c)
    return c


def local_handle(resources):
    """raft-dask `local_handle` parity (comms.py:245): the handle's comms."""
    return resources.get_comms()


_MULTIHOST_INITIALIZED = False


def bootstrap_multihost(coordinator_address: Optional[str] = None,
                        num_processes: Optional[int] = None,
                        process_id: Optional[int] = None,
                        max_retries: int = 3,
                        backoff_s: float = 0.05) -> bool:
    """Multi-controller bootstrap (the raft-dask `Comms.init` / MPI moment,
    comms.py:170): wraps `jax.distributed.initialize`, after which
    `jax.devices()` spans every host and the same Mesh/`shard_map` code
    rides ICI within a slice and DCN across slices.

    On TPU pods all three arguments resolve from the environment; pass
    them explicitly for CPU/GPU clusters. Idempotent — repeat calls (and
    already-initialized runtimes) return False instead of raising.

    Flaky-init failures (a coordinator racing its listeners up, injected
    chaos at site "comms.bootstrap") retry up to `max_retries` times with
    exponential backoff — the serving-path contract is that a pod
    restart converges without operator intervention. Persistent failures
    (bad coordinator address, unreachable peers) still surface after the
    retry window — as `resilience.RetryExhausted` chaining the last
    underlying error (XlaRuntimeError etc.) as `__cause__`; swallowing
    them would silently degrade a multi-host job to single-host."""
    global _MULTIHOST_INITIALIZED
    if _MULTIHOST_INITIALIZED:
        return False
    already = False
    try:
        already = jax.distributed.global_state.client is not None
    except AttributeError:
        pass
    if already:  # launcher (or an earlier caller) initialized the runtime
        _MULTIHOST_INITIALIZED = True
        return False
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id

    def _init_once():
        faults.fault_point("comms.bootstrap",
                           rank=process_id if process_id is not None else None)
        jax.distributed.initialize(**kwargs)

    from raft_tpu.comms.resilience import retry_with_backoff

    retry_with_backoff(
        _init_once, max_retries=max_retries, base_delay_s=backoff_s,
        retry_on=(faults.FaultInjected, RuntimeError),
        describe="multihost bootstrap",
    )
    _MULTIHOST_INITIALIZED = True
    return True
