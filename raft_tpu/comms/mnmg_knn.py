"""Distributed brute-force kNN: shard-local exact scan + top-k merge
(knn_merge_parts semantics) with prefilter + query-mode support."""


import functools
from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.comms.comms import Comms
from raft_tpu.distance.distance_types import DistanceType, resolve_metric
from raft_tpu.comms.mnmg_common import (
    _cached_wrapper, _knn_prefilter_words, _local_layout, _mask_dead_rank,
    _pack_local, _pack_result, _pad_queries, _rank_layout, _ranks_by_proc,
    _resolve_health, _shard_rows, rank_captured, wrapper_key,
)
from raft_tpu.comms.mnmg_merge import (
    _merge_local_topk, _merge_local_topk_scatter, _resolve_query_mode,
)


def _knn_sharded(comms: Comms, xs, queries, k: int, n_total: int, per: int,
                 rank_base: np.ndarray, valid_counts: np.ndarray, m,
                 pf_words=None, query_mode: str = "auto",
                 compute_dtype=None, health=None, replication: int = 1,
                 quantization: str = "auto"):
    """Shard-local exact kNN + merge over an already-sharded dataset.
    `rank_base[j]` maps rank j's shard-local row i to caller id base+i;
    `valid_counts[j]` rows of rank j's shard are real (a prefix — pads
    are masked BEFORE selection so they can't displace true neighbors).
    The one implementation behind knn() and knn_local(). With
    `replication` > 1, dead ranks' row blocks fail over losslessly from
    their ring replica holders (see comms/replication.py) before the
    degraded mask applies."""
    from raft_tpu.neighbors.brute_force import _bf_knn_impl

    from raft_tpu.core.bitset import Bitset
    from raft_tpu.comms.replication import failover_sharded_rows
    from raft_tpu.comms import quantized

    # resolved BEFORE the wrapper cache: the hashable config is part of
    # the cache key, so a tuned comms_quant_mode flip mid-process
    # rebuilds the traced program instead of serving the exact (or
    # stale-quantized) one
    qcfg = quantized.resolve(quantization)

    xs, health, repaired = failover_sharded_rows(comms, xs, replication,
                                                 health)
    ac = comms.comms
    select_min = m != DistanceType.InnerProduct
    worst = jnp.inf if select_min else -jnp.inf
    kk = int(min(k, per))
    qh = jnp.asarray(queries, jnp.float32)
    mode = _resolve_query_mode(query_mode, comms, qh.shape[0], kk)
    live_rep, mode, coverage = _resolve_health(comms, health, query_mode, mode)
    nq = qh.shape[0]
    if mode == "sharded":
        qh, nq = _pad_queries(qh, comms.get_size())
    merge = _merge_local_topk if mode == "replicated" else _merge_local_topk_scatter
    out_spec = P(None, None) if mode == "replicated" else P(comms.axis, None)
    qr = comms.replicate(qh)
    base_rep = comms.replicate(np.asarray(rank_base, np.int32))
    valid_rep = comms.replicate(np.asarray(valid_counts, np.int32))
    filtered = pf_words is not None
    if not filtered:  # 1-word placeholder keeps one jitted signature
        pf_words = np.zeros((comms.get_size(), 1), np.uint32)
    if comms.spans_processes():
        lr = _ranks_by_proc(comms.mesh).get(jax.process_index(), [])
        bits_sh = comms.shard_from_local(np.asarray(pf_words)[lr], axis=0)
    else:
        bits_sh = comms.shard(jnp.asarray(pf_words), axis=0)

    def build():
        @functools.partial(jax.jit, static_argnames=("use_pf",))
        def run(xs, qr, base, valid, bits, live, use_pf: bool):
            def body(xs, qr, base, valid, bits, live):
                rank = ac.get_rank()
                nv = valid[rank]
                pf = Bitset(bits[0], per) if use_pf else None
                if compute_dtype is not None:
                    # cast fuses into the scan's matmul loads; distances
                    # stay f32 (accumulation dtype), so masking/merge
                    # below are unchanged — see
                    # brute_force.knn(compute_dtype=...)
                    xs = xs.astype(compute_dtype)
                    qr = qr.astype(compute_dtype)
                v, i = _bf_knn_impl(xs, qr, kk, m, n_valid=nv, prefilter=pf)
                v = faults.corrupt_in_trace("mnmg.knn.scores", v, rank)
                i = i.astype(jnp.int32)
                # i >= 0 drops tiled-path init slots (-1), which would
                # otherwise map to base[rank]-1 — the previous shard's
                # last row
                keep = (i >= 0) & (i < nv)
                if use_pf:
                    # fewer than kk survivors: worst-scored slots may
                    # carry a filtered row's local index out of the tie —
                    # re-test the ids against the bitset (a score test
                    # would also drop a survivor whose distance
                    # overflowed to inf, and would keep NaN-scored
                    # filtered rows)
                    keep = keep & pf.test(i)
                gid = jnp.where(keep, base[rank] + i, -1)
                v = jnp.where(keep, v, worst)
                v, gid = _mask_dead_rank(v, gid, live, rank, worst)
                return merge(ac, v, gid, min(k, n_total), select_min,
                             quant=qcfg)

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None), P(None, None), P(None),
                          P(None), P(comms.axis, None), P(None)),
                out_specs=(out_spec, out_spec), check_vma=False,
            )(xs, qr, base, valid, bits, live)

        return run

    # every non-array closure input of the traced program, or the cache
    # would silently reuse a wrong program (see _JIT_WRAPPER_CACHE)
    run = _cached_wrapper(
        wrapper_key(
            "knn_sharded", comms, mode, m, int(kk),
            int(min(k, n_total)), int(per),
            None if compute_dtype is None else jnp.dtype(compute_dtype).name,
            qcfg),
        build,
    )
    v, gid = run(xs, qr, base_rep, valid_rep, bits_sh, live_rep, filtered)
    return _pack_result(v, gid, nq, coverage, repaired)


@rank_captured("mnmg.knn")
@obs.spanned("mnmg.knn")
def knn(
    comms: Comms,
    dataset,
    queries,
    k: int,
    metric="sqeuclidean",
    prefilter=None,
    query_mode: str = "auto",
    compute_dtype=None,
    health=None,
    replication: int = 1,
    quantization: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Shard-local exact kNN + allgather + merge (knn_merge_parts pattern,
    survey §5.7). Queries are replicated; dataset is sharded by rows.
    `prefilter` (core.Bitset or boolean mask over dataset row ids)
    excludes rows before selection on every rank. `query_mode` picks the
    merge topology (see `_resolve_query_mode`). `compute_dtype` is the
    per-shard scan's operand dtype (same near-exact speed/recall trade
    as `brute_force.knn`'s knob; merge semantics unchanged). `health`
    (resilience.RankHealth) enables degraded mode: unhealthy ranks'
    shards are masked out of the merge and the return becomes a
    `DegradedSearchResult(values, ids, coverage)`. `replication` > 1
    declares the r-way ring placement over the row blocks: up to r-1
    dead ranks fail over losslessly (bit-identical results, coverage
    1.0, ranks listed in `repaired_ranks`) — the host dataset shipped
    each call is the replica source, so only the election runs on
    device-free host math (see `replication.failover_sharded_rows`).
    `quantization` selects the merge wire transport (comms/quantized):
    "off" is bit-identical to the exact merge, "int8"/"bf16" ship
    block-quantized candidate scores and re-rank survivors on exact
    psum-resolved values; the default "auto" stays exact until a chip
    bench banks a `comms_quant_mode` winner for this backend."""
    m = resolve_metric(metric)
    x = np.asarray(dataset, np.float32)
    xs, n, per = _shard_rows(comms, x)
    r = comms.get_size()
    rank_base = per * np.arange(r, dtype=np.int64)
    valid_counts = np.clip(n - rank_base, 0, per)
    pf_words = _knn_prefilter_words(prefilter, n, rank_base, valid_counts, per)
    if obs.enabled():
        obs.span_cost(**obs.perf.cost_for(
            "mnmg.knn", n=n, nq=int(np.shape(queries)[0]), d=x.shape[1],
            k=int(k), dtype=compute_dtype if compute_dtype is not None
            else "f32"))
    return _knn_sharded(comms, xs, queries, k, n, per, rank_base, valid_counts,
                        m, pf_words=pf_words, query_mode=query_mode,
                        compute_dtype=compute_dtype, health=health,
                        replication=replication, quantization=quantization)


def knn_local(
    comms: Comms,
    local_dataset,
    queries,
    k: int,
    metric="sqeuclidean",
    prefilter=None,
    query_mode: str = "auto",
    compute_dtype=None,
    health=None,
    replication: int = 1,
    quantization: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Distributed exact kNN where each controller contributes its OWN
    rows (collective). Queries must be the same on every controller;
    returned ids are caller row ids — positions in the process-order
    concatenation of the partitions. `prefilter` covers that same global
    id space and, like queries, must be identical on every controller.
    `health` and `replication` must also be identical everywhere (see
    `knn`)."""
    m = resolve_metric(metric)
    local = np.asarray(local_dataset, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    n = int(counts.sum())
    xp, _ = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    rank_base, valid_counts = _rank_layout(comms, counts, per)
    pf_words = _knn_prefilter_words(prefilter, n, rank_base, valid_counts, per)
    return _knn_sharded(comms, xs, queries, k, n, per, rank_base, valid_counts,
                        m, pf_words=pf_words, query_mode=query_mode,
                        compute_dtype=compute_dtype, health=health,
                        replication=replication, quantization=quantization)
