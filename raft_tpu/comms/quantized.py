"""Quantized wire transport for `AxisComms` (EQuARX-style block-scaled
collectives, arxiv 2506.17615 — ROADMAP open item 3).

Every MNMG hot path historically shipped full-precision payloads over
ICI/DCN. This module adds an OPT-IN quantized transport behind the
`quantization=` keyword on `AxisComms.allreduce/allgather/reducescatter/
bcast` plus a top-k candidate-exchange primitive for distributed search
merges. Two codecs:

  "int8"  block-scaled int8: per-block absmax scales (f32 sidecar, one
          per `block` values), encode before the wire / decode after.
          Ring allreduce/reduce-scatter requantize PER HOP (the EQuARX
          schedule), so wire volume is ~1/4 of f32 + the 4/block scale
          overhead. Worst-case per-value error is absmax/254 per
          encode (round-to-nearest over 255 levels).
  "bf16"  cast transport: payloads travel as bfloat16 (2 bytes/value,
          no sidecar); reductions accumulate in bf16.

`quantization=None` (and `"off"`) is GUARANTEED bit-identical to the
unquantized collectives — the dispatch happens in Python before any
tracing, and the exact path's jaxpr is byte-for-byte the pre-quantization
one (pinned by tests/test_qcomms.py). `"auto"` consults the tuned keys
`comms_quant_mode` / `comms_quant_block`, honored only when the
`comms_quant_measured_on` hint matches the running backend (the
`mnmg_replicated_merge_schedule` rule: a chip-measured winner must not
flip the CPU mesh, and vice versa) — so `bench/bench_qcomms.py --apply`
flips serving defaults only on measured chip data.

Exactness fallbacks (quantization silently degrades to the exact path,
never an error): integer/bool payloads, `op_t.PROD` (log-space
recombination amplifies quantization error multiplicatively), and
world size < 2.

Candidate exchange (`exchange_candidates`): round 1 allgathers ONLY the
block-quantized scores (candidate positions are implicit in the
rank-major layout, so no id payload travels); every rank selects the
same `ceil(exchange_mult * k)` survivors from the dequantized scores;
one masked psum then resolves each survivor's EXACT f32 score and int32
id from its owning rank (zeros elsewhere — a sum with one non-zero term
is exact), and the final top-k re-ranks on exact values. Quantization
can therefore only affect WHICH candidates survive the shortlist, never
the reported scores — the recall-safe shape for distributed search.

Fault surface: sites `comms.quant.encode` / `comms.quant.decode`
(core.faults FAULT_SITES) corrupt the scale sidecars on the faulted
rank — seeded scale corruption decodes to visibly degraded (NaN/garbage)
payload contributions, never a crash; the drills live in
tests/test_resilience.py.

Wire accounting: every quantized path charges `obs.collective` with the
ACTUAL wire bytes (quantized payload + scale sidecars, summed over ring
hops) and the wire dtype, so `comms.<op>.wire_bytes` counters tell the
truth the EQuARX-style savings claims are judged against.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core import faults
from raft_tpu import obs
from raft_tpu.comms.comms import AxisComms, op_t

ENCODE_SITE = "comms.quant.encode"
DECODE_SITE = "comms.quant.decode"

#: int8 codec: values per f32 absmax scale. Tuned key `comms_quant_block`
#: overrides via mode="auto"; the choice set must match core.tuned's.
DEFAULT_BLOCK = 32
BLOCK_CHOICES = (16, 32, 64, 128)

#: exchange_candidates shortlist width multiplier: survivors = ceil(mult*k).
DEFAULT_EXCHANGE_MULT = 1.25

MODES = ("off", "int8", "bf16")


@dataclasses.dataclass(frozen=True)
class QuantConfig:
    """Resolved quantization policy — hashable, so it slots directly into
    `mnmg_common.wrapper_key` tuples (cache-key completeness: a tuned
    flip mid-process re-resolves to a different config and rebuilds the
    cached SPMD wrapper)."""

    mode: str
    block: int = DEFAULT_BLOCK
    exchange_mult: float = DEFAULT_EXCHANGE_MULT

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"unknown quantization mode {self.mode!r}; "
                             f"one of {MODES}")
        if int(self.block) < 1:
            raise ValueError(f"block must be >= 1, got {self.block}")
        if float(self.exchange_mult) < 1.0:
            raise ValueError("exchange_mult must be >= 1.0 (the shortlist "
                             f"can never be narrower than k), got "
                             f"{self.exchange_mult}")


def _tuned_mode() -> Optional[str]:
    from raft_tpu.core import tuned

    m = tuned.get("comms_quant_mode")
    if m not in ("int8", "bf16"):
        return None
    # backend guard (the merge_schedule_measured_on rule): only a winner
    # measured on THIS backend may flip the default
    if tuned.hints().get("comms_quant_measured_on") != jax.default_backend():
        return None
    return m


def _tuned_block() -> int:
    from raft_tpu.core import tuned

    return int(tuned.get_choice("comms_quant_block", BLOCK_CHOICES,
                                DEFAULT_BLOCK))


def resolve(quantization) -> Optional[QuantConfig]:
    """Normalize a `quantization=` argument to a QuantConfig (or None for
    the exact path). Accepts None/False/"off" (exact), "int8"/"bf16"
    (explicit, block from the tuned key or the default), "auto" (tuned
    keys with the measured-on backend guard; off until a chip session
    banks a winner), or an explicit QuantConfig."""
    if quantization is None or quantization is False or quantization == "off":
        return None
    if isinstance(quantization, QuantConfig):
        return None if quantization.mode == "off" else quantization
    if quantization == "auto":
        mode = _tuned_mode()
        if mode is None:
            return None
        return QuantConfig(mode=mode, block=_tuned_block())
    if quantization in ("int8", "bf16"):
        return QuantConfig(mode=quantization, block=_tuned_block())
    raise ValueError(
        f"unknown quantization {quantization!r}; one of None, 'off', "
        "'auto', 'int8', 'bf16', or a QuantConfig")


# -- codec --------------------------------------------------------------

def quantize_blocks(x, block: int = DEFAULT_BLOCK):
    """Block-scaled int8 encode: flatten, pad to a whole number of
    `block`-value blocks (pad slots encode exact zero), and quantize each
    block against its own absmax. Returns `(q, scales)`: q int8 of shape
    (nblk * block,), scales f32 of shape (nblk,). An all-zero block gets
    scale 0 and decodes to exact zeros. Worst-case error per value is
    scale/2 == absmax/254."""
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    n = flat.shape[0]
    nblk = max(1, -(-n // block))
    pad = nblk * block - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    b = flat.reshape(nblk, block)
    scales = jnp.max(jnp.abs(b), axis=1) / 127.0
    safe = jnp.where(scales > 0, scales, 1.0)
    q = jnp.clip(jnp.round(b / safe[:, None]), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scales


def dequantize_blocks(q, scales, shape, dtype=jnp.float32):
    """Inverse of `quantize_blocks` for a logical array of `shape`."""
    nblk = scales.shape[0]
    block = q.shape[0] // nblk
    x = q.reshape(nblk, block).astype(jnp.float32) * scales[:, None]
    n = int(np.prod(shape)) if shape else 1
    return x.reshape(-1)[:n].reshape(shape).astype(dtype)


def packet_bytes(n: int, block: int) -> int:
    """Wire bytes of one encoded packet for `n` logical values: int8
    payload (padded to whole blocks) + the f32 scale sidecar."""
    nblk = max(1, -(-n // block))
    return nblk * block + 4 * nblk


_COMBINE = {op_t.SUM: jnp.add, op_t.MIN: jnp.minimum, op_t.MAX: jnp.maximum}


def _quantizable(x, op: Optional[op_t], world: int) -> bool:
    """Payloads the codecs may touch: floats, SUM/MIN/MAX (or no
    reduction), real multi-rank worlds. Everything else silently rides
    the exact path — int tables (replication slot_gids, PQ codes) must
    pass through a quantized call untouched."""
    if world < 2:
        return False
    if op is not None and op not in _COMBINE:
        return False
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


# -- quantized collectives (call inside shard_map) ----------------------

def qallreduce(ac: AxisComms, x, op: op_t, cfg: Optional[QuantConfig]):
    """Quantized allreduce. int8 ungrouped: ring reduce-scatter +
    ring allgather with per-hop requantization (the EQuARX schedule);
    int8 grouped: the intra-group rotation ring on one encoded packet;
    bf16: cast transport through the exact dispatch."""
    x = jnp.asarray(x)
    w = ac._wire_world()
    if cfg is None or not _quantizable(x, op, w):
        return ac.allreduce(x, op)
    identity = ac._reduce_identity(x.dtype, op)
    if cfg.mode == "bf16":
        obs.collective(
            "allreduce", x, axis=ac.axis, world=w,
            wire_bytes=obs.perf.collective_wire_bytes(
                "allreduce", x.size * 2, w),
            wire_dtype="bfloat16")
        xi = ac._inject("comms.allreduce", x, identity)
        return ac._allreduce_raw(xi.astype(jnp.bfloat16), op).astype(x.dtype)
    block = int(cfg.block)
    xi = ac._inject("comms.allreduce", x, identity)
    if ac.groups is not None:
        nblk = max(1, -(-x.size // block))
        obs.collective(
            "allreduce", x, axis=ac.axis, world=w,
            wire_bytes=(ac._max_group_size() - 1) * (nblk * block + 4 * nblk),
            wire_dtype="int8")
        return _grouped_qallreduce_int8(ac, xi, op, block)
    n = x.size
    chunk = block * max(1, -(-n // (ac.size * block)))
    obs.collective(
        "allreduce", x, axis=ac.axis, world=w,
        wire_bytes=2 * (ac.size - 1) * packet_bytes(chunk, block),
        wire_dtype="int8")
    return _ring_qallreduce_int8(ac, xi, op, block)


def _grouped_qallreduce_int8(ac: AxisComms, x, op: op_t, block: int):
    """Grouped int8 allreduce on the `_grouped_reduce_ring` rotation:
    encode ONCE, rotate the (q, scales) packet within each group, decode
    and combine behind the same `k + 1 < s_own` accept gate. One
    quantization error per contribution (no per-hop requantization —
    the accumulator never travels)."""
    combine = _COMBINE[op]
    rank = lax.axis_index(ac.axis)
    q, sc = quantize_blocks(x, block)
    sc = faults.corrupt_in_trace(ENCODE_SITE, sc, rank)
    sizes = np.zeros((ac.size,), np.int32)
    for g in ac.groups:
        for r in g:
            sizes[r] = len(g)
    s_own = jnp.asarray(sizes)[rank]
    perm = ac._ring_perm()
    acc = x.astype(jnp.float32)  # own contribution stays exact
    qy, scy = q, sc
    for k in range(ac._max_group_size() - 1):
        qy = lax.ppermute(qy, ac.axis, perm)
        scy = lax.ppermute(scy, ac.axis, perm)
        scd = faults.corrupt_in_trace(DECODE_SITE, scy, rank)
        y = dequantize_blocks(qy, scd, x.shape)
        acc = jnp.where(k + 1 < s_own, combine(acc, y), acc)
    return acc.astype(x.dtype)


def _ring_qallreduce_int8(ac: AxisComms, x, op: op_t, block: int):
    """Full-axis int8 ring allreduce with per-hop requantization.

    Reduce-scatter phase: the flattened payload splits into `w` chunks of
    whole blocks; at step s rank r ships its requantized accumulator for
    chunk (r - s) and receives chunk (r - 1 - s)'s, combining with its
    own local part — after w-1 steps rank r holds the fully-reduced
    chunk (r + 1) % w. Allgather phase: each rank encodes its reduced
    chunk ONCE and the packet circulates the ring; EVERY rank — owner
    included — decodes the same packet, so the replicated result is
    bit-identical across ranks."""
    w = ac.size
    combine = _COMBINE[op]
    rank = lax.axis_index(ac.axis)
    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    n = flat.shape[0]
    chunk = block * max(1, -(-n // (w * block)))
    padded = w * chunk
    if padded > n:
        flat = jnp.concatenate([flat, jnp.zeros((padded - n,), flat.dtype)])
    parts = flat.reshape(w, chunk)
    perm = [(i, (i + 1) % w) for i in range(w)]
    cur = lax.dynamic_index_in_dim(parts, rank, keepdims=False)
    for s in range(w - 1):
        q, sc = quantize_blocks(cur, block)
        sc = faults.corrupt_in_trace(ENCODE_SITE, sc, rank)
        q = lax.ppermute(q, ac.axis, perm)
        sc = lax.ppermute(sc, ac.axis, perm)
        scd = faults.corrupt_in_trace(DECODE_SITE, sc, rank)
        c = (rank - 1 - s) % w
        cur = combine(lax.dynamic_index_in_dim(parts, c, keepdims=False),
                      dequantize_blocks(q, scd, (chunk,)))
    q, sc = quantize_blocks(cur, block)
    sc = faults.corrupt_in_trace(ENCODE_SITE, sc, rank)
    out = jnp.zeros((w, chunk), jnp.float32)
    scd = faults.corrupt_in_trace(DECODE_SITE, sc, rank)
    out = lax.dynamic_update_index_in_dim(
        out, dequantize_blocks(q, scd, (chunk,)), (rank + 1) % w, 0)
    for s in range(w - 1):
        q = lax.ppermute(q, ac.axis, perm)
        sc = lax.ppermute(sc, ac.axis, perm)
        scd = faults.corrupt_in_trace(DECODE_SITE, sc, rank)
        out = lax.dynamic_update_index_in_dim(
            out, dequantize_blocks(q, scd, (chunk,)), (rank - s) % w, 0)
    return out.reshape(-1)[:n].reshape(x.shape).astype(x.dtype)


def qreducescatter(ac: AxisComms, x, op: op_t, cfg: Optional[QuantConfig],
                   axis: int = 0):
    """Quantized reduce-scatter: the ring reduce-scatter phase alone
    (per-rank output, no allgather), operating on `axis`-major chunks so
    the scattered layout matches the exact path's. Grouped comms mirror
    the exact path's allreduce-then-slice delegation."""
    x = jnp.asarray(x)
    w = ac._wire_world()
    if cfg is None or not _quantizable(x, op, w):
        return ac.reducescatter(x, op, axis=axis)
    if cfg.mode == "bf16":
        obs.collective(
            "reducescatter", x, axis=ac.axis, world=w,
            wire_bytes=obs.perf.collective_wire_bytes(
                "reducescatter", x.size * 2, w),
            wire_dtype="bfloat16")
        return ac._reducescatter_raw(
            x.astype(jnp.bfloat16), op, axis).astype(x.dtype)
    block = int(cfg.block)
    if ac.groups is not None:
        m = ac._max_group_size()
        if x.shape[axis] % m:
            raise ValueError(
                f"x.shape[{axis}]={x.shape[axis]} not divisible by the "
                f"largest group size {m}")
        per = x.shape[axis] // m
        obs.collective(
            "reducescatter", x, axis=ac.axis, world=w,
            wire_bytes=0, wire_dtype="int8")  # the inner qallreduce charges
        red = qallreduce(ac, x, op, cfg)
        return lax.dynamic_slice_in_dim(red, ac.get_rank() * per, per,
                                        axis=axis)
    if x.shape[axis] % ac.size:
        raise ValueError(
            f"x.shape[{axis}]={x.shape[axis]} not divisible by comm "
            f"size {ac.size}")
    chunk_n = x.size // ac.size
    obs.collective(
        "reducescatter", x, axis=ac.axis, world=w,
        wire_bytes=(ac.size - 1) * packet_bytes(chunk_n, block),
        wire_dtype="int8")
    return _ring_qreducescatter_int8(ac, x, op, block, axis)


def _ring_qreducescatter_int8(ac: AxisComms, x, op: op_t, block: int,
                              axis_dim: int):
    """Ring reduce-scatter with per-hop requantization: rank r starts on
    chunk (r - 1), at step s ships its accumulator for chunk (r - 1 - s)
    and receives chunk (r - 2 - s)'s — after w-1 steps rank r holds the
    fully-reduced chunk r (matching psum_scatter's chunk assignment).
    The final combine is a rank-local exact add."""
    w = ac.size
    combine = _COMBINE[op]
    rank = lax.axis_index(ac.axis)
    per = x.shape[axis_dim] // w
    xm = jnp.moveaxis(jnp.asarray(x, jnp.float32), axis_dim, 0)
    parts = xm.reshape((w, per) + xm.shape[1:])
    chunk_shape = parts.shape[1:]
    perm = [(i, (i + 1) % w) for i in range(w)]
    cur = lax.dynamic_index_in_dim(parts, (rank - 1) % w, keepdims=False)
    for s in range(w - 1):
        q, sc = quantize_blocks(cur, block)
        sc = faults.corrupt_in_trace(ENCODE_SITE, sc, rank)
        q = lax.ppermute(q, ac.axis, perm)
        sc = lax.ppermute(sc, ac.axis, perm)
        scd = faults.corrupt_in_trace(DECODE_SITE, sc, rank)
        c = (rank - 2 - s) % w
        cur = combine(lax.dynamic_index_in_dim(parts, c, keepdims=False),
                      dequantize_blocks(q, scd, chunk_shape))
    return jnp.moveaxis(cur, 0, axis_dim).astype(x.dtype)


def qallgather(ac: AxisComms, x, cfg: Optional[QuantConfig], axis: int = 0,
               tiled: bool = False):
    """Quantized allgather: encode once, gather the int8 payload and the
    scale sidecar through the exact dispatch (grouped schedules
    included), decode every slot. Output layout matches the exact
    path's (new axis / moveaxis / tiled concatenation)."""
    x = jnp.asarray(x)
    w = ac._wire_world()
    if cfg is None or not _quantizable(x, None, w):
        return ac.allgather(x, axis=axis, tiled=tiled)
    if cfg.mode == "bf16":
        obs.collective(
            "allgather", x, axis=ac.axis, world=w,
            wire_bytes=obs.perf.collective_wire_bytes(
                "allgather", x.size * 2, w),
            wire_dtype="bfloat16")
        xi = ac._inject("comms.allgather", x, jnp.zeros((), x.dtype))
        return ac._allgather_raw(
            xi.astype(jnp.bfloat16), axis, tiled).astype(x.dtype)
    block = int(cfg.block)
    rank = lax.axis_index(ac.axis)
    obs.collective(
        "allgather", x, axis=ac.axis, world=w,
        wire_bytes=(w - 1) * packet_bytes(x.size, block),
        wire_dtype="int8")
    xi = ac._inject("comms.allgather", x, jnp.zeros((), x.dtype))
    q, sc = quantize_blocks(xi, block)
    sc = faults.corrupt_in_trace(ENCODE_SITE, sc, rank)
    qg = ac._allgather_raw(q, 0, False)
    scg = ac._allgather_raw(sc, 0, False)
    scg = faults.corrupt_in_trace(DECODE_SITE, scg, rank)
    out = jnp.stack([dequantize_blocks(qg[i], scg[i], x.shape)
                     for i in range(qg.shape[0])]).astype(x.dtype)
    if tiled:
        return jnp.concatenate([out[i] for i in range(out.shape[0])],
                               axis=axis)
    if axis != 0:
        return jnp.moveaxis(out, 0, axis)
    return out


def qbcast(ac: AxisComms, x, cfg: Optional[QuantConfig], root: int = 0):
    """Quantized broadcast: every rank encodes (same SPMD program), the
    exact dispatch moves the root-masked int8 payload + scales (a sum of
    one non-zero contribution is exact in int8 — no overflow), and every
    rank decodes the root's packet."""
    xa = jnp.asarray(x)
    w = ac._wire_world()
    if cfg is None or not _quantizable(xa, None, w):
        return ac.bcast(x, root)
    if cfg.mode == "bf16":
        obs.collective(
            "bcast", xa, axis=ac.axis, world=w,
            wire_bytes=obs.perf.collective_wire_bytes(
                "bcast", xa.size * 2, w),
            wire_dtype="bfloat16")
        return ac._bcast_raw(xa.astype(jnp.bfloat16), root).astype(xa.dtype)
    block = int(cfg.block)
    rank = lax.axis_index(ac.axis)
    obs.collective(
        "bcast", xa, axis=ac.axis, world=w,
        wire_bytes=obs.perf.collective_wire_bytes(
            "bcast", packet_bytes(xa.size, block), w),
        wire_dtype="int8")
    q, sc = quantize_blocks(xa, block)
    sc = faults.corrupt_in_trace(ENCODE_SITE, sc, rank)
    qb = ac._bcast_raw(q, root)
    scb = ac._bcast_raw(sc, root)
    scb = faults.corrupt_in_trace(DECODE_SITE, scb, rank)
    return dequantize_blocks(qb, scb, xa.shape).astype(xa.dtype)


# -- candidate exchange -------------------------------------------------

def exchange_candidates(ac: AxisComms, v, ids, k: int, select_min: bool,
                        cfg: QuantConfig):
    """Quantized replicated top-k candidate exchange (the recall-safe
    merge for distributed search; full-axis comms only — callers route
    split comms to the exact merge).

    `v`, `ids`: this rank's (nq, kk) local candidates; ids global,
    invalid entries masked to the worst value in `v` by the caller (the
    `_merge_local_topk` contract). Returns `(values, ids)` of width
    min(k, world * kk), replicated-identical across ranks, with EXACT
    scores: quantization only picks the shortlist, the psum resolve
    round recovers the owners' full-precision scores and ids.

    Tie-break parity: both the shortlist select and the final re-rank
    order by (score, rank-major global position) — the same order one
    flat rank-major select over the exact allgather would use — so a
    saturated shortlist (ceil(mult*k) >= world*kk) reproduces the exact
    merge's candidate set."""
    w = ac.size
    nq, kk = v.shape
    total = w * kk
    rank = lax.axis_index(ac.axis)
    vf = v.astype(jnp.float32)
    out_k = min(int(k), total)
    s = min(total, max(out_k, int(math.ceil(cfg.exchange_mult * out_k))))

    # round 1: block-quantized scores only (bf16 mode ships a cast
    # plane instead); positions are implicit in the rank-major layout
    if cfg.mode == "bf16":
        enc = faults.corrupt_in_trace(ENCODE_SITE, vf.astype(jnp.bfloat16),
                                      rank)
        obs.collective(
            "allgather", vf, axis=ac.axis, world=w,
            wire_bytes=(w - 1) * vf.size * 2, wire_dtype="bfloat16")
        g = lax.all_gather(enc, ac.axis, axis=0)  # (w, nq, kk)
        g = faults.corrupt_in_trace(DECODE_SITE, g.astype(jnp.float32), rank)
        cand = g
    else:
        block = int(cfg.block)
        q, sc = quantize_blocks(vf, block)
        sc = faults.corrupt_in_trace(ENCODE_SITE, sc, rank)
        obs.collective(
            "allgather", vf, axis=ac.axis, world=w,
            wire_bytes=(w - 1) * packet_bytes(vf.size, block),
            wire_dtype="int8")
        qg = lax.all_gather(q, ac.axis, axis=0)
        scg = lax.all_gather(sc, ac.axis, axis=0)
        scg = faults.corrupt_in_trace(DECODE_SITE, scg, rank)
        cand = jnp.stack([dequantize_blocks(qg[i], scg[i], (nq, kk))
                          for i in range(w)])
    cat = jnp.moveaxis(cand, 0, 1).reshape(nq, total)  # rank-major columns

    # shortlist: top-s of the dequantized scores, ties by global position
    key = cat if select_min else -cat
    posg = jnp.broadcast_to(jnp.arange(total, dtype=jnp.int32), (nq, total))
    _, spos = lax.sort((key, posg), dimension=1, num_keys=2)
    pos = spos[:, :s]  # (nq, s) survivor positions, identical on all ranks

    # resolve: each survivor's owner contributes its exact score and id;
    # a psum over one non-zero contribution reconstructs both exactly
    owner = pos // kk
    col = pos % kk
    mine = owner == rank
    sv = jnp.where(mine, jnp.take_along_axis(vf, col, axis=1), 0.0)
    sid = jnp.where(mine,
                    jnp.take_along_axis(ids.astype(jnp.int32), col, axis=1),
                    0)
    obs.collective(
        "allreduce", sv, axis=ac.axis, world=w,
        wire_bytes=obs.perf.collective_wire_bytes("allreduce", sv.size * 4, w),
        wire_dtype="float32")
    obs.collective(
        "allreduce", sid, axis=ac.axis, world=w,
        wire_bytes=obs.perf.collective_wire_bytes("allreduce", sid.size * 4,
                                                  w),
        wire_dtype="int32")
    sv = lax.psum(sv, ac.axis)
    sid = lax.psum(sid, ac.axis)

    # exact re-rank of the survivors, same (score, position) order
    fkey = sv if select_min else -sv
    _, _, rv, rid = lax.sort((fkey, pos, sv, sid), dimension=1, num_keys=2)
    return rv[:, :out_k], rid[:, :out_k]
