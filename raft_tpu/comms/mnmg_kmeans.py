"""Distributed k-means (driver-sharded and multi-controller *_local
variants): allreduce-wrapped EM over the comms mesh (survey 3.4)."""


import functools
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.comms.comms import Comms
from raft_tpu.cluster.kmeans_common import assign_and_reduce
from raft_tpu.comms.mnmg_common import (
    _cached_wrapper,
    wrapper_key,
    _gather_replicated,
    _local_layout,
    _local_shard_rows_host,
    _pack_local,
    _shard_rows,
    rank_captured,
    _valid_global_positions,
    _valid_weights,
)


def _kmeans_fit_sharded(
    comms: Comms,
    xs,
    w,
    centers=None,
    max_iter: int = 100,
    tol: float = 1e-4,
    metric_name: str = "sqeuclidean",
    balance: bool = False,
    seed: int = 0,
    balancing_ratio: float = 4.0,
    n_valid: Optional[int] = None,
    inits=None,
    valid_counts: Optional[np.ndarray] = None,
    quantization: str = "auto",
) -> Tuple[jax.Array, float, int]:
    """Lloyd EM over an already-sharded dataset (`xs` sharded on rows along
    the comms axis, `w` row-validity weights, `centers` replicated).
    `inits` (a sequence of initial center sets) runs restart trials that
    share one compiled EM step and returns the best-inertia run:
    per-iteration partial sums are allreduced across ranks (survey §3.4
    MNMG variant). Returns (centers, inertia, n_iter).

    With `balance`, undersized clusters (global count below
    n/k/balancing_ratio) are re-seeded toward a random valid row each
    iteration — kmeans_balanced's adjust_centers semantics, distributed:
    each cluster's proposal row comes from one rank's shard (cluster_id
    mod ranks) and is shared by psum, so replicated centers stay
    identical everywhere. Two trailing clean EM steps follow, like the
    single-chip balanced trainer. Balanced coarse centers keep IVF list
    sizes even, which directly bounds max_list padding in the list-major
    stores.

    For inner_product/cosine, centers are re-normalized each iteration
    (kmeans_balanced's _maybe_normalize semantics): with unit-norm centers,
    the L2 argmin of assign_and_reduce equals the argmax-dot assignment
    (||x||^2 - 2 x.c + 1 is monotone in -x.c), so the fused L2 engine
    serves both metrics."""
    ac = comms.comms
    from raft_tpu.comms import quantized

    # resolved once per fit (the jit `step` closure is per-fit too, so
    # the traced program always matches the resolved config); only the
    # O(k*d) partial-sum plane is quantized — counts must stay exact
    # (they gate the empty-cluster guard) and inertia is a scalar
    qcfg = quantized.resolve(quantization)
    ip = metric_name in ("inner_product", "cosine")
    r = comms.get_size()
    k = int(jnp.asarray(centers if centers is not None else inits[0]).shape[0])
    if balance:
        if n_valid is None:
            raise ValueError("balance=True requires n_valid (host-known rows)")
        per = xs.shape[0] // r
        # per-rank valid row counts are host knowledge (valid rows are a
        # prefix of each shard): exact at any scale — a float32 sum of w
        # would saturate at 2^24 rows. Default derivation assumes the
        # valid rows form one contiguous global prefix; multi-controller
        # layouts interleave processes and pass their own valid_counts.
        if valid_counts is None:
            valid_counts = np.clip(
                n_valid - per * np.arange(r, dtype=np.int64), 0, per
            )
        valid_counts = np.asarray(valid_counts, np.int64)
        # proposal ownership maps clusters onto the DATA-HOLDING ranks
        # (an empty rank's only row is the zero pad — a useless proposal)
        holders = np.flatnonzero(valid_counts > 0)
        if holders.size == 0:
            holders = np.asarray([0], np.int64)
        owners = jnp.asarray(holders[np.arange(k) % holders.size], jnp.int32)
        threshold = float(n_valid) / k / balancing_ratio

    def _norm(c):
        return c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True), 1e-12)

    if ip and centers is not None:
        centers = _norm(jnp.asarray(centers))

    @functools.partial(jax.jit, static_argnames=("adjust",))
    def step(xs, w, centers, key, adjust: bool):
        def body(xs, w, centers, key):
            _, sums, counts, inertia = assign_and_reduce(xs, centers, w)
            # chaos site: corrupt one rank's partial sums BEFORE the
            # allreduce (a poisoned shard's EM contribution); no-op
            # without an installed FaultPlan — `step` is a per-fit
            # closure, so the plan is read at trace time
            sums = faults.corrupt_in_trace(
                "mnmg.kmeans.partials", sums, lax.axis_index(ac.axis))
            sums = ac.allreduce(sums, quantization=qcfg)
            counts = ac.allreduce(counts)
            inertia = ac.allreduce(inertia)
            safe = jnp.maximum(counts, 1.0)[:, None]
            new_centers = jnp.where(counts[:, None] > 0, sums / safe, centers)
            if adjust:
                # same key on every rank -> same proposal indices; each
                # cluster's proposal comes from one data-holding rank
                rank = lax.axis_index(ac.axis)
                valid = jnp.maximum(jnp.asarray(valid_counts, jnp.int32)[rank], 1)
                props = jax.random.randint(key, (k,), 0, 1 << 30) % valid
                mine = owners == rank
                local = jnp.where(mine[:, None], xs[props].astype(jnp.float32), 0.0)
                proposals = ac.allreduce(local)
                small = counts < threshold
                wc = jnp.minimum(counts, 7.0)[:, None]
                adjusted = (wc * new_centers + proposals) / (wc + 1.0)
                new_centers = jnp.where(small[:, None], adjusted, new_centers)
            if ip:
                new_centers = _norm(new_centers)
            shift = jnp.sum((new_centers - centers) ** 2)
            return new_centers, inertia, shift

        return jax.shard_map(
            body, mesh=comms.mesh,
            in_specs=(P(comms.axis, None), P(comms.axis), P(None, None), P(None)),
            out_specs=(P(None, None), P(), P()), check_vma=False,
        )(xs, w, centers, key)

    def run_one(centers):
        inertia = np.inf
        it = 0
        key = jax.random.PRNGKey(seed)
        for it in range(1, max_iter + 1):
            # slow/flaky drills; rank-scoped faults hit one controller
            faults.fault_point("mnmg.kmeans.step", rank=jax.process_index())
            key, k1 = jax.random.split(key)
            centers, inertia, shift = step(xs, w, centers, k1, balance)
            if not balance and float(shift) < tol * tol:
                break
        if balance:  # trailing clean EM (un-balanced Lloyd updates)
            for _ in range(2):
                centers, inertia, _ = step(xs, w, centers, key, False)
        return centers, float(inertia), it

    if inits is None:
        return run_one(centers)
    # restart trials share `step`'s single compilation (the closure is
    # created once per fit, so jit caches across trials)
    best = None
    for c0 in inits:
        out = run_one(_norm(jnp.asarray(c0)) if ip else c0)
        if best is None or out[1] < best[1]:
            best = out
    return best


@rank_captured("mnmg.kmeans_fit")
@obs.spanned("mnmg.kmeans_fit")
def kmeans_fit(
    comms: Comms,
    X,
    n_clusters: int,
    max_iter: int = 100,
    tol: float = 1e-4,
    seed: int = 0,
    n_init: int = 1,
    quantization: str = "auto",
) -> Tuple[jax.Array, float, int]:
    """Distributed Lloyd: shard rows, allreduce partial sums per iteration
    (survey §3.4 MNMG variant). Returns (centers, inertia, n_iter).
    `n_init` restarts with different k-means++ seeds keep the best-inertia
    run (KMeansParams.n_init parity) — Lloyd's local optima depend
    heavily on init luck. `quantization` selects the partial-sum
    allreduce's wire transport (comms/quantized): "off" is bit-identical
    to the exact fit; the default "auto" stays exact until a chip bench
    banks a `comms_quant_mode` winner for this backend."""
    x = np.asarray(X, np.float32)
    xs, n, per = _shard_rows(comms, x)
    w = comms.shard(_valid_weights(n, per, comms.get_size()), axis=0)
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    inits = []
    for t in range(max(1, n_init)):
        rng = np.random.default_rng(seed + t)
        sub = x[rng.choice(n, min(n, max(n_clusters * 8, 1024)), replace=False)]
        c0 = _kmeans_plusplus(jax.random.PRNGKey(seed + t), jnp.asarray(sub), n_clusters)
        inits.append(comms.replicate(c0))
    centers, inertia, n_iter = _kmeans_fit_sharded(
        comms, xs, w, max_iter=max_iter, tol=tol, inits=inits,
        quantization=quantization)
    if obs.enabled():
        obs.span_cost(**obs.perf.cost_for(
            "mnmg.kmeans_fit", n=n, d=x.shape[1], n_clusters=n_clusters,
            iters=int(n_iter)))
    return centers, inertia, n_iter

def kmeans_fit_local(
    comms: Comms,
    local_X,
    n_clusters: int,
    max_iter: int = 100,
    tol: float = 1e-4,
    seed: int = 0,
    n_init: int = 1,
    quantization: str = "auto",
) -> Tuple[jax.Array, float, int]:
    """Distributed Lloyd where each controller passes its OWN partition
    (collective: every process must call with the same arguments apart
    from local_X). Returns (replicated centers, global inertia, n_iter).
    Single-process it matches kmeans_fit on the concatenated rows;
    `n_init` restarts keep the best-inertia run."""
    local = np.asarray(local_X, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    xp, wl = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    w = comms.shard_from_local(wl, axis=0)
    n = int(counts.sum())
    if n_clusters > n:
        raise ValueError(f"n_clusters={n_clusters} > total rows {n}")

    # init: k-means++ on a deterministic global subsample — identical on
    # every controller (same seed, same gathered rows)
    gpos = _valid_global_positions(comms, counts, per)
    from raft_tpu.cluster.kmeans import _kmeans_plusplus

    subsample = min(n, max(n_clusters * 8, 1024))
    inits = []
    for t in range(max(1, n_init)):
        rng = np.random.default_rng(seed + t)
        sel = gpos[rng.choice(n, subsample, replace=False)]
        sub = _gather_replicated(comms, xs, sel)
        c0 = _kmeans_plusplus(jax.random.PRNGKey(seed + t), jnp.asarray(sub), n_clusters)
        inits.append(comms.replicate(np.asarray(c0)))
    return _kmeans_fit_sharded(comms, xs, w, max_iter=max_iter, tol=tol,
                               inits=inits, quantization=quantization)


def kmeans_predict_local(comms: Comms, local_X, centers) -> jax.Array:
    """Nearest-center labels for this process's OWN rows (collective).
    Returns the (n_local,) labels of the local partition."""
    local = np.asarray(local_X, np.float32)
    counts, per, lranks = _local_layout(comms, local.shape[0])
    xp, _ = _pack_local(local, per, lranks)
    xs = comms.shard_from_local(xp, axis=0)
    labels = _spmd_predict(comms, xs, centers)
    return _local_shard_rows_host(labels)[: local.shape[0]]


def _spmd_predict(comms: Comms, xs, centers) -> jax.Array:
    """Nearest-center labels over an already-sharded dataset (includes any
    pad rows; callers slice to [:n])."""

    def build():
        @jax.jit
        def run(xs, c):
            def body(xs, c):
                labels, _, _, _ = assign_and_reduce(xs, c, needs_sums=False)
                return labels

            return jax.shard_map(
                body, mesh=comms.mesh,
                in_specs=(P(comms.axis, None), P(None, None)),
                out_specs=P(comms.axis), check_vma=False,
            )(xs, c)

        return run

    # predict is a serving path called per request (see _cached_wrapper)
    run = _cached_wrapper(wrapper_key("spmd_predict", comms), build)
    # centers may already be a replicated global array (kmeans_fit_local
    # output) — replicate() reshards those and asarray would fail on them
    c = centers if Comms._is_global(centers) else jnp.asarray(centers, jnp.float32)
    return run(xs, comms.replicate(c))


def kmeans_predict(comms: Comms, X, centers) -> jax.Array:
    """Distributed assignment; returns global labels (n,) on host order."""
    x = np.asarray(X, np.float32)
    xs, n, per = _shard_rows(comms, x)
    return _spmd_predict(comms, xs, centers)[:n]
