"""Self-healing recovery for the distributed indexes: re-materialize
lost shards, verify the mesh, and flip rejoining ranks live again.

The failure lifecycle this module closes (see replication.py for the
failover half):

    healthy --(fault)--> degraded, failover serves replica copies
            --(repair)--> primaries re-materialized on the sick rank
            --(rank_rejoin)--> verified barrier, mask flips healthy
            --> healthy again, primaries serve, mirrors re-coherent

`repair` is the data-plane heal: every unhealthy rank's primary tables
are rewritten from its elected holder's replica copy (one static
ppermute per failure pattern — the same patch program failover uses,
but applied IN PLACE to the index so the healed primaries persist), and
the mirror tables are then re-derived from the healed primaries so the
next failure finds coherent replicas. When a shard has NO surviving
replica copy (more than r-1 failures, or stale mirrors), `repair`
falls back to `resilience.rehydrate` from a checkpoint — the index is
reloaded wholesale and returned in place of the patched one.

`rank_rejoin` is the control-plane heal: a verified `health_barrier`
proves the mesh answers collectives end to end, THEN the rank's mask
bit flips healthy (never before — a rank that cannot pass the barrier
must stay masked). Subsequent searches use the rejoined primary again.

Both emit obs bus events ("repair", "rejoin") so a chaos drill leaves
an auditable heal timeline next to PR 1's fault/health events.
"""

from __future__ import annotations

from typing import Optional, Tuple

from raft_tpu import obs
from raft_tpu.comms.comms import Comms
from raft_tpu.comms import replication
from raft_tpu.core.logger import logger


class RecoveryError(RuntimeError):
    """A lost shard could not be re-materialized: no surviving replica
    holder and no checkpoint to rehydrate from."""


def lost_ranks(index, health) -> Tuple[int, ...]:
    """Unhealthy ranks whose shard has NO surviving (healthy, non-stale)
    replica holder — the ones only a checkpoint can bring back."""
    replicas = getattr(index, "replicas", None)
    stale = replication.stale_holders()
    out = []
    for u in range(health.world):
        if bool(health.mask[u]):
            continue
        if replicas is None or replicas.placement.elect(
                u, health, stale=stale) is None:
            out.append(int(u))
    return tuple(out)


def repair(comms: Comms, health, index, checkpoint: Optional[str] = None):
    """Re-materialize every unhealthy rank's shard. Replica-repairable
    ranks heal from their elected holders' copies (in place: the index's
    primary tables are rewritten and its mirrors re-derived); ranks with
    no surviving copy fall back to `resilience.rehydrate(checkpoint)` —
    without a checkpoint they raise `RecoveryError`. Returns the healed
    index (the same object for replica repairs, a fresh one for
    checkpoint rehydration). `health` is NOT modified — flipping masks
    is `rank_rejoin`'s job, after the barrier proves the rank back."""
    # health is controller-uniform by protocol (one probe/plan feeds every
    # controller's mask), so all controllers take the same side here
    if not health.degraded:  # raftlint: disable=collective-divergence
        return index
    lost = lost_ranks(index, health)
    if lost:
        if checkpoint is None:
            raise RecoveryError(
                f"ranks {list(lost)} have no surviving replica copy "
                f"(r={getattr(getattr(index, 'replicas', None), 'r', 1)}) "
                "and no checkpoint was given to rehydrate from"
            )
        from raft_tpu.comms.resilience import rehydrate

        logger.warning(
            "repair: ranks %s lost every replica copy; rehydrating from %r",
            list(lost), checkpoint,
        )
        fresh, _ = rehydrate(comms, checkpoint)
        r = getattr(getattr(index, "replicas", None), "r", 1)
        if r > 1:
            replication.replicate_index(fresh, r)
        obs.event("repair", source="checkpoint", ranks=list(lost),
                  checkpoint=str(checkpoint))
        return fresh
    replicas = index.replicas
    stale = replication.stale_holders()
    assignment = replicas.placement.assignment(health, stale=stale)
    moves = tuple(sorted(
        (u, h, replicas.placement.slot(h, u))
        for u, h in assignment.items()
    ))
    for name in replication._replicated_attrs(index):
        setattr(index, name, replication.patch_tables(
            comms, getattr(index, name), replicas.tables[name], moves))
    replication._reset_derived_stores(index)
    # the healed rank's HOSTED replica slots are as suspect as its
    # primary was — re-derive every mirror from the healed primaries so
    # the next failure finds coherent copies (drop the old ShardReplicas
    # first: replicate_index is idempotent per placement and would
    # otherwise keep the stale mirrors AND their cached failover views)
    index.replicas = None
    replication.replicate_index(index, replicas.r)
    for u, h in sorted(assignment.items()):
        obs.event("repair", source="replica", rank=u, holder=h)
    return index


def rank_rejoin(comms: Comms, health, rank: int, timeout_s: float = 30.0):
    """Flip `rank` healthy AFTER a verified mesh barrier: the barrier
    (PR 1's `health_barrier`, deadline + cancellable) must complete —
    proving the mesh, rejoining rank included, answers collectives —
    before the mask bit flips. Returns the updated health. A barrier
    timeout propagates as `HealthCheckTimeout` and the mask stays
    degraded (failover keeps serving)."""
    from raft_tpu.comms.resilience import health_barrier

    elapsed = health_barrier(comms, timeout_s=timeout_s)
    health.mark_healthy(rank)
    obs.event("rejoin", rank=int(rank), barrier_s=elapsed,
              coverage=health.coverage())
    return health


def heal(comms: Comms, health, index, checkpoint: Optional[str] = None,
         timeout_s: float = 30.0):
    """The whole heal loop in one call: `repair` every unhealthy rank's
    shard, then rejoin them behind ONE verified barrier (a single
    mesh-wide barrier already proves every rejoining rank answers
    collectives end to end — per-rank barriers would multiply heal
    latency by the failure count for no extra verification). Returns
    `(index, health)` — index possibly fresh (checkpoint rehydration),
    health fully healthy on success. In-flight searches keep full
    coverage throughout: failover serves replica copies until the
    moment the mask flips back."""
    from raft_tpu.comms.resilience import health_barrier

    # same controller-uniform-mask contract as repair() above
    if not health.degraded:  # raftlint: disable=collective-divergence
        return index, health
    index = repair(comms, health, index, checkpoint=checkpoint)
    dead = [int(x) for x in range(health.world) if not health.mask[x]]
    elapsed = health_barrier(comms, timeout_s=timeout_s)
    for u in dead:
        health.mark_healthy(u)
        obs.event("rejoin", rank=u, barrier_s=elapsed,
                  coverage=health.coverage())
    return index, health
