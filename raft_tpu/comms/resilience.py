"""Resilience layer for the comms stack: health-check barrier with
timeout, per-rank liveness masks, bootstrap retry, and degraded-mode
plumbing for the distributed searches.

The MNMG drivers (survey §5.8) assume every rank survives the whole job;
a serving path cannot. The model here: liveness is HOST knowledge — a
`RankHealth` mask over the mesh ranks, fed by the health-check barrier
and by fault drills (`core.faults`), consumed by the distributed
searches, which mask unhealthy ranks' candidates out of the merge and
report a `coverage` fraction (served shards / total) alongside results.
A masked rank's shard simply stops contributing; recall degrades by at
most its data share, the query never dies. Full recovery re-hydrates
the index from a checkpoint (`rehydrate`). On indexes carrying r-way
shard replicas (comms/replication.py) the degradation never shows at
all: searches fail over to the surviving replica holders bit-
identically, and comms/recovery.py repairs + rejoins the rank behind a
verified barrier.

Everything is single-program SPMD underneath, so "dead" is modeled as
"masked": an actually-crashed controller process still takes the XLA
collective down with it — at that blast radius the recovery unit is the
job (restart + `rehydrate`), not the query. The mask covers the larger
class of soft failures (stragglers past deadline, poisoned shards,
drained hosts) where the rank still answers collectives but must not
shape results.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, NamedTuple, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from raft_tpu import obs
from raft_tpu.core import faults
from raft_tpu.core.interruptible import TimeoutException, synchronize
from raft_tpu.core.logger import logger
from raft_tpu.comms.comms import Comms
from raft_tpu.comms.mnmg_common import _cached_wrapper, wrapper_key


class HealthCheckTimeout(RuntimeError):
    """The mesh-wide barrier missed its deadline: at least one rank never
    arrived, and single-controller SPMD cannot attribute which. Recovery
    is job-level (re-bootstrap / rehydrate), not mask-level."""


class DegradedSearchResult(NamedTuple):
    """A distributed search result under a liveness mask: `coverage` is
    served shards / total shards (1.0 == every shard answered, including
    shards served by replica failover); `repaired_ranks` lists unhealthy
    ranks whose shard a surviving replica holder served losslessly (see
    comms/replication.py) — they count as served in `coverage`."""

    values: jax.Array
    ids: jax.Array
    coverage: float
    repaired_ranks: Tuple[int, ...] = ()


@dataclasses.dataclass
class RankHealth:
    """Per-rank liveness mask over a comms mesh (True = healthy)."""

    mask: np.ndarray

    @classmethod
    def all_healthy(cls, world: int) -> "RankHealth":
        return cls(np.ones(int(world), bool))

    @property
    def world(self) -> int:
        return int(self.mask.size)

    def mark_unhealthy(self, rank: int) -> "RankHealth":
        return self._mark(rank, False)

    def mark_healthy(self, rank: int) -> "RankHealth":
        return self._mark(rank, True)

    def _mark(self, rank: int, healthy: bool) -> "RankHealth":
        rank = int(rank)
        changed = bool(self.mask[rank]) != healthy
        self.mask[rank] = healthy  # raftlint: disable=publication-safety  -- single-element bool store is atomic under the GIL; healing publishes via the maybe_heal CAS
        if changed:
            # health TRANSITIONS (not repeated marks) land on the obs
            # bus so a chaos drill leaves an auditable rank timeline
            obs.event("health", rank=rank, healthy=healthy,
                      coverage=self.coverage())
        return self

    def healthy_ranks(self) -> Tuple[int, ...]:
        return tuple(int(r) for r in np.flatnonzero(self.mask))

    @property
    def degraded(self) -> bool:
        return bool((~self.mask).any())

    def coverage(self) -> float:
        return float(self.mask.sum()) / float(self.mask.size)

    def live_f32(self) -> np.ndarray:
        """The (world,) float32 mask the SPMD search programs consume
        (an array argument, so flipping health never retraces)."""
        return self.mask.astype(np.float32)


class RetryExhausted(RuntimeError):
    """`retry_with_backoff` gave up (retry count or elapsed-time budget
    spent). Chains the final underlying failure as `__cause__`, so the
    last real error is never lost behind the retry machinery."""


def retry_with_backoff(
    fn: Callable,
    max_retries: int = 3,
    base_delay_s: float = 0.05,
    max_delay_s: float = 2.0,
    retry_on: tuple = (RuntimeError,),
    describe: str = "operation",
    jitter: float = 0.1,
    seed: Optional[int] = None,
    max_elapsed_s: Optional[float] = None,
):
    """Run `fn()` with exponential backoff: up to `max_retries` retries
    after the first failure, sleeping min(max_delay_s, base * 2^attempt)
    scaled by a SEEDED jitter factor in [1, 1+jitter) between attempts
    (deterministic: derived from (`seed` or $RAFT_TPU_FAULT_SEED,
    `describe`, this process's index) — a replayed chaos drill sleeps
    the identical schedule on each rank, while DIFFERENT ranks draw
    different schedules, so a pod restart's retries decorrelate instead
    of hammering the coordinator in lockstep). `max_elapsed_s` caps the
    WHOLE retry window: once the
    budget is spent no further attempt runs. Exhaustion (either budget)
    raises `RetryExhausted` chaining the final failure as `__cause__`;
    errors outside `retry_on` (bad coordinator address, wrong-kind
    checkpoint) propagate unchanged and immediately. Every retry lands a
    kind="retry" event on the obs bus so run reports show the transient
    failures that used to be invisible."""
    import zlib

    if seed is None:
        seed = int(os.environ.get(faults.ENV_SEED, "0"))
    try:
        pi = jax.process_index()
    except RuntimeError:
        pi = 0  # backend not up yet (mid-bootstrap retries)
    rng = np.random.default_rng(
        (int(seed), zlib.crc32(describe.encode()), int(pi)))
    t0 = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:
            elapsed = time.monotonic() - t0
            delay = min(max_delay_s, base_delay_s * (2 ** attempt))
            delay *= 1.0 + max(0.0, float(jitter)) * float(rng.random())
            exhausted_budget = (max_elapsed_s is not None
                                and elapsed + delay > max_elapsed_s)
            if attempt >= max_retries or exhausted_budget:
                raise RetryExhausted(
                    f"{describe} failed after {attempt + 1} attempt(s) "
                    f"in {elapsed:.3f}s"
                    + (" (max_elapsed_s budget spent)" if exhausted_budget
                       else "")
                    + f": {e}"
                ) from e
            obs.event("retry", describe=describe, attempt=attempt + 1,
                      max_retries=max_retries, delay_s=delay,
                      error=repr(e))
            logger.warning(
                "%s failed (%s); retry %d/%d in %.3fs",
                describe, e, attempt + 1, max_retries, delay,
            )
            time.sleep(delay)
            attempt += 1


def _barrier_fn(comms: Comms):
    """One compiled mesh-wide barrier program per mesh (a scalar psum —
    collectives are ordered, so its readiness fences every rank)."""

    def build():
        ac = comms.comms

        @jax.jit
        def run(x):
            def body(x):
                return ac.barrier(jnp.sum(x))

            return jax.shard_map(
                body, mesh=comms.mesh, in_specs=P(comms.axis),
                out_specs=P(), check_vma=False,
            )(x)

        return run

    return _cached_wrapper(wrapper_key("resilience_barrier", comms), build)


BARRIER_SITE = "resilience.barrier"


def health_barrier(comms: Comms, timeout_s: float = 30.0,
                   poll_interval_s: float = 0.001) -> float:
    """Mesh-wide barrier with a host-side deadline: dispatch one scalar
    collective and poll its readiness via `interruptible.synchronize`
    (cancellable from another thread, `TimeoutException` past the
    deadline — surfaced as `HealthCheckTimeout`). Returns the elapsed
    wall seconds. Injection site "resilience.barrier" adds straggler
    latency under an installed `FaultPlan`."""
    t0 = time.monotonic()
    faults.fault_point(BARRIER_SITE)
    # the deadline covers the WHOLE barrier including any straggler
    # latency spent at the injection site — an injected sleep past the
    # deadline must time out, not hand synchronize a fresh budget
    remaining = timeout_s - (time.monotonic() - t0)
    if remaining <= 0:
        raise HealthCheckTimeout(
            f"mesh barrier missed the {timeout_s}s deadline before dispatch"
        )
    token = _barrier_fn(comms)(comms.shard(np.ones(comms.get_size(), np.float32)))
    try:
        synchronize(token, poll_interval_s=poll_interval_s,
                    timeout_s=remaining)
    except TimeoutException as e:
        raise HealthCheckTimeout(
            f"mesh barrier missed the {timeout_s}s deadline: {e}"
        ) from e
    elapsed = time.monotonic() - t0
    if obs.enabled():
        # the one collective whose completion the host actually fences:
        # its wall latency is the mesh's observable health signal
        obs.histogram("comms.barrier.latency_s").observe(elapsed)
    return elapsed


def probe_health(comms: Comms, timeout_s: float = 30.0,
                 plan: Optional[faults.FaultPlan] = None) -> RankHealth:
    """Build the liveness mask for a mesh: ranks killed by the (installed
    or passed) fault plan are masked out, as are declared stragglers
    whose injected latency exceeds the deadline (they missed it by
    construction — no point actually sleeping it out); then the real
    barrier runs over the mesh with the remaining latency budget. A
    barrier timeout raises `HealthCheckTimeout` — an unattributable hang
    is a job-level failure, not a maskable one."""
    plan = plan if plan is not None else faults.active_plan()
    health = RankHealth.all_healthy(comms.get_size())
    if plan is not None:
        def scoped(rank: int):
            # rank=-1 faults scope to EVERY rank
            return range(health.world) if rank < 0 else (
                [rank] if rank < health.world else [])

        for f in plan.matching(BARRIER_SITE, "kill_rank"):
            for r in scoped(f.rank):
                health.mark_unhealthy(r)
        over_deadline = False
        for f in plan.matching(BARRIER_SITE, "slow_rank"):
            if f.latency_s > timeout_s:
                over_deadline = True
                for r in scoped(f.rank):
                    health.mark_unhealthy(r)
        if over_deadline:
            # the declared straggler would eat the whole deadline; its
            # miss is already recorded above — don't serve it by sleeping
            return health
    if plan is not None and faults.active_plan() is not plan:
        # an explicitly passed plan drives the barrier's injection site
        # too (sub-deadline straggler latency), matching the installed
        # case — "installed or passed" must behave identically
        with plan.install():
            health_barrier(comms, timeout_s=timeout_s)
    else:
        health_barrier(comms, timeout_s=timeout_s)
    return health


REHYDRATE_SITE = "mnmg_ckpt.load"


def rehydrate(comms: Comms, filename: str, max_retries: int = 3):
    """Checkpoint-based rank re-hydration: re-load a distributed index
    checkpoint (`ivf_flat_save[_local]` / `ivf_pq_save[_local]`) onto the
    recovered mesh and return `(index, RankHealth.all_healthy)` — the
    serving loop swaps the degraded index for the fresh one and resumes
    at full coverage. Flaky reads — injected chaos, transient I/O
    errors, a header torn by a concurrent writer (typed
    `SerializationError`, raw struct/JSON decode failures) — retry with
    backoff, surfacing as `RetryExhausted` (chaining the last cause)
    once the window is spent; a well-formed checkpoint of the wrong
    kind raises ValueError immediately without retrying."""
    import json
    import struct

    from raft_tpu.core.serialize import SerializationError, peek_meta
    from raft_tpu.comms import mnmg_ckpt

    def load_once():
        # the kind probe reads only the container header (multi-GB blobs
        # stay untouched) and sits INSIDE the retry so a transient read
        # failure of the probe itself also gets the backoff window
        kind = str(peek_meta(filename).get("kind", ""))
        if kind.startswith("mnmg_ivf_flat"):
            return mnmg_ckpt.ivf_flat_load(comms, filename)
        if kind.startswith("mnmg_ivf_pq"):
            return mnmg_ckpt.ivf_pq_load(comms, filename)
        if kind.startswith("mnmg_ivf_rabitq"):
            return mnmg_ckpt.ivf_rabitq_load(comms, filename)
        raise ValueError(f"not a distributed index checkpoint: kind={kind!r}")

    index = retry_with_backoff(
        load_once,
        max_retries=max_retries,
        # SerializationError covers torn/truncated headers AND checksum
        # failures the heal path could not repair; raw struct/json errors
        # remain for streams that bypass the typed wrappers
        retry_on=(faults.FaultInjected, OSError, SerializationError,
                  struct.error, json.JSONDecodeError),
        describe=f"rehydrate({filename!r})",
    )
    return index, RankHealth.all_healthy(comms.get_size())
