"""Distributed comms over XLA collectives + MNMG algorithms.

TPU-native equivalent of `cpp/include/raft/comms/` + `python/raft-dask/`
(survey §2.8, §2.15, §3.5, §5.8).
"""

from raft_tpu.comms.comms import (
    Comms,
    AxisComms,
    op_t,
    datatype_t,
    init_comms,
    local_handle,
    bootstrap_multihost,
)
from raft_tpu.comms import quantized
from raft_tpu.comms import resilience
from raft_tpu.comms.resilience import (
    DegradedSearchResult,
    HealthCheckTimeout,
    RankHealth,
    RetryExhausted,
    health_barrier,
    probe_health,
    rehydrate,
    retry_with_backoff,
)
from raft_tpu.comms import mnmg
from raft_tpu.comms import replication
from raft_tpu.comms import recovery
from raft_tpu.comms.replication import ReplicaPlacement, replicate_index
from raft_tpu.comms.recovery import RecoveryError, heal, rank_rejoin, repair

__all__ = [
    "Comms",
    "AxisComms",
    "op_t",
    "datatype_t",
    "init_comms",
    "local_handle",
    "bootstrap_multihost",
    "quantized",
    "mnmg",
    "resilience",
    "replication",
    "recovery",
    "DegradedSearchResult",
    "HealthCheckTimeout",
    "RankHealth",
    "RecoveryError",
    "ReplicaPlacement",
    "RetryExhausted",
    "health_barrier",
    "heal",
    "probe_health",
    "rank_rejoin",
    "rehydrate",
    "repair",
    "replicate_index",
    "retry_with_backoff",
]
