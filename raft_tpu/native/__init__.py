"""Native (C++) runtime bindings.

Builds cpp/raft_tpu_native.cc on first use (g++ -O3 -shared), caches the
.so next to the package, and exposes ctypes wrappers. Everything here has a
pure-Python fallback — the native path exists because the reference's host
runtime (list bookkeeping, serialization codec) is native C++, and because
at 100M-vector scale Python-loop packing dominates build time.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_LOCK = threading.Lock()
_TRIED = False

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "cpp", "raft_tpu_native.cc")
_SO = os.path.join(os.path.dirname(__file__), "_raft_tpu_native.so")


def _build() -> Optional[str]:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread", src, "-o", _SO],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _SO
    except Exception:
        return None


_ABI_VERSION = 4  # must match rt_abi_version() in cpp/raft_tpu_native.cc


def _is_stale(so: str, src: str) -> bool:
    try:
        return os.path.getmtime(so) < os.path.getmtime(src)
    except OSError:
        return True


def _load_and_bind(so: str) -> Optional[ctypes.CDLL]:
    """CDLL + symbol binding + ABI check; None on any mismatch (stale .so)."""
    try:
        lib = ctypes.CDLL(so)
        lib.rt_abi_version.restype = ctypes.c_uint32
        if lib.rt_abi_version() != _ABI_VERSION:
            return None
        _bind_symbols(lib)
        return lib
    except (OSError, AttributeError):
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        src = os.path.abspath(_SRC)
        lib = None
        if os.path.exists(_SO) and not _is_stale(_SO, src):
            lib = _load_and_bind(_SO)
        if lib is None and _build() is not None:
            lib = _load_and_bind(_SO)
        _LIB = lib
        return _LIB


def _bind_symbols(lib: ctypes.CDLL) -> None:
    lib.rt_max_list_size.restype = ctypes.c_int64
    lib.rt_max_list_size.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.rt_pack_lists.restype = ctypes.c_int32
    lib.rt_pack_lists.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.rt_write_container.restype = ctypes.c_int32
    lib.rt_write_container.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.rt_read_file.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.rt_read_file.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64)]
    lib.rt_free.restype = None
    lib.rt_free.argtypes = [ctypes.c_void_p]
    _i64p = ctypes.POINTER(ctypes.c_int64)
    lib.rt_coo_rows_to_indptr.restype = ctypes.c_int32
    lib.rt_coo_rows_to_indptr.argtypes = [_i64p, ctypes.c_int64, ctypes.c_int64, _i64p]
    lib.rt_coo_sort_perm.restype = ctypes.c_int32
    lib.rt_coo_sort_perm.argtypes = [_i64p, ctypes.c_int64, ctypes.c_int64, _i64p]
    lib.rt_make_monotonic.restype = ctypes.c_int32
    lib.rt_make_monotonic.argtypes = [
        _i64p, ctypes.c_int64, _i64p, _i64p, ctypes.c_int64, _i64p,
    ]
    _i32p = ctypes.POINTER(ctypes.c_int32)
    lib.rt_mst_linkage.restype = ctypes.c_int64
    lib.rt_mst_linkage.argtypes = [
        _i32p, _i32p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.c_int64, _i64p, ctypes.POINTER(ctypes.c_double), _i64p,
    ]
    lib.rt_cut_tree.restype = ctypes.c_int64
    lib.rt_cut_tree.argtypes = [
        _i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, _i32p,
    ]
    lib.rt_loader_open.restype = ctypes.c_void_p
    lib.rt_loader_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.rt_loader_acquire.restype = ctypes.c_int64
    lib.rt_loader_acquire.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
    ]
    lib.rt_loader_release.restype = ctypes.c_int32
    lib.rt_loader_release.argtypes = [ctypes.c_void_p]
    lib.rt_loader_close.restype = None
    lib.rt_loader_close.argtypes = [ctypes.c_void_p]


def available() -> bool:
    return get_lib() is not None


def pack_lists(labels: np.ndarray, n_lists: int, group: int = 32) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native slot-table packing; None if the native lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    l = np.ascontiguousarray(labels, dtype=np.int64)
    n = len(l)
    lp = l.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    max_sz = lib.rt_max_list_size(lp, n, n_lists, group)
    if max_sz < 0:
        return None
    row_ids = np.empty((n_lists, max_sz), np.int32)
    sizes = np.empty((n_lists,), np.int32)
    rc = lib.rt_pack_lists(
        lp, n, n_lists, max_sz,
        row_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if rc != 0:
        return None
    return row_ids, sizes


def write_container(path: str, header: bytes, bufs, nbytes, offsets) -> bool:
    lib = get_lib()
    if lib is None:
        return False
    n = len(bufs)
    arr_bufs = (ctypes.c_void_p * n)(*[b.ctypes.data_as(ctypes.c_void_p) for b in bufs])
    arr_nb = (ctypes.c_int64 * n)(*[int(x) for x in nbytes])
    arr_off = (ctypes.c_int64 * n)(*[int(x) for x in offsets])
    hdr = (ctypes.c_uint8 * len(header)).from_buffer_copy(header)
    rc = lib.rt_write_container(
        path.encode(), hdr, len(header), n,
        ctypes.cast(arr_bufs, ctypes.POINTER(ctypes.c_void_p)), arr_nb, arr_off,
    )
    return rc == 0


def read_file(path: str) -> Optional[bytes]:
    lib = get_lib()
    if lib is None:
        return None
    size = ctypes.c_int64(0)
    p = lib.rt_read_file(path.encode(), ctypes.byref(size))
    if not p:
        return None
    try:
        return ctypes.string_at(p, size.value)
    finally:
        lib.rt_free(p)


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def coo_rows_to_indptr(rows: np.ndarray, n_rows: int) -> Optional[np.ndarray]:
    """Native COO-rows -> CSR indptr; None if unavailable/invalid."""
    lib = get_lib()
    if lib is None:
        return None
    r = np.ascontiguousarray(rows, dtype=np.int64)
    indptr = np.empty(n_rows + 1, np.int64)
    if lib.rt_coo_rows_to_indptr(_i64(r), len(r), n_rows, _i64(indptr)) != 0:
        return None
    return indptr


def coo_sort_perm(rows: np.ndarray, n_rows: int) -> Optional[np.ndarray]:
    """Stable row-major ordering permutation for COO entries."""
    lib = get_lib()
    if lib is None:
        return None
    r = np.ascontiguousarray(rows, dtype=np.int64)
    perm = np.empty(len(r), np.int64)
    if lib.rt_coo_sort_perm(_i64(r), len(r), n_rows, _i64(perm)) != 0:
        return None
    return perm


def mst_linkage(src: np.ndarray, dst: np.ndarray, w: np.ndarray, n: int):
    """Native union-find dendrogram from weight-SORTED MST edges; returns
    (children (m,2) int64, deltas (m,) float64, sizes (m,) int64) or None.
    The caller sorts (numpy argsort is C-speed; the Python bottleneck was
    the merge loop — agglomerative.cuh host-side role)."""
    lib = get_lib()
    if lib is None or n <= 0:
        return None
    s = np.ascontiguousarray(src, dtype=np.int32)
    d = np.ascontiguousarray(dst, dtype=np.int32)
    ww = np.ascontiguousarray(w, dtype=np.float32)
    if not (len(s) == len(d) == len(ww)):
        return None  # C reads len(s) entries of each; keep fallback contract
    children = np.empty((max(n - 1, 1), 2), np.int64)
    deltas = np.empty(max(n - 1, 1), np.float64)
    sizes = np.empty(max(n - 1, 1), np.int64)
    _i32 = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    m = lib.rt_mst_linkage(
        _i32(s), _i32(d), ww.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        len(s), n, _i64(children.reshape(-1)),
        deltas.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), _i64(sizes),
    )
    if m < 0:
        return None
    return children[:m], deltas[:m], sizes[:m]


def cut_tree(children: np.ndarray, n: int, n_clusters: int) -> Optional[np.ndarray]:
    """Native flat cut of a children table; (n,) int32 labels or None."""
    lib = get_lib()
    if lib is None or n <= 0:
        return None
    ch = np.ascontiguousarray(children, dtype=np.int64)
    labels = np.empty(n, np.int32)
    k = lib.rt_cut_tree(
        _i64(ch.reshape(-1)), len(ch), n, int(n_clusters),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if k < 0:
        return None
    return labels


def make_monotonic(labels: np.ndarray) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native label densification; returns (dense_labels, sorted_unique)."""
    lib = get_lib()
    if lib is None:
        return None
    l = np.ascontiguousarray(labels, dtype=np.int64)
    n = len(l)
    out = np.empty(n, np.int64)
    uniq = np.empty(max(n, 1), np.int64)
    nu = ctypes.c_int64(0)
    if lib.rt_make_monotonic(_i64(l), n, _i64(out), _i64(uniq), len(uniq), ctypes.byref(nu)) != 0:
        return None
    return out, uniq[: nu.value].copy()
