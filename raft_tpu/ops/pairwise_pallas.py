"""Generic tiled pairwise-distance Pallas kernel (unexpanded metrics).

Reference parity: the shared GEMM-like tiling engine under all unexpanded
pairwise distances (`linalg/detail/contractions.cuh:61-290`,
`distance/detail/pairwise_matrix/kernel_sm60.cuh`) parameterized by
per-metric accumulate/epilogue functors (`distance/detail/distance_ops/`).

TPU design: one kernel; grid (m/bm, n/bn, k/kc) with k innermost and the
(bm, bn) output block as the revisited VMEM accumulator (the analogue of
the reference's register tile). Blocks are shaped for Mosaic's layout
rules — x (bm, 1, kc), y (1, bn, kc) with the k-chunk on the 128-wide lane
dimension — so the per-step term is one fully vectorized broadcast
(bm, bn, kc) followed by a lane reduction. No relayouts, no dynamic vector
indexing (both crash or crawl in Mosaic). Zero-padding of k is neutral for
every metric here (term(0,0) == reduce identity).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_KC = 128  # k-chunk = lane width


class MetricOp(NamedTuple):
    """Per-metric functors (distance_ops/*.cuh equivalent)."""

    term: Callable[[jax.Array, jax.Array], jax.Array]  # elementwise (a, b)
    reduce: str  # "sum" | "max" — over k, and to combine chunks
    finalize: Optional[Callable[[jax.Array], jax.Array]] = None


# Shared with the XLA path — one definition of the zero-guard semantics.
from raft_tpu.distance.pairwise import _canberra_term, _kl_term  # noqa: E402

METRIC_OPS = {
    "l1": MetricOp(lambda a, b: jnp.abs(a - b), "sum"),
    "linf": MetricOp(lambda a, b: jnp.abs(a - b), "max"),
    "l2_unexpanded": MetricOp(lambda a, b: (a - b) ** 2, "sum"),
    "l2_sqrt_unexpanded": MetricOp(lambda a, b: (a - b) ** 2, "sum", jnp.sqrt),
    "canberra": MetricOp(_canberra_term, "sum"),
    "kl_divergence": MetricOp(_kl_term, "sum"),
    # normalized inside pairwise_tiled (finalize depends on k)
    "hamming": MetricOp(lambda a, b: (a != b).astype(jnp.float32), "sum"),
}


def _make_kernel(op: MetricOp, k_steps: int):
    identity = 0.0 if op.reduce == "sum" else -jnp.inf
    chunk_reduce = jnp.sum if op.reduce == "sum" else jnp.max
    combine = jnp.add if op.reduce == "sum" else jnp.maximum

    def kernel(x_ref, y_ref, out_ref):
        kk = pl.program_id(2)

        @pl.when(kk == 0)
        def _():
            out_ref[:] = jnp.full(out_ref.shape, identity, jnp.float32)

        t = op.term(x_ref[:], y_ref[:])  # (bm, bn, kc) broadcast
        out_ref[:] = combine(out_ref[:], chunk_reduce(t, axis=-1))

        if op.finalize is not None:

            @pl.when(kk == k_steps - 1)
            def _():
                out_ref[:] = op.finalize(out_ref[:])

    return kernel


def _pad_axis(x: jax.Array, axis: int, mult: int) -> jax.Array:
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(jax.jit, static_argnames=("metric", "bm", "bn", "interpret"))
def pairwise_tiled(
    x: jax.Array,
    y: jax.Array,
    metric: str,
    *,
    bm: int = 128,
    bn: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """(m, n) distance matrix for an unexpanded metric via the Pallas engine.

    Caller guarantees `metric` is a METRIC_OPS key and blocks fit VMEM
    (see `fits_pallas`).
    """
    op = METRIC_OPS[metric]
    m, k = x.shape
    n = y.shape[0]
    if metric == "hamming":
        op = op._replace(finalize=lambda s: s / k)
    xp = _pad_axis(_pad_axis(x.astype(jnp.float32), 0, bm), 1, _KC)
    yp = _pad_axis(_pad_axis(y.astype(jnp.float32), 0, bn), 1, _KC)
    m_pad, k_pad = xp.shape
    n_pad = yp.shape[0]
    k_steps = k_pad // _KC

    out = pl.pallas_call(
        _make_kernel(op, k_steps),
        out_shape=jax.ShapeDtypeStruct((m_pad, n_pad), jnp.float32),
        grid=(m_pad // bm, n_pad // bn, k_steps),
        in_specs=[
            pl.BlockSpec(
                (bm, 1, _KC), lambda i, j, kk: (i, 0, kk), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, bn, _KC), lambda i, j, kk: (0, j, kk), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (bm, bn), lambda i, j, kk: (i, j), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(xp[:, None, :], yp[None, :, :])
    return out[:m, :n]


def fits_pallas(m: int, n: int, k: int, bm: int = 128, bn: int = 128) -> bool:
    """VMEM budget for one grid step: broadcast term + blocks + accumulator."""
    step_bytes = 4 * (bm * bn * _KC + bm * _KC + bn * _KC + bm * bn)
    return k >= 1 and step_bytes <= 10 * 1024 * 1024
