"""Fused L2 distance + argmin Pallas kernel — the k-means inner loop.

Reference parity: `raft::distance::fused_l2_nn` (distance/detail/
fused_l2_nn.cuh:129): one kernel computes, per row of x, the nearest row of
y without materializing the m x n distance matrix, reducing with atomic
KeyValuePair min operations.

TPU design: grid (m_blocks, n_blocks), n innermost. Each step does one MXU
matmul on *augmented* operands — x is extended with a ones column and y
with its squared norms, so [x, 1] @ [-2y, yn]^T = yn - 2 x.y lands straight
out of the systolic array and only the (bm, 1) x-norm broadcast remains on
the VPU. The (bm, 128) tile is folded into a *running per-lane best* kept
in the revisited output block — `better = d < best; best_idx = where(...)`.
No atomics: the j-loop is sequential per output block, so the reduction is
deterministic. A final (m, 128) -> (m,) lane reduction runs outside the
kernel in XLA (negligible).

The augmented-matmul trick is not just MXU efficiency: materializing
(1, bn) norm vectors inside the kernel trips Mosaic relayout bugs (see
ops/pairwise_pallas.py docstring); this formulation keeps every in-kernel
value >= 2-D with natural layouts.

Padded y rows are masked with +inf via the static n bound baked into the
kernel, so they can never win the argmin.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def _make_kernel(n: int, bn: int, precision):
    def kernel(xa_ref, ya_ref, best_d_ref, best_i_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            best_d_ref[:] = jnp.full(best_d_ref.shape, jnp.inf, jnp.float32)
            best_i_ref[:] = jnp.zeros(best_i_ref.shape, jnp.int32)

        xa = xa_ref[:]  # (bm, k+1) f32, last col = 1
        ya = ya_ref[:]  # (bn, k+1) f32, last col = |y|^2; rest = -2y
        xn = jnp.sum(xa[:, :-1] * xa[:, :-1], axis=1, keepdims=True)  # (bm, 1)
        cross = jax.lax.dot_general(
            xa,
            ya,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            # HIGHEST by default for f32 parity with the CUDA reference:
            # bf16 MXU passes flip ~1% of near-tie argmins on random data.
            precision=precision,
        )  # = yn - 2 x.y
        d = jnp.maximum(xn + cross, 0.0)  # (bm, bn)

        col = j * bn + jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
        d = jnp.where(col < n, d, jnp.inf)

        # Fold bn columns into the 128 running lanes.
        for c in range(bn // _LANES):
            dc = d[:, c * _LANES : (c + 1) * _LANES]
            ic = col[:, c * _LANES : (c + 1) * _LANES]
            better = dc < best_d_ref[:]
            best_i_ref[:] = jnp.where(better, ic, best_i_ref[:])
            best_d_ref[:] = jnp.where(better, dc, best_d_ref[:])

    return kernel


def _pad_rows(x: jax.Array, mult: int) -> jax.Array:
    pad = (-x.shape[0]) % mult
    return jnp.pad(x, ((0, pad), (0, 0))) if pad else x


@functools.partial(
    jax.jit, static_argnames=("bm", "bn", "sqrt", "interpret", "precision")
)
def fused_l2_argmin_pallas(
    x: jax.Array,
    y: jax.Array,
    *,
    bm: int = 256,
    bn: int = 128,
    sqrt: bool = False,
    interpret: bool = False,
    precision=jax.lax.Precision.HIGHEST,
) -> Tuple[jax.Array, jax.Array]:
    """(min_distance, argmin) of expanded L2 over rows of y, per row of x.

    On the compiled path bn is pinned to 128: the multi-chunk lane fold
    (bn > 128) trips a Mosaic strided-slice bug on v5e; one lane-width per
    grid step is also the best-pipelined shape in practice.
    """
    if not interpret:
        bn = _LANES
    m, k = x.shape
    n = y.shape[0]
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    ones = jnp.ones((m, 1), jnp.float32)
    yn = jnp.sum(yf * yf, axis=1, keepdims=True)
    xa = _pad_rows(jnp.concatenate([xf, ones], axis=1), bm)
    ya = _pad_rows(jnp.concatenate([-2.0 * yf, yn], axis=1), bn)
    m_pad, n_pad = xa.shape[0], ya.shape[0]
    ka = xa.shape[1]

    best_d, best_i = pl.pallas_call(
        _make_kernel(n, bn, precision),
        out_shape=(
            jax.ShapeDtypeStruct((m_pad, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((m_pad, _LANES), jnp.int32),
        ),
        grid=(m_pad // bm, n_pad // bn),
        in_specs=[
            pl.BlockSpec((bm, ka), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, ka), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((bm, _LANES), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, _LANES), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
        ),
        interpret=interpret,
    )(xa, ya)

    # Lane reduction with lowest-index tie-break: jnp.argmin over lanes would
    # pick the lowest tied *lane*, whose stored column can be higher than
    # another tied lane's — diverging from the XLA path on duplicate rows.
    minv = jnp.min(best_d, axis=1, keepdims=True)  # (m_pad, 1)
    tied = jnp.where(best_d == minv, best_i, jnp.iinfo(jnp.int32).max)
    idx = jnp.min(tied, axis=1)[:m].astype(jnp.int32)
    dist = minv[:m, 0]
    if sqrt:
        dist = jnp.sqrt(dist)
    return dist, idx


def fits_pallas(m: int, n: int, k: int, bm: int = 256, bn: int = 128) -> bool:
    block_bytes = 4 * ((bm + bn) * (k + 1) + bm * bn + 2 * bm * _LANES)
    return n >= 1 and block_bytes <= 8 * 1024 * 1024
