"""Pallas TPU kernels for the performance-critical primitives.

The TPU analogue of the reference's hand-written CUDA kernels: where RAFT
uses the smem-tiled contractions engine (linalg/detail/contractions.cuh) and
per-metric op functors (distance/detail/distance_ops/*.cuh), we use Pallas
kernels with VMEM block tiling; where it uses fused distance+argmin with
atomic KeyValuePair reductions (detail/fused_l2_nn.cuh:129), we keep a
running per-lane best in the revisited output block (deterministic, no
atomics).

Every kernel has a pure-XLA fallback; `use_pallas()` decides the default
(TPU backend only). Tests exercise the kernels via interpret mode on CPU.
"""

from __future__ import annotations

import os

import jax

_FORCE = os.environ.get("RAFT_TPU_FORCE_PALLAS", "").lower() in ("1", "true")
_DISABLE = os.environ.get("RAFT_TPU_DISABLE_PALLAS", "").lower() in ("1", "true")

# Test hooks: force the dispatch decision / run kernels interpreted on CPU.
_OVERRIDE = None  # None = auto; True/False = forced
_INTERPRET = False


def set_pallas_override(enabled) -> None:
    """Force use_pallas() to `enabled` (None restores auto-detection).

    Clears jit caches: the dispatch decision is baked into traces at trace
    time, so cached traces for already-seen shapes would otherwise keep the
    old routing.
    """
    global _OVERRIDE
    _OVERRIDE = enabled
    jax.clear_caches()


def set_pallas_interpret(interpret: bool) -> None:
    """Run dispatched Pallas kernels in interpreter mode (CPU testing)."""
    global _INTERPRET
    _INTERPRET = interpret
    jax.clear_caches()


def interpret_mode() -> bool:
    return _INTERPRET


def use_pallas() -> bool:
    """True when Pallas kernels should be the default execution path."""
    if _OVERRIDE is not None:
        return _OVERRIDE
    if _DISABLE:
        return False
    if _FORCE:
        return True
    # device-kind based: the tunneled chip registers platform "axon", so a
    # bare default_backend()=="tpu" check would disable Pallas on real TPU
    from raft_tpu.core.config import is_tpu_backend

    return is_tpu_backend()


from raft_tpu.ops.pairwise_pallas import pairwise_tiled  # noqa: E402
from raft_tpu.ops.fused_l2_argmin import fused_l2_argmin_pallas  # noqa: E402

__all__ = ["use_pallas", "pairwise_tiled", "fused_l2_argmin_pallas"]
