"""Exact top-k via in-VMEM counting select — the Pallas select_k engine.

Reference parity: `matrix::detail::select_radix` (matrix/detail/
select_radix.cuh:170) finds the k-th smallest by multi-pass digit
histograms so candidate rows never need a full sort; this kernel is the
TPU re-design of that idea. A GPU radix pass narrows via 2048-bin
histograms + atomics; TPU has no scatter, so histograms cost
O(L * bins) vector compares. Counting select replaces the histogram
with a 32-step *bit-fixing binary search* on the order-preserving
uint32 image of the row — each step is one full-row compare+popcount
(2L VPU ops), so threshold finding costs 64L ops instead of the
histogram's 512L, and the row stays resident in VMEM for all 32 steps
(one HBM read total, vs a sort's multiple round trips — the reason
this wins at large L).

Pipeline per grid step (one row):
  1. monotone map: f32 -> uint32 preserving order (sign-flip trick);
  2. 32-iteration bit-fix of T = k-th smallest key (MSB to LSB,
     invariant count(key < P) < k <= count(key < P + 2^(b+1)));
  3. rank: pos = rank among (key < T) plus tie-rank among (key == T)
     offset by count_lt — row-major cumsum via lane cumsum + sublane
     offset; exactly k elements get pos < k (exact select, ties by
     index order, matching select_k's stable-tie contract);
  4. extraction: k-iteration fold keeping (1, k_pad) value/index rows
     via lane one-hots (no dynamic stores, no relayout).

Output is UNSORTED (position order = original index order of the
selected elements); callers finish with a tiny (B, k) top_k — the same
final-merge shape the two-phase path already uses.

Compiled-path status: validated in interpret mode (CPU tests); first
on-chip Mosaic compile may need block-shape adjustment. Opt-in via
select_k(..., strategy="counting") and raced by
bench/bench_select_k_strategies.py.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_LANES = 128


def _monotone_u32(x: jax.Array) -> jax.Array:
    """Order-preserving f32 -> uint32 map, ascending under XLA's sort
    TOTAL order (-0.0 strictly before +0.0) — the same order lax.top_k
    uses, so the counting engine and the XLA select_k paths rank signed
    zeros identically (verified empirically: select_min prefers -0.0 on
    both)."""
    i = lax.bitcast_convert_type(x, jnp.int32)
    flipped = jnp.where(i < 0, ~i, i | jnp.int32(-2147483648))
    return lax.bitcast_convert_type(flipped, jnp.uint32)


def _make_kernel(L: int, k: int, k_pad: int):
    Lf = L // _LANES

    def kernel(vals_ref, outv_ref, outi_ref):
        x = vals_ref[0]  # (Lf, _LANES) row-major tile
        key = _monotone_u32(x)

        # ---- bit-fixing search for T = k-th smallest key ----
        def fix_bit(i, prefix):
            b = 31 - i
            mid = prefix | (jnp.uint32(1) << b)
            c = jnp.sum((key < mid).astype(jnp.int32))
            return jnp.where(c >= k, prefix, mid)

        T = lax.fori_loop(0, 32, fix_bit, jnp.uint32(0))
        lt = key < T
        eq = key == T
        n_lt = jnp.sum(lt.astype(jnp.int32))

        # ---- exact stable positions (row-major order) ----
        def rank(mask):
            m = mask.astype(jnp.int32)
            lane_cs = jnp.cumsum(m, axis=1)
            row_tot = lane_cs[:, -1:]
            row_off = jnp.cumsum(row_tot, axis=0) - row_tot
            return row_off + lane_cs - m  # exclusive rank among mask

        pos = jnp.where(
            lt, rank(lt), jnp.where(eq, n_lt + rank(eq), jnp.int32(L))
        )
        sel = pos < k  # exactly k elements

        gidx = (
            jax.lax.broadcasted_iota(jnp.int32, (Lf, _LANES), 0) * _LANES
            + jax.lax.broadcasted_iota(jnp.int32, (Lf, _LANES), 1)
        )
        slot = jax.lax.broadcasted_iota(jnp.int32, (1, k_pad), 1)

        # ---- extraction: fold the k selected elements into lane slots ----
        def extract(j, carry):
            ov, oi = carry
            m = sel & (pos == j)
            vj = jnp.sum(jnp.where(m, x, 0.0))
            ij = jnp.sum(jnp.where(m, gidx, 0))
            hot = slot == j
            ov = jnp.where(hot, vj, ov)
            oi = jnp.where(hot, ij, oi)
            return ov, oi

        ov0 = jnp.full((1, k_pad), jnp.inf, jnp.float32)
        oi0 = jnp.zeros((1, k_pad), jnp.int32)
        ov, oi = lax.fori_loop(0, k, extract, (ov0, oi0))
        outv_ref[0] = ov
        outi_ref[0] = oi

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def counting_select_min(
    vals: jax.Array, k: int, interpret: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """Exact k smallest per row of (B, L) f32; returns ((B, k) vals,
    (B, k) int32 row-local indices), UNSORTED (original index order,
    stable ties). L must be a multiple of 128; pad with +inf and keep
    k <= the unpadded length. Callers sort the (B, k) result if they
    need best-first order (select_k does)."""
    B, L = vals.shape
    if L % _LANES:
        raise ValueError(f"row length {L} must be a multiple of {_LANES}")
    if not 0 < k <= L:
        raise ValueError(f"k={k} out of range for row length {L}")
    k_pad = max(_LANES, -(-k // _LANES) * _LANES)
    # 3-D layout so every block's minor-two dims meet Mosaic's (8, 128)
    # divisibility contract (the flat (1, L) / (1, k_pad) blocks were
    # rejected on the first on-chip compile: sublane block of 1 row with
    # B > 1). The row tile arrives pre-shaped (Lf, _LANES); outputs ride
    # a singleton middle axis whose block spans it exactly.
    Lf = L // _LANES
    outv, outi = pl.pallas_call(
        _make_kernel(L, k, k_pad),
        grid=(B,),
        in_specs=[pl.BlockSpec((1, Lf, _LANES), lambda i: (i, 0, 0))],
        out_specs=(
            pl.BlockSpec((1, 1, k_pad), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, k_pad), lambda i: (i, 0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, 1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((B, 1, k_pad), jnp.int32),
        ),
        interpret=interpret,
    )(vals.reshape(B, Lf, _LANES))
    return outv[:, 0, :k], outi[:, 0, :k]


def fits_counting(B: int, L: int, k: int) -> bool:
    """VMEM envelope for one grid step: the f32 row + its uint32 image
    + int32 rank/index tiles (~4 row-sized live tensors)."""
    return (
        L % _LANES == 0
        and k <= 256
        and 16 * L <= 10 * 1024 * 1024
    )
