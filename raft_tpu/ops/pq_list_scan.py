"""Fused PQ list-scan Pallas kernel: score one list chunk + bin-reduce.

Reference parity: the IVF-PQ scoring kernel (`compute_similarity_kernel`,
detail/ivf_pq_search.cuh:611) fuses LUT scoring with an optional in-kernel
warpsort top-k queue so per-candidate scores never leave the SM. The XLA
list-major engine (neighbors/ivf_pq.py `_search_impl_recon8_listmajor`)
must instead materialize each (chunk, max_list) score tile in HBM for
`lax.approx_min_k` — at bench shape that round-trip is ~10x the byte
volume of the code stream it scores. This kernel is the TPU analogue of
the reference's fused queue:

  grid = (n_chunks,); per step, scalar-prefetched chunk->list ids index
  the int8 reconstruction store DIRECTLY (no gather copy of codes), one
  MXU matmul scores the chunk's queries against the whole list, and the
  (chunk, L) scores fold on the VPU into 256 per-lane running bests
  (the PartialReduce/approx_min_k bin trick, or the reference's
  `warp_sort_filtered` in spirit) — only (chunk, 256) candidates reach
  HBM (~11x fewer bytes than the score tile).

Scale handling: the caller folds the int8 store's per-dim scale into the
query residuals, so the kernel consumes raw int8 codes with no dequant
multiply. Invalid/padded slots arrive pre-masked to +inf in the `base`
row operand. The selected bins are exact minima of their lane-column
class; a (chunk, 256) -> top-k pass outside the kernel (tiny) finishes
the per-chunk trim. Like approx_min_k at recall_target~0.99, bin
collisions can drop a true top-k member — the engine's exact final merge
bounds the effect to the same degree as the default trim path.

Compiled-path status: validated in interpret mode (CPU tests); first
on-chip Mosaic compile may need block-shape adjustments — the engine
flag (`SearchParams.trim_engine`) defaults to the XLA trim.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_BINS = 2 * _LANES  # two interleaved running-best banks -> 256 candidates


def _make_kernel(L: int, inner_product: bool):
    n_folds = L // _LANES

    def kernel(lof_ref, qres_ref, r8_ref, base_ref, vals_ref, idx_ref):
        # lof_ref: scalar-prefetch (ncb,) int32 — consumed by index_maps
        q = qres_ref[0]  # (chunk, rot) f32, per-dim scale folded in
        r = r8_ref[0].astype(jnp.bfloat16)  # (L, rot)
        base = base_ref[0]  # (1, L) f32: rnorm (+inf on invalid slots)
        dots = jax.lax.dot_general(
            q.astype(jnp.bfloat16),
            r,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (chunk, L)
        if inner_product:
            scores = base - dots  # base=0 valid; minimize -dot
        else:
            scores = base - 2.0 * dots  # + |q-c|^2 const added outside

        chunk = scores.shape[0]
        inf = jnp.float32(jnp.inf)
        b0v = jnp.full((chunk, _LANES), inf, jnp.float32)
        b0i = jnp.zeros((chunk, _LANES), jnp.int32)
        b1v = jnp.full((chunk, _LANES), inf, jnp.float32)
        b1i = jnp.zeros((chunk, _LANES), jnp.int32)
        col = jax.lax.broadcasted_iota(jnp.int32, (chunk, _LANES), 1)
        for c in range(n_folds):
            sc = scores[:, c * _LANES : (c + 1) * _LANES]
            ic = col + c * _LANES
            if c % 2 == 0:
                better = sc < b0v
                b0i = jnp.where(better, ic, b0i)
                b0v = jnp.where(better, sc, b0v)
            else:
                better = sc < b1v
                b1i = jnp.where(better, ic, b1i)
                b1v = jnp.where(better, sc, b1v)
        vals_ref[0] = jnp.concatenate([b0v, b1v], axis=1)
        idx_ref[0] = jnp.concatenate([b0i, b1i], axis=1)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("inner_product", "interpret")
)
def pq_list_scan(
    lof: jax.Array,      # (ncb,) int32 chunk -> list id
    qres_s: jax.Array,   # (ncb, chunk, rot) f32 query residuals * scale
    recon8: jax.Array,   # (n_lists, L, rot) int8 codes or f32/bf16 raw
                         #   vectors (IVF-Flat), L % 128 == 0
    base: jax.Array,     # (n_lists, 1, L) f32 per-slot additive base
                         #   L2: rnorm, +inf for invalid; IP: 0 / +inf
    inner_product: bool = False,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (vals, idx): (ncb, chunk, 256) best-per-bin scores and the
    in-list slot of each, minimizing. Callers add per-query constants and
    finish with an exact top-k over the 256 bins. Works for any store the
    kernel can cast to bf16 — int8 PQ reconstructions or raw IVF-Flat
    vectors."""
    ncb, chunk, rot = qres_s.shape
    n_lists, L, _ = recon8.shape
    if L % _LANES or L < _BINS:
        raise ValueError(f"list length {L} must be a multiple of {_LANES} and >= {_BINS}")

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ncb,),
        in_specs=[
            pl.BlockSpec((1, chunk, rot), lambda i, lof: (i, 0, 0)),
            pl.BlockSpec((1, L, rot), lambda i, lof: (lof[i], 0, 0)),
            pl.BlockSpec((1, 1, L), lambda i, lof: (lof[i], 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, chunk, _BINS), lambda i, lof: (i, 0, 0)),
            pl.BlockSpec((1, chunk, _BINS), lambda i, lof: (i, 0, 0)),
        ),
    )
    return pl.pallas_call(
        _make_kernel(L, inner_product),
        out_shape=(
            jax.ShapeDtypeStruct((ncb, chunk, _BINS), jnp.float32),
            jax.ShapeDtypeStruct((ncb, chunk, _BINS), jnp.int32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(lof, qres_s, recon8, base)


def lane_padded(width: int) -> int:
    """The slot-axis width the kernel's lane contract requires: a multiple
    of the 128-lane register width, with at least _BINS slots (so the two
    candidate banks fill). Shared by every caller that pads a store."""
    return max(_BINS, -(-width // _LANES) * _LANES)


def fits_pallas(chunk: int, L: int, rot: int, store_itemsize: int = 1) -> bool:
    """VMEM envelope for one grid step (f32 scores dominate).
    `store_itemsize` is the per-element width of the list store (1 for
    int8 PQ reconstructions, 4 for raw f32 IVF-Flat vectors)."""
    step_bytes = (
        4 * chunk * L + store_itemsize * L * rot + 4 * chunk * rot + 8 * chunk * _BINS
    )
    return L % _LANES == 0 and L >= _BINS and step_bytes <= 10 * 1024 * 1024
