"""Fused PQ list-scan Pallas kernel: score one list chunk + bin-reduce.

Reference parity: the IVF-PQ scoring kernel (`compute_similarity_kernel`,
detail/ivf_pq_search.cuh:611) fuses LUT scoring with an optional in-kernel
warpsort top-k queue so per-candidate scores never leave the SM. The XLA
list-major engine (neighbors/ivf_pq.py `_search_impl_recon8_listmajor`)
must instead materialize each (chunk, max_list) score tile in HBM for
`lax.approx_min_k` — at bench shape that round-trip is ~10x the byte
volume of the code stream it scores. This kernel is the TPU analogue of
the reference's fused queue:

  grid = (n_chunks,); per step, scalar-prefetched chunk->list ids index
  the int8 reconstruction store DIRECTLY (no gather copy of codes), one
  MXU matmul scores the chunk's queries against the whole list, and the
  (chunk, L) scores fold on the VPU into 256 per-lane bins keeping the
  best AND second-best each (the PartialReduce/approx_min_k bin trick,
  or the reference's `warp_sort_filtered` in spirit) — only
  (chunk, 512) candidates reach HBM (~5-10x fewer bytes than the score
  tile at typical L).

Scale handling: the caller folds the int8 store's per-dim scale into the
query residuals, so the kernel consumes raw int8 codes with no dequant
multiply. Invalid/padded slots arrive pre-masked to +inf in the `base`
row operand. The selected candidates are the exact two minima of each
lane-column class; a (chunk, 512) -> top-k pass outside the kernel
(tiny) finishes the per-chunk trim. Only 3+ true top-k members landing
in one bin can drop a candidate — a strictly smaller loss term than
approx_min_k's — and the engine's exact final merge bounds the effect.

Compiled-path status: validated in interpret mode (CPU tests); first
on-chip Mosaic compile may need block-shape adjustments — the engine
flag (`SearchParams.trim_engine`) defaults to the XLA trim. The known
highest-risk shape property is the non-lane-aligned contracting dim
(rot=96 at bench geometry): if Mosaic rejects it,
RAFT_TPU_PALLAS_ROT_PAD=1 (or tuned `pallas_rot_pad`) zero-pads rot to
128 lanes, bit-identically (tests/test_pallas_ops.py), so the rescue is
one flag, not a rewrite.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_BINS = 2 * _LANES  # two interleaved lane banks; also the kernel's k cap
_CANDS = 2 * _BINS  # best + second-best per (lane, bank) -> 512 candidates


def rot_pad_enabled() -> bool:
    """Opt-in lane padding of the contracting (rot) dim — the one-flag
    fallback if the first Mosaic compile rejects a non-128-multiple rot.
    Env wins in BOTH directions (an explicit 0/false overrides a
    committed tuned key, so A/B debugging stays possible); otherwise the
    tuned key decides. Read at trace time (flip + jax.clear_caches() to
    retrace)."""
    import os

    env = os.environ.get("RAFT_TPU_PALLAS_ROT_PAD", "").lower()
    if env in ("1", "true", "yes"):
        return True
    if env in ("0", "false", "no"):
        return False
    from raft_tpu.core import tuned

    return bool(tuned.get("pallas_rot_pad", False))


def _pack_scores(scores, fold_ids):
    """Monotone f32 -> int32 packing: high 16 bits carry the bf16-coarse
    order-preserving image of the score, low 16 the fold id, xor'd so
    SIGNED min == unsigned packed order. Collapses score ties at ~2^-8
    relative precision — the same noise class as the measured-winning
    bf16 trim (internal_distance_dtype hint, 2026-08-01 ladder)."""
    i = jax.lax.bitcast_convert_type(scores, jnp.int32)
    # order-preserving uint32 image (select_counting's sign-flip trick)
    u = jnp.where(i < 0, ~i, i | jnp.int32(-2147483648))
    hi = u & jnp.int32(-65536)  # keep high 16 bits (order coarsened)
    return (hi | fold_ids) ^ jnp.int32(-2147483648)


def fold_variant() -> str:
    """The fold implementation the engines should use: the measured
    tuned key (`pallas_fold`, written by bench/bench_pallas_scan.py
    --apply on chip) when it names a known variant, else "exact". The
    one whitelist shared by every engine call site."""
    from raft_tpu.core import tuned

    v = tuned.get("pallas_fold", "exact")
    return v if v in ("exact", "packed") else "exact"


def _unpack_scores(packed):
    """Inverse of _pack_scores: (f32 LOWER bound of the score's bf16
    band — truncation rounds toward -inf in both sign branches, so the
    decoded value is always <= the true score — and the fold id)."""
    p = packed ^ jnp.int32(-2147483648)
    fold = p & jnp.int32(0xFFFF)
    u = p & jnp.int32(-65536)
    i = jnp.where(u < 0, u & jnp.int32(2147483647), ~u)
    # NB: u<0 in SIGNED int32 means the uint32 high bit is set = the
    # original f32 was non-negative (the flip set it); recover exactly.
    return jax.lax.bitcast_convert_type(i, jnp.float32), fold


def _make_kernel_packed(L: int, inner_product: bool, q_int8: bool = False):
    """Packed-fold variant: ~3 VPU ops per fold (streaming two-min on the
    int32-packed scores) instead of the exact fold's ~11, at bf16-coarse
    trim precision. Same output contract as the exact kernel; candidate
    VALUES are the bf16-band lower bounds (<= the true score), exact
    re-ranking happens in the engine's final merge as before."""
    n_folds = L // _LANES

    def kernel(lof_ref, qres_ref, r8_ref, base_ref, *rest):
        if q_int8:
            rs_ref, vals_ref, idx_ref = rest
        else:
            vals_ref, idx_ref = rest
        q = qres_ref[0]
        base = base_ref[0]
        if q_int8:
            dots = jax.lax.dot_general(
                q,
                r8_ref[0],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            ).astype(jnp.float32) * rs_ref[0]
        else:
            dots = jax.lax.dot_general(
                q.astype(jnp.bfloat16),
                r8_ref[0].astype(jnp.bfloat16),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        scores = base - dots if inner_product else base - 2.0 * dots

        chunk = scores.shape[0]
        fold_row = jax.lax.broadcasted_iota(jnp.int32, (1, L), 1) // _LANES
        packed = _pack_scores(scores, jnp.broadcast_to(fold_row, scores.shape))
        top = jnp.int32(2147483647)
        banks = []
        for b in range(2):
            m1 = jnp.full((chunk, _LANES), top, jnp.int32)
            m2 = jnp.full((chunk, _LANES), top, jnp.int32)
            for c in range(b, n_folds, 2):
                xcol = packed[:, c * _LANES : (c + 1) * _LANES]
                m2 = jnp.minimum(m2, jnp.maximum(m1, xcol))
                m1 = jnp.minimum(m1, xcol)
            banks.append((m1, m2))
        (a1, a2), (c1, c2) = banks
        allp = jnp.concatenate([a1, c1, a2, c2], axis=1)  # (chunk, _CANDS)
        v, fold = _unpack_scores(allp)
        lane = jax.lax.broadcasted_iota(jnp.int32, (chunk, _CANDS), 1) % _LANES
        idx = fold * _LANES + lane
        # never-filled slots carry fold id 0xFFFF -> out-of-range idx;
        # mask value to +inf so the engine's merge drops them (matches
        # the exact kernel's +inf padding semantics)
        invalid = fold >= n_folds
        vals_ref[0] = jnp.where(invalid, jnp.float32(jnp.inf), v)
        idx_ref[0] = jnp.where(invalid, 0, idx)

    return kernel


def _make_kernel(L: int, inner_product: bool, q_int8: bool = False):
    n_folds = L // _LANES

    def kernel(lof_ref, qres_ref, r8_ref, base_ref, *rest):
        # lof_ref: scalar-prefetch (ncb,) int32 — consumed by index_maps
        if q_int8:
            rs_ref, vals_ref, idx_ref = rest
        else:
            vals_ref, idx_ref = rest
        q = qres_ref[0]  # (chunk, rot): f32 scale-folded, or int8 symmetric
        base = base_ref[0]  # (1, L) f32: rnorm (+inf on invalid slots)
        if q_int8:
            # symmetric int8 x int8 -> int32 at the MXU's doubled int8
            # rate; per-row dequant scale applied on the VPU
            dots = jax.lax.dot_general(
                q,
                r8_ref[0],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            ).astype(jnp.float32) * rs_ref[0]  # (chunk, L) * (chunk, 1)
        else:
            dots = jax.lax.dot_general(
                q.astype(jnp.bfloat16),
                r8_ref[0].astype(jnp.bfloat16),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (chunk, L)
        if inner_product:
            scores = base - dots  # base=0 valid; minimize -dot
        else:
            scores = base - 2.0 * dots  # + |q-c|^2 const added outside

        chunk = scores.shape[0]
        inf = jnp.float32(jnp.inf)
        col = jax.lax.broadcasted_iota(jnp.int32, (chunk, _LANES), 1)
        # two interleaved banks, each keeping the best AND second-best per
        # lane: candidates lost to bin collisions need 3+ of a list's true
        # top-k in one (lane, bank) class instead of 2 — the dominant
        # recall-loss term of the trim drops from ~C(k,2)/256 to
        # ~C(k,3)/256^2 for a handful of extra VPU selects per fold.
        banks = []
        for b in range(2):
            bv1 = jnp.full((chunk, _LANES), inf, jnp.float32)
            bi1 = jnp.zeros((chunk, _LANES), jnp.int32)
            bv2 = jnp.full((chunk, _LANES), inf, jnp.float32)
            bi2 = jnp.zeros((chunk, _LANES), jnp.int32)
            for c in range(b, n_folds, 2):
                sc = scores[:, c * _LANES : (c + 1) * _LANES]
                ic = col + c * _LANES
                best = sc < bv1
                second = (~best) & (sc < bv2)
                # demote the old best where a new best arrives
                bv2 = jnp.where(best, bv1, jnp.where(second, sc, bv2))
                bi2 = jnp.where(best, bi1, jnp.where(second, ic, bi2))
                bv1 = jnp.where(best, sc, bv1)
                bi1 = jnp.where(best, ic, bi1)
            banks.append((bv1, bi1, bv2, bi2))
        (a1v, a1i, a2v, a2i), (c1v, c1i, c2v, c2i) = banks
        vals_ref[0] = jnp.concatenate([a1v, c1v, a2v, c2v], axis=1)
        idx_ref[0] = jnp.concatenate([a1i, c1i, a2i, c2i], axis=1)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("inner_product", "interpret", "fold")
)
def pq_list_scan(
    lof: jax.Array,      # (ncb,) int32 chunk -> list id
    qres_s: jax.Array,   # (ncb, chunk, rot) f32 query residuals * scale,
                         #   or int8 symmetric rows when q_scale is given
    recon8: jax.Array,   # (n_lists, L, rot) int8 codes or f32/bf16 raw
                         #   vectors (IVF-Flat), L % 128 == 0
    base: jax.Array,     # (n_lists, 1, L) f32 per-slot additive base
                         #   L2: rnorm, +inf for invalid; IP: 0 / +inf
    inner_product: bool = False,
    interpret: bool = False,
    q_scale: Optional[jax.Array] = None,  # (ncb, chunk, 1) f32 per-row
                         #   dequant scale -> int8 x int8 MXU scoring
    fold: str = "exact",  # "exact" (f32 fold) | "packed" (bf16-coarse,
                         #   ~3x fewer VPU ops/fold; bench_pallas_scan
                         #   races the two on chip)
) -> Tuple[jax.Array, jax.Array]:
    """Returns (vals, idx): (ncb, chunk, 512) best+second-best-per-bin
    scores and the in-list slot of each, minimizing. Callers add per-query
    constants and finish with an exact top-k over the candidates. Works
    for any store the kernel can cast to bf16 — int8 PQ reconstructions
    or raw IVF-Flat vectors. With `q_scale`, `qres_s` must be int8 and
    the store int8: the matmul runs int8 x int8 -> int32 (the MXU's
    doubled int8 rate) with the per-row scale applied in-kernel."""
    ncb, chunk, rot = qres_s.shape
    n_lists, L, _ = recon8.shape
    if L % _LANES or L < _BINS:
        raise ValueError(f"list length {L} must be a multiple of {_LANES} and >= {_BINS}")
    q_int8 = q_scale is not None
    if q_int8 and (qres_s.dtype != jnp.int8 or recon8.dtype != jnp.int8):
        raise ValueError("q_scale requires int8 queries and an int8 store")
    if rot % _LANES and rot_pad_enabled():
        # First-compile rescue (VERDICT r3 #2 risk): the contracting dim
        # (rot) is not lane-aligned at the bench geometry (96). Mosaic is
        # expected to mask the ragged lane tile, but if the first on-chip
        # compile rejects it, RAFT_TPU_PALLAS_ROT_PAD=1 (or tuned key
        # pallas_rot_pad) zero-pads rot to the 128-lane width instead of
        # a kernel rewrite. Zero lanes contribute 0 to every dot, so
        # results are bit-identical; the pad materializes a store copy
        # per call, so if a chip session ends up needing this, move the
        # padding to store-build time before benching.
        pad = _LANES - rot % _LANES
        qres_s = jnp.pad(qres_s, ((0, 0), (0, 0), (0, pad)))
        recon8 = jnp.pad(recon8, ((0, 0), (0, 0), (0, pad)))
        rot += pad

    in_specs = [
        pl.BlockSpec((1, chunk, rot), lambda i, lof: (i, 0, 0)),
        pl.BlockSpec((1, L, rot), lambda i, lof: (lof[i], 0, 0)),
        pl.BlockSpec((1, 1, L), lambda i, lof: (lof[i], 0, 0)),
    ]
    operands = [lof, qres_s, recon8, base]
    if q_int8:
        in_specs.append(pl.BlockSpec((1, chunk, 1), lambda i, lof: (i, 0, 0)))
        operands.append(q_scale)
    if fold not in ("exact", "packed"):
        raise ValueError(f"unknown fold variant {fold!r}")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(ncb,),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, chunk, _CANDS), lambda i, lof: (i, 0, 0)),
            pl.BlockSpec((1, chunk, _CANDS), lambda i, lof: (i, 0, 0)),
        ),
    )
    make = _make_kernel_packed if fold == "packed" else _make_kernel
    return pl.pallas_call(
        make(L, inner_product, q_int8),
        out_shape=(
            jax.ShapeDtypeStruct((ncb, chunk, _CANDS), jnp.float32),
            jax.ShapeDtypeStruct((ncb, chunk, _CANDS), jnp.int32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)
        ),
    )(*operands)


def lane_padded(width: int) -> int:
    """The slot-axis width the kernel's lane contract requires: a multiple
    of the 128-lane register width, with at least _BINS slots (so the two
    candidate banks fill). Shared by every caller that pads a store."""
    return max(_BINS, -(-width // _LANES) * _LANES)


def fits_pallas(chunk: int, L: int, rot: int, store_itemsize: int = 1) -> bool:
    """VMEM envelope for one grid step (f32 scores dominate).
    `store_itemsize` is the per-element width of the scanned store (1 for
    int8 PQ reconstructions, 2 for IVF-Flat's bf16 residual store).
    Sized against the rot the kernel will ACTUALLY run with: when the
    rot-pad rescue is on, the padded width counts, so dispatch can't
    admit a geometry the padded kernel then OOMs. (The fused family's
    envelopes in ops/fused_scan.py are machine-checked against their
    kernels by raftlint's kernelcheck; this legacy trim's envelope is
    not registered — the rot-pad rescue makes its block geometry
    runtime-conditional.)"""
    if rot % _LANES and rot_pad_enabled():
        rot = -(-rot // _LANES) * _LANES
    step_bytes = (
        4 * chunk * L + store_itemsize * L * rot + 4 * chunk * rot + 8 * chunk * _CANDS
    )
    return L % _LANES == 0 and L >= _BINS and step_bytes <= 10 * 1024 * 1024
