"""Fused pairwise-distance + partial select-k Pallas kernel family.

Reference parity: the TPU-KNN paper (arxiv 2206.14286) runs brute-force
and IVF scans at near-peak FLOP/s by fusing the distance matmul with an
in-register partial top-k, so the (n_queries, n_rows) score matrix never
touches HBM. The CUDA analogue is `fused_l2_knn.cuh` (distance tile +
warp-level select queue in one kernel). This module is that kernel
family for TPU, and `matrix.select_k.scan_select_k` is its one dispatch
door — engines ask for top-k over operands and never pick kernels.

Four geometries share one epilogue:

  `fused_topk`      — flat scan: grid (m/bq, n/bn) with n innermost;
                      each step scores a (bq, bn) tile on the MXU (bf16
                      operands, f32 accumulate) and merges it into a
                      revisited (bq, kbuf) VMEM candidate buffer, the
                      analogue of the paper's per-core partial top-k
                      state. Only (m, kbuf) values+ids reach HBM.
  `fused_list_topk` — list scan: grid (ncb,) with scalar-prefetched
                      chunk->list ids indexing the store directly (the
                      `pq_list_scan` addressing scheme); per step the
                      (chunk, L) scores fold to an exact (chunk, kbuf)
                      top-k in-kernel. Backs the IVF-Flat/IVF-PQ fused
                      trims and the per-query fused rerank (chunk=1,
                      one "list" of gathered candidates per query).
  `fused_list_topk_int8`
                    — the list scan on the INTEGER datapath (ISSUE 11):
                      symmetric int8 queries x the int8 reconstruction
                      store -> int32 accumulate on the MXU's doubled
                      int8 rate (v5e: 394 int8 TOPS vs 197 bf16
                      TFLOP/s), per-row dequant scale applied on the
                      VPU, then the same exact epilogue — only the
                      (chunk, kbuf) survivors are ever dequantized to
                      f32 in HBM. Scoring numerics are IDENTICAL to
                      `pq_list_scan`'s q_int8 path (same quantization,
                      same op order), which is what the bit-agreement
                      tests pin.
  `fused_bitplane_topk`
                    — the RaBitQ bit-plane list scan: uint32 AND +
                      popcount of packed sign codes against the query's
                      quantized bit planes, entirely on the integer
                      VPU, with the unbiased RaBitQ estimator
                      correction applied IN-KERNEL — candidate bit
                      planes never materialize in HBM and only
                      (chunk, kbuf) estimator scores leave. The
                      estimator math mirrors
                      `neighbors/quantizer.binary_dot`/`estimate_dot`
                      op for op (it cannot import them — ops never
                      reaches back into neighbors, ANY_LEVEL_BAN);
                      tests/test_fused_int_scan.py pins exact
                      agreement against those reference helpers.

The epilogue is an EXACT partial selection, unlike `pq_list_scan`'s
lane-bin trim: `k` extraction passes over the merged candidate window
(the running kbuf buffer + the fresh tile — the "2k candidates" the
merge sorts), each pass taking the lexicographic (score, id) minimum so
ties break deterministically to the smaller id — the same stable-tie
order `lax.top_k` produces, which is what makes the fused path
bit-agree with the two-phase reference select-k. Exhausted slots carry
(+inf, _ID_SENTINEL); callers map non-finite winners to id -1.

Scores are canonical-minimizing: `base - 2<q,v>` for L2 (the per-query
|q|^2 constant cannot change any ranking, so it is added OUTSIDE the
kernel) and `base - <q,v>` for inner product (base 0 on valid slots,
+inf on masked/padded ones — the mask IS the base operand). Operands
are cast to bf16 for the one-pass MXU matmul with f32 accumulation;
like `knn(compute_dtype=bfloat16)`, the fused path ranks the
bf16-rounded geometry (exact whenever the inputs embed in bf16, which
is what the agreement tests pin).

Compiled-path status: validated in interpret mode (CPU tests); first
on-chip Mosaic compile may need block-shape adjustment (the lane-axis
concatenate and the fori_loop extraction are the highest-risk shapes).
The dispatch layer can always fall back to strategy="two_phase".
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
#: hard cap on k for every fused engine: the extraction epilogue costs
#: one VPU pass over the candidate window per selected element, and the
#: (bq, kbuf) buffer must stay a small fraction of the score tile
FUSED_MAX_K = 256
_ID_SENTINEL = 2**31 - 1  # python int: kernels close over no arrays

#: injection site for the chaos drill: corrupt_in_trace on the kernel's
#: candidate buffer (the values half), before callers merge/finalize
FUSED_SCORES_SITE = "fused.scan.scores"

#: Machine-readable kernel -> envelope pairing (the FAULT_SITES
#: pattern), read BY AST by raftlint's kernelcheck engine
#: (tools/raftlint/kernels.py): each pallas_call wrapper below is
#: cross-checked against its `fits_*` formula — per-grid-step block
#: bytes compared monomial by monomial over the SHARED parameter names,
#: so an envelope term drifting from the kernel geometry fires at lint
#: time instead of as a chip OOM. The binding dict pins envelope
#: parameters the kernel fixes (the int8 kernel shares the bf16 list
#: envelope at store itemsize 1). Keep it a literal dict.
KERNEL_ENVELOPES = {
    "fused_topk": ("fits_fused", {}),
    "fused_list_topk": ("fits_fused_list", {}),
    "fused_list_topk_int8": ("fits_fused_list", {"store_itemsize": 1}),
    "fused_bitplane_topk": ("fits_fused_bitplane", {}),
}


def fused_kbuf(k: int) -> int:
    """Candidate-buffer width compiled for a requested k: the 128-lane
    multiple that holds it. ONE definition shared by the kernels, the
    dispatch fit checks, and ivf_flat's lazy-store invalidation (a store
    built for kbuf=128 must rebuild when k grows past it, or the
    per-list candidate slice silently truncates)."""
    if not 0 < k <= FUSED_MAX_K:
        raise ValueError(f"fused select-k caps k at {FUSED_MAX_K}; k={k}")
    return max(_LANES, -(-int(k) // _LANES) * _LANES)


def _maybe_corrupt(vals):
    """Chaos hook on the candidate buffer. Inert (same jaxpr) without an
    installed plan; callers key their jits on `faults.trace_key()` so a
    plan install retraces instead of serving the clean program."""
    from raft_tpu.core.faults import corrupt_in_trace

    return corrupt_in_trace(FUSED_SCORES_SITE, vals, jnp.int32(0))


def _extract_topk(wv, wi, out_shape, k: int):
    """The shared exact epilogue: `k` lexicographic-min extraction
    passes over the candidate window (wv, wi), writing a sorted
    best-first (rows, kbuf) buffer. Ties break to the smaller id
    (stable order — the lax.top_k contract the reference paths use);
    selected entries are retired to (+inf, sentinel) so the next pass
    sees the remainder."""
    rows, kbuf = out_shape
    slot = lax.broadcasted_iota(jnp.int32, (rows, kbuf), 1)

    def extract(t, carry):
        wv_, wi_, ov, oi = carry
        m = jnp.min(wv_, axis=1, keepdims=True)  # (rows, 1)
        tie = wv_ == m
        mi = jnp.min(jnp.where(tie, wi_, _ID_SENTINEL), axis=1, keepdims=True)
        sel = tie & (wi_ == mi)
        hot = slot == t
        ov = jnp.where(hot, m, ov)
        oi = jnp.where(hot, mi, oi)
        wv_ = jnp.where(sel, jnp.float32(jnp.inf), wv_)
        wi_ = jnp.where(sel, _ID_SENTINEL, wi_)
        return wv_, wi_, ov, oi

    ov0 = jnp.full((rows, kbuf), jnp.inf, jnp.float32)
    oi0 = jnp.full((rows, kbuf), _ID_SENTINEL, jnp.int32)
    _, _, ov, oi = lax.fori_loop(0, k, extract, (wv, wi, ov0, oi0))
    return ov, oi


# ---------------------------------------------------------------------------
# flat scan: fused_topk
# ---------------------------------------------------------------------------


def _make_flat_kernel(bn: int, kbuf: int, k: int, inner_product: bool):
    coef = 1.0 if inner_product else 2.0

    def kernel(x_ref, y_ref, base_ref, vals_ref, idx_ref):
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _():
            vals_ref[:] = jnp.full(vals_ref.shape, jnp.inf, jnp.float32)
            idx_ref[:] = jnp.full(idx_ref.shape, _ID_SENTINEL, jnp.int32)

        dots = lax.dot_general(
            x_ref[:], y_ref[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bn)
        score = base_ref[:] - coef * dots  # masked/padded slots: base=+inf
        col = j * bn + lax.broadcasted_iota(jnp.int32, score.shape, 1)
        wv = jnp.concatenate([vals_ref[:], score], axis=1)
        wi = jnp.concatenate([idx_ref[:], col], axis=1)
        ov, oi = _extract_topk(wv, wi, vals_ref.shape, k)
        vals_ref[:] = ov
        idx_ref[:] = oi

    return kernel


def fits_fused(m: int, n: int, d: int, k: int,
               bq: int = 128, bn: int = 512) -> bool:
    """VMEM envelope for one flat-scan grid step: the score tile, the
    merged candidate window (values + ids), and the bf16 operand
    blocks. `m`/`n` only gate trivial emptiness; the grid streams any
    row count."""
    if not (0 < k <= FUSED_MAX_K and m >= 1 and n >= 1 and d >= 1):
        return False
    kbuf = fused_kbuf(k)
    d_pad = -(-d // _LANES) * _LANES
    step_bytes = (
        4 * bq * bn            # score tile
        + 8 * bq * (kbuf + bn)  # extraction window (f32 + int32)
        + 8 * bq * kbuf        # output buffers
        + 2 * (bq + bn) * d_pad  # bf16 operand blocks
        + 4 * bn               # base row
    )
    return step_bytes <= 10 * 1024 * 1024


@functools.partial(
    jax.jit,
    static_argnames=("k", "inner_product", "bq", "bn", "interpret",
                     "fault_key"),
)
def fused_topk(
    x: jax.Array,            # (m, d) queries
    y: jax.Array,            # (n, d) database rows
    k: int,
    *,
    inner_product: bool = False,
    valid: Optional[jax.Array] = None,  # (n,) bool: False rows excluded
    bq: int = 128,
    bn: int = 512,
    interpret: bool = False,
    fault_key=None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact fused scan+select over the full (m, n) pair space.

    Returns ((m, kbuf) canonical-minimizing scores, (m, kbuf) int32 row
    ids), best-first, kbuf = fused_kbuf(k); slots past k (and exhausted
    slots) carry (+inf, sentinel). L2 scores are |y|^2 - 2<x,y> — add
    the per-query |x|^2 and clamp at the call site; inner-product
    scores are -<x,y>. `fault_key` must be `faults.trace_key()` so an
    installed chaos plan retraces this jit.
    """
    del fault_key  # participates in the jit cache key only
    m, d = x.shape
    n = y.shape[0]
    kbuf = fused_kbuf(k)

    xb = x.astype(jnp.bfloat16)
    yb = y.astype(jnp.bfloat16)
    # base row: L2 -> |y|^2 of the bf16-rounded rows (the geometry the
    # matmul scores); IP -> 0. Padding and the valid mask fold in as
    # +inf, so the kernel needs no separate mask operand.
    yf = yb.astype(jnp.float32)
    base = jnp.zeros((n,), jnp.float32) if inner_product else jnp.sum(
        yf * yf, axis=1
    )
    if valid is not None:
        base = jnp.where(valid, base, jnp.inf)

    d_pad = -(-d // _LANES) * _LANES
    m_pad = -(-m // bq) * bq
    n_pad = -(-n // bn) * bn
    xb = jnp.pad(xb, ((0, m_pad - m), (0, d_pad - d)))
    yb = jnp.pad(yb, ((0, n_pad - n), (0, d_pad - d)))
    base = jnp.pad(base, (0, n_pad - n), constant_values=jnp.inf)[None, :]

    vals, idx = pl.pallas_call(
        _make_flat_kernel(bn, kbuf, int(k), bool(inner_product)),
        grid=(m_pad // bq, n_pad // bn),
        in_specs=[
            pl.BlockSpec((bq, d_pad), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, d_pad), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((bq, kbuf), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bq, kbuf), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((m_pad, kbuf), jnp.float32),
            jax.ShapeDtypeStruct((m_pad, kbuf), jnp.int32),
        ),
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
    )(xb, yb, base)
    return _maybe_corrupt(vals[:m]), idx[:m]


# ---------------------------------------------------------------------------
# list scan: fused_list_topk
# ---------------------------------------------------------------------------


def _make_list_kernel(kbuf: int, k: int, inner_product: bool,
                      with_valid: bool = False):
    coef = 1.0 if inner_product else 2.0

    def kernel(lof_ref, *refs):
        del lof_ref  # consumed by the index maps
        if with_valid:
            cva_ref, qres_ref, store_ref, base_ref, vals_ref, idx_ref = refs
        else:
            qres_ref, store_ref, base_ref, vals_ref, idx_ref = refs

        def compute():
            q = qres_ref[0]  # (chunk, rot) f32
            dots = lax.dot_general(
                q.astype(jnp.bfloat16),
                store_ref[0].astype(jnp.bfloat16),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # (chunk, L)
            score = base_ref[0] - coef * dots
            slot = lax.broadcasted_iota(jnp.int32, score.shape, 1)
            ov, oi = _extract_topk(score, slot, (score.shape[0], kbuf), k)
            vals_ref[0] = ov
            idx_ref[0] = oi

        if not with_valid:
            compute()
            return
        # sentinel/valid-chunk path (adaptive probe budgets): a chunk
        # with no live pairs skips the MXU matmul and the extraction
        # loop entirely — its output slots are never addressed by a
        # live pair's regroup gather, so (+inf, sentinel) is exact
        i = pl.program_id(0)

        @pl.when(cva_ref[i] != 0)
        def _():
            compute()

        @pl.when(cva_ref[i] == 0)
        def _():
            vals_ref[0] = jnp.full(vals_ref.shape[1:], jnp.inf, jnp.float32)
            idx_ref[0] = jnp.full(idx_ref.shape[1:], _ID_SENTINEL, jnp.int32)

    return kernel


def fits_fused_list(chunk: int, L: int, rot: int, k: int,
                    store_itemsize: int = 2,
                    kbuf: Optional[int] = None) -> bool:
    """VMEM envelope for one list-scan grid step (mirrors
    `pq_list_scan.fits_pallas`, plus the extraction window, the base
    row, and the int8 kernel's dequant-scale column — the mirroring is
    machine-checked against the kernels' actual block bytes by
    raftlint's `kernel-vmem-envelope` via KERNEL_ENVELOPES). `kbuf`:
    the buffer width the kernel will ACTUALLY run with — callers that
    cache a monotonically-grown width (ivf_flat's `fused_kb`) must pass
    it, or a small-k search on a grown store is gated against a
    narrower buffer than it compiles."""
    if not (0 < k <= FUSED_MAX_K):
        return False
    kbuf = fused_kbuf(k) if kbuf is None else int(kbuf)
    step_bytes = (
        4 * chunk * L                    # score tile (f32)
        + 4 * chunk * L                  # slot-id plane (int32)
        + store_itemsize * L * rot       # the scanned list block
        + 4 * chunk * rot                # query residuals
        + 8 * chunk * kbuf               # output buffers
        + 4 * L                          # base row (f32)
        + 4 * chunk                      # per-row dequant scale (int8 kernel)
    )
    return L % _LANES == 0 and step_bytes <= 10 * 1024 * 1024


@functools.partial(
    jax.jit,
    static_argnames=("k", "kbuf", "inner_product", "interpret", "fault_key"),
)
def fused_list_topk(
    lof: jax.Array,     # (ncb,) int32 chunk -> list id (scalar prefetch)
    qres: jax.Array,    # (ncb, chunk, rot) f32 query rows/residuals
    store: jax.Array,   # (n_lists, L, rot) slot table (bf16/f32/int8)
    base: jax.Array,    # (n_lists, 1, L) f32 additive base, +inf invalid
    k: int,
    *,
    kbuf: Optional[int] = None,
    inner_product: bool = False,
    interpret: bool = False,
    fault_key=None,
    chunk_valid: Optional[jax.Array] = None,  # (ncb,) int32 0 = skip
) -> Tuple[jax.Array, jax.Array]:
    """Exact fused scan+select of each chunk's probed list.

    Returns ((ncb, chunk, kbuf) minimizing scores, (ncb, chunk, kbuf)
    int32 in-list slots), best-first per row; slots past k carry
    (+inf, sentinel). `kbuf` defaults to fused_kbuf(k); callers that
    cache a compiled width (ivf_flat's lazy store) pass their recorded
    one — it must be >= fused_kbuf(k) or the top-k truncates, which is
    exactly the invalidation `_pad_store_to_lanes` enforces. Scores are
    `base - 2<q,v>` (L2; add |q|^2 outside) or `base - <q,v>` (IP).

    `chunk_valid` (probe_invert.chunk_validity): chunks flagged 0 hold
    no live pairs — the kernel skips their MXU matmul and extraction
    loop and writes (+inf, sentinel), the exact values a pad slot
    carries anyway. This is the ragged-work path adaptive probe budgets
    ride: shrunken budgets empty out whole chunks, and emptied chunks
    cost no compute.
    """
    del fault_key  # participates in the jit cache key only
    ncb, chunk, rot = qres.shape
    n_lists, L, _ = store.shape
    if qres.dtype != jnp.float32 or base.dtype != jnp.float32:
        # the documented operand contract (f32 rows/base) — also what
        # the envelope charges; trace-time only, so the guard is free
        raise ValueError(
            f"fused_list_topk requires float32 qres and base, got "
            f"{qres.dtype}/{base.dtype}"
        )
    if L % _LANES:
        raise ValueError(f"list length {L} must be a multiple of {_LANES}")
    kb = fused_kbuf(k) if kbuf is None else int(kbuf)
    if kb < fused_kbuf(k):
        raise ValueError(
            f"candidate buffer width {kb} cannot hold k={k} "
            f"(needs {fused_kbuf(k)})"
        )

    with_valid = chunk_valid is not None
    nsp = 2 if with_valid else 1
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=nsp,
        grid=(ncb,),
        in_specs=[
            pl.BlockSpec((1, chunk, rot), lambda i, *s: (i, 0, 0)),
            pl.BlockSpec((1, L, rot), lambda i, *s: (s[0][i], 0, 0)),
            pl.BlockSpec((1, 1, L), lambda i, *s: (s[0][i], 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, chunk, kb), lambda i, *s: (i, 0, 0)),
            pl.BlockSpec((1, chunk, kb), lambda i, *s: (i, 0, 0)),
        ),
    )
    scalars = (lof, chunk_valid.astype(jnp.int32)) if with_valid else (lof,)
    vals, idx = pl.pallas_call(
        _make_list_kernel(kb, int(k), bool(inner_product), with_valid),
        out_shape=(
            jax.ShapeDtypeStruct((ncb, chunk, kb), jnp.float32),
            jax.ShapeDtypeStruct((ncb, chunk, kb), jnp.int32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)
        ),
    )(*scalars, qres, store, base)
    return _maybe_corrupt(vals), idx


# ---------------------------------------------------------------------------
# integer list scan: fused_list_topk_int8
# ---------------------------------------------------------------------------


def _make_list_kernel_int8(kbuf: int, k: int, inner_product: bool,
                           with_valid: bool = False):
    coef = 1.0 if inner_product else 2.0

    def kernel(lof_ref, *refs):
        del lof_ref  # consumed by the index maps
        if with_valid:
            (cva_ref, q8_ref, store_ref, base_ref, rs_ref,
             vals_ref, idx_ref) = refs
        else:
            q8_ref, store_ref, base_ref, rs_ref, vals_ref, idx_ref = refs

        def compute():
            # int8 x int8 -> int32 at the MXU's doubled int8 rate; the
            # per-row dequant scale is the ONLY float multiply before the
            # epilogue — numerics match pq_list_scan's q_int8 path exactly
            idots = lax.dot_general(
                q8_ref[0], store_ref[0],
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # (chunk, L)
            dots = idots.astype(jnp.float32) * rs_ref[0]  # (chunk, 1) scale
            score = base_ref[0] - coef * dots
            slot = lax.broadcasted_iota(jnp.int32, score.shape, 1)
            ov, oi = _extract_topk(score, slot, (score.shape[0], kbuf), k)
            vals_ref[0] = ov
            idx_ref[0] = oi

        if not with_valid:
            compute()
            return
        i = pl.program_id(0)  # empty chunk: skip (see _make_list_kernel)

        @pl.when(cva_ref[i] != 0)
        def _():
            compute()

        @pl.when(cva_ref[i] == 0)
        def _():
            vals_ref[0] = jnp.full(vals_ref.shape[1:], jnp.inf, jnp.float32)
            idx_ref[0] = jnp.full(idx_ref.shape[1:], _ID_SENTINEL, jnp.int32)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("k", "kbuf", "inner_product", "interpret", "fault_key"),
)
def fused_list_topk_int8(
    lof: jax.Array,      # (ncb,) int32 chunk -> list id (scalar prefetch)
    q8: jax.Array,       # (ncb, chunk, rot) int8 symmetric query rows
    store: jax.Array,    # (n_lists, L, rot) int8 reconstruction store
    base: jax.Array,     # (n_lists, 1, L) f32 additive base, +inf invalid
    q_scale: jax.Array,  # (ncb, chunk, 1) f32 per-row dequant scale
    k: int,
    *,
    kbuf: Optional[int] = None,
    inner_product: bool = False,
    interpret: bool = False,
    fault_key=None,
    chunk_valid: Optional[jax.Array] = None,  # (ncb,) int32 0 = skip
) -> Tuple[jax.Array, jax.Array]:
    """Exact fused int8 scan+select of each chunk's probed list: the
    `fused_list_topk` contract (same outputs, same deterministic
    smaller-slot ties) with the scoring matmul on the int8 MXU path —
    int8 dot, int32 accumulate, per-row f32 dequant on the VPU. Callers
    quantize rows exactly like the pallas trim (`ivf_pq.
    _quantize_query_rows` on scale-folded residuals), so the two
    engines' scores are bit-identical f32 values. `fault_key` =
    faults.trace_key() so chaos plans retrace. `chunk_valid`: the
    empty-chunk skip path (see `fused_list_topk`)."""
    del fault_key  # participates in the jit cache key only
    ncb, chunk, rot = q8.shape
    n_lists, L, _ = store.shape
    if q8.dtype != jnp.int8 or store.dtype != jnp.int8:
        raise ValueError(
            f"fused_list_topk_int8 requires int8 queries and store, got "
            f"{q8.dtype}/{store.dtype}"
        )
    if base.dtype != jnp.float32 or q_scale.dtype != jnp.float32:
        # f32 base/dequant-scale operands: the contract the envelope
        # charges (trace-time only)
        raise ValueError(
            f"fused_list_topk_int8 requires float32 base and q_scale, "
            f"got {base.dtype}/{q_scale.dtype}"
        )
    if L % _LANES:
        raise ValueError(f"list length {L} must be a multiple of {_LANES}")
    kb = fused_kbuf(k) if kbuf is None else int(kbuf)
    if kb < fused_kbuf(k):
        raise ValueError(
            f"candidate buffer width {kb} cannot hold k={k} "
            f"(needs {fused_kbuf(k)})"
        )

    with_valid = chunk_valid is not None
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2 if with_valid else 1,
        grid=(ncb,),
        in_specs=[
            pl.BlockSpec((1, chunk, rot), lambda i, *s: (i, 0, 0)),
            pl.BlockSpec((1, L, rot), lambda i, *s: (s[0][i], 0, 0)),
            pl.BlockSpec((1, 1, L), lambda i, *s: (s[0][i], 0, 0)),
            pl.BlockSpec((1, chunk, 1), lambda i, *s: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, chunk, kb), lambda i, *s: (i, 0, 0)),
            pl.BlockSpec((1, chunk, kb), lambda i, *s: (i, 0, 0)),
        ),
    )
    scalars = (lof, chunk_valid.astype(jnp.int32)) if with_valid else (lof,)
    vals, idx = pl.pallas_call(
        _make_list_kernel_int8(kb, int(k), bool(inner_product), with_valid),
        out_shape=(
            jax.ShapeDtypeStruct((ncb, chunk, kb), jnp.float32),
            jax.ShapeDtypeStruct((ncb, chunk, kb), jnp.int32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)
        ),
    )(*scalars, q8, store, base, q_scale)
    return _maybe_corrupt(vals), idx


# ---------------------------------------------------------------------------
# bit-plane list scan: fused_bitplane_topk (RaBitQ)
# ---------------------------------------------------------------------------

#: query scalar-quantization depth cap (mirrors quantizer.DEFAULT_QUERY_BITS'
#: admissible range; a static kernel parameter, so it bounds the unrolled
#: AND+popcount plane loop)
BITPLANE_MAX_BITS = 8


def _make_bitplane_kernel(W: int, bits: int, kbuf: int, k: int,
                          inner_product: bool, rot_dim: int,
                          with_valid: bool = False):
    import math

    sqrt_d = math.sqrt(float(rot_dim))  # divide by it, like estimate_dot

    def kernel(lof_ref, *refs):
        del lof_ref  # consumed by the index maps
        if with_valid:
            (cva_ref, planes_ref, codes_ref, meta_ref, base_ref,
             qmeta_ref, vals_ref, idx_ref) = refs
        else:
            (planes_ref, codes_ref, meta_ref, base_ref,
             qmeta_ref, vals_ref, idx_ref) = refs
        if with_valid:
            i = pl.program_id(0)  # empty chunk: skip (see _make_list_kernel)

            @pl.when(cva_ref[i] != 0)
            def _():
                _compute(planes_ref, codes_ref, meta_ref, base_ref,
                         qmeta_ref, vals_ref, idx_ref)

            @pl.when(cva_ref[i] == 0)
            def _():
                vals_ref[0] = jnp.full(vals_ref.shape[1:], jnp.inf,
                                       jnp.float32)
                idx_ref[0] = jnp.full(idx_ref.shape[1:], _ID_SENTINEL,
                                      jnp.int32)
        else:
            _compute(planes_ref, codes_ref, meta_ref, base_ref,
                     qmeta_ref, vals_ref, idx_ref)

    def _compute(planes_ref, codes_ref, meta_ref, base_ref,
                 qmeta_ref, vals_ref, idx_ref):
        planes = planes_ref[0]  # (chunk, bits*W) uint32
        codes = codes_ref[0]    # (W, L) uint32 word-transposed sign codes
        chunk = planes.shape[0]
        L = codes.shape[1]
        # S_u[c, s] = sum_j 2^j * popcount(codes[s] & plane_j[c]) — the
        # AND+popcount fast-scan core, int32 end to end (associative, so
        # this accumulation order is EXACTLY quantizer.binary_dot's sum)
        acc = jnp.zeros((chunk, L), jnp.int32)
        for j in range(bits):
            pp = jnp.zeros((chunk, L), jnp.int32)
            for w in range(W):
                inter = planes[:, j * W + w][:, None] & codes[w][None, :]
                pp = pp + lax.population_count(inter).astype(jnp.int32)
            acc = acc + pp * (1 << j)
        s_u = acc.astype(jnp.float32)

        pop = meta_ref[0, 0][None, :]    # (1, L) per-slot code popcount
        rn = meta_ref[0, 1][None, :]     # (1, L) |r|
        o_dot = meta_ref[0, 2][None, :]  # (1, L) <o, x_bar>
        lo = qmeta_ref[0, 0][:, None]    # (chunk, 1) query quant offset
        delta = qmeta_ref[0, 1][:, None]  # (chunk, 1) query quant step
        qsum = qmeta_ref[0, 2][:, None]  # (chunk, 1) sum of residual
        qconst = qmeta_ref[0, 3][:, None]  # (chunk, 1) |q-c|^2 or q.c
        # the unbiased estimator, op for op the quantizer reference:
        # s = lo*pop + delta*S_u;  est = ((2s - qsum)/sqrt(D)) / o_dot
        s = lo * pop + delta * s_u
        est = ((2.0 * s - qsum) / sqrt_d) / jnp.maximum(o_dot, 1e-12)
        if inner_product:
            # reference maximizes qdotc + rn*est; canonical-minimizing
            score = -(qconst + rn * est)
        else:
            score = (qconst + rn * rn) - (2.0 * rn) * est
        score = score + base_ref[0]  # +inf on invalid/tombstoned slots
        slot = lax.broadcasted_iota(jnp.int32, score.shape, 1)
        ov, oi = _extract_topk(score, slot, (chunk, kbuf), k)
        vals_ref[0] = ov
        idx_ref[0] = oi

    return kernel


def fits_fused_bitplane(chunk: int, L: int, words: int, bits: int, k: int,
                        kbuf: Optional[int] = None) -> bool:
    """VMEM envelope for one bit-plane grid step: the int32 popcount
    accumulator, the f32 score + slot planes, the uint32 code block and
    query bit planes, the per-slot meta rows and the output buffers.
    `kbuf` follows the `fits_fused_list` convention (pass the recorded
    monotonically-grown width when one exists)."""
    if not (0 < k <= FUSED_MAX_K and 1 <= bits <= BITPLANE_MAX_BITS):
        return False
    kbuf = fused_kbuf(k) if kbuf is None else int(kbuf)
    step_bytes = (
        12 * chunk * L                # popcount accum + score + slot planes
        + 4 * words * L               # uint32 code block
        + 4 * 4 * L                   # meta rows + base row
        + 4 * chunk * bits * words    # query bit planes
        + 4 * 4 * chunk               # qmeta rows
        + 8 * chunk * kbuf            # output buffers
    )
    return L % _LANES == 0 and step_bytes <= 10 * 1024 * 1024


@functools.partial(
    jax.jit,
    static_argnames=("k", "kbuf", "bits", "rot_dim", "inner_product",
                     "interpret", "fault_key"),
)
def fused_bitplane_topk(
    lof: jax.Array,      # (ncb,) int32 chunk -> list id (scalar prefetch)
    planes: jax.Array,   # (ncb, chunk, bits*W) uint32 query bit planes
    codes_t: jax.Array,  # (n_lists, W, L) uint32 word-transposed codes
    meta: jax.Array,     # (n_lists, 3, L) f32 [popcount, |r|, <o,x_bar>]
    base: jax.Array,     # (n_lists, 1, L) f32 0 valid / +inf invalid
    qmeta: jax.Array,    # (ncb, 4, chunk) f32 [lo, delta, qsum, qconst]
    k: int,
    *,
    rot_dim: int,
    bits: int,
    kbuf: Optional[int] = None,
    inner_product: bool = False,
    interpret: bool = False,
    fault_key=None,
    chunk_valid: Optional[jax.Array] = None,  # (ncb,) int32 0 = skip
) -> Tuple[jax.Array, jax.Array]:
    """Exact fused RaBitQ bit-plane scan+select of each chunk's probed
    list: AND+popcount scoring of the packed sign codes against the
    query's quantized bit planes with the unbiased estimator correction
    applied in-kernel — the integer-dominated inner loop of the
    IVF-RaBitQ paper (arxiv 2602.23999) fused so candidate bit planes
    never touch HBM.

    Returns ((ncb, chunk, kbuf) canonical-minimizing estimator scores,
    (ncb, chunk, kbuf) int32 in-list slots), best-first; slots past k
    carry (+inf, sentinel). L2 scores are the FULL estimator distance
    (qconst = |q - center|^2 rides the qmeta operand); inner-product
    scores are the negated estimator similarity — negate back at the
    call site. `fault_key` = faults.trace_key() so chaos plans
    retrace."""
    del fault_key  # participates in the jit cache key only
    ncb, chunk, pw = planes.shape
    n_lists, words, L = codes_t.shape
    if planes.dtype != jnp.uint32 or codes_t.dtype != jnp.uint32:
        raise ValueError(
            f"fused_bitplane_topk requires uint32 planes and codes, got "
            f"{planes.dtype}/{codes_t.dtype}"
        )
    if meta.dtype != jnp.float32 or base.dtype != jnp.float32 \
            or qmeta.dtype != jnp.float32:
        # f32 meta/base/qmeta rows: the contract the envelope charges
        # (trace-time only)
        raise ValueError(
            f"fused_bitplane_topk requires float32 meta/base/qmeta, got "
            f"{meta.dtype}/{base.dtype}/{qmeta.dtype}"
        )
    if not (1 <= int(bits) <= BITPLANE_MAX_BITS):
        raise ValueError(f"bits must be in [1, {BITPLANE_MAX_BITS}], got {bits}")
    if pw != int(bits) * words:
        raise ValueError(
            f"planes width {pw} != bits*words = {int(bits) * words}"
        )
    if L % _LANES:
        raise ValueError(f"list length {L} must be a multiple of {_LANES}")
    kb = fused_kbuf(k) if kbuf is None else int(kbuf)
    if kb < fused_kbuf(k):
        raise ValueError(
            f"candidate buffer width {kb} cannot hold k={k} "
            f"(needs {fused_kbuf(k)})"
        )

    with_valid = chunk_valid is not None
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2 if with_valid else 1,
        grid=(ncb,),
        in_specs=[
            pl.BlockSpec((1, chunk, pw), lambda i, *s: (i, 0, 0)),
            pl.BlockSpec((1, words, L), lambda i, *s: (s[0][i], 0, 0)),
            pl.BlockSpec((1, 3, L), lambda i, *s: (s[0][i], 0, 0)),
            pl.BlockSpec((1, 1, L), lambda i, *s: (s[0][i], 0, 0)),
            pl.BlockSpec((1, 4, chunk), lambda i, *s: (i, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, chunk, kb), lambda i, *s: (i, 0, 0)),
            pl.BlockSpec((1, chunk, kb), lambda i, *s: (i, 0, 0)),
        ),
    )
    scalars = (lof, chunk_valid.astype(jnp.int32)) if with_valid else (lof,)
    vals, idx = pl.pallas_call(
        _make_bitplane_kernel(words, int(bits), kb, int(k),
                              bool(inner_product), int(rot_dim),
                              with_valid),
        out_shape=(
            jax.ShapeDtypeStruct((ncb, chunk, kb), jnp.float32),
            jax.ShapeDtypeStruct((ncb, chunk, kb), jnp.int32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)
        ),
    )(*scalars, planes, codes_t, meta, base, qmeta)
    return _maybe_corrupt(vals), idx
