"""Clustering: k-means, balanced k-means, single-linkage.

TPU-native equivalent of `cpp/include/raft/cluster/` (survey §2.10).
"""

from raft_tpu.cluster import kmeans
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans import KMeansParams
from raft_tpu.cluster.single_linkage import single_linkage, SingleLinkageOutput

__all__ = [
    "kmeans",
    "kmeans_balanced",
    "KMeansParams",
    "single_linkage",
    "SingleLinkageOutput",
]
