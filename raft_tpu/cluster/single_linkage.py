"""Single-linkage hierarchical agglomerative clustering.

Reference parity: `raft::cluster::single_linkage` (cluster/single_linkage.cuh,
detail/single_linkage.cuh:52-111): k-NN-graph (or full pairwise)
connectivities → connect-components fixup → sorted MST
(detail/mst.cuh build_sorted_mst) → agglomerative dendrogram labeling
(detail/agglomerative.cuh union-find) → flat-cut to n_clusters.

TPU design: the distance-heavy stages (knn graph, masked cross-component NN,
Borůvka MST) are the jit-compiled primitives from sparse/; the final
dendrogram build is an O(n α(n)) sequential union-find, inherently host work
(the reference also finishes on serialized label propagation).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp


@dataclasses.dataclass
class SingleLinkageOutput:
    """Mirrors raft::cluster::linkage_output."""

    labels: jax.Array         # (n,) int32 flat clustering
    children: jax.Array       # (n-1, 2) merge tree (scipy convention)
    deltas: jax.Array         # (n-1,) merge distances
    sizes: jax.Array          # (n-1,) merged cluster sizes
    n_clusters: int


def _mst_linkage(n: int, edges_src, edges_dst, edges_w):
    """Union-find dendrogram from MST edges sorted by weight
    (detail/agglomerative.cuh label building, scipy children convention).
    Native C++ merge loop when available (the interpreted loop below is
    the bottleneck at 100k+ rows); numpy fallback otherwise."""
    from raft_tpu import native

    order = np.argsort(edges_w, kind="stable")
    src, dst, w = edges_src[order], edges_dst[order], edges_w[order]
    packed = native.mst_linkage(src, dst, w, n)
    if packed is not None:
        return packed
    parent = np.arange(2 * n - 1)
    cluster_of = np.arange(n)
    size = np.ones(2 * n - 1, np.int64)

    def find(x):
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    children = np.zeros((n - 1, 2), np.int64)
    deltas = np.zeros(n - 1, np.float64)
    sizes = np.zeros(n - 1, np.int64)
    nxt = n
    m = 0
    for a, b, ww in zip(src, dst, w):
        ra, rb = find(cluster_of[a]), find(cluster_of[b])
        if ra == rb:
            continue
        children[m] = (ra, rb)
        deltas[m] = ww
        size[nxt] = size[ra] + size[rb]
        sizes[m] = size[nxt]
        parent[ra] = parent[rb] = nxt
        nxt += 1
        m += 1
        if m == n - 1:
            break
    return children[:m], deltas[:m], sizes[:m]


def _cut_tree(n: int, children, n_clusters: int) -> np.ndarray:
    """Flat labels from the first n - n_clusters merges."""
    from raft_tpu import native

    labels = native.cut_tree(np.asarray(children), n, n_clusters)
    if labels is not None:
        return labels
    parent = np.arange(2 * n - 1)

    def find(x):
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    keep = max(0, len(children) - (n_clusters - 1))
    for m in range(keep):
        a, b = children[m]
        nxt = n + m
        parent[find(a)] = nxt
        parent[find(b)] = nxt
    roots = np.array([find(i) for i in range(n)])
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.int32)


def single_linkage(
    X,
    n_clusters: int = 2,
    metric: str = "sqeuclidean",
    connectivity: str = "knn",
    n_neighbors: int = 15,
) -> SingleLinkageOutput:
    """Fit single-linkage HAC; returns labels + dendrogram.

    connectivity='knn' builds a k-NN graph and repairs disconnected
    components (the reference's KNN_GRAPH mode, detail/connectivities.cuh);
    'pairwise' uses the complete graph (exact, O(n²) edges).
    """
    from raft_tpu.sparse import neighbors as sp_neighbors
    from raft_tpu.sparse.formats import CooMatrix
    from raft_tpu.sparse.solver import mst
    from raft_tpu.label import merge_labels  # noqa: F401 (API surface)

    x = np.asarray(X, np.float32)
    n = x.shape[0]
    if n_clusters < 1 or n_clusters > n:
        raise ValueError(f"n_clusters={n_clusters} out of range")

    if connectivity == "pairwise":
        from scipy.spatial.distance import pdist  # test-grade small-n path

        rows, cols = np.nonzero(~np.eye(n, dtype=bool))
        from raft_tpu.distance.pairwise import _pairwise_impl
        from raft_tpu.distance.distance_types import resolve_metric

        full = np.asarray(_pairwise_impl(jnp.asarray(x), jnp.asarray(x),
                                         resolve_metric(metric)))
        coo = CooMatrix(
            jnp.asarray(rows.astype(np.int32)),
            jnp.asarray(cols.astype(np.int32)),
            jnp.asarray(full[rows, cols].astype(np.float32)),
            (n, n),
        )
    else:
        coo = sp_neighbors.knn_graph(x, n_neighbors, metric=metric)

    tree = mst(coo)
    src = np.asarray(tree.rows)
    dst = np.asarray(tree.cols)
    w = np.asarray(tree.vals)

    # repair forest while the knn graph is disconnected (connect_components);
    # each pass links every component to its nearest other component — a
    # chain of C components needs up to log2(C) passes.
    passes = 0
    while len(src) < n - 1 and passes < 32:
        import scipy.sparse as sp
        from scipy.sparse.csgraph import connected_components

        g = sp.coo_matrix((np.ones(len(src) * 2),
                           (np.concatenate([src, dst]), np.concatenate([dst, src]))),
                          shape=(n, n))
        _, comp = connected_components(g, directed=False)
        extra = sp_neighbors.connect_components(x, comp, metric=metric)
        merged = CooMatrix(
            jnp.concatenate([jnp.asarray(src), jnp.asarray(extra.rows)]),
            jnp.concatenate([jnp.asarray(dst), jnp.asarray(extra.cols)]),
            jnp.concatenate([jnp.asarray(w), jnp.asarray(extra.vals)]),
            (n, n),
        )
        tree = mst(merged)
        src, dst, w = np.asarray(tree.rows), np.asarray(tree.cols), np.asarray(tree.vals)
        passes += 1

    children, deltas, sizes = _mst_linkage(n, src, dst, w)
    labels = _cut_tree(n, children, n_clusters)
    return SingleLinkageOutput(
        jnp.asarray(labels),
        jnp.asarray(children),
        jnp.asarray(deltas.astype(np.float32)),
        jnp.asarray(sizes),
        int(labels.max()) + 1,
    )
