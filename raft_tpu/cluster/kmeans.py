"""k-means (Lloyd) clustering.

Reference parity: `raft::cluster::kmeans` — `fit/predict/fit_predict/
transform/cluster_cost/find_k` (cluster/kmeans.cuh:87,151,214,243,306,366),
k-means++ init (detail/kmeans.cuh:88), main loop (detail/kmeans.cuh:359-548),
`KMeansParams` (cluster/kmeans_types.hpp); pylibraft `cluster.kmeans`
(cluster/kmeans.pyx:54,289,382,496).

TPU design: the Lloyd iteration is a `lax.while_loop` whose body streams the
dataset once through the fused assign+reduce scan (kmeans_common) — MXU
distance tiles, argmin, and one-hot-matmul centroid sums in one pass. The
convergence test (center shift < tol and no inertia change) lives in the
loop condition, so the entire fit compiles to a single XLA program with no
host round-trips per iteration.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.cluster.kmeans_common import assign_and_reduce, predict_labels, cluster_cost_impl
from raft_tpu.core.config import auto_convert_output


@dataclasses.dataclass
class KMeansParams:
    """Mirrors raft::cluster::KMeansParams (cluster/kmeans_types.hpp)."""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    init: str = "k-means++"  # "k-means++" | "random" | "array"
    n_init: int = 1
    seed: int = 0
    oversampling_factor: float = 2.0
    inertia_check: bool = True
    metric: str = "sqeuclidean"
    # TPU design choice (no reference analogue): MXU precision of the
    # assignment matmul. None = f32-parity HIGHEST (six bf16 passes);
    # jax.lax.Precision.DEFAULT = single-pass bf16, ~6x matmul throughput
    # for ~1e-3 relative distance error in the argmin.
    precision: object = None


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_clusters",))
def _kmeans_plusplus(key, x: jax.Array, n_clusters: int) -> jax.Array:
    """k-means++ seeding (detail/kmeans.cuh:88 kmeansPlusPlus).

    Iterative D² weighted sampling expressed as a fori_loop filling a fixed
    (k, d) buffer — compiler-friendly static shapes.
    """
    n, d = x.shape
    xf = x.astype(jnp.float32)
    keys = jax.random.split(key, n_clusters)
    first = jax.random.randint(keys[0], (), 0, n)
    centers0 = jnp.zeros((n_clusters, d), jnp.float32).at[0].set(xf[first])
    d0 = jnp.sum((xf - xf[first][None, :]) ** 2, axis=1)

    def body(i, carry):
        centers, mind = carry
        probs = mind / jnp.maximum(jnp.sum(mind), 1e-30)
        nxt = jax.random.choice(keys[i], n, p=probs)
        c = xf[nxt]
        centers = centers.at[i].set(c)
        dn = jnp.sum((xf - c[None, :]) ** 2, axis=1)
        return centers, jnp.minimum(mind, dn)

    centers, _ = lax.fori_loop(1, n_clusters, body, (centers0, d0))
    return centers

def _random_init(key, x: jax.Array, n_clusters: int) -> jax.Array:
    idx = jax.random.choice(key, x.shape[0], (n_clusters,), replace=False)
    return x[idx].astype(jnp.float32)


# ---------------------------------------------------------------------------
# fit
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("max_iter", "precision"))
def _lloyd(
    x: jax.Array,
    centers0: jax.Array,
    weights: Optional[jax.Array],
    max_iter: int,
    tol: float,
    precision=None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (centers, inertia, n_iter). Convergence: sqrt(Σ‖Δc‖²) < tol
    (detail/kmeans.cuh:494-505 sqrdNormError check)."""

    def cond(state):
        _, shift, it, _ = state
        return (it < max_iter) & (shift >= tol * tol)

    def body(state):
        centers, _, it, _ = state
        _, sums, counts, inertia = assign_and_reduce(x, centers, weights, precision=precision)
        safe = jnp.maximum(counts, 1.0)[:, None]
        new_centers = jnp.where(counts[:, None] > 0, sums / safe, centers)
        shift = jnp.sum((new_centers - centers) ** 2)
        return new_centers, shift, it + 1, inertia

    init = (centers0.astype(jnp.float32), jnp.array(jnp.inf, jnp.float32),
            jnp.zeros((), jnp.int32), jnp.array(jnp.inf, jnp.float32))
    centers, _, n_iter, inertia = lax.while_loop(cond, body, init)
    return centers, inertia, n_iter


@auto_convert_output
def fit(
    X,
    params: Optional[KMeansParams] = None,
    sample_weights=None,
    centroids=None,
    resources=None,
    **kwargs,
) -> Tuple[jax.Array, float, int]:
    """Fit k-means; returns (centroids, inertia, n_iter).

    pylibraft-compatible (cluster/kmeans.pyx:54 `fit`). Extra kwargs build a
    KMeansParams (e.g. fit(X, n_clusters=8)).
    """
    from raft_tpu.core.validation import check_matrix

    if params is None:
        params = KMeansParams(**kwargs)
    x = check_matrix(X, name="X")
    w = None if sample_weights is None else jnp.asarray(sample_weights)
    key = jax.random.PRNGKey(params.seed)

    best = None
    for trial in range(max(1, params.n_init)):
        key, init_key = jax.random.split(key)
        if centroids is not None or params.init == "array":
            if centroids is None:
                raise ValueError("init='array' requires centroids")
            c0 = jnp.asarray(centroids, jnp.float32)
        elif params.init == "random":
            c0 = _random_init(init_key, x, params.n_clusters)
        else:
            c0 = _kmeans_plusplus(init_key, x, params.n_clusters)
        centers, inertia, n_iter = _lloyd(x, c0, w, params.max_iter, params.tol, precision=params.precision)
        if best is None or float(inertia) < float(best[1]):
            best = (centers, inertia, n_iter)
    centers, inertia, n_iter = best
    if resources is not None:
        resources.track(centers)
    return centers, float(inertia), int(n_iter)


@auto_convert_output
def predict(X, centroids, resources=None) -> jax.Array:
    """Nearest-centroid labels (cluster/kmeans.cuh:151)."""
    from raft_tpu.core.validation import check_matrix

    x = check_matrix(X, name="X")
    c = jnp.asarray(centroids)
    return predict_labels(x, c)


@auto_convert_output
def fit_predict(X, params: Optional[KMeansParams] = None, resources=None, **kwargs):
    centers, inertia, n_iter = fit(X, params, resources=resources, **kwargs)
    return predict(X, centers), centers, inertia, n_iter


@auto_convert_output
def transform(X, centroids) -> jax.Array:
    """Distances to all centroids (cluster/kmeans.cuh:306)."""
    from raft_tpu.distance.pairwise import pairwise_distance

    return pairwise_distance(X, centroids, metric="sqeuclidean")


def cluster_cost(X, centroids, resources=None) -> float:
    """Total inertia vs given centroids (pylibraft cluster_cost, kmeans.pyx:289)."""
    from raft_tpu.core.validation import check_matrix

    return float(cluster_cost_impl(check_matrix(X), jnp.asarray(centroids)))


def compute_new_centroids(X, centroids, labels=None, sample_weights=None) -> jax.Array:
    """One centroid-update step (pylibraft compute_new_centroids, kmeans.pyx:382)."""
    from raft_tpu.core.validation import check_matrix

    x = check_matrix(X)
    c = jnp.asarray(centroids)
    w = None if sample_weights is None else jnp.asarray(sample_weights)
    _, sums, counts, _ = assign_and_reduce(x, c, w)
    safe = jnp.maximum(counts, 1.0)[:, None]
    return jnp.where(counts[:, None] > 0, sums / safe, c)


def find_k(
    X,
    kmax: int = 20,
    kmin: int = 1,
    max_iter: int = 100,
    tol: float = 1e-2,
    seed: int = 0,
) -> Tuple[int, float, int]:
    """Auto-select k via binary search on the inertia elbow
    (detail/kmeans_auto_find_k.cuh:231). Returns (best_k, inertia, n_iter)."""
    from raft_tpu.core.validation import check_matrix

    x = check_matrix(X)

    def cost_of(k: int):
        c, inertia, n_iter = fit(x, KMeansParams(n_clusters=k, max_iter=max_iter, seed=seed))
        return inertia, n_iter

    # coarse scan then local refinement on relative inertia drop
    lo, hi = kmin, max(kmin, kmax)
    costs = {}
    for k in {lo, (lo + hi) // 2, hi}:
        costs[k] = cost_of(k)
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if mid not in costs:
            costs[mid] = cost_of(mid)
        c_lo, c_mid, c_hi = costs[lo][0], costs[mid][0], costs[hi][0]
        denom = max(c_lo - c_hi, 1e-30)
        # if most of the improvement happened before mid, shrink right side
        if (c_lo - c_mid) / denom > 1.0 - tol:
            hi = mid
        else:
            lo = mid
    best_k = hi
    inertia, n_iter = costs.get(best_k, cost_of(best_k))
    return best_k, float(inertia), int(n_iter)
