"""Shared k-means machinery: fused assign + centroid-reduce scan.

Reference parity: `minClusterAndDistanceCompute` (cluster/detail/kmeans_common.cuh)
batched through fused_l2_nn, plus `update_centroids` via
`linalg::reduce_rows_by_key` (cluster/detail/kmeans.cuh:285) and
`calc_centers_and_sizes` (detail/kmeans_balanced.cuh:255).

TPU design: one scanned row-block pass computes, per block, the (bm, k)
distance tile on the MXU, its argmin, and the one-hot-matmul partial
centroid sums — so assignment AND reduction stream the data once, the
functional equivalent of the reference's fused_l2_nn + atomics-free
deterministic reduction. Carry = (sums (k,d), counts (k,), inertia).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _block_rows(m: int, k: int, d: int, budget_elems: int = 1 << 21) -> int:
    bm = max(1, budget_elems // max(1, k + d))
    bm = min(bm, m)
    if bm >= 8:
        bm = bm // 8 * 8
    return max(1, bm)


def _dots(xb, centers, precision=None):
    from raft_tpu.distance.pairwise import _dot

    return _dot(xb, centers, precision=precision)


@functools.partial(jax.jit, static_argnames=("needs_sums", "precision"))
def assign_and_reduce(
    x: jax.Array,
    centers: jax.Array,
    weights: Optional[jax.Array] = None,
    needs_sums: bool = True,
    precision=None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Stream x once; return (labels, sums, counts, inertia).

    labels: (n,) int32 nearest-center ids
    sums:   (k, d) weighted per-cluster coordinate sums (zeros if !needs_sums)
    counts: (k,) weighted member counts
    inertia: scalar sum of min squared L2 distances (weighted)

    `precision` overrides the distance matmul's MXU precision for this
    call (None = the module default, lax.Precision.HIGHEST). Trainers can
    pass lax.Precision.DEFAULT (single bf16 pass, ~6x throughput) where
    assignment tolerates ~1e-3 relative distance error.
    """
    n, d = x.shape
    k = centers.shape[0]
    cn = jnp.sum(centers.astype(jnp.float32) ** 2, axis=1)  # (k,)
    bm = _block_rows(n, k, d)
    nblocks = -(-n // bm)
    pad = nblocks * bm - n
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    w = jnp.ones((nblocks * bm,), jnp.float32) if weights is None else jnp.pad(
        weights.astype(jnp.float32), (0, pad)
    )
    if pad:
        # padded rows must not contribute
        w = w.at[n:].set(0.0)
    blocks = xp.reshape(nblocks, bm, d)
    wblocks = w.reshape(nblocks, bm)

    def step(carry, inp):
        sums, counts, inertia = carry
        xb, wb = inp
        dtile = _dots(xb, centers, precision)
        xn = jnp.sum(xb.astype(jnp.float32) ** 2, axis=1)[:, None]
        dist = jnp.maximum(xn + cn[None, :] - 2.0 * dtile, 0.0)  # (bm, k)
        lbl = jnp.argmin(dist, axis=1).astype(jnp.int32)
        best = jnp.min(dist, axis=1)
        onehot = jax.nn.one_hot(lbl, k, dtype=jnp.float32) * wb[:, None]
        if needs_sums:
            sums = sums + lax.dot_general(
                onehot, xb.astype(jnp.float32), (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        counts = counts + jnp.sum(onehot, axis=0)
        inertia = inertia + jnp.sum(best * wb)
        return (sums, counts, inertia), lbl

    init = (
        jnp.zeros((k, d), jnp.float32),
        jnp.zeros((k,), jnp.float32),
        jnp.zeros((), jnp.float32),
    )
    (sums, counts, inertia), labels = lax.scan(step, init, (blocks, wblocks))
    labels = labels.reshape(-1)[:n]
    return labels, sums, counts, inertia


@jax.jit
def predict_labels(x: jax.Array, centers: jax.Array) -> jax.Array:
    labels, _, _, _ = assign_and_reduce(x, centers, needs_sums=False)
    return labels


@jax.jit
def cluster_cost_impl(x: jax.Array, centers: jax.Array) -> jax.Array:
    _, _, _, inertia = assign_and_reduce(x, centers, needs_sums=False)
    return inertia
