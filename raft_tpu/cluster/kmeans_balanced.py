"""Balanced k-means — the trainer for IVF coarse quantizers and PQ codebooks.

Reference parity: `raft::cluster::kmeans_balanced::fit/predict/fit_predict`
(cluster/kmeans_balanced.cuh:75,133,198) with `build_clusters`
(detail/kmeans_balanced.cuh:703), `balancing_em_iters` (:616) and
`adjust_centers` (:522). Supports L2 and inner-product metrics and integer
data via a mapping op (int8/uint8 datasets), and a two-level mesocluster
hierarchy for very large n_clusters (:756-790).

TPU design: EM iterations run as a jit-compiled fori_loop; each iteration
streams the data through the fused assign+reduce scan, then applies the
balancing adjustment *functionally*: undersized clusters (count < avg/ratio)
are re-seeded onto data points drawn from a D²-ish proposal (uniform over
the dataset, which concentrates on large clusters by mass — the same
pressure as the reference's "steal a point from a big cluster" rule) and
nudged via the reference's weighted-average update. The hierarchy for huge k
is host-orchestrated (build-time only): train mesoclusters, partition, train
fine clusters per padded partition bucket.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.cluster.kmeans_common import assign_and_reduce

# Reference adjust_centers uses kAdjustCentersWeight = 7.0 (detail/kmeans_balanced.cuh)
_ADJUST_WEIGHT = 7.0


def _maybe_normalize(centers: jax.Array, metric: str) -> jax.Array:
    if metric in ("inner_product", "cosine"):
        n = jnp.linalg.norm(centers, axis=1, keepdims=True)
        return centers / jnp.maximum(n, 1e-12)
    return centers


@functools.partial(jax.jit, static_argnames=("n_iters", "metric"))
def _balanced_em(
    key: jax.Array,
    x: jax.Array,
    centers0: jax.Array,
    n_iters: int,
    metric: str = "sqeuclidean",
    balancing_ratio: float = 4.0,
) -> jax.Array:
    n, d = x.shape
    k = centers0.shape[0]
    avg = n / k
    threshold = avg / balancing_ratio

    def body(i, carry):
        centers, key = carry
        _, sums, counts, _ = assign_and_reduce(x, centers)
        safe = jnp.maximum(counts, 1.0)[:, None]
        updated = jnp.where(counts[:, None] > 0, sums / safe, centers)
        # balancing: re-seed undersized clusters toward random data points
        key, k1 = jax.random.split(key)
        props = jax.random.randint(k1, (k,), 0, n)
        proposals = x[props].astype(jnp.float32)
        small = counts < threshold
        wc = jnp.minimum(counts, _ADJUST_WEIGHT)[:, None]
        adjusted = (wc * updated + proposals) / (wc + 1.0)
        centers = jnp.where(small[:, None], adjusted, updated)
        centers = _maybe_normalize(centers, metric)
        return centers, key

    centers, _ = lax.fori_loop(0, n_iters, body, (centers0.astype(jnp.float32), key))
    # final clean EM steps (no balancing) so returned centers are a Lloyd
    # update of their members, mirroring balancing_em_iters' trailing
    # predict+calc_centers passes.
    def final_step(_, centers):
        _, sums, counts, _ = assign_and_reduce(x, centers)
        safe = jnp.maximum(counts, 1.0)[:, None]
        centers = jnp.where(counts[:, None] > 0, sums / safe, centers)
        return _maybe_normalize(centers, metric)

    return lax.fori_loop(0, 2, final_step, centers)


def fit(
    X,
    n_clusters: int,
    n_iters: int = 20,
    metric: str = "sqeuclidean",
    seed: int = 0,
    max_train_points: Optional[int] = None,
    resources=None,
) -> jax.Array:
    """Train balanced cluster centers; returns (n_clusters, dim) f32.

    Integer datasets (int8/uint8) are accepted and mapped to f32, mirroring
    the reference's `mapping` operator.
    """
    from raft_tpu.core.validation import check_matrix

    x = check_matrix(X, name="X")
    if x.dtype in (jnp.int8, jnp.uint8, jnp.int32):
        x = x.astype(jnp.float32)
    n = x.shape[0]
    if n_clusters > n:
        raise ValueError(f"n_clusters={n_clusters} > n_samples={n}")
    key = jax.random.PRNGKey(seed)
    if max_train_points is not None and n > max_train_points:
        key, sk = jax.random.split(key)
        sel = jax.random.choice(sk, n, (max_train_points,), replace=False)
        x = x[sel]
        n = max_train_points
    key, ik = jax.random.split(key)
    if n_clusters <= 512:
        # k-means++ seeding markedly improves partition quality at small k;
        # at IVF-scale k the hierarchy (fit_hierarchical) is the quality lever.
        from raft_tpu.cluster.kmeans import _kmeans_plusplus

        centers0 = _kmeans_plusplus(ik, x, n_clusters)
    else:
        init_idx = jax.random.choice(ik, n, (n_clusters,), replace=False)
        centers0 = x[init_idx].astype(jnp.float32)
    centers0 = _maybe_normalize(centers0, metric)
    centers = _balanced_em(key, x, centers0, int(n_iters), metric)
    if resources is not None:
        resources.track(centers)
    return centers


def predict(X, centers, metric: str = "sqeuclidean", resources=None) -> jax.Array:
    """Nearest-center labels under the training metric
    (cluster/kmeans_balanced.cuh:133)."""
    from raft_tpu.core.validation import check_matrix
    from raft_tpu.cluster.kmeans_common import predict_labels

    x = check_matrix(X, name="X")
    if x.dtype in (jnp.int8, jnp.uint8, jnp.int32):
        x = x.astype(jnp.float32)
    c = jnp.asarray(centers, jnp.float32)
    if metric in ("inner_product", "cosine"):
        from raft_tpu.distance.pairwise import _dot

        scores = _dot(x, _maybe_normalize(c, metric))
        return jnp.argmax(scores, axis=1).astype(jnp.int32)
    return predict_labels(x, c)


def fit_predict(
    X, n_clusters: int, n_iters: int = 20, metric: str = "sqeuclidean", seed: int = 0
) -> Tuple[jax.Array, jax.Array]:
    centers = fit(X, n_clusters, n_iters=n_iters, metric=metric, seed=seed)
    return centers, predict(X, centers, metric=metric)


def fit_hierarchical(
    X,
    n_clusters: int,
    n_iters: int = 20,
    metric: str = "sqeuclidean",
    seed: int = 0,
    mesocluster_size: int = 1 << 18,
) -> jax.Array:
    """Two-level trainer for very large n_clusters / datasets
    (detail/kmeans_balanced.cuh:756-790 mesocluster partitioning).

    Trains sqrt(k) mesoclusters, partitions the data, then trains
    proportionally-sized fine clusters inside each partition. Host-side
    orchestration (build-time only); each fine fit is an independent jit.
    """
    import numpy as np

    from raft_tpu.core.validation import check_matrix

    x = check_matrix(X)
    n = x.shape[0]
    k_meso = max(1, int(np.sqrt(n_clusters)))
    if k_meso <= 1 or n_clusters <= 64:
        return fit(x, n_clusters, n_iters=n_iters, metric=metric, seed=seed)
    meso_centers = fit(x, k_meso, n_iters=n_iters, metric=metric, seed=seed)
    meso_labels = np.asarray(predict(x, meso_centers, metric=metric))
    sizes = np.bincount(meso_labels, minlength=k_meso)
    # proportional fine-cluster allocation summing to n_clusters
    fine_k = np.maximum(1, np.floor(sizes / n * n_clusters).astype(int))
    while fine_k.sum() < n_clusters:
        fine_k[np.argmax(sizes - fine_k * (n / n_clusters))] += 1
    while fine_k.sum() > n_clusters:
        cand = np.where(fine_k > 1)[0]
        fine_k[cand[np.argmin(sizes[cand])]] -= 1
    out = []
    for j in range(k_meso):
        members = np.nonzero(meso_labels == j)[0]
        if len(members) == 0:
            # degenerate: reuse the mesocenter replicated
            out.append(jnp.repeat(meso_centers[j][None, :], fine_k[j], axis=0))
            continue
        sub = x[jnp.asarray(members)]
        kj = int(min(fine_k[j], len(members)))
        cj = fit(sub, kj, n_iters=n_iters, metric=metric, seed=seed + j + 1)
        if kj < fine_k[j]:
            cj = jnp.concatenate([cj, jnp.repeat(cj[:1], fine_k[j] - kj, axis=0)])
        out.append(cj)
    return jnp.concatenate(out, axis=0)
