"""Balanced k-means — the trainer for IVF coarse quantizers and PQ codebooks.

Reference parity: `raft::cluster::kmeans_balanced::fit/predict/fit_predict`
(cluster/kmeans_balanced.cuh:75,133,198) with `build_clusters`
(detail/kmeans_balanced.cuh:703), `balancing_em_iters` (:616) and
`adjust_centers` (:522). Supports L2 and inner-product metrics and integer
data via a mapping op (int8/uint8 datasets), and a two-level mesocluster
hierarchy for very large n_clusters (:756-790).

TPU design: EM iterations run as a jit-compiled fori_loop; each iteration
streams the data through the fused assign+reduce scan, then applies the
balancing adjustment *functionally*: undersized clusters (count < avg/ratio)
are re-seeded onto data points drawn from a D²-ish proposal (uniform over
the dataset, which concentrates on large clusters by mass — the same
pressure as the reference's "steal a point from a big cluster" rule) and
nudged via the reference's weighted-average update. The hierarchy for huge k
is host-orchestrated (build-time only): train mesoclusters, partition, train
fine clusters per padded partition bucket.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.cluster.kmeans_common import assign_and_reduce

# Reference adjust_centers uses kAdjustCentersWeight = 7.0 (detail/kmeans_balanced.cuh)
_ADJUST_WEIGHT = 7.0


def _maybe_normalize(centers: jax.Array, metric: str) -> jax.Array:
    if metric in ("inner_product", "cosine"):
        n = jnp.linalg.norm(centers, axis=1, keepdims=True)
        return centers / jnp.maximum(n, 1e-12)
    return centers


@functools.partial(jax.jit, static_argnames=("n_iters", "metric", "precision"))
def _balanced_em(
    key: jax.Array,
    x: jax.Array,
    centers0: jax.Array,
    n_iters: int,
    metric: str = "sqeuclidean",
    balancing_ratio: float = 4.0,
    weights: Optional[jax.Array] = None,
    valid_n: Optional[jax.Array] = None,
    precision=None,
) -> jax.Array:
    """Balanced EM. `weights`/`valid_n` support padded inputs (rows beyond
    valid_n carry weight 0 and are packed first) — used by the vmapped
    hierarchical trainer so every partition shares one compiled program."""
    n, d = x.shape
    k = centers0.shape[0]
    nv = jnp.asarray(n, jnp.float32) if valid_n is None else valid_n.astype(jnp.float32)
    nv_i = jnp.maximum(
        jnp.asarray(n, jnp.int32) if valid_n is None else valid_n.astype(jnp.int32), 1
    )
    threshold = nv / k / balancing_ratio

    def body(i, carry):
        centers, key = carry
        _, sums, counts, _ = assign_and_reduce(x, centers, weights, precision=precision)
        safe = jnp.maximum(counts, 1.0)[:, None]
        updated = jnp.where(counts[:, None] > 0, sums / safe, centers)
        # balancing: re-seed undersized clusters toward random (valid) rows
        key, k1 = jax.random.split(key)
        props = jax.random.randint(k1, (k,), 0, 1 << 30) % nv_i
        proposals = x[props].astype(jnp.float32)
        small = counts < threshold
        wc = jnp.minimum(counts, _ADJUST_WEIGHT)[:, None]
        adjusted = (wc * updated + proposals) / (wc + 1.0)
        centers = jnp.where(small[:, None], adjusted, updated)
        centers = _maybe_normalize(centers, metric)
        return centers, key

    centers, _ = lax.fori_loop(0, n_iters, body, (centers0.astype(jnp.float32), key))
    # final clean EM steps (no balancing) so returned centers are a Lloyd
    # update of their members, mirroring balancing_em_iters' trailing
    # predict+calc_centers passes.
    def final_step(_, centers):
        _, sums, counts, _ = assign_and_reduce(x, centers, weights, precision=precision)
        safe = jnp.maximum(counts, 1.0)[:, None]
        centers = jnp.where(counts[:, None] > 0, sums / safe, centers)
        return _maybe_normalize(centers, metric)

    return lax.fori_loop(0, 2, final_step, centers)


def fit(
    X,
    n_clusters: int,
    n_iters: int = 20,
    metric: str = "sqeuclidean",
    seed: int = 0,
    max_train_points: Optional[int] = None,
    resources=None,
    train_precision=None,
) -> jax.Array:
    """Train balanced cluster centers; returns (n_clusters, dim) f32.

    Integer datasets (int8/uint8) are accepted and mapped to f32, mirroring
    the reference's `mapping` operator. `train_precision` overrides the
    assignment matmul's MXU precision (e.g. lax.Precision.DEFAULT for a
    single-pass bf16 trainer, ~6x matmul throughput on TPU; None keeps
    the library default of f32-parity HIGHEST).
    """
    from raft_tpu.core.validation import check_matrix

    x = check_matrix(X, name="X")
    if x.dtype in (jnp.int8, jnp.uint8, jnp.int32):
        x = x.astype(jnp.float32)
    n = x.shape[0]
    if n_clusters > n:
        raise ValueError(f"n_clusters={n_clusters} > n_samples={n}")
    key = jax.random.PRNGKey(seed)
    if max_train_points is not None and n > max_train_points:
        key, sk = jax.random.split(key)
        sel = jax.random.choice(sk, n, (max_train_points,), replace=False)
        x = x[sel]
        n = max_train_points
    key, ik = jax.random.split(key)
    if n_clusters <= 512:
        # k-means++ seeding markedly improves partition quality at small k;
        # at IVF-scale k the hierarchy (fit_hierarchical) is the quality lever.
        from raft_tpu.cluster.kmeans import _kmeans_plusplus

        centers0 = _kmeans_plusplus(ik, x, n_clusters)
    else:
        init_idx = jax.random.choice(ik, n, (n_clusters,), replace=False)
        centers0 = x[init_idx].astype(jnp.float32)
    centers0 = _maybe_normalize(centers0, metric)
    centers = _balanced_em(key, x, centers0, int(n_iters), metric, precision=train_precision)
    if resources is not None:
        resources.track(centers)
    return centers


def predict(X, centers, metric: str = "sqeuclidean", resources=None) -> jax.Array:
    """Nearest-center labels under the training metric
    (cluster/kmeans_balanced.cuh:133)."""
    from raft_tpu.core.validation import check_matrix
    from raft_tpu.cluster.kmeans_common import predict_labels

    x = check_matrix(X, name="X")
    if x.dtype in (jnp.int8, jnp.uint8, jnp.int32):
        x = x.astype(jnp.float32)
    c = jnp.asarray(centers, jnp.float32)
    if metric in ("inner_product", "cosine"):
        from raft_tpu.distance.pairwise import _dot

        scores = _dot(x, _maybe_normalize(c, metric))
        return jnp.argmax(scores, axis=1).astype(jnp.int32)
    return predict_labels(x, c)


def fit_predict(
    X, n_clusters: int, n_iters: int = 20, metric: str = "sqeuclidean", seed: int = 0
) -> Tuple[jax.Array, jax.Array]:
    centers = fit(X, n_clusters, n_iters=n_iters, metric=metric, seed=seed)
    return centers, predict(X, centers, metric=metric)


@functools.partial(jax.jit, static_argnames=("fine_k", "n_iters", "metric"))
def _fit_partitions_vmapped(key, parts, weights, valid_ns, fine_k: int,
                            n_iters: int, metric: str):
    """One compiled program training fine_k clusters inside EVERY partition:
    vmap of the weighted balanced EM over (k_meso, max_size, d) padded
    partitions. The TPU replacement for the reference's sequential
    per-mesocluster build_clusters calls (detail/kmeans_balanced.cuh:756+)."""
    k_meso = parts.shape[0]
    all_keys = jax.random.split(key, 2 * k_meso)
    init_keys, em_keys = all_keys[:k_meso], all_keys[k_meso:]
    init_idx = jax.vmap(
        lambda k, vn: jax.random.randint(k, (fine_k,), 0, 1 << 30)
        % jnp.maximum(vn, 1)
    )(init_keys, valid_ns)
    inits = jnp.take_along_axis(parts, init_idx[:, :, None], axis=1)
    em = functools.partial(_balanced_em, n_iters=n_iters, metric=metric)
    return jax.vmap(
        lambda k, x, c0, w, vn: em(k, x, c0, weights=w, valid_n=vn)
    )(em_keys, parts, inits, weights, valid_ns)


def fit_hierarchical(
    X,
    n_clusters: int,
    n_iters: int = 20,
    metric: str = "sqeuclidean",
    seed: int = 0,
    max_partition_rows: int = 1 << 17,
) -> jax.Array:
    """Two-level trainer for very large n_clusters / datasets
    (detail/kmeans_balanced.cuh:756-790 mesocluster partitioning).

    Trains k_meso = round(sqrt(k)) mesoclusters, partitions the data, then
    trains ceil(k/k_meso) fine clusters inside EVERY partition with one
    vmapped EM program (uniform shapes -> one compile, batched through the
    device in ~512MB chunks) instead of the reference's sequential
    per-mesocluster loop; the surplus centers are dropped by smallest
    member count, so any n_clusters works. Oversized partitions are
    randomly subsampled to `max_partition_rows` (trainer quality is
    subsample-robust, matching the reference's trainset-fraction
    behavior)."""
    import numpy as np

    from raft_tpu.core.validation import check_matrix
    from raft_tpu.neighbors.ivf_flat import _pack_lists

    x = check_matrix(X)
    n, d = x.shape
    if n_clusters <= 64:
        return fit(x, n_clusters, n_iters=n_iters, metric=metric, seed=seed)
    # every partition trains fine_k = ceil(k / k_meso) clusters (uniform
    # shape -> one compiled program); the surplus centers are dropped by
    # smallest member count afterwards, so any n_clusters works
    k_meso = max(2, int(np.sqrt(n_clusters)))
    fine_k = -(-n_clusters // k_meso)

    meso_centers = fit(x, k_meso, n_iters=n_iters, metric=metric, seed=seed)
    meso_labels = np.asarray(predict(x, meso_centers, metric=metric))

    slots, sizes = _pack_lists(meso_labels.astype(np.int64), k_meso, group=8)
    max_sz = min(slots.shape[1], max(max_partition_rows, 4 * fine_k))
    if max_sz < slots.shape[1]:
        # random subsample of oversized partitions (order-independent, the
        # reference's trainset-fraction behavior): shuffle valid slots first
        rng = np.random.default_rng(seed)
        keys = rng.random(slots.shape) + (slots < 0) * 2.0  # invalid last
        order = np.argsort(keys, axis=1, kind="stable")
        slots = np.take_along_axis(slots, order, axis=1)[:, :max_sz]
    valid_ns = np.minimum(sizes.astype(np.int64), max_sz)

    # batch partitions through the vmapped trainer to bound device memory
    # (~512MB of gathered rows per launch; same shapes -> one compile)
    pb = max(1, min(k_meso, (1 << 27) // max(1, max_sz * d)))
    nb = -(-k_meso // pb)
    out = []
    xd = jnp.asarray(x)
    for b in range(nb):
        lo, hi = b * pb, min((b + 1) * pb, k_meso)
        sl = np.full((pb, max_sz), -1, slots.dtype)
        sl[: hi - lo] = slots[lo:hi]
        parts = xd[jnp.maximum(jnp.asarray(sl), 0)]  # (pb, max_sz, d)
        weights = jnp.asarray((sl >= 0).astype(np.float32))
        vn = np.zeros((pb,), np.int64)
        vn[: hi - lo] = valid_ns[lo:hi]
        c = _fit_partitions_vmapped(
            jax.random.PRNGKey(seed + 1 + b),
            parts,
            weights,
            jnp.asarray(vn),
            fine_k,
            int(n_iters),
            metric,
        )  # (pb, fine_k, d)
        out.append(c[: hi - lo])
    centers = jnp.concatenate(out, axis=0)  # (k_meso, fine_k, d)
    # degenerate partitions: replicate the mesocenter (never NaN downstream)
    bad = jnp.asarray(valid_ns < 1)
    centers = jnp.where(bad[:, None, None], meso_centers[:, None, :], centers)
    centers = centers.reshape(k_meso * fine_k, d)
    surplus = k_meso * fine_k - n_clusters
    if surplus:
        # drop the `surplus` centers with the fewest members on the trainset
        counts = np.bincount(
            np.asarray(predict(x, centers, metric=metric)),
            minlength=k_meso * fine_k,
        )
        keep = np.sort(np.argsort(counts, kind="stable")[surplus:])
        centers = centers[jnp.asarray(keep)]
    return centers
