"""Isotropic Gaussian blob dataset generator.

Reference parity: `raft::random::make_blobs` (random/make_blobs.cuh:63) —
cluster centers (given or uniform in center_box), per-cluster std, optional
shuffle; returns (data, labels). Used throughout tests and benchmarks.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.random.rng import RngState, _key_of


def make_blobs(
    n_samples: int,
    n_features: int,
    centers=None,
    n_clusters: int = 5,
    cluster_std: float = 1.0,
    shuffle: bool = True,
    center_box: Tuple[float, float] = (-10.0, 10.0),
    seed: int = 0,
    dtype=jnp.float32,
    state: Optional[RngState] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (data (n_samples, n_features), labels (n_samples,) int32)."""
    st = state if state is not None else RngState(seed)
    if centers is None:
        ckey = _key_of(st)
        centers = jax.random.uniform(
            ckey, (n_clusters, n_features), minval=center_box[0], maxval=center_box[1]
        )
    else:
        centers = jnp.asarray(centers)
        n_clusters = centers.shape[0]

    lkey = _key_of(st)
    labels = jax.random.randint(lkey, (n_samples,), 0, n_clusters)
    nkey = _key_of(st)
    noise = cluster_std * jax.random.normal(nkey, (n_samples, n_features))
    data = centers[labels] + noise
    if shuffle:
        skey = _key_of(st)
        perm = jax.random.permutation(skey, n_samples)
        data, labels = data[perm], labels[perm]
    return data.astype(dtype), labels.astype(jnp.int32)
