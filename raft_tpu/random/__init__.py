"""Random generation: RNG state, distributions, dataset generators.

TPU-native equivalent of `cpp/include/raft/random/` (survey §2.5).
RMAT graph generation and make_regression live in their own modules.
"""

from raft_tpu.random.rng import (
    RngState,
    uniform,
    uniform_int,
    normal,
    normal_int,
    normal_table,
    bernoulli,
    scaled_bernoulli,
    gumbel,
    lognormal,
    logistic,
    exponential,
    rayleigh,
    laplace,
    discrete,
    permute,
    shuffle_rows,
    sample_without_replacement,
    multi_variable_gaussian,
)
from raft_tpu.random.make_blobs import make_blobs
from raft_tpu.random.generators import make_regression, rmat

__all__ = [
    "make_regression",
    "rmat",
    "RngState",
    "uniform",
    "uniform_int",
    "normal",
    "normal_int",
    "normal_table",
    "bernoulli",
    "scaled_bernoulli",
    "gumbel",
    "lognormal",
    "logistic",
    "exponential",
    "rayleigh",
    "laplace",
    "discrete",
    "permute",
    "shuffle_rows",
    "sample_without_replacement",
    "multi_variable_gaussian",
    "make_blobs",
]
