"""Dataset/graph generators: make_regression, RMAT.

Reference parity: `raft::random::make_regression`
(random/make_regression.cuh) and the RMAT rectangular generator
(random/rmat_rectangular_generator.cuh; pylibraft
random/rmat_rectangular_generator.pyx `rmat`).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.random.rng import RngState, _key_of


def make_regression(
    n_samples: int,
    n_features: int,
    n_informative: int = 10,
    n_targets: int = 1,
    bias: float = 0.0,
    noise: float = 0.0,
    effective_rank: Optional[int] = None,
    tail_strength: float = 0.5,
    shuffle: bool = True,
    seed: int = 0,
    dtype=jnp.float32,
    state: Optional[RngState] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Linear-model dataset; returns (X, y, coef) (make_regression.cuh)."""
    st = state if state is not None else RngState(seed)
    n_informative = min(n_informative, n_features)
    X = jax.random.normal(_key_of(st), (n_samples, n_features), dtype=jnp.float32)
    if effective_rank is not None:
        # low-rank-ish inputs via spectral decay (reference's low_rank path)
        u, _, vt = jnp.linalg.svd(X, full_matrices=False)
        r = min(n_samples, n_features)
        low = effective_rank / r
        s = jnp.exp(-jnp.arange(r) / (effective_rank * tail_strength + 1e-6))
        X = (u * s[None, :]) @ vt * jnp.sqrt(jnp.asarray(n_samples, jnp.float32))
    coef = jnp.zeros((n_features, n_targets), jnp.float32)
    w = 100.0 * jax.random.uniform(_key_of(st), (n_informative, n_targets))
    coef = coef.at[:n_informative].set(w)
    y = X @ coef + bias
    if noise > 0:
        y = y + noise * jax.random.normal(_key_of(st), y.shape)
    if shuffle:
        perm = jax.random.permutation(_key_of(st), n_samples)
        X, y = X[perm], y[perm]
    y = y[:, 0] if n_targets == 1 else y
    return X.astype(dtype), y.astype(dtype), coef.astype(dtype)


def rmat(
    r_scale: int,
    c_scale: int,
    n_edges: int,
    theta=None,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
    seed: int = 0,
    state: Optional[RngState] = None,
) -> jax.Array:
    """RMAT rectangular graph generator: (n_edges, 2) int32 [src, dst]
    (rmat_rectangular_generator.cuh).

    Each edge picks one quadrant per scale level; levels are independent
    bits, so the whole generation is one vectorized (n_edges, max_scale)
    categorical draw — no per-edge loop.
    """
    st = state if state is not None else RngState(seed)
    if theta is not None:
        theta = jnp.asarray(theta, jnp.float32).reshape(-1, 4)
        if theta.shape[0] == 1:
            theta = jnp.repeat(theta, max(r_scale, c_scale), axis=0)
    else:
        theta = jnp.tile(jnp.asarray([[a, b, c, 1.0 - a - b - c]], jnp.float32),
                         (max(r_scale, c_scale), 1))
    max_scale = max(r_scale, c_scale)
    key = _key_of(st)
    # quadrant per (edge, level): 0=TL 1=TR 2=BL 3=BR
    logits = jnp.log(jnp.maximum(theta, 1e-30))  # (max_scale, 4)
    quad = jax.random.categorical(
        key, logits[None, :, :], axis=-1, shape=(n_edges, max_scale)
    )
    row_bit = (quad >= 2).astype(jnp.int32)
    col_bit = (quad % 2).astype(jnp.int32)
    # levels beyond a side's scale contribute nothing to that side
    r_weights = jnp.where(jnp.arange(max_scale) < r_scale,
                          2 ** jnp.arange(max_scale, dtype=jnp.int32), 0)
    c_weights = jnp.where(jnp.arange(max_scale) < c_scale,
                          2 ** jnp.arange(max_scale, dtype=jnp.int32), 0)
    src = jnp.sum(row_bit * r_weights[None, :], axis=1)
    dst = jnp.sum(col_bit * c_weights[None, :], axis=1)
    return jnp.stack([src, dst], axis=1).astype(jnp.int32)
