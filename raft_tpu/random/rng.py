"""RNG state and distributions.

Reference parity: `raft::random::RngState` (random/rng_state.hpp:28-52) with
Philox/PCG generators and the distribution set in random/rng.cuh:44-576.

TPU design: JAX's counter-based threefry PRNG replaces Philox/PCG — the
functional key-splitting model is the idiomatic (and reproducible-under-jit)
equivalent of the reference's seed+subsequence scheme. Exact bitwise parity
with the reference's streams is explicitly out of scope (different
generator); distribution semantics match.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


class RngState:
    """Mutable convenience wrapper over a functional PRNG key.

    Mirrors `RngState{seed, base_subsequence}`: each draw advances the
    stream. All draw methods also exist as pure module-level functions taking
    an explicit key.
    """

    def __init__(self, seed: int = 0, generator: str = "threefry"):
        self.seed = seed
        self.generator = generator
        self._key = jax.random.PRNGKey(seed)

    def advance(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    @property
    def key(self) -> jax.Array:
        return self._key


def _key_of(state_or_key) -> jax.Array:
    if isinstance(state_or_key, RngState):
        return state_or_key.advance()
    return state_or_key


def uniform(state, shape, low=0.0, high=1.0, dtype=jnp.float32) -> jax.Array:
    return jax.random.uniform(_key_of(state), shape, dtype=dtype, minval=low, maxval=high)


def uniform_int(state, shape, low, high, dtype=jnp.int32) -> jax.Array:
    return jax.random.randint(_key_of(state), shape, low, high, dtype=dtype)


def normal(state, shape, mu=0.0, sigma=1.0, dtype=jnp.float32) -> jax.Array:
    return mu + sigma * jax.random.normal(_key_of(state), shape, dtype=dtype)


def normal_int(state, shape, mu, sigma, dtype=jnp.int32) -> jax.Array:
    return jnp.round(normal(state, shape, mu, sigma)).astype(dtype)


def normal_table(state, n_rows, mu_vec, sigma_vec=None, dtype=jnp.float32) -> jax.Array:
    """Per-column mu/sigma gaussian table (rng.cuh normalTable)."""
    mu = jnp.asarray(mu_vec, dtype=dtype)
    sigma = jnp.ones_like(mu) if sigma_vec is None else jnp.asarray(sigma_vec, dtype=dtype)
    z = jax.random.normal(_key_of(state), (n_rows, mu.shape[0]), dtype=dtype)
    return mu[None, :] + sigma[None, :] * z


def bernoulli(state, shape, prob=0.5, dtype=jnp.bool_) -> jax.Array:
    return jax.random.bernoulli(_key_of(state), prob, shape).astype(dtype)


def scaled_bernoulli(state, shape, prob, scale, dtype=jnp.float32) -> jax.Array:
    b = jax.random.bernoulli(_key_of(state), prob, shape)
    return jnp.where(b, scale, -scale).astype(dtype)


def gumbel(state, shape, mu=0.0, beta=1.0, dtype=jnp.float32) -> jax.Array:
    return mu + beta * jax.random.gumbel(_key_of(state), shape, dtype=dtype)


def lognormal(state, shape, mu=0.0, sigma=1.0, dtype=jnp.float32) -> jax.Array:
    return jnp.exp(normal(state, shape, mu, sigma, dtype=dtype))


def logistic(state, shape, mu=0.0, scale=1.0, dtype=jnp.float32) -> jax.Array:
    return mu + scale * jax.random.logistic(_key_of(state), shape, dtype=dtype)


def exponential(state, shape, lambda_=1.0, dtype=jnp.float32) -> jax.Array:
    return jax.random.exponential(_key_of(state), shape, dtype=dtype) / lambda_


def rayleigh(state, shape, sigma=1.0, dtype=jnp.float32) -> jax.Array:
    u = jax.random.uniform(_key_of(state), shape, dtype=dtype, minval=1e-7, maxval=1.0)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def laplace(state, shape, mu=0.0, scale=1.0, dtype=jnp.float32) -> jax.Array:
    return mu + scale * jax.random.laplace(_key_of(state), shape, dtype=dtype)


def discrete(state, shape, weights) -> jax.Array:
    """Sample indices with given unnormalized weights (rng.cuh discrete)."""
    w = jnp.asarray(weights, dtype=jnp.float32)
    return jax.random.categorical(_key_of(state), jnp.log(jnp.maximum(w, 1e-30)), shape=shape)


def permute(state, n: int) -> jax.Array:
    """Random permutation of [0, n) (random/permute.cuh)."""
    return jax.random.permutation(_key_of(state), n)


def shuffle_rows(state, matrix) -> Tuple[jax.Array, jax.Array]:
    m = jnp.asarray(matrix)
    perm = jax.random.permutation(_key_of(state), m.shape[0])
    return m[perm], perm


def sample_without_replacement(
    state, n_population: int, n_samples: int, weights: Optional[jax.Array] = None
) -> jax.Array:
    """k-of-n sampling without replacement (rng.cuh:sampleWithoutReplacement).

    Weighted variant uses the Gumbel-top-k trick (exponential race), which is
    the order-statistics method the reference implements with per-item keys.
    """
    key = _key_of(state)
    if weights is None:
        if 8 * n_samples <= n_population:
            # top-k of iid random keys is a uniform k-subset and avoids
            # materializing + sorting the full n permutation. Keys are
            # raw 32-bit draws, not f32 uniforms: floats carry only ~2^23
            # distinct values, and top_k's low-index tie-break would bias
            # selection toward low row ids at large n.
            g = jax.random.bits(key, (n_population,), jnp.uint32)
            return jax.lax.top_k(g, n_samples)[1]
        return jax.random.permutation(key, n_population)[:n_samples]
    w = jnp.asarray(weights, dtype=jnp.float32)
    g = jax.random.gumbel(key, (n_population,)) + jnp.log(jnp.maximum(w, 1e-30))
    return jax.lax.top_k(g, n_samples)[1]


def multi_variable_gaussian(state, mean, cov, n_samples: int) -> jax.Array:
    """Samples from N(mean, cov) (random/multi_variable_gaussian.cuh)."""
    mean = jnp.asarray(mean, dtype=jnp.float32)
    cov = jnp.asarray(cov, dtype=jnp.float32)
    return jax.random.multivariate_normal(
        _key_of(state), mean, cov, shape=(n_samples,), method="svd"
    )
