"""File-backed dataset streaming for out-of-HBM index builds.

Reference parity: `batch_load_iterator` (spatial/knn/detail/ann_utils.cuh:388)
streams host datasets through fixed-size staging batches; its host-IO half is
the role of this module. `neighbors.batch_loader.BatchLoadIterator` covers
arrays already in host RAM; this covers datasets that live in FILES — the
regime of the 100M-row north star, where even host RAM can't hold the data.

Two layers:
- format probing: `.npy` (numpy) and the big-ann-benchmarks binary family
  (`.fbin` f32 / `.u8bin` uint8 / `.i8bin` int8 / `.ibin` int32 — a u32
  (n_rows, dim) header then row-major data), the formats public ANN
  datasets actually ship in;
- `FileBatchLoader`: iterates (batch ndarray, valid_rows) with a uniform
  padded batch shape (one XLA compilation for every batch). When the
  native library is available, a C++ reader thread pread()s batches into
  a ring of buffers AHEAD of the consumer (cpp/raft_tpu_native.cc
  rt_loader_*), overlapping disk/page-cache latency with device work;
  otherwise a numpy memmap fallback reads synchronously.

Buffer lifetime contract (native path, copy=False): a yielded batch is a
zero-copy view of a ring slot. It stays valid while the CURRENT and the
next `depth - 2` batches are being consumed, and EVERY view dies when
iteration finishes (the ring is freed on close). Consumers that keep
blocks past an iteration must copy them — which is why `copy=True` is
the default; the streamed-build helpers opt into zero-copy because they
upload each batch to the device within its own iteration.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional, Tuple

import numpy as np

__all__ = [
    "probe_file",
    "FileBatchLoader",
    "NativeLoaderUnavailable",
    "extend_from_file",
    "extend_from_file_local",
]


class NativeLoaderUnavailable(RuntimeError):
    """``native=True`` was requested but the C++ runtime is not built/
    loadable on this host. Typed so callers that *require* the prefetch
    ring can catch precisely this and fall back (or fail loudly) without
    swallowing unrelated RuntimeErrors."""

_BIN_DTYPES = {
    ".fbin": np.float32,
    ".u8bin": np.uint8,
    ".i8bin": np.int8,
    ".ibin": np.int32,
}


def probe_file(path: str) -> Tuple[int, Tuple[int, ...], np.dtype]:
    """Return (data_offset_bytes, shape, dtype) for a supported file.

    Supports numpy `.npy` (row-major, no pickling) and the big-ann binary
    family (u32 n_rows, u32 dim header). Raises ValueError on anything
    else — format sniffing a 100 GB file must fail loudly, not guess.
    """
    ext = os.path.splitext(path)[1].lower()
    if ext == ".npy":
        with open(path, "rb") as f:
            version = np.lib.format.read_magic(f)
            if version == (1, 0):
                shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
            elif version in ((2, 0), (3, 0)):
                shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
            else:
                raise ValueError(f"{path}: unsupported .npy version {version}")
            if fortran:
                raise ValueError(f"{path}: Fortran-order .npy is not streamable row-major")
            if dtype.hasobject:
                raise ValueError(f"{path}: object dtypes are not supported")
            return f.tell(), tuple(int(s) for s in shape), dtype
    if ext in _BIN_DTYPES:
        dtype = np.dtype(_BIN_DTYPES[ext])
        with open(path, "rb") as f:
            hdr = f.read(8)
        if len(hdr) != 8:
            raise ValueError(f"{path}: truncated big-ann header")
        n, dim = np.frombuffer(hdr, np.uint32)
        expect = 8 + int(n) * int(dim) * dtype.itemsize
        actual = os.path.getsize(path)
        if actual < expect:
            raise ValueError(
                f"{path}: file holds {actual} bytes, header promises {expect}"
            )
        return 8, (int(n), int(dim)), dtype
    raise ValueError(f"unsupported dataset file extension {ext!r} ({path})")


class FileBatchLoader:
    """Iterate a row-major on-disk array in uniform (padded) batches.

    Yields (batch, valid_rows) where batch is (batch_rows, *row_shape) of
    the file's dtype; the final partial batch is zero-padded and `valid`
    gives its true row count (static shapes = one XLA compile, the
    BatchLoadIterator convention). Usable as a context manager; iterating
    twice re-opens the underlying stream.
    """

    def __init__(
        self,
        path: str,
        batch_rows: int,
        depth: int = 3,
        copy: bool = True,
        native: Optional[bool] = None,
        start_batch: int = 0,
    ):
        if batch_rows <= 0:
            raise ValueError("batch_rows must be positive")
        self.path = path
        self.data_off, self.shape, self.dtype = probe_file(path)
        if len(self.shape) == 0:
            raise ValueError(f"{path}: scalar arrays are not streamable")
        self.n_rows = self.shape[0]
        self.row_shape = self.shape[1:]
        self.row_bytes = int(np.prod(self.row_shape, dtype=np.int64)) * self.dtype.itemsize
        if self.row_bytes <= 0:
            raise ValueError(f"{path}: zero-byte rows are not streamable")
        self.batch_rows = int(batch_rows)
        self.depth = max(2, int(depth))
        self.copy = copy
        self.n_batches = -(-self.n_rows // self.batch_rows) if self.n_rows else 0
        # start_batch: resume a killed streaming build mid-file — batches
        # [start_batch, n_batches) yield with IDENTICAL contents/padding
        # to the same positions of a from-zero iteration (batch geometry
        # is anchored to the file start, so a cursor-driven resume is
        # bit-identical; raft_tpu/jobs/streaming drives this)
        if not (0 <= int(start_batch) <= self.n_batches):
            raise ValueError(
                f"start_batch={start_batch} outside [0, {self.n_batches}]")
        self.start_batch = int(start_batch)
        if native is None:
            from raft_tpu import native as native_mod

            self._lib = native_mod.get_lib()
        elif native:
            from raft_tpu import native as native_mod

            self._lib = native_mod.get_lib()
            if self._lib is None:
                raise NativeLoaderUnavailable(
                    "native loader requested but library unavailable")
        else:
            self._lib = None

    def __len__(self) -> int:
        return self.n_batches

    # -- native path ------------------------------------------------------
    def _iter_native(self) -> Iterator[Tuple[np.ndarray, int]]:
        lib = self._lib
        # resume: shift the data window to the first resumed batch — the
        # batch grid is anchored to the file start and start_batch lands
        # on a grid line, so the remaining batches (incl. the padded
        # tail) are bit-identical to a from-zero iteration's tail
        skip_rows = self.start_batch * self.batch_rows
        handle = lib.rt_loader_open(
            self.path.encode(),
            self.data_off + skip_rows * self.row_bytes, self.row_bytes,
            self.n_rows - skip_rows, self.batch_rows, self.depth,
        )
        if not handle:
            raise OSError(f"rt_loader_open failed for {self.path}")
        outstanding = 0
        try:
            while True:
                ptr = ctypes.POINTER(ctypes.c_uint8)()
                rows = lib.rt_loader_acquire(handle, ctypes.byref(ptr))
                if rows == 0:
                    break
                if rows < 0:
                    raise OSError(f"loader IO error {rows} reading {self.path}")
                outstanding += 1
                buf = np.ctypeslib.as_array(ptr, shape=(self.batch_rows * self.row_bytes,))
                batch = np.frombuffer(buf, dtype=self.dtype).reshape(
                    (self.batch_rows,) + self.row_shape
                )
                rows = int(rows)
                if rows < self.batch_rows:
                    # pad the tail batch; the ring slot itself must not be
                    # mutated (the reader owns its contents), so pad a copy
                    pad = np.zeros_like(batch)
                    pad[:rows] = batch[:rows]
                    batch = pad
                elif self.copy:
                    batch = batch.copy()
                yield batch, rows
                # hold `depth - 1` slots (current + depth-2 previous) so a
                # yielded view's documented lifetime scales with depth; the
                # one remaining slot keeps the reader prefetching ahead
                if outstanding > self.depth - 1:
                    lib.rt_loader_release(handle)
                    outstanding -= 1
        finally:
            lib.rt_loader_close(handle)

    # -- memmap fallback --------------------------------------------------
    def _iter_fallback(self) -> Iterator[Tuple[np.ndarray, int]]:
        mm = np.memmap(
            self.path, dtype=self.dtype, mode="r", offset=self.data_off,
            shape=(self.n_rows,) + self.row_shape,
        )
        for b in range(self.start_batch, self.n_batches):
            lo = b * self.batch_rows
            hi = min(lo + self.batch_rows, self.n_rows)
            # materialize now: np.asarray of a memmap slice is a lazy view
            # that would defer page-in to first touch, breaking the "batch
            # is resident when yielded" contract the native path provides
            block = np.array(mm[lo:hi])
            if hi - lo < self.batch_rows:
                pad = np.zeros(
                    (self.batch_rows,) + self.row_shape, self.dtype
                )
                pad[: hi - lo] = block
                block = pad
            yield block, hi - lo

    def __iter__(self) -> Iterator[Tuple[np.ndarray, int]]:
        if self.start_batch >= self.n_batches:
            return iter(())  # fully-consumed resume: nothing left
        if self._lib is not None:
            return self._iter_native()
        return self._iter_fallback()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # iteration owns the native handle; nothing held between iterations
        return False


def extend_from_file(extend_fn, index, path: str, batch_rows: int,
                     start_id: int = 0, depth: int = 3):
    """Stream an on-disk dataset into an ANN index via repeated
    `extend_fn` (ivf_flat.extend / ivf_pq.extend) — the file-backed
    variant of `neighbors.batch_loader.extend_batched`, for builds whose
    dataset never fits host RAM. The native loader prefetches batch b+1
    from disk while the device encodes batch b."""
    import jax.numpy as jnp

    # zero-copy is safe here: each batch is uploaded to the device inside
    # its own iteration, within the ring view's documented lifetime
    loader = FileBatchLoader(path, batch_rows, depth=depth, copy=False)
    offset = start_id
    for batch, valid in loader:
        ids = jnp.arange(offset, offset + valid, dtype=jnp.int32)
        index = extend_fn(index, batch[:valid], ids)
        offset += valid
    return index


def extend_from_file_local(extend_local_fn, index, path: str,
                           batch_rows: int, depth: int = 3):
    """Collective file-backed ingestion for the multi-controller API:
    every controller streams its OWN on-disk partition through repeated
    `extend_local_fn` (comms.mnmg.ivf_flat_extend_local /
    ivf_pq_extend_local). Files may have different row counts per
    controller, but every controller must make the SAME number of
    `extend_local` calls (they are collective) — so the batch COUNT is
    agreed first (one host allgather of ceil(rows/batch_rows)) and
    controllers whose file runs out early keep participating with empty
    batches. Ids are assigned by the collective extend itself (the
    process-order id-space continuation)."""
    import jax
    import numpy as np

    loader = FileBatchLoader(path, batch_rows, depth=depth, copy=False)
    my_batches = loader.n_batches
    if jax.process_count() > 1:
        import jax.numpy as jnp
        from jax.experimental import multihost_utils

        all_b = np.asarray(multihost_utils.process_allgather(
            jnp.asarray([my_batches]), tiled=True))
        total_batches = int(all_b.max())
    else:
        total_batches = my_batches
    empty = np.zeros((0,) + tuple(loader.shape[1:]), loader.dtype)
    it = iter(loader)
    for _ in range(total_batches):
        try:
            batch, valid = next(it)
            rows = batch[:valid]
        except StopIteration:
            rows = empty
        index = extend_local_fn(index, rows)
    return index
