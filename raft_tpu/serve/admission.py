"""Admission control for the serving engine: bounded queueing with
backpressure, per-request deadlines, and graceful degradation under
overload.

The queue is bounded in ROWS (queries), not requests — device cost is
per-row, so a thousand 1-row callers and one 1000-row caller should hit
the same wall. Two full-queue policies:

  "block"   the submitting thread waits (bounded by `block_timeout_s`)
            until the worker drains room — backpressure propagates to
            callers, total memory stays bounded (the classic
            producer/consumer stance).
  "reject"  `submit` raises `RejectedError` immediately — the
            load-shedding stance: callers own their retry/fallback
            policy and the serving path never blocks.

Deadlines: a request may carry an absolute budget (`deadline_s` from
submit time, or `default_deadline_s`). The batcher drops expired
requests AT POP TIME, before any device work — a request that waited
out its budget in the queue wastes zero device cycles and fails with
`DeadlineExceeded` (RAFT has no analogue; this is standard
earliest-deadline load shedding).

Degradation: under overload, approximate-search quality is the cheapest
currency — `probe_scale()` maps queue fill to a multiplier the engine
applies to `n_probes` (floor `min_probe_scale`), trading recall for
latency exactly the way the degraded MNMG path trades coverage
(`comms.resilience`). Scale-ups are capped at 1.0: overload never
*raises* work.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


class RejectedError(RuntimeError):
    """Admission refused the request (full queue under policy="reject",
    or a blocked submit that timed out)."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before results were delivered; if
    it expired in the queue, it was dropped without executing."""


class ServerClosed(RuntimeError):
    """The server was stopped; queued/new requests cannot complete."""


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Knobs for `AdmissionController`.

    max_pending_rows   row bound on the queue (backpressure threshold)
    policy             "block" | "reject" (see module docstring)
    block_timeout_s    max wall seconds a blocked submit waits for room
    default_deadline_s deadline applied when submit passes none
                       (None = no deadline)
    degrade_at         queue-fill fraction where probe shrinking starts
    min_probe_scale    floor of the n_probes multiplier at 100% fill
    """

    max_pending_rows: int = 4096
    policy: str = "block"
    block_timeout_s: float = 30.0
    default_deadline_s: Optional[float] = None
    degrade_at: float = 0.75
    min_probe_scale: float = 0.25

    def __post_init__(self):
        if self.policy not in ("block", "reject"):
            raise ValueError(f"unknown admission policy {self.policy!r}")
        if self.max_pending_rows <= 0:
            raise ValueError("max_pending_rows must be positive")
        if not (0.0 < self.degrade_at <= 1.0):
            raise ValueError("degrade_at must be in (0, 1]")
        if not (0.0 < self.min_probe_scale <= 1.0):
            raise ValueError("min_probe_scale must be in (0, 1]")


class AdmissionController:
    """Pure policy object: the batcher owns the lock/condition and the
    row counter; this class answers "may this request enter?", "when
    does it expire?", and "how degraded is the engine right now?"."""

    def __init__(self, config: AdmissionConfig):
        self.config = config

    # -- admission -----------------------------------------------------

    def has_room(self, pending_rows: int, n_rows: int) -> bool:
        return pending_rows + n_rows <= self.config.max_pending_rows

    def admit(self, n_rows: int, pending_rows_fn, cond,
              closed_fn) -> None:
        """Gate one submit. Caller MUST hold `cond`'s lock. Blocks (on
        `cond`) or raises `RejectedError` per policy; oversized requests
        that could never fit are rejected under either policy."""
        cfg = self.config
        if n_rows > cfg.max_pending_rows:
            raise RejectedError(
                f"request of {n_rows} rows exceeds max_pending_rows="
                f"{cfg.max_pending_rows}; split it (see batch_loader)"
            )
        if self.has_room(pending_rows_fn(), n_rows):
            return
        if cfg.policy == "reject":
            raise RejectedError(
                f"queue full ({pending_rows_fn()}/{cfg.max_pending_rows} "
                "rows) under policy='reject'"
            )
        deadline = time.monotonic() + cfg.block_timeout_s
        while not self.has_room(pending_rows_fn(), n_rows):
            if closed_fn():
                raise ServerClosed("server stopped while submit was blocked")
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not cond.wait(timeout=remaining):
                raise RejectedError(
                    f"blocked submit timed out after {cfg.block_timeout_s}s "
                    f"({pending_rows_fn()}/{cfg.max_pending_rows} rows queued)"
                )

    # -- deadlines -----------------------------------------------------

    def deadline_for(self, deadline_s: Optional[float]) -> Optional[float]:
        """Relative budget -> absolute monotonic deadline (None = none)."""
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        if deadline_s is None:
            return None
        return time.monotonic() + float(deadline_s)

    @staticmethod
    def expired(deadline: Optional[float], now: Optional[float] = None) -> bool:
        if deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= deadline

    # -- degradation ---------------------------------------------------

    def probe_scale(self, pending_rows: int) -> float:
        """n_probes multiplier for the CURRENT queue fill: 1.0 below
        `degrade_at`, then linear down to `min_probe_scale` at a full
        queue. Continuous (no cliff), monotone in load.

        Composition with adaptive probing: the searcher applies this
        scale FIRST, as a floor-with-min-1 CAP on n_probes
        (engine._scaled_probes), and a request's `recall_target`
        budgets then adapt within that cap — overload can only shrink
        work, per-query adaptivity only redistributes it."""
        cfg = self.config
        fill = min(1.0, pending_rows / cfg.max_pending_rows)
        if fill <= cfg.degrade_at:
            return 1.0
        frac = (fill - cfg.degrade_at) / (1.0 - cfg.degrade_at)
        return 1.0 - frac * (1.0 - cfg.min_probe_scale)
